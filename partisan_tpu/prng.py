"""Per-node PRNG discipline.

The reference seeds every slave node deterministically with
``{phash2(Node), 1, 1}`` (test/partisan_support.erl:162-166) so that protocol
randomness (view eviction, walk targets, shuffle samples) is reproducible per
node.  The TPU rebuild mirrors this with one jax PRNG key per virtual node,
folded with the round number each step — randomness is a pure function of
(seed, node_id, round, decision_slot).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def node_keys(seed: int, n_nodes: int) -> jax.Array:
    """[N, 2] uint32 — one independent key per virtual node."""
    root = jax.random.PRNGKey(seed)
    return jax.random.split(root, n_nodes)


def round_key(key: jax.Array, rnd: jax.Array) -> jax.Array:
    """Fold the round counter into a per-node key (call inside the step)."""
    return jax.random.fold_in(key, rnd)


def decision_key(key: jax.Array, slot: int) -> jax.Array:
    """Distinct stream per decision site within one node-round."""
    return jax.random.fold_in(key, slot)
