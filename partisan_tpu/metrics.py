"""Cluster observability (SURVEY §5.5) — per-round scalar metrics reduced
ON DEVICE, the rebuild of the reference's scattered instrumentation
(distance ping/pong RTTs pluggable :852-873, queue-depth logging :875-879,
transmission logging plumtree :666-685).

RTT is degenerate in a round-synchronous simulator (always one round), so
the useful health signals are topology ones: view-size histograms, isolated
node counts, convergence.  Everything here is jittable and cheap enough to
run every round inside a scan; stream the dict to host at whatever cadence
observability needs.

For full-speed in-scan collection use :mod:`partisan_tpu.telemetry`: its
windowed runner wires these collectors (plus the engine counter taps)
into a [window, K] device ring behind a per-metric enable mask, flushes
to host once per window, and exports through JSONL / Prometheus sinks —
see the "Observability" section of README.md for the registry, the
ring/window model, and the exported metric names."""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from .engine import ProtocolBase, World
from .ops import graph


def view_stats(views: jax.Array, alive: jax.Array,
               prefix: str = "") -> Dict[str, jax.Array]:
    """[N, C] padded views -> size histogram + isolation count (the
    active-view histogram / isolated-node metrics of SURVEY §5.5)."""
    sizes = jnp.sum(views >= 0, axis=1)
    sizes = jnp.where(alive, sizes, -1)
    C = views.shape[1]
    hist = jnp.zeros((C + 1,), jnp.int32).at[
        jnp.clip(sizes, 0, C)].add(jnp.where(alive, 1, 0))
    return {
        prefix + "isolated": jnp.sum(alive & (sizes == 0)).astype(jnp.int32),
        prefix + "mean_view": jnp.sum(jnp.maximum(sizes, 0))
        / jnp.maximum(jnp.sum(alive), 1),
        prefix + "view_hist": hist,
    }


def connectivity(views: jax.Array, alive: jax.Array) -> Dict[str, jax.Array]:
    """All-pairs reachability + symmetry, on device (the digraph check,
    test/partisan_SUITE.erl:2044-2109).  O(N^2 log N) — meant for health
    probes at test scale, not the 10^6-node fast path."""
    n = views.shape[0]
    adj = graph.adjacency_from_views(views, n)
    return {
        "connected": graph.is_connected(adj, alive),
        "symmetric": graph.is_symmetric(adj, alive),
    }


def convergence(member_masks: jax.Array, alive: jax.Array) -> jax.Array:
    """Fraction of alive nodes sharing the modal membership view —
    rounds-to-convergence is THE full-membership metric (SURVEY §7.2 M1).
    member_masks: [N, N] bool (row i = node i's view)."""
    ref = member_masks[jnp.argmax(alive)]
    agree = jnp.all(member_masks == ref[None, :], axis=1) & alive
    return jnp.sum(agree) / jnp.maximum(jnp.sum(alive), 1)


def world_health(world: World, proto: ProtocolBase) -> Dict[str, jax.Array]:
    """One-call health snapshot for protocols exposing member_mask."""
    masks = jax.vmap(proto.member_mask)(world.state)
    out = {
        "alive": jnp.sum(world.alive).astype(jnp.int32),
        "inflight": world.msgs.count(),
        "convergence": convergence(masks, world.alive),
    }
    for k, v in proto.health_counters(world.state).items():
        out[k] = jnp.asarray(v).astype(jnp.int32)
    st = world.state
    views = None
    while views is None and st is not None:
        views = getattr(st, "active", None)
        if views is None:
            views = getattr(st, "partial", None)
        st = getattr(st, "lower", None)  # unwrap Stacked layers
    if views is not None:
        out.update(view_stats(views, world.alive))
    return out
