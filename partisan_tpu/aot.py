"""AOT export plane (ISSUE 17): kill the compile wall by shipping the
flagship programs as on-disk artifacts instead of retracing them.

XLA compile time is the binding constraint on everything this repo
claims (ROADMAP; the explorer's HyParView checker compiles ~13-15 min
cold on this box).  PRs 11-12 built the measurement layer — the
flagship registry (``verify/lint/fingerprint.FLAGSHIP``), the compile
ledger (``COMPILE_ledger.jsonl``) and the recompile-regression gate
(``COMPILE_goldens.json``).  This module is the *doing*: each flagship
entrypoint is ``jax.export``-serialized into a versioned artifact that
a cold process deserializes-and-calls in seconds where tracing +
backend compile took minutes (measured 2.2 s vs 41.9 s for the sharded
dataplane round — see BASELINE.md).

An artifact bundle (``aot_artifacts/`` at the repo root) holds, per
program name:

* ``<name>.jexp``       — the serialized :func:`jax.export.export` of a
  *flat* wrapper over the tree-flattened canonical args (export
  serialization cannot carry the repo's custom pytrees, so the
  treedefs travel separately);
* ``<name>.trees.pkl``  — pickled ``(in_tree, out_tree)`` treedefs;
* ``jit_aot_<name>-<cachekey>-cache`` — the persistent-compilation-
  cache entry for the deserialized program, captured at build time by
  calling it once through a jit wrapper *named* ``aot_<name>`` (the
  name lands in the module ``sym_name`` and therefore in the cache
  key, which is what makes the entry identifiable and shippable);
* one ``MANIFEST.json`` for the bundle: per-entry module hash (the
  observatory's lowered-StableHLO sha), file digests, plus the jax /
  jaxlib versions, platform, device count and **canonical cache-dir
  path** they were built against.

The cache-dir path is part of the contract, not a detail: jax embeds
``<cache_dir>/xla_gpu_per_fusion_autotune_cache_dir`` in the compile
options that enter the persistent-cache key, so an entry staged under
one directory is unreachable from another.  Build and load therefore
both pin ``<repo>/.jax_cache`` (``canonical_cache_dir``), and the
manifest records the absolute path so a moved checkout fails NAMED
instead of silently recompiling.

Staleness is NAMED, never silent (SURVEY §7.3 discipline): every load
check that fails raises :class:`AotStale` with a human reason and —
when a ledger is attached — emits an ``aot_stale`` row through the
PR-12 ledger; callers fall back to tracing.  Freshness against the
*code* is delegated to the observatory: :func:`load` compares the
manifest's module hash against ``COMPILE_goldens.json`` (kept honest
by ``scripts/observatory.py --check``), so adopting an artifact never
requires the trace it exists to avoid.

Consumers: ``scripts/warm_cache.py`` (artifact hit -> load, miss ->
compile-and-export), ``bridge/port_server.py`` and ``verify/explorer``
cold starts (:func:`attach` / :func:`adopt`), and the
``scripts/aot_pack.py --build/--verify`` CLI which proves every
deserialized program executes bit-identical to its freshly-traced
twin.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "AotStale", "AotProgram", "MANIFEST_BASENAME", "ARTIFACT_DIRNAME",
    "artifact_dir", "canonical_cache_dir", "read_manifest",
    "export_entry", "build_bundle", "load", "maybe_load", "adopt",
    "attach", "verify_entry",
]

MANIFEST_BASENAME = "MANIFEST.json"
ARTIFACT_DIRNAME = "aot_artifacts"
MANIFEST_VERSION = 1

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def artifact_dir(root: Optional[str] = None) -> str:
    """Default bundle location: ``<repo>/aot_artifacts``."""
    return os.path.join(root or _REPO, ARTIFACT_DIRNAME)


def canonical_cache_dir(root: Optional[str] = None) -> str:
    """The ONE persistent-cache path artifacts are keyed against (the
    cache-dir path leaks into the compile-options hash — module
    docstring)."""
    return os.path.join(root or _REPO, ".jax_cache")


class AotStale(RuntimeError):
    """A named reason an artifact cannot be adopted (fall back to
    tracing; the reason also lands in the ledger as ``aot_stale``)."""

    def __init__(self, name: str, reason: str):
        super().__init__(f"aot[{name}]: {reason}")
        self.name = name
        self.reason = reason


# ----------------------------------------------------------- small utils

def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _env_record() -> Dict[str, Any]:
    import jax
    import jaxlib
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.default_backend(),
        "device_count": jax.device_count(),
    }


def _mesh_shape(leaves: Sequence[Any]) -> Optional[List[int]]:
    """Best-effort mesh shape from the first NamedSharding-committed
    leaf (part of the manifest's staleness key for sharded programs)."""
    for x in leaves:
        sh = getattr(x, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and getattr(mesh, "shape", None):
            return [int(v) for v in dict(mesh.shape).values()]
    return None


def read_manifest(art_dir: Optional[str] = None) -> Optional[Dict[str, Any]]:
    path = os.path.join(art_dir or artifact_dir(), MANIFEST_BASENAME)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _write_manifest(art_dir: str, manifest: Mapping[str, Any]) -> None:
    path = os.path.join(art_dir, MANIFEST_BASENAME)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def _ensure_cache(cache_dir: str) -> None:
    """Point jax's persistent cache at ``cache_dir`` (the canonical
    path) with zeroed write thresholds, matching what the warm-cache /
    observatory discipline already does."""
    import jax
    os.makedirs(cache_dir, exist_ok=True)
    if jax.config.jax_compilation_cache_dir != cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _module_hash(fn: Callable, args: tuple) -> str:
    """The observatory's program identity: sha256 of the lowered
    StableHLO text, truncated to 16 hex chars (matches
    ``telemetry.observatory.measure_entry``)."""
    lowered = fn.trace(*args).lower()
    text = lowered.as_text()
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:16]


def _ledger_aot(ledger: Any, event: str, program: str,
                duration: Optional[float] = None,
                reason: Optional[str] = None,
                fingerprint: Optional[str] = None) -> None:
    if ledger is not None and hasattr(ledger, "record_aot"):
        ledger.record_aot(event, program, duration=duration,
                          reason=reason, fingerprint=fingerprint)


# ---------------------------------------------------------------- build

def export_entry(name: str, fn: Callable, args: tuple,
                 art_dir: Optional[str] = None,
                 cache_dir: Optional[str] = None,
                 ledger: Any = None) -> Dict[str, Any]:
    """Export ONE program into the bundle: serialize the flat wrapper,
    pickle the treedefs, compile the *deserialized* program once under
    the canonical cache dir to capture its ``jit_aot_<name>-*-cache``
    entry, and return the manifest record.  The original ``fn`` is
    lowered (for the module hash) but never backend-compiled — the only
    XLA compile paid here is the exported program's own, which is
    exactly the entry being shipped."""
    import jax
    from jax import export as jexport

    art_dir = art_dir or artifact_dir()
    cache_dir = cache_dir or canonical_cache_dir()
    os.makedirs(art_dir, exist_ok=True)
    _ensure_cache(cache_dir)

    t0 = time.time()
    leaves, in_tree = jax.tree_util.tree_flatten(args)
    mhash = _module_hash(fn, args)

    box: Dict[str, Any] = {}

    def flat(*flat_leaves):
        out = fn(*jax.tree_util.tree_unflatten(in_tree, flat_leaves))
        out_leaves, out_tree = jax.tree_util.tree_flatten(out)
        box["out_tree"] = out_tree
        return tuple(out_leaves)

    exp = jexport.export(jax.jit(flat))(*leaves)
    blob = exp.serialize()
    out_tree = box["out_tree"]

    exp_file = f"{name}.jexp"
    trees_file = f"{name}.trees.pkl"
    with open(os.path.join(art_dir, exp_file), "wb") as f:
        f.write(blob)
    with open(os.path.join(art_dir, trees_file), "wb") as f:
        pickle.dump((in_tree, out_tree), f)

    # compile the DESERIALIZED program (what loaders will run) through a
    # jit wrapper named aot_<name>: the name reaches the module sym_name
    # and hence the persistent-cache key, making the new entry
    # identifiable below.  This is the one real compile of the build.
    exp2 = jexport.deserialize(blob)

    def caller(*flat_leaves):
        return exp2.call(*flat_leaves)
    caller.__name__ = f"aot_{name}"

    before = set(os.listdir(cache_dir))
    out = jax.jit(caller)(*leaves)
    jax.block_until_ready(out)
    new = sorted(p for p in set(os.listdir(cache_dir)) - before
                 if p.startswith(f"jit_aot_{name}-") and p.endswith("-cache"))
    cache_file: Optional[str] = None
    if new:
        cache_file = new[-1]
        shutil.copy(os.path.join(cache_dir, cache_file),
                    os.path.join(art_dir, cache_file))
    else:
        # already cached from a previous build of the same program —
        # find the existing entry so the bundle still ships it
        have = sorted(p for p in os.listdir(cache_dir)
                      if p.startswith(f"jit_aot_{name}-")
                      and p.endswith("-cache"))
        if have:
            cache_file = have[-1]
            shutil.copy(os.path.join(cache_dir, cache_file),
                        os.path.join(art_dir, cache_file))
    built_s = time.time() - t0

    files = {"export": exp_file, "trees": trees_file}
    if cache_file is not None:
        files["cache"] = cache_file
    entry = {
        "module_hash": mhash,
        "files": files,
        "sha256": {k: _sha256_file(os.path.join(art_dir, v))
                   for k, v in files.items()},
        "mesh_shape": _mesh_shape(leaves),
        "n_leaves": len(leaves),
        "built_s": round(built_s, 2),
    }

    manifest = read_manifest(art_dir) or {
        "version": MANIFEST_VERSION, "entries": {}}
    manifest.update(_env_record())
    manifest["version"] = MANIFEST_VERSION
    manifest["cache_dir"] = os.path.abspath(cache_dir)
    manifest.setdefault("entries", {})[name] = entry
    _write_manifest(art_dir, manifest)
    _ledger_aot(ledger, "aot_export", name, duration=built_s,
                fingerprint=mhash)
    return entry


def build_bundle(names: Optional[Sequence[str]] = None,
                 art_dir: Optional[str] = None,
                 cache_dir: Optional[str] = None,
                 ledger: Any = None,
                 progress: Optional[Callable[[str], None]] = None,
                 registry: Optional[Mapping[str, Callable]] = None
                 ) -> Dict[str, Dict[str, Any]]:
    """Export every flagship entrypoint (or ``names``) into the bundle."""
    if registry is None:
        from .verify.lint.fingerprint import FLAGSHIP
        registry = FLAGSHIP
    out: Dict[str, Dict[str, Any]] = {}
    for name, build in registry.items():
        if names is not None and name not in names:
            continue
        if progress:
            progress(name)
        fn, args = build()
        out[name] = export_entry(name, fn, args, art_dir=art_dir,
                                 cache_dir=cache_dir, ledger=ledger)
    return out


# ----------------------------------------------------------------- load

class AotProgram:
    """A loaded artifact: callable with the ORIGINAL (pytree) calling
    convention of its flagship twin.  ``in_tree`` / ``in_avals`` let
    adopters check compatibility before committing."""

    def __init__(self, name: str, exported: Any, in_tree: Any,
                 out_tree: Any, module_hash: str):
        import jax
        self.name = name
        self.exported = exported
        self.in_tree = in_tree
        self.out_tree = out_tree
        self.module_hash = module_hash
        self.in_avals = tuple(exported.in_avals)

        def caller(*flat_leaves):
            return exported.call(*flat_leaves)
        caller.__name__ = f"aot_{name}"
        self._jcall = jax.jit(caller)

    def matches(self, args: tuple) -> bool:
        """True when ``args`` flatten to this program's treedef and
        leaf shapes/dtypes (the adoption precondition)."""
        import jax
        leaves, tree = jax.tree_util.tree_flatten(args)
        if tree != self.in_tree or len(leaves) != len(self.in_avals):
            return False
        for x, av in zip(leaves, self.in_avals):
            if (tuple(getattr(x, "shape", ())) != tuple(av.shape)
                    or getattr(x, "dtype", None) != av.dtype):
                return False
        return True

    def __call__(self, *args):
        import jax
        leaves, tree = jax.tree_util.tree_flatten(args)
        if tree != self.in_tree:
            raise AotStale(self.name,
                           "call args do not flatten to the exported "
                           "treedef — program/caller drift")
        out = self._jcall(*leaves)
        return jax.tree_util.tree_unflatten(self.out_tree, out)


def _golden_hash(name: str, root: Optional[str] = None) -> Optional[str]:
    """Module hash ``COMPILE_goldens.json`` pins for ``name`` (None when
    the goldens file or entry is absent)."""
    path = os.path.join(root or _REPO, "COMPILE_goldens.json")
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        golden = json.load(f)
    rec = golden.get(name)
    return rec.get("module_hash") if isinstance(rec, dict) else None


def load(name: str, art_dir: Optional[str] = None,
         cache_dir: Optional[str] = None,
         expect_module_hash: Optional[str] = "goldens",
         ledger: Any = None) -> AotProgram:
    """Deserialize one artifact, after the staleness gauntlet.  Every
    failure raises :class:`AotStale` with a NAMED reason (and ledgers
    ``aot_stale``); success seeds the canonical cache with the shipped
    entry so the first call is a cache load, not a compile.

    ``expect_module_hash="goldens"`` (default) checks the manifest hash
    against ``COMPILE_goldens.json`` — the cheap no-trace freshness
    check, honest as long as the observatory gate keeps goldens == code.
    Pass an explicit hash (e.g. from ``measure_entry``) for a hard
    check, or ``None`` to skip."""
    import jax
    from jax import export as jexport

    art_dir = art_dir or artifact_dir()
    cache_dir = cache_dir or canonical_cache_dir()

    def stale(reason: str) -> AotStale:
        _ledger_aot(ledger, "aot_stale", name, reason=reason)
        return AotStale(name, reason)

    manifest = read_manifest(art_dir)
    if manifest is None:
        # no bundle at all: nothing is stale, there is just nothing —
        # still a named condition, but not ledgered as aot_stale
        raise AotStale(name, f"no artifact bundle at {art_dir}")
    entry = manifest.get("entries", {}).get(name)
    if entry is None:
        raise stale(f"bundle has no artifact for {name} "
                    f"(run scripts/aot_pack.py --build)")

    env = _env_record()
    for key in ("jax", "jaxlib", "platform", "device_count"):
        want, have = manifest.get(key), env[key]
        if want != have:
            raise stale(f"{key} mismatch: artifact built for {want!r}, "
                        f"process has {have!r}")
    want_cache = manifest.get("cache_dir")
    if want_cache and os.path.abspath(cache_dir) != want_cache:
        raise stale(
            f"cache_dir mismatch: artifacts keyed against {want_cache}, "
            f"process uses {os.path.abspath(cache_dir)} (the cache-dir "
            f"path enters the compile-options hash; rebuild the bundle "
            f"for this checkout)")

    ms = entry.get("mesh_shape")
    if ms:
        need = 1
        for v in ms:
            need *= int(v)
        if need > env["device_count"]:
            raise stale(f"mesh shape mismatch: artifact built on a "
                        f"{ms} mesh ({need} devices), process has "
                        f"{env['device_count']}")

    if expect_module_hash == "goldens":
        expect_module_hash = _golden_hash(name)
    if (expect_module_hash is not None
            and entry["module_hash"] != expect_module_hash):
        raise stale(
            f"module hash drift: artifact serialized "
            f"{entry['module_hash']}, current program is "
            f"{expect_module_hash} — the code moved; rebuild "
            f"(scripts/aot_pack.py --build) after re-blessing")

    for kind, fname in entry["files"].items():
        path = os.path.join(art_dir, fname)
        if not os.path.exists(path):
            raise stale(f"artifact file missing: {fname}")
        if _sha256_file(path) != entry["sha256"][kind]:
            raise stale(f"artifact file corrupt (sha256 mismatch): "
                        f"{fname}")

    _ensure_cache(cache_dir)
    cache_file = entry["files"].get("cache")
    if cache_file is not None:
        dst = os.path.join(cache_dir, cache_file)
        if not os.path.exists(dst):
            shutil.copy(os.path.join(art_dir, cache_file), dst)

    with open(os.path.join(art_dir, entry["files"]["export"]), "rb") as f:
        blob = f.read()
    with open(os.path.join(art_dir, entry["files"]["trees"]), "rb") as f:
        in_tree, out_tree = pickle.load(f)
    try:
        exported = jexport.deserialize(blob)
    except Exception as e:  # deserialization is version-sensitive
        raise stale(f"export blob failed to deserialize: {e!r}")
    return AotProgram(name, exported, in_tree, out_tree,
                      entry["module_hash"])


def maybe_load(name: str, **kw: Any) -> Optional[AotProgram]:
    """:func:`load`, with staleness collapsed to ``None`` (the reason
    was already ledgered when a ledger is attached)."""
    try:
        return load(name, **kw)
    except AotStale:
        return None


def adopt(args: tuple, names: Optional[Sequence[str]] = None,
          art_dir: Optional[str] = None, ledger: Any = None
          ) -> Optional[Tuple[str, AotProgram]]:
    """Find a bundle entry whose exported signature matches ``args``
    (treedef + leaf avals) — the port server's cold-start hook, which
    knows its world but not which flagship name (if any) it equals.
    Returns ``(name, program)`` or None.  Candidate loads that fail the
    staleness gauntlet are skipped (already ledgered)."""
    manifest = read_manifest(art_dir)
    if manifest is None:
        return None
    for name in sorted(manifest.get("entries", {})):
        if names is not None and name not in names:
            continue
        prog = maybe_load(name, art_dir=art_dir, ledger=ledger)
        if prog is not None and prog.matches(args):
            return name, prog
    return None


def attach(name: str, fallback: Callable, art_dir: Optional[str] = None,
           ledger: Any = None,
           on_adopt: Optional[Callable[[AotProgram], None]] = None,
           gate: Optional[Callable[[AotProgram, tuple], bool]] = None
           ) -> Callable:
    """Wrap ``fallback`` with a lazy AOT fast path: the first call
    tries to :func:`load` artifact ``name`` and adopts it if its
    signature matches the actual args; otherwise (or on any named
    staleness) every call goes to ``fallback``.  The adoption attempt
    happens once — cold-start consumers (explorer) pay zero tracing
    when the artifact is fresh and exactly the old path when not.

    ``gate``, when given, runs after the signature match and must
    return True for adoption — the hook where a caller adds a hard
    module-hash check (trace the fallback, compare) when equal avals
    alone cannot distinguish two programs."""
    state: Dict[str, Any] = {"tried": False, "prog": None}

    def dispatch(*args):
        if not state["tried"]:
            state["tried"] = True
            prog = maybe_load(name, art_dir=art_dir, ledger=ledger)
            if prog is not None and prog.matches(args) \
                    and (gate is None or gate(prog, args)):
                state["prog"] = prog
                if on_adopt is not None:
                    on_adopt(prog)
        if state["prog"] is not None:
            return state["prog"](*args)
        return fallback(*args)

    dispatch.__name__ = f"aot_dispatch_{name}"
    dispatch.aot_state = state
    return dispatch


# --------------------------------------------------------------- verify

def verify_entry(name: str, art_dir: Optional[str] = None,
                 cache_dir: Optional[str] = None, ledger: Any = None,
                 registry: Optional[Mapping[str, Callable]] = None
                 ) -> Dict[str, Any]:
    """The bit-identity proof behind ``aot_pack.py --verify``: load the
    artifact, retrace the flagship twin, check the module hash still
    matches the manifest, execute BOTH, and compare every output leaf
    bitwise.  Returns a result record; raises :class:`AotStale` (named)
    on staleness and ``AssertionError`` on a bit mismatch."""
    import numpy as np
    import jax

    if registry is None:
        from .verify.lint.fingerprint import FLAGSHIP
        registry = FLAGSHIP
    if name not in registry:
        raise AotStale(name, "not in the flagship registry")
    fn, args = registry[name]()

    mhash = _module_hash(fn, args)
    t0 = time.time()
    prog = load(name, art_dir=art_dir, cache_dir=cache_dir,
                expect_module_hash=mhash, ledger=ledger)
    t1 = time.time()
    got = prog(*args)
    jax.block_until_ready(got)
    t_load = time.time() - t0
    t2 = time.time()
    ref = fn(*args)
    jax.block_until_ready(ref)
    t_ref = time.time() - t2

    got_leaves = jax.tree_util.tree_leaves(got)
    ref_leaves = jax.tree_util.tree_leaves(ref)
    assert len(got_leaves) == len(ref_leaves), (
        f"{name}: leaf count {len(got_leaves)} != {len(ref_leaves)}")
    for i, (a, b) in enumerate(zip(got_leaves, ref_leaves)):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype and a.shape == b.shape, (
            f"{name}: leaf {i} aval {a.dtype}{a.shape} != "
            f"{b.dtype}{b.shape}")
        if not (a == b).all():
            bad = int(np.sum(a != b))
            raise AssertionError(
                f"{name}: leaf {i} differs in {bad}/{a.size} elements — "
                f"deserialized program is NOT bit-identical to its "
                f"freshly-traced twin")
    _ledger_aot(ledger, "aot_load", name, duration=t_load,
                fingerprint=mhash)
    return {"name": name, "module_hash": mhash, "leaves": len(got_leaves),
            "deserialize_s": round(t1 - t0, 2),
            "load_call_s": round(t_load, 2), "twin_exec_s": round(t_ref, 2),
            "bit_identical": True}
