"""Trace recording + deterministic replay — the TPU rebuild of
``src/partisan_trace_orchestrator.erl`` / ``src/partisan_trace_file.erl``.

The reference records ``{pre_interposition_fun, {Node, Type, Origin, Msg}}``
tuples into an ordered trace, persists them via dets, and under
``REPLAY=true`` blocks every process until its message is next in the trace
(partial-order replay, :160-202, 476-560).

In the round-synchronous simulator, determinism is already total — fixed
PRNG keys make every run bit-identical (SURVEY §5.2) — so "replay" needs no
blocking: re-running with the same Config IS the replay.  What remains of
the orchestrator's job is (a) capturing the wire for inspection and
schedule enumeration, and (b) re-running with an *omission schedule*
applied (faults.drop_schedule), which is exactly what the model checker
explores.  Traces serialize to JSONL (the dets-file analog).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import jax
import numpy as np

from ..config import Config
from ..engine import ProtocolBase, World, init_world, make_step


@dataclasses.dataclass(frozen=True)
class TraceEntry:
    """One wire message (round, src, dst, typ, channel, payload hash)."""
    rnd: int
    src: int
    dst: int
    typ: int
    channel: int
    hash: int

    @property
    def key(self) -> Tuple[int, int, int, int]:
        """Schedule-matching identity (round, src, dst, typ) — the drop
        granularity of faults.drop_schedule."""
        return (self.rnd, self.src, self.dst, self.typ)


class TraceRecorder:
    """Runs a protocol while dumping each round's wire buffer to host.

    >>> rec = TraceRecorder(cfg, proto)
    >>> world = rec.run(world, n_rounds=30)
    >>> rec.entries          # ordered list[TraceEntry]
    """

    def __init__(self, cfg: Config, proto: ProtocolBase,
                 interpose_send=None, interpose_recv=None,
                 randomize_delivery: bool = True):
        self.cfg = cfg
        self.proto = proto
        self.step = make_step(cfg, proto, donate=False,
                              interpose_send=interpose_send,
                              interpose_recv=interpose_recv,
                              randomize_delivery=randomize_delivery,
                              capture_wire=True)
        self.entries: List[TraceEntry] = []

    def run(self, world: World, n_rounds: int,
            on_round: Optional[Callable[[World, Dict], None]] = None
            ) -> World:
        for _ in range(n_rounds):
            world, metrics = self.step(world)
            valid = np.asarray(metrics["wire_valid"])
            if valid.any():
                rnd = int(metrics["round"])
                src = np.asarray(metrics["wire_src"])
                dst = np.asarray(metrics["wire_dst"])
                typ = np.asarray(metrics["wire_typ"])
                ch = np.asarray(metrics["wire_channel"])
                h = np.asarray(metrics["wire_hash"])
                for i in np.flatnonzero(valid):
                    self.entries.append(TraceEntry(
                        rnd, int(src[i]), int(dst[i]), int(typ[i]),
                        int(ch[i]), int(h[i])))
            if on_round is not None:
                on_round(world, metrics)
        return world

    # ------------------------------------------------------------- filtering

    def protocol_entries(self, typs: Iterable[int]) -> List[TraceEntry]:
        """The membership_strategy_tracing filter (:508-560): keep only the
        message types worth exploring."""
        ts = set(typs)
        return [e for e in self.entries if e.typ in ts]


# ------------------------------------------------------------ persistence

def write_trace(path: str, entries: Iterable[TraceEntry]) -> None:
    """partisan_trace_file:write/2 — one JSON object per line."""
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(dataclasses.asdict(e)) + "\n")


def read_trace(path: str) -> List[TraceEntry]:
    """partisan_trace_file:read/1."""
    out = []
    with open(path) as f:
        for line in f:
            if line.strip():
                out.append(TraceEntry(**json.loads(line)))
    return out
