"""Trace recording + deterministic replay — the TPU rebuild of
``src/partisan_trace_orchestrator.erl`` / ``src/partisan_trace_file.erl``.

The reference records ``{pre_interposition_fun, {Node, Type, Origin, Msg}}``
tuples into an ordered trace, persists them via dets, and under
``REPLAY=true`` blocks every process until its message is next in the trace
(partial-order replay, :160-202, 476-560).

In the round-synchronous simulator, determinism is already total — fixed
PRNG keys make every run bit-identical (SURVEY §5.2) — so "replay" needs no
blocking: re-running with the same Config IS the replay.  What remains of
the orchestrator's job is (a) capturing the wire for inspection and
schedule enumeration, and (b) re-running with an *omission schedule*
applied (faults.drop_schedule), which is exactly what the model checker
explores.  Traces serialize to JSONL (the dets-file analog).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import jax
import numpy as np

from ..config import Config
from ..engine import ProtocolBase, World, init_world, make_step


@dataclasses.dataclass(frozen=True)
class TraceEntry:
    """One wire message (round, src, dst, typ, channel, payload hash)."""
    rnd: int
    src: int
    dst: int
    typ: int
    channel: int
    hash: int

    @property
    def key(self) -> Tuple[int, int, int, int]:
        """Schedule-matching identity (round, src, dst, typ) — the drop
        granularity of faults.drop_schedule."""
        return (self.rnd, self.src, self.dst, self.typ)


class TraceRecorder:
    """Runs a protocol while dumping each round's wire buffer to host.

    >>> rec = TraceRecorder(cfg, proto)
    >>> world = rec.run(world, n_rounds=30)
    >>> rec.entries          # ordered list[TraceEntry]

    Two capture paths fill the same ``entries`` stream:

    * :meth:`run` — the legacy per-round path (``capture_wire=True``):
      one device->host transfer of the whole wire buffer EVERY round.
      Keep for per-round host callbacks (``on_round``).
    * :meth:`run_windowed` — the flight-recorder fast path (ISSUE 3):
      ``window`` rounds compile into one ``lax.scan`` carrying a
      device-side :class:`telemetry.flight.FlightRing`; ONE transfer
      per window.  Entry-for-entry identical to :meth:`run`
      (tests/test_flight.py pins the bit-match), so everything
      downstream — the model checker, ``faults.drop_schedule`` keys,
      the golden crosswalk, :func:`write_trace` — is unchanged.
    """

    def __init__(self, cfg: Config, proto: ProtocolBase,
                 interpose_send=None, interpose_recv=None,
                 randomize_delivery: bool = True):
        self.cfg = cfg
        self.proto = proto
        self._step_kw = dict(interpose_send=interpose_send,
                             interpose_recv=interpose_recv,
                             randomize_delivery=randomize_delivery)
        self.step = make_step(cfg, proto, donate=False,
                              capture_wire=True, **self._step_kw)
        self.entries: List[TraceEntry] = []
        # windowed-path state: compiled scans per (window, cap) and the
        # cumulative head-capped slot count (0 at the lossless default)
        self._flight_runners: Dict = {}
        self.flight_overflow: int = 0

    def run(self, world: World, n_rounds: int,
            on_round: Optional[Callable[[World, Dict], None]] = None
            ) -> World:
        for _ in range(n_rounds):
            world, metrics = self.step(world)
            valid = np.asarray(metrics["wire_valid"])
            if valid.any():
                rnd = int(metrics["round"])
                src = np.asarray(metrics["wire_src"])
                dst = np.asarray(metrics["wire_dst"])
                typ = np.asarray(metrics["wire_typ"])
                ch = np.asarray(metrics["wire_channel"])
                h = np.asarray(metrics["wire_hash"])
                for i in np.flatnonzero(valid):
                    self.entries.append(TraceEntry(
                        rnd, int(src[i]), int(dst[i]), int(typ[i]),
                        int(ch[i]), int(h[i])))
            if on_round is not None:
                on_round(world, metrics)
        return world

    # --------------------------------------------------- windowed fast path

    def _flight_runner(self, window: int, cap: int):
        """One compiled (scan-of-step, ring) pair per (window, cap)."""
        import functools
        import jax
        from ..telemetry.flight import FlightSpec
        key = (window, cap)
        hit = self._flight_runners.get(key)
        if hit is not None:
            return hit
        spec = FlightSpec(window=window, cap=cap)
        fstep = make_step(self.cfg, self.proto, donate=False,
                          flight=spec, **self._step_kw)

        @functools.partial(jax.jit, static_argnames=("length",))
        def run_window(world, ring, length):
            def body(carry, _):
                w, r = carry
                w2, r2, _m = fstep(w, r)
                return (w2, r2), None
            (w2, r2), _ = jax.lax.scan(body, (world, ring), None,
                                       length=length)
            return w2, r2

        self._flight_runners[key] = (spec, run_window)
        return spec, run_window

    def run_windowed(self, world: World, n_rounds: int,
                     window: int = 32,
                     cap: Optional[int] = None) -> World:
        """Record ``n_rounds`` through the in-scan flight recorder: one
        jitted ``window``-round scan + ONE ring transfer per window (a
        trailing partial window reuses the same compiled scan via the
        static ``length`` arg).  ``cap`` defaults to the world's buffer
        capacity — lossless; a tighter cap head-caps each round's
        capture with the excess counted in ``flight_overflow``, never
        silent."""
        from .. import telemetry
        from ..telemetry.flight import (flight_entries, flight_flush,
                                        make_flight_ring)
        cap = cap or world.msgs.cap
        spec, run_window = self._flight_runner(window, cap)
        ring = make_flight_ring(spec)
        done = 0
        while done < n_rounds:
            length = min(window, n_rounds - done)
            world, ring = run_window(world, ring, length)
            rows, overflow, ring = flight_flush(ring)  # the sync point
            self.entries.extend(flight_entries(rows))
            self.flight_overflow += overflow
            done += length
            telemetry.note_round(int(world.rnd))
        return world

    # ------------------------------------------------------------- filtering

    def protocol_entries(self, typs: Iterable[int]) -> List[TraceEntry]:
        """The membership_strategy_tracing filter (:508-560): keep only the
        message types worth exploring."""
        ts = set(typs)
        return [e for e in self.entries if e.typ in ts]


# ------------------------------------------------------------ persistence

def write_trace(path: str, entries: Iterable[TraceEntry]) -> None:
    """partisan_trace_file:write/2 — one JSON object per line."""
    with open(path, "w") as f:
        for e in entries:
            f.write(json.dumps(dataclasses.asdict(e)) + "\n")


def read_trace(path: str) -> List[TraceEntry]:
    """partisan_trace_file:read/1."""
    out = []
    with open(path) as f:
        for line in f:
            if line.strip():
                out.append(TraceEntry(**json.loads(line)))
    return out
