"""Property-based cluster testing — the TPU rebuild of
``test/prop_partisan.erl`` (proper statem, 1162 LoC).

The reference composes three command sources — cluster commands
(join/leave), fault-model commands (crash, omissions), and pluggable
system-model commands (:62-104, 302-325) — generates random sequences,
runs them against a live cluster, and shrinks failures.  Here a command
sequence is generated from a seeded RNG, applied to a World
interleaved with simulation rounds, and the system model's assertions run
after a fault-free settling window (the reference asserts after resolving
faults too).  Failures shrink by greedy command-deletion (delta
debugging), which is exactly what proper's shrinking does to statem
command lists.

System models implement the prop_partisan node-model contract
(node_commands/node_assertion_functions — prop_partisan.erl:273-460):

  * ``commands(rng, n_nodes) -> list[Command]`` candidate pool
  * ``assert_ok(world, proto) -> None`` (raise AssertionError on violation)
"""

from __future__ import annotations

import dataclasses
import random as _random
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..engine import ProtocolBase, World, init_world, make_step
from .. import peer_service
from . import faults


@dataclasses.dataclass(frozen=True)
class Command:
    """One abstract cluster/fault command (the statem symbolic call)."""
    verb: str                 # join | leave | crash | recover | partition |
                              # resolve_partition | <system-model verb>
    args: Tuple = ()

    def __repr__(self) -> str:
        return f"{self.verb}{self.args}"


def apply_command(world: World, proto: ProtocolBase,
                  cmd: Command) -> World:
    if cmd.verb == "join":
        return peer_service.join(world, proto, *cmd.args)
    if cmd.verb == "leave":
        return peer_service.leave(world, proto, cmd.args[0])
    if cmd.verb == "crash":
        return faults.crash(world, [cmd.args[0]])
    if cmd.verb == "recover":
        return faults.recover(world, [cmd.args[0]])
    if cmd.verb == "partition":
        return faults.inject_partition(world, [list(cmd.args[0])])
    if cmd.verb == "resolve_partition":
        return faults.resolve_partition(world)
    raise ValueError(f"unknown command verb {cmd.verb}")


class ClusterCommands:
    """The cluster + crash-fault command pool (prop_partisan cluster
    commands + prop_partisan_crash_fault_model :33-37), bounded by a crash
    ``tolerance`` exactly like the reference's fault model."""

    def __init__(self, n_nodes: int, tolerance: int = 1,
                 with_partitions: bool = True):
        self.n = n_nodes
        self.tolerance = tolerance
        self.with_partitions = with_partitions
        self._crashed: set = set()

    def reset(self) -> None:
        self._crashed = set()

    def next_command(self, rng: _random.Random) -> Command:
        verbs = ["join", "join", "join", "leave"]
        if len(self._crashed) < self.tolerance:
            verbs.append("crash")
        if self._crashed:
            verbs.append("recover")
        if self.with_partitions:
            verbs += ["partition", "resolve_partition"]
        v = rng.choice(verbs)
        if v == "join":
            a, b = rng.sample(range(self.n), 2)
            return Command("join", (a, b))
        if v == "leave":
            return Command("leave", (rng.randrange(self.n),))
        if v == "crash":
            victim = rng.choice(
                [i for i in range(self.n) if i not in self._crashed])
            self._crashed.add(victim)
            return Command("crash", (victim,))
        if v == "recover":
            victim = rng.choice(sorted(self._crashed))
            self._crashed.discard(victim)
            return Command("recover", (victim,))
        if v == "partition":
            k = rng.randrange(1, self.n)
            return Command("partition",
                           (tuple(rng.sample(range(self.n), k)),))
        return Command("resolve_partition")


@dataclasses.dataclass
class Failure:
    seed: int
    commands: List[Command]      # shrunk sequence
    original_len: int
    error: str


@dataclasses.dataclass
class PropResult:
    cases: int
    failures: List[Failure]

    @property
    def ok(self) -> bool:
        return not self.failures


class PropRunner:
    """prop_sequential (:62-104): random command sequences against a fresh
    cluster, post-settle assertions, shrinking on failure."""

    def __init__(self, cfg: Config, proto: ProtocolBase,
                 assert_ok: Callable[[World, ProtocolBase], None],
                 commands: Optional[ClusterCommands] = None,
                 rounds_between: int = 3,
                 settle_rounds: int = 40):
        self.cfg = cfg
        self.proto = proto
        self.assert_ok = assert_ok
        self.commands = commands or ClusterCommands(cfg.n_nodes)
        self.rounds_between = rounds_between
        self.settle_rounds = settle_rounds
        self.step = make_step(cfg, proto, donate=False)

    # ------------------------------------------------------------- execution

    def _execute(self, cmds: Sequence[Command]) -> None:
        """Run one case; raises on assertion failure."""
        import jax.numpy as jnp
        world = init_world(self.cfg, self.proto)
        # formation phase: everyone joins via node 0 and the overlay
        # settles (the reference's support harness clusters first; random
        # commands then perturb a live cluster)
        world = peer_service.cluster(
            world, self.proto,
            [(i, 0) for i in range(1, self.cfg.n_nodes)], stagger=4)
        for _ in range(self.settle_rounds):
            world, _ = self.step(world)
        for cmd in cmds:
            world = apply_command(world, self.proto, cmd)
            for _ in range(self.rounds_between):
                world, _ = self.step(world)
        # settle: resolve partitions + recover everyone (the reference
        # resolves faults before asserting), then let repair run
        world = faults.resolve_partition(world)
        world = world.replace(alive=jnp.ones_like(world.alive))
        for _ in range(self.settle_rounds):
            world, _ = self.step(world)
        self.assert_ok(world, self.proto)

    def _generate(self, seed: int, n_commands: int) -> List[Command]:
        rng = _random.Random(seed)
        self.commands.reset()
        return [self.commands.next_command(rng) for _ in range(n_commands)]

    def _shrink(self, cmds: List[Command]) -> List[Command]:
        """Greedy delta-debugging: drop commands while the case still
        fails (proper's statem shrinking collapsed to one pass)."""
        current = list(cmds)
        improved = True
        while improved:
            improved = False
            for i in range(len(current)):
                cand = current[:i] + current[i + 1:]
                try:
                    self._execute(cand)
                except AssertionError:
                    current = cand
                    improved = True
                    break
        return current

    def check(self, n_cases: int = 10, n_commands: int = 12,
              shrink: bool = True) -> PropResult:
        failures: List[Failure] = []
        for seed in range(n_cases):
            cmds = self._generate(seed, n_commands)
            try:
                self._execute(cmds)
            except AssertionError as e:
                shrunk = self._shrink(cmds) if shrink else cmds
                failures.append(Failure(seed, shrunk, len(cmds), str(e)))
        return PropResult(n_cases, failures)


# ------------------------------------------------- stock assertion models

def connectivity_model(view_attr: str = "active"):
    """The reliable-broadcast/membership system-model assertion: after
    settling, alive nodes form a connected overlay
    (prop_partisan_reliable_broadcast + hyparview_membership_check)."""
    from ..ops import graph
    import jax.numpy as jnp

    def assert_ok(world: World, proto: ProtocolBase) -> None:
        views = getattr(world.state, view_attr)
        n = np.asarray(world.alive).shape[0]
        left = getattr(world.state, "left", None)
        active_nodes = np.asarray(world.alive)
        if left is not None:
            active_nodes = active_nodes & ~np.asarray(left)
        if active_nodes.sum() < 2:
            return
        adj = graph.adjacency_from_views(views, n)
        ok = graph.is_connected(adj, jnp.asarray(active_nodes))
        assert bool(ok), \
            f"overlay disconnected among alive nodes {np.flatnonzero(active_nodes)}"
    return assert_ok


def convergence_model():
    """Full-membership convergence assertion: all alive nodes agree."""
    import jax

    def assert_ok(world: World, proto: ProtocolBase) -> None:
        masks = np.asarray(jax.vmap(proto.member_mask)(world.state))
        alive = np.asarray(world.alive)
        left = getattr(world.state, "left", None)
        if left is not None:  # departed nodes stopped; their view is moot
            alive = alive & ~np.asarray(left)
        rows = masks[alive]
        if rows.shape[0] == 0:  # everyone left/crashed: vacuously agreed
            return
        assert (rows == rows[0]).all(), "membership views diverged"
    return assert_ok
