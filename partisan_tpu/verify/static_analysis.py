"""Static causality analysis — the compile-time half of the
``partisan_analysis`` analog.

The reference derives its annotation files by a *static* walk of each
protocol's Core-Erlang AST (``src/partisan_analysis.erl:9-14``: 1237
LoC of cerl traversal mapping each receive clause to the sends its body
can reach), and only then hand-checks the result into
``annotations/partisan-annotations-<mod>``.  The rebuild's dynamic
inference (verify/analysis.py) samples the executed handlers instead —
an UNDER-approximation wherever sampling misses a branch, which is the
wrong direction for the model checker's independence pruning (VERDICT
r4 missing #3: a pruned schedule is only sound if the causality map is
a SUPERSET of the truth).

This module restores the reference's direction.  Rebuilt handlers are
plain Python methods (``handle_<type>`` / ``tick``,
engine.ProtocolBase), and every emitted wire tag is built by a
``self.typ("<literal>")`` call — so a transitive AST walk over a
handler and every self-method it can reach collects a superset of the
tags the handler can ever put on the wire, no execution needed:

    true causality  ⊆  static_causality   (every emission site is a
                                           typ() literal in some
                                           reachable method body)
    dynamic inference ⊆ true causality    (only observed emissions)

so ``static ⊇ dynamic`` is machine-checkable (test_static_analysis.py
asserts it protocol by protocol) and pruning with the static map is
sound by construction.  The cost is the usual flow-insensitivity: a
``typ()`` literal mentioned in a dead branch, or used only in a
comparison, still lands in the edge set — extra edges mean the checker
prunes less, never wrongly.

Guarantees and their guards:
  * non-literal ``self.typ(x)`` anywhere reachable -> loud ValueError
    (the walk cannot bound what ``x`` is; no protocol in the tree does
    this — the guard keeps it that way);
  * a call that passes ``self`` to a non-method -> ValueError likewise
    (emissions could hide behind it);
  * methods are resolved on ``type(proto)`` so subclass overrides
    (e.g. BernsteinCTP._participant_tick) are the bodies walked;
  * zero-arg ``super().method()`` resolves past the defining class via
    the MRO and the parent body is walked too (XBotHyParView.tick ->
    HyParView.tick's shuffle/promotion literals); two-arg super and
    ``super().typ`` raise rather than under-approximate.

Output matches verify/analysis.py's map shape — ``{type: [caused
types]}`` plus ``__tick__`` — and plugs directly into
ModelChecker.check(annotations=...).  There is deliberately NO static
``__background__``: schedule-independence of a timer send is a
property of state reachability, which a syntactic walk cannot certify;
leaving the key absent makes the checker treat every tick emission as
related-to-everything (maximally conservative).  Use
:func:`merged_causality` to combine the static edge superset with the
dynamic pass's probe-certified background classification.
"""

from __future__ import annotations

import ast
import inspect
import os
import textwrap
from typing import Dict, List, Optional, Set

from ..engine import ProtocolBase

# ProtocolBase utilities that build/bundle messages but contain no
# typ() literals of their own — skipping them keeps the walk small;
# walking them anyway would be harmless (they are literal-free).
_LEAF_METHODS = frozenset({
    "typ", "emit", "no_emit", "merge", "replace", "handlers", "init",
})


def _fn_ast(fn):
    fn = getattr(fn, "__func__", fn)   # unwrap class/static methods
    if fn is None or not callable(fn):
        return None
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):       # C-level / dynamically built
        return None
    return ast.parse(src)


def _defining_class(cls: type, name: str):
    """First class in cls's MRO whose __dict__ holds ``name`` — the
    class ``super()`` inside that body resolves RELATIVE TO."""
    for c in cls.__mro__:
        if name in c.__dict__:
            return c
    return None


def _resolve_super(cls: type, defining: type, name: str):
    """Zero-arg ``super().name`` resolution as the interpreter performs
    it: the first class AFTER ``defining`` in ``cls``'s MRO that
    defines ``name``."""
    mro = cls.__mro__
    try:
        i = mro.index(defining)
    except ValueError:                 # pragma: no cover — defensive
        return None
    for c in mro[i + 1:]:
        if name in c.__dict__:
            return c
    return None


def _is_super_attr(f) -> bool:
    """AST shape of ``super().<attr>`` (zero-arg form)."""
    return (isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Call)
            and isinstance(f.value.func, ast.Name)
            and f.value.func.id == "super")


def _walk_method(cls: type, name: str, seen: Set[str],
                 out: Set[str], owner: type = None) -> None:
    """Accumulate into ``out`` every ``self.typ("<lit>")`` argument in
    ``name``'s body and, transitively, in every self-method it calls —
    including methods reached through zero-arg ``super()`` (ADVICE r5
    high: ``XBotHyParView.tick`` calls ``super().tick``, whose shuffle/
    promotion literals the walk previously missed SILENTLY, violating
    the superset-or-loud-ValueError contract).  ``owner`` pins which
    MRO class supplies the body (the super() chain); None = dynamic
    resolution on ``cls``.  ``seen`` keys on (defining class, name) so
    an override and the parent body it extends are both walked."""
    if name in _LEAF_METHODS:
        return
    defining = owner if owner is not None else _defining_class(cls, name)
    if defining is None:
        # not a class attribute anywhere in the MRO (instance-only data
        # attr, or plain absent) — nothing to walk
        return
    key = (defining.__qualname__, name)
    if key in seen:
        return
    seen.add(key)
    tree = _fn_ast(defining.__dict__.get(name))
    if tree is None:
        return
    # direct-call positions: an Attribute that is the func of some Call.
    # `self.typ` referenced anywhere ELSE (t = self.typ; t("pong")) is
    # an alias the literal extraction below cannot see through — refuse
    # it loudly (code-review r5: aliasing silently evaded both guards)
    call_funcs = {id(n.func) for n in ast.walk(tree)
                  if isinstance(n, ast.Call)}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr == "typ"
                and id(node) not in call_funcs):
            raise ValueError(
                f"{cls.__name__}.{name}: self.typ referenced outside a "
                f"direct call (line {node.lineno}) — aliasing would "
                f"evade the literal extraction")
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            is_self_call = (isinstance(f, ast.Attribute)
                            and isinstance(f.value, ast.Name)
                            and f.value.id == "self")
            if is_self_call and f.attr == "typ":
                if (not node.args
                        or not isinstance(node.args[0], ast.Constant)
                        or not isinstance(node.args[0].value, str)):
                    raise ValueError(
                        f"{cls.__name__}.{name}: non-literal "
                        f"self.typ(...) call — the static walk cannot "
                        f"bound its value (line {node.lineno})")
                out.add(node.args[0].value)
            elif _is_super_attr(f):
                # super().method(...) — resolve past the DEFINING class
                # via the MRO and walk the parent body (ADVICE r5 high:
                # skipping it silently under-approximated the edge set)
                if f.value.args:
                    raise ValueError(
                        f"{cls.__name__}.{name}: two-arg super() call "
                        f"(line {node.lineno}) — only zero-arg super "
                        f"resolution is modeled; the walk cannot bound "
                        f"an explicit-class dispatch")
                if f.attr == "typ":
                    raise ValueError(
                        f"{cls.__name__}.{name}: super().typ(...) "
                        f"(line {node.lineno}) — tag literals must go "
                        f"through self.typ for the literal extraction")
                parent = _resolve_super(cls, defining, f.attr)
                if parent is None:
                    raise ValueError(
                        f"{cls.__name__}.{name}: super().{f.attr} "
                        f"(line {node.lineno}) resolves to nothing "
                        f"past {defining.__name__} in the MRO — "
                        f"refusing to under-approximate")
                _walk_method(cls, f.attr, seen, out, owner=parent)
            elif not is_self_call:
                # emissions can only hide behind a callee that receives
                # `self`; refuse loudly rather than under-approximate
                for a in (list(node.args)
                          + [kw.value for kw in node.keywords]):
                    if isinstance(a, ast.Name) and a.id == "self":
                        raise ValueError(
                            f"{cls.__name__}.{name}: passes self to a "
                            f"non-method callable (line {node.lineno}) "
                            f"— static emission walk would be unsound")
        elif (isinstance(node, ast.Attribute)
              and isinstance(node.value, ast.Name)
              and node.value.id == "self"):
            # ANY self.<attr> reference — called, passed as a branch
            # callee to lax.cond/switch/vmap, or stored — is walked if
            # it resolves to a method on the class; non-callables and
            # instance-only data attrs resolve to None and are skipped
            _walk_method(cls, node.attr, seen, out)


def _reachable_typs(proto: ProtocolBase, method: str) -> Set[str]:
    out: Set[str] = set()
    _walk_method(type(proto), method, set(), out)
    return out & set(proto.msg_types)


def static_causality(proto: ProtocolBase) -> Dict[str, List[str]]:
    """{message type: sorted superset of types its handler can emit},
    plus ``__tick__`` for the timer pseudo-source — the static analog
    of verify/analysis.py:infer_causality (same map shape, opposite
    approximation direction).

    Stacked compositions are walked component-wise: each layer's
    ``typ()`` literals resolve in its OWN name space (stack.py offsets
    them at runtime), so the per-layer maps are exact sub-maps of the
    combined relation; the upper layer's timer source is its
    ``tick_upper``.  A type name shared by both layers unions its edge
    sets (conservative — the two tags are distinct on the wire)."""
    from ..models.stack import Stacked
    if isinstance(proto, Stacked):
        lo = static_causality(proto.lower)
        up: Dict[str, List[str]] = {}
        for t in proto.upper.msg_types:
            up[t] = sorted(_reachable_typs(proto.upper, "handle_" + t))
        up["__tick__"] = sorted(_reachable_typs(proto.upper, "tick_upper"))
        keys = set(lo) | set(up)
        return {k: sorted(set(lo.get(k, [])) | set(up.get(k, [])))
                for k in keys}
    out: Dict[str, List[str]] = {}
    for t in proto.msg_types:
        out[t] = sorted(_reachable_typs(proto, "handle_" + t))
    out["__tick__"] = sorted(_reachable_typs(proto, "tick"))
    return out


# --------------------------------------------------------------------- #
# Dense-dataplane mail kinds (ISSUE 11 satellite): the dense protocols  #
# bypass ProtocolBase entirely — no self.typ() literals to walk.  Their #
# wire tags are the integer `kind` column of the mail block, written    #
# only by _emit() / its functools.partial alias `emit`, always from a   #
# module-level K_*/S_* constant.  The same superset contract therefore  #
# holds by a different walk: collect the kind argument of every emit    #
# site in the round builder's scope and resolve it against the module   #
# constants.  Anything that does not resolve to a static int is an      #
# UNBOUNDED wire tag and raises — the static map could no longer be a   #
# superset of what the round puts on the wire.                          #
# --------------------------------------------------------------------- #

# round-builder scope per dense model; hyparview and plumtree share one
# builder (model= is a build-time flag) and hence one kind space
_DENSE_SCOPES = {
    "hyparview": ("make_sharded_dense_round", "HV_KINDS"),
    "plumtree": ("make_sharded_dense_round", "HV_KINDS"),
    "scamp": ("_make_sharded_scamp_round", "SCAMP_KINDS"),
}

# kind-argument position: _emit(blocks, n_loc, gids, alive, part, dst,
# kind, ...) and emit = partial(_emit, blocks, n_loc, gids)
_EMIT_KIND_POS = {"_emit": 6, "emit": 3}


def _dense_source() -> str:
    # read, don't import — keeps this walk pure AST like the rest of
    # the module (dense_dataplane pulls in the whole jax stack)
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "parallel", "dense_dataplane.py")
    with open(path, encoding="utf-8") as f:
        return f.read()


def dense_static_kinds(model: str = "hyparview",
                       source: Optional[str] = None) -> Set[int]:
    """Superset of the integer mail kinds ``model``'s dense round can
    put on the wire — the dense analog of :func:`static_causality`
    (``source`` overrides the dense_dataplane module text, for tests).

    Raises ValueError (named site) for an emit call whose kind is
    neither an int literal nor a module-level int constant, and for a
    resolved kind outside ``[0, <KINDS>)`` — either way the tag space
    would be unbounded and the static-superset contract void."""
    if model not in _DENSE_SCOPES:
        raise ValueError(f"unknown dense model {model!r}; "
                         f"one of {sorted(_DENSE_SCOPES)}")
    scope, space_name = _DENSE_SCOPES[model]
    tree = ast.parse(source if source is not None else _dense_source())
    consts: Dict[str, int] = {}
    fn = None
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
                and not isinstance(node.value.value, bool)):
            consts[node.targets[0].id] = node.value.value
        elif isinstance(node, ast.FunctionDef) and node.name == scope:
            fn = node
    if fn is None:
        raise ValueError(f"dense round builder {scope!r} not found")
    n_kinds = consts.get(space_name)
    out: Set[int] = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _EMIT_KIND_POS):
            continue
        kind = next((kw.value for kw in node.keywords
                     if kw.arg == "kind"), None)
        if kind is None:
            pos = _EMIT_KIND_POS[node.func.id]
            if len(node.args) <= pos:
                raise ValueError(
                    f"{scope}: {node.func.id}() call at line "
                    f"{node.lineno} has no kind argument — the walk "
                    f"cannot bound its wire tag")
            kind = node.args[pos]
        if isinstance(kind, ast.Constant) and isinstance(kind.value, int):
            val = kind.value
        elif isinstance(kind, ast.Name) and kind.id in consts:
            val = consts[kind.id]
        else:
            raise ValueError(
                f"{scope}: emit at line {node.lineno} has a non-static "
                f"mail kind {ast.unparse(kind)!r} — unbounded wire tag "
                f"voids the static-superset contract")
        if n_kinds is not None and not 0 <= val < n_kinds:
            raise ValueError(
                f"{scope}: emit at line {node.lineno} kind {val} is "
                f"outside [0, {space_name}={n_kinds})")
        out.add(val)
    return out


def merged_causality(static: Dict[str, List[str]],
                     dynamic: Dict[str, List[str]]) -> Dict[str, List[str]]:
    """Edge-superset union of the two passes, keeping the dynamic
    pass's probe-certified ``__background__`` (static cannot produce
    one).  Union preserves pruning soundness — the result still
    contains every true edge because the static side alone does —
    while the background key recovers the dynamic pass's
    delivery-insensitivity pruning for unconditional periodic sends."""
    keys = set(static) | set(dynamic)
    out = {k: sorted(set(static.get(k, [])) | set(dynamic.get(k, [])))
           for k in keys if k != "__background__"}
    if "__background__" in dynamic:
        out["__background__"] = list(dynamic["__background__"])
    return out
