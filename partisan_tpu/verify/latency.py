"""The compiled geo/WAN latency plane (ISSUE 19 tentpole b).

The reference suite sweeps realistic WAN round-trip times with netem —
``partisan_SUITE.erl:1029-1136`` runs its cluster groups under RTT in
{1, 20, 100} ms (SURVEY §6) — while the simulator's only latency knob so
far is the chaos plane's KIND_DELAY (one (src, dst, round) bump).  This
module generalizes it into a topology: every node lives in a REGION, and
every (region, region) pair has a base RTT in rounds, plus deterministic
per-message jitter.  The plane is a jit closure constant (frozen,
hashable), applied at EMISSION time in both dataplanes:

  * emission, not the ready buffer: a delay stamped once at birth ages
    through the existing held-buffer arithmetic; a ready-buffer bump
    would re-fire every round a message sits held;
  * the one-way split is asymmetric-exact — ``src < dst`` pays
    ``ceil(rtt / 2)``, the reverse direction ``floor(rtt / 2)`` — so any
    request/response pair crossing the same region edge pays EXACTLY the
    configured RTT, which is what makes ``models/distance.py``'s
    ping/pong the plane's built-in validator (measured RTT == 2 + rtt,
    the 2 being the simulator's own hop-per-round floor);
  * jitter hashes MESSAGE FIELDS only (seed, src, dst, born, typ) —
    never buffer positions — so the sharded and unsharded paths stamp
    bit-identical delays (the chaos planes' residency discipline);
  * zero collectives, zero new metric keys: the plane is pure slot-local
    int arithmetic folded into the existing delay field, so the sharded
    budget {all-to-all: 1, all-reduce: 1, all-gather: 0} holds, and
    ``latency=None`` is Python-gated — the lowered program is
    byte-identical to one built before this module existed.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from ..ops.msg import Msgs
from ..ops.bitset import mix32 as _mix32


@dataclasses.dataclass(frozen=True)
class LatencyPlane:
    """A frozen (hashable) WAN topology: ``regions[n]`` maps node id ->
    region, ``base_rtt[R][R]`` is the symmetric-intent region-pair RTT
    in ROUNDS (the wan_* soak cells use 1 round ~= 10 ms), and
    ``jitter_milli`` adds +1 round to a deterministic ``jitter_milli``
    per-mille of messages (counter-based hash of ``seed`` and the
    message's fields).  Build::

        plane = LatencyPlane(regions=(0,) * 32 + (1,) * 32,
                             base_rtt=((0, 2), (2, 0)),
                             jitter_milli=50, seed=7)
    """

    regions: Tuple[int, ...]
    base_rtt: Tuple[Tuple[int, ...], ...]
    jitter_milli: int = 0
    seed: int = 0

    def __post_init__(self):
        # normalize to hashable tuples so literal lists work too
        object.__setattr__(self, "regions", tuple(int(r) for r in
                                                  self.regions))
        object.__setattr__(self, "base_rtt", tuple(
            tuple(int(v) for v in row) for row in self.base_rtt))

    @property
    def n_regions(self) -> int:
        return len(self.base_rtt)

    def validate(self, n_nodes: int) -> "LatencyPlane":
        """Compile-point validation (the ChaosSchedule.validate pattern):
        shape/range errors raise named ValueErrors instead of folding
        into silent misdelivery."""
        if len(self.regions) != n_nodes:
            raise ValueError(
                f"latency plane maps {len(self.regions)} nodes but the "
                f"config has {n_nodes}")
        r = self.n_regions
        if any(len(row) != r for row in self.base_rtt):
            raise ValueError(
                f"base_rtt must be square, got rows of "
                f"{[len(row) for row in self.base_rtt]} for {r} regions")
        if any(not 0 <= reg < r for reg in self.regions):
            raise ValueError(
                f"region ids must be in [0, {r}), got {self.regions}")
        if any(v < 0 for row in self.base_rtt for v in row):
            raise ValueError("base_rtt entries must be >= 0 rounds")
        if not 0 <= self.jitter_milli <= 1000:
            raise ValueError(
                f"jitter_milli is a per-mille rate in [0, 1000], got "
                f"{self.jitter_milli}")
        return self


def apply_latency(plane: LatencyPlane, m: Msgs) -> Msgs:
    """Stamp the plane's per-edge one-way delay onto a freshly emitted
    buffer (call where the dataplanes stamp ingress/egress delay).  Pure
    slot-local arithmetic over message fields; invalid slots untouched
    in effect (their delay is never read)."""
    reg = jnp.asarray(plane.regions, jnp.int32)
    rtt = jnp.asarray(plane.base_rtt, jnp.int32)
    n = reg.shape[0]
    src = jnp.clip(m.src, 0, n - 1)
    dst = jnp.clip(m.dst, 0, n - 1)
    pair = rtt[reg[src], reg[dst]]
    # asymmetric-exact split: the low->high direction pays the ceiling,
    # high->low the floor, so a round trip over one edge totals `pair`
    oneway = jnp.where(m.src < m.dst, (pair + 1) // 2, pair // 2)
    extra = oneway
    if plane.jitter_milli:
        h = _mix32(m.src.astype(jnp.uint32)
                   ^ _mix32(m.dst.astype(jnp.uint32)
                            ^ _mix32(m.born.astype(jnp.uint32)
                                     ^ _mix32(m.typ.astype(jnp.uint32)
                                              ^ jnp.uint32(plane.seed)))))
        jit = (h % jnp.uint32(1000)
               < jnp.uint32(plane.jitter_milli)).astype(jnp.int32)
        extra = extra + jit
    extra = jnp.where(m.valid, extra, 0)
    return m.replace(delay=m.delay + extra)
