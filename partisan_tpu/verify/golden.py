"""Parser for the reference's golden causality annotation files
(``/root/reference/annotations/partisan-annotations-<protocol>``) — the
hand-checked edge sets ``partisan_analysis.erl:9-14`` feeds the
filibuster checker's independence pruning with.

File shape (an Erlang term):

    [
        {causality, [
            {{forward_message, T}, [{{receive_message, P}, Count}]},
            {{forward_message, T2}, [true]}
        ]},
        {background, [heartbeat, ...]}
    ].

Meaning: sending ``T`` is causally enabled by having received ``Count``
messages of type ``P`` (a quorum precondition); ``[true]`` marks a
spontaneous send (client/timer-originated); ``background`` lists the
unconditionally periodic types the checker may ignore.

The files are regular enough for a small grammar-specific parser — no
Erlang term scanner needed.  Used by tests/test_prop_analysis.py to
cross-validate the DYNAMIC inference (verify/analysis.py) against the
reference's static, hand-checked truth: a golden edge missing from the
inferred relation would make the checker's pruning unsound (VERDICT r3
weak #5)."""

from __future__ import annotations

import dataclasses
import re
from typing import List, Tuple


@dataclasses.dataclass(frozen=True)
class GoldenAnnotation:
    # (recv_type, send_type, count): receiving `count` messages of
    # recv_type enables sending send_type — stored in the RECV->SEND
    # direction to match analysis.infer_causality's map orientation
    edges: Tuple[Tuple[str, str, int], ...]
    spontaneous: Tuple[str, ...]   # sends annotated [true]
    background: Tuple[str, ...]


_ENTRY = re.compile(
    r"\{\{forward_message,\s*'?(\w+)'?\},\s*\[(.*?)\]\}", re.S)
_PRE = re.compile(r"\{\{receive_message,\s*'?(\w+)'?\},\s*(\d+)\}")
_BACKGROUND = re.compile(r"\{background,\s*\[(.*?)\]\}", re.S)


def parse_golden(path: str) -> GoldenAnnotation:
    with open(path) as f:
        text = f.read()
    edges: List[Tuple[str, str, int]] = []
    spontaneous: List[str] = []
    for send_t, pres in _ENTRY.findall(text):
        found = _PRE.findall(pres)
        for recv_t, count in found:
            edges.append((recv_t, send_t, int(count)))
        if not found and "true" in pres:
            spontaneous.append(send_t)
    m = _BACKGROUND.search(text)
    background = tuple(
        t.strip().strip("'") for t in m.group(1).split(",")
        if t.strip()) if m else ()
    return GoldenAnnotation(tuple(edges), tuple(spontaneous), background)
