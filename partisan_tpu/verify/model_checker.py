"""Omission-schedule model checker — the TPU rebuild of the "filibuster"
harness (``test/filibuster_SUITE.erl``): record a golden trace, enumerate
schedules of message omissions over it, deterministically replay each, and
check a protocol invariant (``model_checker_test`` :244, schedule
enumeration + causal pruning :697-930, ``execute_schedule`` :1264).

Determinism makes replay exact (SURVEY §5.2): with fixed seeds the replay's
execution prefix is bit-identical to golden up to the first omission, so a
schedule is an *execution*, not a heuristic.  Pruning mirrors the
reference's: a k-omission schedule is explored only if its last omission
was actually attempted in the (k-1)-omission parent execution — omissions
of messages that are never sent are skipped, not counted
(filibuster's trace-membership pruning).

The reference's CI pins pass/fail counts per workload
(lampson_2pc "Passed: 7, Failed: 1" etc., Makefile:105-113); counts here
depend on this engine's schedule granularity, so tests pin OUR counts and
assert the known minimal counterexamples are found.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..engine import ProtocolBase, World, init_world, make_step
from . import faults

Key = Tuple[int, int, int, int]  # (round, src, dst, typ)
# a schedule ENTRY is a Key + action: (round, src, dst, typ, action)
# with action 0 = drop (omission) and action k > 0 = delay k rounds
# (the trace orchestrator's ordering control, :160-202,476-560)
Entry = Tuple[int, int, int, int, int]


@dataclasses.dataclass
class Execution:
    world: World
    wire_keys: List[Key]         # every delivered message, in order
    invariant_ok: bool


@dataclasses.dataclass
class CheckResult:
    passed: int
    failed: int
    pruned: int                       # naive combinations never generated
    failures: List[Tuple[Entry, ...]]  # failing schedules (5-tuples)
    golden: Execution
    pruned_independent: int = 0       # extensions skipped by annotations

    @property
    def explored(self) -> int:
        return self.passed + self.failed


class ModelChecker:
    def __init__(self, cfg: Config, proto: ProtocolBase,
                 setup: Callable[[World], World],
                 invariant: Callable[[World], bool],
                 n_rounds: int,
                 sched_cap: int = 4,
                 randomize_delivery: bool = True):
        self.cfg, self.proto = cfg, proto
        self.setup, self.invariant = setup, invariant
        self.n_rounds = n_rounds
        self.sched_cap = sched_cap
        # NOTE the drop hook sits on the RECV side: trace keys carry the
        # DELIVERY round (capture_wire records the routed buffer), and only
        # the recv hook sees messages at that same round — a send-side hook
        # would be one round early and never match.
        self.step = make_step(
            cfg, proto, donate=False, capture_wire=True,
            randomize_delivery=randomize_delivery,
            interpose_recv=faults.fault_schedule_dynamic())

    def _pad(self, schedule: Sequence[Entry]) -> jax.Array:
        # 4-tuple rows (legacy omission keys) normalize to action = 0
        rows = [tuple(r) + (0,) if len(r) == 4 else tuple(r)
                for r in list(schedule)[: self.sched_cap]]
        rows += [(-1, -1, -1, -1, 0)] * (self.sched_cap - len(rows))
        return jnp.asarray(rows, jnp.int32)

    def execute(self, schedule: Sequence[Entry] = ()) -> Execution:
        """execute_schedule (:1264): one deterministic replay."""
        world = self.setup(init_world(self.cfg, self.proto))
        world = world.replace(aux={"sched": self._pad(schedule)})
        keys: List[Key] = []
        for _ in range(self.n_rounds):
            world, met = self.step(world)
            valid = np.asarray(met["wire_valid"])
            if valid.any():
                rnd = int(met["round"])
                src = np.asarray(met["wire_src"])
                dst = np.asarray(met["wire_dst"])
                typ = np.asarray(met["wire_typ"])
                for i in np.flatnonzero(valid):
                    keys.append((rnd, int(src[i]), int(dst[i]), int(typ[i])))
        return Execution(world, keys, bool(self.invariant(world)))

    def check(self, candidate_typs: Optional[Iterable[int]] = None,
              max_drops: int = 1,
              max_schedules: int = 1000,
              annotations: Optional[Dict[str, list]] = None,
              candidate_filter: Optional[Callable[[Key], bool]] = None,
              delays: Sequence[int] = (),
              ) -> CheckResult:
        """Enumerate and replay omission schedules up to ``max_drops``
        simultaneous omissions (the powerset walk of :697-930, breadth
        first, causally pruned).

        ``candidate_filter`` restricts the omission candidates by full
        key (round, src, dst, typ) — e.g. targeting one destination to
        explore deep blocking classes without the full combinatorial
        frontier (the reference narrows candidates the same way, by
        tracing only the protocol under test).

        ``annotations`` (a causality map from verify/analysis.py) enables
        the reference's independence pruning (:697-930 prune via the
        annotation files): a schedule extension whose type is causally
        UNRELATED to every already-scheduled omission explores a redundant
        combination — the faults compose independently, so the pair's
        outcome is implied by the singletons — and is skipped (counted in
        ``pruned_independent``).  Types the annotations mark as
        state-gated timer emissions (in ``__tick__`` but not
        ``__background__``) are conservatively related to EVERYTHING: a
        tick handler's emission predicate reads state that arbitrary
        deliveries mutate, so no delivery type can be proven independent
        of it (the soundness hole VERDICT r3 weak #5 named; unconditional
        periodic sends — ``__background__`` — still prune).

        ``delays`` adds delivery-ORDER exploration: for every omission
        candidate the enumeration also tries delaying it by each d ∈
        delays rounds (the trace orchestrator's reordering machinery,
        :160-202,476-560) — anomalies that need a LATE message rather
        than a lost one are invisible to an omission-only sweep."""
        golden = self.execute(())
        if not golden.invariant_ok:
            return CheckResult(0, 1, 0, [()], golden)

        def cands(keys: List[Key]) -> List[Key]:
            seen, out = set(), []
            for k in keys:
                if candidate_typs is not None and k[3] not in candidate_typs:
                    continue
                if candidate_filter is not None and not candidate_filter(k):
                    continue
                if k not in seen:
                    seen.add(k)
                    out.append(k)
            return out

        # independence pruning setup: map typ index <-> name, precompute
        # per-type causal neighborhoods (related = one can reach the other)
        related = None
        relate_all: set = set()
        if annotations is not None:
            # shared with the fault-space explorer's frontier pruning
            # (verify/explorer.py) — one construction, one semantics
            from .analysis import independence_relation
            related, relate_all = independence_relation(
                annotations, self.proto)

        actions = (0,) + tuple(int(d) for d in delays)
        passed = failed = 0
        pruned_indep = 0
        failures: List[Tuple[Entry, ...]] = []
        # frontier: schedule -> execution whose wire feeds its children
        frontier: List[Tuple[Tuple[Entry, ...], Execution]] = [((), golden)]
        budget = max_schedules

        for depth in range(1, max_drops + 1):
            nxt: List[Tuple[Tuple[Entry, ...], Execution]] = []
            for sched, parent in frontier:
                base_cands = cands(parent.wire_keys)
                for k in base_cands:
                    if any(e[:4] == k for e in sched):
                        continue
                    # only extend forward in time to avoid permuted dupes
                    if sched and k <= max(e[:4] for e in sched):
                        continue
                    if (related is not None and sched
                            and k[3] not in relate_all
                            and not any(s[3] in relate_all for s in sched)
                            and not any(
                                (k[3], s[3]) in related for s in sched)):
                        pruned_indep += 1
                        continue
                    for act in actions:
                        if budget <= 0:
                            break
                        budget -= 1
                        child_sched = sched + (k + (act,),)
                        ex = self.execute(child_sched)
                        if ex.invariant_ok:
                            passed += 1
                        else:
                            failed += 1
                            failures.append(child_sched)
                        nxt.append((child_sched, ex))
            frontier = nxt

        # pruning accounting: schedules whose extension key never occurred
        # in the parent are simply not generated; report how many
        # generatable combinations were skipped.  The universe is
        # C(keys, d) * actions^d — distinct keys (the enumerator never
        # schedules one key twice), each independently dropped or
        # delayed.
        naive = 0
        all_keys = cands(golden.wire_keys)
        for d in range(1, max_drops + 1):
            naive += math.comb(len(all_keys), d) * len(actions) ** d
        # `pruned` counts golden-trace combinations never generated;
        # `pruned_indep` counts skipped extensions drawn from (possibly
        # divergent) CHILD traces — different universes, reported apart
        pruned = max(naive - (passed + failed), 0)
        return CheckResult(passed, failed, pruned, failures, golden,
                           pruned_independent=pruned_indep)
