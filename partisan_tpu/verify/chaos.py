"""The compiled chaos plane (ISSUE 4 tentpole) — fault SCHEDULES as data,
applied by in-scan arithmetic on BOTH execution paths.

``verify/faults.py`` rebuilt the reference's fault machinery
(test/prop_partisan_crash_fault_model.erl crash/omission interposition,
the hyparview partition flood :1731-1797) as host-driven mutations: the
harness stops the scan, edits ``world.alive``/``world.partition`` or
installs an interposition fun, and resumes.  That shape cannot run at
scan speed, and the sharded dataplane (parallel/dataplane.py) cannot
host per-round Python at all.  This module compiles the whole campaign
instead:

  * :class:`ChaosSchedule` — a STATIC ``[n_events, 5]`` int32 table of
    ``(round, kind, a, b, c)`` events, baked into the jitted step as a
    compile-time constant (the registry enable-mask pattern: swapping
    schedules recompiles, running one costs fused elementwise masks).
  * :func:`apply_chaos_nodes` — the node plane: crash / recover /
    partition / heal events rewrite the ``alive``/``partition`` vectors
    at the top of the round.  Events apply in table order (later rows
    win ties), so a schedule is replayable and order-unambiguous.
  * :func:`apply_chaos_msgs` — the message plane: drop-matching /
    delay-matching / duplicate events edit the ready buffer right after
    the held split — BEFORE the alive/partition masks, which is the one
    point both execution paths see the message on its src's shard (the
    dataplane residency invariant).  Delayed messages re-hold exactly
    like the engine's '$delay' recv split; duplicates append a copy to
    the held buffer with their own delivery delay.  Every edit is
    counted (``chaos_dropped`` / ``chaos_delayed`` /
    ``chaos_duplicated`` step metrics), never silent (SURVEY §7.3).
  * Byzantine kinds (ISSUE 19) on the same capture point: equivocate
    (conflicting payload variants to disjoint receiver halves), forge
    (a message its claimed src never sent), replay (record-and-replay
    of delivered traffic c rounds later) and corrupt (in-flight payload
    mutation) — commission faults as table rows, counted in the
    :data:`BYZ_COUNTER_KEYS` step metrics, batchable by the explorer
    like any omission (SURVEY §2.9: the reference's hbbft worker exists
    to survive exactly these).

Both ``engine.make_step(chaos=)`` and
``parallel/dataplane.make_sharded_step(chaos=)`` consume the same
schedule: the planes are pure row/slot-local arithmetic (the node plane
reads only this shard's rows via their GLOBAL ids; the message plane
reads only message fields), so the sharded round adds ZERO collectives
— the asserted 2-collective budget holds chaos-on — and the two paths
stay bit-identical in states and metrics (tests/test_dataplane.py
TestChaosFaultParity).

This is the reference's fault-injection surface
(``partisan_trace_orchestrator.erl`` held-sender schedules, the
filibuster omission schedules, crash_fault_model interposition) with
the orchestrator compiled away: a campaign is rows in a table, and
``scripts/chaos_soak.py`` sweeps seed x fault-mix matrices of them.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.msg import Msgs
from ..ops import msg as msgops
from ..ops.bitset import mix32 as _mix32

# event kinds, column 1 of the table
KIND_CRASH = 0      # nodes [a, b] crash-stop                   (c unused)
KIND_RECOVER = 1    # nodes [a, b] come back                    (c unused)
KIND_PARTITION = 2  # nodes [a, b] take partition id c (>= 1)
KIND_HEAL = 3       # nodes [a, b] back to partition 0; a < 0 = everyone
KIND_DROP = 4       # msgs src=a dst=b (-1 wildcard) dropped for c rounds
KIND_DELAY = 5      # msgs src=a dst=b delayed +c rounds (this round only)
KIND_DUP = 6        # msgs src=a dst=b duplicated, copy lands +c rounds
KIND_DROP_TYP = 7   # msgs typ=a dst=b (-1 wildcard) dropped for c rounds
                    # — the channel-targeted omission the fault-space
                    # explorer perturbs (ISSUE 7): "drop the recovery
                    # channel" is a typ, not a (src, dst) pair

# Byzantine kinds (ISSUE 19): commission faults on the same ready-buffer
# capture point.  The reference wraps an hbbft worker whose whole point
# is surviving these (partisan_hbbft_worker.erl, SURVEY §2.9); here they
# are table rows the explorer can enumerate like any omission.
KIND_EQUIVOCATE = 8  # src=a (-1 any) sends conflicting variants of its
                     # typ=b messages: receivers with odd dst get the
                     # payload XOR-salted by c, even-dst receivers the
                     # original — one logical send, two disjoint stories
KIND_FORGE = 9       # inject a message claiming src=a (never sent by a)
                     # to dst=b with wire type c, payload zeroed — the
                     # view-poisoning attack
KIND_REPLAY = 10     # re-deliver this round's already-delivered typ=a
                     # messages (to dst=b, -1 any) again c rounds later
KIND_CORRUPT = 11    # msgs src=a dst=b (-1 wildcard): every integer
                     # payload field XOR-mutated by salt c in flight

KIND_NAMES = ("crash", "recover", "partition", "heal", "drop", "delay",
              "duplicate", "drop_typ", "equivocate", "forge", "replay",
              "corrupt")
_NODE_KINDS = (KIND_CRASH, KIND_RECOVER, KIND_PARTITION, KIND_HEAL)
_MSG_KINDS = (KIND_DROP, KIND_DELAY, KIND_DUP, KIND_DROP_TYP,
              KIND_EQUIVOCATE, KIND_FORGE, KIND_REPLAY, KIND_CORRUPT)
_BYZ_KINDS = (KIND_EQUIVOCATE, KIND_FORGE, KIND_REPLAY, KIND_CORRUPT)
N_COLS = 5

# step-metric keys of the Byzantine planes, in kind order; emitted by the
# message plane whenever the schedule carries any Byzantine event (the
# dynamic table twin always emits them — its program must cover the whole
# alphabet).  Ride the sharded dataplane's ONE stacked psum as extra rows.
BYZ_COUNTER_KEYS = ("chaos_equivocated", "chaos_forged", "chaos_replayed",
                    "chaos_corrupted")


def counter_keys(sched) -> Tuple[str, ...]:
    """The chaos-counter metric keys a step compiled against ``sched``
    emits: the base omission triple always, plus :data:`BYZ_COUNTER_KEYS`
    when the schedule carries Byzantine events (or is a
    :class:`DynamicSchedule`, whose one program covers the whole
    alphabet).  Schedules without Byzantine rows keep the exact
    pre-ISSUE-19 key set, so their compiled programs stay byte-stable."""
    base = ("chaos_dropped", "chaos_delayed", "chaos_duplicated")
    if isinstance(sched, DynamicSchedule) or sched.has_byzantine:
        return base + BYZ_COUNTER_KEYS
    return base

# the padding row of a dynamic table: kind -1 matches no plane, round -1
# never fires — a guaranteed no-op on both the node and message planes
SENTINEL = (-1, -1, -1, -1, 0)


def _rng(nodes) -> Tuple[int, int]:
    """Normalize a node spec: int -> (n, n), (lo, hi) -> inclusive range."""
    if isinstance(nodes, (tuple, list)):
        lo, hi = int(nodes[0]), int(nodes[1])
    else:
        lo = hi = int(nodes)
    if 0 <= lo <= hi:
        return lo, hi
    raise ValueError(f"bad node range {nodes!r}: need 0 <= lo <= hi")


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """An immutable, hashable event table.  Build fluently::

        sched = (ChaosSchedule()
                 .crash(10, (3, 6))          # nodes 3..6 die at round 10
                 .partition(15, (0, 31), 1)  # two halves at round 15
                 .partition(15, (32, 63), 2)
                 .drop(18, src=-1, dst=7, rounds=4)
                 .delay(20, src=3, extra=2)
                 .duplicate(22, copy_delay=1)
                 .heal(30)                   # partitions resolve
                 .recover(32, (3, 6)))       # crashed nodes return

    Each builder returns a NEW schedule (frozen dataclass over a tuple),
    so a schedule is a valid jit closure constant and dict key.
    """

    events: Tuple[Tuple[int, int, int, int, int], ...] = ()

    # ------------------------------------------------------------ builders

    def _add(self, rnd: int, kind: int, a: int, b: int,
             c: int) -> "ChaosSchedule":
        if rnd < 0:
            raise ValueError(f"event round must be >= 0, got {rnd}")
        return ChaosSchedule(self.events
                             + ((int(rnd), int(kind), int(a), int(b),
                                 int(c)),))

    def crash(self, rnd: int, nodes) -> "ChaosSchedule":
        lo, hi = _rng(nodes)
        return self._add(rnd, KIND_CRASH, lo, hi, 0)

    def recover(self, rnd: int, nodes) -> "ChaosSchedule":
        lo, hi = _rng(nodes)
        return self._add(rnd, KIND_RECOVER, lo, hi, 0)

    def partition(self, rnd: int, nodes, gid: int) -> "ChaosSchedule":
        if gid < 1:
            raise ValueError(f"partition id must be >= 1, got {gid}")
        lo, hi = _rng(nodes)
        return self._add(rnd, KIND_PARTITION, lo, hi, gid)

    def heal(self, rnd: int, nodes=None) -> "ChaosSchedule":
        if nodes is None:
            return self._add(rnd, KIND_HEAL, -1, -1, 0)
        lo, hi = _rng(nodes)
        return self._add(rnd, KIND_HEAL, lo, hi, 0)

    def drop(self, rnd: int, src: int = -1, dst: int = -1,
             rounds: int = 1) -> "ChaosSchedule":
        if rounds < 1:
            raise ValueError(f"drop window must be >= 1 rounds, got {rounds}")
        return self._add(rnd, KIND_DROP, src, dst, rounds)

    def delay(self, rnd: int, src: int = -1, dst: int = -1,
              extra: int = 1) -> "ChaosSchedule":
        if extra < 1:
            raise ValueError(f"delay must be >= 1 rounds, got {extra}")
        return self._add(rnd, KIND_DELAY, src, dst, extra)

    def duplicate(self, rnd: int, src: int = -1, dst: int = -1,
                  copy_delay: int = 1) -> "ChaosSchedule":
        if copy_delay < 1:
            raise ValueError(
                f"duplicate copy_delay must be >= 1, got {copy_delay}")
        return self._add(rnd, KIND_DUP, src, dst, copy_delay)

    def drop_typ(self, rnd: int, typ: int, dst: int = -1,
                 rounds: int = 1) -> "ChaosSchedule":
        """Drop messages of wire type ``typ`` (to ``dst``, -1 = any) for
        ``rounds`` rounds — the channel-targeted omission (e.g. "drop
        every recovery-channel message cluster-wide")."""
        if typ < 0:
            raise ValueError(f"drop_typ type must be >= 0, got {typ}")
        if rounds < 1:
            raise ValueError(f"drop window must be >= 1 rounds, got {rounds}")
        return self._add(rnd, KIND_DROP_TYP, typ, dst, rounds)

    def equivocate(self, rnd: int, src: int = -1, typ: int = 0,
                   salt: int = 1) -> "ChaosSchedule":
        """Node ``src`` (-1 = every sender) equivocates on its wire-type
        ``typ`` messages this round: odd-numbered receivers get the
        payload's non-scalar fields XOR-mutated by ``salt``, even ones
        the original — one logical broadcast telling two disjoint
        receiver halves two different stories.  Scalar control headers
        (epoch counters, digests the receiver recomputes anyway) stay
        intact so the variant is still a well-formed protocol message."""
        if typ < 0:
            raise ValueError(
                f"equivocate type must be >= 0, got {typ} — equivocation "
                f"needs a concrete wire type to tell two stories about")
        if salt < 1:
            raise ValueError(f"equivocate salt must be >= 1, got {salt}")
        return self._add(rnd, KIND_EQUIVOCATE, src, typ, salt)

    def forge(self, rnd: int, src: int, dst: int,
              typ: int) -> "ChaosSchedule":
        """Inject a message claiming ``src`` that ``src`` never sent, to
        ``dst`` with wire type ``typ`` and an all-zero payload — the
        view-poisoning attack (a forged join/membership claim).  No
        wildcards: a forgery is a concrete lie about a concrete id."""
        if src < 0 or dst < 0:
            raise ValueError(
                f"forge of an out-of-range id: src/dst ({src}, {dst}) "
                f"must both be concrete node ids >= 0")
        if typ < 0:
            raise ValueError(f"forge type must be >= 0, got {typ}")
        return self._add(rnd, KIND_FORGE, src, dst, typ)

    def replay(self, rnd: int, typ: int, dst: int = -1,
               after: int = 1) -> "ChaosSchedule":
        """Record this round's delivered wire-type ``typ`` messages (to
        ``dst``, -1 = any) and re-deliver the copies ``after`` rounds
        later — the adversarial record-and-replay (a stale ack or vote
        presented again after the protocol moved on)."""
        if typ < 0:
            raise ValueError(f"replay type must be >= 0, got {typ}")
        if after < 1:
            raise ValueError(
                f"replay horizon must be >= 1 rounds, got {after}")
        return self._add(rnd, KIND_REPLAY, typ, dst, after)

    def corrupt(self, rnd: int, src: int = -1, dst: int = -1,
                salt: int = 1) -> "ChaosSchedule":
        """Deterministically mutate matching messages in flight this
        round: every integer payload field is XORed with a hash of
        ``salt`` — the bit-flipping relay (distinct from equivocate:
        EVERY matching receiver sees the same corrupted payload)."""
        if salt < 1:
            raise ValueError(f"corrupt salt must be >= 1, got {salt}")
        return self._add(rnd, KIND_CORRUPT, src, dst, salt)

    # ------------------------------------------------------------- queries

    @property
    def n_events(self) -> int:
        return len(self.events)

    def table(self) -> np.ndarray:
        """The [n_events, 5] int32 host table (empty -> [0, 5])."""
        if not self.events:
            return np.zeros((0, N_COLS), np.int32)
        return np.asarray(self.events, np.int32)

    def _kinds(self, kinds) -> Tuple[Tuple[int, ...], ...]:
        return tuple(e for e in self.events if e[1] in kinds)

    @property
    def has_node_events(self) -> bool:
        return bool(self._kinds(_NODE_KINDS))

    @property
    def has_drop(self) -> bool:
        return bool(self._kinds((KIND_DROP, KIND_DROP_TYP)))

    @property
    def has_delay(self) -> bool:
        return bool(self._kinds((KIND_DELAY,)))

    @property
    def has_dup(self) -> bool:
        return bool(self._kinds((KIND_DUP,)))

    @property
    def has_equivocate(self) -> bool:
        return bool(self._kinds((KIND_EQUIVOCATE,)))

    @property
    def has_forge(self) -> bool:
        return bool(self._kinds((KIND_FORGE,)))

    @property
    def has_replay(self) -> bool:
        return bool(self._kinds((KIND_REPLAY,)))

    @property
    def has_corrupt(self) -> bool:
        return bool(self._kinds((KIND_CORRUPT,)))

    @property
    def has_byzantine(self) -> bool:
        return bool(self._kinds(_BYZ_KINDS))

    @property
    def has_msg_events(self) -> bool:
        return (self.has_drop or self.has_delay or self.has_dup
                or self.has_byzantine)

    def last_heal_round(self) -> int:
        """The round after which no injected disruption remains standing:
        the max over heal/recover event rounds and drop-window ends (the
        soak's convergence-after-heal anchor).  -1 when the schedule
        never disrupts (or never heals what it broke — a schedule that
        crashes without recovering reports the crash round so the soak
        measures from the last state change)."""
        ends = [-1]
        for rnd, kind, _a, _b, c in self.events:
            if kind in (KIND_HEAL, KIND_RECOVER, KIND_CRASH,
                        KIND_PARTITION):
                ends.append(rnd)
            elif kind in (KIND_DROP, KIND_DROP_TYP):
                ends.append(rnd + max(c, 1) - 1)
            elif kind == KIND_REPLAY:
                # the replayed copies only land c rounds after the event
                ends.append(rnd + max(c, 1))
            else:
                ends.append(rnd)
        return max(ends)

    def disruptive_rounds(self) -> np.ndarray:
        """Rounds at which a crash or partition event fires — the
        quiesce window anchors of :func:`quiesce_resub`."""
        rr = [e[0] for e in self.events
              if e[1] in (KIND_CRASH, KIND_PARTITION)]
        return np.asarray(sorted(set(rr)), np.int32)

    def padded_table(self, n_events: int) -> np.ndarray:
        """The [n_events, 5] int32 table padded with :data:`SENTINEL`
        no-op rows — the fixed-shape row a :class:`DynamicSchedule` step
        consumes and the fault-space explorer stacks along its batch
        axis.  Raises if the schedule has more events than ``n_events``
        (a silent truncation would un-inject faults)."""
        if self.n_events > n_events:
            raise ValueError(
                f"schedule has {self.n_events} events, table capacity "
                f"is {n_events}")
        rows = list(self.events) + [SENTINEL] * (n_events - self.n_events)
        return np.asarray(rows, np.int32).reshape(n_events, N_COLS)

    # ---------------------------------------------------------- validation

    def validate(self, n_nodes: Optional[int] = None,
                 n_rounds: Optional[int] = None,
                 n_types: Optional[int] = None) -> "ChaosSchedule":
        """Compile-time schedule validation (ISSUE 7 satellite): events
        that previously folded into silent no-ops now raise named
        ``ValueError``s.  Checks, each gated on the caller knowing the
        bound:

          * ``n_rounds`` — an event at ``round >= n_rounds`` never fires
            (builders already reject ``round < 0``);
          * ``n_nodes`` — node-range or src/dst ids outside ``[0, n)``
            never match a node or message (``-1`` wildcards stay legal);
          * ``n_types`` — a ``drop_typ`` type outside ``[0, n_types)``
            matches no wire type;
          * same-round partition events whose SAME gid covers every node
            (requires ``n_nodes``) — "two halves, one gid" puts the
            whole cluster in one group, i.e. no partition at all.

        Returns ``self`` so call sites can validate inline."""
        n = n_nodes
        # (round, gid) -> node-count covered, for the collision check
        cover: dict = {}
        for i, (rnd, kind, a, b, c) in enumerate(self.events):
            name = KIND_NAMES[kind] if 0 <= kind < len(KIND_NAMES) else kind
            where = f"chaos event #{i} ({name} @ round {rnd})"
            if n_rounds is not None and rnd >= n_rounds:
                raise ValueError(
                    f"{where}: fires at round {rnd} but the run is only "
                    f"{n_rounds} rounds — the event would never apply")
            if kind in _NODE_KINDS:
                if n is not None and a >= 0 and (a >= n or b >= n):
                    raise ValueError(
                        f"{where}: node range ({a}, {b}) out of "
                        f"[0, {n}) — the event would never match a node")
                if kind == KIND_PARTITION and n is not None:
                    lo, hi = max(a, 0), min(b, n - 1)
                    cover[(rnd, c)] = (cover.get((rnd, c), 0)
                                       + max(hi - lo + 1, 0))
            elif kind == KIND_DROP_TYP:
                if n_types is not None and a >= n_types:
                    raise ValueError(
                        f"{where}: wire type {a} out of [0, {n_types}) "
                        f"— the event would never match a message")
                if n is not None and b >= n:
                    raise ValueError(
                        f"{where}: dst {b} out of [0, {n}) — the event "
                        f"would never match a message")
            elif kind == KIND_EQUIVOCATE:
                if n is not None and a >= n:
                    raise ValueError(
                        f"{where}: src {a} out of [0, {n}) — the event "
                        f"would never match a message")
                if n_types is not None and b >= n_types:
                    raise ValueError(
                        f"{where}: equivocation on a typ outside the "
                        f"protocol's wire space — type {b} out of "
                        f"[0, {n_types})")
            elif kind == KIND_FORGE:
                if n is not None and (a >= n or b >= n):
                    raise ValueError(
                        f"{where}: forge of an out-of-range id — "
                        f"src/dst ({a}, {b}) out of [0, {n})")
                if n_types is not None and c >= n_types:
                    raise ValueError(
                        f"{where}: wire type {c} out of [0, {n_types}) "
                        f"— the forged message would hit no handler")
            elif kind == KIND_REPLAY:
                if n_types is not None and a >= n_types:
                    raise ValueError(
                        f"{where}: wire type {a} out of [0, {n_types}) "
                        f"— the event would never match a message")
                if n is not None and b >= n:
                    raise ValueError(
                        f"{where}: dst {b} out of [0, {n}) — the event "
                        f"would never match a message")
                if n_rounds is not None and rnd + c >= n_rounds:
                    raise ValueError(
                        f"{where}: replay horizon past rounds — the "
                        f"copies land at round {rnd + c} but the run is "
                        f"only {n_rounds} rounds")
            else:  # src/dst message kinds (drop / delay / dup / corrupt)
                if n is not None and (a >= n or b >= n):
                    raise ValueError(
                        f"{where}: src/dst ({a}, {b}) out of [0, {n}) "
                        f"— the event would never match a message")
        for (rnd, gid), covered in cover.items():
            if n is not None and covered >= n:
                raise ValueError(
                    f"partition gid collision at round {rnd}: gid {gid} "
                    f"covers all {n} nodes — every node lands in one "
                    f"group, which is no partition at all (use distinct "
                    f"gids per side)")
        return self


# --------------------------------------------------------------- node plane

def apply_chaos_nodes(sched: ChaosSchedule, rnd: jax.Array,
                      alive: jax.Array, partition: jax.Array,
                      node_ids: jax.Array
                      ) -> Tuple[jax.Array, jax.Array]:
    """Fold this round's crash/recover/partition/heal events into the
    fault-plane vectors.  ``node_ids`` carries GLOBAL ids, so under the
    sharded dataplane each shard folds the same table over its own row
    slice — pure local arithmetic, zero collectives, bit-identical to
    the global fold restricted to those rows.

    The event loop unrolls over the static table (schedules are small);
    events apply in table order, so a later row overrides an earlier one
    in the same round (e.g. partition-then-heal is a no-op round).
    """
    for ev_rnd, kind, a, b, c in sched._kinds(_NODE_KINDS):
        fire = rnd == ev_rnd
        if a < 0:
            in_rng = jnp.ones_like(node_ids, dtype=bool)
        else:
            in_rng = (node_ids >= a) & (node_ids <= b)
        hit = fire & in_rng
        if kind == KIND_CRASH:
            alive = alive & ~hit
        elif kind == KIND_RECOVER:
            alive = alive | hit
        elif kind == KIND_PARTITION:
            partition = jnp.where(hit, jnp.int32(c), partition)
        else:  # KIND_HEAL
            partition = jnp.where(hit, jnp.int32(0), partition)
    return alive, partition


# ------------------------------------------------------------ message plane

def _match(m: Msgs, src: int, dst: int) -> jax.Array:
    hit = m.valid
    if src >= 0:
        hit = hit & (m.src == src)
    if dst >= 0:
        hit = hit & (m.dst == dst)
    return hit


def _salt32(c) -> jax.Array:
    """Hash an event salt into a nonzero uint32 XOR pattern (the |1 keeps
    at least one bit set, so a salted payload always differs)."""
    return _mix32(jnp.asarray(c, jnp.uint32)) | jnp.uint32(1)


def _xor_data(m: Msgs, xmask: jax.Array, vectors_only: bool) -> Msgs:
    """XOR every integer payload field with the per-slot uint32 pattern
    ``xmask`` ([cap], 0 = untouched).  ``vectors_only`` skips scalar
    (per-slot ()-shaped) fields — equivocation mutates only the DATA a
    message carries (batch contents, view samples), keeping scalar
    control headers (epoch counters, recomputed digests) intact so the
    variant still parses as a well-formed message of its type."""
    data = dict(m.data)
    for name, arr in data.items():
        if not jnp.issubdtype(arr.dtype, jnp.integer):
            continue
        if vectors_only and arr.ndim == 1:
            continue
        x = xmask.reshape((xmask.shape[0],) + (1,) * (arr.ndim - 1))
        data[name] = (arr.astype(jnp.uint32) ^ x).astype(arr.dtype)
    return m.replace(data=data)


def _forge_one(now: Msgs, do: jax.Array, src, dst, typ,
               rnd: jax.Array) -> Tuple[Msgs, jax.Array]:
    """Write one forged message into the first free slot of ``now`` when
    ``do`` (scalar bool) holds and a free slot exists.  Returns the
    edited buffer and the 0/1 fired count.  The forged slot rides a
    connection of its own ((src, dst, channel 0, lane 0) that the honest
    src never uses this round), so the router's per-connection order
    hash — not buffer position — decides its inbox slot: bit-identical
    between the sharded and unsharded paths."""
    free = ~now.valid
    do = do & jnp.any(free)
    idx = jnp.argmax(free)
    fired = do.astype(jnp.int32)

    def wr(arr, val):
        return arr.at[idx].set(jnp.where(do, jnp.asarray(val, arr.dtype),
                                         arr[idx]))

    zero = jnp.int32(0)
    data = {name: arr.at[idx].set(
                jnp.where(do, jnp.zeros_like(arr[idx]), arr[idx]))
            for name, arr in now.data.items()}
    now = now.replace(
        valid=now.valid.at[idx].set(now.valid[idx] | do),
        src=wr(now.src, src), dst=wr(now.dst, dst), typ=wr(now.typ, typ),
        channel=wr(now.channel, zero), lane=wr(now.lane, zero),
        delay=wr(now.delay, zero), born=wr(now.born, rnd),
        data=data)
    return now, fired


def apply_chaos_msgs(sched: ChaosSchedule, rnd: jax.Array, now: Msgs,
                     want_masks: bool = False, *, node_lo=None,
                     node_hi=None):
    """Apply drop / delay / duplicate / Byzantine events to the READY
    buffer (post held-split, pre fault-plane — the point where both
    execution paths still hold every message on its src's shard).
    Returns ``(now, extra_held, counts)``:

      * ``now`` with dropped and re-held slots invalidated, corrupted /
        equivocated payloads mutated in place, and forged slots written
        into free capacity;
      * ``extra_held`` — a flat buffer of chaos-delayed re-holds,
        duplicate copies and replay copies for the caller to concat into
        its held traffic (``None`` when the schedule has no
        delay/dup/replay events, so the carry shape is unchanged —
        program shape depends only on the static schedule);
      * ``counts`` — ``{"chaos_dropped", "chaos_delayed",
        "chaos_duplicated"}`` int32 scalars over THIS buffer, plus the
        four :data:`BYZ_COUNTER_KEYS` when the schedule carries
        Byzantine events (the sharded step psums them; the totals match
        the unsharded run).  Schedules without Byzantine rows emit the
        exact pre-existing key set and program.

    ``node_lo``/``node_hi`` are the sharded caller's GLOBAL node-id
    bounds for this shard: a forged message materializes only on the
    shard that owns its claimed src (``node_lo <= src < node_hi``), the
    same src-residency invariant every real message obeys.  ``None``
    (the unsharded engine) means every id is local.

    ``want_masks=True`` (the lifecycle tracer's tap, ISSUE 16) appends a
    fourth element: ``{"dropped", "delayed"}`` — [cap] bool masks
    positionally ALIGNED to the INPUT buffer (every plane here edits
    ``valid`` in place, never moves slots), where ``delayed`` covers
    re-holds, duplicate copies and replay copies.  Forged slots are NOT
    in the masks (they have no input-aligned position); the engine
    rehashes the buffer after this plane when forgery is on.
    Python-level gating: the default call builds the exact pre-existing
    program.

    Order inside the plane: drops first, then corruption and
    equivocation of the survivors' payloads, then delays, duplication,
    replay of the remaining ready slots, and forged injections last —
    one deterministic pipeline, identical on both paths and in the
    traced-table twin.
    """
    zero = jnp.int32(0)
    counts = {"chaos_dropped": zero, "chaos_delayed": zero,
              "chaos_duplicated": zero}
    if sched.has_byzantine:
        counts.update({k: zero for k in BYZ_COUNTER_KEYS})
    if not sched.has_msg_events:
        if want_masks:
            z = jnp.zeros((now.cap,), bool)
            return now, None, counts, {"dropped": z, "delayed": z}
        return now, None, counts

    drop = None
    if sched.has_drop:
        drop = jnp.zeros((now.cap,), bool)
        for ev_rnd, kind, a, b, c in sched._kinds((KIND_DROP,
                                                   KIND_DROP_TYP)):
            active = (rnd >= ev_rnd) & (rnd < ev_rnd + max(c, 1))
            if kind == KIND_DROP_TYP:
                hit = now.valid & (now.typ == a)
                if b >= 0:
                    hit = hit & (now.dst == b)
            else:
                hit = _match(now, a, b)
            drop = drop | (hit & active)
        counts["chaos_dropped"] = jnp.sum(drop).astype(jnp.int32)
        now = now.replace(valid=now.valid & ~drop)

    if sched.has_corrupt:
        xmask = jnp.zeros((now.cap,), jnp.uint32)
        for ev_rnd, _k, a, b, c in sched._kinds((KIND_CORRUPT,)):
            hit = _match(now, a, b) & (rnd == ev_rnd)
            xmask = xmask ^ jnp.where(hit, _salt32(c), jnp.uint32(0))
        counts["chaos_corrupted"] = jnp.sum(xmask != 0).astype(jnp.int32)
        now = _xor_data(now, xmask, vectors_only=False)

    if sched.has_equivocate:
        # XOR-fold over events (order-independent, like drop's OR): odd
        # receivers see the salted variant, even ones the original
        emask = jnp.zeros((now.cap,), jnp.uint32)
        for ev_rnd, _k, a, b, c in sched._kinds((KIND_EQUIVOCATE,)):
            hit = (_match(now, a, -1) & (now.typ == b) & (rnd == ev_rnd)
                   & (now.dst % 2 == 1))
            emask = emask ^ jnp.where(hit, _salt32(c), jnp.uint32(0))
        counts["chaos_equivocated"] = jnp.sum(emask != 0).astype(jnp.int32)
        now = _xor_data(now, emask, vectors_only=True)

    parts = []
    re_held = copy = rcopy = None
    if sched.has_delay:
        bump = jnp.zeros((now.cap,), jnp.int32)
        for ev_rnd, _k, a, b, c in sched._kinds((KIND_DELAY,)):
            hit = _match(now, a, b) & (rnd == ev_rnd)
            bump = jnp.maximum(bump, jnp.where(hit, jnp.int32(c), 0))
        delayed = now.replace(delay=now.delay + bump)
        # the '$delay' re-hold split, exactly the engine's recv-side
        # shape: held copies age one round immediately (the next round's
        # held split would otherwise double-count this round)
        re_held = delayed.replace(
            valid=delayed.valid & (delayed.delay > 0),
            delay=jnp.maximum(delayed.delay - 1, 0))
        counts["chaos_delayed"] = jnp.sum(re_held.valid).astype(jnp.int32)
        now = delayed.replace(valid=delayed.valid & (delayed.delay <= 0))
        parts.append(re_held)

    if sched.has_dup:
        cdel = jnp.full((now.cap,), -1, jnp.int32)
        for ev_rnd, _k, a, b, c in sched._kinds((KIND_DUP,)):
            hit = _match(now, a, b) & (rnd == ev_rnd)
            cdel = jnp.maximum(cdel, jnp.where(hit, jnp.int32(max(c, 1)),
                                               -1))
        copy = now.replace(valid=now.valid & (cdel >= 0),
                           delay=jnp.maximum(cdel - 1, 0))
        counts["chaos_duplicated"] = jnp.sum(copy.valid).astype(jnp.int32)
        parts.append(copy)

    if sched.has_replay:
        # record-and-replay: copies of this round's delivered typ=a
        # traffic land again c rounds later (like dup, but typ-matched —
        # the stale-vote/ack presented after the protocol moved on)
        rdel = jnp.full((now.cap,), -1, jnp.int32)
        for ev_rnd, _k, a, b, c in sched._kinds((KIND_REPLAY,)):
            hit = now.valid & (now.typ == a) & (rnd == ev_rnd)
            if b >= 0:
                hit = hit & (now.dst == b)
            rdel = jnp.maximum(rdel, jnp.where(hit, jnp.int32(max(c, 1)),
                                               -1))
        rcopy = now.replace(valid=now.valid & (rdel >= 0),
                            delay=jnp.maximum(rdel - 1, 0))
        counts["chaos_replayed"] = jnp.sum(rcopy.valid).astype(jnp.int32)
        parts.append(rcopy)

    if sched.has_forge:
        # sequential fold in table order (each forgery takes the next
        # free slot), matching the table twin's fori_loop exactly
        nforged = zero
        for ev_rnd, _k, a, b, c in sched._kinds((KIND_FORGE,)):
            do = rnd == ev_rnd
            if node_lo is not None:
                do = do & (a >= node_lo) & (a < node_hi)
            now, fired = _forge_one(now, do, a, b, c, rnd)
            nforged = nforged + fired
        counts["chaos_forged"] = nforged

    extra_held = None
    if parts:
        extra_held = msgops.concat(*parts) if len(parts) > 1 else parts[0]
    if want_masks:
        z = jnp.zeros((now.cap,), bool)
        delayed = z
        if re_held is not None:
            delayed = delayed | re_held.valid
        if copy is not None:
            delayed = delayed | copy.valid
        if rcopy is not None:
            delayed = delayed | rcopy.valid
        masks = {"dropped": drop if drop is not None else z,
                 "delayed": delayed}
        return now, extra_held, counts, masks
    return now, extra_held, counts


# ------------------------------------------------------ dynamic (traced)
#
# The static plane above bakes the schedule into the compiled step —
# right for a soak running ONE campaign, wrong for a fault-space SEARCH
# where every candidate schedule would recompile the world.  The
# explorer (verify/explorer.py) instead compiles the step ONCE against a
# fixed-shape [n_events, 5] table passed as a TRACED argument, and vmaps
# it over a [B, n_events, 5] stack: hundreds of fault scenarios per
# compiled scan.  The table functions below are the traced twins of
# apply_chaos_nodes / apply_chaos_msgs and are BIT-IDENTICAL to them for
# any schedule the static path accepts:
#
#   * the node plane folds rows sequentially (fori_loop), so table order
#     still wins ties exactly like the static unroll;
#   * the message plane's folds are all order-independent reductions
#     (drop = OR, delay bump = max, dup/replay copy-delay = max,
#     corrupt/equivocate payload salt = XOR) computed over the event
#     axis at once — except forgery, which consumes free slots and so
#     folds sequentially (fori_loop), matching the static unroll's
#     table order;
#   * SENTINEL padding rows (kind -1) match no plane and no kind;
#   * extra_held is ALWAYS materialized ([3 * cap]: delay re-holds then
#     dup copies then replay copies, all-invalid when nothing matched) —
#     msgops.compact is a stable sort on validity, so trailing invalid
#     slots change no downstream valid content, only which garbage sits
#     in dead slots.


@dataclasses.dataclass(frozen=True)
class DynamicSchedule:
    """Marker for ``engine.make_step(chaos=DynamicSchedule(E))``: compile
    the chaos planes against a TRACED ``[n_events, 5]`` table instead of
    a baked-in :class:`ChaosSchedule` — the returned step is then
    ``step(world, chaos_table)`` and one compiled program executes any
    schedule of up to ``n_events`` events (pad with
    :meth:`ChaosSchedule.padded_table`)."""

    n_events: int

    def __post_init__(self):
        if self.n_events < 1:
            raise ValueError(
                f"DynamicSchedule needs n_events >= 1, got {self.n_events}")


def apply_chaos_nodes_table(table: jax.Array, rnd: jax.Array,
                            alive: jax.Array, partition: jax.Array,
                            node_ids: jax.Array
                            ) -> Tuple[jax.Array, jax.Array]:
    """Traced-table twin of :func:`apply_chaos_nodes`: sequential fold
    over the event rows (later rows win ties, exactly the static
    unroll's semantics), each row a fused select on its kind."""

    def body(i, carry):
        alive, part = carry
        ev_rnd, kind, a, b, c = (table[i, 0], table[i, 1], table[i, 2],
                                 table[i, 3], table[i, 4])
        fire = rnd == ev_rnd
        in_rng = jnp.where(a < 0, jnp.ones_like(node_ids, dtype=bool),
                           (node_ids >= a) & (node_ids <= b))
        hit = fire & in_rng
        alive = jnp.where(kind == KIND_CRASH, alive & ~hit, alive)
        alive = jnp.where(kind == KIND_RECOVER, alive | hit, alive)
        part = jnp.where((kind == KIND_PARTITION) & hit, c, part)
        part = jnp.where((kind == KIND_HEAL) & hit, jnp.int32(0), part)
        return alive, part

    return jax.lax.fori_loop(0, table.shape[0], body, (alive, partition))


def apply_chaos_msgs_table(table: jax.Array, rnd: jax.Array, now: Msgs):
    """Traced-table twin of :func:`apply_chaos_msgs`.  Same pipeline
    (drops, then corrupt/equivocate payload salts, then delays,
    duplication, replay and forged injections), but each stage reduces
    over the whole event axis at once — legal because the static folds
    are order-independent (OR / max / XOR) — except forgery, whose
    free-slot consumption folds sequentially over the rows exactly like
    the static unroll.  ``extra_held`` is always a ``[3 * cap]`` buffer
    (delay re-holds ++ dup copies ++ replay copies), so the program
    shape is schedule-independent.  Emits the full 7-key counter set
    (the one compiled program covers the whole alphabet)."""
    ev_rnd, kind = table[:, 0], table[:, 1]
    a, b, c = table[:, 2], table[:, 3], table[:, 4]

    def pair_match(m: Msgs) -> jax.Array:
        """[E, cap] — src/dst wildcard match per event row."""
        msrc = (a[:, None] < 0) | (m.src[None, :] == a[:, None])
        mdst = (b[:, None] < 0) | (m.dst[None, :] == b[:, None])
        return m.valid[None, :] & msrc & mdst

    def typ_match(m: Msgs) -> jax.Array:
        """[E, cap] — wire-type/dst match per event row (KIND_DROP_TYP)."""
        mtyp = m.typ[None, :] == a[:, None]
        mdst = (b[:, None] < 0) | (m.dst[None, :] == b[:, None])
        return m.valid[None, :] & mtyp & mdst

    def xor_fold(hit: jax.Array, salt: jax.Array) -> jax.Array:
        """[cap] — XOR of the per-event salts over matching rows."""
        contrib = jnp.where(hit, salt[:, None], jnp.uint32(0))
        return jax.lax.reduce(contrib, jnp.uint32(0),
                              jax.lax.bitwise_xor, (0,))

    # -- drops (windowed): OR over events, matching the static fold
    win = jnp.maximum(c, 1)
    drop_active = ((ev_rnd >= 0) & (rnd >= ev_rnd)
                   & (rnd < ev_rnd + win))                       # [E]
    drop_ev = (((kind == KIND_DROP) & drop_active)[:, None] & pair_match(now)
               | ((kind == KIND_DROP_TYP) & drop_active)[:, None]
               & typ_match(now))
    drop = jnp.any(drop_ev, axis=0)
    counts = {"chaos_dropped": jnp.sum(drop).astype(jnp.int32)}
    now = now.replace(valid=now.valid & ~drop)

    salt = _salt32(c)                                            # [E]

    # -- corruption of the survivors: XOR-salt every integer payload
    #    field of matching slots (order-independent XOR fold)
    corr_fire = ((kind == KIND_CORRUPT) & (rnd == ev_rnd))       # [E]
    xmask = xor_fold(corr_fire[:, None] & pair_match(now), salt)
    counts["chaos_corrupted"] = jnp.sum(xmask != 0).astype(jnp.int32)
    now = _xor_data(now, xmask, vectors_only=False)

    # -- equivocation: salt only non-scalar payload fields, only for
    #    odd-numbered receivers (the disjoint half)
    eq_fire = ((kind == KIND_EQUIVOCATE) & (rnd == ev_rnd))      # [E]
    msrc = (a[:, None] < 0) | (now.src[None, :] == a[:, None])
    hit_e = (now.valid[None, :] & eq_fire[:, None] & msrc
             & (now.typ[None, :] == b[:, None])
             & ((now.dst % 2) == 1)[None, :])
    emask = xor_fold(hit_e, salt)
    counts["chaos_equivocated"] = jnp.sum(emask != 0).astype(jnp.int32)
    now = _xor_data(now, emask, vectors_only=True)

    # -- delays on the survivors: max bump over events, then the
    #    '$delay' re-hold split (held copies age one round immediately)
    delay_fire = ((kind == KIND_DELAY) & (rnd == ev_rnd))        # [E]
    hit_d = delay_fire[:, None] & pair_match(now)
    bump = jnp.max(jnp.where(hit_d, c[:, None], 0), axis=0,
                   initial=0).astype(jnp.int32)
    delayed = now.replace(delay=now.delay + bump)
    re_held = delayed.replace(
        valid=delayed.valid & (delayed.delay > 0),
        delay=jnp.maximum(delayed.delay - 1, 0))
    counts["chaos_delayed"] = jnp.sum(re_held.valid).astype(jnp.int32)
    now = delayed.replace(valid=delayed.valid & (delayed.delay <= 0))

    # -- duplication of the remaining ready slots: max copy-delay with a
    #    -1 "no copy" floor, exactly the static fold
    dup_fire = ((kind == KIND_DUP) & (rnd == ev_rnd))            # [E]
    hit_u = dup_fire[:, None] & pair_match(now)
    cdel = jnp.max(jnp.where(hit_u, jnp.maximum(c, 1)[:, None], -1),
                   axis=0, initial=-1).astype(jnp.int32)
    copy = now.replace(valid=now.valid & (cdel >= 0),
                       delay=jnp.maximum(cdel - 1, 0))
    counts["chaos_duplicated"] = jnp.sum(copy.valid).astype(jnp.int32)

    # -- replay: typ/dst-matched copies landing c rounds later
    rp_fire = ((kind == KIND_REPLAY) & (rnd == ev_rnd))          # [E]
    hit_r = rp_fire[:, None] & typ_match(now)
    rdel = jnp.max(jnp.where(hit_r, jnp.maximum(c, 1)[:, None], -1),
                   axis=0, initial=-1).astype(jnp.int32)
    rcopy = now.replace(valid=now.valid & (rdel >= 0),
                        delay=jnp.maximum(rdel - 1, 0))
    counts["chaos_replayed"] = jnp.sum(rcopy.valid).astype(jnp.int32)

    # -- forged injections: sequential free-slot fold over the rows
    def fbody(i, carry):
        m, nf = carry
        do = (kind[i] == KIND_FORGE) & (rnd == ev_rnd[i])
        m, fired = _forge_one(m, do, a[i], b[i], c[i], rnd)
        return m, nf + fired

    now, nforged = jax.lax.fori_loop(0, table.shape[0], fbody,
                                     (now, jnp.int32(0)))
    counts["chaos_forged"] = nforged

    return now, msgops.concat(re_held, copy, rcopy), counts


# ----------------------------------------------------- resubscribe policy

def quiesce_resub(sched: ChaosSchedule, margin: int = 2):
    """Chaos-aware isolation-resubscribe policy for the dense models
    (``hyparview_dense.make_dense_round(resub_policy=)`` /
    ``scamp_dense.make_dense_scamp_round(resub_policy=)``): suppress the
    re-subscribe for ``margin`` rounds starting at each crash/partition
    event.  A node isolated BY the event would otherwise fire a join
    storm into an overlay that is mid-disruption (walks into crashed
    contacts, subscriptions across a partition boundary) — the
    reference's own isolation detection waits out a silence window
    before re-subscribing (scamp_v2 :130-178).  Pure table arithmetic:
    jit-safe, zero collectives, and the all-clear schedule folds to the
    identity policy."""
    if margin < 1:
        raise ValueError(f"margin must be >= 1, got {margin}")
    rr = sched.disruptive_rounds()

    def policy(lonely: jax.Array, rnd: jax.Array) -> jax.Array:
        if rr.size == 0:
            return jnp.ones_like(lonely)
        r = jnp.asarray(rr)
        quiet = jnp.any((rnd >= r) & (rnd < r + margin))
        return jnp.broadcast_to(~quiet, lonely.shape)

    return policy
