"""The compiled chaos plane (ISSUE 4 tentpole) — fault SCHEDULES as data,
applied by in-scan arithmetic on BOTH execution paths.

``verify/faults.py`` rebuilt the reference's fault machinery
(test/prop_partisan_crash_fault_model.erl crash/omission interposition,
the hyparview partition flood :1731-1797) as host-driven mutations: the
harness stops the scan, edits ``world.alive``/``world.partition`` or
installs an interposition fun, and resumes.  That shape cannot run at
scan speed, and the sharded dataplane (parallel/dataplane.py) cannot
host per-round Python at all.  This module compiles the whole campaign
instead:

  * :class:`ChaosSchedule` — a STATIC ``[n_events, 5]`` int32 table of
    ``(round, kind, a, b, c)`` events, baked into the jitted step as a
    compile-time constant (the registry enable-mask pattern: swapping
    schedules recompiles, running one costs fused elementwise masks).
  * :func:`apply_chaos_nodes` — the node plane: crash / recover /
    partition / heal events rewrite the ``alive``/``partition`` vectors
    at the top of the round.  Events apply in table order (later rows
    win ties), so a schedule is replayable and order-unambiguous.
  * :func:`apply_chaos_msgs` — the message plane: drop-matching /
    delay-matching / duplicate events edit the ready buffer right after
    the held split — BEFORE the alive/partition masks, which is the one
    point both execution paths see the message on its src's shard (the
    dataplane residency invariant).  Delayed messages re-hold exactly
    like the engine's '$delay' recv split; duplicates append a copy to
    the held buffer with their own delivery delay.  Every edit is
    counted (``chaos_dropped`` / ``chaos_delayed`` /
    ``chaos_duplicated`` step metrics), never silent (SURVEY §7.3).

Both ``engine.make_step(chaos=)`` and
``parallel/dataplane.make_sharded_step(chaos=)`` consume the same
schedule: the planes are pure row/slot-local arithmetic (the node plane
reads only this shard's rows via their GLOBAL ids; the message plane
reads only message fields), so the sharded round adds ZERO collectives
— the asserted 2-collective budget holds chaos-on — and the two paths
stay bit-identical in states and metrics (tests/test_dataplane.py
TestChaosFaultParity).

This is the reference's fault-injection surface
(``partisan_trace_orchestrator.erl`` held-sender schedules, the
filibuster omission schedules, crash_fault_model interposition) with
the orchestrator compiled away: a campaign is rows in a table, and
``scripts/chaos_soak.py`` sweeps seed x fault-mix matrices of them.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.msg import Msgs
from ..ops import msg as msgops

# event kinds, column 1 of the table
KIND_CRASH = 0      # nodes [a, b] crash-stop                   (c unused)
KIND_RECOVER = 1    # nodes [a, b] come back                    (c unused)
KIND_PARTITION = 2  # nodes [a, b] take partition id c (>= 1)
KIND_HEAL = 3       # nodes [a, b] back to partition 0; a < 0 = everyone
KIND_DROP = 4       # msgs src=a dst=b (-1 wildcard) dropped for c rounds
KIND_DELAY = 5      # msgs src=a dst=b delayed +c rounds (this round only)
KIND_DUP = 6        # msgs src=a dst=b duplicated, copy lands +c rounds

KIND_NAMES = ("crash", "recover", "partition", "heal", "drop", "delay",
              "duplicate")
_NODE_KINDS = (KIND_CRASH, KIND_RECOVER, KIND_PARTITION, KIND_HEAL)
_MSG_KINDS = (KIND_DROP, KIND_DELAY, KIND_DUP)
N_COLS = 5


def _rng(nodes) -> Tuple[int, int]:
    """Normalize a node spec: int -> (n, n), (lo, hi) -> inclusive range."""
    if isinstance(nodes, (tuple, list)):
        lo, hi = int(nodes[0]), int(nodes[1])
    else:
        lo = hi = int(nodes)
    if 0 <= lo <= hi:
        return lo, hi
    raise ValueError(f"bad node range {nodes!r}: need 0 <= lo <= hi")


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """An immutable, hashable event table.  Build fluently::

        sched = (ChaosSchedule()
                 .crash(10, (3, 6))          # nodes 3..6 die at round 10
                 .partition(15, (0, 31), 1)  # two halves at round 15
                 .partition(15, (32, 63), 2)
                 .drop(18, src=-1, dst=7, rounds=4)
                 .delay(20, src=3, extra=2)
                 .duplicate(22, copy_delay=1)
                 .heal(30)                   # partitions resolve
                 .recover(32, (3, 6)))       # crashed nodes return

    Each builder returns a NEW schedule (frozen dataclass over a tuple),
    so a schedule is a valid jit closure constant and dict key.
    """

    events: Tuple[Tuple[int, int, int, int, int], ...] = ()

    # ------------------------------------------------------------ builders

    def _add(self, rnd: int, kind: int, a: int, b: int,
             c: int) -> "ChaosSchedule":
        if rnd < 0:
            raise ValueError(f"event round must be >= 0, got {rnd}")
        return ChaosSchedule(self.events
                             + ((int(rnd), int(kind), int(a), int(b),
                                 int(c)),))

    def crash(self, rnd: int, nodes) -> "ChaosSchedule":
        lo, hi = _rng(nodes)
        return self._add(rnd, KIND_CRASH, lo, hi, 0)

    def recover(self, rnd: int, nodes) -> "ChaosSchedule":
        lo, hi = _rng(nodes)
        return self._add(rnd, KIND_RECOVER, lo, hi, 0)

    def partition(self, rnd: int, nodes, gid: int) -> "ChaosSchedule":
        if gid < 1:
            raise ValueError(f"partition id must be >= 1, got {gid}")
        lo, hi = _rng(nodes)
        return self._add(rnd, KIND_PARTITION, lo, hi, gid)

    def heal(self, rnd: int, nodes=None) -> "ChaosSchedule":
        if nodes is None:
            return self._add(rnd, KIND_HEAL, -1, -1, 0)
        lo, hi = _rng(nodes)
        return self._add(rnd, KIND_HEAL, lo, hi, 0)

    def drop(self, rnd: int, src: int = -1, dst: int = -1,
             rounds: int = 1) -> "ChaosSchedule":
        if rounds < 1:
            raise ValueError(f"drop window must be >= 1 rounds, got {rounds}")
        return self._add(rnd, KIND_DROP, src, dst, rounds)

    def delay(self, rnd: int, src: int = -1, dst: int = -1,
              extra: int = 1) -> "ChaosSchedule":
        if extra < 1:
            raise ValueError(f"delay must be >= 1 rounds, got {extra}")
        return self._add(rnd, KIND_DELAY, src, dst, extra)

    def duplicate(self, rnd: int, src: int = -1, dst: int = -1,
                  copy_delay: int = 1) -> "ChaosSchedule":
        if copy_delay < 1:
            raise ValueError(
                f"duplicate copy_delay must be >= 1, got {copy_delay}")
        return self._add(rnd, KIND_DUP, src, dst, copy_delay)

    # ------------------------------------------------------------- queries

    @property
    def n_events(self) -> int:
        return len(self.events)

    def table(self) -> np.ndarray:
        """The [n_events, 5] int32 host table (empty -> [0, 5])."""
        if not self.events:
            return np.zeros((0, N_COLS), np.int32)
        return np.asarray(self.events, np.int32)

    def _kinds(self, kinds) -> Tuple[Tuple[int, ...], ...]:
        return tuple(e for e in self.events if e[1] in kinds)

    @property
    def has_node_events(self) -> bool:
        return bool(self._kinds(_NODE_KINDS))

    @property
    def has_drop(self) -> bool:
        return bool(self._kinds((KIND_DROP,)))

    @property
    def has_delay(self) -> bool:
        return bool(self._kinds((KIND_DELAY,)))

    @property
    def has_dup(self) -> bool:
        return bool(self._kinds((KIND_DUP,)))

    @property
    def has_msg_events(self) -> bool:
        return self.has_drop or self.has_delay or self.has_dup

    def last_heal_round(self) -> int:
        """The round after which no injected disruption remains standing:
        the max over heal/recover event rounds and drop-window ends (the
        soak's convergence-after-heal anchor).  -1 when the schedule
        never disrupts (or never heals what it broke — a schedule that
        crashes without recovering reports the crash round so the soak
        measures from the last state change)."""
        ends = [-1]
        for rnd, kind, _a, _b, c in self.events:
            if kind in (KIND_HEAL, KIND_RECOVER, KIND_CRASH,
                        KIND_PARTITION):
                ends.append(rnd)
            elif kind == KIND_DROP:
                ends.append(rnd + max(c, 1) - 1)
            else:
                ends.append(rnd)
        return max(ends)

    def disruptive_rounds(self) -> np.ndarray:
        """Rounds at which a crash or partition event fires — the
        quiesce window anchors of :func:`quiesce_resub`."""
        rr = [e[0] for e in self.events
              if e[1] in (KIND_CRASH, KIND_PARTITION)]
        return np.asarray(sorted(set(rr)), np.int32)


# --------------------------------------------------------------- node plane

def apply_chaos_nodes(sched: ChaosSchedule, rnd: jax.Array,
                      alive: jax.Array, partition: jax.Array,
                      node_ids: jax.Array
                      ) -> Tuple[jax.Array, jax.Array]:
    """Fold this round's crash/recover/partition/heal events into the
    fault-plane vectors.  ``node_ids`` carries GLOBAL ids, so under the
    sharded dataplane each shard folds the same table over its own row
    slice — pure local arithmetic, zero collectives, bit-identical to
    the global fold restricted to those rows.

    The event loop unrolls over the static table (schedules are small);
    events apply in table order, so a later row overrides an earlier one
    in the same round (e.g. partition-then-heal is a no-op round).
    """
    for ev_rnd, kind, a, b, c in sched._kinds(_NODE_KINDS):
        fire = rnd == ev_rnd
        if a < 0:
            in_rng = jnp.ones_like(node_ids, dtype=bool)
        else:
            in_rng = (node_ids >= a) & (node_ids <= b)
        hit = fire & in_rng
        if kind == KIND_CRASH:
            alive = alive & ~hit
        elif kind == KIND_RECOVER:
            alive = alive | hit
        elif kind == KIND_PARTITION:
            partition = jnp.where(hit, jnp.int32(c), partition)
        else:  # KIND_HEAL
            partition = jnp.where(hit, jnp.int32(0), partition)
    return alive, partition


# ------------------------------------------------------------ message plane

def _match(m: Msgs, src: int, dst: int) -> jax.Array:
    hit = m.valid
    if src >= 0:
        hit = hit & (m.src == src)
    if dst >= 0:
        hit = hit & (m.dst == dst)
    return hit


def apply_chaos_msgs(sched: ChaosSchedule, rnd: jax.Array, now: Msgs):
    """Apply drop / delay / duplicate events to the READY buffer (post
    held-split, pre fault-plane — the point where both execution paths
    still hold every message on its src's shard).  Returns
    ``(now, extra_held, counts)``:

      * ``now`` with dropped and re-held slots invalidated;
      * ``extra_held`` — a flat buffer of chaos-delayed re-holds and
        duplicate copies for the caller to concat into its held traffic
        (``None`` when the schedule has no delay/dup events, so the
        carry shape is unchanged — program shape depends only on the
        static schedule);
      * ``counts`` — ``{"chaos_dropped", "chaos_delayed",
        "chaos_duplicated"}`` int32 scalars over THIS buffer (the
        sharded step psums them; the totals match the unsharded run).

    Order inside the plane: drops first, then delays on the survivors,
    then duplication of the remaining ready slots — one deterministic
    pipeline, identical on both paths.
    """
    zero = jnp.int32(0)
    counts = {"chaos_dropped": zero, "chaos_delayed": zero,
              "chaos_duplicated": zero}
    if not sched.has_msg_events:
        return now, None, counts

    if sched.has_drop:
        drop = jnp.zeros((now.cap,), bool)
        for ev_rnd, _k, a, b, c in sched._kinds((KIND_DROP,)):
            active = (rnd >= ev_rnd) & (rnd < ev_rnd + max(c, 1))
            drop = drop | (_match(now, a, b) & active)
        counts["chaos_dropped"] = jnp.sum(drop).astype(jnp.int32)
        now = now.replace(valid=now.valid & ~drop)

    parts = []
    if sched.has_delay:
        bump = jnp.zeros((now.cap,), jnp.int32)
        for ev_rnd, _k, a, b, c in sched._kinds((KIND_DELAY,)):
            hit = _match(now, a, b) & (rnd == ev_rnd)
            bump = jnp.maximum(bump, jnp.where(hit, jnp.int32(c), 0))
        delayed = now.replace(delay=now.delay + bump)
        # the '$delay' re-hold split, exactly the engine's recv-side
        # shape: held copies age one round immediately (the next round's
        # held split would otherwise double-count this round)
        re_held = delayed.replace(
            valid=delayed.valid & (delayed.delay > 0),
            delay=jnp.maximum(delayed.delay - 1, 0))
        counts["chaos_delayed"] = jnp.sum(re_held.valid).astype(jnp.int32)
        now = delayed.replace(valid=delayed.valid & (delayed.delay <= 0))
        parts.append(re_held)

    if sched.has_dup:
        cdel = jnp.full((now.cap,), -1, jnp.int32)
        for ev_rnd, _k, a, b, c in sched._kinds((KIND_DUP,)):
            hit = _match(now, a, b) & (rnd == ev_rnd)
            cdel = jnp.maximum(cdel, jnp.where(hit, jnp.int32(max(c, 1)),
                                               -1))
        copy = now.replace(valid=now.valid & (cdel >= 0),
                           delay=jnp.maximum(cdel - 1, 0))
        counts["chaos_duplicated"] = jnp.sum(copy.valid).astype(jnp.int32)
        parts.append(copy)

    if not parts:
        return now, None, counts
    extra_held = msgops.concat(*parts) if len(parts) > 1 else parts[0]
    return now, extra_held, counts


# ----------------------------------------------------- resubscribe policy

def quiesce_resub(sched: ChaosSchedule, margin: int = 2):
    """Chaos-aware isolation-resubscribe policy for the dense models
    (``hyparview_dense.make_dense_round(resub_policy=)`` /
    ``scamp_dense.make_dense_scamp_round(resub_policy=)``): suppress the
    re-subscribe for ``margin`` rounds starting at each crash/partition
    event.  A node isolated BY the event would otherwise fire a join
    storm into an overlay that is mid-disruption (walks into crashed
    contacts, subscriptions across a partition boundary) — the
    reference's own isolation detection waits out a silence window
    before re-subscribing (scamp_v2 :130-178).  Pure table arithmetic:
    jit-safe, zero collectives, and the all-clear schedule folds to the
    identity policy."""
    if margin < 1:
        raise ValueError(f"margin must be >= 1, got {margin}")
    rr = sched.disruptive_rounds()

    def policy(lonely: jax.Array, rnd: jax.Array) -> jax.Array:
        if rr.size == 0:
            return jnp.ones_like(lonely)
        r = jnp.asarray(rr)
        quiet = jnp.any((rnd >= r) & (rnd < r + margin))
        return jnp.broadcast_to(~quiet, lonely.shape)

    return policy
