"""Named interposition registry — the TPU analog of the pluggable manager's
``add_pre/post/interposition_fun`` API
(partisan_pluggable_peer_service_manager.erl:51-58, 297-334, 640-667).

In the reference, interposition funs are keyed by name on a live gen_server
and fire on every send/receive: *pre* funs observe, *interposition* funs may
rewrite a message, drop it (return ``undefined``) or delay it (``'$delay'``);
*post* funs observe original+rewritten pairs.  Here the registry is built
BEFORE compiling the step (functions are staged into the jitted program —
the XLA analog of installing hooks): each fun is a pure
``(Msgs, rnd) -> Msgs`` transform over the flat wire buffer; drop = clear
``valid``, delay = bump ``delay``, rewrite = replace fields.  Observation
(the pre/post role) is served by ``capture_wire`` tracing
(engine.make_step) rather than callbacks.

Unlike the reference, changing the set of funs requires re-compiling the
step (~seconds); within a run, funs can still vary behaviour by round
number, which covers every schedule the fault models need.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax

from ..ops.msg import Msgs

InterpFun = Callable[[Msgs, jax.Array], Msgs]


class Interposition:
    """Ordered, named send/recv interposition sets.

    >>> interp = Interposition()
    >>> interp.add_send("drop-joins", faults.send_omission(typ=3))
    >>> step = make_step(cfg, proto, **interp.hooks())
    """

    def __init__(self) -> None:
        self._send: Dict[str, InterpFun] = {}
        self._recv: Dict[str, InterpFun] = {}

    # -- registry (add/remove by name, like :297-334) -----------------------

    def add_send(self, name: str, fn: InterpFun) -> "Interposition":
        self._send[name] = fn
        return self

    def add_recv(self, name: str, fn: InterpFun) -> "Interposition":
        self._recv[name] = fn
        return self

    def remove_send(self, name: str) -> "Interposition":
        self._send.pop(name, None)
        return self

    def remove_recv(self, name: str) -> "Interposition":
        self._recv.pop(name, None)
        return self

    # -- compilation --------------------------------------------------------

    def _compose(self, funs: Dict[str, InterpFun]) -> Optional[InterpFun]:
        if not funs:
            return None
        ordered = tuple(funs.values())

        def composed(m: Msgs, rnd: jax.Array) -> Msgs:
            for f in ordered:
                m = f(m, rnd)
            return m

        return composed

    def hooks(self) -> Dict[str, Optional[InterpFun]]:
        """kwargs for :func:`engine.make_step`."""
        return {
            "interpose_send": self._compose(self._send),
            "interpose_recv": self._compose(self._recv),
        }
