"""Protocol causality analysis — the TPU analog of
``src/partisan_analysis.erl`` (1237 LoC of Core-Erlang static analysis
computing which message sends each receive can cause, feeding the
``analysis/partisan-causality-<mod>`` + ``annotations/…`` files the model
checker prunes with).

This module is the DYNAMIC half of the analog: every handler is executed
(vmapped) over randomized state rows and message payloads, and the types
observed among its valid emissions form the causality edge set.  Sampling
makes this an under-approximation of rare branches (more samples tighten
it) and the random payloads an over-approximation of unreachable ones.
The STATIC half — the direction the reference's cerl walk actually takes
— is verify/static_analysis.py: an AST walk over the handler methods
whose edge map provably over-approximates this one (pruning-sound);
``static_analysis.merged_causality`` combines the static superset with
this module's probe-certified ``__background__`` classification.

Output shape mirrors the reference's annotation files: a JSON map
``{type: [caused types]}`` with the pseudo-sources ``__tick__`` (timer
emissions, the analog of the reference's periodic sends).
"""

from __future__ import annotations

import json
from typing import Dict, List, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..engine import ProtocolBase
from ..ops import msg as msgops
from .. import prng


def _random_msgs(proto: ProtocolBase, cfg: Config, typ: int, samples: int,
                 key: jax.Array) -> msgops.Msgs:
    """A batch of plausible single messages of one type: node ids in
    [-1, N), payload fields uniform over a small id-flavored range."""
    n = cfg.n_nodes
    m = msgops.empty(samples, proto.data_spec)
    keys = jax.random.split(key, 2 + len(m.data))
    m = m.replace(
        valid=jnp.ones((samples,), bool),
        src=jax.random.randint(keys[0], (samples,), 0, n),
        dst=jax.random.randint(keys[1], (samples,), 0, n),
        typ=jnp.full((samples,), typ, jnp.int32),
    )
    for i, name in enumerate(sorted(m.data)):
        f = m.data[name]
        m.data[name] = jax.random.randint(
            keys[2 + i], f.shape, -1, max(n, cfg.arwl + 2)
        ).astype(f.dtype)
    return m


def infer_causality(cfg: Config, proto: ProtocolBase,
                    samples: int = 256, seed: int = 0,
                    rounds_of_state: int = 0,
                    setup=None) -> Dict[str, List[str]]:
    """{message type: sorted list of types its handler can emit}.

    ``rounds_of_state`` > 0 seeds the sampled state rows from a briefly
    simulated world instead of ``proto.init`` (some emissions only occur
    from populated views); ``setup`` (World -> World) runs before the
    evolution — pass the workload's cluster-join setup so periodic sends
    that need a populated membership actually fire.  The
    ``__background__`` classification is relative to this state and
    errs toward soundness: background requires BOTH cluster-wide
    prevalence (the 50% rule — presence of one gate-satisfying row is
    not enough) AND delivery-insensitivity (no observed-wire message
    delivered to a row may change whether the send fires — ADVICE r4;
    see the probe pool below).  Types failing either test are merely
    never pruned against — an efficiency cost, not a soundness one."""
    key = jax.random.PRNGKey(seed)
    state = proto.init(cfg, key)
    # full-payload snapshots of the in-flight message buffer, one per
    # evolution round — the OBSERVED-wire pool the delivery-sensitivity
    # probes below draw from
    obs_msgs = []
    if rounds_of_state:
        from ..engine import init_world, make_step
        w = init_world(cfg, proto)
        if setup is not None:
            w = setup(w)
        obs_msgs.append(jax.tree_util.tree_map(np.asarray, w.msgs))
        step = make_step(cfg, proto, donate=False)
        for _ in range(rounds_of_state):
            w, _ = step(w)
            obs_msgs.append(jax.tree_util.tree_map(np.asarray, w.msgs))
        state = w.state

    n = cfg.n_nodes

    def randomize_row(row, k):
        """Fuzz a state row: guarded branches (e.g. 'all votes in ->
        commit', reachable only from specific states) need state sampling,
        not just payload sampling.  Bools lean True so conjunctive guards
        ('all prepared') have real mass."""
        leaves, treedef = jax.tree_util.tree_flatten(row)
        keys = jax.random.split(k, len(leaves))
        out = []
        for leaf, lk in zip(leaves, keys):
            if leaf.dtype == jnp.bool_:
                out.append(jax.random.bernoulli(lk, 0.7, leaf.shape))
            elif jnp.issubdtype(leaf.dtype, jnp.integer):
                out.append(jax.random.randint(
                    lk, leaf.shape, -1, max(n, 8)).astype(leaf.dtype))
            else:
                out.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, out)

    out: Dict[str, List[str]] = {}
    handlers = proto.handlers()
    for t, name in enumerate(proto.msg_types):
        hkey = jax.random.fold_in(key, t)
        m = _random_msgs(proto, cfg, t, samples, hkey)
        me = jax.random.randint(jax.random.fold_in(hkey, 1),
                                (samples,), 0, n)

        def run_one(j, i, mi, k):
            row = jax.tree_util.tree_map(lambda x: x[i % n], state)
            # half the samples run on fuzzed state rows
            row = jax.lax.cond(
                j % 2 == 0, lambda r: r,
                lambda r: randomize_row(r, jax.random.fold_in(k, 99)), row)
            _, em = handlers[t](cfg, i, row, mi, k)
            return em

        keys = jax.random.split(jax.random.fold_in(hkey, 2), samples)
        ems = jax.vmap(run_one)(jnp.arange(samples), me, m, keys)
        valid = np.asarray(ems.valid)
        typs = np.asarray(ems.typ)
        caused: Set[str] = set()
        for ti in np.unique(typs[valid]):
            caused.add(proto.msg_types[int(ti)])
        out[name] = sorted(caused)

    # timer emissions (the periodic/tick pseudo-source).  Two samplings:
    #   __background__  tick over UNFUZZED rows (init/evolved state) —
    #                   the unconditionally periodic sends, the analog of
    #                   the reference annotations' {background, [...]}
    #                   list (gossip, heartbeats); safe to prune against.
    #   __tick__        union with tick over FUZZED rows at random round
    #                   numbers — adds the STATE-GATED timer emissions
    #                   (e.g. a timeout's decision_request fires only
    #                   from uncertain states).  A gated timer send
    #                   depends on state that arbitrary deliveries
    #                   mutate, so the model checker treats
    #                   __tick__ - __background__ as related to
    #                   everything (never pruned against).
    # sampled nodes x a grid of round numbers (periodic gates key off
    # rnd % interval and (rnd + me) % interval — a single rnd=0 probe
    # misses phase-offset schedules); node count bounded so the pass
    # stays within the caller's `samples` budget at large N
    n_bg = min(n, max(1, samples // 8))
    me = jnp.tile(jnp.arange(n_bg, dtype=jnp.int32), 8)
    brnds = jnp.repeat(jnp.arange(8, dtype=jnp.int32), n_bg)
    tkeys = jax.vmap(prng.decision_key, in_axes=(0, None))(
        jax.random.split(key, me.shape[0]), 7)
    rows = jax.tree_util.tree_map(lambda x: x[me % n], state)
    _, tems = jax.vmap(
        lambda i, r, rnd, k: proto.tick(cfg, i, r, rnd, k)
    )(me, rows, brnds, tkeys)
    tvalid = np.asarray(tems.valid).reshape(me.shape[0], -1)
    ttyps = np.asarray(tems.typ).reshape(me.shape[0], -1)
    # the delivery-sensitivity cross-check (ADVICE r4): the pruning
    # question __background__ answers is "can dropping/reordering OTHER
    # messages ever change whether this timer send fires?".  Probe it
    # directly — deliver ONE message of each type to every grid row
    # (same node/round/tick-key), re-run the tick, and compare firing.
    # A send whose gate a delivery can flip (a timeout cleared by the
    # decision arriving, a suspicion cleared by an ack) flips on some
    # probe and is excluded; a send no delivery can touch is genuinely
    # schedule-independent, which is exactly what makes pruning against
    # it sound.  Works for ANY gate type — int thresholds, single
    # bools, conjunctions — unlike rate-over-random-states heuristics
    # (randomize_row's biased bool draws defeat any fixed threshold).
    #
    # Probes are drawn from the OBSERVED-wire pool of the evolution,
    # not white-noise: pruning soundness is relative to the deliveries
    # a schedule can actually produce, and random payloads
    # over-approximate into unreachable transitions (a random OR-set
    # digest erases a healthy membership, which no real gossip does —
    # measured: such probes flip 2/3 of gossip's firing points).  A
    # type never observed on the wire cannot be rescheduled by the
    # checker, so it contributes no probes.  Residual approximation:
    # gates only a multi-delivery SEQUENCE can flip, or payloads from
    # rounds beyond the evolution window, can slip through; the golden
    # cross-walk (tests/test_prop_analysis.py::TestGoldenCrosswalk)
    # checks the net classification against the reference's
    # hand-checked files.  With rounds_of_state=0 there is no pool and
    # classification falls back to the prevalence rule alone.
    mut_obs = []
    if obs_msgs:
        leaves0, mdef = jax.tree_util.tree_flatten(obs_msgs[0])
        cat = [np.concatenate(
            [jax.tree_util.tree_flatten(o)[0][i] for o in obs_msgs],
            axis=0) for i in range(len(leaves0))]
        pool = jax.tree_util.tree_unflatten(mdef, cat)
        pv = np.asarray(pool.valid)
        ptyp = np.asarray(pool.typ)
        rng_np = np.random.default_rng(seed ^ 0x5EED)
        for tprime in range(len(proto.msg_types)):
            sel = np.nonzero(pv & (ptyp == tprime))[0]
            if sel.size == 0:
                continue
            idx = rng_np.choice(sel, size=me.shape[0], replace=True)
            mm = jax.tree_util.tree_map(
                lambda x: jnp.asarray(np.asarray(x)[idx]), pool)

            def deliver_then_tick(i, r, mi, rnd, k, _h=handlers[tprime]):
                r2, _ = _h(cfg, i, r, mi, jax.random.fold_in(k, 55))
                _, em = proto.tick(cfg, i, r2, rnd, k)
                return em

            ems_m = jax.vmap(deliver_then_tick)(me, rows, mm, brnds,
                                                tkeys)
            mut_obs.append(
                (np.asarray(ems_m.typ).reshape(me.shape[0], -1),
                 np.asarray(ems_m.valid).reshape(me.shape[0], -1)))
    # PREVALENCE rule: background = the cluster fires it ON SCHEDULE —
    # >=50% of sampled rows emit the type at its best probe round.  Mere
    # presence is not enough: a single row evolved into a timeout gate
    # (a PREPARED-past-timeout participant firing decision_request)
    # must NOT be classed background, or the checker would prune
    # against a state-gated send and lose real counterexamples.
    # Misclassifying the other way (a phase-offset periodic send under
    # 50%, or a state-insensitive send the fuzz check can't certify)
    # only costs pruning efficiency.
    background = set()
    for t in np.unique(ttyps[tvalid]):
        emits = ((ttyps == t) & tvalid).any(axis=-1)     # [8 * n_bg]
        frac = emits.reshape(8, n_bg).mean(axis=1)       # per probe round
        if float(frac.max()) < 0.5:
            continue
        # background ALSO requires delivery-insensitivity (ADVICE r4):
        # firing must be unchanged when any single random delivery
        # mutates the row first.  A state-gated timer send a majority
        # of the EVOLVED rows happen to satisfy (all participants past
        # a shared timeout) is unmasked by the delivery that clears its
        # gate; a send whose firing no delivery can change is safe to
        # prune against by construction.
        sensitive = False
        for mtyps, mvalid in mut_obs:
            memits = ((mtyps == t) & mvalid).any(axis=-1)
            if bool(np.any(memits != emits)):
                sensitive = True
                break
        if not sensitive:
            background.add(proto.msg_types[int(t)])

    # 4x the per-handler sample count: gated timer predicates are
    # CONJUNCTIVE (status == X and timer == 1), so single-sample hit
    # rates are ~1/d^2 over the fuzz domain — oversample to make every
    # reachable gate a near-certain find
    nf = 4 * samples
    fme = jax.random.randint(jax.random.fold_in(key, 501),
                             (nf,), 0, n)
    fkeys = jax.random.split(jax.random.fold_in(key, 502), nf)
    frnds = jax.random.randint(jax.random.fold_in(key, 503),
                               (nf,), 0, 64)

    def tick_one(i, rnd, k):
        row = jax.tree_util.tree_map(lambda x: x[i % n], state)
        row = randomize_row(row, jax.random.fold_in(k, 98))
        _, em = proto.tick(cfg, i, row, rnd, k)
        return em

    gems = jax.vmap(tick_one)(fme, frnds, fkeys)
    gtyps, gvalid = np.asarray(gems.typ), np.asarray(gems.valid)
    gated = {proto.msg_types[int(t)] for t in np.unique(gtyps[gvalid])}
    out["__background__"] = sorted(background)
    out["__tick__"] = sorted(background | gated)
    return out


def write_annotations(path: str, causality: Dict[str, List[str]]) -> None:
    """The annotations/partisan-annotations-<mod> analog (JSON)."""
    with open(path, "w") as f:
        json.dump(causality, f, indent=2, sort_keys=True)


def read_annotations(path: str) -> Dict[str, List[str]]:
    with open(path) as f:
        return json.load(f)


def independence_relation(causality: Dict[str, List[str]],
                          proto) -> Tuple[Set[Tuple[int, int]], Set[int]]:
    """The pruning relation both schedule searchers share (ISSUE 7):
    from a causality map (:func:`infer_causality` /
    ``static_analysis.merged_causality``) build

      * ``related`` — the symmetric set of wire-tag pairs ``(ta, tb)``
        where one type can causally reach the other: faults on UNRELATED
        types compose independently, so a schedule combining them is
        implied by its singletons (the reference's annotation pruning,
        filibuster_SUITE :697-930);
      * ``relate_all`` — wire tags of state-gated timer emissions (in
        ``__tick__`` but not ``__background__``): their firing predicate
        reads state arbitrary deliveries mutate, so nothing can be
        proven independent of them (the VERDICT r3 soundness hole).

    Keys use ``proto.typ()`` (not ``msg_types.index``) so layered
    protocols with a ``_typ_offset`` relate their actual wire tags.
    ``verify/model_checker.py`` consults it to skip redundant schedule
    extensions; ``verify/explorer.py`` consults it to keep only frontier
    perturbations causally related to the invariant's channels."""
    names = list(proto.msg_types)
    reach = {t: reachable_types(causality, [t]) for t in names}
    related = {
        (proto.typ(a), proto.typ(b))
        for a in names for b in names
        if a in reach.get(b, ()) or b in reach.get(a, ())}
    gated = (set(causality.get("__tick__", []))
             - set(causality.get("__background__", [])))
    relate_all = {proto.typ(t) for t in gated if t in names}
    return related, relate_all


def reachable_types(causality: Dict[str, List[str]],
                    roots: List[str]) -> Set[str]:
    """Transitive closure — which types can an omission of ``roots`` ever
    suppress downstream (the model checker's pruning question)."""
    seen: Set[str] = set()
    stack = list(roots)
    while stack:
        t = stack.pop()
        if t in seen:
            continue
        seen.add(t)
        stack.extend(causality.get(t, []))
    return seen
