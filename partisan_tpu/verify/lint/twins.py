"""Host-twin drift detection.

Repo-wide convention since ISSUE 10: a device function ``X`` that needs
bit-parity validation ships a pure-Python twin ``host_X`` in the same
module (``admit``/``host_admit``, ``update_plane``/``host_update_plane``,
``ewma_filter``/``host_ewma_filter`` …) and a test asserts bit-equality
over randomized streams.  The tests catch *value* drift; this rule
catches *structural* drift the moment it is written: a twin pair whose
parameter lists disagree, or whose integer-constant sets disagree (the
milli-unit scale factors, clamps, and sentinels ARE the algorithm in
this integer-arithmetic codebase — if the device side changes 1000 to
1024 and the host side doesn't, parity is stale even if today's test
inputs happen not to reach the changed region).

Constants 0/1/-1 and the float literals are excluded from the
comparison: both sides use them ubiquitously for masks/increments in
ways that legitimately differ (jnp.where(m, 1, 0) vs `if m:`), and
floats appear only in jnp dtype positions.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .engine import FnInfo, ModuleIndex
from .report import Finding


def _params(fn: FnInfo) -> List[str]:
    a = fn.node.args
    names = [x.arg for x in (a.posonlyargs + a.args + a.kwonlyargs)]
    return [n for n in names if n != "self"]


def _const_set(fn: FnInfo, idx: ModuleIndex,
               seen: Optional[Set[str]] = None) -> Set[int]:
    """Integer constants referenced by ``fn``, following same-module
    calls one hop at a time (``host_admit_dynamic`` references 1000
    THROUGH ``host_admit`` — delegation is not drift)."""
    seen = set() if seen is None else seen
    if fn.qualname in seen:
        return set()
    seen.add(fn.qualname)
    out: Set[int] = set()
    for node in fn.own_nodes():
        if (isinstance(node, ast.Constant)
                and isinstance(node.value, int)
                and not isinstance(node.value, bool)
                and abs(node.value) > 1):
            out.add(node.value)
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Name)):
            callee = _module_level(idx, node.func.id)
            if callee is not None:
                out |= _const_set(callee, idx, seen)
    return out


def _module_level(idx: ModuleIndex, name: str) -> Optional[FnInfo]:
    for f in idx.fns:
        if f.name == name and f.parent is None and f.cls is None:
            return f
    return None


def check_twins(idx: ModuleIndex) -> List[Finding]:
    out: List[Finding] = []
    for host in idx.fns:
        if not (host.name.startswith("host_") and host.parent is None
                and host.cls is None):
            continue
        dev = _module_level(idx, host.name[len("host_"):])
        if dev is None:
            continue              # twin lives elsewhere / free-standing
        hp, dp = _params(host), _params(dev)
        if hp != dp:
            out.append(Finding(
                "twin-drift", idx.path, host.node.lineno,
                f"{host.name} vs {dev.name}: parameter lists diverged "
                f"({hp} vs {dp}) — the bit-parity twin contract "
                f"requires identical signatures"))
        hc, dc = _const_set(host, idx), _const_set(dev, idx)
        if hc != dc:
            only_h = sorted(hc - dc)
            only_d = sorted(dc - hc)
            out.append(Finding(
                "twin-drift", idx.path, host.node.lineno,
                f"{host.name} vs {dev.name}: integer-constant sets "
                f"diverged (host-only {only_h}, device-only {only_d}) "
                f"— scale factors/clamps must match for bit parity"))
    return out
