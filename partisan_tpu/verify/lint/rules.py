"""Trace-lint AST rules — each runs over ONE traced function with the
provenance environment from :mod:`.engine`.

Thresholds, stated once: loop-structure rules fire at CONFIG and above
(a config/shape/runtime trip count changes the *program*), value rules
fire at RUNTIME only (coercing a config int is legal and common — it is
coercing the *output of traced ops* that concretizes a tracer).
"""

from __future__ import annotations

import ast
from typing import List

from .engine import (CONFIG, LEVEL_NAMES, RUNTIME, STATIC, FnInfo,
                     ModuleIndex, ProvEnv, _dotted_root, _is_cfg_base)
from .report import Finding


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:                 # pragma: no cover — defensive
        return "<expr>"


def _clip(s: str, n: int = 48) -> str:
    return s if len(s) <= n else s[:n - 3] + "..."


def run_rules(idx: ModuleIndex, fn: FnInfo) -> List[Finding]:
    env = idx.env_for(fn)
    out: List[Finding] = []
    where = f"{fn.qualname} (tier {fn.tier})"
    for node in fn.own_nodes():
        _unroll_bomb(idx, fn, env, node, where, out)
        _traced_coercion(idx, fn, env, node, where, out)
        _traced_format(idx, fn, env, node, where, out)
        _config_fork(idx, fn, env, node, where, out)
    return out


# -------------------------------------------------------------- the rules

def _unroll_bomb(idx: ModuleIndex, fn: FnInfo, env: ProvEnv,
                 node: ast.AST, where: str, out: List[Finding]) -> None:
    if isinstance(node, ast.For):
        it = node.iter
        # unwrap enumerate()/reversed() around the real iterable
        while (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
               and it.func.id in ("enumerate", "reversed")
               and it.args):
            it = it.args[0]
        # only NUMERIC trip counts (range) are unroll bombs — a for
        # over a python container (zip/items/list of invariants) walks
        # build-time structure, which is the intended pattern here
        if not (isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id == "range"):
            return
        lvl = max((env.prov(a) for a in it.args), default=STATIC)
        if lvl >= CONFIG:
            out.append(Finding(
                "unroll-bomb", idx.path, node.lineno,
                f"{where}: for-loop over `{_clip(_src(node.iter))}` — "
                f"trip count has {LEVEL_NAMES[lvl]} provenance, so the "
                f"body unrolls per config/shape into the jaxpr; use "
                f"lax.fori_loop/scan or hoist the bound to build time"))
    elif isinstance(node, ast.While):
        lvl = env.prov(node.test)
        if lvl >= CONFIG:
            out.append(Finding(
                "unroll-bomb", idx.path, node.lineno,
                f"{where}: while-loop test `{_clip(_src(node.test))}` "
                f"has {LEVEL_NAMES[lvl]} provenance — a data-dependent "
                f"Python while in traced code either unrolls unboundedly "
                f"or concretizes; use lax.while_loop"))


_COERCERS = ("int", "float", "bool")


def _traced_coercion(idx: ModuleIndex, fn: FnInfo, env: ProvEnv,
                     node: ast.AST, where: str,
                     out: List[Finding]) -> None:
    if not isinstance(node, ast.Call):
        return
    f = node.func
    if (isinstance(f, ast.Name) and f.id in _COERCERS and node.args
            and env.prov(node.args[0]) >= RUNTIME):
        out.append(Finding(
            "traced-coercion", idx.path, node.lineno,
            f"{where}: {f.id}() on traced value "
            f"`{_clip(_src(node.args[0]))}` — concretizes the tracer "
            f"(ConcretizationTypeError under jit)"))
    elif isinstance(f, ast.Attribute) and f.attr == "item":
        if env.prov(f.value) >= RUNTIME:
            out.append(Finding(
                "traced-coercion", idx.path, node.lineno,
                f"{where}: .item() on traced value "
                f"`{_clip(_src(f.value))}` — host sync inside traced "
                f"code"))
    elif (isinstance(f, ast.Attribute)
          and _dotted_root(f) in ("np", "numpy")):
        hot = [a for a in node.args if env.prov(a) >= RUNTIME]
        if hot:
            out.append(Finding(
                "traced-coercion", idx.path, node.lineno,
                f"{where}: np.{f.attr}() on traced value "
                f"`{_clip(_src(hot[0]))}` — numpy pulls the tracer to "
                f"host; use the jnp equivalent"))


def _traced_format(idx: ModuleIndex, fn: FnInfo, env: ProvEnv,
                   node: ast.AST, where: str,
                   out: List[Finding]) -> None:
    if isinstance(node, ast.JoinedStr):
        for v in node.values:
            if (isinstance(v, ast.FormattedValue)
                    and env.prov(v.value) >= RUNTIME):
                out.append(Finding(
                    "traced-format", idx.path, node.lineno,
                    f"{where}: f-string interpolates traced value "
                    f"`{_clip(_src(v.value))}` — formats the tracer "
                    f"repr, not the runtime value"))
                return
    elif isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id == "str" and node.args:
            if env.prov(node.args[0]) >= RUNTIME:
                out.append(Finding(
                    "traced-format", idx.path, node.lineno,
                    f"{where}: str() on traced value "
                    f"`{_clip(_src(node.args[0]))}`"))
        elif isinstance(f, ast.Attribute) and f.attr == "format":
            hot = [a for a in list(node.args)
                   + [kw.value for kw in node.keywords]
                   if env.prov(a) >= RUNTIME]
            if hot:
                out.append(Finding(
                    "traced-format", idx.path, node.lineno,
                    f"{where}: .format() over traced value "
                    f"`{_clip(_src(hot[0]))}`"))


def _config_fork(idx: ModuleIndex, fn: FnInfo, env: ProvEnv,
                 node: ast.AST, where: str, out: List[Finding]) -> None:
    if not isinstance(node, ast.If):
        return
    for sub in ast.walk(node.test):
        if isinstance(sub, ast.Attribute) and _is_cfg_base(sub.value):
            out.append(Finding(
                "config-fork", idx.path, node.lineno,
                f"{where}: branches on `{_clip(_src(sub))}` inside a "
                f"traced function — every distinct config traces a "
                f"distinct program (program-shape fork); hoist the "
                f"branch to the builder"))
            return
