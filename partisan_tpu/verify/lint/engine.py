"""Trace-lint Level 1: the AST rule engine.

The reference derives protocol annotations from a *static* Core-Erlang
walk (``partisan_analysis.erl``); ``verify/static_analysis.py`` already
rebuilt that direction for causality.  This module extends it to the
whole compile surface: every module under ``partisan_tpu/`` is parsed
(never imported — no JAX required) and functions are classified as
traced or host, then the rules in :mod:`.rules` run over the traced
ones with a provenance analysis that tells a build-time constant from a
Config field from a traced value.

Classification (deliberately two-tier, over-approximating TRACED):

* **Tier A — structurally traced.**  A function is traced if it is
  passed by name into a trace entry point (``jax.jit``, ``vmap``,
  ``lax.scan``/``cond``/``switch``/``while_loop``/``fori_loop``,
  ``shard_map``, ``pallas_call`` …), if it is a protocol handler by the
  repo's naming convention (``handle_*``, ``tick``, ``tick_upper``), or
  if it is reachable from a Tier-A function through ``self.X(...)`` /
  local-name calls / ``functools.partial`` aliases.  Nested ``def``s
  inside a Tier-A body are traced too (they are the scan/cond bodies).
* **Tier B — heuristically traced.**  Anything else that *uses* traced
  ops (``jnp.``/``lax.``/``jax.lax`` …) and shows no host marker
  (``np.asarray``, ``device_get``, ``block_until_ready``, ``.tolist``,
  ``print``, ``time.``) and is not a builder by name (``make_*``,
  ``*_init``, ``host_*``, ``__init__``, ``test_*`` …).  Builders run at
  Python time by convention across this repo ("the feature gates at
  build time"), so their loops over config are exactly the intended
  place for config-dependent structure.

Provenance lattice (what the rules compare against)::

    STATIC(0) < PARAM(1) < CONFIG(2) < SHAPE(3) < RUNTIME(4)

Function parameters sit at PARAM — builder params like ``fanout`` or
``n_shards`` are static-by-construction in this codebase, and treating
them as runtime would bury the real findings under noise.  The price is
flow-insensitivity in the other direction: a loop bounded by a
genuinely-traced *parameter* is not flagged (it would not trace at all,
so XLA catches it long before CI would).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .report import Finding, apply_pragmas, parse_pragmas

# ----------------------------------------------------------- provenance

STATIC, PARAM, CONFIG, SHAPE, RUNTIME = 0, 1, 2, 3, 4
LEVEL_NAMES = {STATIC: "static", PARAM: "param", CONFIG: "config",
               SHAPE: "shape", RUNTIME: "runtime"}

#: callables that begin a traced region when handed a function by name
TRACE_ENTRIES = frozenset({
    "jit", "vmap", "pmap", "scan", "cond", "switch", "while_loop",
    "fori_loop", "shard_map", "pallas_call", "remat", "checkpoint",
    "associative_scan", "custom_vjp", "custom_jvp", "named_call",
})

#: module aliases whose attribute calls mean "this code builds a jaxpr"
_TRACED_ROOTS = frozenset({"jnp", "lax"})
#: calls/attrs that mean "this function syncs to host" — a function
#: containing one is host-side glue even if it also touches jnp
_HOST_CALL_ATTRS = frozenset({
    "device_get", "block_until_ready", "tolist", "item",
})
#: these are host markers only under a numpy root (jnp.asarray is a
#: device op; np.asarray is THE canonical host transfer)
_NP_HOST_ATTRS = frozenset({"asarray", "array"})
_NP_ROOTS = frozenset({"np", "numpy", "onp"})
_HOST_CALL_NAMES = frozenset({"print", "input", "open"})
_HOST_ROOTS = frozenset({"time", "os", "sys", "json", "csv"})

#: ``.attr`` accesses that stay compile-time even on a traced array
_SHAPE_ATTRS = frozenset({"shape", "size"})
_STATIC_ATTRS = frozenset({"ndim", "dtype", "at"})


def _dotted_root(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an Attribute chain (``jax.lax.scan`` -> jax)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_cfg_base(node: ast.AST) -> bool:
    """``cfg.X`` / ``self.cfg.X`` / ``some_cfg.X`` bases."""
    if isinstance(node, ast.Name):
        return node.id == "cfg" or node.id.endswith("cfg")
    if isinstance(node, ast.Attribute):
        return node.attr == "cfg" or node.attr.endswith("cfg")
    return False


class ProvEnv:
    """Per-function provenance environment with a lexical parent chain
    (closures see the enclosing function's locals)."""

    def __init__(self, fn: "FnInfo", parent: Optional["ProvEnv"],
                 module_consts: Dict[str, int]):
        self.parent = parent
        self.module_consts = module_consts
        self.names: Dict[str, int] = {}
        args = fn.node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            self.names[a.arg] = PARAM
        if args.vararg:
            self.names[args.vararg.arg] = PARAM
        if args.kwarg:
            self.names[args.kwarg.arg] = PARAM
        if "self" in self.names:
            # `self` itself is the protocol/builder object, not a tracer
            self.names["self"] = STATIC
        self._fill(fn)

    def _fill(self, fn: "FnInfo") -> None:
        # single in-order pass over the function's OWN statements
        # (nested defs excluded): flow-insensitive, last write wins,
        # which matches the straight-line style of the traced code here
        for node in fn.own_nodes():
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    self._assign(tgt, node.value)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    lvl = max(self.lookup(node.target.id),
                              self.prov(node.value))
                    self.names[node.target.id] = lvl
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._assign(node.target, node.value)
            elif isinstance(node, ast.For):
                # loop variable inherits the iterable's provenance
                self._assign_level(node.target, self.prov(node.iter))
            elif isinstance(node, (ast.withitem,)) and node.optional_vars:
                self._assign(node.optional_vars, node.context_expr)

    def _assign(self, tgt: ast.AST, value: ast.AST) -> None:
        if isinstance(tgt, ast.Name):
            self.names[tgt.id] = self.prov(value)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            vals = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                    and len(value.elts) == len(tgt.elts) else None)
            for i, t in enumerate(tgt.elts):
                if vals is not None:
                    self._assign(t, vals[i])
                else:
                    self._assign_level(t, self.prov(value))
        # attribute/subscript targets carry no new name binding

    def _assign_level(self, tgt: ast.AST, level: int) -> None:
        if isinstance(tgt, ast.Name):
            self.names[tgt.id] = level
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for t in tgt.elts:
                self._assign_level(t, level)

    def lookup(self, name: str) -> int:
        env: Optional[ProvEnv] = self
        while env is not None:
            if name in env.names:
                return env.names[name]
            env = env.parent
        # module-level constant / import / def — build-time by definition
        return STATIC

    # -- expression provenance ------------------------------------------

    def prov(self, node: ast.AST) -> int:
        if isinstance(node, ast.Constant):
            return STATIC
        if isinstance(node, ast.Name):
            return self.lookup(node.id)
        if isinstance(node, ast.Attribute):
            return self._attr_prov(node)
        if isinstance(node, ast.Subscript):
            if (isinstance(node.value, ast.Attribute)
                    and node.value.attr in _SHAPE_ATTRS):
                return SHAPE          # x.shape[0]
            return max(self.prov(node.value), self.prov(node.slice))
        if isinstance(node, ast.Call):
            return self._call_prov(node)
        if isinstance(node, (ast.BinOp,)):
            return max(self.prov(node.left), self.prov(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.prov(node.operand)
        if isinstance(node, ast.BoolOp):
            return max((self.prov(v) for v in node.values), default=STATIC)
        if isinstance(node, ast.Compare):
            return max([self.prov(node.left)]
                       + [self.prov(c) for c in node.comparators])
        if isinstance(node, ast.IfExp):
            return max(self.prov(node.test), self.prov(node.body),
                       self.prov(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return max((self.prov(e) for e in node.elts), default=STATIC)
        if isinstance(node, ast.Starred):
            return self.prov(node.value)
        if isinstance(node, ast.JoinedStr):
            return max((self.prov(v.value) for v in node.values
                        if isinstance(v, ast.FormattedValue)),
                       default=STATIC)
        if isinstance(node, ast.Slice):
            return max((self.prov(p) for p in
                        (node.lower, node.upper, node.step)
                        if p is not None), default=STATIC)
        return STATIC

    def _attr_prov(self, node: ast.Attribute) -> int:
        if node.attr in _STATIC_ATTRS:
            return STATIC
        if node.attr in _SHAPE_ATTRS:
            return SHAPE
        if _is_cfg_base(node.value):
            return CONFIG
        if isinstance(node.value, ast.Name):
            base = node.value.id
            if base == "self":
                # instance attribute: fixed at build time per object,
                # but forks the program per configuration -> CONFIG
                return CONFIG
            root_lvl = self.lookup(base)
            if root_lvl == STATIC:
                return STATIC         # module alias / class / import
            # attribute on a local or parameter: a field of whatever
            # flows through — conservatively runtime
            return RUNTIME
        return max(self.prov(node.value), PARAM)

    def _call_prov(self, node: ast.Call) -> int:
        f = node.func
        arg_lvl = max(
            [self.prov(a) for a in node.args]
            + [self.prov(kw.value) for kw in node.keywords]
            + [STATIC])
        if isinstance(f, ast.Name):
            if f.id in ("len", "isinstance", "getattr", "hasattr",
                        "callable", "type", "id"):
                return STATIC
            if f.id in ("range", "min", "max", "abs", "int", "float",
                        "bool", "sum", "enumerate", "zip", "reversed",
                        "sorted", "tuple", "list"):
                return arg_lvl
            # free function: result no cleaner than its inputs
            return arg_lvl
        if isinstance(f, ast.Attribute):
            root = _dotted_root(f)
            if root in _TRACED_ROOTS or root == "jax":
                return RUNTIME        # jnp./lax./jax.* build tracers
            if root in ("np", "numpy", "math", "functools", "operator"):
                return arg_lvl
            # bound method: result follows the receiver and the args
            return max(self.prov(f.value), arg_lvl)
        return arg_lvl


# ------------------------------------------------- function classification

@dataclass
class FnInfo:
    node: ast.AST                     # FunctionDef / AsyncFunctionDef
    name: str
    qualname: str
    cls: Optional[str]                # enclosing class name, if a method
    parent: Optional["FnInfo"]        # lexically enclosing function
    traced: bool = field(default=False)
    tier: str = field(default="")     # "A" / "B" / "" (host)

    def own_nodes(self) -> Iterable[ast.AST]:
        """Every AST node of THIS function's body, stopping at nested
        function boundaries (a nested def is its own FnInfo)."""
        stack: List[ast.AST] = list(reversed(self.node.body))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue              # nested def: its own FnInfo's walk
            yield n
            # preorder in SOURCE order — the provenance pass is
            # last-write-wins, so document order is load-bearing
            stack.extend(reversed(list(ast.iter_child_nodes(n))))


_HOST_NAME_PREFIXES = ("make_", "build_", "host_", "init", "_init",
                       "test_", "bench_", "run_", "load_", "save_",
                       "format_", "plot_", "main")
_HOST_NAME_SUFFIXES = ("_init", "_main")
_HANDLER_NAMES = ("tick", "tick_upper")


#: classes that are host-side harnesses by convention — their methods
#: drive compiled programs, they are not traced themselves
_HOST_CLASS_SUFFIXES = ("Runner", "Checker", "Suite", "Bridge", "Server",
                        "Service", "Session", "Launcher", "Explorer")


def _is_host_by_name(fn: FnInfo) -> bool:
    n = fn.name
    if n.startswith("__") and n.endswith("__"):
        return True
    if fn.cls is not None and fn.cls.endswith(_HOST_CLASS_SUFFIXES):
        return True
    return (n.startswith(_HOST_NAME_PREFIXES)
            or n.endswith(_HOST_NAME_SUFFIXES))


class ModuleIndex:
    """All functions of one module + the Tier-A/Tier-B classification."""

    def __init__(self, tree: ast.Module, path: str):
        self.path = path
        self.tree = tree
        self.fns: List[FnInfo] = []
        self.module_consts: Dict[str, int] = {}
        self._collect(tree, cls=None, parent=None, prefix="")
        for stmt in tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, int)):
                self.module_consts[stmt.targets[0].id] = stmt.value.value
        self._classify()

    # -- collection -----------------------------------------------------

    def _collect(self, node: ast.AST, cls: Optional[str],
                 parent: Optional[FnInfo], prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FnInfo(child, child.name, prefix + child.name,
                            cls, parent)
                self.fns.append(fi)
                self._collect(child, cls=None, parent=fi,
                              prefix=fi.qualname + ".")
            elif isinstance(child, ast.ClassDef):
                self._collect(child, cls=child.name, parent=parent,
                              prefix=prefix + child.name + ".")
            elif not isinstance(child, ast.Lambda):
                self._collect(child, cls=cls, parent=parent, prefix=prefix)

    # -- classification -------------------------------------------------

    def _resolve(self, name: str, scope: FnInfo,
                 cls: Optional[str]) -> Optional[FnInfo]:
        """Function the bare name ``name`` refers to from inside
        ``scope``: nested def, enclosing-scope def, or module-level."""
        chain: List[Optional[FnInfo]] = []
        p: Optional[FnInfo] = scope
        while p is not None:
            chain.append(p)
            p = p.parent
        chain.append(None)            # module scope
        for holder in chain:
            for f in self.fns:
                if f.name == name and f.parent is holder:
                    return f
        return None

    def _method(self, cls: Optional[str], name: str) -> Optional[FnInfo]:
        if cls is None:
            return None
        for f in self.fns:
            if f.cls == cls and f.name == name:
                return f
        return None

    def _classify(self) -> None:
        # partial aliases: `emit = functools.partial(_emit, ...)` makes a
        # call to `emit` inside a traced fn reach `_emit`
        aliases: Dict[Tuple[Optional[str], str], str] = {}
        for fn in self.fns:
            for node in fn.own_nodes():
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)):
                    cf = node.value.func
                    tail = (cf.attr if isinstance(cf, ast.Attribute)
                            else cf.id if isinstance(cf, ast.Name) else "")
                    if (tail == "partial" and node.value.args
                            and isinstance(node.value.args[0], ast.Name)):
                        aliases[(fn.qualname, node.targets[0].id)] = \
                            node.value.args[0].id

        seeds: List[FnInfo] = []
        # (1) protocol handlers by convention
        for fn in self.fns:
            if fn.cls is not None and (
                    fn.name.startswith("handle_")
                    or fn.name in _HANDLER_NAMES):
                seeds.append(fn)
        # (2) functions handed to trace entry points by name
        for fn in self.fns:
            for node in fn.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                tail = (node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else node.func.id
                        if isinstance(node.func, ast.Name) else "")
                if tail not in TRACE_ENTRIES:
                    continue
                cands = list(node.args) + [kw.value for kw in node.keywords]
                for a in cands:
                    if (isinstance(a, ast.Call)
                            and isinstance(a.func, (ast.Name, ast.Attribute))
                            and (a.func.id if isinstance(a.func, ast.Name)
                                 else a.func.attr) == "partial"
                            and a.args):
                        a = a.args[0]
                    if isinstance(a, ast.Name):
                        t = self._resolve(a.id, fn, fn.cls)
                        if t is not None:
                            seeds.append(t)
                    elif (isinstance(a, ast.Attribute)
                          and isinstance(a.value, ast.Name)
                          and a.value.id == "self"):
                        t = self._method(fn.cls, a.attr)
                        if t is not None:
                            seeds.append(t)
        # (3) @jit-style decorators
        for fn in self.fns:
            for dec in getattr(fn.node, "decorator_list", ()):
                d = dec.func if isinstance(dec, ast.Call) else dec
                tail = (d.attr if isinstance(d, ast.Attribute)
                        else d.id if isinstance(d, ast.Name) else "")
                if tail in TRACE_ENTRIES or tail == "partial" and \
                        isinstance(dec, ast.Call) and any(
                            (isinstance(a, ast.Attribute)
                             and a.attr in TRACE_ENTRIES)
                            or (isinstance(a, ast.Name)
                                and a.id in TRACE_ENTRIES)
                            for a in dec.args):
                    seeds.append(fn)

        # transitive closure: self-calls, local-name calls, aliases,
        # and nested defs of traced functions
        work = list(seeds)
        while work:
            fn = work.pop()
            if fn.traced:
                continue
            fn.traced, fn.tier = True, "A"
            for g in self.fns:
                if g.parent is fn and not g.traced:
                    work.append(g)
            for node in fn.own_nodes():
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"):
                    t = self._method(fn.cls, f.attr)
                    if t is not None and not t.traced:
                        work.append(t)
                elif isinstance(f, ast.Name):
                    name = f.id
                    # follow partial aliases bound in any enclosing scope
                    p: Optional[FnInfo] = fn
                    while p is not None:
                        name = aliases.get((p.qualname, name), name)
                        p = p.parent
                    t = self._resolve(name, fn, fn.cls)
                    if t is not None and not t.traced:
                        work.append(t)

        # Tier B: jnp/lax users with no host markers and a non-builder name
        for fn in self.fns:
            if fn.traced or _is_host_by_name(fn):
                continue
            has_traced, has_host = False, False
            for node in fn.own_nodes():
                if isinstance(node, ast.Attribute):
                    root = _dotted_root(node)
                    if root in _TRACED_ROOTS:
                        has_traced = True
                    if root in _HOST_ROOTS:
                        has_host = True
                    if node.attr in _HOST_CALL_ATTRS:
                        has_host = True
                    if (node.attr in _NP_HOST_ATTRS
                            and root in _NP_ROOTS):
                        has_host = True
                    if node.attr == "Tracer":
                        # an explicit isinstance(x, jax.core.Tracer)
                        # guard marks deliberate host/trace dual-mode
                        # code — the host branch owns the coercions
                        has_host = True
                elif isinstance(node, ast.Call):
                    if (isinstance(node.func, ast.Name)
                            and node.func.id in _HOST_CALL_NAMES):
                        has_host = True
                    # int()/float()/bool() applied DIRECTLY to a jnp/lax
                    # result is legal only on a concrete (host) array —
                    # code doing it is host-side analysis, not a traced
                    # fn (Tier A, where it would be a real bug, is
                    # classified structurally and ignores this marker)
                    if (isinstance(node.func, ast.Name)
                            and node.func.id in ("int", "float", "bool")
                            and node.args
                            and isinstance(node.args[0], ast.Call)
                            and _dotted_root(node.args[0].func)
                            in _TRACED_ROOTS):
                        has_host = True
            if has_traced and not has_host:
                fn.traced, fn.tier = True, "B"

    def env_for(self, fn: FnInfo) -> ProvEnv:
        parent_env = self.env_for(fn.parent) if fn.parent else None
        return ProvEnv(fn, parent_env, self.module_consts)


# ------------------------------------------------------------ module walk

def lint_source(src: str, path: str) -> List[Finding]:
    """Level-1 lint of one module's source: rules + twins + pragmas."""
    from .rules import run_rules          # local: avoid import cycle
    from .twins import check_twins
    tree = ast.parse(src)
    idx = ModuleIndex(tree, path)
    pragmas, engine_findings = parse_pragmas(src, path)
    findings: List[Finding] = []
    for fn in idx.fns:
        if fn.traced:
            findings.extend(run_rules(idx, fn))
    findings.extend(check_twins(idx))
    return apply_pragmas(findings, pragmas, path) + engine_findings


def lint_paths(paths: Iterable[str], root: str = "") -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        rel = os.path.relpath(p, root) if root else p
        with open(p, encoding="utf-8") as f:
            out.extend(lint_source(f.read(), rel))
    return out


def lint_tree(pkg_dir: str, root: str = "") -> List[Finding]:
    """Lint every ``*.py`` under ``pkg_dir`` (the partisan_tpu tree)."""
    paths = []
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))
    return lint_paths(sorted(paths), root or os.path.dirname(pkg_dir))
