"""Findings, pragmas, and report formatting for trace-lint.

Pure stdlib — this module (like the whole Level-1 linter) must be
importable without JAX so `scripts/trace_lint.py` can run the AST pass
in milliseconds on a box with no accelerator stack warmed up.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

# Rules the AST/twin passes can emit (suppressible via pragma).
RULES: Dict[str, str] = {
    "unroll-bomb": (
        "Python for/while loop in traced code whose trip count derives "
        "from a Config field, array shape, or runtime value — unrolls "
        "into the jaxpr and multiplies compile time"),
    "traced-coercion": (
        "int()/float()/bool()/.item()/np.* applied to a value computed "
        "by traced ops — concretizes a tracer (ConcretizationTypeError "
        "at best, silent host sync at worst)"),
    "traced-format": (
        "f-string/str()/.format() over a traced value — formats the "
        "tracer repr, not the runtime value"),
    "config-fork": (
        "branch on a Config attribute inside a traced function — every "
        "config value traces a distinct program (per-config "
        "program-shape fork); hoist the fork to build time"),
    "twin-drift": (
        "a host_* twin's signature or constant set diverged from its "
        "device counterpart — the bit-parity contract is stale"),
}

# Errors the engine itself emits (NOT suppressible — a pragma problem
# cannot be pragma'd away).
ENGINE_RULES: Dict[str, str] = {
    "unused-pragma": "a trace-lint pragma that suppressed nothing",
    "pragma-missing-reason": "allow(<rule>) without a ': reason' string",
    "unknown-rule": "allow(<rule>) naming a rule the linter doesn't have",
}

#: the pragma shape: ``trace-lint: allow(<rule>): reason text``
PRAGMA_RE = re.compile(
    r"#\s*trace-lint:\s*allow\(([\w-]+)\)\s*(?::\s*(\S.*?))?\s*$")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Pragma:
    rule: str
    line: int
    reason: Optional[str]
    used: bool = field(default=False)


def parse_pragmas(src: str, path: str):
    """-> (pragmas, engine findings for malformed ones).

    A pragma suppresses findings of its rule on its OWN line or the
    line directly BELOW it (so it can trail the flagged statement or
    sit on its own line above a long one).
    """
    pragmas: List[Pragma] = []
    findings: List[Finding] = []
    for i, text in enumerate(src.splitlines(), start=1):
        m = PRAGMA_RE.search(text)
        if not m:
            continue
        rule, reason = m.group(1), m.group(2)
        if rule not in RULES:
            findings.append(Finding(
                "unknown-rule", path, i,
                f"allow({rule}) names no rule; known: "
                + ", ".join(sorted(RULES))))
            continue
        if not reason:
            findings.append(Finding(
                "pragma-missing-reason", path, i,
                f"allow({rule}) needs ': <reason>' — an unexplained "
                f"suppression is indistinguishable from a stale one"))
        pragmas.append(Pragma(rule, i, reason))
    return pragmas, findings


def apply_pragmas(findings: List[Finding], pragmas: List[Pragma],
                  path: str) -> List[Finding]:
    """Drop suppressed findings, then turn every still-unused pragma
    into an ``unused-pragma`` finding (a suppression that suppresses
    nothing is stale by definition and must be deleted, not kept)."""
    by_line: Dict[int, List[Pragma]] = {}
    for p in pragmas:
        by_line.setdefault(p.line, []).append(p)
    kept: List[Finding] = []
    for f in findings:
        hit = None
        for cand_line in (f.line, f.line - 1):
            for p in by_line.get(cand_line, ()):
                if p.rule == f.rule:
                    hit = p
                    break
            if hit:
                break
        if hit is not None:
            hit.used = True
        else:
            kept.append(f)
    for p in pragmas:
        if not p.used:
            kept.append(Finding(
                "unused-pragma", path, p.line,
                f"allow({p.rule}) suppressed nothing — delete it (or "
                f"the hazard it excused moved)"))
    return kept


def format_report(findings: List[Finding]) -> str:
    if not findings:
        return "trace-lint: clean (0 findings)"
    lines = [str(f) for f in
             sorted(findings, key=lambda f: (f.path, f.line, f.rule))]
    lines.append(f"trace-lint: {len(findings)} finding(s)")
    return "\n".join(lines)
