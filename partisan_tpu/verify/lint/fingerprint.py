"""Trace-lint Level 2: lower-only program fingerprints.

``mesh.assert_collective_budget`` pins collective counts, but only on a
COMPILED executable — and XLA compile time is the binding constraint
(ROADMAP "kill the compile wall": the explorer checker compiles ~13 min
cold).  This module generalizes the budget into a static CI gate that
never invokes XLA: each flagship entrypoint is ``.trace()``d and
``.lower()``d at a small canonical shape, and three structural metrics
are recorded:

* ``eqns`` — total jaxpr equation count (recursive through sub-jaxprs:
  a top-level shard_map/scan wraps everything in one equation);
* ``collectives`` — per-kind counts of explicit ``stablehlo.*``
  collective ops in the lowered StableHLO text (the explicit-SPMD
  dataplane's all_to_all/all_reduce are visible pre-compile);
* ``text_bytes`` — lowered-text size (informational; tracks HLO bloat).

``check()`` diffs against the committed golden ``LINT_fingerprints.json``
and fails on ANY collective-count change or >10% eqn growth — the two
regressions that respectively break the collective budget and feed the
compile wall.  Shrinkage and text-size drift are reported but pass;
re-bless with ``scripts/trace_lint.py --bless`` after an intended
program change.

Importing this module imports JAX (unlike the Level-1 engine); callers
must set ``JAX_PLATFORMS=cpu`` + the 8-device host-platform flag first
(scripts/trace_lint.py and tests/conftest.py both do).
"""

from __future__ import annotations

import json
import re
from collections import Counter
from typing import Callable, Dict, List, Optional, Tuple

GOLDEN_BASENAME = "LINT_fingerprints.json"

#: allowed relative eqn-count growth before check() fails
EQN_GROWTH_LIMIT = 0.10

_COLLECTIVE_RE = re.compile(
    r"\bstablehlo\.(all_to_all|all_reduce|all_gather|collective_permute"
    r"|reduce_scatter|collective_broadcast)\b")


# ------------------------------------------------------------ measurement

def _eqn_count(jaxpr) -> int:
    """Total equations including every nested sub-jaxpr (scan/cond/
    shard_map bodies live in eqn params, so the top level alone is ~1)."""
    inner = getattr(jaxpr, "jaxpr", None)   # ClosedJaxpr -> Jaxpr
    if inner is not None:
        jaxpr = inner
    n = len(jaxpr.eqns)
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (tuple, list)) else (v,)):
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    n += _eqn_count(sub)
    return n


def fingerprint_one(build: Callable[[], Tuple[Callable, tuple]]
                    ) -> Dict[str, object]:
    """Trace + lower ONE entrypoint (no XLA compile) and measure it."""
    fn, args = build()
    traced = fn.trace(*args)
    text = traced.lower().as_text()
    colls = Counter(m.group(1).replace("_", "-")
                    for m in _COLLECTIVE_RE.finditer(text))
    return {
        "eqns": _eqn_count(traced.jaxpr),
        "text_bytes": len(text),
        "collectives": dict(sorted(colls.items())),
    }


# --------------------------------------------------- flagship entrypoints
#
# Shapes deliberately mirror the test suite's (test_dense_dataplane /
# test_control / test_explorer module constants) so any session that has
# run tier-1 shares its warm persistent cache with nothing — lowering
# needs no cache — but the PROGRAMS fingerprinted are the ones CI
# actually exercises.

def _cfg16():
    from partisan_tpu.config import Config
    return Config(n_nodes=16, inbox_cap=16, seed=3, slo_deadline_rounds=8,
                  shed_token_burst_milli=8000)


def _control_spec():
    from partisan_tpu.control import ControlSpec, Controller
    return ControlSpec((
        Controller(name="admit", metric="rpc_slo_violated",
                   actuator="wl.shed_rate_milli", kind="aimd",
                   init=4000, target_milli=0, sense=1, delta=True,
                   alpha_milli=400, add=200, mult_milli=900,
                   lo=1000, hi=8000),
    ))


def _control_proto(cfg):
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.models.stack import Lifted, Stacked
    from partisan_tpu.workload import arrivals
    from partisan_tpu.workload.driver import AdaptiveWorkloadRpc
    drv = AdaptiveWorkloadRpc(
        cfg, promise_cap=8,
        spec=arrivals.ArrivalSpec(kind=arrivals.POISSON, max_issue=4),
        rate_milli=6000, shed_rate_milli=4000)
    return Stacked(HyParView(cfg), Lifted(drv))


def _engine_step_hyparview():
    import partisan_tpu as pt
    from partisan_tpu.models.hyparview import HyParView
    cfg = pt.Config(n_nodes=64, inbox_cap=16, shuffle_interval=5, seed=3)
    proto = HyParView(cfg)
    world = pt.init_world(cfg, proto)
    return pt.make_step(cfg, proto, donate=False), (world,)


def _engine_step_control():
    import partisan_tpu as pt
    from partisan_tpu.control import attach_plane
    cfg = _cfg16()
    proto, spec = _control_proto(cfg), _control_spec()
    world = attach_plane(pt.init_world(cfg, proto), spec)
    return pt.make_step(cfg, proto, donate=False, control=spec), (world,)


def _sharded_dataplane_round():
    import partisan_tpu as pt
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.parallel.dataplane import (init_sharded_world,
                                                 make_sharded_step)
    from partisan_tpu.parallel.mesh import make_mesh
    cfg = pt.Config(n_nodes=64, inbox_cap=16, shuffle_interval=5, seed=3)
    proto = HyParView(cfg)
    mesh = make_mesh(n_devices=8)
    world = init_sharded_world(cfg, proto, mesh)
    return make_sharded_step(cfg, proto, mesh, donate=False), (world,)


def _dense(model: str):
    import partisan_tpu as pt
    from partisan_tpu.parallel import dense_dataplane as dd
    from partisan_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(n_devices=8)
    if model == "scamp":
        cfg = pt.Config(n_nodes=256)
        st = dd.sharded_scamp_init(cfg, 8)
        step = dd.make_sharded_dense_round(cfg, mesh, model="scamp")
    else:
        cfg = pt.Config(n_nodes=256, shuffle_interval=4,
                        random_promotion_interval=2)
        if model == "plumtree":
            st = dd.sharded_pt_init(cfg, 8)
            step = dd.make_sharded_dense_round(cfg, mesh, model="plumtree",
                                               broadcast_interval=5)
        else:
            st = dd.sharded_dense_init(cfg, 8)
            step = dd.make_sharded_dense_round(cfg, mesh)
    return step, (dd.place_sharded(st, mesh),)


def _dense_hv_control():
    import partisan_tpu as pt
    from partisan_tpu.control import ControlSpec, Controller
    from partisan_tpu.parallel import dense_dataplane as dd
    from partisan_tpu.parallel.mesh import make_mesh
    cfg = pt.Config(n_nodes=256, shuffle_interval=4,
                    random_promotion_interval=2)
    spec = ControlSpec((
        Controller(name="cadence", metric="lonely",
                   actuator="dense.shuffle_interval", kind="step",
                   init=4, target_milli=0, sense=-1, delta=False,
                   alpha_milli=600, step=1, deadband_milli=200,
                   lo=1, hi=16),
    ))
    mesh = make_mesh(n_devices=8)
    step = dd.make_sharded_dense_round(cfg, mesh, control=spec)
    st = dd.place_sharded(dd.sharded_dense_init(cfg, 8), mesh)
    return step, (st, spec.init_plane())


def _engine_step_tracer():
    import partisan_tpu as pt
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.telemetry import tracer
    cfg = pt.Config(n_nodes=64, inbox_cap=16, shuffle_interval=5, seed=3)
    proto = HyParView(cfg)
    spec = tracer.TraceSpec(window=16, cap=256)
    world = pt.init_world(cfg, proto)
    tring = tracer.make_trace_ring(spec)
    return pt.make_step(cfg, proto, donate=False, trace=spec), (world, tring)


def _sharded_dataplane_tracer():
    import partisan_tpu as pt
    from partisan_tpu.models.hyparview import HyParView
    from partisan_tpu.parallel.dataplane import (init_sharded_world,
                                                 make_sharded_step)
    from partisan_tpu.parallel.mesh import make_mesh
    from partisan_tpu.telemetry import tracer
    cfg = pt.Config(n_nodes=64, inbox_cap=16, shuffle_interval=5, seed=3)
    proto = HyParView(cfg)
    spec = tracer.TraceSpec(window=16, cap=256)
    mesh = make_mesh(n_devices=8)
    world = init_sharded_world(cfg, proto, mesh)
    tring = tracer.place_trace_ring(
        tracer.make_trace_ring(spec, n_shards=8), mesh)
    return (make_sharded_step(cfg, proto, mesh, donate=False, trace=spec),
            (world, tring))


def _explorer_checker_b1():
    import partisan_tpu as pt
    from partisan_tpu.verify.chaos import ChaosSchedule
    from partisan_tpu.verify.explorer import SETUPS, Explorer
    cfg = pt.Config(n_nodes=16, inbox_cap=16, shuffle_interval=5, seed=3)
    proto, world = SETUPS["hyparview_tree"](cfg)
    ex = Explorer(cfg, proto, n_rounds=60, n_events=10, batch=1,
                  world=world, heal_margin=12)
    sched = ChaosSchedule().crash(8, (4, 7)).recover(32, (4, 7))
    worldB, tables, check = ex._stack_inputs(ex._pad_batch([sched]))
    return ex._run, (worldB, tables, check)


#: name -> builder returning (jitted fn, args); each is lowered at a
#: small canonical shape mirroring the tier-1 suite's programs
FLAGSHIP: Dict[str, Callable[[], Tuple[Callable, tuple]]] = {
    "engine_step_hyparview_n64": _engine_step_hyparview,
    "engine_step_control_n16": _engine_step_control,
    "sharded_dataplane_round_n64x8": _sharded_dataplane_round,
    "dense_hyparview_n256x8": lambda: _dense("hyparview"),
    "dense_scamp_n256x8": lambda: _dense("scamp"),
    "dense_plumtree_n256x8": lambda: _dense("plumtree"),
    "dense_hyparview_control_n256x8": _dense_hv_control,
    "explorer_checker_hyparview_b1": _explorer_checker_b1,
    "engine_step_tracer_n64": _engine_step_tracer,
    "sharded_dataplane_tracer_n64x8": _sharded_dataplane_tracer,
}


# --------------------------------------------------------- bless / check

def fingerprint_all(registry: Optional[Dict] = None,
                    progress: Optional[Callable[[str], None]] = None
                    ) -> Dict[str, Dict]:
    out = {}
    for name, build in (registry or FLAGSHIP).items():
        if progress:
            progress(name)
        out[name] = fingerprint_one(build)
    return out


def bless(path: str, registry: Optional[Dict] = None,
          progress: Optional[Callable[[str], None]] = None) -> Dict:
    fps = fingerprint_all(registry, progress)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(fps, f, indent=2, sort_keys=True)
        f.write("\n")
    return fps


def check(path: str, registry: Optional[Dict] = None,
          progress: Optional[Callable[[str], None]] = None) -> List[str]:
    """-> list of failure strings (empty = gate passes).  Every failure
    names the entrypoint and the metric that moved."""
    with open(path, encoding="utf-8") as f:
        golden = json.load(f)
    registry = registry or FLAGSHIP
    errors: List[str] = []
    for name in sorted(set(golden) - set(registry)):
        errors.append(
            f"{name}: in {GOLDEN_BASENAME} but not in the FLAGSHIP "
            f"registry — remove it or restore the entrypoint, then "
            f"re-bless")
    for name, build in registry.items():
        if name not in golden:
            errors.append(
                f"{name}: flagship entrypoint has no golden fingerprint "
                f"— run scripts/trace_lint.py --bless")
            continue
        if progress:
            progress(name)
        cur, ref = fingerprint_one(build), golden[name]
        if cur["collectives"] != ref["collectives"]:
            errors.append(
                f"{name}: collective counts changed "
                f"{ref['collectives']} -> {cur['collectives']} — the "
                f"collective budget is pinned exactly; re-bless only "
                f"if the change is intended")
        growth = (cur["eqns"] - ref["eqns"]) / max(ref["eqns"], 1)
        if growth > EQN_GROWTH_LIMIT:
            errors.append(
                f"{name}: eqn count grew {ref['eqns']} -> {cur['eqns']} "
                f"(+{growth:.0%}, limit +{EQN_GROWTH_LIMIT:.0%}) — "
                f"compile-surface regression; shrink the program or "
                f"re-bless with justification")
    return errors
