"""Trace-lint: compile-surface static analysis (ISSUE 11).

Two levels:

* **Level 1** (:mod:`.engine`, :mod:`.rules`, :mod:`.twins`) — a pure
  stdlib AST walk over every module in ``partisan_tpu/`` flagging
  tracing hazards in jit-reachable code.  Importing these modules does
  NOT import JAX; ``scripts/trace_lint.py`` runs them in milliseconds.
* **Level 2** (:mod:`.fingerprint`) — lower-only program fingerprints
  of the flagship entrypoints (jaxpr eqn counts, StableHLO collective
  counts, lowered-text size) diffed against the committed golden
  ``LINT_fingerprints.json``.  Importing it DOES import JAX, so it is
  deliberately not re-exported here.

See README "Static analysis & compile-surface lint".
"""

from .engine import lint_paths, lint_source, lint_tree  # noqa: F401
from .report import (ENGINE_RULES, Finding, RULES,  # noqa: F401
                     format_report)
