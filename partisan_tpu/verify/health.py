"""The in-scan health plane (ISSUE 4) — self-healing monitors recorded
into the PR-1 metrics ring.

``metrics.connectivity`` is the faithful check (all-pairs reachability,
the digraph membership check of test/partisan_SUITE.erl:2044-2109) but
costs O(N^2 log N) — a health PROBE, not an every-round-in-scan cost.
This module provides the scan-speed proxies a chaos soak needs to watch
an overlay break and re-knit:

  * :func:`reach_fraction` — bounded frontier BFS over the padded views
    (the dense models' ``bounded_bfs`` expansion shape: per hop one
    scatter along each view edge and one gather back, O(hops * N * C)),
    from the first alive node.  ``1.0`` PROVES the alive subgraph is
    connected (undirected closure); ``< 1.0`` means disconnected OR
    diameter > hops — conservative in exactly the direction a
    convergence assertion needs.
  * :func:`view_fill` — mean occupied view-slot fraction over alive
    rows (the view-starvation signal; HyParView health is "views full",
    hyparview_membership_check).
  * ``isolated`` / ``inflight`` ride the existing registry metrics; the
    inflight WATERMARK is a host fold over flushed rows
    (:func:`inflight_watermark`) — a running max has no business
    costing ring state.

:func:`health_registry` appends the health, chaos-plane and QoS-ring
metric specs to the default registry so
``telemetry.run_with_telemetry(registry=health_registry(), ...)``
records the whole plane with the standard one-transfer-per-window ring;
``telemetry.runner.collect_round_metrics`` wires the collectors (and
the ``ProtocolBase.health_counters`` tap that surfaces the qos ack-ring
overflow / dead-letter counters) whenever these names are present.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry.registry import (COUNTER, GAUGE, MetricRegistry,
                                  MetricSpec, default_registry)

# Gauges, not counters: the chaos metrics are per-round counts (counter
# semantics) but the qos-ring taps are CUMULATIVE device counters — a
# Prometheus sink accumulates counter rows as deltas, which would
# double-count a cumulative series, so cumulative taps export as gauges.
HEALTH_SPECS = (
    MetricSpec("health_reach_frac", GAUGE,
               "Fraction of alive nodes reached from the first alive "
               "root by the bounded frontier BFS over the padded views "
               "(1.0 proves the alive overlay is connected)."),
    MetricSpec("health_view_fill", GAUGE,
               "Mean occupied view-slot fraction over alive nodes."),
)

CHAOS_SPECS = (
    MetricSpec("chaos_dropped", COUNTER,
               "Messages dropped by chaos-plane drop events this round."),
    MetricSpec("chaos_delayed", COUNTER,
               "Messages re-held by chaos-plane delay events this round."),
    MetricSpec("chaos_duplicated", COUNTER,
               "Duplicate copies injected by chaos-plane events this "
               "round."),
    # the Byzantine alphabet (ISSUE 19) — emitted only when the compiled
    # schedule carries Byzantine events (verify.chaos.counter_keys), a
    # registry no-op otherwise
    MetricSpec("chaos_equivocated", COUNTER,
               "Messages payload-split by chaos-plane equivocate events "
               "this round (odd-numbered receivers got the variant)."),
    MetricSpec("chaos_forged", COUNTER,
               "Messages injected by chaos-plane forge events this round "
               "(claimed senders never sent them)."),
    MetricSpec("chaos_replayed", COUNTER,
               "Delivered messages recorded for re-delivery by "
               "chaos-plane replay events this round."),
    MetricSpec("chaos_corrupted", COUNTER,
               "Messages payload-mutated in flight by chaos-plane "
               "corrupt events this round."),
)

QOS_SPECS = (
    MetricSpec("ack_outstanding", GAUGE,
               "Unacked slots across all outstanding rings."),
    MetricSpec("ack_send_dropped", GAUGE,
               "Cumulative sends lost to a full outstanding ring."),
    MetricSpec("ack_dead_lettered", GAUGE,
               "Cumulative slots abandoned by retransmission give-up "
               "(backoff max_attempts exhausted)."),
    MetricSpec("fwd_send_dropped", GAUGE,
               "Cumulative DataPlane acked sends lost to a full ring."),
    MetricSpec("fwd_dead_lettered", GAUGE,
               "Cumulative DataPlane outstanding slots dead-lettered by "
               "retransmission give-up."),
    MetricSpec("relay_expired", GAUGE,
               "Cumulative relays dropped at TTL 0 / no next hop."),
    MetricSpec("rpc_call_dropped", GAUGE,
               "Cumulative RPC calls lost to a full promise ring "
               "(qos/rpc.py call_dropped — the ack-ring-overflow "
               "treatment, ISSUE 8 satellite: counted AND read)."),
)

# The workload / SLO plane (ISSUE 8): all cumulative device counters
# (GAUGE kind per the rule above) except wl_outstanding, a true gauge.
WORKLOAD_SPECS = (
    MetricSpec("wl_issued", GAUGE,
               "Cumulative workload requests issued (admitted and "
               "promise-ring-allocated)."),
    MetricSpec("wl_shed", GAUGE,
               "Cumulative requests refused by admission control "
               "(token bucket / outstanding cap, workload/shed.py)."),
    MetricSpec("wl_retries", GAUGE,
               "Cumulative workload rpc_req retransmissions."),
    MetricSpec("wl_dead_lettered", GAUGE,
               "Cumulative workload promises abandoned at the "
               "retransmission give-up threshold."),
    MetricSpec("wl_outstanding", GAUGE,
               "Requests currently in flight across all promise rings."),
    MetricSpec("rpc_slo_ok", GAUGE,
               "Cumulative completions within slo_deadline_rounds."),
    MetricSpec("rpc_slo_violated", GAUGE,
               "Cumulative completions past slo_deadline_rounds."),
    MetricSpec("otp_slo_ok", GAUGE,
               "Cumulative gen_server replies within the deadline."),
    MetricSpec("otp_slo_violated", GAUGE,
               "Cumulative gen_server replies past the deadline."),
    MetricSpec("otp_timed_out", GAUGE,
               "Currently timed-out gen_server call slots."),
)


def health_registry(extra: Sequence[MetricSpec] = (),
                    disabled: Optional[Iterable[str]] = None
                    ) -> MetricRegistry:
    """The default registry + health + chaos + qos specs (the chaos
    soak's ring layout).  ``disabled`` behaves like
    ``default_registry``'s (None keeps the default off-set)."""
    reg = default_registry(disabled)
    return reg.with_specs(HEALTH_SPECS + CHAOS_SPECS + QOS_SPECS
                          + tuple(extra))


def workload_registry(extra: Sequence[MetricSpec] = (),
                      disabled: Optional[Iterable[str]] = None
                      ) -> MetricRegistry:
    """health_registry + the workload/SLO counters and the rpc latency
    histogram family — the ring layout of the load suite and the chaos
    soak's workload arm."""
    from ..workload import latency as _latency
    return health_registry(
        WORKLOAD_SPECS + _latency.latency_specs(
            "rpc_latency",
            "RPC request completion latency (rounds).") + tuple(extra),
        disabled)


def default_hops(n: int) -> int:
    """Default BFS hop budget: gossip overlays have O(log N) diameter;
    2*log2 + 4 covers the post-heal re-knit transient without paying a
    diameter-N worst case every round."""
    return int(2 * np.ceil(np.log2(max(n, 2)))) + 4


def reach_mask(views: jax.Array, alive: jax.Array,
               hops: Optional[int] = None,
               partition: Optional[jax.Array] = None) -> jax.Array:
    """[N] bool — alive nodes reached from the first alive node within
    ``hops`` frontier expansions of the UNDIRECTED view graph.  Each hop
    is one scatter (row -> its view members) plus one gather (row <- any
    reached member), so cost is O(hops * N * C) — in-scan safe, no
    [N, N] adjacency ever materializes.  ``partition`` (the world's
    fault-plane vector) additionally severs cross-partition edges, so a
    standing partition reads as disconnected even while stale views
    still list peers across the boundary — EFFECTIVE connectivity, the
    signal a chaos soak watches."""
    n, c = views.shape
    hops = default_hops(n) if hops is None else hops
    vc = jnp.clip(views, 0, n - 1)
    vok = (views >= 0) & alive[:, None] & alive[vc]
    if partition is not None:
        vok = vok & (partition[:, None] == partition[vc])
    root = jnp.argmax(alive)          # first alive node (0 if none)
    reached0 = (jnp.arange(n) == root) & alive

    def body(_, reached):
        fwd = jnp.zeros((n,), bool).at[vc].max(reached[:, None] & vok)
        rev = jnp.any(reached[vc] & vok, axis=1)
        return (reached | fwd | rev) & alive

    return jax.lax.fori_loop(0, hops, body, reached0)


def reach_fraction(views: jax.Array, alive: jax.Array,
                   hops: Optional[int] = None,
                   partition: Optional[jax.Array] = None) -> jax.Array:
    """Scalar float32 in [0, 1]; 1.0 proves connectivity of the alive
    subgraph (sufficient, not necessary, when diameter > hops)."""
    reached = reach_mask(views, alive, hops, partition)
    return (jnp.sum(reached) / jnp.maximum(jnp.sum(alive), 1)
            ).astype(jnp.float32)


def view_fill(views: jax.Array, alive: jax.Array) -> jax.Array:
    """Scalar float32 — mean occupied view-slot fraction over alive
    rows (0 when nobody is alive)."""
    frac = jnp.sum(views >= 0, axis=1) / views.shape[1]
    return (jnp.sum(jnp.where(alive, frac, 0.0))
            / jnp.maximum(jnp.sum(alive), 1)).astype(jnp.float32)


def collect_health_views(views: jax.Array, alive: jax.Array,
                         hops: Optional[int] = None,
                         partition: Optional[jax.Array] = None
                         ) -> Dict[str, jax.Array]:
    """The device-side health collectors keyed by registry names (the
    runner calls this when ``health_reach_frac`` is in the registry)."""
    return {
        "health_reach_frac": reach_fraction(views, alive, hops,
                                            partition),
        "health_view_fill": view_fill(views, alive),
    }


# ------------------------------------------------------------ host folds

def inflight_watermark(rows: Sequence[Dict[str, float]]) -> float:
    """Host fold over flushed ring rows: the in-flight buffer occupancy
    high-water mark (the queue-depth instrumentation analog, pluggable
    :875-879, folded instead of carried)."""
    return max((r.get("inflight", 0.0) for r in rows), default=0.0)


def converged_round(rows: Sequence[Dict[str, float]], after: int,
                    key: str = "health_reach_frac") -> Optional[int]:
    """First round > ``after`` from which ``key`` stays 1.0 through the
    END of the recorded rows (a momentary reconnect that re-splits does
    not count).  None if never."""
    cand: Optional[int] = None
    for r in rows:
        rnd = int(r.get("round", -1))
        if rnd <= after:
            continue
        if r.get(key, 0.0) >= 1.0:
            if cand is None:
                cand = rnd
        else:
            cand = None
    return cand
