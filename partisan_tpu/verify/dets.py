"""Reference trace-file interop — import traces recorded by the Erlang
implementation (``src/partisan_trace_file.erl:26-65``) so that a schedule
found by one checker can drive the other.

The reference persists traces with ``dets``: a table holding
``{num_keys, K}`` plus numbered records ``{N, Entry}`` for N in 1..K
(partisan_trace_file.erl:49-65), where each ``Entry`` is one of the trace
orchestrator's line shapes (partisan_trace_orchestrator.erl:134-150,
509-540):

    {pre_interposition_fun, {TracingNode, InterpositionType, OriginNode,
                             MessagePayload}}
        InterpositionType = forward_message: TracingNode is the SENDER and
        OriginNode the destination (the pre fun fires on the send path,
        partisan_pluggable_peer_service_manager.erl:560-583);
        receive_message: TracingNode is the RECEIVER, OriginNode the sender.
    {enter_command, ...} / {exit_command, ...}
        harness bookkeeping — imported but not mapped to wire entries.

The on-disk container is a dets v9 file: a hash table whose objects are
``term_to_binary`` blobs embedded in slot structures.  This reader does
NOT reimplement the dets hash layout (it is an OTP-internal format that
has drifted across releases); it *carves* the external-term-format blobs
out of the raw bytes — every stored object begins with the ETF version
magic 131, and a trace file is written once, append-only (the writer rms
any existing file first, partisan_trace_file.erl:56-60), so carving
recovers exactly the inserted objects.  The numbered-record scheme then
reorders and validates them: we require num_keys and the full 1..K range,
so a partial carve fails loudly instead of yielding a silently truncated
schedule.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from ..bridge import etf
from ..bridge.etf import Atom
from .trace import TraceEntry


@dataclasses.dataclass(frozen=True)
class RefTraceLine:
    """One decoded reference trace line, pre-mapping."""
    kind: str                 # pre_interposition_fun | enter_command | ...
    tracing_node: Optional[str] = None
    interposition_type: Optional[str] = None   # forward_message | receive_message
    origin_node: Optional[str] = None
    payload: Any = None       # the protocol message term

    @property
    def payload_head(self) -> Optional[str]:
        """The message-type atom the checker keys schedules on (the head
        of the payload tuple, e.g. ``forward_message`` / ``prepare``)."""
        p = self.payload
        if isinstance(p, tuple) and p and isinstance(p[0], Atom):
            return str(p[0])
        if isinstance(p, Atom):
            return str(p)
        return None


def carve_terms(data: bytes) -> List[Any]:
    """Extract every decodable external-term-format blob from raw bytes.

    dets object slots frame each blob with internal size/status words; we
    skip straight to the 131 magic and let the ETF grammar bound each
    term.  False positives (a 131 byte inside another blob's payload)
    decode as garbage terms that the numbered-record validation below
    rejects; overlapping matches are avoided by resuming the scan after
    each successful decode.
    """
    out: List[Any] = []
    i = 0
    n = len(data)
    while i < n:
        j = data.find(b"\x83", i)
        if j < 0:
            break
        try:
            term, used = etf.decode_prefix(data[j:])
        except Exception:  # noqa: BLE001 — not a real term boundary
            i = j + 1
            continue
        out.append(term)
        i = j + used
    return out


def parse_ref_trace(data: bytes) -> List[RefTraceLine]:
    """Decode a reference dets trace file's bytes into ordered lines.

    Validates the numbered-record contract of partisan_trace_file:write/2:
    a ``{num_keys, K}`` record and exactly one ``{N, Entry}`` for each
    N in 1..K.
    """
    records: Dict[int, Any] = {}
    num_keys: Optional[int] = None
    for term in carve_terms(data):
        if not (isinstance(term, tuple) and len(term) == 2):
            continue
        k, v = term
        if k == Atom("num_keys") and isinstance(v, int):
            num_keys = v
        elif isinstance(k, int) and not isinstance(k, bool) and k >= 1:
            records[k] = v
    if num_keys is None:
        raise ValueError("no num_keys record — not a partisan trace file")
    missing = [n for n in range(1, num_keys + 1) if n not in records]
    if missing:
        raise ValueError(
            f"trace carve incomplete: missing records {missing[:8]} "
            f"of 1..{num_keys}")
    lines = []
    for n in range(1, num_keys + 1):
        lines.append(_parse_line(records[n]))
    return lines


def _parse_line(entry: Any) -> RefTraceLine:
    if (isinstance(entry, tuple) and len(entry) == 2
            and entry[0] == Atom("pre_interposition_fun")
            and isinstance(entry[1], tuple) and len(entry[1]) == 4):
        node, itype, origin, payload = entry[1]
        return RefTraceLine(
            kind="pre_interposition_fun",
            tracing_node=str(node),
            interposition_type=str(itype),
            origin_node=str(origin),
            payload=payload)
    head = entry[0] if isinstance(entry, tuple) and entry else entry
    return RefTraceLine(kind=str(head), payload=entry)


def ref_trace_to_entries(
        lines: List[RefTraceLine],
        node_ids: Mapping[str, int],
        typ_of: Mapping[str, int]) -> List[TraceEntry]:
    """Map reference pre_interposition lines onto :class:`TraceEntry`.

    ``node_ids`` maps Erlang node names to virtual node ids (the port
    bridge's integer-id table, SURVEY §5.6); ``typ_of`` maps payload-head
    atoms to this engine's wire tags (``proto.typ``).  Only
    forward_message lines become entries — they are the send events the
    reference's model checker enumerates omissions over
    (test/filibuster_SUITE.erl:697-930); receive_message lines duplicate
    them one hop later and harness bookkeeping lines carry no wire
    identity.  The reference is asynchronous so lines carry no round;
    imported entries use rnd = -1 ("any round") and schedule matching
    falls back to (src, dst, typ) — see :func:`imported_schedule_filter`.
    Unknown nodes or payload heads raise: a schedule that silently maps
    to nothing would "pass" vacuously.
    """
    out: List[TraceEntry] = []
    for ln in lines:
        if ln.kind != "pre_interposition_fun":
            continue
        if ln.interposition_type != "forward_message":
            continue
        if ln.tracing_node not in node_ids:
            raise KeyError(f"unknown node {ln.tracing_node!r}")
        if ln.origin_node not in node_ids:
            raise KeyError(f"unknown node {ln.origin_node!r}")
        head = ln.payload_head
        if head is None or head not in typ_of:
            raise KeyError(f"unmapped message type {head!r}")
        out.append(TraceEntry(
            rnd=-1,
            src=node_ids[ln.tracing_node],
            dst=node_ids[ln.origin_node],
            typ=typ_of[head],
            channel=0,
            hash=zlib.crc32(etf.encode(ln.payload)) & 0x7FFFFFFF))
    return out


def imported_schedule_filter(entries: List[TraceEntry]
                             ) -> Callable[[Tuple[int, int, int, int]], bool]:
    """A ModelChecker ``candidate_filter`` that restricts omission
    candidates to the (src, dst, typ) identities of an imported reference
    schedule — the round-agnostic match that replays an asynchronous
    reference schedule against the round-synchronous engine."""
    keys = {(e.src, e.dst, e.typ) for e in entries}
    return lambda k: (k[1], k[2], k[3]) in keys


# --------------------------------------------------------------- test aid

def synthesize_dets_bytes(lines: List[Any]) -> bytes:
    """Build bytes with the dets object framing the carver sees: each
    ``{N, Entry}`` record as a size/status-framed ``term_to_binary`` blob
    after an opaque header.  This mirrors how objects sit in a real dets
    file (32-bit size + status words, then the ETF blob) WITHOUT the hash
    directory, which the reader deliberately ignores.  Used by tests; a
    trace written by an actual BEAM carves identically because carving
    keys on the ETF blobs alone.
    """
    out = bytearray()
    # opaque header: dets v9 reserves the first kilobytes for the hash
    # directory; fill with values that cannot alias the ETF magic
    out += bytes([0x00, 0x01, 0x02] * 80)
    records = [(Atom("num_keys"), len(lines))]
    records += [(n + 1, ln) for n, ln in enumerate(lines)]
    for rec in records:
        blob = etf.encode(rec)
        out += len(blob).to_bytes(4, "big")       # slot size word
        out += (0x3C5A).to_bytes(4, "big")        # status word (active)
        out += blob
    return bytes(out)
