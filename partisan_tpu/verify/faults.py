"""Fault models — the TPU rebuild of prop_partisan's crash / omission
machinery (test/prop_partisan_crash_fault_model.erl:33-37, 94-140: crash,
general/send/receive omissions implemented as interposition funs returning
``undefined``) and the delay faults (``ingress_delay``/``egress_delay``,
server :85-90, client :88-93).

Each builder returns a pure ``(Msgs, rnd) -> Msgs`` interposition fun for
:class:`verify.Interposition`; crash/partition faults act on the World's
fault plane instead (``alive`` / ``partition`` arrays, SURVEY §5.3)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import telemetry
from ..engine import World
from ..ops.msg import Msgs


def _match(m: Msgs, src, dst, typ) -> jax.Array:
    hit = m.valid
    if src is not None:
        hit = hit & (m.src == src)
    if dst is not None:
        hit = hit & (m.dst == dst)
    if typ is not None:
        hit = hit & (m.typ == typ)
    return hit


def send_omission(src: Optional[int] = None, dst: Optional[int] = None,
                  typ: Optional[int] = None,
                  rounds: Optional[Tuple[int, int]] = None):
    """Drop matching messages (interposition returning `undefined`,
    crash_fault_model :116-128).  ``rounds=(lo, hi)`` limits the fault
    window; None = always."""
    telemetry.emit_event("fault_omission_installed", src=src, dst=dst,
                         typ=typ, rounds=rounds)

    def fn(m: Msgs, rnd: jax.Array) -> Msgs:
        hit = _match(m, src, dst, typ)
        if rounds is not None:
            hit = hit & (rnd >= rounds[0]) & (rnd < rounds[1])
        return m.replace(valid=m.valid & ~hit)
    return fn


# receive omission is the same transform applied on the recv hook
# (crash_fault_model :129-140 distinguishes them only by hook site)
receive_omission = send_omission


def message_delay(extra: int, src: Optional[int] = None,
                  dst: Optional[int] = None, typ: Optional[int] = None,
                  rounds: Optional[Tuple[int, int]] = None):
    """The '$delay' interposition verb / ingress+egress delay sleeps."""
    telemetry.emit_event("fault_delay_installed", extra=extra, src=src,
                         dst=dst, typ=typ, rounds=rounds)

    def fn(m: Msgs, rnd: jax.Array) -> Msgs:
        hit = _match(m, src, dst, typ)
        if rounds is not None:
            hit = hit & (rnd >= rounds[0]) & (rnd < rounds[1])
        return m.replace(delay=jnp.where(hit, m.delay + extra, m.delay))
    return fn


def drop_schedule(schedule: Sequence[Tuple[int, int, int, int]]):
    """Drop an explicit set of (round, src, dst, typ) wire entries — the
    model checker's omission schedule (filibuster_SUITE execute_schedule
    :1264).  Duplicate quadruples drop every matching copy that round."""
    if not schedule:
        return lambda m, rnd: m
    sched = jnp.asarray(schedule, jnp.int32)  # [S, 4]

    def fn(m: Msgs, rnd: jax.Array) -> Msgs:
        hit = ((sched[:, 0][:, None] == rnd)
               & (sched[:, 1][:, None] == m.src[None, :])
               & (sched[:, 2][:, None] == m.dst[None, :])
               & (sched[:, 3][:, None] == m.typ[None, :]))
        drop = jnp.any(hit, axis=0) & m.valid
        return m.replace(valid=m.valid & ~drop)
    return fn


def drop_schedule_dynamic(slot: str = "sched"):
    """Like :func:`drop_schedule` but reads the [S, 4] (round, src, dst,
    typ) schedule from ``world.aux[slot]`` at run time — rows with
    ``round < 0`` are inert padding.  One compiled step then replays EVERY
    schedule of the model checker's enumeration (schedules are data, not
    code).  Implemented as the action-0 plane of
    :func:`fault_schedule_dynamic` (drop = the zero-delay action), so
    the schedule-matching logic lives in one place."""
    full = fault_schedule_dynamic(slot)

    def fn(m: Msgs, rnd: jax.Array, world: World) -> Msgs:
        sched = world.aux[slot]                       # [S, 4]
        sched5 = jnp.concatenate(
            [sched, jnp.zeros((sched.shape[0], 1), sched.dtype)], axis=1)
        world5 = world.replace(aux={**world.aux, slot: sched5})
        return full(m, rnd, world5)
    return fn


def fault_schedule_dynamic(slot: str = "sched"):
    """The drop/delay superset of :func:`drop_schedule_dynamic`: reads an
    [S, 5] (round, src, dst, typ, action) schedule from
    ``world.aux[slot]``.  ``action == 0`` drops the matched message
    (omission); ``action == k > 0`` bumps its ``delay`` by k rounds — the
    '$delay' interposition verb, re-held by the engine's recv split
    (engine.py collect) and delivered k rounds late.  Rows with
    ``round < 0`` are inert padding.  This is the reference's
    delivery-ORDER exploration surface
    (``partisan_trace_orchestrator.erl:160-202,476-560`` holds senders to
    force an ordering): the model checker enumerates late-message
    schedules with it, not just lost-message ones."""
    def fn(m: Msgs, rnd: jax.Array, world: World) -> Msgs:
        sched = world.aux[slot]
        active = sched[:, 0] >= 0
        hit = (active[:, None]
               & (sched[:, 0][:, None] == rnd)
               & (sched[:, 1][:, None] == m.src[None, :])
               & (sched[:, 2][:, None] == m.dst[None, :])
               & (sched[:, 3][:, None] == m.typ[None, :]))
        act = sched[:, 4]
        drop = jnp.any(hit & (act == 0)[:, None], axis=0) & m.valid
        bump = jnp.max(jnp.where(hit, act[:, None], 0), axis=0)
        return m.replace(valid=m.valid & ~drop,
                         delay=m.delay + jnp.where(drop, 0, bump))
    return fn


# ---------------------------------------------------------- world faults

def crash(world: World, nodes: Sequence[int]) -> World:
    """Crash-stop: the node neither sends nor receives from now on (the
    ct_slave stop analog; engine masks both directions)."""
    alive = world.alive
    for n in nodes:
        alive = alive.at[n].set(False)
    telemetry.emit_event("fault_crash", nodes=[int(n) for n in nodes])
    return world.replace(alive=alive)


def recover(world: World, nodes: Sequence[int]) -> World:
    alive = world.alive
    for n in nodes:
        alive = alive.at[n].set(True)
    telemetry.emit_event("fault_recover", nodes=[int(n) for n in nodes])
    return world.replace(alive=alive)


def inject_partition(world: World, groups: Sequence[Sequence[int]]) -> World:
    """Assign partition ids; cross-partition messages drop (the TTL-flood
    partition marking of hyparview :1731-1797 collapsed to its effect)."""
    part = world.partition
    for gid, members in enumerate(groups, start=1):
        for n in members:
            part = part.at[n].set(gid)
    telemetry.emit_event("fault_partition_inject",
                         groups=[[int(n) for n in g] for g in groups])
    return world.replace(partition=part)


def resolve_partition(world: World) -> World:
    telemetry.emit_event("fault_partition_resolve")
    return world.replace(partition=jnp.zeros_like(world.partition))
