"""Batched fault-space explorer (ISSUE 7 tentpole) — the TPU rebuild of
the reference's "filibuster" search loop (``test/filibuster_SUITE.erl``,
``bin/counterexample-find.sh`` / ``counterexample-replay.sh``) with the
search itself moved onto the batch axis.

The model checker (verify/model_checker.py) replays one omission
schedule per host call; scripts/chaos_soak.py runs one fault cell per
compile.  Here a fault SCENARIO is a row: B :class:`ChaosSchedule`
tables stack into one ``[B, n_events, 5]`` array, the engine round
compiles ONCE against a traced table (``engine.make_step(chaos=
DynamicSchedule(E))``), and ``vmap`` + ``lax.scan`` executes B complete
chaos'd runs in one program — hundreds of fault scenarios per scan
(lineage-driven fault injection's systematic search, at device speed).

Invariants evaluate ON DEVICE inside the scan as ``[I]`` boolean
verdicts per execution, built from the verify/health.py primitives:

  * ``convergence_after_heal`` — the partition-aware connectivity proxy
    (:func:`verify.health.reach_mask`) must be 1.0 from ``check_from``
    (last heal + margin) to the end of the run;
  * ``view_fill_floor`` — mean view occupancy over alive nodes stays
    above a floor after ``check_from`` (view starvation);
  * ``no_dead_letter_loss`` — the qos ``dead_lettered`` give-up counter
    stays zero (``qos.ack.dead_letter_total``, summed over the layer
    stack);
  * ``causal_order`` — the causal delivery frontier (``last_seq``) and
    delivered count (``log_n``) never move backwards on the acked
    protocols.

A full batch costs ONE host transfer: the ``[B, I]`` verdict bits and
first-violation rounds.  The schedule frontier comes from PR 3 flight
traces (:func:`telemetry.flight.flight_pairs` — only (src, dst, typ)
triples that actually carried traffic are perturbed) filtered through
the causality annotations' independence relation
(:func:`verify.analysis.independence_relation`), with a seeded random
fallback.  Failing schedules shrink by delta-debugging directly on the
event table — every single-removal candidate of a round re-executes in
ONE device batch — and the minimal counterexample serializes to JSON
that ``scripts/chaos_soak.py --replay`` re-executes, flight-recorder
postmortem attached.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..engine import ProtocolBase, World, init_world, make_step
from . import health
from .chaos import KIND_NAMES, ChaosSchedule, DynamicSchedule


# ------------------------------------------------------------- invariants
#
# An invariant is (name, init, update): ``init(world) -> aux`` builds the
# carried auxiliary state (previous-round snapshots for monotonicity
# checks; () when stateless) and ``update(aux, world, metrics, rnd,
# check_from) -> (aux, violated)`` returns the device bool for THIS
# round.  The explorer folds (ok, first_violation_round) generically.


@dataclasses.dataclass(frozen=True)
class Invariant:
    name: str
    init: Callable[[World], object]
    update: Callable[..., Tuple[object, jax.Array]]


def _views_of(state):
    """The padded view array ([N, C], -1 padding) of a membership layer,
    unwrapping Stacked ``lower`` chains — telemetry.runner's walk."""
    st = state
    while st is not None:
        views = getattr(st, "active", None)
        if views is None:
            views = getattr(st, "partial", None)
        if views is not None:
            return views
        st = getattr(st, "lower", None)
    return None


def _state_attr(state, name):
    """Find ``name`` anywhere in the (possibly nested) state tree:
    protocols wrap rows both linearly (Stacked ``lower`` chains) and as
    plain fields (CausalAckedRow holds its CausalRow under ``causal``),
    so descend into every dataclass-valued field, shallowest first."""
    queue = [state]
    while queue:
        st = queue.pop(0)
        if st is None:
            continue
        arr = getattr(st, name, None)
        if arr is not None:
            return arr
        for f in getattr(st, "__dataclass_fields__", {}):
            v = getattr(st, f, None)
            if hasattr(v, "__dataclass_fields__"):
                queue.append(v)
    return None


def convergence_after_heal(hops: Optional[int] = None) -> Invariant:
    """reach_fraction == 1.0 for every round >= check_from: the overlay
    re-knit after the last injected disruption and STAYED connected."""

    def update(aux, world, metrics, rnd, check_from):
        frac = health.reach_fraction(_views_of(world.state), world.alive,
                                     hops, world.partition)
        return aux, (rnd >= check_from) & (frac < 1.0)

    return Invariant("convergence_after_heal", lambda w: (), update)


def view_fill_floor(floor: float = 0.1) -> Invariant:
    """Mean occupied view fraction over alive nodes >= floor after
    check_from — the view-starvation signal."""

    def update(aux, world, metrics, rnd, check_from):
        fill = health.view_fill(_views_of(world.state), world.alive)
        return aux, (rnd >= check_from) & (fill < floor)

    return Invariant("view_fill_floor", lambda w: (), update)


def no_dead_letter_loss() -> Invariant:
    """The qos give-up counter stays zero: no acked message was ever
    abandoned at the retransmit backoff threshold.  Checked EVERY round
    (the counter is cumulative), so first_violation_round is the round
    the first slot dead-lettered."""
    from ..qos.ack import dead_letter_total

    def update(aux, world, metrics, rnd, check_from):
        return aux, dead_letter_total(world.state) > 0

    return Invariant("no_dead_letter_loss", lambda w: (), update)


def causal_order() -> Invariant:
    """The causal delivery frontier never regresses: per-receiver
    ``last_seq`` (last delivered seq per sender) and ``log_n`` (total
    delivered) are monotone round-over-round.  A violation means a
    delivery was un-delivered or the frontier moved backwards — the
    causal-order safety net on the acked/causal protocols."""

    def init(world):
        return (_state_attr(world.state, "last_seq"),
                _state_attr(world.state, "log_n"))

    def update(aux, world, metrics, rnd, check_from):
        prev_seq, prev_n = aux
        seq = _state_attr(world.state, "last_seq")
        log_n = _state_attr(world.state, "log_n")
        viol = jnp.any(seq < prev_seq) | jnp.any(log_n < prev_n)
        return (seq, log_n), viol

    return Invariant("causal_order", init, update)


def no_fork() -> Invariant:
    """Per-epoch agreement on committed blocks (ISSUE 19): wherever two
    alive nodes both committed an epoch, their ledger digests agree.
    Digests are >= 1 by construction (0 is the absent sentinel), so the
    min/max fold over present entries detects any split — the device
    twin of models.hbbft.verify_chain's 'divergent blocks' probe,
    checked EVERY round (a fork is permanent once written)."""

    def update(aux, world, metrics, rnd, check_from):
        ld = _state_attr(world.state, "ledger_digest")  # [N, E]
        present = (ld != 0) & world.alive[:, None]
        mn = jnp.min(jnp.where(present, ld, jnp.int32(2**31 - 1)), axis=0)
        mx = jnp.max(jnp.where(present, ld, jnp.int32(0)), axis=0)
        return aux, jnp.any(present.any(axis=0) & (mn != mx))

    return Invariant("no_fork", lambda w: (), update)


def no_replay_commit() -> Invariant:
    """Committed blocks are write-once: a node's ledger digest for an
    epoch never CHANGES after its first commit — replayed or forged
    sync traffic must not rewrite history.  Round-over-round
    monotonicity fold (the causal_order pattern)."""

    def init(world):
        return _state_attr(world.state, "ledger_digest")

    def update(aux, world, metrics, rnd, check_from):
        ld = _state_attr(world.state, "ledger_digest")
        viol = jnp.any((aux != 0) & (ld != aux))
        return ld, viol

    return Invariant("no_replay_commit", init, update)


def no_view_poisoning(poison: Sequence[int] = ()) -> Invariant:
    """No alive node's membership view ever contains a POISONED id — an
    id the schedule only ever injects through forged join/membership
    traffic (chaos.forge), so its presence in any view proves the forgery
    took root.  With no ``poison`` ids (or no membership view at all) the
    verdict is constant green: the factory is safe in the default set and
    forge schedules pin the ids they inject."""
    ids = tuple(int(p) for p in poison)

    def update(aux, world, metrics, rnd, check_from):
        views = _views_of(world.state)
        if views is None or not ids:
            return aux, jnp.zeros((), bool)
        bad = jnp.zeros((), bool)
        for p in ids:
            bad = bad | jnp.any((views == p) & world.alive[:, None])
        return aux, bad

    return Invariant("no_view_poisoning", lambda w: (), update)


def default_invariants(proto: ProtocolBase, world: World,
                       view_floor: float = 0.1,
                       hops: Optional[int] = None) -> List[Invariant]:
    """Pick the invariants the protocol's state actually supports (host
    inspection, once): membership layers get the connectivity pair,
    acked layers the dead-letter check, causal layers the order check."""
    inv: List[Invariant] = []
    if _views_of(world.state) is not None:
        inv.append(convergence_after_heal(hops))
        inv.append(view_fill_floor(view_floor))
    if _state_attr(world.state, "dead_lettered") is not None:
        inv.append(no_dead_letter_loss())
    if (_state_attr(world.state, "last_seq") is not None
            and _state_attr(world.state, "log_n") is not None):
        inv.append(causal_order())
    if _state_attr(world.state, "ledger_digest") is not None:
        # epoch-ledger protocols (models.hbbft): the Byzantine trio.
        # no_view_poisoning with no poison ids is constant green here —
        # listed so replayed counterexamples can name any of the three.
        inv.append(no_fork())
        inv.append(no_replay_commit())
        inv.append(no_view_poisoning())
    if not inv:
        raise ValueError(
            f"no explorer invariant applies to {type(proto).__name__} "
            f"state — pass invariants= explicitly")
    return inv


# --------------------------------------------------------------- verdicts

@dataclasses.dataclass
class BatchVerdict:
    """One host transfer's worth of answers for a batch of schedules."""
    names: Tuple[str, ...]
    ok: np.ndarray          # [B, I] bool — invariant held for the run
    first_bad: np.ndarray   # [B, I] int32 — first violation round, -1

    def failures(self) -> List[Tuple[int, str, int]]:
        """(batch index, invariant name, first violation round) rows."""
        out = []
        for b, i in zip(*np.nonzero(~self.ok)):
            out.append((int(b), self.names[i], int(self.first_bad[b, i])))
        return out

    def passed(self, b: int) -> bool:
        return bool(self.ok[b].all())


# --------------------------------------------------------------- explorer

class Explorer:
    """Compile once, search many: one vmapped scan checks a batch of
    fault schedules against device-evaluated invariants.

    ``batch`` is the compiled batch width — every ``run_batch`` call
    pads its schedule list to this width (repeating the last schedule)
    so ONE compiled program serves the whole campaign, shrinking
    included.  B=1 executions are bit-identical to the static
    ``engine.make_step(chaos=)`` path (tests/test_explorer.py pins
    states, fault planes, metrics and chaos counters on 60-round
    HyParView)."""

    def __init__(self, cfg: Config, proto: ProtocolBase, *,
                 n_rounds: int, n_events: int = 8, batch: int = 16,
                 world: Optional[World] = None,
                 invariants: Optional[Sequence[Invariant]] = None,
                 heal_margin: int = 12,
                 view_floor: float = 0.1,
                 hops: Optional[int] = None,
                 mesh=None,
                 stream=None,
                 aot: Optional[bool] = None):
        self.cfg, self.proto = cfg, proto
        self.stream = stream
        self.n_rounds, self.n_events = n_rounds, n_events
        self.batch = batch
        self.heal_margin = heal_margin
        self.world0 = world if world is not None else init_world(cfg, proto)
        self.invariants = list(invariants) if invariants is not None \
            else default_invariants(proto, self.world0, view_floor, hops)
        self.names = tuple(i.name for i in self.invariants)
        self.mesh = mesh
        self._shard = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            axis = tuple(mesh.axis_names)[0]
            self._shard = NamedSharding(mesh, PartitionSpec(axis))
        # ONE compiled step for every schedule: the table is traced
        self.step = make_step(cfg, proto, donate=False,
                              chaos=DynamicSchedule(n_events))
        # ... and ONE compiled scan for every entry point: the verdict
        # fold and the stacked per-round metrics ride the same program
        # (the metrics ys cost B * n_rounds scalars — nothing — and a
        # second lean program would double the dominant cost on this
        # engine, XLA compile time)
        #
        # ``stream`` (telemetry.observatory.StreamSpec) swaps in the
        # heartbeat variant — same fold, plus one UNORDERED io_callback
        # per round (ordered effects cannot be vmapped; the operand is
        # the unbatched scan index, so the beat fires once per round,
        # not B times).  stream=None keeps _one untouched, so the
        # flagship checker program stays byte-identical AND
        # persistently cacheable (callbacks poison the cache key).
        body = self._one if stream is None else self._one_streamed
        self._run = jax.jit(jax.vmap(body, in_axes=(0, 0, 0)))
        # ISSUE 17 cold-start hook: adopt the shipped AOT artifact of
        # the flagship checker instead of compiling (~26 min cold on
        # this box).  Adoption is HASH-GATED — the first run traces the
        # would-be program (~9 s) and adopts only on an exact lowered-
        # module match, so equal shapes with different baked-in
        # constants (another heal_margin, another view_floor) can never
        # run the wrong artifact; results stay bit-identical by
        # construction.  Default off (aot=None reads
        # PARTISAN_TPU_EXPLORER_AOT) so warm-cache suite runs never pay
        # the ~9 s trace gate; cold-start consumers opt in.
        if aot is None:
            aot = os.environ.get("PARTISAN_TPU_EXPLORER_AOT", "0") == "1"
        if aot and stream is None:
            from .. import aot as aot_mod
            run0 = self._run
            self._run = aot_mod.attach(
                "explorer_checker_hyparview_b1", run0,
                gate=lambda prog, args:
                    aot_mod._module_hash(run0, args) == prog.module_hash)

    # ----------------------------------------------------------- core scan

    def _one(self, world: World, table: jax.Array,
             check_from: jax.Array):
        """One complete chaos'd execution + in-scan invariant fold."""
        I = len(self.invariants)
        auxs = tuple(inv.init(world) for inv in self.invariants)
        ok0 = jnp.ones((I,), bool)
        fb0 = jnp.full((I,), -1, jnp.int32)

        def body(carry, _):
            w, auxs, ok, fb = carry
            w2, m = self.step(w, table)
            rnd = m["round"]
            new_auxs, viols = [], []
            for inv, aux in zip(self.invariants, auxs):
                aux2, viol = inv.update(aux, w2, m, rnd, check_from)
                new_auxs.append(aux2)
                viols.append(viol)
            viol = jnp.stack(viols)
            fb = jnp.where(ok & viol & (fb < 0), rnd, fb)
            ok = ok & ~viol
            return (w2, tuple(new_auxs), ok, fb), m

        (wf, _, ok, fb), metrics = jax.lax.scan(
            body, (world, auxs, ok0, fb0), None, length=self.n_rounds)
        return wf, ok, fb, metrics

    def _one_streamed(self, world: World, table: jax.Array,
                      check_from: jax.Array):
        """The stream-heartbeat variant of :meth:`_one` (selected in
        ``__init__`` when ``stream`` is set): the identical execution +
        invariant fold, scanned over the round index so every round
        emits one unordered host beat — the index is unbatched under
        the vmap, so the callback fires once per round, not B times."""
        from jax.experimental import io_callback
        I = len(self.invariants)
        auxs = tuple(inv.init(world) for inv in self.invariants)
        ok0 = jnp.ones((I,), bool)
        fb0 = jnp.full((I,), -1, jnp.int32)
        beat = self.stream._beat

        def body(carry, x):
            w, auxs, ok, fb = carry
            w2, m = self.step(w, table)
            rnd = m["round"]
            new_auxs, viols = [], []
            for inv, aux in zip(self.invariants, auxs):
                aux2, viol = inv.update(aux, w2, m, rnd, check_from)
                new_auxs.append(aux2)
                viols.append(viol)
            viol = jnp.stack(viols)
            fb = jnp.where(ok & viol & (fb < 0), rnd, fb)
            ok = ok & ~viol
            io_callback(beat, None, x, ordered=False)
            return (w2, tuple(new_auxs), ok, fb), m

        (wf, _, ok, fb), metrics = jax.lax.scan(
            body, (world, auxs, ok0, fb0), jnp.arange(self.n_rounds))
        return wf, ok, fb, metrics

    # --------------------------------------------------------- batch entry

    def _check_from(self, sched: ChaosSchedule) -> int:
        return max(sched.last_heal_round(), 0) + self.heal_margin

    def _pad_batch(self, schedules: Sequence[ChaosSchedule]
                   ) -> List[ChaosSchedule]:
        if len(schedules) > self.batch:
            raise ValueError(
                f"{len(schedules)} schedules > compiled batch width "
                f"{self.batch}; chunk the frontier (Explorer.explore "
                f"does)")
        pad = [schedules[-1]] * (self.batch - len(schedules))
        return list(schedules) + pad

    def _stack_inputs(self, schedules: Sequence[ChaosSchedule]):
        n_types = len(self.proto.msg_types)
        for s in schedules:
            s.validate(n_nodes=self.cfg.n_nodes, n_rounds=self.n_rounds,
                       n_types=n_types)
        tables = jnp.asarray(np.stack(
            [s.padded_table(self.n_events) for s in schedules]))
        check = jnp.asarray([self._check_from(s) for s in schedules],
                            jnp.int32)
        B = len(schedules)
        worldB = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(
                x, (B,) + jnp.shape(x)).copy(), self.world0)
        if self._shard is not None and B % self.mesh.devices.size == 0:
            tables = jax.device_put(tables, self._shard)
            check = jax.device_put(check, self._shard)
            worldB = jax.device_put(worldB, self._shard)
        return worldB, tables, check

    def run_batch(self, schedules: Sequence[ChaosSchedule]
                  ) -> BatchVerdict:
        """Execute up to ``batch`` schedules in one vmapped scan; ONE
        host transfer of verdict bits + first-violation rounds."""
        n = len(schedules)
        worldB, tables, check = self._stack_inputs(
            self._pad_batch(schedules))
        _, ok, fb, _ = self._run(worldB, tables, check)
        ok, fb = np.asarray(ok), np.asarray(fb)  # the one transfer
        if self.stream is not None:
            jax.effects_barrier()  # every heartbeat has landed
        return BatchVerdict(self.names, ok[:n], fb[:n])

    def run_batch_with_metrics(self, schedules: Sequence[ChaosSchedule]):
        """Parity variant: returns ``(final_worlds, metrics, verdict)``
        where ``metrics`` stacks the per-round metric dict to
        ``[B, n_rounds]`` per key — the B=1 bit-identity surface against
        the static chaos path.  Same compiled program as
        :meth:`run_batch`; the extra outputs are simply fetched."""
        n = len(schedules)
        worldB, tables, check = self._stack_inputs(
            self._pad_batch(schedules))
        wf, ok, fb, metrics = self._run(worldB, tables, check)
        verdict = BatchVerdict(self.names, np.asarray(ok)[:n],
                               np.asarray(fb)[:n])
        if self.stream is not None:
            jax.effects_barrier()
        return wf, metrics, verdict

    def explore(self, schedules: Sequence[ChaosSchedule],
                on_batch: Optional[Callable] = None
                ) -> List[Tuple[ChaosSchedule, str, int]]:
        """Sweep a frontier in compiled-width chunks.  Returns failing
        ``(schedule, invariant, first_violation_round)`` rows."""
        failures = []
        for i in range(0, len(schedules), self.batch):
            chunk = list(schedules[i:i + self.batch])
            verdict = self.run_batch(chunk)
            for b, name, rnd in verdict.failures():
                failures.append((chunk[b], name, rnd))
            if on_batch is not None:
                on_batch(i // self.batch, chunk, verdict)
        return failures

    # ----------------------------------------------------------- shrinking

    def _fails(self, verdict: BatchVerdict, b: int,
               invariant: str) -> bool:
        return not verdict.ok[b, self.names.index(invariant)]

    def shrink(self, sched: ChaosSchedule, invariant: str,
               max_iters: int = 64) -> ChaosSchedule:
        """Greedy delta-debugging directly on the event table: each
        round, EVERY single-event-removal candidate executes in one
        device batch (padded to the compiled width, chunked if the
        schedule has more events than the batch); the first failing
        candidate (table order — deterministic) becomes the new
        schedule.  Stops when no single removal still violates
        ``invariant``, i.e. the result is 1-minimal."""
        if invariant not in self.names:
            raise ValueError(f"unknown invariant {invariant!r}; "
                             f"have {self.names}")
        current = ChaosSchedule(tuple(sched.events))
        for _ in range(max_iters):
            events = list(current.events)
            if len(events) <= 1:
                break
            cands = [ChaosSchedule(tuple(events[:i] + events[i + 1:]))
                     for i in range(len(events))]
            chosen = None
            for lo in range(0, len(cands), self.batch):
                chunk = cands[lo:lo + self.batch]
                verdict = self.run_batch(chunk)
                for b in range(len(chunk)):
                    if self._fails(verdict, b, invariant):
                        chosen = chunk[b]
                        break
                if chosen is not None:
                    break
            if chosen is None:
                return current
            current = chosen
        return current


# --------------------------------------------------------------- frontier

def frontier_from_trace(entries, proto: Optional[ProtocolBase] = None, *,
                        n_rounds: int,
                        causality: Optional[Dict] = None,
                        target_types: Optional[Sequence[str]] = None,
                        base: Optional[ChaosSchedule] = None,
                        start: Optional[int] = None,
                        window: Optional[int] = None,
                        max_schedules: int = 64
                        ) -> List[ChaosSchedule]:
    """Generate candidate schedules from OBSERVED traffic: the flight
    recorder's (src, dst, typ) pairs (:func:`telemetry.flight.
    flight_pairs`), optionally pruned through the causality annotations
    (keep a pair only if its type is causally related to a
    ``target_types`` root or is a never-prunable state-gated timer —
    the reference's annotation pruning via
    :func:`verify.analysis.independence_relation`).  Each surviving
    pair yields a drop-window schedule on the pair, a cluster-wide
    ``drop_typ`` on its type, and a delay schedule — grafted onto
    ``base`` (e.g. a partition/heal scaffold) when given.  Pairs are
    ordered by traffic volume (then key) so the frontier is
    deterministic and truncation keeps the busiest channels."""
    from ..telemetry.flight import flight_pairs
    pairs = flight_pairs(entries)
    keep: List[Tuple[int, int, int]] = sorted(
        pairs, key=lambda k: (-pairs[k], k))
    if causality is not None and proto is not None and target_types:
        from .analysis import independence_relation
        related, relate_all = independence_relation(causality, proto)
        roots = {proto.typ(t) for t in target_types}
        keep = [k for k in keep
                if k[2] in relate_all
                or any((k[2], r) in related for r in roots)]
    start = (n_rounds // 4) if start is None else start
    window = max(n_rounds // 4, 1) if window is None else window
    base = base or ChaosSchedule()
    out: List[ChaosSchedule] = []
    seen_typ = set()
    for src, dst, typ in keep:
        if len(out) >= max_schedules:
            break
        out.append(base.drop(start, src=src, dst=dst, rounds=window))
        if typ not in seen_typ:
            seen_typ.add(typ)
            out.append(base.drop_typ(start, typ=typ, rounds=window))
        out.append(base.delay(start, src=src, dst=dst, extra=2))
    return out[:max_schedules]


def random_frontier(seed: int, n_nodes: int, n_rounds: int, *,
                    count: int = 32, n_types: int = 4,
                    base: Optional[ChaosSchedule] = None
                    ) -> List[ChaosSchedule]:
    """Seeded random fallback when no trace/annotations exist: uniform
    drop / drop_typ / delay / crash-recover perturbations over the node
    and type space, deterministic in ``seed``."""
    rng = np.random.default_rng(seed)
    base = base or ChaosSchedule()
    out: List[ChaosSchedule] = []
    horizon = max(n_rounds // 2, 2)
    for _ in range(count):
        rnd = int(rng.integers(1, horizon))
        kind = int(rng.integers(0, 4))
        if kind == 0:
            out.append(base.drop(rnd, src=int(rng.integers(0, n_nodes)),
                                 dst=int(rng.integers(0, n_nodes)),
                                 rounds=int(rng.integers(1, horizon))))
        elif kind == 1:
            out.append(base.drop_typ(rnd, typ=int(rng.integers(0, n_types)),
                                     rounds=int(rng.integers(1, horizon))))
        elif kind == 2:
            out.append(base.delay(rnd, src=int(rng.integers(0, n_nodes)),
                                  extra=int(rng.integers(1, 4))))
        else:
            lo = int(rng.integers(0, n_nodes))
            hi = min(lo + int(rng.integers(0, max(n_nodes // 8, 1))),
                     n_nodes - 1)
            out.append(base.crash(rnd, (lo, hi))
                       .recover(min(rnd + int(rng.integers(1, horizon)),
                                    n_rounds - 1), (lo, hi)))
    return out


# ------------------------------------------------ counterexample artifact
#
# A counterexample must be REPLAYABLE from the JSON alone, so it names a
# setup from this registry (protocol + initial world construction) plus
# the Config — not a pickled closure.

def _setup_hyparview_tree(cfg: Config):
    """HyParView bootstrapped over a binary-tree contact graph — the
    chaos_soak.run_cell world shape."""
    from .. import peer_service as ps
    from ..models.hyparview import HyParView
    proto = HyParView(cfg)
    world = ps.cluster(init_world(cfg, proto), proto,
                       [(i, (i - 1) // 2) for i in range(1, cfg.n_nodes)])
    return proto, world


def _setup_acked_uniform(cfg: Config):
    """AckedDelivery with every node holding one in-flight ctl_send to
    its ring successor — the dead-letter / causal-order surface."""
    from .. import peer_service as ps
    from ..qos.ack import AckedDelivery
    proto = AckedDelivery(cfg)
    world = init_world(cfg, proto)
    n = cfg.n_nodes
    for i in range(n):
        world = ps.send_ctl(world, proto, i, "ctl_send",
                            peer=(i + 1) % n, payload=100 + i)
    return proto, world


def _setup_hbbft(cfg: Config, hardened: bool):
    """HbbftWorker with every node holding one pending transaction, so
    epoch 0's leader proposes immediately — the Byzantine fork surface
    (ISSUE 19).  Replayable in both modes: ``hbbft_unhardened`` is the
    explorer's demonstration target, ``hbbft_hardened`` the survival
    twin the same schedule must NOT fork."""
    from ..models.hbbft import HbbftWorker, submit_transaction
    proto = HbbftWorker(cfg, hardened=hardened)
    world = init_world(cfg, proto)
    for i in range(cfg.n_nodes):
        world = submit_transaction(world, proto, i, 1000 + i)
    return proto, world


SETUPS: Dict[str, Callable[[Config], Tuple[ProtocolBase, World]]] = {
    "hyparview_tree": _setup_hyparview_tree,
    "acked_uniform": _setup_acked_uniform,
    "hbbft_unhardened": lambda cfg: _setup_hbbft(cfg, hardened=False),
    "hbbft_hardened": lambda cfg: _setup_hbbft(cfg, hardened=True),
}


def write_counterexample(path: str, *, setup: str, cfg: Config,
                         sched: ChaosSchedule, invariant: str,
                         first_violation_round: int, n_rounds: int,
                         heal_margin: int, n_events: int,
                         original_events: int,
                         extra: Optional[Dict] = None) -> str:
    """Serialize a (shrunk) failing schedule as the replayable artifact
    — the analog of the reference's counterexample.tar
    (bin/counterexample-find.sh)."""
    doc = {
        "kind": "chaos_counterexample",
        "setup": setup,
        "config": dataclasses.asdict(cfg),
        "n_rounds": int(n_rounds),
        "n_events": int(n_events),
        "heal_margin": int(heal_margin),
        "invariant": invariant,
        "first_violation_round": int(first_violation_round),
        "events": [list(e) for e in sched.events],
        "event_names": [
            f"{KIND_NAMES[k] if 0 <= k < len(KIND_NAMES) else k}"
            f"@{r}(a={a}, b={b}, c={c})"
            for r, k, a, b, c in sched.events],
        "original_events": int(original_events),
    }
    doc.update(extra or {})
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    return path


def read_counterexample(path: str) -> Dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("kind") != "chaos_counterexample":
        raise ValueError(f"{path}: not a chaos counterexample artifact")
    return doc


def replay_counterexample(path: str,
                          postmortem_dir: Optional[str] = None) -> Dict:
    """Rebuild the world from the artifact's named setup + Config, re-run
    the schedule through the SAME vmapped checker (B=1), and report
    whether the violation reproduces.  With ``postmortem_dir`` the
    schedule additionally re-executes on the STATIC chaos path with the
    flight recorder armed and the last window's wire trace is written —
    the counterexample-replay.sh + postmortem workflow."""
    doc = read_counterexample(path)
    raw = dict(doc["config"])
    for k, v in raw.items():
        if isinstance(v, list):
            raw[k] = tuple(v)
    cfg = Config(**raw)
    proto, world = SETUPS[doc["setup"]](cfg)
    sched = ChaosSchedule(tuple(tuple(int(x) for x in e)
                                for e in doc["events"]))
    ex = Explorer(cfg, proto, n_rounds=doc["n_rounds"],
                  n_events=doc["n_events"], batch=1, world=world,
                  heal_margin=doc["heal_margin"])
    verdict = ex.run_batch([sched])
    try:
        idx = ex.names.index(doc["invariant"])
        reproduced = not bool(verdict.ok[0, idx])
        first_bad = int(verdict.first_bad[0, idx])
    except ValueError:
        reproduced, first_bad = False, -1
    out = {"reproduced": reproduced, "invariant": doc["invariant"],
           "first_violation_round": first_bad,
           "expected_round": doc["first_violation_round"],
           "postmortem": None}
    if postmortem_dir is not None:
        out["postmortem"] = _flight_postmortem(
            cfg, proto, world, sched, doc, postmortem_dir)
    return out


def _flight_postmortem(cfg: Config, proto: ProtocolBase, world: World,
                       sched: ChaosSchedule, doc: Dict,
                       out_dir: str) -> str:
    """Re-execute on the static chaos path with the flight recorder and
    dump the last recorded window's wire trace (verify.trace format)."""
    from .. import telemetry
    from ..telemetry.flight import FlightSpec
    from . import trace as trace_mod
    n_rounds = int(doc["n_rounds"])
    window = min(32, max(n_rounds, 1))
    last = {"entries": []}

    def on_flight(entries):
        last["entries"] = entries

    telemetry.run_with_telemetry(
        cfg, proto, n_rounds, window=window, world=world,
        registry=health.health_registry(),
        flight=FlightSpec(window=window,
                          cap=int(doc.get("flight_cap", 2048))),
        on_flight=on_flight, step_kw={"chaos": sched})
    os.makedirs(out_dir, exist_ok=True)
    base = os.path.join(
        out_dir, f"counterexample_{doc['setup']}_{doc['invariant']}")
    trace_path = base + ".trace"
    trace_mod.write_trace(trace_path, last["entries"])
    return trace_path
