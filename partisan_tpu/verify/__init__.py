"""Verification harness (SURVEY §2.9, §5.1-5.3): named interposition
registry, fault models, trace record/replay, dynamic causality analysis and
the omission-schedule model checker — TPU-native rebuilds of the
interposition API (partisan_pluggable_peer_service_manager.erl:51-58),
prop_partisan's fault models, partisan_trace_orchestrator.erl and
test/filibuster_SUITE.erl."""

from .interposition import Interposition  # noqa: F401
from . import faults  # noqa: F401
from .trace import TraceRecorder, TraceEntry  # noqa: F401
from . import chaos  # noqa: F401  (ISSUE 4: compiled fault schedules)
from . import health  # noqa: F401  (ISSUE 4: in-scan health plane)
from .chaos import ChaosSchedule, DynamicSchedule  # noqa: F401
from . import explorer  # noqa: F401  (ISSUE 7: batched fault-space search)
from . import latency  # noqa: F401  (ISSUE 19: geo/WAN latency plane)
from .latency import LatencyPlane  # noqa: F401
