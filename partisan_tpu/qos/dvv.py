"""Fixed-slot sparse vector clocks — the dotted-version-vector-style
compression prototype for large actor sets (ROADMAP 8; the scaling escape
for qos/causal.py's dense ``[A]`` clocks).

The reference's clocks are orddicts over *discovered* actors
(``src/partisan_vclock.erl`` — entries exist only for actors that have
incremented), so a clock's size tracks its causal history, not the cluster
size.  The dense rebuild (qos/vclock.py) trades that for vectorization by
materializing all A counters.  This module restores the sparse shape under
fixed TPU-friendly dimensions: a clock is K slots of ``(actor, counter)``
pairs (actor −1 = empty), where K bounds the number of *distinct actors in
one causal history* — typically the handful of nodes that write to a
label, independent of cluster size.  That is exactly the compression DVVs
exploit (Preguiça et al., "Dotted Version Vectors": per-entry dots bound
growth by writers, not replicas).

Semantics match qos/vclock.py (absent actor = counter 0).  Slot exhaustion
(more than K distinct actors in one history) cannot be represented; every
op returns an ``ok`` flag the caller must surface — the same
count-don't-silence rule as the engine's fixed-shape buffers
(SURVEY §7.3).  tests/test_qos.py drives the equivalence property: any
increment/merge program over ≤ K actors yields bitwise-identical
descends/dominates/compare results to the dense clocks.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def fresh(k_slots: int) -> Tuple[jax.Array, jax.Array]:
    """The empty clock: (actors [K] int32 = −1, counters [K] int32 = 0)."""
    return (jnp.full((k_slots,), -1, jnp.int32),
            jnp.zeros((k_slots,), jnp.int32))


def counter_of(actors: jax.Array, counters: jax.Array,
               actor: jax.Array) -> jax.Array:
    """The actor's counter, 0 when absent (orddict miss default)."""
    hit = actors == actor
    return jnp.sum(jnp.where(hit, counters, 0))


def increment(actors: jax.Array, counters: jax.Array, actor: jax.Array
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """partisan_vclock:increment/2.  Returns (actors', counters', ok);
    ok = False (clock unchanged) when the actor is new and no slot is
    free, or when actor < 0 (the empty-slot sentinel — matching free
    slots by value would corrupt the clock)."""
    hit = (actors == actor) & (actor >= 0)
    present = jnp.any(hit)
    free = actors < 0
    has_free = jnp.any(free)
    slot = jnp.where(present, jnp.argmax(hit), jnp.argmax(free))
    ok = (present | has_free) & (actor >= 0)
    actors = actors.at[slot].set(jnp.where(ok, actor, actors[slot]))
    counters = counters.at[slot].add(jnp.where(ok, 1, 0))
    return actors, counters, ok


def merge(a_act: jax.Array, a_cnt: jax.Array,
          b_act: jax.Array, b_cnt: jax.Array
          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """partisan_vclock:merge/1 — pointwise max over the union of actors.
    Result lives in a's slot layout first, b's new actors appended into
    free slots.  Returns (actors, counters, ok); ok = False when the
    union needs more than K slots (result then holds a ⊔ the b-entries
    that fit — callers must treat it as poisoned)."""
    K = a_act.shape[0]
    # max b's counters into a's existing entries
    b_in_a = jax.vmap(lambda x: counter_of(b_act, b_cnt, x))(a_act)
    a_cnt = jnp.where(a_act >= 0, jnp.maximum(a_cnt, b_in_a), a_cnt)
    # append b's actors absent from a, in slot order
    is_new = (b_act >= 0) & jax.vmap(
        lambda x: ~jnp.any(a_act == x))(b_act)
    free = a_act < 0
    n_free = jnp.sum(free)
    # rank of each free slot among free slots / of each new actor among new
    new_rank = jnp.cumsum(is_new) - 1
    # target slot for new actor j: the (new_rank[j])-th free slot; entries
    # that are not new (or don't fit) scatter to index K, dropped — value
    # masking alone would leave duplicate indices in tgt (a non-new entry
    # sharing a later new entry's slot), whose write order is undefined
    free_slots = jnp.nonzero(free, size=K, fill_value=K - 1)[0]
    fits = is_new & (new_rank < n_free)
    tgt = jnp.where(fits, free_slots[jnp.clip(new_rank, 0, K - 1)], K)
    a_act = a_act.at[tgt].set(b_act, mode="drop")
    a_cnt = a_cnt.at[tgt].set(b_cnt, mode="drop")
    ok = ~jnp.any(is_new & ~fits)
    return a_act, a_cnt, ok


def descends(a_act: jax.Array, a_cnt: jax.Array,
             b_act: jax.Array, b_cnt: jax.Array) -> jax.Array:
    """partisan_vclock:descends/2 — a >= b on every actor of b."""
    a_for_b = jax.vmap(lambda x: counter_of(a_act, a_cnt, x))(b_act)
    return jnp.all(jnp.where(b_act >= 0, a_for_b >= b_cnt, True))


def dominates(a_act: jax.Array, a_cnt: jax.Array,
              b_act: jax.Array, b_cnt: jax.Array) -> jax.Array:
    """Strict descent (partisan_vclock:dominates/2)."""
    return descends(a_act, a_cnt, b_act, b_cnt) \
        & ~descends(b_act, b_cnt, a_act, a_cnt)


def equal(a_act: jax.Array, a_cnt: jax.Array,
          b_act: jax.Array, b_cnt: jax.Array) -> jax.Array:
    return descends(a_act, a_cnt, b_act, b_cnt) \
        & descends(b_act, b_cnt, a_act, a_cnt)


def to_dense(actors: jax.Array, counters: jax.Array,
             n_actors: int) -> jax.Array:
    """Expand to a qos/vclock.py dense clock (the equivalence bridge).
    Actors outside [0, n_actors) scatter-drop rather than aliasing into
    the last slot (count-don't-silence: the caller picked n_actors)."""
    dense = jnp.zeros((n_actors,), jnp.int32)
    ok = actors >= 0
    return dense.at[actors].max(jnp.where(ok, counters, 0), mode="drop")
