"""RPC over the simulated overlay — TPU-native rebuild of
``src/partisan_rpc_backend.erl``: ``call/5`` forwards
``{call, M, F, A, Timeout, {origin, Node, Self}}`` on the rpc channel
(:49-65, 120-127); the receiving side applies the function and replies
(:84-99); ``partisan_promise_backend`` is the reply store.

The TPU analog: the callable surface is a static table of pure jittable
functions (the reference dispatches to M:F — dynamic code loading has no
jit analog, so functions register at trace time); a call ships
``(ref, fn, arg)``, the server applies ``lax.switch`` over the table and
replies ``(ref, result)``; replies land in a fixed promise ring per node
(the promise backend), matched by ref.
"""

from __future__ import annotations

from typing import Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import ring
from ..ops.msg import Msgs


@struct.dataclass
class RpcRow:
    next_ref: jax.Array      # scalar — per-node monotone call ref
    prom_valid: jax.Array    # [P] promise ring (partisan_promise_backend)
    prom_ref: jax.Array      # [P]
    prom_result: jax.Array   # [P]
    prom_done: jax.Array     # [P] reply arrived
    call_dropped: jax.Array  # scalar — calls lost to a full promise ring


def init_rows(n_nodes: int, promise_cap: int = 8) -> RpcRow:
    n = n_nodes
    return RpcRow(
        next_ref=jnp.ones((n,), jnp.int32),
        prom_valid=jnp.zeros((n, promise_cap), bool),
        prom_ref=jnp.zeros((n, promise_cap), jnp.int32),
        prom_result=jnp.zeros((n, promise_cap), jnp.int32),
        prom_done=jnp.zeros((n, promise_cap), bool),
        call_dropped=jnp.zeros((n,), jnp.int32),
    )


class Rpc(ProtocolBase):
    """``ctl_call`` = partisan_rpc_backend:call (fire a request, park a
    promise); the reply fulfils the promise.  ``fns`` is the registered
    function table: int32 -> int32 pure functions."""

    msg_types = ("rpc_req", "rpc_reply", "ctl_call")

    def __init__(self, cfg: Config,
                 fns: Sequence[Callable[[jax.Array], jax.Array]] = (),
                 promise_cap: int = 8):
        self.cfg = cfg
        self.fns = tuple(fns) or (lambda x: x,)
        self.P = promise_cap
        self.data_spec: Dict = {
            "ref": ((), jnp.int32),
            "fn": ((), jnp.int32),
            "arg": ((), jnp.int32),
            "result": ((), jnp.int32),
            "peer": ((), jnp.int32),
        }
        self.emit_cap = 1
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> RpcRow:
        return init_rows(cfg.n_nodes, self.P)

    def handle_ctl_call(self, cfg, me, row: RpcRow, m: Msgs, key):
        dst, fn, arg = m.data["peer"], m.data["fn"], m.data["arg"]
        ok, slot = ring.alloc(row.prom_valid)
        ok = ok & (dst >= 0)
        ref = row.next_ref
        wr = lambda a, v: ring.masked_set(a, slot, ok, v)
        row = row.replace(
            next_ref=ref + 1,
            prom_valid=wr(row.prom_valid, True),
            prom_ref=wr(row.prom_ref, ref),
            prom_done=wr(row.prom_done, False),
            call_dropped=row.call_dropped
            + ((~ok) & (dst >= 0)).astype(jnp.int32),
        )
        em = self.emit(jnp.where(ok, dst, -1)[None], self.typ("rpc_req"),
                       ref=ref, fn=fn, arg=arg)
        return row, em

    def handle_rpc_req(self, cfg, me, row: RpcRow, m: Msgs, key):
        """Server side: apply the registered function, reply to origin
        (rpc_backend :84-99)."""
        fn = jnp.clip(m.data["fn"], 0, len(self.fns) - 1)
        result = jax.lax.switch(fn, self.fns, m.data["arg"])
        return row, self.emit(m.src[None], self.typ("rpc_reply"),
                              ref=m.data["ref"], result=result)

    def handle_rpc_reply(self, cfg, me, row: RpcRow, m: Msgs, key):
        """Fulfil the promise and free its slot for reuse (the reference's
        promise backend discards resolved promises); the done flag and
        result stay readable until the slot is reallocated."""
        hit = row.prom_valid & (row.prom_ref == m.data["ref"])
        row = row.replace(
            prom_valid=row.prom_valid & ~hit,
            prom_done=row.prom_done | hit,
            prom_result=jnp.where(hit, m.data["result"], row.prom_result))
        return row, self.no_emit()
