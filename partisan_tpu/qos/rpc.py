"""RPC over the simulated overlay — TPU-native rebuild of
``src/partisan_rpc_backend.erl``: ``call/5`` forwards
``{call, M, F, A, Timeout, {origin, Node, Self}}`` on the rpc channel
(:49-65, 120-127); the receiving side applies the function and replies
(:84-99); ``partisan_promise_backend`` is the reply store.

The TPU analog: the callable surface is a static table of pure jittable
functions (the reference dispatches to M:F — dynamic code loading has no
jit analog, so functions register at trace time); a call ships
``(ref, fn, arg)``, the server applies ``lax.switch`` over the table and
replies ``(ref, result)``; replies land in a fixed promise ring per node
(the promise backend), matched by ref.
"""

from __future__ import annotations

from typing import Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import ring
from ..ops.msg import Msgs
from ..workload import latency


@struct.dataclass
class RpcRow:
    next_ref: jax.Array      # scalar — per-node monotone call ref
    prom_valid: jax.Array    # [P] promise ring (partisan_promise_backend)
    prom_ref: jax.Array      # [P]
    prom_result: jax.Array   # [P]
    prom_done: jax.Array     # [P] reply arrived
    call_dropped: jax.Array  # scalar — calls lost to a full promise ring
    # --- workload plane (ISSUE 8): request birth + latency histogram ---
    prom_birth: jax.Array    # [P] round the call was issued
    lat_hist: jax.Array      # [K] log2-bucketed completion latencies
    lat_sum: jax.Array       # scalar — sum of observed latencies (rounds)
    slo_ok: jax.Array        # scalar — completions within the deadline
    slo_violated: jax.Array  # scalar — completions past the deadline


def init_rows(n_nodes: int, promise_cap: int = 8) -> RpcRow:
    n = n_nodes
    return RpcRow(
        next_ref=jnp.ones((n,), jnp.int32),
        prom_valid=jnp.zeros((n, promise_cap), bool),
        prom_ref=jnp.zeros((n, promise_cap), jnp.int32),
        prom_result=jnp.zeros((n, promise_cap), jnp.int32),
        prom_done=jnp.zeros((n, promise_cap), bool),
        call_dropped=jnp.zeros((n,), jnp.int32),
        prom_birth=jnp.zeros((n, promise_cap), jnp.int32),
        lat_hist=jnp.zeros((n, latency.N_BUCKETS), jnp.int32),
        lat_sum=jnp.zeros((n,), jnp.int32),
        slo_ok=jnp.zeros((n,), jnp.int32),
        slo_violated=jnp.zeros((n,), jnp.int32),
    )


class Rpc(ProtocolBase):
    """``ctl_call`` = partisan_rpc_backend:call (fire a request, park a
    promise); the reply fulfils the promise.  ``fns`` is the registered
    function table: int32 -> int32 pure functions."""

    msg_types = ("rpc_req", "rpc_reply", "ctl_call")

    def __init__(self, cfg: Config,
                 fns: Sequence[Callable[[jax.Array], jax.Array]] = (),
                 promise_cap: int = 8):
        self.cfg = cfg
        self.fns = tuple(fns) or (lambda x: x,)
        self.P = promise_cap
        self.data_spec: Dict = {
            "ref": ((), jnp.int32),
            "fn": ((), jnp.int32),
            "arg": ((), jnp.int32),
            "result": ((), jnp.int32),
            "peer": ((), jnp.int32),
        }
        self.emit_cap = 1
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> RpcRow:
        return init_rows(cfg.n_nodes, self.P)

    def handle_ctl_call(self, cfg, me, row: RpcRow, m: Msgs, key):
        dst, fn, arg = m.data["peer"], m.data["fn"], m.data["arg"]
        ok, slot = ring.alloc(row.prom_valid)
        ok = ok & (dst >= 0)
        ref = row.next_ref
        wr = lambda a, v: ring.masked_set(a, slot, ok, v)
        # birth round of the request: host injections stamp the ctl with
        # born = world.rnd, and a delay-0 ctl is delivered during the very
        # next step — whose emissions the engine stamps with that same
        # round.  So the rpc_req we emit NOW carries born == m.born, and
        # that is the birth the latency sample must be measured from.
        # Delay knobs don't apply to the loopback ctl leg.
        row = row.replace(
            next_ref=ref + 1,
            prom_valid=wr(row.prom_valid, True),
            prom_ref=wr(row.prom_ref, ref),
            prom_done=wr(row.prom_done, False),
            prom_birth=wr(row.prom_birth, m.born),
            call_dropped=row.call_dropped
            + ((~ok) & (dst >= 0)).astype(jnp.int32),
        )
        em = self.emit(jnp.where(ok, dst, -1)[None], self.typ("rpc_req"),
                       ref=ref, fn=fn, arg=arg)
        return row, em

    def handle_rpc_req(self, cfg, me, row: RpcRow, m: Msgs, key):
        """Server side: apply the registered function, reply to origin
        (rpc_backend :84-99)."""
        fn = jnp.clip(m.data["fn"], 0, len(self.fns) - 1)
        result = jax.lax.switch(fn, self.fns, m.data["arg"])
        return row, self.emit(m.src[None], self.typ("rpc_reply"),
                              ref=m.data["ref"], result=result)

    def handle_rpc_reply(self, cfg, me, row: RpcRow, m: Msgs, key):
        """Fulfil the promise and free its slot for reuse (the reference's
        promise backend discards resolved promises); the done flag and
        result stay readable until the slot is reallocated.

        Completion is also the latency observation point (ISSUE 8): the
        current round is recoverable from the reply itself — a message
        born at round r is delivered at r + 1 + delay, and the engine's
        emission-time delay is the ingress+egress sum — so
        ``now = m.born + 1 + ingress + egress`` and the sample is
        ``now - prom_birth`` at the matched slot.  Duplicate replies
        (retransmission) can't double-count: the first delivery clears
        prom_valid, so ``hit`` is empty on re-delivery.
        """
        hit = row.prom_valid & (row.prom_ref == m.data["ref"])
        got = jnp.any(hit)
        now = m.born + 1 + cfg.ingress_delay + cfg.egress_delay
        birth = jnp.sum(jnp.where(hit, row.prom_birth, 0))
        lat = jnp.maximum(now - birth, 0)
        hist, lat_sum = latency.observe(row.lat_hist, row.lat_sum,
                                        lat, got)
        slo_ok, slo_bad = latency.slo_observe(
            row.slo_ok, row.slo_violated, lat, got,
            cfg.slo_deadline_rounds)
        row = row.replace(
            prom_valid=row.prom_valid & ~hit,
            prom_done=row.prom_done | hit,
            prom_result=jnp.where(hit, m.data["result"], row.prom_result),
            lat_hist=hist, lat_sum=lat_sum,
            slo_ok=slo_ok, slo_violated=slo_bad)
        return row, self.no_emit()

    def health_counters(self, state: RpcRow):
        """Promise-ring losses + the SLO/latency plane (ISSUE 8: the
        call_dropped counter finally has a reader — telemetry ring +
        host event tap, the PR-4 ack-ring-overflow treatment)."""
        out = {"rpc_call_dropped": jnp.sum(state.call_dropped),
               "rpc_slo_ok": jnp.sum(state.slo_ok),
               "rpc_slo_violated": jnp.sum(state.slo_violated)}
        out.update(latency.hist_counters(
            "rpc_latency", state.lat_hist, state.lat_sum))
        return out
