"""Causal delivery — TPU-native rebuild of
``src/partisan_causality_backend.erl`` (per-label gen_server).

Reference semantics (sites):
  * ``emit`` (:115-139): bump the local vclock; the wire message carries the
    *order buffer entry for the destination* (the clock of the last message
    we sent to that same destination — absent on first send) as its causal
    dependency, plus the new message clock; then the order buffer is updated.
  * ``receive`` (:143-154) buffers the message and attempts delivery of the
    whole buffer; ``internal_receive_message`` (:232-254) delivers when the
    receiver has no entry in the incoming order buffer (no dependency) or
    when the local clock **dominates** the dependency clock.
  * ``deliver`` (:193-223): local = increment(me, merge(local, msg_clock)).
  * a periodic ``deliver`` timer retries the buffer (:168-180) — here every
    round's drain plays that role (redelivery_interval 1).

State is one row per node (vmap over N); the actor universe is the node-id
table so clocks are dense ``[A] int32`` (qos/vclock.py).  The order buffer
is ``[A, A]`` per node — O(N²) per node is intentional: causal labels are a
small-cluster app feature in the reference too (causal_test runs on 2-3
nodes, test/partisan_SUITE.erl:402).

:class:`CausalDelivery` wraps the row ops into a runnable protocol — the
analog of wiring the backend into the pluggable manager's forward_message
path (partisan_pluggable_peer_service_manager.erl:693-725, 1198-1214).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import ring
from ..ops.msg import Msgs
from . import vclock


@struct.dataclass
class CausalRow:
    vc: jax.Array          # [A] local vector clock
    ob: jax.Array          # [A, A] order buffer: last clock sent per dst
    ob_sent: jax.Array     # [A] bool — have we ever sent to dst (orddict key)
    pend_valid: jax.Array  # [B] pending (buffered) messages
    pend_src: jax.Array    # [B]
    pend_payload: jax.Array  # [B]
    pend_dep: jax.Array    # [B, A] dependency clock
    pend_has_dep: jax.Array  # [B] bool
    pend_clock: jax.Array  # [B, A] message clock
    log: jax.Array         # [L] first L delivered payloads, delivery order
    log_src: jax.Array     # [L] their senders
    log_n: jax.Array       # scalar int32 TOTAL delivered count (may exceed L;
                           # entries past L are delivered but unrecorded)
    pend_dropped: jax.Array  # scalar int32 — messages lost to a full pending
                             # ring (the reference buffers unboundedly
                             # :148-151; fixed shapes make loss explicit)


def init_rows(n_nodes: int, buf_cap: int = 8, log_cap: int = 16) -> CausalRow:
    """Batched [N, ...] causal state (one label)."""
    n, a = n_nodes, n_nodes
    return CausalRow(
        vc=jnp.zeros((n, a), jnp.int32),
        ob=jnp.zeros((n, a, a), jnp.int32),
        ob_sent=jnp.zeros((n, a), bool),
        pend_valid=jnp.zeros((n, buf_cap), bool),
        pend_src=jnp.zeros((n, buf_cap), jnp.int32),
        pend_payload=jnp.zeros((n, buf_cap), jnp.int32),
        pend_dep=jnp.zeros((n, buf_cap, a), jnp.int32),
        pend_has_dep=jnp.zeros((n, buf_cap), bool),
        pend_clock=jnp.zeros((n, buf_cap, a), jnp.int32),
        log=jnp.full((n, log_cap), -1, jnp.int32),
        log_src=jnp.full((n, log_cap), -1, jnp.int32),
        log_n=jnp.zeros((n,), jnp.int32),
        pend_dropped=jnp.zeros((n,), jnp.int32),
    )


def emit(row: CausalRow, me: jax.Array, dst: jax.Array
         ) -> Tuple[CausalRow, jax.Array, jax.Array, jax.Array]:
    """The emit half (:115-139).  Returns (row', dep_clock, has_dep,
    msg_clock) — the wire fields of the causal message."""
    clock = vclock.increment(row.vc, me)
    d = jnp.clip(dst, 0, row.ob.shape[0] - 1)
    dep = row.ob[d]
    has_dep = row.ob_sent[d]
    row = row.replace(
        vc=clock,
        ob=row.ob.at[d].set(clock),
        ob_sent=row.ob_sent.at[d].set(True),
    )
    return row, dep, has_dep, clock


def receive(row: CausalRow, src, payload, dep, has_dep, clock
            ) -> Tuple[CausalRow, jax.Array]:
    """Buffer an incoming causal message (:143-154).  Returns (row',
    dropped) — dropped is True when the pending ring is full (the reference
    buffers unboundedly; fixed shapes force an explicit overflow signal)."""
    ok, slot = ring.alloc(row.pend_valid)
    wr = lambda a, v: ring.masked_set(a, slot, ok, v)
    row = row.replace(
        pend_valid=wr(row.pend_valid, True),
        pend_src=wr(row.pend_src, src),
        pend_payload=wr(row.pend_payload, payload),
        pend_dep=wr(row.pend_dep, dep),
        pend_has_dep=wr(row.pend_has_dep, has_dep),
        pend_clock=wr(row.pend_clock, clock),
        pend_dropped=row.pend_dropped + (~ok).astype(jnp.int32),
    )
    return row, ~ok


def drain(row: CausalRow, me: jax.Array) -> Tuple[CausalRow, jax.Array]:
    """Attempt delivery of every buffered message (the fold of :149-152 +
    the periodic deliver timer :168-180).  Two passes over the ring so a
    delivery that satisfies another message's dependency in the same round
    is honored (the reference re-folds on every receive).  Returns (row',
    n_delivered)."""
    B = row.pend_valid.shape[0]
    L = row.log.shape[0]

    def try_slot(i, carry):
        row, n = carry
        deliverable = row.pend_valid[i] & (
            ~row.pend_has_dep[i]
            | vclock.dominates(row.vc, row.pend_dep[i]))
        new_vc = vclock.increment(vclock.merge(row.vc, row.pend_clock[i]), me)
        li = jnp.clip(row.log_n, 0, L - 1)
        record = deliverable & (row.log_n < L)  # log holds the first L only
        row = row.replace(
            vc=jnp.where(deliverable, new_vc, row.vc),
            pend_valid=row.pend_valid.at[i].set(
                row.pend_valid[i] & ~deliverable),
            log=row.log.at[li].set(jnp.where(
                record, row.pend_payload[i], row.log[li])),
            log_src=row.log_src.at[li].set(jnp.where(
                record, row.pend_src[i], row.log_src[li])),
            log_n=row.log_n + deliverable.astype(jnp.int32),
        )
        return row, n + deliverable.astype(jnp.int32)

    n0 = jnp.int32(0)
    row, n = jax.lax.fori_loop(0, B, try_slot, (row, n0))
    row, n = jax.lax.fori_loop(0, B, try_slot, (row, n))
    return row, n


class CausalDelivery(ProtocolBase):
    """Runnable causal-messaging layer: ``ctl_csend`` stamps and ships a
    causal message; receivers buffer and drain every round.  The delivery
    log per node is the assertion surface (causal_test,
    test/partisan_SUITE.erl:402)."""

    msg_types = ("causal", "ctl_csend")

    def __init__(self, cfg: Config, buf_cap: int = 8, log_cap: int = 16):
        self.cfg = cfg
        self.buf_cap, self.log_cap = buf_cap, log_cap
        a = cfg.n_nodes
        self.data_spec: Dict = {
            "payload": ((), jnp.int32),
            "peer": ((), jnp.int32),
            "dep": ((a,), jnp.int32),
            "has_dep": ((), jnp.int32),
            "clock": ((a,), jnp.int32),
            "cdelay": ((), jnp.int32),  # test hook: wire delay for reordering
        }
        self.emit_cap = 1
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> CausalRow:
        return init_rows(cfg.n_nodes, self.buf_cap, self.log_cap)

    def handle_ctl_csend(self, cfg, me, row: CausalRow, m: Msgs, key):
        dst = m.data["peer"]
        row, dep, has_dep, clock = emit(row, me, dst)
        em = self.emit(dst[None], self.typ("causal"),
                       payload=m.data["payload"], dep=dep,
                       has_dep=has_dep.astype(jnp.int32), clock=clock,
                       delay=m.data["cdelay"])
        return row, em

    def handle_causal(self, cfg, me, row: CausalRow, m: Msgs, key):
        row, _ = receive(row, m.src, m.data["payload"], m.data["dep"],
                         m.data["has_dep"] > 0, m.data["clock"])
        return row, self.no_emit()

    def tick(self, cfg, me, row: CausalRow, rnd, key):
        row, _ = drain(row, me)
        return row, self.no_emit(self.tick_emit_cap)
