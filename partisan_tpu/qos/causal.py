"""Causal delivery — TPU-native rebuild of
``src/partisan_causality_backend.erl`` (per-label gen_server).

Reference semantics (sites):
  * ``emit`` (:115-139): bump the local vclock; the wire message carries the
    *order buffer entry for the destination* (the clock of the last message
    we sent to that same destination — absent on first send) as its causal
    dependency, plus the new message clock; then the order buffer is updated.
  * ``receive`` (:143-154) buffers the message and attempts delivery of the
    whole buffer; ``internal_receive_message`` (:232-254) delivers when the
    receiver has no entry in the incoming order buffer (no dependency) or
    when the local clock **dominates** the dependency clock.
  * ``deliver`` (:193-223): local = increment(me, merge(local, msg_clock)).
  * a periodic ``deliver`` timer retries the buffer (:168-180) — here every
    round's drain plays that role (redelivery_interval 1).

State is one row per node (vmap over N); the actor universe is the node-id
table so clocks are dense ``[A] int32`` (qos/vclock.py).  The order buffer
is ``[A, A]`` per node — O(N²) per node is intentional: causal labels are a
small-cluster app feature in the reference too (causal_test runs on 2-3
nodes, test/partisan_SUITE.erl:402).

:class:`CausalDelivery` wraps the row ops into a runnable protocol — the
analog of wiring the backend into the pluggable manager's forward_message
path (partisan_pluggable_peer_service_manager.erl:693-725, 1198-1214).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import ring
from ..ops.msg import Msgs
from . import ack as ack_mod
from . import vclock


@struct.dataclass
class CausalRow:
    vc: jax.Array          # [A] local vector clock
    ob: jax.Array          # [A, A] order buffer: last clock sent per dst
    ob_sent: jax.Array     # [A] bool — have we ever sent to dst (orddict key)
    pend_valid: jax.Array  # [B] pending (buffered) messages
    pend_src: jax.Array    # [B]
    pend_payload: jax.Array  # [B]
    pend_dep: jax.Array    # [B, A] dependency clock
    pend_has_dep: jax.Array  # [B] bool
    pend_clock: jax.Array  # [B, A] message clock
    pend_seq: jax.Array    # [B] per-STREAM wire seq (0 = unsequenced)
    last_seq: jax.Array    # [A] last seq delivered per sender.  Seqs are
                           # allocated per (sender -> dst) stream, so they
                           # are CONTIGUOUS here, and drain() delivers
                           # sequenced messages strictly in seq order —
                           # dependency dominance alone does NOT give
                           # per-stream FIFO (a third node's clock can
                           # satisfy m2's dep before m1 arrives), and a
                           # clock-descends dup check fails the same way
    log: jax.Array         # [L] first L delivered payloads, delivery order
    log_src: jax.Array     # [L] their senders
    log_n: jax.Array       # scalar int32 TOTAL delivered count (may exceed L;
                           # entries past L are delivered but unrecorded)
    pend_dropped: jax.Array  # scalar int32 — messages lost to a full pending
                             # ring (the reference buffers unboundedly
                             # :148-151; fixed shapes make loss explicit)


def init_rows(n_nodes: int, buf_cap: int = 8, log_cap: int = 16) -> CausalRow:
    """Batched [N, ...] causal state (one label)."""
    n, a = n_nodes, n_nodes
    return CausalRow(
        vc=jnp.zeros((n, a), jnp.int32),
        ob=jnp.zeros((n, a, a), jnp.int32),
        ob_sent=jnp.zeros((n, a), bool),
        pend_valid=jnp.zeros((n, buf_cap), bool),
        pend_src=jnp.zeros((n, buf_cap), jnp.int32),
        pend_payload=jnp.zeros((n, buf_cap), jnp.int32),
        pend_dep=jnp.zeros((n, buf_cap, a), jnp.int32),
        pend_has_dep=jnp.zeros((n, buf_cap), bool),
        pend_clock=jnp.zeros((n, buf_cap, a), jnp.int32),
        pend_seq=jnp.zeros((n, buf_cap), jnp.int32),
        last_seq=jnp.zeros((n, a), jnp.int32),
        log=jnp.full((n, log_cap), -1, jnp.int32),
        log_src=jnp.full((n, log_cap), -1, jnp.int32),
        log_n=jnp.zeros((n,), jnp.int32),
        pend_dropped=jnp.zeros((n,), jnp.int32),
    )


def emit(row: CausalRow, me: jax.Array, dst: jax.Array
         ) -> Tuple[CausalRow, jax.Array, jax.Array, jax.Array]:
    """The emit half (:115-139).  Returns (row', dep_clock, has_dep,
    msg_clock) — the wire fields of the causal message."""
    clock = vclock.increment(row.vc, me)
    d = jnp.clip(dst, 0, row.ob.shape[0] - 1)
    dep = row.ob[d]
    has_dep = row.ob_sent[d]
    row = row.replace(
        vc=clock,
        ob=row.ob.at[d].set(clock),
        ob_sent=row.ob_sent.at[d].set(True),
    )
    return row, dep, has_dep, clock


def receive(row: CausalRow, src, payload, dep, has_dep, clock,
            seq=None) -> Tuple[CausalRow, jax.Array]:
    """Buffer an incoming causal message (:143-154).  Returns (row',
    dropped) — dropped is True when the pending ring is full (the reference
    buffers unboundedly; fixed shapes force an explicit overflow signal).
    ``seq`` > 0 enables retransmission dedup (CausalAcked); an
    already-delivered seq is ignored without counting as a drop."""
    seq = jnp.int32(0) if seq is None else seq
    dup = (seq > 0) & (seq <= row.last_seq[jnp.clip(
        src, 0, row.last_seq.shape[0] - 1)])
    ok, slot = ring.alloc(row.pend_valid)
    ok = ok & ~dup
    wr = lambda a, v: ring.masked_set(a, slot, ok, v)
    row = row.replace(
        pend_valid=wr(row.pend_valid, True),
        pend_src=wr(row.pend_src, src),
        pend_payload=wr(row.pend_payload, payload),
        pend_dep=wr(row.pend_dep, dep),
        pend_has_dep=wr(row.pend_has_dep, has_dep),
        pend_clock=wr(row.pend_clock, clock),
        pend_seq=wr(row.pend_seq, seq),
        pend_dropped=row.pend_dropped
        + (~ok & ~dup).astype(jnp.int32),
    )
    return row, ~ok & ~dup


def drain(row: CausalRow, me: jax.Array) -> Tuple[CausalRow, jax.Array]:
    """Attempt delivery of every buffered message (the fold of :149-152 +
    the periodic deliver timer :168-180).  Two passes over the ring so a
    delivery that satisfies another message's dependency in the same round
    is honored (the reference re-folds on every receive).  Returns (row',
    n_delivered)."""
    B = row.pend_valid.shape[0]
    L = row.log.shape[0]

    def try_slot(i, carry):
        row, n = carry
        # retransmission dedup (sequenced messages only): a pending entry
        # whose seq was already delivered for its sender is a duplicate
        # that crossed its ack — drop without delivering or counting
        src_i = jnp.clip(row.pend_src[i], 0, row.last_seq.shape[0] - 1)
        dup = row.pend_valid[i] & (row.pend_seq[i] > 0) \
            & (row.pend_seq[i] <= row.last_seq[src_i])
        row = row.replace(pend_valid=row.pend_valid.at[i].set(
            row.pend_valid[i] & ~dup))
        # sequenced messages additionally deliver in exact stream order
        # (seq == last+1): dominance alone would let a successor overtake
        # a delayed predecessor via transitive clock advancement
        in_order = (row.pend_seq[i] == 0) \
            | (row.pend_seq[i] == row.last_seq[src_i] + 1)
        deliverable = row.pend_valid[i] & in_order & (
            ~row.pend_has_dep[i]
            | vclock.dominates(row.vc, row.pend_dep[i]))
        new_vc = vclock.increment(vclock.merge(row.vc, row.pend_clock[i]), me)
        li = jnp.clip(row.log_n, 0, L - 1)
        record = deliverable & (row.log_n < L)  # log holds the first L only
        row = row.replace(
            vc=jnp.where(deliverable, new_vc, row.vc),
            pend_valid=row.pend_valid.at[i].set(
                row.pend_valid[i] & ~deliverable),
            log=row.log.at[li].set(jnp.where(
                record, row.pend_payload[i], row.log[li])),
            log_src=row.log_src.at[li].set(jnp.where(
                record, row.pend_src[i], row.log_src[li])),
            log_n=row.log_n + deliverable.astype(jnp.int32),
            last_seq=row.last_seq.at[src_i].set(jnp.where(
                deliverable,
                jnp.maximum(row.last_seq[src_i], row.pend_seq[i]),
                row.last_seq[src_i])),
        )
        return row, n + deliverable.astype(jnp.int32)

    n0 = jnp.int32(0)
    row, n = jax.lax.fori_loop(0, B, try_slot, (row, n0))
    row, n = jax.lax.fori_loop(0, B, try_slot, (row, n))
    return row, n


@struct.dataclass
class CausalAckedRow:
    causal: CausalRow
    # reemit storage: the wire copy of every unacked causal message
    # (causality_backend stores each emitted message for reemit :107-113,
    # 134-136; the manager's retransmit loop re-sends it, pluggable
    # :905-942)
    out_valid: jax.Array   # [R]
    out_dst: jax.Array     # [R]
    out_payload: jax.Array  # [R]
    out_dep: jax.Array     # [R, A]
    out_has_dep: jax.Array  # [R]
    out_clock: jax.Array   # [R, A]
    out_seq: jax.Array     # [R]
    out_age: jax.Array     # [R]
    out_attempt: jax.Array  # [R] retransmissions fired (backoff plane)
    next_seq_to: jax.Array  # [A] per-destination stream seq source (so
                            # seqs per (me -> dst) stream are contiguous)
    send_dropped: jax.Array  # scalar — full-ring losses, surfaced
    dead_lettered: jax.Array  # scalar — slots abandoned at the backoff
                              # give-up threshold.  NOTE: dead-lettering
                              # a SEQUENCED slot abandons the whole
                              # (me -> dst) stream suffix (drain delivers
                              # in seq order) — the counter is the alarm;
                              # default max_attempts=0 never gives up.


class CausalDelivery(ProtocolBase):
    """Runnable causal-messaging layer: ``ctl_csend`` stamps and ships a
    causal message; receivers buffer and drain every round.  The delivery
    log per node is the assertion surface (causal_test,
    test/partisan_SUITE.erl:402)."""

    msg_types = ("causal", "ctl_csend")

    def __init__(self, cfg: Config, buf_cap: int = 8, log_cap: int = 16):
        self.cfg = cfg
        self.buf_cap, self.log_cap = buf_cap, log_cap
        # dense [A] clocks on the wire and an [A, A] order buffer per node
        # make causal labels an O(N^3) state feature — the reference has
        # the same practical shape (per-label gen_servers holding orddict
        # clocks; causal_test runs on 2-3 nodes,
        # test/partisan_SUITE.erl:402).  Guard like FullMembership's so
        # the limit is an error, not an allocation surprise; qos/dvv.py
        # holds the fixed-slot sparse-clock prototype for larger actor
        # sets (ROADMAP 8).
        assert cfg.n_nodes <= 128, (
            f"causal labels carry dense [N] clocks and [N, N] order "
            f"buffers per node (O(N^3) total); a causal label over "
            f"{cfg.n_nodes} > 128 nodes needs the sparse-clock path "
            f"(qos/causal_sparse.py CausalDeliverySparse)")
        a = cfg.n_nodes
        self.data_spec: Dict = {
            "payload": ((), jnp.int32),
            "peer": ((), jnp.int32),
            "dep": ((a,), jnp.int32),
            "has_dep": ((), jnp.int32),
            "clock": ((a,), jnp.int32),
            "cdelay": ((), jnp.int32),  # test hook: wire delay for reordering
        }
        self.emit_cap = 1
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> CausalRow:
        return init_rows(cfg.n_nodes, self.buf_cap, self.log_cap)

    def handle_ctl_csend(self, cfg, me, row: CausalRow, m: Msgs, key):
        dst = m.data["peer"]
        row, dep, has_dep, clock = emit(row, me, dst)
        em = self.emit(dst[None], self.typ("causal"),
                       payload=m.data["payload"], dep=dep,
                       has_dep=has_dep.astype(jnp.int32), clock=clock,
                       delay=m.data["cdelay"])
        return row, em

    def handle_causal(self, cfg, me, row: CausalRow, m: Msgs, key):
        row, _ = receive(row, m.src, m.data["payload"], m.data["dep"],
                         m.data["has_dep"] > 0, m.data["clock"])
        return row, self.no_emit()

    def tick(self, cfg, me, row: CausalRow, rnd, key):
        row, _ = drain(row, me)
        return row, self.no_emit(self.tick_emit_cap)


class CausalAcked(CausalDelivery):
    """The `with_causal_send_and_ack` suite-group composition
    (test/partisan_SUITE.erl groups; pluggable :693-741): causal messages
    are also parked for acknowledgement and the retransmit timer REEMITS
    the stored wire copy — byte-identical dependency clock and message
    clock, which is why the backend stores emitted messages instead of
    re-stamping (causality_backend reemit :107-113).  At-least-once +
    causal order: duplicates are buffered again but their clocks are
    already dominated, so delivery stays exactly-once per clock."""

    msg_types = ("causal", "causal_ack", "ctl_csend")

    def __init__(self, cfg: Config, buf_cap: int = 8, log_cap: int = 16,
                 ring_cap: int = 8):
        super().__init__(cfg, buf_cap, log_cap)
        self.R = ring_cap
        self.data_spec = dict(self.data_spec)
        self.data_spec["seq"] = ((), jnp.int32)
        self.tick_emit_cap = ring_cap

    def init(self, cfg: Config, key: jax.Array) -> CausalAckedRow:
        n, a, r = cfg.n_nodes, cfg.n_nodes, self.R
        return CausalAckedRow(
            causal=super().init(cfg, key),
            out_valid=jnp.zeros((n, r), bool),
            out_dst=jnp.zeros((n, r), jnp.int32),
            out_payload=jnp.zeros((n, r), jnp.int32),
            out_dep=jnp.zeros((n, r, a), jnp.int32),
            out_has_dep=jnp.zeros((n, r), bool),
            out_clock=jnp.zeros((n, r, a), jnp.int32),
            out_seq=jnp.zeros((n, r), jnp.int32),
            out_age=jnp.zeros((n, r), jnp.int32),
            out_attempt=jnp.zeros((n, r), jnp.int32),
            next_seq_to=jnp.ones((n, a), jnp.int32),
            send_dropped=jnp.zeros((n,), jnp.int32),
            dead_lettered=jnp.zeros((n,), jnp.int32),
        )

    def handle_ctl_csend(self, cfg, me, row: CausalAckedRow, m: Msgs, key):
        dst = m.data["peer"]
        # allocate the reemit slot FIRST: on a full ring the send must not
        # happen at all — stamping the clock/order-buffer for a message
        # that never reaches the wire would wedge every later message to
        # this destination behind an unsatisfiable dependency
        ok, slot = ring.alloc(row.out_valid)
        crow, dep, has_dep, clock = emit(row.causal, me, dst)
        crow = jax.tree_util.tree_map(
            lambda new, old: jnp.where(ok, new, old), crow, row.causal)
        d = jnp.clip(dst, 0, row.next_seq_to.shape[0] - 1)
        seq = row.next_seq_to[d]
        wr = lambda a_, v: ring.masked_set(a_, slot, ok, v)
        row = row.replace(
            causal=crow,
            out_valid=wr(row.out_valid, True),
            out_dst=wr(row.out_dst, dst),
            out_payload=wr(row.out_payload, m.data["payload"]),
            out_dep=wr(row.out_dep, dep),
            out_has_dep=wr(row.out_has_dep, has_dep),
            out_clock=wr(row.out_clock, clock),
            out_seq=wr(row.out_seq, seq),
            out_age=wr(row.out_age, 0),
            out_attempt=wr(row.out_attempt, 0),
            next_seq_to=row.next_seq_to.at[d].add(ok.astype(jnp.int32)),
            send_dropped=row.send_dropped + (~ok).astype(jnp.int32),
        )
        em = self.emit(jnp.where(ok, dst, -1)[None], self.typ("causal"),
                       payload=m.data["payload"], dep=dep,
                       has_dep=has_dep.astype(jnp.int32), clock=clock,
                       seq=seq, delay=m.data["cdelay"])
        return row, em

    def handle_causal(self, cfg, me, row: CausalAckedRow, m: Msgs, key):
        # seq-based dedup lives in receive()/drain(); a message LOST to a
        # full pending ring must NOT be acked — the sender's reemit timer
        # is the recovery path for exactly that case
        crow, dropped = receive(row.causal, m.src, m.data["payload"],
                                m.data["dep"], m.data["has_dep"] > 0,
                                m.data["clock"], seq=m.data["seq"])
        ack_rep = self.emit(jnp.where(dropped, -1, m.src)[None],
                            self.typ("causal_ack"), seq=m.data["seq"])
        return row.replace(causal=crow), ack_rep

    def handle_causal_ack(self, cfg, me, row: CausalAckedRow, m: Msgs, key):
        # seqs are per-DESTINATION streams (next_seq_to is indexed by
        # dst), so every stream starts at 1 and the ack must match
        # (dst, seq) — seq alone would clear other destinations' unacked
        # same-seq messages, losing them with no retransmit
        hit = row.out_valid & (row.out_dst == m.src) \
            & (row.out_seq == m.data["seq"])
        return row.replace(out_valid=row.out_valid & ~hit), self.no_emit()

    def tick(self, cfg, me, row: CausalAckedRow, rnd, key):
        crow, _ = drain(row.causal, me)
        row = row.replace(causal=crow)
        # reemit the stored wire copies of unacked messages (backoff
        # timer; defaults bit-equal the fixed interval — ack.py)
        valid, age, attempt, due, dead = ack_mod.retransmit_backoff(
            row.out_valid, row.out_age, row.out_attempt, me,
            **ack_mod.backoff_kw(cfg))
        row = row.replace(out_valid=valid, out_age=age,
                          out_attempt=attempt,
                          dead_lettered=row.dead_lettered + dead)
        em = self.emit(jnp.where(due, row.out_dst, -1),
                       self.typ("causal"), cap=self.tick_emit_cap,
                       payload=row.out_payload, dep=row.out_dep,
                       has_dep=row.out_has_dep.astype(jnp.int32),
                       clock=row.out_clock, seq=row.out_seq)
        return row, em

    def health_counters(self, state: CausalAckedRow):
        return {"ack_outstanding": jnp.sum(state.out_valid),
                "ack_send_dropped": jnp.sum(state.send_dropped),
                "ack_dead_lettered": jnp.sum(state.dead_lettered)}
