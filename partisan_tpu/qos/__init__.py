"""Messaging QoS backends (SURVEY §2.6): vector clocks, causal delivery,
acknowledgement + retransmission, RPC, promises — the TPU-native rebuilds
of ``src/partisan_vclock.erl``, ``src/partisan_causality_backend.erl``,
``src/partisan_acknowledgement_backend.erl``,
``src/partisan_rpc_backend.erl`` and ``src/partisan_promise_backend.erl``."""

from . import vclock  # noqa: F401
