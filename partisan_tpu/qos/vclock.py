"""Vector clocks over a fixed actor universe — the TPU-native rebuild of
``src/partisan_vclock.erl`` (Riak-derived: fresh/descends/dominates/merge/
increment/glb, :57-77 ff.).

The reference represents a clock as an orddict ``[{actor, counter}]`` over
dynamically-discovered actors; here the actor universe is the node-id table
(SURVEY §5.6), so a clock is a dense ``[A] int32`` row and every comparison
is a vectorized reduction.  All functions operate on single clocks and are
designed to be ``vmap``-ped; "absent actor" equals counter 0 exactly as in
the reference (missing orddict key defaults to 0 in descends).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def fresh(n_actors: int) -> jax.Array:
    """partisan_vclock:fresh/0 — the zero clock."""
    return jnp.zeros((n_actors,), jnp.int32)


def increment(clock: jax.Array, actor: jax.Array) -> jax.Array:
    """partisan_vclock:increment/2 — bump one actor's counter."""
    return clock.at[actor].add(1)


def merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """partisan_vclock:merge/1 — pointwise max."""
    return jnp.maximum(a, b)


def glb(a: jax.Array, b: jax.Array) -> jax.Array:
    """partisan_vclock:glb/2 — pointwise min (greatest lower bound)."""
    return jnp.minimum(a, b)


def descends(a: jax.Array, b: jax.Array) -> jax.Array:
    """True iff ``a`` has seen every event ``b`` has (a >= b pointwise) —
    partisan_vclock:descends/2.  Every clock descends the fresh clock."""
    return jnp.all(a >= b)


def dominates(a: jax.Array, b: jax.Array) -> jax.Array:
    """Strict descent: descends(a, b) and a != b
    (partisan_vclock:dominates/2)."""
    return descends(a, b) & jnp.any(a > b)


def equal(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.all(a == b)


def concurrent(a: jax.Array, b: jax.Array) -> jax.Array:
    """Neither descends the other."""
    return ~descends(a, b) & ~descends(b, a)
