"""Acknowledgement + retransmission — TPU-native rebuild of
``src/partisan_acknowledgement_backend.erl`` (ETS store of
{MessageClock, RescheduleableMessage}, store/ack/outstanding :49-78) plus
the manager's 1 s ``retransmit`` timer that re-sends everything outstanding
(partisan_pluggable_peer_service_manager.erl:905-942, 1299-1301).

Per-node state is a fixed ring of outstanding slots (SURVEY §2.11: an
"outstanding-message ring buffer per node; retransmit as a masked re-emit
each round").  Delivery is at-least-once exactly like the reference: a
retransmitted message that crosses its own ack is delivered twice; acks are
keyed by a per-origin monotone sequence number (the analog of the message
clock, pluggable :687, 737-741).

:class:`AckedDelivery` is the runnable layer (the `with_ack` suite group,
test/partisan_SUITE.erl:573).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import ring
from ..ops.bitset import mix32 as _mix
from ..ops.msg import Msgs


@struct.dataclass
class AckRow:
    out_valid: jax.Array    # [R] outstanding slots
    out_dst: jax.Array      # [R]
    out_payload: jax.Array  # [R]
    out_seq: jax.Array      # [R] origin-scoped message id
    out_age: jax.Array      # [R] rounds since (re)transmission
    out_attempt: jax.Array  # [R] retransmissions fired so far (backoff)
    next_seq: jax.Array     # scalar — monotone id source
    seen: jax.Array         # [S] delivery counters per origin (test surface)
    send_dropped: jax.Array  # scalar — ctl_sends lost to a full ring
                             # (overflow surfaced, never silent)
    dead_lettered: jax.Array  # scalar — slots abandoned at the backoff
                              # give-up threshold (surfaced, never silent)


def init_rows(n_nodes: int, ring_cap: int = 8) -> AckRow:
    n = n_nodes
    return AckRow(
        out_valid=jnp.zeros((n, ring_cap), bool),
        out_dst=jnp.zeros((n, ring_cap), jnp.int32),
        out_payload=jnp.zeros((n, ring_cap), jnp.int32),
        out_seq=jnp.zeros((n, ring_cap), jnp.int32),
        out_age=jnp.zeros((n, ring_cap), jnp.int32),
        out_attempt=jnp.zeros((n, ring_cap), jnp.int32),
        next_seq=jnp.ones((n,), jnp.int32),
        seen=jnp.zeros((n, n_nodes), jnp.int32),
        send_dropped=jnp.zeros((n,), jnp.int32),
        dead_lettered=jnp.zeros((n,), jnp.int32),
    )


def store(row: AckRow, dst, payload) -> Tuple[AckRow, jax.Array, jax.Array]:
    """acknowledgement_backend:store/2 — park an outgoing message until its
    ack arrives.  Returns (row', seq, stored_ok); stored_ok False = ring
    full (surfaced, never silent)."""
    ok, slot = ring.alloc(row.out_valid)
    seq = row.next_seq
    wr = lambda a, v: ring.masked_set(a, slot, ok, v)
    row = row.replace(
        out_valid=wr(row.out_valid, True),
        out_dst=wr(row.out_dst, dst),
        out_payload=wr(row.out_payload, payload),
        out_seq=wr(row.out_seq, seq),
        out_age=wr(row.out_age, 0),
        out_attempt=wr(row.out_attempt, 0),
        next_seq=seq + 1,
    )
    return row, seq, ok


def ack(row: AckRow, seq) -> AckRow:
    """acknowledgement_backend:ack/1 — clear the matching slot."""
    hit = row.out_valid & (row.out_seq == seq)
    return row.replace(out_valid=row.out_valid & ~hit)


def outstanding(row: AckRow) -> jax.Array:
    return jnp.sum(row.out_valid).astype(jnp.int32)


def retransmit_due(valid: jax.Array, age: jax.Array,
                   interval: int) -> Tuple[jax.Array, jax.Array]:
    """The fixed-interval retransmit-timer step (pluggable :905-942):
    ages valid slots, fires those at the interval, resets fired ages.
    Returns (new_age, due).  Kept as the minimal primitive;
    :func:`retransmit_backoff` is the full self-healing timer (ISSUE 4)
    that every acked layer now routes through — with backoff disabled
    it reduces to exactly this function."""
    age = jnp.where(valid, age + 1, 0)
    due = valid & (age >= interval)
    return jnp.where(due, 0, age), due


def retransmit_backoff(valid: jax.Array, age: jax.Array,
                       attempt: jax.Array, me, *, base: int,
                       factor: int = 1, max_interval: int = 0,
                       jitter: int = 0, max_attempts: int = 0
                       ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                  jax.Array, jax.Array]:
    """The self-healing retransmit timer (ISSUE 4): exponential backoff
    with deterministic jitter and a give-up/dead-letter exit, replacing
    the fixed ageing of :func:`retransmit_due` in every acked layer.

    Per slot, attempt k fires after ``base * factor^k`` rounds (capped
    at ``max_interval`` when > 0) plus a deterministic jitter draw in
    ``[0, jitter]`` hashed from ``(me, slot, attempt)`` — replayable,
    but cluster-wide retransmit storms desynchronize.  A slot that has
    already fired ``max_attempts`` retransmissions (> 0) is
    DEAD-LETTERED when it next comes due: freed and counted, never
    retried silently forever against a peer that is gone.

    Runs per node under the engine's vmap: ``valid/age/attempt`` are the
    node's ``[R]`` ring slices, ``me`` its scalar id.  Returns
    ``(valid', age', attempt', due, dead_count)``.

    Disabled knobs (``factor=1, jitter=0, max_attempts=0`` — the Config
    defaults) make this BIT-EQUAL to ``retransmit_due(valid, age,
    base)`` with ``valid`` untouched (tests/test_chaos.py pins the
    equivalence), so every protocol that switched to this timer is
    bit-compatible with its pre-backoff self by default.
    """
    age = jnp.where(valid, age + 1, 0)
    # ``base`` may be a TRACED per-node scalar (the ISSUE-10 adaptive
    # retransmit setpoint); the static-int path below traces exactly the
    # pre-ISSUE-10 ops, so existing programs stay byte-identical.
    static_base = isinstance(base, (int, np.integer))
    if factor > 1:
        # int32-safe exponent clamp; the cap (if any) is applied after
        expo = jnp.clip(attempt, 0, 20)
        base_i = jnp.int32(base) if static_base \
            else jnp.asarray(base, jnp.int32)
        interval = base_i * jnp.power(jnp.int32(factor), expo)
        if max_interval > 0:
            interval = jnp.minimum(interval, jnp.int32(max_interval))
    elif static_base:
        interval = jnp.full_like(age, jnp.int32(base))
    else:
        interval = jnp.broadcast_to(jnp.asarray(base, jnp.int32),
                                    age.shape)
    if jitter > 0:
        slot_ids = jnp.arange(valid.shape[0], dtype=jnp.uint32)
        h = _mix(jnp.uint32(me) * jnp.uint32(0x9E3779B9)
                 ^ (slot_ids << 8) ^ attempt.astype(jnp.uint32))
        interval = interval + (h % jnp.uint32(jitter + 1)
                               ).astype(jnp.int32)
    due = valid & (age >= interval)
    if max_attempts > 0:
        dead = due & (attempt >= max_attempts)
        due = due & ~dead
    else:
        dead = jnp.zeros_like(due)
    valid = valid & ~dead
    age = jnp.where(due | dead, 0, age)
    attempt = jnp.where(valid, attempt + due.astype(jnp.int32), 0)
    return valid, age, attempt, due, jnp.sum(dead).astype(jnp.int32)


def backoff_kw(cfg: Config, base: Optional[int] = None) -> dict:
    """The Config tier of the backoff knobs (one place, every layer)."""
    return dict(base=cfg.retransmit_interval if base is None else base,
                factor=cfg.retransmit_backoff_factor,
                max_interval=cfg.retransmit_backoff_max,
                jitter=cfg.retransmit_jitter,
                max_attempts=cfg.retransmit_max_attempts)


class AckedDelivery(ProtocolBase):
    """``ctl_send`` ships an app message expecting an ack; unacked messages
    are re-sent every ``retransmit_interval`` rounds (pluggable :905-942).
    ``seen[origin]`` counts deliveries per origin — the store_proc assertion
    surface of ack_test."""

    msg_types = ("app", "app_ack", "ctl_send")

    def __init__(self, cfg: Config, ring_cap: int = 8):
        self.cfg = cfg
        self.R = ring_cap
        self.data_spec: Dict = {
            "payload": ((), jnp.int32),
            "seq": ((), jnp.int32),
            "peer": ((), jnp.int32),
        }
        self.emit_cap = 1
        self.tick_emit_cap = ring_cap

    def init(self, cfg: Config, key: jax.Array) -> AckRow:
        return init_rows(cfg.n_nodes, self.R)

    def handle_ctl_send(self, cfg, me, row: AckRow, m: Msgs, key):
        dst = m.data["peer"]
        row, seq, ok = store(row, dst, m.data["payload"])
        row = row.replace(send_dropped=row.send_dropped
                          + (~ok).astype(jnp.int32))
        em = self.emit(jnp.where(ok, dst, -1)[None], self.typ("app"),
                       payload=m.data["payload"], seq=seq)
        return row, em

    def handle_app(self, cfg, me, row: AckRow, m: Msgs, key):
        """Deliver + send_acknowledgement back to the origin (pluggable
        :1217-1227, 1612-1617)."""
        src = jnp.clip(m.src, 0, row.seen.shape[0] - 1)
        row = row.replace(seen=row.seen.at[src].add(1))
        return row, self.emit(m.src[None], self.typ("app_ack"),
                              seq=m.data["seq"])

    def handle_app_ack(self, cfg, me, row: AckRow, m: Msgs, key):
        return ack(row, m.data["seq"]), self.no_emit()

    def tick(self, cfg, me, row: AckRow, rnd, key):
        """Retransmit timer: re-emit every outstanding slot whose age
        reaches its (backoff) interval; a slot past the give-up
        threshold is dead-lettered and counted."""
        valid, age, attempt, due, dead = retransmit_backoff(
            row.out_valid, row.out_age, row.out_attempt, me,
            **backoff_kw(cfg))
        row = row.replace(out_valid=valid, out_age=age,
                          out_attempt=attempt,
                          dead_lettered=row.dead_lettered + dead)
        em = self.emit(jnp.where(due, row.out_dst, -1),
                       self.typ("app"), cap=self.tick_emit_cap,
                       payload=row.out_payload, seq=row.out_seq)
        return row, em

    def health_counters(self, state: AckRow) -> Dict[str, jax.Array]:
        """The ack-ring degradation taps (ISSUE 4 satellite): overflow
        and dead-letter totals, surfaced through metrics.world_health
        and the telemetry ring (verify.health.QOS_SPECS)."""
        return {"ack_outstanding": jnp.sum(state.out_valid),
                "ack_send_dropped": jnp.sum(state.send_dropped),
                "ack_dead_lettered": jnp.sum(state.dead_lettered)}

    def trace_taps(self, cfg, pre, mid, post, rnd):
        """Lifecycle-tracer taps (ISSUE 16) over the send-ring diffs.
        Pair with ``TraceSpec(seq_field="seq")`` so wire spans and these
        sender-side transitions share the ``(src, seq)`` trace id.

        * ``acked`` — a slot valid at round start whose deliver phase
          freed it (an ``app_ack`` landed) or re-stored it under a new
          seq (freed AND reused within the same round);
        * ``retransmitted`` — tick bumped the slot's attempt counter
          (the re-emission itself also shows as a fresh ``emitted``);
        * ``dead_lettered`` — tick abandoned the slot at the backoff
          give-up threshold."""
        app = self.typ("app")
        acked = pre.out_valid & (~mid.out_valid
                                 | (mid.out_seq != pre.out_seq))
        retrans = (mid.out_valid & post.out_valid
                   & (post.out_attempt > mid.out_attempt))
        dead = mid.out_valid & ~post.out_valid
        return (
            ("acked", dict(keep=acked, dst=pre.out_dst, typ=app,
                           seq=pre.out_seq)),
            ("retransmitted", dict(keep=retrans, dst=post.out_dst,
                                   typ=app, seq=post.out_seq)),
            ("dead_lettered", dict(keep=dead, dst=mid.out_dst, typ=app,
                                   seq=mid.out_seq)),
        )


# ================= adaptive retransmission (ISSUE 10 control plane) ======

@struct.dataclass
class AdaptiveAckRow(AckRow):
    """AckRow + the controller-driven base interval and the two counters
    the adaptive-retransmit loop feeds on."""
    rt_base: jax.Array  # [n] retransmit base interval (rounds, >= 1)
    acked: jax.Array    # [n] cumulative slots cleared by acks
    retx: jax.Array     # [n] cumulative retransmissions fired


class AdaptiveAcked(AckedDelivery):
    """AckedDelivery whose retransmit base interval is a per-node
    setpoint (``ack.retransmit_base``) the control plane moves.

    The adaptive-retransmit loop (scripts/control_suite.py chaos arm):
    during an outage no acks come back, so an AIMD controller on the
    ``ack_acked`` delta doubles the base toward ``hi`` — retransmissions
    stop hammering a dead partition; when acks resume the base decays
    additively back down.  Same at-least-once delivery as the fixed
    timer (the ring holds every unacked slot either way), strictly fewer
    wasted emissions.
    """

    actuator_names = ("ack.retransmit_base",)
    round_counter_names = ("ack_acked", "ack_retx", "ack_outstanding_now")

    def __init__(self, cfg: Config, ring_cap: int = 8,
                 retransmit_base: Optional[int] = None):
        super().__init__(cfg, ring_cap)
        self.retransmit_base0 = int(
            cfg.retransmit_interval if retransmit_base is None
            else retransmit_base)

    def init(self, cfg: Config, key: jax.Array) -> AdaptiveAckRow:
        base = init_rows(cfg.n_nodes, self.R)
        n = cfg.n_nodes
        return AdaptiveAckRow(
            **{f.name: getattr(base, f.name)
               for f in dataclasses.fields(AckRow)},
            rt_base=jnp.full((n,), self.retransmit_base0, jnp.int32),
            acked=jnp.zeros((n,), jnp.int32),
            retx=jnp.zeros((n,), jnp.int32))

    def handle_app_ack(self, cfg, me, row: AdaptiveAckRow, m: Msgs, key):
        hit = row.out_valid & (row.out_seq == m.data["seq"])
        row = row.replace(
            out_valid=row.out_valid & ~hit,
            acked=row.acked + jnp.sum(hit).astype(jnp.int32))
        return row, self.no_emit()

    def tick(self, cfg, me, row: AdaptiveAckRow, rnd, key):
        valid, age, attempt, due, dead = retransmit_backoff(
            row.out_valid, row.out_age, row.out_attempt, me,
            **backoff_kw(cfg, base=jnp.maximum(row.rt_base, 1)))
        row = row.replace(out_valid=valid, out_age=age,
                          out_attempt=attempt,
                          dead_lettered=row.dead_lettered + dead,
                          retx=row.retx + jnp.sum(due).astype(jnp.int32))
        em = self.emit(jnp.where(due, row.out_dst, -1),
                       self.typ("app"), cap=self.tick_emit_cap,
                       payload=row.out_payload, seq=row.out_seq)
        return row, em

    def round_counters(self, state: AdaptiveAckRow) -> Dict[str, jax.Array]:
        return {
            "ack_acked": jnp.sum(state.acked),
            "ack_retx": jnp.sum(state.retx),
            "ack_outstanding_now":
                jnp.sum(state.out_valid).astype(jnp.int32)}

    def health_counters(self, state: AdaptiveAckRow) -> Dict[str, jax.Array]:
        out = dict(super().health_counters(state))
        out["ack_retransmissions"] = jnp.sum(state.retx)
        return out

    def apply_setpoints(self, cfg, state: AdaptiveAckRow, values):
        if "ack.retransmit_base" in values:
            state = state.replace(rt_base=jnp.full_like(
                state.rt_base,
                jnp.asarray(values["ack.retransmit_base"], jnp.int32)))
        return state


# ---------------------------------------------------------- device taps

def dead_letter_total(state) -> jax.Array:
    """Device-side scalar: total dead-lettered slots summed across the
    protocol's layer stack (walks ``.lower`` wrappers, so Stacked /
    causal layers over an acked core all surface their give-ups).  The
    fault-space explorer's no-dead-letter-loss invariant reads this
    every round INSIDE the scan (verify/explorer.py) — zero when the
    state carries no ``dead_lettered`` field, so the invariant is
    vacuously true on un-acked protocols rather than an error."""
    total = jnp.int32(0)
    st = state
    while st is not None:
        arr = getattr(st, "dead_lettered", None)
        if arr is not None:
            total = total + jnp.sum(arr).astype(jnp.int32)
        st = getattr(st, "lower", None)
    return total


# ------------------------------------------------------------- host taps

def emit_ring_events(state, label: str = "ack") -> Dict[str, int]:
    """Host-side telemetry tap (ISSUE 4 satellite): fold the ring's
    degradation counters and emit one event per NONZERO total to the
    global sinks — ``<label>_send_ring_overflow`` for sends lost to a
    full outstanding ring (the ``store`` overflow that previously only
    bumped ``send_dropped``) and ``<label>_dead_letter`` for slots
    abandoned at the backoff give-up threshold.  Works on any row state
    carrying ``send_dropped`` / ``dead_lettered`` (AckRow,
    CausalAckedRow, CausalAckedSparseRow, DataPlane's DataRow), so
    soaks can assert on the event stream regardless of layer.  Returns
    the totals either way (zero-cost contract: no sinks, no events)."""
    from .. import telemetry
    out: Dict[str, int] = {}
    for event, field in (("send_ring_overflow", "send_dropped"),
                         ("dead_letter", "dead_lettered"),
                         # rpc promise-ring losses (ISSUE 8 satellite:
                         # qos/rpc.py call_dropped gets the same host
                         # event surface as ack-ring overflow)
                         ("call_ring_overflow", "call_dropped")):
        arr = getattr(state, field, None)
        if arr is None:
            continue
        total = int(np.asarray(jax.device_get(jnp.sum(arr))))
        out[event] = total
        if total:
            telemetry.emit_event(f"{label}_{event}", total=total)
    return out
