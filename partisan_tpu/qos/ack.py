"""Acknowledgement + retransmission — TPU-native rebuild of
``src/partisan_acknowledgement_backend.erl`` (ETS store of
{MessageClock, RescheduleableMessage}, store/ack/outstanding :49-78) plus
the manager's 1 s ``retransmit`` timer that re-sends everything outstanding
(partisan_pluggable_peer_service_manager.erl:905-942, 1299-1301).

Per-node state is a fixed ring of outstanding slots (SURVEY §2.11: an
"outstanding-message ring buffer per node; retransmit as a masked re-emit
each round").  Delivery is at-least-once exactly like the reference: a
retransmitted message that crosses its own ack is delivered twice; acks are
keyed by a per-origin monotone sequence number (the analog of the message
clock, pluggable :687, 737-741).

:class:`AckedDelivery` is the runnable layer (the `with_ack` suite group,
test/partisan_SUITE.erl:573).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import ring
from ..ops.msg import Msgs


@struct.dataclass
class AckRow:
    out_valid: jax.Array    # [R] outstanding slots
    out_dst: jax.Array      # [R]
    out_payload: jax.Array  # [R]
    out_seq: jax.Array      # [R] origin-scoped message id
    out_age: jax.Array      # [R] rounds since (re)transmission
    next_seq: jax.Array     # scalar — monotone id source
    seen: jax.Array         # [S] delivery counters per origin (test surface)
    send_dropped: jax.Array  # scalar — ctl_sends lost to a full ring
                             # (overflow surfaced, never silent)


def init_rows(n_nodes: int, ring_cap: int = 8) -> AckRow:
    n = n_nodes
    return AckRow(
        out_valid=jnp.zeros((n, ring_cap), bool),
        out_dst=jnp.zeros((n, ring_cap), jnp.int32),
        out_payload=jnp.zeros((n, ring_cap), jnp.int32),
        out_seq=jnp.zeros((n, ring_cap), jnp.int32),
        out_age=jnp.zeros((n, ring_cap), jnp.int32),
        next_seq=jnp.ones((n,), jnp.int32),
        seen=jnp.zeros((n, n_nodes), jnp.int32),
        send_dropped=jnp.zeros((n,), jnp.int32),
    )


def store(row: AckRow, dst, payload) -> Tuple[AckRow, jax.Array, jax.Array]:
    """acknowledgement_backend:store/2 — park an outgoing message until its
    ack arrives.  Returns (row', seq, stored_ok); stored_ok False = ring
    full (surfaced, never silent)."""
    ok, slot = ring.alloc(row.out_valid)
    seq = row.next_seq
    wr = lambda a, v: ring.masked_set(a, slot, ok, v)
    row = row.replace(
        out_valid=wr(row.out_valid, True),
        out_dst=wr(row.out_dst, dst),
        out_payload=wr(row.out_payload, payload),
        out_seq=wr(row.out_seq, seq),
        out_age=wr(row.out_age, 0),
        next_seq=seq + 1,
    )
    return row, seq, ok


def ack(row: AckRow, seq) -> AckRow:
    """acknowledgement_backend:ack/1 — clear the matching slot."""
    hit = row.out_valid & (row.out_seq == seq)
    return row.replace(out_valid=row.out_valid & ~hit)


def outstanding(row: AckRow) -> jax.Array:
    return jnp.sum(row.out_valid).astype(jnp.int32)


def retransmit_due(valid: jax.Array, age: jax.Array,
                   interval: int) -> Tuple[jax.Array, jax.Array]:
    """The shared retransmit-timer step (pluggable :905-942): ages valid
    slots, fires those at the interval, resets fired ages.  Returns
    (new_age, due).  Used by AckedDelivery and CausalAcked so the timer
    logic exists exactly once."""
    age = jnp.where(valid, age + 1, 0)
    due = valid & (age >= interval)
    return jnp.where(due, 0, age), due


class AckedDelivery(ProtocolBase):
    """``ctl_send`` ships an app message expecting an ack; unacked messages
    are re-sent every ``retransmit_interval`` rounds (pluggable :905-942).
    ``seen[origin]`` counts deliveries per origin — the store_proc assertion
    surface of ack_test."""

    msg_types = ("app", "app_ack", "ctl_send")

    def __init__(self, cfg: Config, ring_cap: int = 8):
        self.cfg = cfg
        self.R = ring_cap
        self.data_spec: Dict = {
            "payload": ((), jnp.int32),
            "seq": ((), jnp.int32),
            "peer": ((), jnp.int32),
        }
        self.emit_cap = 1
        self.tick_emit_cap = ring_cap

    def init(self, cfg: Config, key: jax.Array) -> AckRow:
        return init_rows(cfg.n_nodes, self.R)

    def handle_ctl_send(self, cfg, me, row: AckRow, m: Msgs, key):
        dst = m.data["peer"]
        row, seq, ok = store(row, dst, m.data["payload"])
        row = row.replace(send_dropped=row.send_dropped
                          + (~ok).astype(jnp.int32))
        em = self.emit(jnp.where(ok, dst, -1)[None], self.typ("app"),
                       payload=m.data["payload"], seq=seq)
        return row, em

    def handle_app(self, cfg, me, row: AckRow, m: Msgs, key):
        """Deliver + send_acknowledgement back to the origin (pluggable
        :1217-1227, 1612-1617)."""
        src = jnp.clip(m.src, 0, row.seen.shape[0] - 1)
        row = row.replace(seen=row.seen.at[src].add(1))
        return row, self.emit(m.src[None], self.typ("app_ack"),
                              seq=m.data["seq"])

    def handle_app_ack(self, cfg, me, row: AckRow, m: Msgs, key):
        return ack(row, m.data["seq"]), self.no_emit()

    def tick(self, cfg, me, row: AckRow, rnd, key):
        """Retransmit timer: re-emit every outstanding slot whose age hits
        the interval; age resets on retransmission."""
        age, due = retransmit_due(row.out_valid, row.out_age,
                                  cfg.retransmit_interval)
        row = row.replace(out_age=age)
        em = self.emit(jnp.where(due, row.out_dst, -1),
                       self.typ("app"), cap=self.tick_emit_cap,
                       payload=row.out_payload, seq=row.out_seq)
        return row, em
