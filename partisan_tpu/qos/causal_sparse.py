"""Sparse-clock causal delivery — ``src/partisan_causality_backend.erl``
re-laid over the fixed-slot sparse clocks of qos/dvv.py (ROADMAP 8: the
scaling escape from qos/causal.py's dense ``[A]`` clocks and ``[A, A]``
order buffers).

The dense rebuild (qos/causal.py) is exact but O(N³) in total state, so
it carries a construction-time N ≤ 128 guard.  This variant keeps the
reference's *actual* data shape: orddict clocks whose size tracks the
causal history, not the cluster (``src/partisan_vclock.erl`` — entries
exist only for actors that incremented), and an order buffer keyed by
the destinations actually written to (``src/partisan_causality_backend.erl``
:115-139 — an orddict from peer to last-sent clock).  Under fixed TPU
shapes that becomes:

  clock         K slots of (actor, counter) — K bounds the distinct
                WRITERS in one causal history (the DVV compression:
                growth bounded by writers, not replicas)
  order buffer  D slots of (dst, clock) — D bounds the distinct
                destinations one node sends causal messages to

Total state is O(N·D·K) — a causal label over thousands of nodes with a
handful of writers costs what the reference's orddicts cost.  Slot
exhaustion cannot be represented; every op surfaces an ``ok`` flag and
the row counts failures (``clock_overflow``, ``ob_dropped``) instead of
silently corrupting order — the engine's count-don't-silence rule
(SURVEY §7.3).  A message emitted past an exhausted order buffer ships
WITHOUT a dependency (delivered eagerly, order not enforced), which is
the explicit, counted analog of the reference crashing its per-label
gen_server on resource exhaustion.

Delivery semantics are bit-compatible with qos/causal.py for histories
that fit K/D — tests/test_causal_sparse.py drives both protocols through
identical scenarios and asserts identical logs — while
test_scales_past_dense_cap runs N = 512, four times the dense guard.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import ring
from ..ops.msg import Msgs
from . import ack as ack_mod
from . import dvv


@struct.dataclass
class CausalSparseRow:
    vc_act: jax.Array       # [K] local clock actors (-1 empty)
    vc_cnt: jax.Array       # [K] local clock counters
    ob_dst: jax.Array       # [D] order-buffer destination keys (-1 empty)
    ob_act: jax.Array       # [D, K] last clock sent per destination
    ob_cnt: jax.Array       # [D, K]
    ob_seq: jax.Array       # [D] next stream seq per destination (the
                            # CausalAckedSparse seq source; same key
                            # domain as the order buffer, so seqs cost
                            # no extra table)
    pend_valid: jax.Array   # [B] buffered messages
    pend_src: jax.Array     # [B]
    pend_payload: jax.Array  # [B]
    pend_has_dep: jax.Array  # [B] bool
    pend_dep_act: jax.Array  # [B, K] dependency clock
    pend_dep_cnt: jax.Array  # [B, K]
    pend_clk_act: jax.Array  # [B, K] message clock
    pend_clk_cnt: jax.Array  # [B, K]
    pend_seq: jax.Array     # [B] per-stream wire seq (0 = unsequenced)
    ls_src: jax.Array       # [S] last-seq table keys (senders; -1 empty)
    ls_seq: jax.Array       # [S] last seq delivered per sender
    log: jax.Array          # [L] delivered payloads, delivery order
    log_src: jax.Array      # [L]
    log_n: jax.Array        # scalar — total delivered (may exceed L)
    pend_dropped: jax.Array   # scalar — full pending ring
    ob_dropped: jax.Array     # scalar — sends past a full dst table
    clock_overflow: jax.Array  # scalar — clock ops that exceeded K slots
    ls_dropped: jax.Array     # scalar — sequenced deliveries past a full
                              # sender table (FIFO/dedup degrades to
                              # dominance-only for that sender, counted)


def init_rows(n_nodes: int, k_slots: int = 8, d_slots: int = 16,
              buf_cap: int = 8, log_cap: int = 16) -> CausalSparseRow:
    """Batched [N, ...] sparse causal state (one label)."""
    n, k, d = n_nodes, k_slots, d_slots
    return CausalSparseRow(
        vc_act=jnp.full((n, k), -1, jnp.int32),
        vc_cnt=jnp.zeros((n, k), jnp.int32),
        ob_dst=jnp.full((n, d), -1, jnp.int32),
        ob_act=jnp.full((n, d, k), -1, jnp.int32),
        ob_cnt=jnp.zeros((n, d, k), jnp.int32),
        ob_seq=jnp.ones((n, d), jnp.int32),
        pend_valid=jnp.zeros((n, buf_cap), bool),
        pend_src=jnp.zeros((n, buf_cap), jnp.int32),
        pend_payload=jnp.zeros((n, buf_cap), jnp.int32),
        pend_has_dep=jnp.zeros((n, buf_cap), bool),
        pend_dep_act=jnp.full((n, buf_cap, k), -1, jnp.int32),
        pend_dep_cnt=jnp.zeros((n, buf_cap, k), jnp.int32),
        pend_clk_act=jnp.full((n, buf_cap, k), -1, jnp.int32),
        pend_clk_cnt=jnp.zeros((n, buf_cap, k), jnp.int32),
        pend_seq=jnp.zeros((n, buf_cap), jnp.int32),
        ls_src=jnp.full((n, d), -1, jnp.int32),
        ls_seq=jnp.zeros((n, d), jnp.int32),
        log=jnp.full((n, log_cap), -1, jnp.int32),
        log_src=jnp.full((n, log_cap), -1, jnp.int32),
        log_n=jnp.zeros((n,), jnp.int32),
        pend_dropped=jnp.zeros((n,), jnp.int32),
        ob_dropped=jnp.zeros((n,), jnp.int32),
        clock_overflow=jnp.zeros((n,), jnp.int32),
        ls_dropped=jnp.zeros((n,), jnp.int32),
    )


def _ls_lookup(row: CausalSparseRow, src) -> Tuple[jax.Array, jax.Array]:
    """(known, last_seq) for a sender — 0 when absent (first stream
    message is seq 1)."""
    hit = (row.ls_src == src) & (src >= 0)
    return jnp.any(hit), jnp.sum(jnp.where(hit, row.ls_seq, 0))


def emit(row: CausalSparseRow, me: jax.Array, dst: jax.Array,
         sequenced: bool = False
         ) -> Tuple[CausalSparseRow, jax.Array, jax.Array, jax.Array,
                    jax.Array, jax.Array, jax.Array]:
    """The emit half (:115-139) on ONE node's row.  Returns
    (row', dep_act, dep_cnt, has_dep, clk_act, clk_cnt, seq).
    ``sequenced`` draws a per-destination stream seq from the order
    buffer's slot (CausalAckedSparse); seq 0 = unsequenced — the value
    shipped when the destination table is full (counted, and the
    receiver falls back to dominance-only delivery for that message)."""
    vc_act, vc_cnt, ok_inc = dvv.increment(row.vc_act, row.vc_cnt, me)
    # dependency = the order-buffer entry for dst (absent on first send)
    hit = (row.ob_dst == dst) & (dst >= 0)
    has_dep = jnp.any(hit)
    dep_act = jnp.where(
        has_dep, jnp.sum(jnp.where(hit[:, None], row.ob_act, 0), axis=0), -1)
    dep_cnt = jnp.sum(jnp.where(hit[:, None], row.ob_cnt, 0), axis=0)
    # store the NEW clock under dst: existing slot, else first free
    free = row.ob_dst < 0
    slot = jnp.where(has_dep, jnp.argmax(hit), jnp.argmax(free))
    ok_slot = has_dep | jnp.any(free)
    seq = jnp.where(ok_slot, row.ob_seq[slot], 0) if sequenced \
        else jnp.int32(0)
    row = row.replace(
        vc_act=vc_act, vc_cnt=vc_cnt,
        ob_dst=row.ob_dst.at[slot].set(
            jnp.where(ok_slot, dst, row.ob_dst[slot])),
        ob_act=row.ob_act.at[slot].set(
            jnp.where(ok_slot, vc_act, row.ob_act[slot])),
        ob_cnt=row.ob_cnt.at[slot].set(
            jnp.where(ok_slot, vc_cnt, row.ob_cnt[slot])),
        ob_seq=row.ob_seq.at[slot].add(
            jnp.where(ok_slot & bool(sequenced), 1, 0)),
        ob_dropped=row.ob_dropped + (~ok_slot).astype(jnp.int32),
        clock_overflow=row.clock_overflow + (~ok_inc).astype(jnp.int32),
    )
    return row, dep_act, dep_cnt, has_dep, vc_act, vc_cnt, seq


def receive(row: CausalSparseRow, src, payload, dep_act, dep_cnt, has_dep,
            clk_act, clk_cnt, seq=None) -> Tuple[CausalSparseRow, jax.Array]:
    """Buffer an incoming causal message (:143-154).  ``seq`` > 0 enables
    retransmission dedup (CausalAckedSparse); an already-delivered seq is
    ignored without counting as a drop."""
    seq = jnp.int32(0) if seq is None else seq
    _, last = _ls_lookup(row, src)
    dup = (seq > 0) & (seq <= last)
    ok, slot = ring.alloc(row.pend_valid)
    ok = ok & ~dup
    wr = lambda a, v: ring.masked_set(a, slot, ok, v)
    row = row.replace(
        pend_valid=wr(row.pend_valid, True),
        pend_src=wr(row.pend_src, src),
        pend_payload=wr(row.pend_payload, payload),
        pend_has_dep=wr(row.pend_has_dep, has_dep),
        pend_dep_act=wr(row.pend_dep_act, dep_act),
        pend_dep_cnt=wr(row.pend_dep_cnt, dep_cnt),
        pend_clk_act=wr(row.pend_clk_act, clk_act),
        pend_clk_cnt=wr(row.pend_clk_cnt, clk_cnt),
        pend_seq=wr(row.pend_seq, seq),
        pend_dropped=row.pend_dropped + (~ok & ~dup).astype(jnp.int32),
    )
    return row, ~ok & ~dup


def drain(row: CausalSparseRow, me: jax.Array
          ) -> Tuple[CausalSparseRow, jax.Array]:
    """Deliver every buffered message whose dependency the local clock
    dominates (:232-254); two passes so same-round chains resolve, like
    qos/causal.py's drain.  Sequenced messages (seq > 0) additionally
    deliver in exact per-sender stream order via the sparse last-seq
    table — dominance alone lets a successor overtake a delayed
    predecessor through transitive clock advancement (the dense
    backend's drain documents the same trap).  A sequenced delivery for
    a sender the full table cannot admit degrades to dominance-only and
    is counted (ls_dropped), never silent.

    Known, bounded degradation edge (ADVICE r3): a 'degraded' delivery
    carries no last-seq record, so a RETRANSMIT of the same message
    that crosses its ack cannot be recognized as a duplicate — under
    the acked composition, at-least-once can become at-least-twice for
    exactly the messages delivered while the sender table was full.
    Each such delivery is already counted in ls_dropped; senders that
    must not risk duplicates should size k_slots to their writer set
    (the sender side symmetrically REFUSES to send when its own tables
    are full, seq==0 refusal)."""
    B = row.pend_valid.shape[0]
    L = row.log.shape[0]

    def try_slot(i, carry):
        row, n = carry
        src_i = row.pend_src[i]
        known, last = _ls_lookup(row, src_i)
        seq_i = row.pend_seq[i]
        # retransmission that crossed its ack: drop without delivering
        dup = row.pend_valid[i] & (seq_i > 0) & (seq_i <= last)
        row = row.replace(pend_valid=row.pend_valid.at[i].set(
            row.pend_valid[i] & ~dup))
        free = row.ls_src < 0
        has_free = jnp.any(free)
        degraded = (seq_i > 0) & ~known & ~has_free
        in_order = (seq_i == 0) | (seq_i == last + 1) | degraded
        deliverable = row.pend_valid[i] & in_order & (
            ~row.pend_has_dep[i]
            | dvv.dominates(row.vc_act, row.vc_cnt,
                            row.pend_dep_act[i], row.pend_dep_cnt[i]))
        m_act, m_cnt, ok_m = dvv.merge(
            row.vc_act, row.vc_cnt,
            row.pend_clk_act[i], row.pend_clk_cnt[i])
        m_act, m_cnt, ok_i = dvv.increment(m_act, m_cnt, me)
        li = jnp.clip(row.log_n, 0, L - 1)
        record = deliverable & (row.log_n < L)
        # last-seq table update: existing slot keeps the max; an unknown
        # sender takes a free slot (degraded deliveries skip the table)
        track = deliverable & (seq_i > 0) & ~degraded
        ls_slot = jnp.where(known, jnp.argmax(row.ls_src == src_i),
                            jnp.argmax(free))
        row = row.replace(
            vc_act=jnp.where(deliverable, m_act, row.vc_act),
            vc_cnt=jnp.where(deliverable, m_cnt, row.vc_cnt),
            pend_valid=row.pend_valid.at[i].set(
                row.pend_valid[i] & ~deliverable),
            log=row.log.at[li].set(jnp.where(
                record, row.pend_payload[i], row.log[li])),
            log_src=row.log_src.at[li].set(jnp.where(
                record, row.pend_src[i], row.log_src[li])),
            log_n=row.log_n + deliverable.astype(jnp.int32),
            ls_src=row.ls_src.at[ls_slot].set(jnp.where(
                track, src_i, row.ls_src[ls_slot])),
            ls_seq=row.ls_seq.at[ls_slot].set(jnp.where(
                track, jnp.maximum(row.ls_seq[ls_slot], seq_i),
                row.ls_seq[ls_slot])),
            clock_overflow=row.clock_overflow
            + (deliverable & (~ok_m | ~ok_i)).astype(jnp.int32),
            ls_dropped=row.ls_dropped
            + (deliverable & degraded).astype(jnp.int32),
        )
        return row, n + deliverable.astype(jnp.int32)

    n0 = jnp.int32(0)
    row, n = jax.lax.fori_loop(0, B, try_slot, (row, n0))
    row, n = jax.lax.fori_loop(0, B, try_slot, (row, n))
    return row, n


class CausalDeliverySparse(ProtocolBase):
    """Runnable sparse-clock causal layer — the same ``ctl_csend`` /
    ``causal`` surface as qos/causal.py's CausalDelivery, wire fields in
    (actor, counter)-slot form.  No cluster-size cap: state scales with
    writers (K) and destinations (D), not N."""

    msg_types = ("causal", "ctl_csend")

    def __init__(self, cfg: Config, k_slots: int = 8, d_slots: int = 16,
                 buf_cap: int = 8, log_cap: int = 16):
        self.cfg = cfg
        self.K, self.D = k_slots, d_slots
        self.buf_cap, self.log_cap = buf_cap, log_cap
        k = k_slots
        self.data_spec: Dict = {
            "payload": ((), jnp.int32),
            "peer": ((), jnp.int32),
            "dep_act": ((k,), jnp.int32),
            "dep_cnt": ((k,), jnp.int32),
            "has_dep": ((), jnp.int32),
            "clk_act": ((k,), jnp.int32),
            "clk_cnt": ((k,), jnp.int32),
            "cdelay": ((), jnp.int32),
        }
        self.emit_cap = 1
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> CausalSparseRow:
        return init_rows(cfg.n_nodes, self.K, self.D,
                         self.buf_cap, self.log_cap)

    def handle_ctl_csend(self, cfg, me, row: CausalSparseRow, m: Msgs, key):
        dst = m.data["peer"]
        row, dep_act, dep_cnt, has_dep, clk_act, clk_cnt, _ = \
            emit(row, me, dst)
        em = self.emit(dst[None], self.typ("causal"),
                       payload=m.data["payload"],
                       dep_act=dep_act, dep_cnt=dep_cnt,
                       has_dep=has_dep.astype(jnp.int32),
                       clk_act=clk_act, clk_cnt=clk_cnt,
                       delay=m.data["cdelay"])
        return row, em

    def handle_causal(self, cfg, me, row: CausalSparseRow, m: Msgs, key):
        row, _ = receive(row, m.src, m.data["payload"],
                         m.data["dep_act"], m.data["dep_cnt"],
                         m.data["has_dep"] > 0,
                         m.data["clk_act"], m.data["clk_cnt"])
        return row, self.no_emit()

    def tick(self, cfg, me, row: CausalSparseRow, rnd, key):
        row, _ = drain(row, me)
        return row, self.no_emit(self.tick_emit_cap)


@struct.dataclass
class CausalAckedSparseRow:
    causal: CausalSparseRow
    # reemit storage: the wire copy of every unacked causal message —
    # byte-identical dep/clock on retransmit is why the backend stores
    # emitted messages instead of re-stamping (causality_backend
    # :107-113; same shape as the dense CausalAckedRow, clocks in
    # (actor, counter)-slot form)
    out_valid: jax.Array    # [R]
    out_dst: jax.Array      # [R]
    out_payload: jax.Array  # [R]
    out_dep_act: jax.Array  # [R, K]
    out_dep_cnt: jax.Array  # [R, K]
    out_has_dep: jax.Array  # [R]
    out_clk_act: jax.Array  # [R, K]
    out_clk_cnt: jax.Array  # [R, K]
    out_seq: jax.Array      # [R]
    out_age: jax.Array      # [R]
    out_attempt: jax.Array  # [R] retransmissions fired (backoff plane)
    send_dropped: jax.Array  # scalar — full-ring losses, surfaced
    dead_lettered: jax.Array  # scalar — backoff give-up slots (counted;
                              # abandoning a sequenced slot abandons the
                              # stream suffix — see qos/causal.py note)


class CausalAckedSparse(CausalDeliverySparse):
    """The `with_causal_send_and_ack` composition with sparse clocks:
    at-least-once via stored-wire-copy reemit + causal order, no cluster
    cap.  Stream seqs ride the order buffer's destination slots
    (ob_seq), so the acked layer adds no dense [A] table; the receiver's
    last-seq dedup table is sparse too (drain's ls_* fields).

    Delivery-count contract (ADVICE r3): at-least-once, exactly-once in
    the common case — EXCEPT for messages a receiver delivered in
    drain's counted 'degraded' mode (its ls table full): those carry no
    dedup record, so a reemit crossing the ack can deliver twice.  The
    sender side refuses new sends when its own tables are full rather
    than degrade (seq==0 refusal); the receiver-side overflow is the
    one place duplication can leak, bounded and counted (ls_dropped) —
    see drain's docstring."""

    msg_types = ("causal", "causal_ack", "ctl_csend")

    def __init__(self, cfg: Config, k_slots: int = 8, d_slots: int = 16,
                 buf_cap: int = 8, log_cap: int = 16, ring_cap: int = 8):
        super().__init__(cfg, k_slots, d_slots, buf_cap, log_cap)
        self.R = ring_cap
        self.data_spec = dict(self.data_spec)
        self.data_spec["seq"] = ((), jnp.int32)
        self.tick_emit_cap = ring_cap

    def init(self, cfg: Config, key: jax.Array) -> CausalAckedSparseRow:
        n, k, r = cfg.n_nodes, self.K, self.R
        return CausalAckedSparseRow(
            causal=super().init(cfg, key),
            out_valid=jnp.zeros((n, r), bool),
            out_dst=jnp.zeros((n, r), jnp.int32),
            out_payload=jnp.zeros((n, r), jnp.int32),
            out_dep_act=jnp.full((n, r, k), -1, jnp.int32),
            out_dep_cnt=jnp.zeros((n, r, k), jnp.int32),
            out_has_dep=jnp.zeros((n, r), bool),
            out_clk_act=jnp.full((n, r, k), -1, jnp.int32),
            out_clk_cnt=jnp.zeros((n, r, k), jnp.int32),
            out_seq=jnp.zeros((n, r), jnp.int32),
            out_age=jnp.zeros((n, r), jnp.int32),
            out_attempt=jnp.zeros((n, r), jnp.int32),
            send_dropped=jnp.zeros((n,), jnp.int32),
            dead_lettered=jnp.zeros((n,), jnp.int32),
        )

    def handle_ctl_csend(self, cfg, me, row: CausalAckedSparseRow,
                         m: Msgs, key):
        dst = m.data["peer"]
        # allocate the reemit slot FIRST: on a full ring the send must
        # not happen at all — stamping the clock/order buffer for a
        # message that never reaches the wire would wedge every later
        # message to this destination behind an unsatisfiable dependency
        ok, slot = ring.alloc(row.out_valid)
        crow, dep_act, dep_cnt, has_dep, clk_act, clk_cnt, seq = \
            emit(row.causal, me, dst, sequenced=True)
        # a destination the full ob table cannot admit gets seq 0 —
        # unsequenced means unackable (acks match by seq) and
        # non-dedupable at the receiver, so the at-least-once contract
        # cannot hold: refuse the send outright and count it, like the
        # full-ring case
        ok = ok & (seq > 0)
        crow = jax.tree_util.tree_map(
            lambda new, old: jnp.where(ok, new, old), crow, row.causal)
        seq = jnp.where(ok, seq, 0)
        wr = lambda a_, v: ring.masked_set(a_, slot, ok, v)
        row = row.replace(
            causal=crow,
            out_valid=wr(row.out_valid, True),
            out_dst=wr(row.out_dst, dst),
            out_payload=wr(row.out_payload, m.data["payload"]),
            out_dep_act=wr(row.out_dep_act, dep_act),
            out_dep_cnt=wr(row.out_dep_cnt, dep_cnt),
            out_has_dep=wr(row.out_has_dep, has_dep),
            out_clk_act=wr(row.out_clk_act, clk_act),
            out_clk_cnt=wr(row.out_clk_cnt, clk_cnt),
            out_seq=wr(row.out_seq, seq),
            out_age=wr(row.out_age, 0),
            out_attempt=wr(row.out_attempt, 0),
            send_dropped=row.send_dropped + (~ok).astype(jnp.int32),
        )
        em = self.emit(jnp.where(ok, dst, -1)[None], self.typ("causal"),
                       payload=m.data["payload"],
                       dep_act=dep_act, dep_cnt=dep_cnt,
                       has_dep=has_dep.astype(jnp.int32),
                       clk_act=clk_act, clk_cnt=clk_cnt,
                       seq=seq, delay=m.data["cdelay"])
        return row, em

    def handle_causal(self, cfg, me, row: CausalAckedSparseRow,
                      m: Msgs, key):
        # a message LOST to a full pending ring must NOT be acked — the
        # sender's reemit timer is the recovery path for exactly that
        crow, dropped = receive(row.causal, m.src, m.data["payload"],
                                m.data["dep_act"], m.data["dep_cnt"],
                                m.data["has_dep"] > 0,
                                m.data["clk_act"], m.data["clk_cnt"],
                                seq=m.data["seq"])
        ack_rep = self.emit(jnp.where(dropped, -1, m.src)[None],
                            self.typ("causal_ack"), seq=m.data["seq"])
        return row.replace(causal=crow), ack_rep

    def handle_causal_ack(self, cfg, me, row: CausalAckedSparseRow,
                          m: Msgs, key):
        # seqs are per-DESTINATION streams: every stream starts at 1, so
        # the ack must match (dst, seq), not seq alone — a seq-only
        # match would let node 2's ack of its seq-1 message clear the
        # still-unacked seq-1 message bound for node 3
        hit = row.out_valid & (row.out_dst == m.src) \
            & (m.data["seq"] > 0) & (row.out_seq == m.data["seq"])
        return row.replace(out_valid=row.out_valid & ~hit), self.no_emit()

    def tick(self, cfg, me, row: CausalAckedSparseRow, rnd, key):
        crow, _ = drain(row.causal, me)
        row = row.replace(causal=crow)
        # reemit the stored wire copies of unacked messages (backoff
        # timer; defaults bit-equal the fixed interval — ack.py)
        valid, age, attempt, due, dead = ack_mod.retransmit_backoff(
            row.out_valid, row.out_age, row.out_attempt, me,
            **ack_mod.backoff_kw(cfg))
        row = row.replace(out_valid=valid, out_age=age,
                          out_attempt=attempt,
                          dead_lettered=row.dead_lettered + dead)
        em = self.emit(jnp.where(due, row.out_dst, -1),
                       self.typ("causal"), cap=self.tick_emit_cap,
                       payload=row.out_payload,
                       dep_act=row.out_dep_act, dep_cnt=row.out_dep_cnt,
                       has_dep=row.out_has_dep.astype(jnp.int32),
                       clk_act=row.out_clk_act, clk_cnt=row.out_clk_cnt,
                       seq=row.out_seq)
        return row, em

    def health_counters(self, state: CausalAckedSparseRow):
        return {"ack_outstanding": jnp.sum(state.out_valid),
                "ack_send_dropped": jnp.sum(state.send_dropped),
                "ack_dead_lettered": jnp.sum(state.dead_lettered)}
