"""Sparse-clock causal delivery — ``src/partisan_causality_backend.erl``
re-laid over the fixed-slot sparse clocks of qos/dvv.py (ROADMAP 8: the
scaling escape from qos/causal.py's dense ``[A]`` clocks and ``[A, A]``
order buffers).

The dense rebuild (qos/causal.py) is exact but O(N³) in total state, so
it carries a construction-time N ≤ 128 guard.  This variant keeps the
reference's *actual* data shape: orddict clocks whose size tracks the
causal history, not the cluster (``src/partisan_vclock.erl`` — entries
exist only for actors that incremented), and an order buffer keyed by
the destinations actually written to (``src/partisan_causality_backend.erl``
:115-139 — an orddict from peer to last-sent clock).  Under fixed TPU
shapes that becomes:

  clock         K slots of (actor, counter) — K bounds the distinct
                WRITERS in one causal history (the DVV compression:
                growth bounded by writers, not replicas)
  order buffer  D slots of (dst, clock) — D bounds the distinct
                destinations one node sends causal messages to

Total state is O(N·D·K) — a causal label over thousands of nodes with a
handful of writers costs what the reference's orddicts cost.  Slot
exhaustion cannot be represented; every op surfaces an ``ok`` flag and
the row counts failures (``clock_overflow``, ``ob_dropped``) instead of
silently corrupting order — the engine's count-don't-silence rule
(SURVEY §7.3).  A message emitted past an exhausted order buffer ships
WITHOUT a dependency (delivered eagerly, order not enforced), which is
the explicit, counted analog of the reference crashing its per-label
gen_server on resource exhaustion.

Delivery semantics are bit-compatible with qos/causal.py for histories
that fit K/D — tests/test_causal_sparse.py drives both protocols through
identical scenarios and asserts identical logs — while
test_scales_past_dense_cap runs N = 512, four times the dense guard.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import ring
from ..ops.msg import Msgs
from . import dvv


@struct.dataclass
class CausalSparseRow:
    vc_act: jax.Array       # [K] local clock actors (-1 empty)
    vc_cnt: jax.Array       # [K] local clock counters
    ob_dst: jax.Array       # [D] order-buffer destination keys (-1 empty)
    ob_act: jax.Array       # [D, K] last clock sent per destination
    ob_cnt: jax.Array       # [D, K]
    pend_valid: jax.Array   # [B] buffered messages
    pend_src: jax.Array     # [B]
    pend_payload: jax.Array  # [B]
    pend_has_dep: jax.Array  # [B] bool
    pend_dep_act: jax.Array  # [B, K] dependency clock
    pend_dep_cnt: jax.Array  # [B, K]
    pend_clk_act: jax.Array  # [B, K] message clock
    pend_clk_cnt: jax.Array  # [B, K]
    log: jax.Array          # [L] delivered payloads, delivery order
    log_src: jax.Array      # [L]
    log_n: jax.Array        # scalar — total delivered (may exceed L)
    pend_dropped: jax.Array   # scalar — full pending ring
    ob_dropped: jax.Array     # scalar — sends past a full dst table
    clock_overflow: jax.Array  # scalar — clock ops that exceeded K slots


def init_rows(n_nodes: int, k_slots: int = 8, d_slots: int = 16,
              buf_cap: int = 8, log_cap: int = 16) -> CausalSparseRow:
    """Batched [N, ...] sparse causal state (one label)."""
    n, k, d = n_nodes, k_slots, d_slots
    return CausalSparseRow(
        vc_act=jnp.full((n, k), -1, jnp.int32),
        vc_cnt=jnp.zeros((n, k), jnp.int32),
        ob_dst=jnp.full((n, d), -1, jnp.int32),
        ob_act=jnp.full((n, d, k), -1, jnp.int32),
        ob_cnt=jnp.zeros((n, d, k), jnp.int32),
        pend_valid=jnp.zeros((n, buf_cap), bool),
        pend_src=jnp.zeros((n, buf_cap), jnp.int32),
        pend_payload=jnp.zeros((n, buf_cap), jnp.int32),
        pend_has_dep=jnp.zeros((n, buf_cap), bool),
        pend_dep_act=jnp.full((n, buf_cap, k), -1, jnp.int32),
        pend_dep_cnt=jnp.zeros((n, buf_cap, k), jnp.int32),
        pend_clk_act=jnp.full((n, buf_cap, k), -1, jnp.int32),
        pend_clk_cnt=jnp.zeros((n, buf_cap, k), jnp.int32),
        log=jnp.full((n, log_cap), -1, jnp.int32),
        log_src=jnp.full((n, log_cap), -1, jnp.int32),
        log_n=jnp.zeros((n,), jnp.int32),
        pend_dropped=jnp.zeros((n,), jnp.int32),
        ob_dropped=jnp.zeros((n,), jnp.int32),
        clock_overflow=jnp.zeros((n,), jnp.int32),
    )


def emit(row: CausalSparseRow, me: jax.Array, dst: jax.Array
         ) -> Tuple[CausalSparseRow, jax.Array, jax.Array, jax.Array,
                    jax.Array, jax.Array]:
    """The emit half (:115-139) on ONE node's row.  Returns
    (row', dep_act, dep_cnt, has_dep, clk_act, clk_cnt)."""
    vc_act, vc_cnt, ok_inc = dvv.increment(row.vc_act, row.vc_cnt, me)
    # dependency = the order-buffer entry for dst (absent on first send)
    hit = (row.ob_dst == dst) & (dst >= 0)
    has_dep = jnp.any(hit)
    dep_act = jnp.where(
        has_dep, jnp.sum(jnp.where(hit[:, None], row.ob_act, 0), axis=0), -1)
    dep_cnt = jnp.sum(jnp.where(hit[:, None], row.ob_cnt, 0), axis=0)
    # store the NEW clock under dst: existing slot, else first free
    free = row.ob_dst < 0
    slot = jnp.where(has_dep, jnp.argmax(hit), jnp.argmax(free))
    ok_slot = has_dep | jnp.any(free)
    row = row.replace(
        vc_act=vc_act, vc_cnt=vc_cnt,
        ob_dst=row.ob_dst.at[slot].set(
            jnp.where(ok_slot, dst, row.ob_dst[slot])),
        ob_act=row.ob_act.at[slot].set(
            jnp.where(ok_slot, vc_act, row.ob_act[slot])),
        ob_cnt=row.ob_cnt.at[slot].set(
            jnp.where(ok_slot, vc_cnt, row.ob_cnt[slot])),
        ob_dropped=row.ob_dropped + (~ok_slot).astype(jnp.int32),
        clock_overflow=row.clock_overflow + (~ok_inc).astype(jnp.int32),
    )
    return row, dep_act, dep_cnt, has_dep, vc_act, vc_cnt


def receive(row: CausalSparseRow, src, payload, dep_act, dep_cnt, has_dep,
            clk_act, clk_cnt) -> Tuple[CausalSparseRow, jax.Array]:
    """Buffer an incoming causal message (:143-154)."""
    ok, slot = ring.alloc(row.pend_valid)
    wr = lambda a, v: ring.masked_set(a, slot, ok, v)
    row = row.replace(
        pend_valid=wr(row.pend_valid, True),
        pend_src=wr(row.pend_src, src),
        pend_payload=wr(row.pend_payload, payload),
        pend_has_dep=wr(row.pend_has_dep, has_dep),
        pend_dep_act=wr(row.pend_dep_act, dep_act),
        pend_dep_cnt=wr(row.pend_dep_cnt, dep_cnt),
        pend_clk_act=wr(row.pend_clk_act, clk_act),
        pend_clk_cnt=wr(row.pend_clk_cnt, clk_cnt),
        pend_dropped=row.pend_dropped + (~ok).astype(jnp.int32),
    )
    return row, ~ok


def drain(row: CausalSparseRow, me: jax.Array
          ) -> Tuple[CausalSparseRow, jax.Array]:
    """Deliver every buffered message whose dependency the local clock
    dominates (:232-254); two passes so same-round chains resolve, like
    qos/causal.py's drain."""
    B = row.pend_valid.shape[0]
    L = row.log.shape[0]

    def try_slot(i, carry):
        row, n = carry
        deliverable = row.pend_valid[i] & (
            ~row.pend_has_dep[i]
            | dvv.dominates(row.vc_act, row.vc_cnt,
                            row.pend_dep_act[i], row.pend_dep_cnt[i]))
        m_act, m_cnt, ok_m = dvv.merge(
            row.vc_act, row.vc_cnt,
            row.pend_clk_act[i], row.pend_clk_cnt[i])
        m_act, m_cnt, ok_i = dvv.increment(m_act, m_cnt, me)
        li = jnp.clip(row.log_n, 0, L - 1)
        record = deliverable & (row.log_n < L)
        row = row.replace(
            vc_act=jnp.where(deliverable, m_act, row.vc_act),
            vc_cnt=jnp.where(deliverable, m_cnt, row.vc_cnt),
            pend_valid=row.pend_valid.at[i].set(
                row.pend_valid[i] & ~deliverable),
            log=row.log.at[li].set(jnp.where(
                record, row.pend_payload[i], row.log[li])),
            log_src=row.log_src.at[li].set(jnp.where(
                record, row.pend_src[i], row.log_src[li])),
            log_n=row.log_n + deliverable.astype(jnp.int32),
            clock_overflow=row.clock_overflow
            + (deliverable & (~ok_m | ~ok_i)).astype(jnp.int32),
        )
        return row, n + deliverable.astype(jnp.int32)

    n0 = jnp.int32(0)
    row, n = jax.lax.fori_loop(0, B, try_slot, (row, n0))
    row, n = jax.lax.fori_loop(0, B, try_slot, (row, n))
    return row, n


class CausalDeliverySparse(ProtocolBase):
    """Runnable sparse-clock causal layer — the same ``ctl_csend`` /
    ``causal`` surface as qos/causal.py's CausalDelivery, wire fields in
    (actor, counter)-slot form.  No cluster-size cap: state scales with
    writers (K) and destinations (D), not N."""

    msg_types = ("causal", "ctl_csend")

    def __init__(self, cfg: Config, k_slots: int = 8, d_slots: int = 16,
                 buf_cap: int = 8, log_cap: int = 16):
        self.cfg = cfg
        self.K, self.D = k_slots, d_slots
        self.buf_cap, self.log_cap = buf_cap, log_cap
        k = k_slots
        self.data_spec: Dict = {
            "payload": ((), jnp.int32),
            "peer": ((), jnp.int32),
            "dep_act": ((k,), jnp.int32),
            "dep_cnt": ((k,), jnp.int32),
            "has_dep": ((), jnp.int32),
            "clk_act": ((k,), jnp.int32),
            "clk_cnt": ((k,), jnp.int32),
            "cdelay": ((), jnp.int32),
        }
        self.emit_cap = 1
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> CausalSparseRow:
        return init_rows(cfg.n_nodes, self.K, self.D,
                         self.buf_cap, self.log_cap)

    def handle_ctl_csend(self, cfg, me, row: CausalSparseRow, m: Msgs, key):
        dst = m.data["peer"]
        row, dep_act, dep_cnt, has_dep, clk_act, clk_cnt = \
            emit(row, me, dst)
        em = self.emit(dst[None], self.typ("causal"),
                       payload=m.data["payload"],
                       dep_act=dep_act, dep_cnt=dep_cnt,
                       has_dep=has_dep.astype(jnp.int32),
                       clk_act=clk_act, clk_cnt=clk_cnt,
                       delay=m.data["cdelay"])
        return row, em

    def handle_causal(self, cfg, me, row: CausalSparseRow, m: Msgs, key):
        row, _ = receive(row, m.src, m.data["payload"],
                         m.data["dep_act"], m.data["dep_cnt"],
                         m.data["has_dep"] > 0,
                         m.data["clk_act"], m.data["clk_cnt"])
        return row, self.no_emit()

    def tick(self, cfg, me, row: CausalSparseRow, rnd, key):
        row, _ = drain(row, me)
        return row, self.no_emit(self.tick_emit_cap)
