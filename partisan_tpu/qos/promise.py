"""Standalone promise backend — ``src/partisan_promise_backend.erl``.

The reference module is a declared-but-skeletal gen_server owning an ETS
table (:1-78 — no public verbs beyond start_link); its intended role is
the reply store for rpc-style request/response flows.  This rebuild gives
the table the full verb set that role implies, as pure fixed-shape row
functions usable inside jitted handlers (every array is a per-node slice):

  create   park a pending promise under a caller-chosen ref
  resolve  fulfil it with a value — FIRST resolve wins; later resolves
           (duplicate acks) are counted, not applied
  tick     age pending promises; those older than ``timeout`` flip to
           TIMED_OUT (the reference analog: partisan_gen's call timeout,
           src/partisan_gen.erl:156-186 — timeout -> exit)
  query    read (found, state, value) by ref
  forget   free a slot for reuse once the caller has consumed it

:class:`Promises` wraps the table as an engine protocol so promises span
nodes: ``ctl_expect`` parks a promise locally, ``p_resolve`` messages
from any node fulfil it over the simulated overlay.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import ring
from ..ops.msg import Msgs

PENDING = 0
RESOLVED = 1
TIMED_OUT = 2


@struct.dataclass
class PromiseRow:
    valid: jax.Array         # [P] slot allocated
    ref: jax.Array           # [P]
    state: jax.Array         # [P] PENDING / RESOLVED / TIMED_OUT
    value: jax.Array         # [P]
    age: jax.Array           # [P] rounds pending
    dropped: jax.Array       # scalar — creates lost to a full table
    dup_resolved: jax.Array  # scalar — resolves of a non-pending ref
                             # (duplicate acks; counted, never applied)


def init_rows(n_nodes: int, cap: int = 8) -> PromiseRow:
    n = n_nodes
    return PromiseRow(
        valid=jnp.zeros((n, cap), bool),
        ref=jnp.zeros((n, cap), jnp.int32),
        state=jnp.zeros((n, cap), jnp.int32),
        value=jnp.zeros((n, cap), jnp.int32),
        age=jnp.zeros((n, cap), jnp.int32),
        dropped=jnp.zeros((n,), jnp.int32),
        dup_resolved=jnp.zeros((n,), jnp.int32),
    )


def create(row: PromiseRow, ref) -> Tuple[PromiseRow, jax.Array]:
    """Park a pending promise; returns (row', ok).  The table is keyed by
    ref like the reference's ETS table: a create whose ref already holds a
    slot is a no-op returning ok (so retried creates never double-allocate
    and query stays single-valued).  Full table => ok False and the drop
    is counted."""
    exists = jnp.any(row.valid & (row.ref == ref))
    free_ok, slot = ring.alloc(row.valid)
    do = ~exists & free_ok
    wr = lambda a, v: ring.masked_set(a, slot, do, v)
    row = row.replace(
        valid=wr(row.valid, True),
        ref=wr(row.ref, ref),
        state=wr(row.state, PENDING),
        value=wr(row.value, 0),
        age=wr(row.age, 0),
        dropped=row.dropped + (~exists & ~free_ok).astype(jnp.int32),
    )
    return row, exists | do


def resolve(row: PromiseRow, ref, value) -> PromiseRow:
    """First resolve wins; a resolve matching no PENDING slot (already
    resolved, timed out, or never created) increments dup_resolved."""
    hit = row.valid & (row.ref == ref) & (row.state == PENDING)
    any_hit = jnp.any(hit)
    return row.replace(
        state=jnp.where(hit, RESOLVED, row.state),
        value=jnp.where(hit, value, row.value),
        dup_resolved=row.dup_resolved + (~any_hit).astype(jnp.int32),
    )


def tick(row: PromiseRow, timeout: int) -> PromiseRow:
    """Age pending promises; expire those reaching ``timeout`` rounds."""
    pending = row.valid & (row.state == PENDING)
    age = jnp.where(pending, row.age + 1, row.age)
    expired = pending & (age >= timeout)
    return row.replace(age=age,
                       state=jnp.where(expired, TIMED_OUT, row.state))


def query(row: PromiseRow, ref) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(found, state, value) for ``ref`` — found False => state/value
    undefined (0)."""
    hit = row.valid & (row.ref == ref)
    found = jnp.any(hit)
    pick = lambda a: jnp.sum(jnp.where(hit, a, 0))
    return found, pick(row.state), pick(row.value)


def forget(row: PromiseRow, ref) -> PromiseRow:
    """Free the slot once consumed (the ETS delete)."""
    hit = row.valid & (row.ref == ref)
    return row.replace(valid=row.valid & ~hit)


_tick_rows = tick  # the method below shadows the name inside the class


class Promises(ProtocolBase):
    """Cross-node promises over the overlay: ``ctl_expect`` parks a
    pending promise at this node; any node's ``p_resolve {ref, value}``
    message fulfils it; unresolved promises time out after
    ``timeout`` rounds (counted per state, queryable per ref)."""

    msg_types = ("p_resolve", "ctl_expect", "ctl_resolve")

    def __init__(self, cfg: Config, cap: int = 8, timeout: int = 16):
        self.cfg = cfg
        self.P = cap
        self.timeout = timeout
        self.data_spec: Dict = {
            "ref": ((), jnp.int32),
            "value": ((), jnp.int32),
            "peer": ((), jnp.int32),
        }
        self.emit_cap = 1
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> PromiseRow:
        return init_rows(cfg.n_nodes, self.P)

    def handle_ctl_expect(self, cfg, me, row: PromiseRow, m: Msgs, key):
        row, _ = create(row, m.data["ref"])
        return row, self.no_emit()

    def handle_ctl_resolve(self, cfg, me, row: PromiseRow, m: Msgs, key):
        """Host-injected: ship a resolution to the promise's owner."""
        return row, self.emit(m.data["peer"][None], self.typ("p_resolve"),
                              ref=m.data["ref"], value=m.data["value"])

    def handle_p_resolve(self, cfg, me, row: PromiseRow, m: Msgs, key):
        return resolve(row, m.data["ref"], m.data["value"]), self.no_emit()

    def tick(self, cfg, me, row: PromiseRow, rnd, key):
        return _tick_rows(row, self.timeout), \
            self.no_emit(self.tick_emit_cap)
