"""Public facade — the ``partisan_peer_service.erl`` analog.

The reference facade (src/partisan_peer_service.erl:24-42) exposes
join/leave/members/forward_message against whatever manager is configured.
Here the same verbs operate on a :class:`~partisan_tpu.engine.World` by
injecting control messages into the in-flight buffer; effects take place when
the next round runs.  All helpers are pure (world in, world out) so they can
be composed inside jit or driven from the host / the Erlang port bridge.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .engine import ProtocolBase, World
from .ops import msg as msgops


def send_ctl(world: World, proto: ProtocolBase, node: int, typ_name: str,
             delay: int = 0, channel=None, **data) -> World:
    """Inject one control message addressed to ``node`` itself — the
    host-side verb entry point every façade call (and the test harness)
    goes through."""
    em = proto.emit(jnp.asarray([node], jnp.int32), proto.typ(typ_name),
                    cap=1, delay=delay, channel=channel, **data)
    msgs, _ = msgops.inject(world.msgs, em, src=node, born=world.rnd)
    return world.replace(msgs=msgs)


_ctl = send_ctl


def join(world: World, proto: ProtocolBase, node: int, peer: int,
         delay: int = 0) -> World:
    """node joins the cluster via peer (partisan_peer_service:join/1 :52)."""
    return _ctl(world, proto, node, "ctl_join", delay=delay,
                **{proto.ctl_peer_field: peer})


def leave(world: World, proto: ProtocolBase, node: int, target: int | None = None) -> World:
    """leave/0-1: self-leave when target is None (partisan_peer_service.erl:62-70)."""
    return _ctl(world, proto, node, "ctl_leave",
                **{proto.ctl_peer_field: node if target is None else target})


def cluster(world: World, proto: ProtocolBase,
            pairs: Sequence[Tuple[int, int]],
            stagger: int = 0) -> World:
    """Pairwise joins, the test-harness clustering pattern
    (test/partisan_support.erl cluster/3).  ``stagger > 0`` trickles joins
    in batches of ``stagger`` per round (the reference's sequential join +
    avoid_rush jitter, pluggable :1423-1458) to keep join storms under the
    contact node's inbox cap.

    All joins are injected as ONE batched buffer write: per-join injects
    are eager device ops, and at N in the thousands the per-dispatch
    latency (~100 ms through the TPU tunnel) would dwarf everything else.
    """
    if not pairs:
        return world
    k = len(pairs)
    nodes = jnp.asarray([p[0] for p in pairs], jnp.int32)
    peers = jnp.asarray([p[1] for p in pairs], jnp.int32)
    delays = jnp.asarray([(i // stagger) if stagger else 0
                          for i in range(k)], jnp.int32)
    em = proto.emit(nodes, proto.typ("ctl_join"), cap=k, delay=delays,
                    **{proto.ctl_peer_field: peers})
    msgs, dropped = msgops.inject(world.msgs, em, src=nodes, born=world.rnd)
    if not isinstance(dropped, jax.core.Tracer) and int(dropped) > 0:
        # host path only — inside jit the caller owns overflow accounting
        raise ValueError(
            f"in-flight buffer too small for the join batch "
            f"({int(dropped)} of {k} joins dropped); raise out_cap")
    return world.replace(msgs=msgs)


def members(world: World, proto: ProtocolBase, node: int) -> jax.Array:
    """[N] bool membership mask as seen by ``node``
    (partisan_peer_service:members/0)."""
    row = jax.tree_util.tree_map(lambda x: x[node], world.state)
    return proto.member_mask(row)


def sync_join(world: World, proto: ProtocolBase, node: int, peer: int,
              step, max_rounds: int = 100) -> Tuple[World, int]:
    """Blocking join — partisan_peer_service:sync_join via the pluggable
    manager's sync_joins list + fully_connected check
    (partisan_pluggable_peer_service_manager.erl:953-963, 1461-1480).
    Runs rounds until BOTH sides list each other as members (the
    simulator's "all channels x parallelism connections up" analog:
    connections are implicit in membership here).  Returns
    (world, rounds_taken); raises TimeoutError when the join does not
    complete within ``max_rounds`` — the reference's gen_server call
    timeout."""
    world = join(world, proto, node, peer)
    for r in range(1, max_rounds + 1):
        world, _ = step(world)
        if bool(members(world, proto, node)[peer]) and \
                bool(members(world, proto, peer)[node]):
            return world, r
    raise TimeoutError(
        f"sync_join({node} -> {peer}) incomplete after {max_rounds} rounds")


# --------------------------------------------------------------- data plane
# (partisan_peer_service:forward_message, the reference facade's data verb;
#  requires the protocol to be Stacked(manager, DataPlane) — see
#  models/dataplane.py)


def _dataplane_of(proto: ProtocolBase):
    """Locate the DataPlane in a (possibly lower-nested) stack.  Returns
    (dp, state_path): ``state_path`` is the attribute path from
    ``world.state`` to the DataRow subtree, mirroring the walk through
    the Stacked tree (upper layers nest on the lower side only)."""
    from .models.dataplane import DataPlane
    p, path = proto, []
    while p is not None:
        up = getattr(p, "upper", None)
        if isinstance(up, DataPlane):
            return up, path + ["upper"]
        path.append("lower")
        p = getattr(p, "lower", None)
    raise TypeError("protocol has no DataPlane layer; build it as "
                    "Stacked(manager, DataPlane(cfg))")


def forward_message(world: World, proto: ProtocolBase, src: int, dst: int,
                    server_ref: int = 0, payload=(), ack: bool = False,
                    channel=None, partition_key: int = -1,
                    delay: int = 0) -> World:
    """forward_message/5 (partisan_peer_service.erl:24-42 facade over
    pluggable :183-248): ship ``payload`` from ``src`` to ``server_ref``
    on ``dst`` over the simulated overlay.  The send-side pipeline (clock
    stamping, ack store) runs inside the step at the source row.
    One-record convenience over :func:`forward_batch` (single pipeline —
    the two entry points cannot diverge)."""
    return forward_batch(world, proto, [{
        "src": src, "dst": dst, "server_ref": server_ref,
        "payload": payload, "ack": ack, "channel": channel,
        "partition_key": partition_key, "delay": delay}])


def forward_batch(world: World, proto: ProtocolBase, records) -> World:
    """Batched forward_message — ONE buffer write for the whole batch
    (the port bridge's command-batching contract, SURVEY §7.3).  Each
    record is a dict with keys src, dst, server_ref, payload and optional
    ack / channel / partition_key / delay."""
    if not records:
        return world
    dp, _ = _dataplane_of(proto)
    k = len(records)
    srcs = jnp.asarray([r["src"] for r in records], jnp.int32)
    em = proto.emit(
        srcs, proto.typ("ctl_fwd"), cap=k,
        channel=jnp.asarray([r.get("channel", 0) or 0 for r in records],
                            jnp.int32),
        delay=jnp.asarray([r.get("delay", 0) for r in records], jnp.int32),
        peer=jnp.asarray([r["dst"] for r in records], jnp.int32),
        server_ref=jnp.asarray([r.get("server_ref", 0) for r in records],
                               jnp.int32),
        payload=jnp.asarray(np.stack([dp.pad_payload(r.get("payload", ()))
                                      for r in records])),
        ack=jnp.asarray([int(bool(r.get("ack", False))) for r in records],
                        jnp.int32),
        partition_key=jnp.asarray([r.get("partition_key", -1)
                                   for r in records], jnp.int32))
    msgs, dropped = msgops.inject(world.msgs, em, src=srcs, born=world.rnd)
    if not isinstance(dropped, jax.core.Tracer) and int(dropped) > 0:
        raise ValueError(f"in-flight buffer too small for the forward "
                         f"batch ({int(dropped)} of {k} dropped); raise "
                         f"out_cap")
    return world.replace(msgs=msgs)


def set_knob(world: World, control, name: str, value: int) -> World:
    """Runtime override of a controller setpoint — the
    ``partisan_config:set/2`` analog (partisan_config.erl set/2).  Pins
    controller ``name`` (a :class:`control.plane.ControlSpec` entry) to
    ``value``: the setpoint jumps immediately and the override flag
    holds it there until :func:`clear_knob`.  Host-side (world in,
    world out) like every façade verb; apply at a window boundary —
    the port bridge's ``set_knob`` command routes here.  Unknown knob
    names raise the spec's named ValueError."""
    if world.aux is None:
        raise ValueError(
            "set_knob: world carries no ControlPlane (attach one with "
            "control.plane.attach_plane and build the step with "
            "control=spec)")
    from .control.plane import set_knob as _set
    return world.replace(aux=_set(world.aux, control, name, value))


def clear_knob(world: World, control, name: str) -> World:
    """Release a :func:`set_knob` pin; the controller resumes closed-
    loop from the pinned value."""
    if world.aux is None:
        raise ValueError("clear_knob: world carries no ControlPlane")
    from .control.plane import clear_knob as _clear
    return world.replace(aux=_clear(world.aux, control, name))


def receive_messages(world: World, proto: ProtocolBase, node: int,
                     cursor: int = 0):
    """Drain app messages delivered to ``node`` since ``cursor`` — the
    receive half of the check_forward_message round-trip
    (test/partisan_SUITE.erl:1955).  Returns (records, new_cursor, lost);
    records are (src, server_ref, payload_words).  The DataPlane may sit
    anywhere in a lower-nested stack — the state subtree is resolved by
    the same walk forward_message uses."""
    dp, path = _dataplane_of(proto)
    sub = world.state
    for attr in path:
        sub = getattr(sub, attr)
    return dp.received(sub, node, cursor)
