"""Public facade — the ``partisan_peer_service.erl`` analog.

The reference facade (src/partisan_peer_service.erl:24-42) exposes
join/leave/members/forward_message against whatever manager is configured.
Here the same verbs operate on a :class:`~partisan_tpu.engine.World` by
injecting control messages into the in-flight buffer; effects take place when
the next round runs.  All helpers are pure (world in, world out) so they can
be composed inside jit or driven from the host / the Erlang port bridge.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp

from .config import Config
from .engine import ProtocolBase, World
from .ops import msg as msgops


def send_ctl(world: World, proto: ProtocolBase, node: int, typ_name: str,
             delay: int = 0, **data) -> World:
    """Inject one control message addressed to ``node`` itself — the
    host-side verb entry point every façade call (and the test harness)
    goes through."""
    em = proto.emit(jnp.asarray([node], jnp.int32), proto.typ(typ_name),
                    cap=1, delay=delay, **data)
    msgs, _ = msgops.inject(world.msgs, em, src=node)
    return world.replace(msgs=msgs)


_ctl = send_ctl


def join(world: World, proto: ProtocolBase, node: int, peer: int,
         delay: int = 0) -> World:
    """node joins the cluster via peer (partisan_peer_service:join/1 :52)."""
    return _ctl(world, proto, node, "ctl_join", delay=delay,
                **{proto.ctl_peer_field: peer})


def leave(world: World, proto: ProtocolBase, node: int, target: int | None = None) -> World:
    """leave/0-1: self-leave when target is None (partisan_peer_service.erl:62-70)."""
    return _ctl(world, proto, node, "ctl_leave",
                **{proto.ctl_peer_field: node if target is None else target})


def cluster(world: World, proto: ProtocolBase,
            pairs: Sequence[Tuple[int, int]],
            stagger: int = 0) -> World:
    """Pairwise joins, the test-harness clustering pattern
    (test/partisan_support.erl cluster/3).  ``stagger > 0`` trickles joins
    in batches of ``stagger`` per round (the reference's sequential join +
    avoid_rush jitter, pluggable :1423-1458) to keep join storms under the
    contact node's inbox cap.

    All joins are injected as ONE batched buffer write: per-join injects
    are eager device ops, and at N in the thousands the per-dispatch
    latency (~100 ms through the TPU tunnel) would dwarf everything else.
    """
    if not pairs:
        return world
    k = len(pairs)
    nodes = jnp.asarray([p[0] for p in pairs], jnp.int32)
    peers = jnp.asarray([p[1] for p in pairs], jnp.int32)
    delays = jnp.asarray([(i // stagger) if stagger else 0
                          for i in range(k)], jnp.int32)
    em = proto.emit(nodes, proto.typ("ctl_join"), cap=k, delay=delays,
                    **{proto.ctl_peer_field: peers})
    msgs, dropped = msgops.inject(world.msgs, em, src=nodes)
    if not isinstance(dropped, jax.core.Tracer) and int(dropped) > 0:
        # host path only — inside jit the caller owns overflow accounting
        raise ValueError(
            f"in-flight buffer too small for the join batch "
            f"({int(dropped)} of {k} joins dropped); raise out_cap")
    return world.replace(msgs=msgs)


def members(world: World, proto: ProtocolBase, node: int) -> jax.Array:
    """[N] bool membership mask as seen by ``node``
    (partisan_peer_service:members/0)."""
    row = jax.tree_util.tree_map(lambda x: x[node], world.state)
    return proto.member_mask(row)
