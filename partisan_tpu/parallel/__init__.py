from .mesh import (assert_collective_budget, collective_stats, make_mesh,
                   node_sharding, place_world, shard_spec)
from .dataplane import (init_sharded_world, make_sharded_run_scan,
                        make_sharded_step, place_sharded_world,
                        shard_align_msgs, sharded_out_cap)
