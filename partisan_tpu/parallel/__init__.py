from .mesh import (assert_collective_budget, collective_stats, make_mesh,
                   node_sharding, place_world, shard_spec)
from .dataplane import (init_sharded_world, make_sharded_run_scan,
                        make_sharded_step, place_sharded_world,
                        shard_align_msgs, sharded_out_cap)
from .dense_dataplane import (make_sharded_dense_round, make_sharded_runner,
                              place_sharded,
                              run_sharded, run_sharded_chunked,
                              run_sharded_staggered, sharded_dense_init,
                              sharded_pt_init, sharded_scamp_init, to_dense,
                              to_dense_scamp, to_pt_dense)
