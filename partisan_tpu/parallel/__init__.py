from .mesh import make_mesh, node_sharding, place_world, shard_spec
