"""Multi-chip driver for the HBM rumor plane (VERDICT r3 #9).

``ops/rumor_kernel_hbm.py`` is the INTRA-chip fast path: a pallas
kernel streaming HBM blocks through VMEM.  Across chips the same
epidemic round is a layout question, not a kernel question: the state
shards on the node axis and the per-(round, fanout) partner permutation
— a ROW translation q composed with an intra-row bit rotation r (the
halo decomposition, rumor_kernel_hbm.py docstring) — becomes a
``jnp.roll`` over the sharded row axis, which XLA lowers to
collective-permutes over ICI.  This module is that global program,
written once in jnp with the SAME host-side draws as the kernel
(fold_in(PRNGKey(0xB10C), round)), so its outputs are bit-identical to
``rumor_run_hbm(churn=0)`` — asserted by tests/test_mesh.py and the
driver's ``dryrun_multichip``.

On a real v5e pod the composition is: this program jitted over the
mesh, with the per-shard body replaced by the pallas kernel via
shard_map once per-chip N exceeds the jnp path's efficiency — the
cross-chip contract (who sends which halo rows to whom) is exactly what
this module pins down and the dryrun validates.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..ops.rumor_kernel import CELL


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def rumor_plane_run(inf: jax.Array, hot: jax.Array, alive: jax.Array,
                    n_rounds: int, n: int, fanout: int = 2,
                    start_rnd: jax.Array | int = 0):
    """``n_rounds`` of the HBM kernel's exact round semantics on bool
    [n] arrays (stop_k = 1 push-ack feedback, one-round-delayed restart
    reseed, churn = 0).  Shard the inputs on the node axis and the row
    translation rides XLA collectives."""
    R = n // CELL
    key = jax.random.fold_in(jax.random.PRNGKey(0xB10C),
                             jnp.asarray(start_rnd, jnp.int32))
    kq, kr, kp, _ = jax.random.split(key, 4)
    q = jax.random.randint(kq, (n_rounds, fanout), 0, R)
    r = jax.random.randint(kr, (n_rounds, fanout), 1, CELL)
    pz = jax.random.randint(kp, (n_rounds,), 0, n)

    def perm_roll(x, qi, ri):
        rows = x.reshape(R, CELL)
        rows = jnp.roll(rows, qi, axis=0)      # cross-shard translation
        rows = jnp.roll(rows, ri, axis=1)      # intra-row rotation
        return rows.reshape(-1)

    def body(carry, xs):
        inf, hot, prev_hot_alive, i = carry
        qi, ri, pzi = xs
        send = hot & alive
        hit = jnp.zeros_like(send)
        for j in range(fanout):
            hit = hit | perm_roll(send, qi[j], ri[j])
        new_inf = inf | (hit & alive)
        dup = perm_roll(inf, -qi[0], -ri[0]) & send
        newly = new_inf & ~inf
        new_hot = (hot | newly) & ~dup
        dead = (i > 0) & (prev_hot_alive == 0)
        onehot = jnp.arange(n) == pzi
        new_inf = new_inf | (onehot & dead)
        new_hot = new_hot | (onehot & dead)
        pha = jnp.sum(new_hot & alive).astype(jnp.int32)
        return (new_inf, new_hot, pha, i + 1), None

    (inf, hot, _, _), _ = jax.lax.scan(
        body, (inf, hot, jnp.int32(1), jnp.int32(0)), (q, r, pz))
    return inf, hot
