"""Explicit SPMD dataplane — the shard_map round driver (ISSUE 2 tentpole).

``parallel/mesh.py`` is the IMPLICIT story: annotate shardings, jit the
single-program step, let XLA's partitioner infer collectives.  Measured
on the dense round that inference costs 11 all-gathers per round with no
ceiling anywhere (VERDICT r5) — multi-chip perf was "hope XLA infers it".
This module is the EXPLICIT story: one manual-SPMD round whose only
cross-chip traffic is

  * ONE bucketed ``lax.all_to_all`` carrying exactly the cross-shard
    messages (every field packed into a single int32 matrix so the
    exchange is one collective, not one per field), and
  * ONE ``lax.psum`` of the stacked per-round metric partials.

Everything else is the UNSHARDED round restricted to a row slice: the
node axis (state, keys, alive, partition) and the flat message buffer
both shard on axis 0, each device routes its received messages with the
same ``ops/msg.py`` lexsort-route-scatter (shard-local destinations,
GLOBAL connection hashes — ``build_inbox_idx(n_total=, node_base=)``)
and delivers/ticks/collects through the same ``engine.make_round_kernels``
the single-program step compiles.  Result: bit-identical states and
metrics to ``engine.make_step`` (tests/test_mesh.py asserts it on the
8-device CPU mesh), with a communication contract you can ASSERT —
``mesh.assert_collective_budget`` red-lines the compiled round if it
ever grows a third collective or exceeds the byte ceiling.

Invariant: **a message lives on its src's shard** from emission until
the round it becomes deliverable; the exchange moves it to its dst's
shard in the same round it is delivered, so the src-side fault masks
(sender aliveness, sender partition id — stamped into a ghost column
and checked against the receiver's after the exchange) and the dst-side
masks each read only shard-local rows.  Host-side injectors
(peer_service.cluster / send_ctl) write messages at arbitrary buffer
rows, so worlds built by them must pass :func:`shard_align_msgs` before
:func:`jax.device_put` — :func:`place_sharded_world` does both.

Deliberate non-goals (use the implicit path / unsharded step instead):
``interpose_recv`` ('$delay' re-holds would strand a message on its
dst's shard, breaking the invariant for later src-side masks — passing
it raises a ValueError at build time pointing at the supported
alternative, a ``verify.chaos.ChaosSchedule`` drop/delay event applied
pre-exchange; ISSUE 4) and ``capture_wire`` (the per-round host dump
would sync the mesh every round).  The trace plane is instead the ``flight`` parameter (ISSUE 3):
a :class:`telemetry.flight.FlightSpec` makes each shard record its
post-exchange wire slice into a per-shard device ring carried through
the step — shard-local arithmetic only, ZERO extra collectives, so the
asserted 2-collective budget holds with the recorder on; the host
flushes one transfer per window and the per-round entry MULTISET equals
the unsharded trace (tests/test_flight.py).  ``interpose_send`` is
supported — it runs on the shard-local collect output, which is exactly
the global buffer slice.

With ``parallelism > 1`` the random (un-keyed) lane draw hashes LOCAL
buffer positions where the unsharded step hashes global ones: lane
assignment is a uniform modeling draw either way (dispatch_pid picks
uniformly, partisan_util.erl:142-201), so sharded and unsharded runs
are distributionally — not bit — identical there; partition-KEYED lanes
(the deterministic contract) match bit-for-bit.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ..config import Config
from ..engine import (ProtocolBase, World, autotune, default_out_cap,
                      make_round_kernels, init_world)
from ..ops import msg as msgops
from ..ops.msg import Msgs
from .. import prng
from .mesh import Mesh, NODE_AXIS, place_world

# fixed per-message core columns of the packed exchange matrix
_CORE = ("src", "dst", "typ", "channel", "lane", "delay", "born")

# metric keys, in the engine's order, that are SUMS over shards (one
# stacked psum); "round" is replicated and "xshard_dropped" is the
# dataplane's own bucket-overflow counter (0 unless bucket_cap is
# deliberately undersized — counted, never silent)
_SUM_KEYS = ("delivered", "sent", "inbox_overflow", "out_dropped",
             "routed", "fault_dropped", "inflight", "alive",
             "unhandled", "xshard_dropped")

# chaos-plane counters appended to the stacked psum when a ChaosSchedule
# is compiled in (still ONE psum — the stack grows three rows)
_CHAOS_KEYS = ("chaos_dropped", "chaos_delayed", "chaos_duplicated")


def _field_layout(data_spec):
    """Column layout of the packed [cap, F] int32 exchange matrix:
    valid, the 7 core int32 fields, then each data field (sorted name
    order) flattened to its trailing size.  Returns (names, widths,
    total)."""
    names, widths = ["valid"] + list(_CORE), [1] * (1 + len(_CORE))
    for name in sorted(data_spec):
        spec = data_spec[name]
        shape, dt = tuple(spec[0]), spec[1]
        w = 1
        for d in shape:
            w *= d
        d32 = jnp.dtype(dt)
        if not (d32 == jnp.dtype(bool)
                or (d32.kind in "iu" and d32.itemsize <= 4)):
            raise ValueError(
                f"dataplane exchange packs bool and <=32-bit integer "
                f"payload fields only; {name} is {dt}")
        names.append(name)
        widths.append(w)
    return names, widths, sum(widths)


def _pack(m: Msgs, data_spec, extra=()):
    """Msgs -> one [cap, F(+len(extra))] int32 matrix (uint32 payloads
    bitcast, not value-converted) so the cross-shard exchange is ONE
    all_to_all.  ``extra`` columns ride along (ghost fields, e.g. the
    sender's partition id)."""
    cap = m.cap
    cols = [m.valid.astype(jnp.int32).reshape(cap, 1)]
    cols += [getattr(m, f).reshape(cap, 1) for f in _CORE]
    for name in sorted(data_spec):
        x = m.data[name].reshape(cap, -1)
        if x.dtype == jnp.uint32:
            # bitcast, not value-convert: values >= 2^31 must survive
            x = jax.lax.bitcast_convert_type(x, jnp.int32)
        elif x.dtype != jnp.int32:
            # narrower ints / bool round-trip exactly through int32
            x = x.astype(jnp.int32)
        cols.append(x)
    cols += [jnp.asarray(e, jnp.int32).reshape(cap, 1) for e in extra]
    return jnp.concatenate(cols, axis=1)


def _unpack(packed: jax.Array, data_spec, n_extra: int = 0):
    """Inverse of :func:`_pack`; returns (Msgs, extra_columns)."""
    cap = packed.shape[0]
    i = 0

    def take(w):
        nonlocal i
        out = packed[:, i:i + w]
        i += w
        return out

    valid = take(1)[:, 0] != 0
    core = {f: take(1)[:, 0] for f in _CORE}
    data = {}
    for name in sorted(data_spec):
        spec = data_spec[name]
        shape, dt = tuple(spec[0]), spec[1]
        w = 1
        for d in shape:
            w *= d
        x = take(w).reshape((cap,) + shape)
        if jnp.dtype(dt) == jnp.dtype(jnp.uint32):
            x = jax.lax.bitcast_convert_type(x, jnp.uint32)
        elif jnp.dtype(dt) != jnp.dtype(jnp.int32):
            x = x.astype(dt)
        data[name] = x
    extra = [take(1)[:, 0] for _ in range(n_extra)]
    return Msgs(valid=valid, data=data, **core), extra


def sharded_out_cap(cfg: Config, proto: ProtocolBase, n_shards: int,
                    out_cap: Optional[int] = None) -> int:
    """Global in-flight buffer capacity rounded up to a multiple of the
    shard count (each shard carries an equal slice).  Capacity becomes
    per-shard under the dataplane — overflow compacts per shard, counted
    in out_dropped exactly like the global compact."""
    cfg = autotune(cfg, proto)
    cap = out_cap or default_out_cap(cfg, proto)
    return -(-cap // n_shards) * n_shards


def shard_align_msgs(m: Msgs, n_nodes: int, n_shards: int,
                     cap: Optional[int] = None) -> Msgs:
    """Host-side re-pack of a flat buffer so every valid message sits in
    its src's shard slice (the dataplane invariant) — required after
    host injectors (peer_service.cluster / send_ctl / inject) which
    write at arbitrary free slots.  Stable per shard, so within-
    connection FIFO order is preserved.  Raises loudly if a shard's
    slice overflows (host path — the caller owns capacity)."""
    cap = cap or m.cap
    assert cap % n_shards == 0 and n_nodes % n_shards == 0
    loc, nl = cap // n_shards, n_nodes // n_shards
    M = m.cap
    shard = jnp.where(m.valid,
                      jnp.clip(m.src, 0, n_nodes - 1) // nl, n_shards)
    order = jnp.argsort(shard, stable=True)
    sk = shard[order]
    starts = jnp.searchsorted(sk, jnp.arange(n_shards))
    pos = jnp.arange(M) - starts[jnp.clip(sk, 0, n_shards - 1)]
    ok = (sk < n_shards) & (pos < loc)
    if bool(jnp.any((sk < n_shards) & ~ok)):
        raise ValueError(
            f"shard_align_msgs: a shard slice of {loc} slots overflowed "
            f"re-packing {int(jnp.sum(m.valid))} messages; raise out_cap")
    tgt = jnp.where(ok, sk * loc + jnp.clip(pos, 0, loc - 1), cap)

    def put(x):
        fresh = jnp.zeros((cap + 1,) + x.shape[1:], x.dtype)
        return fresh.at[tgt].set(x[order])[:cap]

    out = jax.tree_util.tree_map(put, m)
    return out.replace(valid=put(m.valid))


def init_sharded_world(cfg: Config, proto: ProtocolBase, mesh: Mesh,
                       out_cap: Optional[int] = None) -> World:
    """init_world with the buffer capacity rounded for the mesh, leaves
    device_put with their node shardings.  N must divide evenly."""
    D = mesh.devices.size
    assert cfg.n_nodes % D == 0, (cfg.n_nodes, D)
    world = init_world(cfg, proto,
                       out_cap=sharded_out_cap(cfg, proto, D, out_cap))
    return place_world(world, mesh)


def place_sharded_world(world: World, cfg: Config, mesh: Mesh) -> World:
    """shard_align_msgs + place_world — the one call a host-built world
    (cluster joins injected, ctl traffic queued) needs before the
    sharded step."""
    D = mesh.devices.size
    world = world.replace(
        msgs=shard_align_msgs(world.msgs, cfg.n_nodes, D))
    return place_world(world, mesh)


def make_sharded_step(
    cfg: Config,
    proto: ProtocolBase,
    mesh: Mesh,
    out_cap: Optional[int] = None,
    interpose_send: Optional[Callable] = None,
    interpose_recv: Optional[Callable] = None,
    randomize_delivery: bool = True,
    donate: bool = True,
    bucket_cap: Optional[int] = None,
    flight=None,
    chaos=None,
    control=None,
    trace=None,
    latency=None,
) -> Callable[..., Tuple]:
    """Compile one explicitly-sharded simulation round.

    ``control`` (a :class:`control.plane.ControlSpec`) compiles the
    ISSUE-10 adaptive control plane into the round.  Controller inputs
    come from the post-psum TOTALS of the one stacked metrics reduce —
    already global and identical on every shard — so each shard updates
    its REPLICATED ControlPlane copy (``world.aux``, [n_ctl] leaves,
    P() specs) identically and the collective budget is UNCHANGED:
    still 1 all-to-all + 1 all-reduce, 0 all-gathers, controllers on.
    ``control=None`` traces zero extra ops (byte-identical programs).

    Per-round cross-shard traffic: ONE all_to_all of
    ``[D, bucket_cap, F]`` int32 (F = packed field columns + 1 ghost)
    plus ONE psum of the stacked metric partials — assert it with
    ``mesh.assert_collective_budget(step.lower(world).compile())``.

    ``bucket_cap`` bounds how many messages one shard may send to one
    other shard per round; the default (the full per-shard buffer
    slice) is lossless.  Tighter caps trade exchange bytes for counted
    ``xshard_dropped`` overflow — same contract as every other fixed
    shape in the simulator (SURVEY §7.3).

    ``flight`` (a :class:`telemetry.flight.FlightSpec`) turns on the
    in-scan message flight recorder: each shard records its
    post-exchange wire slice (``spec.cap`` slots/round/shard) into a
    per-shard ring — the step then takes and returns a
    :class:`telemetry.flight.FlightRing` built by
    ``make_flight_ring(spec, n_shards=D)`` + ``place_flight_ring``:
    ``step(world, fring) -> (world, fring, metrics)``.  Recording adds
    no collectives (the budget above is unchanged); flush on the host,
    outside the round.

    ``chaos`` (a :class:`verify.chaos.ChaosSchedule`) compiles the fault
    campaign into the round, bit-identically to
    ``engine.make_step(chaos=)``: the node plane folds each shard's OWN
    alive/partition rows against the static event table, and the message
    plane edits the ready buffer PRE-exchange — while every message
    still sits on its src's shard, so chaos-delayed re-holds and
    duplicate copies join the shard-local held traffic without breaking
    the residency invariant.  Both planes are shard-local arithmetic:
    the 2-collective budget holds chaos-on (the metric psum stack grows
    three ``chaos_*`` rows, still ONE psum).  Byzantine events (ISSUE
    19) run at the same pre-exchange point — a forged message
    materializes only on the shard owning its claimed src (the same
    residency every real message obeys) and the four Byzantine counters
    ride the same stacked psum.  A ``verify.chaos.DynamicSchedule`` is
    rejected here (explicit ValueError): the traced-table step arity is
    the unsharded explorer's contract — run found schedules through the
    static path.

    ``latency`` (a :class:`verify.latency.LatencyPlane`, ISSUE 19)
    stamps the geo/WAN region-pair one-way delay (+ deterministic
    field-hashed jitter, never buffer positions — the sharded/unsharded
    bit-parity discipline) onto fresh emissions exactly where the
    transport delays are stamped.  Pure shard-local arithmetic: zero
    added collectives, zero new metric keys, and ``latency=None``
    compiles byte-identical programs.

    ``trace`` (a :class:`telemetry.tracer.TraceSpec`) turns on the
    ISSUE-16 message lifecycle tracer: each shard records its own span
    events (held / chaos verdicts / EXCHANGED cross-shard hops /
    delivered / emitted / protocol-state transitions via
    ``proto.trace_taps``) into its slice of a
    :class:`telemetry.tracer.TraceRing` built by
    ``make_trace_ring(spec, n_shards=D)`` + ``place_trace_ring`` — the
    step takes the ring after any flight ring.  Recording is
    shard-local arithmetic only: the 2-collective budget holds with the
    tracer on, and ``trace=None`` compiles byte-identical programs.

    ``interpose_recv`` is rejected here (a clear ``ValueError`` at build
    time): the recv hook runs AFTER routing on the unsharded path, which
    under the dataplane is post-exchange — a hook that bumps ``delay``
    ('$delay') would re-hold the message on its DESTINATION's shard,
    breaking the src-residency invariant the src-side fault masks and
    the next round's exchange depend on (the message would silently
    never re-deliver).  Express recv-side drops and delays as chaos
    ``KIND_DROP``/``KIND_DELAY`` events instead — they run pre-exchange
    on both paths — or use the unsharded ``engine.make_step``."""
    if interpose_recv is not None:
        raise ValueError(
            "make_sharded_step does not support interpose_recv: a "
            "'$delay' re-hold fired after the exchange would strand the "
            "message on its destination's shard (silent loss — it could "
            "never re-deliver through the src-side held split).  Use a "
            "verify.chaos.ChaosSchedule drop/delay event instead "
            "(applied pre-exchange, bit-identical on both paths), or "
            "the unsharded engine.make_step.")
    cfg = autotune(cfg, proto)
    N = cfg.n_nodes
    K = cfg.inbox_cap
    T = proto.tick_emit_cap
    D = int(mesh.devices.size)
    assert N % D == 0, f"n_nodes {N} must divide the mesh size {D}"
    n_loc = N // D
    out_cap = sharded_out_cap(cfg, proto, D, out_cap)
    m_loc = out_cap // D
    B = bucket_cap or m_loc
    kernels = make_round_kernels(cfg, proto, n_loc)
    n_types = kernels.n_types
    rc_names = tuple(proto.round_counter_names)
    _, _, F = _field_layout(proto.data_spec)
    pk_field = "partition_key" if "partition_key" in proto.data_spec \
        else None
    mono_mask = None
    if cfg.monotonic_channels:
        mono_mask = jnp.asarray(
            [c in cfg.monotonic_channels for c in cfg.channels],
            dtype=bool)

    def _interp(fn, m, rnd, world):
        import inspect
        if len(inspect.signature(fn).parameters) >= 3:
            return fn(m, rnd, world)   # sees the SHARD-LOCAL world slice
        return fn(m, rnd)

    if flight is not None:
        from ..telemetry.flight import (flight_partition_specs,
                                        flight_record)
    if trace is not None:
        from ..telemetry import tracer as _tr
        if trace.seq_field is not None:
            if trace.seq_field not in proto.data_spec:
                raise ValueError(
                    f"make_sharded_step: trace seq_field "
                    f"{trace.seq_field!r} is not a payload field of "
                    f"{type(proto).__name__} "
                    f"(has: {sorted(proto.data_spec)})")
            if tuple(proto.data_spec[trace.seq_field][0]) != ():
                raise ValueError(
                    f"make_sharded_step: trace seq_field "
                    f"{trace.seq_field!r} must be scalar per message, "
                    f"has trailing shape "
                    f"{proto.data_spec[trace.seq_field][0]}")
    if chaos is not None:
        from ..verify.chaos import (DynamicSchedule, apply_chaos_msgs,
                                    apply_chaos_nodes, counter_keys)
        if isinstance(chaos, DynamicSchedule):
            raise ValueError(
                "make_sharded_step does not support DynamicSchedule: "
                "the traced-table arity (step(world, chaos_table)) is "
                "the unsharded explorer's contract.  Compile the found "
                "schedule through the static chaos= path instead — the "
                "static planes are bit-identical here.")
        chaos.validate(n_nodes=cfg.n_nodes)
    if latency is not None:
        from ..verify.latency import apply_latency as apply_latency_plane
        latency.validate(cfg.n_nodes)
    if control is not None:
        from ..control.plane import (metric_names as ctl_metric_names,
                                     plane_metrics, setpoint_values,
                                     update_plane, validate_control)

    def exchange(now: Msgs, src_part: jax.Array):
        """Bucket the local ready messages by destination shard and
        swap buckets with ONE packed all_to_all.  Returns the received
        flat buffer (src-shard-major, preserving each shard's local
        order — the same relative order the global route's stable sort
        would see) + ghost columns + overflow count."""
        packed = _pack(now, proto.data_spec, extra=(src_part,))
        M = now.cap
        shard = jnp.where(now.valid,
                          jnp.clip(now.dst, 0, N - 1) // n_loc, D)
        order = jnp.argsort(shard, stable=True)
        sk = shard[order]
        starts = jnp.searchsorted(sk, jnp.arange(D))
        pos = jnp.arange(M) - starts[jnp.clip(sk, 0, D - 1)]
        ok = (sk < D) & (pos < B)
        xdrop = jnp.sum((sk < D) & ~ok).astype(jnp.int32)
        tgt = jnp.where(ok, sk * B + jnp.clip(pos, 0, B - 1), D * B)
        buck = jnp.zeros((D * B + 1, F + 1), jnp.int32)
        buck = buck.at[tgt].set(packed[order])[:D * B]
        recv = jax.lax.all_to_all(
            buck.reshape(D, B, F + 1), NODE_AXIS,
            split_axis=0, concat_axis=0).reshape(D * B, F + 1)
        got, (gpart,) = _unpack(recv, proto.data_spec, n_extra=1)
        return got, gpart, xdrop

    def step_body(world: World, fring=None, tring=None):
        rnd = world.rnd
        me = jax.lax.axis_index(NODE_AXIS)
        node_base = (me * n_loc).astype(jnp.int32)
        node_ids = node_base + jnp.arange(n_loc, dtype=jnp.int32)
        if chaos is not None:
            # chaos node plane over this shard's OWN rows (global ids):
            # the same fold the unsharded step runs, restricted to a
            # slice — zero collectives, carried in the sharded world
            alive2, part2 = apply_chaos_nodes(
                chaos, rnd, world.alive, world.partition, node_ids)
            world = world.replace(alive=alive2, partition=part2)
        state, msgs = world.state, world.msgs
        rkeys = jax.vmap(prng.round_key, in_axes=(0, None))(world.keys,
                                                            rnd)

        # -- held split (delay plane), exactly the unsharded shape; held
        #    traffic stays on its src's shard
        inflight = jnp.sum(msgs.valid).astype(jnp.int32)
        held = msgs.replace(valid=msgs.valid & (msgs.delay > 0),
                            delay=jnp.maximum(msgs.delay - 1, 0))
        now = msgs.replace(valid=msgs.valid & (msgs.delay <= 0))
        ready = jnp.sum(now.valid).astype(jnp.int32)

        # -- lifecycle tracer (ISSUE 16): shard-local span events into
        #    this shard's ring slice.  One payload-hash pass covers the
        #    carried buffer (held/chaos captures — pre-exchange planes
        #    edit `valid` in place); the exchange RELOCATES slots, so
        #    the post-exchange buffer hashes separately below.
        tcaps = []
        if trace is not None:
            seq_all = _tr.msg_seq(trace, msgs)
            tcaps.append(_tr.wire_capture(
                trace, _tr.EV_HELD, held, keep=held.valid, seq=seq_all))

        # -- chaos message plane, PRE-exchange: every message is still
        #    on its src's shard here, so re-holds and duplicate copies
        #    join the shard-local held traffic (residency invariant
        #    kept) and the arithmetic matches the unsharded step's
        #    capture point bit for bit
        chaos_counts = None
        if chaos is not None:
            if trace is not None:
                pre_chaos = now
                now, chaos_held, chaos_counts, cmasks = apply_chaos_msgs(
                    chaos, rnd, now, want_masks=True,
                    node_lo=node_base, node_hi=node_base + n_loc)
                tcaps.append(_tr.wire_capture(
                    trace, _tr.EV_CHAOS_DROPPED, pre_chaos,
                    keep=cmasks["dropped"], seq=seq_all))
                tcaps.append(_tr.wire_capture(
                    trace, _tr.EV_CHAOS_DELAYED, pre_chaos,
                    keep=cmasks["delayed"], seq=seq_all))
            else:
                now, chaos_held, chaos_counts = apply_chaos_msgs(
                    chaos, rnd, now,
                    node_lo=node_base, node_hi=node_base + n_loc)
            if chaos_held is not None:
                held = msgops.concat(held, chaos_held)

        # -- src-side fault plane: sender aliveness reads only local
        #    rows (the shard invariant); the sender's partition id is
        #    stamped into a ghost column and checked on the dst side
        src_row = jnp.clip(now.src - node_base, 0, n_loc - 1)
        now = now.replace(valid=now.valid & world.alive[src_row])
        src_part = world.partition[src_row]

        # -- connection lanes + monotonic elide run PRE-exchange: every
        #    message of a (src, dst, channel, lane) connection is still
        #    on the src's shard here, so keep-latest sees the whole group
        # trace-lint: allow(config-fork): lane dispatch compiled in or out per config at build time, mirrors engine.make_step
        if cfg.parallelism > 1:
            now = msgops.dispatch(
                now, cfg.parallelism,
                now.data[pk_field] if pk_field else None,
                salt=jnp.uint32(rnd))
        if mono_mask is not None:
            now = msgops.monotonic_elide(now, N, mono_mask,
                                         cfg.n_channels, cfg.parallelism)

        # -- THE exchange: one bucketed all_to_all
        now, gpart, xdrop = exchange(now, src_part)
        if trace is not None:
            # EXCHANGED: slots that just crossed a shard boundary (src
            # resides on another shard) — the sharded-only lifecycle
            # hop; same-shard traffic is not a hop.  Post-exchange
            # positions are new, so hash once here and reuse for the
            # DELIVERED capture (route preserves positions).
            seq_got = _tr.msg_seq(trace, now)
            xmask = now.valid & (jnp.clip(now.src, 0, N - 1)
                                 // n_loc != me)
            tcaps.append(_tr.wire_capture(
                trace, _tr.EV_EXCHANGED, now, keep=xmask, seq=seq_got))

        # -- dst-side fault plane (receiver aliveness + partition),
        #    local rows again
        dst_row = jnp.clip(now.dst - node_base, 0, n_loc - 1)
        now = now.replace(valid=now.valid & world.alive[dst_row]
                          & (world.partition[dst_row] == gpart))
        survived = jnp.sum(now.valid).astype(jnp.int32)
        fault_dropped = ready - survived - xdrop
        if chaos_counts is not None:
            # re-held (chaos-delayed) messages are deferred, not dropped
            fault_dropped = fault_dropped - chaos_counts["chaos_delayed"]
            if "chaos_forged" in chaos_counts:
                # forged slots were never in `ready` — mirror the engine
                fault_dropped = (fault_dropped
                                 + chaos_counts["chaos_forged"])

        # -- flight recorder (ISSUE 3): this shard's post-exchange wire
        #    slice into its local ring row — the same capture point as
        #    the unsharded step's (post fault plane / lanes / exchange,
        #    pre-route); shard-local arithmetic, zero collectives
        if flight is not None:
            fring = flight_record(fring, flight, now, rnd)

        # -- route on the shard-local slice: local inbox cells, GLOBAL
        #    connection hashes (bit-identical cell + order assignment)
        route_key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed ^ 0x5EED), rnd) \
            if randomize_delivery else None
        ib_idx, ib_valid, overflow = msgops.build_inbox_idx(
            now, n_loc, K, key=route_key,
            n_channels=cfg.n_channels, parallelism=cfg.parallelism,
            n_total=N, node_base=node_base)
        nowp = jax.tree_util.tree_map(
            lambda x: jnp.concatenate(
                [x, jnp.zeros((1,) + x.shape[1:], x.dtype)]), now)
        if trace is not None:
            # DELIVERED: the engine's scatter of the index map back
            # onto (post-exchange) buffer positions
            didx = jnp.where(ib_valid, ib_idx, now.cap).reshape((-1,))
            dmask = jnp.zeros((now.cap + 1,), bool).at[didx].set(
                True)[:now.cap]
            tcaps.append(_tr.wire_capture(
                trace, _tr.EV_DELIVERED, now, keep=dmask, seq=seq_got))
            pre_state = world.state

        # -- deliver + tick + collect: the engine's own kernels over the
        #    local rows (handlers see global node ids)
        dkeys = jax.vmap(prng.decision_key, in_axes=(0, None))(rkeys, 1)
        delivered = kernels.deliver_batch(state, nowp, ib_idx, ib_valid,
                                          dkeys, node_ids)
        state = delivered[0]
        mid_state = state
        tkeys = jax.vmap(prng.decision_key, in_axes=(0, None))(rkeys, 2)

        def tick(i, r, k):
            r2, em = proto.tick(cfg, i, r, rnd, k)
            return r2, msgops.pad_to(em, T)
        state, temits = jax.vmap(tick, in_axes=(0, 0, 0))(
            node_ids, state, tkeys)
        new, src_row2, node_dropped = kernels.collect(
            delivered, temits, node_ids, rnd)
        new = new.replace(valid=new.valid & world.alive[src_row2])
        # trace-lint: allow(config-fork): delay stamping traces in only when configured, mirrors engine.make_step
        if cfg.ingress_delay or cfg.egress_delay:
            new = new.replace(
                delay=new.delay + cfg.ingress_delay + cfg.egress_delay)
        # geo/WAN latency plane (ISSUE 19): stamped once at emission over
        # message fields only — bit-identical to the unsharded stamp
        if latency is not None:
            new = apply_latency_plane(latency, new)
        if interpose_send is not None:
            new = _interp(interpose_send, new, rnd, world)
        if trace is not None:
            # EMITTED (post send-interposition) + protocol-state taps —
            # identical shapes to engine.make_step, over local rows
            tcaps.append(_tr.wire_capture(trace, _tr.EV_EMITTED, new))
            for ev_name, tap in proto.trace_taps(
                    cfg, pre_state, mid_state, state, rnd):
                tcaps.append(_tr.tap_capture(
                    trace, _tr.EVENT_CODES[ev_name], node_ids, tap))
            tring = _tr.trace_record(tring, trace, tcaps, rnd)
        out = msgops.concat(new, held)
        out, dropped = msgops.compact(out, m_loc)
        dropped = dropped + node_dropped

        inbox_typ = nowp.typ[jnp.where(ib_valid, ib_idx, nowp.cap - 1)]
        rows = [
            jnp.sum(ib_valid).astype(jnp.int32),            # delivered
            out.count(),                                    # sent
            overflow,                                       # inbox_overflow
            dropped,                                        # out_dropped
            survived,                                       # routed
            fault_dropped,
            inflight,
            jnp.sum(world.alive).astype(jnp.int32),         # alive
            jnp.sum(ib_valid & ((inbox_typ < 0)
                                | (inbox_typ >= n_types))
                    ).astype(jnp.int32),                    # unhandled
            xdrop,                                          # xshard_dropped
        ]
        if chaos_counts is not None:
            rows += [chaos_counts[k] for k in chaos_keys]
        if rc_names:
            # workload-plane round counters (ISSUE 8): shard-local
            # partial sums riding the SAME stacked psum — the collective
            # budget is unchanged with the workload plane enabled.
            rc = proto.round_counters(state)
            rows += [jnp.asarray(rc[k], jnp.int32).reshape(())
                     for k in rc_names]
        partials = jnp.stack(rows)
        totals = jax.lax.psum(partials, NODE_AXIS)          # ONE psum
        metrics = {"round": rnd}
        metrics.update({k: totals[i] for i, k in enumerate(sum_keys)})
        # -- adaptive control plane (ISSUE 10): inputs are the post-psum
        #    TOTALS — already global, identical on every shard — so each
        #    shard updates its replicated plane copy identically (the
        #    sharded==unsharded trajectory parity).  Shard-local
        #    arithmetic: ZERO added collectives.
        if control is not None:
            plane = update_plane(control, world.aux, metrics)
            state = proto.apply_setpoints(
                cfg, state, setpoint_values(control, plane))
            metrics.update(plane_metrics(control, plane))
            new_world = world.replace(state=state, msgs=out,
                                      rnd=rnd + 1, aux=plane)
        else:
            new_world = world.replace(state=state, msgs=out, rnd=rnd + 1)
        if flight is not None:
            if trace is not None:
                return new_world, fring, tring, metrics
            return new_world, fring, metrics
        if trace is not None:
            return new_world, tring, metrics
        return new_world, metrics

    # chaos counter rows: the byzantine-free key set is exactly the
    # pre-ISSUE-19 one, so existing chaos-on programs stay byte-stable
    chaos_keys = counter_keys(chaos) if chaos is not None else ()
    sum_keys = _SUM_KEYS + chaos_keys + rc_names

    def spec_of(x):
        return P(NODE_AXIS) if getattr(x, "ndim", 0) >= 1 else P()

    def world_specs(world):
        specs = jax.tree_util.tree_map(spec_of, world)
        if control is not None:
            # the ControlPlane in aux is REPLICATED ([n_ctl] leaves have
            # no node axis); spec_of would row-shard them
            specs = specs.replace(aux=jax.tree_util.tree_map(
                lambda x: P(), world.aux))
        return specs

    metric_specs = {"round": P()}
    metric_specs.update({k: P() for k in sum_keys})
    if control is not None:
        validate_control(control, ("round",) + sum_keys,
                         proto.actuator_names, where="make_sharded_step")
        metric_specs.update({k: P() for k in ctl_metric_names(control)})

    if flight is not None and trace is not None:
        fr_specs = flight_partition_specs(NODE_AXIS)
        tr_specs = _tr.trace_partition_specs(NODE_AXIS)

        @functools.partial(jax.jit,
                           donate_argnums=(0, 1, 2) if donate else ())
        def sharded_flight_trace_step(world: World, fring, tring):
            in_specs = world_specs(world)
            return shard_map(step_body, mesh=mesh,
                             in_specs=(in_specs, fr_specs, tr_specs),
                             out_specs=(in_specs, fr_specs, tr_specs,
                                        metric_specs),
                             check_rep=False)(world, fring, tring)

        return sharded_flight_trace_step

    if flight is not None:
        fr_specs = flight_partition_specs(NODE_AXIS)

        @functools.partial(jax.jit,
                           donate_argnums=(0, 1) if donate else ())
        def sharded_flight_step(world: World, fring):
            in_specs = world_specs(world)
            return shard_map(step_body, mesh=mesh,
                             in_specs=(in_specs, fr_specs),
                             out_specs=(in_specs, fr_specs,
                                        metric_specs),
                             check_rep=False)(world, fring)

        return sharded_flight_step

    if trace is not None:
        tr_specs = _tr.trace_partition_specs(NODE_AXIS)

        @functools.partial(jax.jit,
                           donate_argnums=(0, 1) if donate else ())
        def sharded_trace_step(world: World, tring):
            in_specs = world_specs(world)

            def body(world, tring):
                return step_body(world, None, tring)
            return shard_map(body, mesh=mesh,
                             in_specs=(in_specs, tr_specs),
                             out_specs=(in_specs, tr_specs,
                                        metric_specs),
                             check_rep=False)(world, tring)

        return sharded_trace_step

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def sharded_step(world: World):
        in_specs = world_specs(world)
        return shard_map(step_body, mesh=mesh,
                         in_specs=(in_specs,),
                         out_specs=(in_specs, metric_specs),
                         check_rep=False)(world)

    return sharded_step


def make_sharded_run_scan(cfg: Config, proto: ProtocolBase, mesh: Mesh,
                          n_rounds: int, **kw):
    """Whole-run-on-device over the mesh: lax.scan of the sharded round
    — the multi-chip analog of engine.make_run_scan (zero host
    round-trips per round; collectives per ROUND stay at the budget,
    the scan multiplies rounds, not program collectives)."""
    step = make_sharded_step(cfg, proto, mesh, donate=False, **kw)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_scan(world: World):
        def body(w, _):
            w2, m = step(w)
            return w2, m
        return jax.lax.scan(body, world, None, length=n_rounds)

    return run_scan
