"""Device-mesh sharding of the simulator — the distributed backend.

The reference's distribution story is TCP sockets + ETF framing
(src/partisan_peer_connection.erl, src/partisan_socket.erl:17-19); the
TPU-native equivalent (SURVEY §2.11, §5.8) is sharding the **node axis**
across a ``jax.sharding.Mesh`` and letting XLA insert ICI collectives for the
cross-shard message traffic: the router's sort-by-destination is a global
all-to-all under the hood, exactly the "pick a mesh, annotate shardings, let
XLA insert collectives" recipe.

Every state leaf is ``[N, ...]`` sharded on axis 0; the flat message buffer
``[M, ...]`` is likewise sharded on axis 0 (messages live where they were
emitted; routing moves them).  Scalars (round counter) are replicated.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine import World

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the node axis.  On a real slice this is the ICI ring; in
    tests it is the 8-device virtual CPU mesh (tests/conftest.py)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (NODE_AXIS,))


def shard_spec(leaf: Any) -> P:
    """Shard axis 0 for arrays with a leading (node or message) axis;
    replicate scalars."""
    if hasattr(leaf, "ndim") and leaf.ndim >= 1:
        return P(NODE_AXIS) if leaf.ndim >= 1 else P()
    return P()


def node_sharding(mesh: Mesh, leaf: Any) -> NamedSharding:
    if hasattr(leaf, "ndim") and leaf.ndim >= 1:
        return NamedSharding(mesh, P(NODE_AXIS))
    return NamedSharding(mesh, P())


def place_world(world: World, mesh: Mesh) -> World:
    """device_put every leaf with its sharding; XLA propagates from there.

    Scalar leaves (round counter) replicate; [N,...] and [M,...] leaves are
    row-sharded.  Requires N and the message cap to be divisible by the mesh
    size (pad N up if needed — node ids beyond the real N just stay inert
    rows with alive=False).
    """
    def put(leaf):
        return jax.device_put(leaf, node_sharding(mesh, leaf))
    return jax.tree_util.tree_map(put, world)


_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2,
                "s16": 2, "u16": 2, "f32": 4, "s32": 4, "u32": 4,
                "f64": 8, "s64": 8, "u64": 8}


def collective_stats(compiled: Any) -> dict:
    """Parse a compiled (SPMD-partitioned) executable's HLO for its
    cross-device collectives — the hardware-free multi-chip perf proxy
    (VERDICT r4 #7): on a real slice these are the ICI transfers, so
    their count and byte volume are the per-round communication cost.

    Returns ``{"counts": {op: n}, "all_gather_outputs": [(shape_str,
    elements, bytes)], "all_gather_total_bytes": int}``.  Byte figures
    are whole-array (the per-device wire cost is that times
    (devices-1)/devices for a ring all-gather).

    Handles the partitioner's variadic/combined form (tuple result
    shapes) and the async split (``all-gather-start``; the matching
    ``-done`` is not double-counted).  For async/tuple forms every
    shape token in the result is accounted, which can include operand
    aliases — a slight OVERcount, i.e. conservative for the cap tests
    built on top.  Raises if an all-gather was counted but no result
    shape could be parsed (parser drift must fail loudly, not let the
    quality gate pass vacuously)."""
    import re
    txt = compiled.as_text()
    counts = {op: 0 for op in (
        "all-gather", "collective-permute", "reduce-scatter",
        "all-reduce", "all-to-all")}
    ag = []
    line_re = re.compile(
        r"= (.*?) (all-gather|collective-permute|reduce-scatter|"
        r"all-reduce|all-to-all)(-start)?\(")
    for line in txt.splitlines():
        m = line_re.search(line)
        if not m:
            continue
        res, op = m.group(1), m.group(2)
        counts[op] += 1
        if op != "all-gather":
            continue
        for sm in re.finditer(r"(\w+)\[([\d,]*)\]", res):
            dt, dims = sm.group(1), sm.group(2)
            if dt not in _DTYPE_BYTES:
                continue
            shape = [int(d) for d in dims.split(",")] if dims else []
            elems = int(np.prod(shape)) if shape else 1
            ag.append((f"{dt}[{dims}]", elems,
                       elems * _DTYPE_BYTES[dt]))
    if counts["all-gather"] > 0 and not ag:
        raise ValueError(
            "collective_stats: all-gather instructions present but no "
            "result shapes parsed — HLO text format drifted; fix the "
            "parser before trusting the comms quality gate")
    return {"counts": counts,
            "all_gather_outputs": ag,
            "all_gather_total_bytes": sum(b for _, _, b in ag)}


def constrain(tree: Any, mesh: Mesh) -> Any:
    """with_sharding_constraint over a pytree — used inside jitted steps to
    pin intermediate layouts when XLA's propagation needs a hint."""
    def c(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1:
            return jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, P(NODE_AXIS)))
        return leaf
    return jax.tree_util.tree_map(c, tree)
