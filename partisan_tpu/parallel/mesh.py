"""Device-mesh sharding of the simulator — the distributed backend.

The reference's distribution story is TCP sockets + ETF framing
(src/partisan_peer_connection.erl, src/partisan_socket.erl:17-19); the
TPU-native equivalent (SURVEY §2.11, §5.8) is sharding the **node axis**
across a ``jax.sharding.Mesh`` and letting XLA insert ICI collectives for the
cross-shard message traffic: the router's sort-by-destination is a global
all-to-all under the hood, exactly the "pick a mesh, annotate shardings, let
XLA insert collectives" recipe.

Every state leaf is ``[N, ...]`` sharded on axis 0; the flat message buffer
``[M, ...]`` is likewise sharded on axis 0 (messages live where they were
emitted; routing moves them).  Scalars (round counter) are replicated.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine import World

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the node axis.  On a real slice this is the ICI ring; in
    tests it is the 8-device virtual CPU mesh (tests/conftest.py)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (NODE_AXIS,))


def shard_spec(leaf: Any) -> P:
    """Shard axis 0 for arrays with a leading (node or message) axis;
    replicate scalars."""
    if hasattr(leaf, "ndim") and leaf.ndim >= 1:
        return P(NODE_AXIS) if leaf.ndim >= 1 else P()
    return P()


def node_sharding(mesh: Mesh, leaf: Any) -> NamedSharding:
    if hasattr(leaf, "ndim") and leaf.ndim >= 1:
        return NamedSharding(mesh, P(NODE_AXIS))
    return NamedSharding(mesh, P())


def place_world(world: World, mesh: Mesh) -> World:
    """device_put every leaf with its sharding; XLA propagates from there.

    Scalar leaves (round counter) replicate; [N,...] and [M,...] leaves are
    row-sharded.  Requires N and the message cap to be divisible by the mesh
    size (pad N up if needed — node ids beyond the real N just stay inert
    rows with alive=False).
    """
    def put(leaf):
        return jax.device_put(leaf, node_sharding(mesh, leaf))
    return jax.tree_util.tree_map(put, world)


def constrain(tree: Any, mesh: Mesh) -> Any:
    """with_sharding_constraint over a pytree — used inside jitted steps to
    pin intermediate layouts when XLA's propagation needs a hint."""
    def c(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1:
            return jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, P(NODE_AXIS)))
        return leaf
    return jax.tree_util.tree_map(c, tree)
