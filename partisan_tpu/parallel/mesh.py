"""Device-mesh sharding of the simulator — the distributed backend.

The reference's distribution story is TCP sockets + ETF framing
(src/partisan_peer_connection.erl, src/partisan_socket.erl:17-19); the
TPU-native equivalent (SURVEY §2.11, §5.8) is sharding the **node axis**
across a ``jax.sharding.Mesh`` and letting XLA insert ICI collectives for the
cross-shard message traffic: the router's sort-by-destination is a global
all-to-all under the hood, exactly the "pick a mesh, annotate shardings, let
XLA insert collectives" recipe.

Every state leaf is ``[N, ...]`` sharded on axis 0; the flat message buffer
``[M, ...]`` is likewise sharded on axis 0 (messages live where they were
emitted; routing moves them).  Scalars (round counter) are replicated.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine import World

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the node axis.  On a real slice this is the ICI ring; in
    tests it is the 8-device virtual CPU mesh (tests/conftest.py)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (NODE_AXIS,))


def shard_spec(leaf: Any) -> P:
    """Shard axis 0 for arrays with a leading (node or message) axis;
    replicate scalars."""
    if hasattr(leaf, "ndim") and leaf.ndim >= 1:
        return P(NODE_AXIS) if leaf.ndim >= 1 else P()
    return P()


def node_sharding(mesh: Mesh, leaf: Any) -> NamedSharding:
    if hasattr(leaf, "ndim") and leaf.ndim >= 1:
        return NamedSharding(mesh, P(NODE_AXIS))
    return NamedSharding(mesh, P())


def place_world(world: World, mesh: Mesh) -> World:
    """device_put every leaf with its sharding; XLA propagates from there.

    Scalar leaves (round counter) replicate; [N,...] and [M,...] leaves are
    row-sharded.  Requires N and the message cap to be divisible by the mesh
    size (pad N up if needed — node ids beyond the real N just stay inert
    rows with alive=False).
    """
    def put(leaf):
        return jax.device_put(leaf, node_sharding(mesh, leaf))
    # World.aux is harness-owned and never node-indexed: the ISSUE-10
    # ControlPlane carries [n_ctl] vectors that are semantically
    # REPLICATED (every shard runs the same controller update on the
    # same post-psum globals), and n_ctl has no divisibility relation to
    # the mesh — so aux leaves replicate wholesale.
    aux = world.aux
    placed = jax.tree_util.tree_map(put, world.replace(aux=None))
    if aux is not None:
        aux = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P())), aux)
        placed = placed.replace(aux=aux)
    return placed


_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2,
                "s16": 2, "u16": 2, "f32": 4, "s32": 4, "u32": 4,
                "f64": 8, "s64": 8, "u64": 8}


def collective_stats(compiled: Any) -> dict:
    """Parse a compiled (SPMD-partitioned) executable's HLO for its
    cross-device collectives — the hardware-free multi-chip perf proxy
    (VERDICT r4 #7): on a real slice these are the ICI transfers, so
    their count and byte volume are the per-round communication cost.

    Returns ``{"counts": {op: n}, "outputs": {op: [(shape_str,
    elements, bytes)]}, "total_bytes": {op: int}, "all_gather_outputs":
    [...], "all_gather_total_bytes": int}`` (the last two are the
    legacy all-gather views of the same data).  Byte figures are
    whole-array (the per-device wire cost is that times
    (devices-1)/devices for a ring all-gather; for an all-to-all it is
    (devices-1)/devices of the per-device buffer).

    Handles the partitioner's variadic/combined form (tuple result
    shapes) and the async split (``all-gather-start``; the matching
    ``-done`` is not double-counted).  For async/tuple forms every
    shape token in the result is accounted, which can include operand
    aliases — a slight OVERcount, i.e. conservative for the cap tests
    built on top.  Raises if any collective was counted but no result
    shape could be parsed (parser drift must fail loudly, not let the
    quality gate pass vacuously)."""
    import re
    ops = ("all-gather", "collective-permute", "reduce-scatter",
           "all-reduce", "all-to-all")
    txt = compiled.as_text()
    counts = {op: 0 for op in ops}
    outputs = {op: [] for op in ops}
    line_re = re.compile(
        r"= (.*?) (all-gather|collective-permute|reduce-scatter|"
        r"all-reduce|all-to-all)(-start)?\(")
    for line in txt.splitlines():
        m = line_re.search(line)
        if not m:
            continue
        res, op = m.group(1), m.group(2)
        counts[op] += 1
        for sm in re.finditer(r"(\w+)\[([\d,]*)\]", res):
            dt, dims = sm.group(1), sm.group(2)
            if dt not in _DTYPE_BYTES:
                continue
            shape = [int(d) for d in dims.split(",")] if dims else []
            elems = int(np.prod(shape)) if shape else 1
            outputs[op].append((f"{dt}[{dims}]", elems,
                                elems * _DTYPE_BYTES[dt]))
    for op in ops:
        if counts[op] > 0 and not outputs[op]:
            raise ValueError(
                f"collective_stats: {op} instructions present but no "
                f"result shapes parsed — HLO text format drifted; fix "
                f"the parser before trusting the comms quality gate")
    total = {op: sum(b for _, _, b in outputs[op]) for op in ops}
    return {"counts": counts,
            "outputs": outputs,
            "total_bytes": total,
            "all_gather_outputs": outputs["all-gather"],
            "all_gather_total_bytes": total["all-gather"]}


def assert_collective_budget(compiled: Any, *, max_collectives: int,
                             max_bytes: int,
                             forbid: Sequence[str] = (),
                             max_counts: Optional[Dict[str, int]] = None
                             ) -> dict:
    """The hard per-round communication budget of the explicit dataplane
    (ISSUE 2): the compiled round may contain at most
    ``max_collectives`` cross-device collectives totalling at most
    ``max_bytes`` of whole-array result bytes, and none of the op kinds
    in ``forbid`` (e.g. ``("all-gather",)`` — the dataplane exists to
    replace whole-state gathers).  Raises AssertionError with the full
    stats on violation; returns the stats so gates can log them.  This
    converts multi-chip perf from "hope XLA infers it" into an asserted
    contract — a regression that grows a third collective or re-gathers
    a state plane fails the comms quality gate outright
    (tests/test_mesh.py).

    ``max_counts`` adds PER-KIND caps on top of the total (ISSUE 9: the
    dense sharded round pins <= 1 all-to-all + <= 2 all-reduce/
    collective-permute explicitly, not just a total) — kinds absent
    from the dict are bounded only by ``max_collectives``/``forbid``."""
    st = collective_stats(compiled)
    n = sum(st["counts"].values())
    assert n <= max_collectives, (
        f"collective budget blown: {n} collectives > {max_collectives} "
        f"allowed per round", st["counts"])
    for op in forbid:
        assert st["counts"].get(op, 0) == 0, (
            f"forbidden collective {op} present", st["counts"])
    for op, cap in (max_counts or {}).items():
        assert st["counts"].get(op, 0) <= cap, (
            f"per-kind collective budget blown: {op} x "
            f"{st['counts'].get(op, 0)} > {cap} allowed", st["counts"])
    total = sum(st["total_bytes"].values())
    assert total <= max_bytes, (
        f"collective byte ceiling blown: {total} > {max_bytes}",
        st["total_bytes"])
    return st


def constrain(tree: Any, mesh: Mesh) -> Any:
    """with_sharding_constraint over a pytree — used inside jitted steps to
    pin intermediate layouts when XLA's propagation needs a hint."""
    def c(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1:
            return jax.lax.with_sharding_constraint(
                leaf, NamedSharding(mesh, P(NODE_AXIS)))
        return leaf
    return jax.tree_util.tree_map(c, tree)
