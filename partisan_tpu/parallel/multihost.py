"""Multi-host scaling (SURVEY §5.8) — the DCN half of the distributed
communication backend.

The reference scales across machines with per-peer TCP connections; the
TPU rebuild scales across hosts with JAX's multi-controller runtime: every
host runs the SAME program, ``jax.distributed.initialize`` wires the
coordination service, and a global ``Mesh`` over ``jax.devices()`` (all
hosts' devices) makes the node-axis sharding span slices — XLA routes
intra-slice traffic over ICI and cross-slice traffic over DCN with no code
changes to the simulator (the whole point of the mesh design in mesh.py).

Single-host virtual testing: the driver validates the sharded program on
an ``xla_force_host_platform_device_count`` CPU mesh
(``__graft_entry__.dryrun_multichip``); this module only adds the
initialization ceremony a real multi-host deployment needs.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

from .mesh import make_mesh


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` wrapper.  With no arguments, JAX
    auto-detects the environment (TPU pods populate it from metadata);
    pass explicit values for manual clusters.  Call ONCE per process,
    before any device use."""
    kw = {}
    if coordinator_address is not None:
        kw["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    jax.distributed.initialize(**kw)


def global_mesh() -> Mesh:
    """1-D node-axis mesh over EVERY device of EVERY host.  On a multi-
    slice TPU deployment the axis order keeps slice-local devices adjacent
    so most gossip traffic (node-local shards) rides ICI and only the
    shard-boundary all-to-all crosses DCN."""
    return make_mesh(devices=jax.devices())


def is_coordinator() -> bool:
    return jax.process_index() == 0


def hosts() -> int:
    return jax.process_count()
