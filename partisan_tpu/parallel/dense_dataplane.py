"""Explicit-SPMD dense dataplane (ISSUE 9 tentpole) — the dense gossip
rounds of models/{hyparview,scamp,plumtree}_dense re-expressed as
shard-local arithmetic plus a HARD collective budget:

    <= 1 all-to-all  +  <= 2 all-reduce/collective-permute,  0 all-gathers

per round, asserted by ``mesh.assert_collective_budget`` (vs the 19
all-gathers + 16 collective-permutes + 5 all-reduces XLA's implicit
pjit partitioning emits for the same round — MULTICHIP_r06.json).

The structural move is the same one the PR-2 sparse dataplane made,
applied to the dense round's cross-row reads: every place the unsharded
round GATHERS another node's row (the repair mutuality check, the
promotion accept/readback pair, the shuffle walk hops, SCAMP's walker
table, plumtree's digest) becomes MAIL — a fixed-layout int32 outbox
carried in the state, moved by ONE bucketed ``lax.all_to_all``
(ops/shard_exchange.bucket_exchange) at the top of the next round, and
routed to its destination rows by ONE shard-local sort over the
combined (kind, local-node) key space (ops/shard_exchange.route_select,
replacing the unsharded round's three global N-element sorts).  Every
multi-step interaction pipelines across rounds with a uniform 1-round
mail latency — which is the latency model the dense round already
claims for itself ("the message delay of the reference, without the
message", hyparview_dense.py repair notes).

Mail rows are ``[valid, dst, src, kind, part, p0..p9]`` int32
(MAIL_COLS = 15); ``part`` is the sender's partition id stamped at
emission — the receive side drops cross-partition and dead-destination
rows, which makes the verify-plane semantics (faults.inject_partition /
chaos node events) hold without any cross-shard read.  The outbox
layout is STATIC (a fixed slot block per emission site, invalid rows
flagged off), so every program variant — flat, staggered heavy/light,
churned, chaos-folded — shares one state shape and composes under
``dense_cadence.block_scan``.

Protocol re-expression per model (distributional parity vs the
unsharded round is the bar — SURVEY §7.3 "two RNG semantics" — pinned
at N=256 on the 8-device CPU mesh in tests/test_dense_dataplane.py):

  hyparview  promotion PROPOSE/ACCEPT mail replaces reverse_select's
             global routing + acceptance readback; evictions emit
             DISCONNECT; the shuffle walk carries (origin, ttl, sample)
             one hop per round; the repair mutuality gather is replaced
             by KEEPALIVE mail on ``cfg.keepalive_interval`` cadence +
             a per-slot ``astamp`` TTL (``cfg.keepalive_ttl``) — the
             exact failure-detection shape config.py already documents
             for the engine path ("dead/one-sided active edges are
             detected by keepalive expiry").
  scamp      walkers live IN the mail (no [N, C] walker table): JOIN
             mail spawns the fan at the contact, WALK mail hops with
             the keep-coin applied at each holder, KEEP-NOTIFY mail
             fills the subject's in_view.  The cross-shard stale sweep
             (``last_reset`` gather) is intentionally NOT carried — it
             exists to garbage-collect entries referencing RESTARTED
             peers, and restart-in-place churn keeps those ids live;
             the named limitation is documented here rather than paid
             for with a second collective.
  plumtree   the per-round digest gather becomes a seq field on
             KEEPALIVE mail (pushed every round in plumtree mode);
             delivery = the parent's received seq, grafting picks the
             freshest received source.  Fused into the hyparview round:
             same outbox, same single exchange, budget unchanged.

Telemetry rides along shard-locally: received mail rows decode into a
synthetic :class:`~partisan_tpu.ops.msg.Msgs` wire (typ = mail kind) so
the PR-3 flight recorder's ``FlightSpec`` typ/node masks apply
unchanged, and a ``counters=`` hook (the PR-8 round-counter tap shape)
appends caller-defined per-round reductions to the ONE metrics psum.

Known distributional deltas vs the unsharded round (accepted and
counted, never silent): bucket/route overflow drops (``state.dropped``),
mail addressed to a dead/cross-partition destination, the unsharded
promotion's dead-candidate passive drop (no synchronous aliveness
probe exists here — dead candidates age out via the keepalive TTL),
and in-flight mail addressed to a node that restarted mid-flight.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import struct
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import Config
from ..ops import padded_set as ps
from ..ops.bitset import mix32 as _mix
from ..ops.msg import Msgs
from ..ops.shard_exchange import (bucket_exchange, default_bucket_cap,
                                  route_select, take_rows, take_vals)
from ..models.hyparview_dense import (DenseHvState, bulk_passive_merge,
                                      dense_init, launch_cap_for)
from ..models.scamp_dense import DenseScampState, walker_caps
from ..models.plumtree_dense import PtDense
from ..models import dense_cadence
from ..telemetry.flight import (FlightRing, FlightSpec, flight_record,
                                flight_partition_specs)
from .mesh import NODE_AXIS

# ---- mail layout: [valid, dst, src, kind, part, p0..p9] ----------------
N_PAYLOAD = 10
MAIL_COLS = 5 + N_PAYLOAD

# hyparview/plumtree mail kinds (the FlightSpec typ space of the round)
K_KEEPALIVE = 0   # p0 = sender's plumtree seq (0 in plain hyparview)
K_PROPOSE = 1     # p0 = proposer-isolated priority bit
K_ACCEPT = 2      # "your proposal to me succeeded"
K_DISCONNECT = 3  # explicit eviction notice
K_SHUF = 4        # p0 = origin, p1 = ttl, p2..p9 = 8-id sample
K_SHUF_REPLY = 5  # p2..p9 = 8-id sample back to the origin
HV_KINDS = 6

# scamp mail kinds
S_WALK = 0        # p0 = subject, p1 = age
S_NOTIFY = 1      # src = holder that admitted dst's subscription
S_JOIN = 2        # src = (re)subscriber, dst = contact
SCAMP_KINDS = 3

_SEL_CAP_HV = None   # filled per-cfg: max over per-kind receive caps


def hv_mail_slots(cfg: Config) -> int:
    """Static outbox rows per node per round (hyparview/plumtree):
    A keepalives + 1 propose + 2 accept-replies + 2 evict-disconnects
    from proposal handling + 2 from accept handling + 1 shuffle init +
    2 shuffle forwards + 2 shuffle replies."""
    return cfg.max_active_size + 12


def scamp_mail_slots(cfg: Config) -> int:
    """1 join + 2*C spawn fan + 6 walk forwards + 6 keep-notifies."""
    _, c = walker_caps(cfg)
    return 1 + 2 * c + 12


# ---- state ------------------------------------------------------------

@struct.dataclass
class ShardedDenseHv:
    """Sharded hyparview state: the unsharded planes + the keepalive
    stamp plane + the mail outbox.  Every [N, ...] plane shards on
    axis 0 over the mesh; ``dropped`` is one cumulative overflow
    counter per shard (bucket head-caps + route-cap misses)."""
    active: jax.Array     # [N, A]
    passive: jax.Array    # [N, P]
    astamp: jax.Array     # [N, A] round of last keepalive per slot
    alive: jax.Array      # [N] bool
    partition: jax.Array  # [N] int32 (0 = unpartitioned)
    mail: jax.Array       # [N * hv_mail_slots, MAIL_COLS] outbox
    dropped: jax.Array    # [n_shards] int32, cumulative
    rnd: jax.Array        # scalar int32


@struct.dataclass
class ShardedDensePt:
    """Plumtree fused over the sharded hyparview round — the broadcast
    planes of models/plumtree_dense.PtDense, sharded."""
    hv: ShardedDenseHv
    seq: jax.Array        # [N] highest delivered broadcast seq
    parent: jax.Array     # [N] eager parent (-1 = none / root)
    pstale: jax.Array     # [N] rounds behind without parent delivery


@struct.dataclass
class ShardedDenseScamp:
    """Sharded SCAMP state.  NOTE the deliberate omissions vs
    DenseScampState: no walker table (walkers live in the mail), no
    last_reset/pstamp/ivstamp planes (the cross-shard stale sweep is
    the one unsharded phase NOT carried over — see the module
    docstring's named limitation)."""
    partial: jax.Array        # [N, P]
    in_view: jax.Array        # [N, P]
    alive: jax.Array          # [N] bool
    partition: jax.Array      # [N] int32
    last_join: jax.Array      # [N] round of last (re)subscribe
    insert_dropped: jax.Array   # [N] keeps refused by a full view
    walk_expired: jax.Array     # [N] walks dead of old age
    walk_truncated: jax.Array   # [N] join-fan copies lost to the cap
    in_view_dropped: jax.Array  # [N] notify inserts lost to a full view
    mail: jax.Array           # [N * scamp_mail_slots, MAIL_COLS]
    dropped: jax.Array        # [n_shards] int32, cumulative
    rnd: jax.Array            # scalar int32


# ---- init / placement / readback --------------------------------------

def sharded_dense_init(cfg: Config, n_shards: int,
                       seeds_per_node: int = 2) -> ShardedDenseHv:
    """The unsharded bootstrap (dense_init) + empty mail/stamp planes."""
    n = cfg.n_nodes
    assert n % n_shards == 0, (n, n_shards)
    base = dense_init(cfg, seeds_per_node)
    return ShardedDenseHv(
        active=base.active, passive=base.passive,
        astamp=jnp.zeros((n, cfg.max_active_size), jnp.int32),
        alive=base.alive,
        partition=jnp.zeros((n,), jnp.int32),
        mail=jnp.zeros((n * hv_mail_slots(cfg), MAIL_COLS), jnp.int32),
        dropped=jnp.zeros((n_shards,), jnp.int32),
        rnd=jnp.int32(0))


def sharded_pt_init(cfg: Config, n_shards: int) -> ShardedDensePt:
    n = cfg.n_nodes
    return ShardedDensePt(
        hv=sharded_dense_init(cfg, n_shards),
        seq=jnp.zeros((n,), jnp.int32),
        parent=jnp.full((n,), -1, jnp.int32),
        pstale=jnp.zeros((n,), jnp.int32))


def sharded_scamp_init(cfg: Config, n_shards: int) -> ShardedDenseScamp:
    """Every node starts unsubscribed with ``last_join`` backdated, so
    round 0 re-subscribes the whole population through the normal JOIN
    mail path — the bootstrap IS the join protocol here, no special
    contact-table init."""
    n = cfg.n_nodes
    assert n % n_shards == 0, (n, n_shards)
    p, _ = walker_caps(cfg)
    z = lambda: jnp.zeros((n,), jnp.int32)  # noqa: E731
    return ShardedDenseScamp(
        partial=jnp.full((n, p), -1, jnp.int32),
        in_view=jnp.full((n, p), -1, jnp.int32),
        alive=jnp.ones((n,), bool),
        partition=z(), last_join=jnp.full((n,), -(1 << 20), jnp.int32),
        insert_dropped=z(), walk_expired=z(), walk_truncated=z(),
        in_view_dropped=z(),
        mail=jnp.zeros((n * scamp_mail_slots(cfg), MAIL_COLS), jnp.int32),
        dropped=jnp.zeros((n_shards,), jnp.int32),
        rnd=jnp.int32(0))


def _spec_of(x):
    return P(NODE_AXIS) if getattr(x, "ndim", 0) >= 1 else P()


def place_sharded(state, mesh):
    """device_put every [N, ...] plane sharded on the node axis."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, _spec_of(x))),
        state)


def to_dense(st: ShardedDenseHv) -> DenseHvState:
    """Host-side readback into the unsharded state type, so the
    existing health surface (hyparview_dense.connectivity) runs
    unchanged on sharded runs."""
    g = lambda x: jnp.asarray(jax.device_get(x))  # noqa: E731
    return DenseHvState(active=g(st.active), passive=g(st.passive),
                        alive=g(st.alive), rnd=g(st.rnd),
                        partition=g(st.partition))


def to_dense_scamp(st: ShardedDenseScamp, cfg: Config) -> DenseScampState:
    """Readback for models/scamp_dense.scamp_health: walker planes are
    empty by construction (walkers live in the mail) and the sweep
    stamp planes are zeros (the sweep is not carried — module
    docstring)."""
    g = lambda x: jnp.asarray(jax.device_get(x))  # noqa: E731
    n = st.partial.shape[0]
    p, c = walker_caps(cfg)
    return DenseScampState(
        partial=g(st.partial), in_view=g(st.in_view),
        walk_pos=jnp.full((n, c), -1, jnp.int32),
        walk_age=jnp.zeros((n, c), jnp.int32),
        alive=g(st.alive),
        insert_dropped=g(st.insert_dropped),
        walk_expired=g(st.walk_expired),
        walk_truncated=g(st.walk_truncated),
        in_view_dropped=g(st.in_view_dropped),
        last_reset=jnp.full((n,), -(10 ** 6), jnp.int32),
        pstamp=jnp.zeros((n, p), jnp.int32),
        ivstamp=jnp.zeros((n, p), jnp.int32),
        rnd=g(st.rnd))


def to_pt_dense(st: ShardedDensePt) -> PtDense:
    g = lambda x: jnp.asarray(jax.device_get(x))  # noqa: E731
    return PtDense(seq=g(st.seq), parent=g(st.parent), stale=g(st.pstale))


# ---- shared round machinery -------------------------------------------

def _round_prng(seed_tag: int, cfg: Config, rnd, gids):
    """(s32, rbits): scalar-salted uint32s and per-(node, slot) bits,
    derived from GLOBAL node ids so shard count never changes a node's
    coin flips — hyparview_dense.make_rbits with the ids passed in."""
    key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed ^ seed_tag), rnd)

    def s32(salt: int):
        return jax.random.bits(jax.random.fold_in(key, salt), (),
                               jnp.uint32)

    def rbits(salt: int, w: int):
        assert w <= 256, "rbits packs the slot in 8 bits"
        ctr = ((gids.astype(jnp.uint32)[:, None] << 8)
               | jnp.arange(w, dtype=jnp.uint32)[None, :])
        return _mix(ctr ^ s32(salt))
    return s32, rbits


def _emit(blocks, n_loc, gids, alive, part, dst, kind, pay=None):
    """Append one static outbox block: ``dst`` [n_loc] or [n_loc, b]
    GLOBAL destination ids (−1 = no mail), ``pay`` [n_loc, b, k<=10]
    int32 payload columns.  Dead senders emit nothing."""
    d = dst[:, None] if dst.ndim == 1 else dst
    b = d.shape[1]
    v = (d >= 0) & alive[:, None]
    hdr = jnp.stack([
        v.astype(jnp.int32),
        jnp.where(v, d, 0),
        jnp.broadcast_to(gids[:, None], (n_loc, b)),
        jnp.full((n_loc, b), kind, jnp.int32),
        jnp.broadcast_to(part[:, None], (n_loc, b)),
    ], axis=2)
    p = jnp.zeros((n_loc, b, N_PAYLOAD), jnp.int32)
    if pay is not None:
        p = p.at[:, :, : pay.shape[2]].set(pay.astype(jnp.int32))
    blocks.append(jnp.concatenate([hdr, p], axis=2))


def _flight_tap(fring, flight, keep, rsrc, rdst, rkind, rp, rnd):
    """Decode received mail into a synthetic Msgs wire so the PR-3
    flight recorder applies unchanged (typ = mail kind; the payload
    columns feed wire_hash).  Shard-local, zero collectives."""
    m = Msgs(valid=keep, src=rsrc, dst=rdst, typ=rkind,
             channel=jnp.zeros_like(rsrc), lane=jnp.zeros_like(rsrc),
             delay=jnp.zeros_like(rsrc),
             born=jnp.full_like(rsrc, rnd),
             data={"payload": rp})
    return flight_record(fring, flight, m, rnd)


def _psum_metrics(names, vals):
    tot = jax.lax.psum(jnp.stack([v.astype(jnp.int32) for v in vals]),
                       NODE_AXIS)
    return {k: tot[i] for i, k in enumerate(names)}


def _interpose_unsupported(interpose):
    if interpose is not None:
        raise ValueError(
            "interpose= is not supported by the sharded dense round: "
            "the unsharded hooks see whole-[N] destination vectors, "
            "which do not exist on any shard.  Use chaos= (message/"
            "node fault schedules run shard-local) or the unsharded "
            "make_dense_round for interposition experiments.")


# ---- hyparview / plumtree round ---------------------------------------

def make_sharded_dense_round(
    cfg: Config,
    mesh,
    *,
    model: str = "hyparview",
    churn: float = 0.0,
    skip: frozenset = frozenset(),
    phase_window: int = 1,
    shuffle_window: Optional[int] = None,
    resub_policy=None,
    chaos=None,
    flight: Optional[FlightSpec] = None,
    counters: Optional[Dict[str, Callable]] = None,
    bucket_cap: Optional[int] = None,
    interpose=None,
    root: int = 0,
    broadcast_interval: int = 5,
    graft_timeout: int = 1,
    control=None,
):
    """Compile one sharded dense round: ``state -> (state, metrics)``
    (``(state, ring) -> (state, ring, metrics)`` with ``flight=``).

    ``model`` is "hyparview", "plumtree" (the broadcast fold fused over
    the hyparview round — ShardedDensePt state) or "scamp"
    (ShardedDenseScamp).  ``skip`` suppresses phase EMISSIONS (the
    outbox layout stays static so every variant shares one state
    shape): {"promotion", "shuffle", "repair", "merge"} for hyparview,
    {"resub"} for scamp.  ``counters`` is the PR-8 round-counter tap:
    a dict name -> fn(local_planes_dict) -> scalar, appended to the
    single metrics psum.  ``chaos`` is a verify.chaos schedule whose
    node events fold shard-locally; ``flight`` a telemetry FlightSpec
    recording received mail as synthetic wire rows (typ = mail kind).

    Budget: exactly ONE all-to-all (the mail exchange) + ONE all-reduce
    (the stacked metrics psum) — asserted in tests via
    mesh.assert_collective_budget(max_counts=...).

    ``control`` (a :class:`control.plane.ControlSpec`) compiles the
    ISSUE-10 adaptive control plane into the round: the heavy-phase
    cadences become controller-gated ``due_in_window`` variants with
    TRACED intervals (actuators ``dense.promotion_interval`` /
    ``dense.shuffle_interval``, consumed by the dataplane itself), and
    the plane updates from the post-psum dense metric totals — zero
    added collectives, replicated [n_ctl] plane, bit-identical on every
    shard.  The step then takes and returns the plane:
    ``step(st, plane) -> (st, plane, metrics)``.  Hyparview/plumtree
    non-flight variants only; ``control=None`` (default) compiles
    byte-identical programs."""
    _interpose_unsupported(interpose)
    if control is not None and model == "scamp":
        raise ValueError(
            "make_sharded_dense_round: control= is not supported for "
            "model='scamp' (no controller-gated cadence in the walker "
            "round); use hyparview or plumtree")
    if control is not None and flight is not None:
        raise ValueError(
            "make_sharded_dense_round: control= and flight= cannot "
            "combine (both change the step arity); record the flight "
            "trace with controllers off, or pin the setpoints via "
            "Config instead")
    if model == "scamp":
        return _make_sharded_scamp_round(
            cfg, mesh, churn=churn, skip=skip, resub_policy=resub_policy,
            chaos=chaos, flight=flight, counters=counters,
            bucket_cap=bucket_cap)
    assert model in ("hyparview", "plumtree"), model
    pt = model == "plumtree"
    assert skip <= {"promotion", "shuffle", "repair", "merge"}, skip

    n = cfg.n_nodes
    d = len(mesh.devices.flat)
    assert n % d == 0, (n, d)
    n_loc = n // d
    a_cap = cfg.max_active_size
    p_cap = cfg.max_passive_size
    slots = hv_mail_slots(cfg)
    b_cap = bucket_cap or default_bucket_cap(slots * n_loc, d)
    sel_cap = max(a_cap, 2)
    s_win = shuffle_window if shuffle_window is not None else phase_window
    ctr_names = tuple(sorted(counters)) if counters else ()
    if control is not None:
        from ..control.plane import (metric_names as ctl_metric_names,
                                     plane_metrics, setpoint_values,
                                     update_plane, validate_control)

    def body_hv(st: ShardedDenseHv, pt_planes, fring, plane=None):
        base = jax.lax.axis_index(NODE_AXIS).astype(jnp.int32) * n_loc
        gids = base + jnp.arange(n_loc, dtype=jnp.int32)
        rnd = st.rnd
        s32, rbits = _round_prng(0xD5DA7A, cfg, rnd, gids)
        active, passive, astamp = st.active, st.passive, st.astamp
        alive, part = st.alive, st.partition
        if pt:
            seq, parent, pstale = pt_planes

        # ---- chaos node plane + churn (shard-local folds) ----
        if chaos is not None:
            from ..verify.chaos import apply_chaos_nodes
            alive, part = apply_chaos_nodes(chaos, rnd, alive, part, gids)
        if churn > 0.0:
            thresh = jnp.uint32(int(churn * (2 ** 32)))
            reset = (rbits(0, 1)[:, 0] < thresh) & alive
            contact = (_mix(gids.astype(jnp.uint32) ^ s32(1))
                       % jnp.uint32(n)).astype(jnp.int32)
            contact = jnp.where(contact == gids, (contact + 1) % n,
                                contact)
            active = jnp.where(reset[:, None], -1, active)
            astamp = jnp.where(reset[:, None], 0, astamp)
            passive = jnp.where(reset[:, None], -1, passive)
            passive = passive.at[:, 0].set(
                jnp.where(reset, contact, passive[:, 0]))

        # ---- deliver last round's mail: THE one all-to-all ----
        recv, xdrop = bucket_exchange(st.mail, n_loc, d, b_cap, NODE_AXIS,
                                      use_kernel=cfg.use_pallas_route)
        rvalid = recv[:, 0] != 0
        rdst, rsrc, rkind, rpart = (recv[:, 1], recv[:, 2], recv[:, 3],
                                    recv[:, 4])
        rp = recv[:, 5:]
        dstl = jnp.clip(rdst - base, 0, n_loc - 1)
        # receive-side fault plane: dead / cross-partition dst drops
        keep = (rvalid & alive[:, None][dstl, 0]
                & (part[:, None][dstl, 0] == rpart))
        if flight is not None:
            fring = _flight_tap(fring, flight, keep, rsrc, rdst, rkind,
                                rp, rnd)

        # ---- ONE local sort routes the whole inbox ----
        # route_select now owns the overflow count (ISSUE 17 satellite):
        # sel_drop is its cap-overflow scalar, not a caller-side diff
        sel, sel_drop = route_select(rkind, dstl, keep, HV_KINDS, n_loc,
                                     sel_cap, s32(2),
                                     use_kernel=cfg.use_pallas_route)
        routed = jnp.sum(sel >= 0)

        blocks = []
        emit = functools.partial(_emit, blocks, n_loc, gids)
        demote = []

        # KEEPALIVE: refresh the per-slot stamp (failure detection)
        ka = sel[K_KEEPALIVE]                     # [n_loc, sel_cap]
        ka_src = take_vals(rsrc, ka)
        hit = ((active[:, :, None] == ka_src[:, None, :])
               & (active >= 0)[:, :, None] & (ka_src >= 0)[:, None, :])
        astamp = jnp.where(jnp.any(hit, axis=2), rnd, astamp)
        if pt:
            ka_seq = take_vals(rp[:, 0], ka)      # −1 on empty slots

        # DISCONNECT: explicit eviction notice — drop + demote
        for j in range(2):
            sj = take_vals(rsrc, sel[K_DISCONNECT][:, j])
            hitj = (active == sj[:, None]) & (sj >= 0)[:, None]
            demote.append(jnp.where(jnp.any(hitj, axis=1), sj, -1)[:, None])
            active = jnp.where(hitj, -1, active)

        # ACCEPT: my proposal succeeded — add the target two-sided
        for j in range(2):
            sj = take_vals(rsrc, sel[K_ACCEPT][:, j])
            active, ev, _ = jax.vmap(ps.insert_evict_bits)(
                active, sj, rbits(5 + j, 1)[:, 0])
            astamp = jnp.where((active == sj[:, None]) & (sj >= 0)[:, None],
                               rnd, astamp)
            demote.append(ev[:, None])
            emit(alive, part, ev, K_DISCONNECT)

        # PROPOSE: accept when there is room or the proposer is isolated
        # (priority HIGH forces a random eviction — :1466-1512)
        for j in range(2):
            idx = sel[K_PROPOSE][:, j]
            pj = take_vals(rsrc, idx)
            high = take_vals(rp[:, 0], idx) > 0
            room = jnp.sum(active >= 0, axis=1) < a_cap
            aj = (pj >= 0) & alive & (room | high)
            active, ev, _ = jax.vmap(ps.insert_evict_bits)(
                active, jnp.where(aj, pj, -1), rbits(7 + j, 1)[:, 0])
            astamp = jnp.where((active == pj[:, None]) & aj[:, None],
                               rnd, astamp)
            demote.append(ev[:, None])
            emit(alive, part, jnp.where(aj, pj, -1), K_ACCEPT)
            emit(alive, part, ev, K_DISCONNECT)

        # my own shuffle sample: me ++ k_a active ++ k_p passive
        my_samp = jnp.concatenate([
            gids[:, None],
            jax.vmap(ps.random_k_bits, in_axes=(0, 0, None))(
                active, rbits(11, a_cap), cfg.shuffle_k_active),
            jax.vmap(ps.random_k_bits, in_axes=(0, 0, None))(
                passive, rbits(12, p_cap), cfg.shuffle_k_passive),
        ], axis=1)                                 # [n_loc, 8]

        # SHUF: one walk hop per round, carried (origin, ttl, sample)
        for j in range(2):
            idx = sel[K_SHUF][:, j]
            origin = take_vals(rp[:, 0], idx)
            ttl = take_vals(rp[:, 1], idx)
            samp_in = take_rows(rp, idx)[:, 2:10]  # [n_loc, 8]
            excl = jnp.stack([gids, origin], axis=1)
            fwd = jax.vmap(
                lambda s, b, e: ps.random_member_bits(s, b, exclude=e)
            )(active, rbits(13 + j, a_cap), excl)
            okr = idx >= 0
            can_fwd = okr & (ttl > 0) & (fwd >= 0)
            emit(alive, part, jnp.where(can_fwd, fwd, -1), K_SHUF,
                 pay=jnp.concatenate([
                     origin[:, None], (ttl - 1)[:, None], samp_in],
                     axis=1)[:, None, :])
            acc = okr & ~can_fwd
            demote.append(jnp.where(acc[:, None], samp_in, -1))
            emit(alive, part, jnp.where(acc, origin, -1), K_SHUF_REPLY,
                 pay=jnp.concatenate([
                     jnp.zeros((n_loc, 2), jnp.int32), my_samp],
                     axis=1)[:, None, :])

        # SHUF_REPLY: origin folds the endpoint's sample
        for j in range(2):
            demote.append(take_rows(rp, sel[K_SHUF_REPLY][:, j])[:, 2:10])

        # ---- repair: dead-row clear + keepalive-TTL prune (the mail
        # analog of the mutuality gather: a dead or one-sided edge stops
        # producing keepalives and ages out — config.py's documented
        # detection shape) ----
        if "repair" not in skip:
            active = jnp.where(alive[:, None], active, -1)
            ttl_stale = ((active >= 0)
                         & ((rnd - astamp) > jnp.int32(cfg.keepalive_ttl)))
            demote.append(jnp.where(ttl_stale, active, -1))
            active = jnp.where(ttl_stale, -1, active)

        # ---- isolation re-subscribe (every round, like the unsharded
        # round; resub_policy is the chaos-aware gate) ----
        lonely = (alive & (jnp.sum(active >= 0, axis=1) == 0)
                  & (jnp.sum(passive >= 0, axis=1) == 0))
        if resub_policy is not None:
            lonely = lonely & resub_policy(lonely, rnd)
        fresh = (_mix(gids.astype(jnp.uint32) ^ s32(40))
                 % jnp.uint32(n)).astype(jnp.int32)
        fresh = jnp.where(fresh == gids, (fresh + 1) % n, fresh)
        passive = passive.at[:, 0].set(
            jnp.where(lonely, fresh, passive[:, 0]))

        def due_in_window(interval, window):
            x = (rnd + gids) % interval
            return ((interval - x) % interval) < window

        # controller-gated cadence (ISSUE 10): the heavy-phase periods
        # come from LAST round's setpoints — actuation runs one round
        # behind the signal, like the sparse path's apply_setpoints.
        # Static Config ints when controllers are off: identical program.
        iv_promo = cfg.random_promotion_interval
        iv_shuf = cfg.shuffle_interval
        if control is not None:
            spv = setpoint_values(control, plane)
            if "dense.promotion_interval" in spv:
                iv_promo = jnp.maximum(spv["dense.promotion_interval"], 1)
            if "dense.shuffle_interval" in spv:
                iv_shuf = jnp.maximum(spv["dense.shuffle_interval"], 1)

        # ---- promotion initiation ----
        sizes = jnp.sum(active >= 0, axis=1)
        isolated = sizes == 0
        due = due_in_window(iv_promo, phase_window) | isolated
        cand = jax.vmap(ps.random_member_bits)(passive, rbits(3, p_cap))
        cand = jnp.where(jax.vmap(ps.contains)(active, cand), -1, cand)
        propose = alive & due & (sizes < a_cap) & (cand >= 0)
        if "promotion" in skip:
            propose = propose & False
        emit(alive, part, jnp.where(propose, cand, -1), K_PROPOSE,
             pay=isolated.astype(jnp.int32)[:, None, None])

        # ---- shuffle initiation: first hop of the walk ----
        due_s = alive & due_in_window(iv_shuf, s_win)
        t0 = jax.vmap(ps.random_member_bits)(active, rbits(30, a_cap))
        go = due_s & (t0 >= 0)
        if "shuffle" in skip:
            go = go & False
        emit(alive, part, jnp.where(go, t0, -1), K_SHUF,
             pay=jnp.concatenate([
                 gids[:, None],
                 jnp.full((n_loc, 1), cfg.arwl - 1, jnp.int32),
                 my_samp], axis=1)[:, None, :])

        # ---- plumtree fold (digest/deliver/graft off keepalive mail) --
        pt_metrics = []
        if pt:
            bump = ((broadcast_interval > 0)
                    & ((rnd % max(broadcast_interval, 1)) == 0))
            seq = jnp.where((gids == root) & bump, seq + 1, seq)
            known = jnp.max(jnp.where(ka_seq >= 0, ka_seq, -1), axis=1)
            pmask = ((ka_src == parent[:, None])
                     & (parent >= 0)[:, None] & (ka_seq >= 0))
            p_seq = jnp.max(jnp.where(pmask, ka_seq, -1), axis=1)
            delivered = p_seq > seq
            seq = jnp.maximum(seq, p_seq)
            parent_ok = (parent >= 0) & jnp.any(
                active == parent[:, None], axis=1)
            behind = known > seq
            pstale = jnp.where(behind & ~delivered, pstale + 1, 0)
            need = ((behind & (pstale >= graft_timeout))
                    | (behind & ~parent_ok))
            score = jnp.where(
                ka_seq >= 0,
                ka_seq * 8 + (rbits(60, sel_cap) >> 29).astype(jnp.int32),
                -(1 << 30))
            pick = jnp.argmax(score, axis=1)
            cand_p = jnp.take_along_axis(ka_src, pick[:, None],
                                         axis=1)[:, 0]
            grafted = need & (cand_p >= 0) & (gids != root)
            parent = jnp.where(grafted, cand_p, parent)
            parent = jnp.where(gids == root, -1, parent)
            pt_metrics = [jnp.sum(behind), jnp.sum(grafted)]

        # ---- keepalive emission (every round in plumtree mode: the
        # seq digest rides it) ----
        if pt:
            ka_due = jnp.ones((n_loc,), bool)
            ka_pay = jnp.broadcast_to(seq[:, None, None],
                                      (n_loc, a_cap, 1))
        else:
            ka_due = ((rnd + gids) % cfg.keepalive_interval) == 0
            ka_pay = None
        emit(alive, part, jnp.where(ka_due[:, None], active, -1),
             K_KEEPALIVE, pay=ka_pay)

        # ---- single fused passive merge ----
        if "merge" not in skip:
            passive = bulk_passive_merge(
                active, passive, jnp.concatenate(demote, axis=1), gids,
                jax.random.fold_in(
                    jax.random.fold_in(
                        jax.random.PRNGKey(cfg.seed ^ 0xD5DA7A), rnd),
                    50))

        mail = jnp.concatenate(blocks, axis=1)
        assert mail.shape[1] == slots, (mail.shape, slots)
        mail = mail.reshape(n_loc * slots, MAIL_COLS)
        sent = jnp.sum(mail[:, 0])

        names = ["mail_sent", "mail_processed", "mail_dropped", "live",
                 "lonely"]
        vals = [sent, routed, xdrop + sel_drop, jnp.sum(alive),
                jnp.sum(lonely)]
        if pt:
            names += ["pt_behind", "pt_grafts"]
            vals += pt_metrics
        if counters:
            planes = {"active": active, "passive": passive,
                      "alive": alive, "gids": gids, "rnd": rnd}
            for k in ctr_names:
                names.append(k)
                vals.append(counters[k](planes))
        metrics = _psum_metrics(names, vals)
        # -- adaptive control plane: updates from the post-psum totals
        #    (identical on every shard — replicated plane stays bit-
        #    identical); zero added collectives
        plane2 = None
        if control is not None:
            plane2 = update_plane(control, plane, metrics)
            metrics.update(plane_metrics(control, plane2))

        st2 = ShardedDenseHv(
            active=active, passive=passive, astamp=astamp, alive=alive,
            partition=part, mail=mail,
            dropped=st.dropped + xdrop + sel_drop, rnd=rnd + 1)
        pt2 = (seq, parent, pstale) if pt else None
        return st2, pt2, fring, metrics, plane2

    metric_names = ["mail_sent", "mail_processed", "mail_dropped",
                    "live", "lonely"]
    if pt:
        metric_names += ["pt_behind", "pt_grafts"]
    metric_names += list(ctr_names)
    metric_specs = {k: P() for k in metric_names}
    fr_specs = flight_partition_specs(NODE_AXIS)
    if control is not None:
        validate_control(control, metric_names,
                         ("dense.promotion_interval",
                          "dense.shuffle_interval"),
                         where="make_sharded_dense_round")
        metric_specs.update({k: P() for k in ctl_metric_names(control)})

    if control is not None:
        # step(st, plane) -> (st, plane, metrics): the plane is carried
        # explicitly (dense state is not a World, there is no aux slot)
        if pt:
            @jax.jit
            def step(st: ShardedDensePt, plane):
                specs = jax.tree_util.tree_map(_spec_of, st)
                pspecs = jax.tree_util.tree_map(lambda x: P(), plane)

                def b(s, pl):
                    hv2, pt2, _, m, pl2 = body_hv(
                        s.hv, (s.seq, s.parent, s.pstale), None, pl)
                    return (ShardedDensePt(hv=hv2, seq=pt2[0],
                                           parent=pt2[1], pstale=pt2[2]),
                            pl2, m)
                return shard_map(b, mesh=mesh, in_specs=(specs, pspecs),
                                 out_specs=(specs, pspecs, metric_specs),
                                 check_rep=False)(st, plane)
            return step

        @jax.jit
        def step(st: ShardedDenseHv, plane):
            specs = jax.tree_util.tree_map(_spec_of, st)
            pspecs = jax.tree_util.tree_map(lambda x: P(), plane)

            def b(s, pl):
                s2, _, _, m, pl2 = body_hv(s, None, None, pl)
                return s2, pl2, m
            return shard_map(b, mesh=mesh, in_specs=(specs, pspecs),
                             out_specs=(specs, pspecs, metric_specs),
                             check_rep=False)(st, plane)
        return step

    if pt:
        if flight is not None:
            @jax.jit
            def step(st: ShardedDensePt, fring: FlightRing):
                specs = jax.tree_util.tree_map(_spec_of, st)

                def b(s, fr):
                    hv2, pt2, fr2, m, _ = body_hv(s.hv,
                                                  (s.seq, s.parent,
                                                   s.pstale),
                                                  fr)
                    return (ShardedDensePt(hv=hv2, seq=pt2[0],
                                           parent=pt2[1], pstale=pt2[2]),
                            fr2, m)
                return shard_map(b, mesh=mesh, in_specs=(specs, fr_specs),
                                 out_specs=(specs, fr_specs, metric_specs),
                                 check_rep=False)(st, fring)
            return step

        @jax.jit
        def step(st: ShardedDensePt):
            specs = jax.tree_util.tree_map(_spec_of, st)

            def b(s):
                hv2, pt2, _, m, _ = body_hv(s.hv,
                                            (s.seq, s.parent, s.pstale),
                                            None)
                return (ShardedDensePt(hv=hv2, seq=pt2[0], parent=pt2[1],
                                       pstale=pt2[2]), m)
            return shard_map(b, mesh=mesh, in_specs=(specs,),
                             out_specs=(specs, metric_specs),
                             check_rep=False)(st)
        return step

    if flight is not None:
        @jax.jit
        def step(st: ShardedDenseHv, fring: FlightRing):
            specs = jax.tree_util.tree_map(_spec_of, st)

            def b(s, fr):
                s2, _, fr2, m, _ = body_hv(s, None, fr)
                return s2, fr2, m
            return shard_map(b, mesh=mesh, in_specs=(specs, fr_specs),
                             out_specs=(specs, fr_specs, metric_specs),
                             check_rep=False)(st, fring)
        return step

    @jax.jit
    def step(st: ShardedDenseHv):
        specs = jax.tree_util.tree_map(_spec_of, st)

        def b(s):
            s2, _, _, m, _ = body_hv(s, None, None)
            return s2, m
        return shard_map(b, mesh=mesh, in_specs=(specs,),
                         out_specs=(specs, metric_specs),
                         check_rep=False)(st)
    return step


# ---- scamp round -------------------------------------------------------

def _make_sharded_scamp_round(cfg: Config, mesh, *, churn=0.0,
                              skip=frozenset(), resub_policy=None,
                              chaos=None, flight=None, counters=None,
                              bucket_cap=None, max_age: int = 64,
                              join_patience: int = 12):
    """SCAMP with walkers IN the mail.  ``join_patience`` rounds must
    pass after a (re)subscribe before an empty view re-subscribes again
    — the in-flight-walker guard the unsharded round read off its
    walker table, expressed as a local timer."""
    assert skip <= {"resub"}, skip
    n = cfg.n_nodes
    d = len(mesh.devices.flat)
    assert n % d == 0, (n, d)
    n_loc = n // d
    p_cap, c_cap = walker_caps(cfg)
    slots = scamp_mail_slots(cfg)
    b_cap = bucket_cap or default_bucket_cap(slots * n_loc, d)
    sel_cap = 6
    ctr_names = tuple(sorted(counters)) if counters else ()

    def body(st: ShardedDenseScamp, fring):
        base = jax.lax.axis_index(NODE_AXIS).astype(jnp.int32) * n_loc
        gids = base + jnp.arange(n_loc, dtype=jnp.int32)
        rnd = st.rnd
        s32, rbits = _round_prng(0x5CADA7, cfg, rnd, gids)
        partial, in_view = st.partial, st.in_view
        alive, part, last_join = st.alive, st.partition, st.last_join
        ins_drop, wexp, wtrunc, ivdrop = (
            st.insert_dropped, st.walk_expired, st.walk_truncated,
            st.in_view_dropped)

        if chaos is not None:
            from ..verify.chaos import apply_chaos_nodes
            alive, part = apply_chaos_nodes(chaos, rnd, alive, part, gids)
        if churn > 0.0:
            thresh = jnp.uint32(int(churn * (2 ** 32)))
            reset = (rbits(0, 1)[:, 0] < thresh) & alive
            partial = jnp.where(reset[:, None], -1, partial)
            in_view = jnp.where(reset[:, None], -1, in_view)
            # backdate so the resub fold below re-joins immediately
            last_join = jnp.where(reset, rnd - join_patience, last_join)

        recv, xdrop = bucket_exchange(st.mail, n_loc, d, b_cap, NODE_AXIS,
                                      use_kernel=cfg.use_pallas_route)
        rvalid = recv[:, 0] != 0
        rdst, rsrc, rkind, rpart = (recv[:, 1], recv[:, 2], recv[:, 3],
                                    recv[:, 4])
        rp = recv[:, 5:]
        dstl = jnp.clip(rdst - base, 0, n_loc - 1)
        keep = (rvalid & alive[:, None][dstl, 0]
                & (part[:, None][dstl, 0] == rpart))
        if flight is not None:
            fring = _flight_tap(fring, flight, keep, rsrc, rdst, rkind,
                                rp, rnd)

        sel, sel_drop = route_select(rkind, dstl, keep, SCAMP_KINDS,
                                     n_loc, sel_cap, s32(2),
                                     use_kernel=cfg.use_pallas_route)
        routed = jnp.sum(sel >= 0)

        blocks = []
        emit = functools.partial(_emit, blocks, n_loc, gids)

        # NOTIFY: a holder admitted my subscription -> my in_view
        for j in range(4):
            hj = take_vals(rsrc, sel[S_NOTIFY][:, j])
            want = (hj >= 0) & ~jax.vmap(ps.contains)(in_view, hj)
            in_view, _, ins = jax.vmap(
                lambda s, x: ps.insert_evict(s, x, None))(in_view, hj)
            ivdrop = ivdrop + (want & ~ins).astype(jnp.int32)
        # route cap spill (sel rows beyond 4) counts via mail_dropped

        # WALK: keep-coin at the holder, else hop (walker = the mail)
        exact = getattr(cfg, "scamp_exact_keep_probability", True)
        for j in range(6):
            idx = sel[S_WALK][:, j]
            subj = take_vals(rp[:, 0], idx)
            age = take_vals(rp[:, 1], idx)
            okr = (idx >= 0) & alive & (subj >= 0)
            size_p = jnp.sum(partial >= 0, axis=1)
            if exact:
                pnum = 1.0 / (1.0 + size_p.astype(jnp.float32))
            else:
                pnum = jnp.full((n_loc,), 0.4, jnp.float32)
            coin = ((rbits(20 + j, 1)[:, 0] >> 8).astype(jnp.float32)
                    * (1.0 / (1 << 24))) < pnum
            # an empty view always keeps (v2: the contact itself)
            keepw = okr & (coin | (size_p == 0)) & (subj != gids)
            present = jax.vmap(ps.contains)(partial, subj)
            partial, _, ins = jax.vmap(
                lambda s, x: ps.insert_evict(s, x, None))(
                partial, jnp.where(keepw & ~present, subj, -1))
            admitted = keepw & ~present & ins
            full_drop = keepw & ~present & ~ins
            ins_drop = ins_drop + full_drop.astype(jnp.int32)
            emit(alive, part, jnp.where(admitted, subj, -1), S_NOTIFY)
            # forward / retry / expire
            fwd_needed = okr & ~admitted
            age2 = age + 1
            die = fwd_needed & (age2 > max_age)
            wexp = wexp + die.astype(jnp.int32)
            tgt = jax.vmap(ps.random_member_bits)(partial,
                                                  rbits(26 + j, p_cap))
            tgt = jnp.where(tgt >= 0, tgt, gids)   # hold at self
            tgt = jnp.where(full_drop, gids, tgt)  # retry next round
            emit(alive, part, jnp.where(fwd_needed & ~die, tgt, -1),
                 S_WALK,
                 pay=jnp.stack([subj, age2], axis=1)[:, None, :])

        # JOIN: spawn the walk fan at the contact (one copy per view
        # member + c extras, truncated to the walker cap, counted)
        for j in range(2):
            idx = sel[S_JOIN][:, j]
            subj = take_vals(rsrc, idx)
            okj = (idx >= 0) & alive & (subj >= 0)
            size_p = jnp.sum(partial >= 0, axis=1)
            extras = jax.vmap(ps.random_k_bits, in_axes=(0, 0, None))(
                partial, rbits(32 + j, p_cap), cfg.scamp_c)
            mf = jax.vmap(ps.members_first)(
                jnp.concatenate([partial, extras], axis=1))
            wtrunc = wtrunc + jnp.where(
                okj, jnp.sum(mf[:, c_cap:] >= 0, axis=1), 0)
            fan = jnp.where(okj[:, None], mf[:, :c_cap], -1)
            # empty contact view: the walker stays at the contact
            fan = fan.at[:, 0].set(
                jnp.where(okj & (size_p == 0), gids, fan[:, 0]))
            emit(alive, part, fan, S_WALK,
                 pay=jnp.concatenate([
                     jnp.broadcast_to(subj[:, None, None],
                                      (n_loc, c_cap, 1)),
                     jnp.zeros((n_loc, c_cap, 1), jnp.int32)], axis=2))

        # ---- (re)subscribe: empty view + patience elapsed ----
        lonely = (alive & (jnp.sum(partial >= 0, axis=1) == 0)
                  & ((rnd - last_join) >= join_patience))
        if "resub" in skip:
            lonely = lonely & False
        if resub_policy is not None:
            lonely = lonely & resub_policy(lonely, rnd)
        contact = (_mix(gids.astype(jnp.uint32) ^ s32(40))
                   % jnp.uint32(n)).astype(jnp.int32)
        contact = jnp.where(contact == gids, (contact + 1) % n, contact)
        partial = partial.at[:, 0].set(
            jnp.where(lonely, contact, partial[:, 0]))
        last_join = jnp.where(lonely, rnd, last_join)
        emit(alive, part, jnp.where(lonely, contact, -1), S_JOIN)

        # dead rows keep no views (restart-in-place rebuilds via churn)
        partial = jnp.where(alive[:, None], partial, -1)
        in_view = jnp.where(alive[:, None], in_view, -1)

        mail = jnp.concatenate(blocks, axis=1)
        assert mail.shape[1] == slots, (mail.shape, slots)
        mail = mail.reshape(n_loc * slots, MAIL_COLS)
        sent = jnp.sum(mail[:, 0])

        names = ["mail_sent", "mail_processed", "mail_dropped", "live",
                 "resubs"]
        vals = [sent, routed, xdrop + sel_drop, jnp.sum(alive),
                jnp.sum(lonely)]
        if counters:
            planes = {"partial": partial, "in_view": in_view,
                      "alive": alive, "gids": gids, "rnd": rnd}
            for k in ctr_names:
                names.append(k)
                vals.append(counters[k](planes))
        metrics = _psum_metrics(names, vals)

        st2 = ShardedDenseScamp(
            partial=partial, in_view=in_view, alive=alive, partition=part,
            last_join=last_join, insert_dropped=ins_drop,
            walk_expired=wexp, walk_truncated=wtrunc,
            in_view_dropped=ivdrop, mail=mail,
            dropped=st.dropped + xdrop + sel_drop, rnd=rnd + 1)
        return st2, fring, metrics

    metric_names = (["mail_sent", "mail_processed", "mail_dropped",
                     "live", "resubs"] + list(ctr_names))
    metric_specs = {k: P() for k in metric_names}
    fr_specs = flight_partition_specs(NODE_AXIS)

    if flight is not None:
        @jax.jit
        def step(st: ShardedDenseScamp, fring: FlightRing):
            specs = jax.tree_util.tree_map(_spec_of, st)
            return shard_map(body, mesh=mesh, in_specs=(specs, fr_specs),
                             out_specs=(specs, fr_specs, metric_specs),
                             check_rep=False)(st, fring)
        return step

    @jax.jit
    def step(st: ShardedDenseScamp):
        specs = jax.tree_util.tree_map(_spec_of, st)

        def b(s):
            s2, _, m = body(s, None)
            return s2, m
        return shard_map(b, mesh=mesh, in_specs=(specs,),
                         out_specs=(specs, metric_specs),
                         check_rep=False)(st)
    return step


# ---- runners -----------------------------------------------------------

def make_sharded_runner(step, *, stream=None):
    """Build the k-round whole-launch scan over a metrics-returning
    sharded step (flight-less programs).  ``stream`` (a
    :class:`~..telemetry.observatory.StreamSpec`) drains each round's
    replicated metrics dict to the host MID-SCAN through an ordered
    ``io_callback`` — the scan sits OUTSIDE shard_map and the metrics
    are replicated, so the drain adds ZERO collectives to the budget.
    ``stream=None`` compiles a byte-identical program (the
    ``flight=None`` discipline); streaming programs are never
    persistently cacheable, so the flagship runs stay ``stream=None``.
    Exposed (rather than inlined in :func:`run_sharded`) so tests can
    ``.lower()`` both variants and pin the byte-identity."""
    if stream is not None:
        drain = stream._drain_metrics
        from jax.experimental import io_callback

        def emit(m):
            io_callback(drain, None, m, ordered=True)
    else:
        def emit(m):
            return None

    @functools.partial(jax.jit, static_argnums=(1,))
    def run(st, k):
        def b(s, _):
            s2, m = step(s)
            emit(m)
            return s2, None
        out, _ = jax.lax.scan(b, st, None, length=k)
        return out
    return run


def run_sharded(step, state, n_rounds: int, *, stream=None):
    """Whole-launch-on-device scan over a metrics-returning sharded
    step (flight-less programs); see :func:`make_sharded_runner` for
    the ``stream`` heartbeat."""
    out = make_sharded_runner(step, stream=stream)(state, n_rounds)
    if stream is not None:
        jax.effects_barrier()  # every streamed row has landed
    return out


def run_sharded_chunked(step, state, n_rounds: int,
                        cfg: Config, *, stream=None):
    """Launch-capped host loop (the TPU worker-fault medicine of the
    unsharded runners — launch_cap_for): per-LAUNCH scan lengths stay
    under the validated caps; chunk boundaries are bit-invariant
    because the state carries everything, pinned in tests."""
    cap = launch_cap_for(cfg.n_nodes)
    run = make_sharded_runner(step, stream=stream)
    done = 0
    while done < n_rounds:
        k = min(cap, n_rounds - done)
        state = run(state, k)
        done += k
    if stream is not None:
        jax.effects_barrier()
    return state


def run_sharded_staggered(cfg: Config, mesh, state, n_blocks: int,
                          *, model: str = "hyparview", churn: float = 0.0,
                          k: int = 5, **kw):
    """Phase-staggered cadence over the sharded round via
    dense_cadence.block_scan.  hyparview/plumtree: one 2k block is
    [promo+shuffle heavy, light x k-1, promo heavy, light x k-1] with
    due windows widened to k / 2k (the unsharded staggered program's
    shape); LIGHT rounds still run the full mail plane — delivery,
    keepalives, repair — because in-flight walks hop via mail every
    round here.  scamp: [heavy, light x k-1] where light only skips the
    re-subscribe fold; at k=1 the block reduces to exactly the flat
    program (bit-parity, pinned in tests)."""
    if model == "scamp":
        heavy = _make_sharded_scamp_round(cfg, mesh, churn=churn, **kw)
        light = _make_sharded_scamp_round(cfg, mesh, churn=churn,
                                          skip=frozenset({"resub"}), **kw)
        segments = [(dense_cadence.as_body(lambda s: heavy(s)[0]), 1),
                    (dense_cadence.as_body(lambda s: light(s)[0]), k - 1)]
    else:
        assert cfg.random_promotion_interval >= k, (
            "stagger coarser than the promotion interval")
        assert cfg.shuffle_interval >= 2 * k, (
            "stagger coarser than the shuffle interval")
        mk = functools.partial(make_sharded_dense_round, cfg, mesh,
                               model=model, churn=churn, **kw)
        hps = mk(phase_window=k, shuffle_window=2 * k)
        hp = mk(phase_window=k, skip=frozenset({"shuffle"}))
        light = mk(skip=frozenset({"promotion", "shuffle"}))
        segments = [(dense_cadence.as_body(lambda s: hps(s)[0]), 1),
                    (dense_cadence.as_body(lambda s: light(s)[0]), k - 1),
                    (dense_cadence.as_body(lambda s: hp(s)[0]), 1),
                    (dense_cadence.as_body(lambda s: light(s)[0]), k - 1)]

    @functools.partial(jax.jit, static_argnums=(1,))
    def run(st, nb):
        return dense_cadence.block_scan(segments, st, nb)
    return run(state, n_blocks)
