"""Static configuration for the TPU-native partisan rebuild.

Mirrors the reference's config system (``src/partisan_config.erl:37-151`` and
``include/partisan.hrl``) as a frozen dataclass: reads are attribute lookups on
an immutable object that is closed over by jitted step functions, which is the
JAX-idiomatic analog of the reference's compiled-module globals
(``src/partisan_mochiglobal.erl`` — deliberately NOT ported, see SURVEY §7.4).

Timer cadences in the reference are wall-clock milliseconds
(``include/partisan.hrl:28,58-59``); the simulator is round-synchronous, so we
express every cadence in *rounds*.  With the default mapping of 1 round = 1 s:
periodic gossip 10 s -> 10 rounds, connection retry / retransmit / plumtree
lazy tick 1 s -> 1 round, shuffle + exchange 10 s -> 10 rounds, random
promotion 5 s -> 5 rounds.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Mapping, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Config:
    """Frozen simulation config.

    Field defaults follow ``partisan_config:init/0``
    (``src/partisan_config.erl:37-151``) where a corresponding key exists, and
    ``include/partisan.hrl`` constants otherwise.  ARWL/PRWL follow the config
    init values (5/30), not the module fallbacks (6/6) — ``partisan_sup``
    always runs ``partisan_config:init`` first (see SURVEY §7.3).
    """

    # --- cluster shape -----------------------------------------------------
    n_nodes: int = 64                  # N virtual nodes (rows of the state arrays)

    # --- HyParView (partisan_hyparview_peer_service_manager.erl:310-312) ---
    max_active_size: int = 6
    min_active_size: int = 3
    max_passive_size: int = 30
    arwl: int = 5                      # active random-walk length  (partisan_config.erl:103)
    prwl: int = 30                     # passive random-walk length (partisan_config.erl:104)
    shuffle_k_active: int = 3          # k_active()  (hyparview :1559-1562)
    shuffle_k_passive: int = 4         # k_passive() (hyparview :1563-1565)
    shuffle_interval: int = 10         # passive_view_maintenance, 10 s (hyparview :27)
    random_promotion_interval: int = 5  # 5 s (hyparview :28)

    # --- gossip / membership strategies ------------------------------------
    fanout: int = 5                    # ?FANOUT (partisan.hrl:5)
    periodic_interval: int = 10        # ?PERIODIC_INTERVAL 10000 ms (partisan.hrl:28)
    scamp_c: int = 5                   # ?SCAMP_C_VALUE (partisan.hrl:31)
    scamp_message_window: int = 10     # ?SCAMP_MESSAGE_WINDOW (partisan.hrl:32)
    scamp_exact_keep_probability: bool = True
    # ^ the reference quantizes SCAMP's keep probability to a biased coin
    #   (scamp_v2 :292-296, 352-360); True uses the paper's 1/(1+|view|),
    #   False reproduces the reference's 0.4 coin for behavioural parity.
    scamp_paper_fanout: bool = True
    # ^ True: a contact receiving a NEW subscription fans copies to its whole
    #   partial view + c extras (the SCAMP paper's subscription algorithm,
    #   which yields the (c+1)·ln N view-size fixed point).  False: the
    #   reference's shape — the *joiner* fans over its own (trivial) view
    #   (v1 :51-100, v2 :64-117), so every join injects only ~3 walks.
    scamp_walker_slots: int = 8
    # ^ C: per-subject concurrent walk-copy slots in the DENSE SCAMP
    #   re-layout (models/scamp_dense.walker_caps).  The walker plane's two
    #   reverse_select sorts run over N·C slots, so C trades join fan-out
    #   fidelity for throughput: 8 (default) truncates a typical join fan
    #   (mean view ~4 + scamp_c extras, counted in walk_truncated) and
    #   runs ~55-60% faster on chip than 16, with views settling thinner
    #   (mean 3.6-3.8 vs 4.3-5.6 at 2^16) but weak connectivity unchanged
    #   (99.59% vs 99.6% reached, results.csv round 4).  Raise back toward
    #   16 when a workload needs the fatter-view equilibrium more than the
    #   throughput; tests/test_scamp_dense.py's engine-matched parity band
    #   red-lines below ~6.

    # --- plumtree (partisan.hrl:58-59, plumtree_broadcast.erl) --------------
    lazy_tick_period: int = 1          # 1 s
    exchange_tick_period: int = 10     # 10 s
    broadcast_start_exchange_limit: int = 1
    broadcast_heartbeat_interval: int = 10  # plumtree_backend heartbeats, 10 s

    # --- messaging QoS ------------------------------------------------------
    parallelism: int = 1               # ?PARALLELISM (partisan.hrl:16): k lanes per edge
    channels: Tuple[str, ...] = ("undefined",)  # ?CHANNELS (partisan.hrl:19)
    monotonic_channels: Tuple[str, ...] = ()    # {monotonic, C} channels keep-latest
    retransmit_interval: int = 1       # retransmit timer 1 s (pluggable :1299-1301)
    retransmit_backoff_factor: int = 1
    # ^ interval multiplier per retransmission ATTEMPT (the self-healing
    #   leg, ISSUE 4): attempt k waits interval * factor^k rounds.  The
    #   reference re-sends everything outstanding on a FIXED 1 s timer
    #   (pluggable :905-942); 1 (default) reproduces that bit-for-bit,
    #   2 halves retransmit pressure per surviving loss under sustained
    #   faults (tests/test_chaos.py asserts the reduction at 20% loss).
    retransmit_backoff_max: int = 0    # interval ceiling in rounds (0 = none)
    retransmit_jitter: int = 0
    # ^ deterministic per-(node, slot, attempt) jitter in [0, jitter]
    #   extra rounds, desynchronizing cluster-wide retransmit storms
    #   after a heal; hash-derived, so runs stay replayable.  0 = off.
    retransmit_max_attempts: int = 0
    # ^ give-up threshold: a slot retransmitted this many times is
    #   DEAD-LETTERED — freed and counted (dead_lettered, surfaced via
    #   health_counters/telemetry) instead of retried forever.  0 (the
    #   reference's shape: retry until acked) = never give up.
    connection_retry_interval: int = 1  # reconnect tick 1 s (pluggable :1304-1306)
    relay_ttl: int = 5                 # ?RELAY_TTL (partisan.hrl:9)
    keepalive_interval: int = 2        # rounds between active-view keepalives
    keepalive_ttl: int = 8             # rounds without keepalive => link dead
    # ^ the failure-detection analog of the reference's TCP keepalive +
    #   linked-process EXIT pruning (partisan_socket.erl:17-19, SURVEY §5.3):
    #   the simulator's transport can drop messages (inbox overflow), so
    #   dead/one-sided active edges are detected by keepalive expiry instead
    #   of socket death.
    ingress_delay: int = 0             # server-side receive sleep, in rounds
    egress_delay: int = 0              # client-side send sleep, in rounds
    # ^ partisan_peer_service_server.erl:85-90 / _client.erl:88-93.  In a
    #   round-synchronous simulator both collapse to extra rounds in
    #   flight, applied once at emission (their sum); the two knobs are
    #   kept distinct so each reference config group maps to its own
    #   field (with_ingress_delay / with_egress_delay).
    broadcast: bool = False            # tree-based transitive relay when disconnected
    distance_enabled: bool = False     # ?DISTANCE_ENABLED (partisan.hrl:40)
    distance_interval: int = 10        # ping/pong distance metrics (pluggable :852-873)

    # --- simulator capacities (fixed shapes; SURVEY §7.3 "dynamic sparsity")
    # (per-handler emission caps live on each protocol class, which alone
    # knows its fan-out; only the shared routing cap lives here)
    inbox_cap: int = 16                # max messages a node processes per round
    auto_tune: bool = True
    # ^ derive the engine performance knobs below (node_emit_cap,
    #   deliver_gather_cap) from N when they are unset, so a naive
    #   Config(n_nodes=...) hits the measured-optimal program shape the
    #   way the reference runs its whole suite on config defaults
    #   (test/partisan_SUITE.erl).  See engine.autotune for the rule;
    #   False = the knobs mean exactly what they say (None = unbounded /
    #   gated-dense).  Explicitly-set knobs always win over the rule.
    node_emit_cap: Optional[int] = None
    # ^ per-node emission budget per round (handler + tick emissions
    #   combined): when set, the engine collects emissions with a
    #   RUNNING-OFFSET write into a fixed [N, C] region instead of
    #   materializing the [N, K*E] worst-case buffer and argsorting it —
    #   the dominant engine cost for wide-emit protocols (SCAMP at
    #   N=1024 carried ~1.5M mostly-empty slots through that sort; the
    #   offset collect moves ~N*C).  The carry buffer shrinks to
    #   N*(C+4) as well (engine.default_out_cap).  Entry order per node
    #   is slot-major with tick emissions last — identical to the
    #   unbounded path, so per-connection FIFO semantics are unchanged;
    #   per-node overflow is counted in out_dropped, never silent.
    #   None = unbounded (exact worst-case shapes).
    deliver_gate: bool = True
    # ^ False removes the per-(slot, type) emptiness conds from the
    #   deliver loop: every handler runs full-batch every slot.  The
    #   gates are what make SMALL-N rounds cheap (skip absent types), but
    #   the branch machinery dominates XLA *compile* time at scale — on
    #   TPU the gated HyParView program at N=4096 did not finish
    #   compiling in 10 min, while the ungated one is a flat fusable
    #   pipeline.  Rule of thumb: gate on CPU/small N, ungate for big-N
    #   TPU runs.  (Measured later: with the batched cluster() fix, the
    #   gated program compiles fine on TPU and gated+gather beats ungated
    #   at N=4096 — 18 vs 11 rounds/s — so prefer gated unless compile
    #   time is the problem.)  False takes precedence over
    #   deliver_gather_cap: without gates there is no sparse branch, so
    #   the gather knob is ignored.
    deliver_gather_cap: Optional[int] = None
    # ^ sparse-delivery gather width G: when set (and < n_nodes), each
    #   (inbox-slot, msg-type) dispatch gathers only the <= G receiving node
    #   rows and runs the handler over those, falling back to the dense
    #   full-batch path when more than G nodes hold that type this slot.
    #   Steady-state gossip touches few nodes per type per round, so this
    #   turns the deliver phase from O(N · handlers-present) into
    #   O(G · handlers-present) — the big-N engine knob (BASELINE round-1
    #   notes).  None = always dense (bit-identical results either way;
    #   handlers see the same per-node PRNG keys on both paths).
    use_pallas_route: bool = False
    # ^ run the dense round's shard-local routing sorts
    #   (ops/shard_exchange.reverse_select / bucket_exchange) through
    #   the fused Pallas kernels (ops/route_kernel.py, ISSUE 17)
    #   instead of the jnp reference: one pallas_call per primitive in
    #   place of XLA's multi-kernel sort/iota/scatter pipeline.
    #   Bit-identical outputs by construction (the kernels' bitonic
    #   network reproduces lax.sort's stable order exactly; property-
    #   pinned in tests/test_route_kernel.py); off-TPU the kernels run
    #   in interpret mode, so False (default) stays the right call
    #   everywhere but TPU — and False compiles the byte-identical
    #   programs this repo always compiled (fingerprint-gated).

    # --- workload / SLO plane (workload/, Dean & Barroso tail-at-scale) -----
    slo_deadline_rounds: int = 16
    # ^ request deadline in rounds for SLO accounting: a completion with
    #   latency <= deadline counts rpc_slo_ok, else rpc_slo_violated
    #   (counted device-side at reply delivery, workload/latency.py).
    shed_token_rate_milli: int = 0
    # ^ admission-control token refill, milli-tokens per round per node
    #   (1000 = 1 admitted request/round sustained).  0 = shedding OFF —
    #   the workload driver bypasses the bucket entirely.
    shed_token_burst_milli: int = 4000
    # ^ token bucket cap (burst size), milli-tokens.
    shed_max_outstanding: int = 0
    # ^ per-node outstanding-promise cap at admission: a new request is
    #   shed when this many calls are already in flight.  0 = no cap.

    # --- verification-harness flags (env tier, partisan_config.erl:37-151) --
    tag: Optional[str] = None          # node tag (client/server), TAG env
    replaying: bool = False            # trace replay mode, REPLAY env (:78-85)
    shrinking: bool = False            # relaxed replay matching, SHRINKING env (:88-94)
    trace_file: Optional[str] = None   # TRACE_FILE env (trace_orchestrator :450-457)

    # --- determinism --------------------------------------------------------
    seed: int = 1                      # per-node keys derive from this (support :163-166)

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)

    @property
    def n_channels(self) -> int:
        return len(self.channels)

    def channel_index(self, name: str) -> int:
        """Channel name -> lane index (names live host-side only, SURVEY §5.6)."""
        return self.channels.index(name)


DEFAULT = Config()


# Reference manager module names -> port manager keys, so the PEER_SERVICE
# env var accepts the exact values partisan_SUITE exports (e.g.
# ``PEER_SERVICE=partisan_hyparview_peer_service_manager``,
# test/partisan_support.erl:35-81) as well as our short names.
_MANAGER_ALIASES = {
    "partisan_pluggable_peer_service_manager": "full",
    "partisan_default_peer_service_manager": "full",
    "partisan_hyparview_peer_service_manager": "hyparview",
    "partisan_hyparview_xbot_peer_service_manager": "hyparview",
    "partisan_client_server_peer_service_manager": "client_server",
    "partisan_static_peer_service_manager": "static",
}


def env_overrides(environ: Optional[Mapping[str, str]] = None
                  ) -> Dict[str, Any]:
    """The OS-env tier of the reference's three-tier config system
    (``partisan_config:init/0``, src/partisan_config.erl:37-151): keys set
    in the environment supersede app-level overrides, which supersede the
    dataclass defaults.  Handled keys and their reference read sites:

      PEER_SERVICE  manager selection (:42-48) — returned under the
                    reserved key ``"peer_service"`` for the session layer
                    (the port server's ``start``), translated from
                    reference module names via _MANAGER_ALIASES
      TAG           node tag (:67-75)
      REPLAY        replay mode (:78-85)
      SHRINKING     shrinking mode (:88-94)
      TRACE_FILE    trace output path (trace_orchestrator :450-457)

    The reference treats the literal string "false" as unset for all four
    flag keys (``os:getenv(Key, "false")`` with a "false" guard clause);
    any other set value enables REPLAY/SHRINKING.  That quirk is
    preserved.
    """
    env = os.environ if environ is None else environ
    out: Dict[str, Any] = {}
    ps = env.get("PEER_SERVICE", "false")
    if ps != "false":
        out["peer_service"] = _MANAGER_ALIASES.get(ps, ps)
    tag = env.get("TAG", "false")
    if tag != "false":
        out["tag"] = tag
    if env.get("REPLAY", "false") != "false":
        out["replaying"] = True
    if env.get("SHRINKING", "false") != "false":
        out["shrinking"] = True
    tf = env.get("TRACE_FILE")
    if tf:
        out["trace_file"] = tf
    return out


def from_mapping(m: Optional[Mapping[str, Any]] = None,
                 environ: Optional[Mapping[str, str]] = None,
                 **kw: Any) -> Config:
    """Build a Config from a dict of overrides (the `partisan_config:set`
    analog used by the test harness, cf. test/partisan_support.erl:109-330).

    The OS-env tier (``env_overrides``) is applied on top, mirroring
    ``partisan_config:init/0`` priority: env > app overrides > defaults.
    Pass ``environ={}`` to disable it (hermetic tests).  The
    ``peer_service`` env key is not a Config field — it is consumed by the
    session layer (bridge/port_server.cmd_start) before this call.
    """
    merged = dict(m or {})
    merged.update(kw)
    env = env_overrides(environ)
    env.pop("peer_service", None)
    merged.update(env)
    return dataclasses.replace(DEFAULT, **merged)
