"""The device-side message flight recorder (ISSUE 3 tentpole) — the
in-scan rebuild of ``partisan_trace_orchestrator.erl`` /
``partisan_trace_file.erl``'s wire capture.

The reference's trace orchestrator installs pre-interposition funs on
every node and records ``{Node, Type, Origin, Msg}`` tuples as the run
executes.  Our legacy analog (:class:`partisan_tpu.verify.trace.
TraceRecorder`) drives ``engine.make_step(capture_wire=True)`` from a
Python loop — one device->host transfer of the whole wire buffer per
ROUND, unsharded only.  This module moves the capture into the scan:

  * :class:`FlightRing` — a fixed-shape ``[window, cap, 6]`` int32
    buffer carried in the scan state; each round the engine writes one
    ``[cap, 6]`` row of ``(round, src, dst, typ, channel, hash)`` slots
    (``dynamic_update_slice`` at the cursor, like the metrics ring) and
    the host flushes the whole window in ONE transfer.
  * :class:`FlightSpec` — host-side recorder config baked into the
    jitted program as compile-time constants: the capture cap, the
    message-type mask (the ``membership_strategy_tracing`` filter of
    trace_orchestrator :508-560) and a node-sampling filter
    (``node_mod``/``node_phase``: keep a message iff src or dst lands
    in the sampled residue class — the tracing-at-scale dial).
  * head-cap + ``overflow``: a round emitting more matching messages
    than ``cap`` keeps the first ``cap`` (buffer order, the same order
    the legacy recorder's ``np.flatnonzero`` walk produced) and COUNTS
    the excess — never silent (SURVEY §7.3).

Capture order inside a round row is flat-buffer order, which makes the
unsharded recorder's entry stream IDENTICAL (not just multiset-equal)
to the legacy per-round path.  Under the sharded dataplane each shard
records its own ``[window, cap, 6]`` slice (the ring's cap axis is
sharded over the mesh), so rows come out dst-shard-major and parity
with the unsharded trace is per-round MULTISET equality
(tests/test_flight.py).  Recording is shard-local arithmetic only —
it adds ZERO collectives to the dataplane round, so the asserted
2-collective budget holds with the recorder on.

Decoded rows become :class:`partisan_tpu.verify.trace.TraceEntry`
streams, so everything downstream of the legacy recorder — the model
checker, ``faults.drop_schedule`` replay, the golden crosswalk and
``write_trace``/``read_trace`` persistence — consumes recorder output
unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..ops import msg as msgops
from ..ops.msg import Msgs

# columns of one flight slot, in order
COLUMNS = ("rnd", "src", "dst", "typ", "channel", "hash")
N_COLS = len(COLUMNS)


@struct.dataclass
class FlightRing:
    """Device state of the recorder, carried through the scan.

    ``buf[w, s]`` holds slot ``s`` of window-row ``w``; empty slots have
    ``rnd == -1`` (real rounds are always >= 0).  ``overflow`` is a
    ``[n_shards]`` vector so the sharded dataplane counts per shard
    without a collective; the unsharded ring uses ``[1]``.
    """
    buf: jax.Array       # [window, cap, 6] int32
    cursor: jax.Array    # scalar int32 — rows recorded since last flush
    overflow: jax.Array  # [n_shards] int32 — head-capped slots, cumulative


@dataclasses.dataclass(frozen=True)
class FlightSpec:
    """Host-side recorder config — every field is a compile-time
    constant of the jitted step (the registry enable-mask pattern:
    reconfiguring the filter recompiles, running it costs a fused
    elementwise mask).

    ``cap`` is the per-round slot budget — PER SHARD under the
    dataplane (each shard records the messages delivered to its own
    rows).  ``typs=None`` records every type; otherwise only the listed
    wire tags (trace_orchestrator's protocol filter).  ``node_mod > 1``
    samples the node population: a message is kept iff
    ``src % node_mod == node_phase or dst % node_mod == node_phase``.
    """
    window: int
    cap: int
    typs: Optional[Tuple[int, ...]] = None
    node_mod: int = 1
    node_phase: int = 0

    def __post_init__(self):
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.cap < 1:
            raise ValueError(f"cap must be >= 1, got {self.cap}")
        if self.node_mod < 1:
            raise ValueError(f"node_mod must be >= 1, got {self.node_mod}")
        if not (0 <= self.node_phase < self.node_mod):
            raise ValueError(
                f"node_phase {self.node_phase} outside [0, {self.node_mod})")


def make_flight_ring(spec: FlightSpec, n_shards: int = 1) -> FlightRing:
    """An empty ring.  ``n_shards > 1`` builds the dataplane's ring:
    the cap axis concatenates every shard's ``spec.cap`` slots (shard
    k's slice is ``[:, k*cap:(k+1)*cap]``) and ``overflow`` holds one
    counter per shard — place with :func:`place_flight_ring` before a
    sharded run."""
    return FlightRing(
        buf=jnp.full((spec.window, n_shards * spec.cap, N_COLS), -1,
                     jnp.int32),
        cursor=jnp.int32(0),
        overflow=jnp.zeros((n_shards,), jnp.int32),
    )


def flight_partition_specs(NODE_AXIS: str) -> FlightRing:
    """shard_map in/out specs for the ring: the cap axis shards over
    the mesh (each device records its own slots), the cursor replicates
    (every shard advances it identically), overflow is one counter per
    shard."""
    from jax.sharding import PartitionSpec as P
    return FlightRing(buf=P(None, NODE_AXIS), cursor=P(),
                      overflow=P(NODE_AXIS))


def place_flight_ring(ring: FlightRing, mesh) -> FlightRing:
    """device_put the ring with its dataplane shardings."""
    from jax.sharding import NamedSharding
    from ..parallel.mesh import NODE_AXIS
    specs = flight_partition_specs(NODE_AXIS)
    return FlightRing(
        buf=jax.device_put(ring.buf, NamedSharding(mesh, specs.buf)),
        cursor=jax.device_put(ring.cursor,
                              NamedSharding(mesh, specs.cursor)),
        overflow=jax.device_put(ring.overflow,
                                NamedSharding(mesh, specs.overflow)),
    )


def flight_mask(spec: FlightSpec, m: Msgs) -> jax.Array:
    """[M] bool — which wire slots the recorder keeps this round.  The
    typ-mask and node-sampling predicates are baked from host constants
    (``where``-style masks, no branches), so the filter is jit-safe
    inside scan and a permissive spec folds to ``m.valid``."""
    keep = m.valid
    if spec.typs is not None:
        tt = jnp.asarray(tuple(spec.typs), jnp.int32)
        keep = keep & jnp.any(m.typ[:, None] == tt[None, :], axis=1)
    if spec.node_mod > 1:
        phase = jnp.int32(spec.node_phase)
        mod = jnp.int32(spec.node_mod)
        keep = keep & ((jnp.maximum(m.src, 0) % mod == phase)
                       | (jnp.maximum(m.dst, 0) % mod == phase))
    return keep


def flight_record(ring: FlightRing, spec: FlightSpec, m: Msgs,
                  rnd: jax.Array) -> FlightRing:
    """Write one round's wire buffer into the ring (device, inside the
    scan / shard_map body).  Compaction is GATHER-shaped, not scatter:
    each of the ``cap`` row slots binary-searches the keep-mask's
    running count for its source index (``searchsorted`` — O(cap log
    M) after one O(M) cumsum), so the kept slots land at the front of
    the row in flat-buffer order (the legacy recorder's order) and the
    payload hash is computed on the ``cap`` gathered slots only — the
    round cost scales with what the recorder KEEPS, not with the
    buffer it filters (the <=5% recorder-on bench bar).  Slots past
    ``cap`` increment ``overflow``.

    Under the dataplane this runs on each shard's local ring slice
    (``buf [window, cap, 6]``, ``overflow [1]``) — pure shard-local
    arithmetic, zero collectives.
    """
    window, cap = ring.buf.shape[0], ring.buf.shape[1]
    keep = flight_mask(spec, m)
    csum = jnp.cumsum(keep.astype(jnp.int32))     # [M] inclusive
    total = csum[-1]
    n_kept = jnp.minimum(total, cap)
    slots = jnp.arange(cap, dtype=jnp.int32)
    ok = slots < n_kept
    # slot s <- first buffer index whose running keep-count is s+1
    gi = jnp.where(ok, jnp.searchsorted(csum, slots + 1)
                   .astype(jnp.int32), 0)
    sub = jax.tree_util.tree_map(lambda x: x[gi], m)   # [cap, ...] rows
    h = jax.lax.bitcast_convert_type(
        msgops.wire_hash(sub), jnp.int32)         # value-preserving
    cols = jnp.stack([
        jnp.broadcast_to(jnp.asarray(rnd, jnp.int32), (cap,)),
        sub.src, sub.dst, sub.typ, sub.channel, h], axis=1)  # [cap, 6]
    row = jnp.where(ok[:, None], cols, -1)
    slot = jnp.mod(ring.cursor, window)           # wrap = keep-latest
    buf = jax.lax.dynamic_update_slice(
        ring.buf, row[None], (slot, jnp.int32(0), jnp.int32(0)))
    ovf = ring.overflow + (total - n_kept)
    return ring.replace(buf=buf, cursor=ring.cursor + 1, overflow=ovf)


def flight_flush(ring: FlightRing
                 ) -> Tuple[np.ndarray, int, FlightRing]:
    """ONE device->host transfer of the whole window.  Returns
    ``(rows, overflow, reset_ring)`` where ``rows`` is the host
    ``[n_recorded, cap, 6]`` array (oldest round first; wrap degrades
    to keep-latest like the metrics ring) and ``overflow`` is the
    total head-capped slot count since the last flush (summed over
    shards).  Host-side only — never call under jit."""
    buf = np.asarray(jax.device_get(ring.buf))
    n = int(ring.cursor)
    window = buf.shape[0]
    if n > window:  # wrapped: only the latest `window` rows survive
        start = n % window
        buf = np.concatenate([buf[start:], buf[:start]])
        n = window
    overflow = int(np.asarray(jax.device_get(ring.overflow)).sum())
    # rows are fully rewritten at record time, so only the counters
    # need resetting — no device-side buffer clear
    reset = ring.replace(cursor=jnp.int32(0),
                         overflow=jnp.zeros_like(ring.overflow))
    return buf[:n], overflow, reset


def flight_entries(rows: np.ndarray) -> List["TraceEntry"]:
    """Decode flushed rows into the legacy recorder's TraceEntry stream
    (``rnd == -1`` slots are padding; hash column bitcasts back to the
    uint32 the legacy path recorded).  Everything downstream —
    write_trace, drop_schedule keys, the model checker, the golden
    crosswalk — consumes this unchanged."""
    # lazy import: verify/__init__ imports faults -> telemetry; a
    # module-level import here would cycle during package init
    from ..verify.trace import TraceEntry
    out: List[TraceEntry] = []
    rows = np.asarray(rows)
    if rows.size == 0:
        return out
    flat = rows.reshape((-1, N_COLS))
    valid = flat[:, 0] >= 0
    for r, s, d, t, c, h in flat[valid]:
        out.append(TraceEntry(int(r), int(s), int(d), int(t), int(c),
                              int(np.uint32(h))))
    return out


def flight_pairs(entries) -> Dict[Tuple[int, int, int], int]:
    """Fold a flight-trace entry stream into observed traffic:
    ``(src, dst, typ) -> count``.  This is the fault-space explorer's
    frontier source (ISSUE 7): only pairs that actually carried protocol
    traffic are worth perturbing — the reference's trace-membership
    pruning (filibuster_SUITE), read off the recorder instead of a
    bespoke trace pass.  Accepts any iterable of
    :class:`verify.trace.TraceEntry` (``flight_entries`` output or the
    legacy recorder's stream)."""
    out: Dict[Tuple[int, int, int], int] = {}
    for e in entries:
        k = (int(e.src), int(e.dst), int(e.typ))
        out[k] = out.get(k, 0) + 1
    return out
