"""Benchplane (ISSUE 18): the unified performance ledger + perf gates.

The repo's perf record was nine incompatible ``BENCH_*`` schemas with
zero gating — no tool could read the numbers across PRs, so the bench
trajectory was unqueryable and suite-runtime regressions surfaced three
PRs late.  This module is the missing observability plane for *runtime
performance itself*, mirroring how ``observatory.py`` gates compiles:

* :data:`SCHEMA` / :func:`make_row` / :func:`validate` — the canonical
  ``BenchRow``: suite, arm, config fingerprint, N/rounds/devices,
  rounds_per_sec + derived metrics, wall/compile split (compile seconds
  come from the existing :class:`~.observatory.CompileLedger`
  attribution), jax/platform/device fields, and the **machine
  calibration fingerprint** — a ~2 s fixed pure-numpy microbenchmark
  (:func:`calibrate`) whose score normalizes cross-box numbers (CHANGES
  records this box itself drifting 1.7x between PRs; raw rounds/sec is
  not comparable across runs, ``norm_rounds_per_sec`` is, to first
  order).  Every bench entrypoint appends rows to
  ``BENCH_ledger.jsonl`` (:func:`append_rows`); legacy artifacts and
  stdout contracts are untouched.

* :func:`bless_perf` / :func:`check_perf` — the run-over-run regression
  gate over a CHEAP pinned subset (:data:`PERF_SUBSET`: flagship
  micro-rounds at tier-1 shapes, AOT-loaded by ``scripts/perf_gate.py``
  so there is no compile wall).  ``check`` compares
  calibration-normalized rounds/sec against ``PERF_goldens.json`` with
  explicit noise bands: fail NAMED above the fail band, warn-only in
  the band below it.  Throughput is estimated as the MAX over repeats
  (the least-noise estimator on a contended 1-vCPU box).

* :func:`bless_budget` / :func:`check_budget` — the tier-1 runtime
  budget over ``BENCH_suite_durations.jsonl`` (written per-test by
  ``tests/conftest.py``): fail NAMED when a test exceeds its committed
  per-test budget or the projected tier-1 total exceeds the 870 s
  ceiling.  Budgets are calibration-normalized too, so a slower box
  does not read as a regression.

* :func:`trend_report` — the cross-PR trend table, rendered from the
  ledger alone (no jax import on this path — readable anywhere).

``scripts/perf_gate.py`` is the CLI (``--bless/--check/--report``).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import (Any, Callable, Dict, List, Mapping, Optional,
                    Sequence, Tuple)

__all__ = [
    "SCHEMA", "LEDGER_BASENAME", "PERF_GOLDEN_BASENAME",
    "DURATIONS_BASENAME", "PERF_SUBSET", "TIER1_CEILING_S",
    "calibrate", "config_fingerprint", "make_row", "validate",
    "append_row", "append_rows", "append_rows_nonfatal",
    "read_bench_ledger", "default_ledger_path",
    "convert_trials", "measure_rps", "bless_perf", "check_perf",
    "bless_budget", "check_budget", "trend_report",
]

SCHEMA = "benchrow/v1"
GOLDEN_SCHEMA = "perf_goldens/v1"
LEDGER_BASENAME = "BENCH_ledger.jsonl"
PERF_GOLDEN_BASENAME = "PERF_goldens.json"
DURATIONS_BASENAME = "BENCH_suite_durations.jsonl"

#: the tier-1 verify wall from ROADMAP.md — the budget gate's ceiling.
TIER1_CEILING_S = 870.0

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# The pinned cheap subset for perf_gate --check: flagship entrypoints
# (verify/lint/fingerprint.py names) that advance a single state arg,
# micro-round host loops at tier-1 canonical shapes.  iters are sized
# so the warm gate stays well under 120 s on a 1-vCPU box.
PERF_SUBSET: Dict[str, Dict[str, int]] = {
    "engine_step_hyparview_n64":    {"iters": 48, "warm": 4, "repeats": 3},
    "sharded_dataplane_round_n64x8": {"iters": 12, "warm": 2, "repeats": 3},
    "dense_hyparview_n256x8":       {"iters": 12, "warm": 2, "repeats": 3},
    "dense_scamp_n256x8":           {"iters": 12, "warm": 2, "repeats": 3},
    "dense_plumtree_n256x8":        {"iters": 12, "warm": 2, "repeats": 3},
}


def default_ledger_path() -> str:
    """``$PARTISAN_BENCH_LEDGER`` or ``<repo>/BENCH_ledger.jsonl`` —
    resolved from this module's location, NOT the cwd, so a bench run
    from a scratch directory still lands in the repo ledger."""
    return os.environ.get("PARTISAN_BENCH_LEDGER",
                          os.path.join(_REPO, LEDGER_BASENAME))


# --------------------------------------------------------- calibration

_CALIB: Optional[Dict[str, float]] = None


def _calib_block(a, b):
    """One fixed unit of work: 8 chained 128x128 f32 matmuls with a
    rescale (keeps values finite without changing the op count)."""
    for _ in range(8):
        a = a @ b
        a *= 1.0 / (abs(a).max() + 1.0)
    return a


def calibrate(target_s: Optional[float] = None, *, force: bool = False
              ) -> Dict[str, float]:
    """The machine calibration fingerprint: run a fixed pure-numpy
    workload for ~``target_s`` wall seconds and return
    ``{"score": work_units_per_sec, "wall_s": ..., "blocks": ...}``.

    The score divides raw rounds/sec (``norm_rounds_per_sec``) and
    multiplies raw durations (``norm_s``), so numbers from boxes of
    different speed land on a shared scale.  Cached per process (one
    ~2 s payment covers every row); ``$PARTISAN_CALIB_SECS`` shortens
    it for tests.  The workload is deterministic — variance across
    calls on one box is scheduler noise, pinned by the determinism-band
    test.
    """
    global _CALIB
    if _CALIB is not None and not force and target_s is None:
        return _CALIB
    import numpy as np
    if target_s is None:
        target_s = float(os.environ.get("PARTISAN_CALIB_SECS", "2.0"))
    rng = np.random.RandomState(0)
    a = rng.rand(128, 128).astype(np.float32)
    b = rng.rand(128, 128).astype(np.float32)
    _calib_block(a, b)                       # untimed spin-up
    blocks = 0
    t0 = time.perf_counter()
    while True:
        a = _calib_block(a, b)
        blocks += 1
        dt = time.perf_counter() - t0
        if dt >= target_s:
            break
    out = {"score": round(blocks / dt, 3), "wall_s": round(dt, 3),
           "blocks": blocks}
    if target_s >= 1.0:                      # only cache full-length runs
        _CALIB = out
    return out


# ----------------------------------------------------------- BenchRow

def config_fingerprint(config: Any) -> Optional[str]:
    """Stable 16-hex fingerprint of an arbitrary config mapping (or any
    JSON-serializable-with-default=str value)."""
    if config is None:
        return None
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


_RUN_ID: Optional[str] = None


def _run_id() -> str:
    global _RUN_ID
    if _RUN_ID is None:
        _RUN_ID = time.strftime("%Y%m%d_%H%M%S") + f"_{os.getpid()}"
    return _RUN_ID


def _device_fields() -> Dict[str, Any]:
    try:
        import jax
        dev = jax.devices()[0]
        return {"jax": jax.__version__, "platform": dev.platform,
                "device_kind": getattr(dev, "device_kind", dev.platform),
                "n_devices": len(jax.devices()),
                "cpu_fallback": dev.platform != "tpu"}
    except Exception:  # noqa: BLE001 — report path has no jax
        return {"jax": None, "platform": None, "device_kind": None,
                "n_devices": None, "cpu_fallback": None}


def make_row(suite: str, arm: str, *,
             config: Any = None,
             n_nodes: Optional[int] = None,
             rounds: Optional[int] = None,
             rounds_per_sec: Optional[float] = None,
             wall_s: Optional[float] = None,
             compile_s: Optional[float] = None,
             metrics: Optional[Mapping[str, Any]] = None,
             calibration: Any = True,
             legacy: bool = False,
             **extra: Any) -> Dict[str, Any]:
    """Build a canonical BenchRow.  ``calibration=True`` runs (or
    reuses) the per-process :func:`calibrate`; pass a calibrate() dict
    to share one, or ``None`` for legacy/backfill rows that predate the
    fingerprint.  ``compile_s`` is the CompileLedger-attributed compile
    wall for this arm (None when unattributed)."""
    if calibration is True:
        calibration = calibrate()
    score = calibration["score"] if isinstance(calibration, Mapping) \
        else calibration
    row: Dict[str, Any] = {
        "schema": SCHEMA, "suite": suite, "arm": arm,
        "config_fp": config_fingerprint(config),
        "n_nodes": n_nodes, "rounds": rounds,
        "rounds_per_sec": None if rounds_per_sec is None
        else round(float(rounds_per_sec), 4),
        "wall_s": None if wall_s is None else round(float(wall_s), 4),
        "compile_s": None if compile_s is None
        else round(float(compile_s), 4),
        "calib_score": None if score is None else round(float(score), 3),
        "norm_rounds_per_sec": None,
        "t_wall": time.time(), "run": _run_id(),
    }
    row.update(_device_fields())
    if "n_devices" in extra:               # caller knows better than jax
        row["n_devices"] = extra.pop("n_devices")
    if rounds_per_sec is not None and score:
        row["norm_rounds_per_sec"] = round(float(rounds_per_sec) / score, 5)
    if metrics:
        row["metrics"] = dict(metrics)
    if legacy:
        row["legacy"] = True
    row.update(extra)
    return row


def validate(row: Any) -> List[str]:
    """-> list of NAMED schema violations (empty = valid BenchRow)."""
    if not isinstance(row, Mapping):
        return [f"BENCHROW INVALID — row is not a mapping: {type(row).__name__}"]
    errs: List[str] = []
    if row.get("schema") != SCHEMA:
        errs.append(f"BENCHROW SCHEMA — expected {SCHEMA!r}, got "
                    f"{row.get('schema')!r}")
    for k in ("suite", "arm", "run"):
        v = row.get(k)
        if not isinstance(v, str) or not v:
            errs.append(f"BENCHROW FIELD {k} — missing or not a "
                        f"non-empty string: {v!r}")
    for k in ("rounds_per_sec", "wall_s", "compile_s", "calib_score",
              "norm_rounds_per_sec", "t_wall"):
        v = row.get(k)
        if v is not None and not isinstance(v, (int, float)):
            errs.append(f"BENCHROW FIELD {k} — not numeric: {v!r}")
        elif isinstance(v, (int, float)) and v < 0:
            errs.append(f"BENCHROW FIELD {k} — negative: {v!r}")
    if not isinstance(row.get("t_wall"), (int, float)):
        errs.append("BENCHROW FIELD t_wall — missing timestamp")
    rps, score = row.get("rounds_per_sec"), row.get("calib_score")
    if isinstance(rps, (int, float)) and isinstance(score, (int, float)) \
            and score > 0 and row.get("norm_rounds_per_sec") is None:
        errs.append("BENCHROW FIELD norm_rounds_per_sec — missing while "
                    "rounds_per_sec and calib_score are both present")
    return errs


def append_rows(rows: Sequence[Mapping[str, Any]],
                path: Optional[str] = None) -> str:
    """Append validated BenchRows to the unified ledger (one JSON line
    each).  Raises ValueError with the NAMED violations on an invalid
    row — a bench must not silently pollute the trajectory."""
    path = path or default_ledger_path()
    for row in rows:
        errs = validate(row)
        if errs:
            raise ValueError("refusing to append invalid BenchRow: "
                             + "; ".join(errs))
    with open(path, "a", encoding="utf-8") as f:
        for row in rows:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    return path


def append_row(row: Mapping[str, Any], path: Optional[str] = None) -> str:
    return append_rows([row], path)


def append_rows_nonfatal(rows: Sequence[Mapping[str, Any]],
                         path: Optional[str] = None) -> Optional[str]:
    """:func:`append_rows` for bench CLIs: a ledger failure must not
    tank a long soak run whose legacy artifacts already landed — it is
    reported LOUDLY on stderr, never silently swallowed."""
    import sys
    try:
        return append_rows(rows, path)
    except Exception as e:  # noqa: BLE001 — warn-and-continue by design
        print(f"benchplane: BENCH_ledger append FAILED "
              f"({type(e).__name__}: {e}) — legacy artifacts are "
              f"unaffected, but this run is missing from the unified "
              f"trajectory", file=sys.stderr)
        return None


def read_bench_ledger(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Read the unified ledger; silently skips blank lines, raises on
    unparseable ones (a corrupt ledger should be loud)."""
    path = path or default_ledger_path()
    rows: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{i + 1}: unparseable ledger line ({e})")
    return rows


def convert_trials(trials_path: str) -> List[Dict[str, Any]]:
    """Back-convert legacy ``BENCH_trials.jsonl`` rows (bench.py's
    per-trial artifact) into BenchRows — the historical seed for the
    unified ledger.  Legacy rows predate calibration, so they carry
    ``calib_score: null`` and ``legacy: true``; their original wall
    timestamps are preserved so the trend report orders them first."""
    out: List[Dict[str, Any]] = []
    with open(trials_path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            t = json.loads(line)
            row = {
                "schema": SCHEMA, "suite": "bench_rumor",
                "arm": t.get("variant", "unknown"),
                "config_fp": config_fingerprint(
                    {"churn": t.get("churn"), "fanout": t.get("fanout")}),
                "n_nodes": t.get("n"), "rounds": t.get("rounds"),
                "rounds_per_sec": t.get("rounds_per_sec"),
                "wall_s": t.get("seconds"), "compile_s": None,
                "calib_score": None, "norm_rounds_per_sec": None,
                "jax": None, "platform": t.get("device"),
                "device_kind": t.get("device"), "n_devices": None,
                "cpu_fallback": (None if t.get("device") is None
                                 else t.get("device") != "tpu"),
                "t_wall": t.get("t_wall", 0.0),
                "run": "legacy_backfill", "legacy": True,
                "metrics": {"trial": t.get("trial"),
                            "infected": t.get("infected")},
            }
            out.append(row)
    return out


# ------------------------------------------------- throughput measure

def measure_rps(fn: Callable, args: tuple, *, iters: int = 16,
                warm: int = 2, repeats: int = 3) -> Dict[str, Any]:
    """Micro-round throughput of a compiled/AOT program: host loop of
    ``iters`` calls, the first output re-fed as the first argument
    (every flagship round is ``state -> (state, metrics)``), synced
    once per repeat.  Returns max-over-repeats rounds/sec — on a noisy
    shared box the max is the least-biased throughput estimate; the
    spread across repeats is reported so the gate can widen its band.
    """
    import jax
    state, rest = args[0], tuple(args[1:])

    def _step(s):
        out = fn(s, *rest)
        return out[0] if isinstance(out, tuple) else out

    for _ in range(warm):
        state = _step(state)
    state = jax.block_until_ready(state)
    samples: List[float] = []
    t_all = time.perf_counter()
    for _ in range(repeats):
        s = state
        t0 = time.perf_counter()
        for _ in range(iters):
            s = _step(s)
        jax.block_until_ready(s)
        samples.append(iters / (time.perf_counter() - t0))
    best = max(samples)
    spread_pct = 100.0 * (best - min(samples)) / best if best else 0.0
    return {"rounds_per_sec": round(best, 4),
            "samples": [round(x, 4) for x in samples],
            "spread_pct": round(spread_pct, 1),
            "wall_s": round(time.perf_counter() - t_all, 3)}


def _default_loader(name: str, build: Callable) -> Tuple[Callable, tuple, str]:
    """(fn, args, how) from a flagship-style builder; perf_gate swaps in
    an AOT-aware loader so --check never compiles."""
    fn, args = build()
    return fn, args, "jit"


# ----------------------------------------------- perf regression gate

def bless_perf(path: str, registry: Mapping[str, Callable],
               subset: Optional[Mapping[str, Mapping[str, int]]] = None,
               *, loader: Callable = _default_loader,
               calibration: Any = True,
               progress: Optional[Callable[[str], None]] = None
               ) -> Dict[str, Any]:
    """Measure the pinned subset and write ``PERF_goldens.json``.  An
    existing file's ``suite_budget`` section is PRESERVED (the two
    blesses are independent: perf rows re-bless after an intended perf
    change, budgets re-bless after a clean tier-1 run)."""
    if calibration is True:
        calibration = calibrate()
    subset = _resolve_subset(registry, subset)
    golden: Dict[str, Any] = {"schema": GOLDEN_SCHEMA,
                              "calibration": calibration, "rows": {}}
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            old = json.load(f)
        if "suite_budget" in old:
            golden["suite_budget"] = old["suite_budget"]
    for name, knobs in subset.items():
        if progress:
            progress(name)
        fn, args, how = loader(name, registry[name])
        m = measure_rps(fn, args, **knobs)
        golden["rows"][name] = {
            "norm_rps": round(m["rounds_per_sec"] / calibration["score"], 5),
            "rounds_per_sec": m["rounds_per_sec"],
            "spread_pct": m["spread_pct"], "iters": knobs.get("iters"),
            "how": how,
        }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(golden, f, indent=2, sort_keys=True)
        f.write("\n")
    return golden


def _resolve_subset(registry, subset):
    if subset is None:
        subset = {k: v for k, v in PERF_SUBSET.items() if k in registry}
        if not subset:     # toy registries: measure everything, default knobs
            subset = {k: {"iters": 16, "warm": 2, "repeats": 3}
                      for k in registry}
    missing = set(subset) - set(registry)
    if missing:
        raise KeyError(f"perf subset names not in registry: "
                       f"{sorted(missing)}")
    return subset


def check_perf(path: str, registry: Mapping[str, Callable],
               subset: Optional[Mapping[str, Mapping[str, int]]] = None,
               *, loader: Callable = _default_loader,
               fail_pct: float = 45.0, warn_pct: float = 18.0,
               calibration: Any = True,
               progress: Optional[Callable[[str], None]] = None
               ) -> Tuple[List[str], List[str], List[Dict[str, Any]]]:
    """The regression gate: -> (errors, warnings, bench_rows).

    Per pinned row, the calibration-normalized rounds/sec is compared
    against the golden.  A drop beyond ``max(fail_pct, 2x the blessed
    repeat spread)`` fails NAMED; a drop beyond ``warn_pct`` but inside
    the fail band is warn-only (explicit noise band — a contended box
    should nag, not block).  ``bench_rows`` are canonical BenchRows
    (suite ``perf_gate``) for the unified ledger, one per measured
    entry, whatever the verdict — the gate's own runs ARE trajectory.
    """
    with open(path, encoding="utf-8") as f:
        golden = json.load(f)
    if calibration is True:
        calibration = calibrate()
    subset = _resolve_subset(registry, subset)
    errors: List[str] = []
    warnings: List[str] = []
    rows: List[Dict[str, Any]] = []
    for name, knobs in subset.items():
        ref = golden.get("rows", {}).get(name)
        if ref is None:
            errors.append(
                f"{name}: PERF GOLDEN MISSING — pinned subset entry has "
                f"no row in {os.path.basename(path)}; run "
                f"scripts/perf_gate.py --bless")
            continue
        if progress:
            progress(name)
        fn, args, how = loader(name, registry[name])
        m = measure_rps(fn, args, **knobs)
        cur_norm = m["rounds_per_sec"] / calibration["score"]
        gold_norm = ref["norm_rps"]
        drop_pct = 100.0 * (gold_norm - cur_norm) / gold_norm \
            if gold_norm else 0.0
        band = max(fail_pct, 2.0 * ref.get("spread_pct", 0.0))
        rows.append(make_row(
            "perf_gate", name, rounds=knobs.get("iters"),
            rounds_per_sec=m["rounds_per_sec"],
            wall_s=m["wall_s"], calibration=calibration,
            metrics={"how": how, "spread_pct": m["spread_pct"],
                     "drop_pct": round(drop_pct, 1),
                     "golden_norm_rps": gold_norm}))
        if drop_pct > band:
            errors.append(
                f"{name}: PERF REGRESSION — normalized rounds/sec "
                f"{cur_norm:.2f} is {drop_pct:.0f}% below the golden "
                f"{gold_norm:.2f} (fail band {band:.0f}%; raw "
                f"{m['rounds_per_sec']:.1f} r/s via {how}, calib score "
                f"{calibration['score']:.0f}) — find the regressing "
                f"change, or re-bless if intended "
                f"(scripts/perf_gate.py --bless)")
        elif drop_pct > warn_pct:
            warnings.append(
                f"{name}: perf warn — normalized rounds/sec "
                f"{cur_norm:.2f} is {drop_pct:.0f}% below golden "
                f"{gold_norm:.2f} (inside the {band:.0f}% fail band; "
                f"watch the trend: scripts/perf_gate.py --report)")
    return errors, warnings, rows


# ------------------------------------------------ tier-1 runtime budget

def read_durations(path: str) -> List[Dict[str, Any]]:
    """Per-test duration rows (``{"bench": "suite_durations", "test":
    nodeid, "duration_s": ...}``) from conftest's artifact."""
    rows = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            r = json.loads(line)
            if r.get("bench") == "suite_durations" and "test" in r:
                rows.append(r)
    return rows


def bless_budget(durations_path: str, *,
                 ceiling_s: float = TIER1_CEILING_S,
                 slack_pct: float = 75.0, floor_s: float = 3.0,
                 ceiling_slack_pct: float = 15.0,
                 calibration: Any = True) -> Dict[str, Any]:
    """Regenerate the per-test budget section from a CLEAN tier-1 run's
    durations artifact.  Tests under ``floor_s`` are pooled into
    ``small_total_s`` (per-test noise there exceeds signal); tests at
    or over it get individual calibration-normalized budgets."""
    if calibration is True:
        calibration = calibrate()
    score = calibration["score"]
    rows = read_durations(durations_path)
    if not rows:
        raise ValueError(f"no suite_durations rows in {durations_path} — "
                         f"run tier-1 first (tests/conftest.py writes it)")
    per: Dict[str, float] = {}
    for r in rows:
        per[r["test"]] = per.get(r["test"], 0.0) + float(r["duration_s"])
    big = {t: d for t, d in per.items() if d >= floor_s}
    small_total = sum(d for d in per.values()) - sum(big.values())
    return {
        "ceiling_s": ceiling_s, "slack_pct": slack_pct,
        "floor_s": floor_s, "calib_score": score,
        "ceiling_slack_pct": ceiling_slack_pct,
        "n_tests": len(per), "small_total_s": round(small_total, 1),
        "total_s": round(sum(per.values()), 1),
        "tests": {t: {"budget_s": round(d, 2),
                      "norm_s": round(d * score, 1)}
                  for t, d in sorted(big.items())},
    }


def check_budget(budget: Mapping[str, Any], durations_path: str, *,
                 calibration: Any = True
                 ) -> Tuple[List[str], List[str], Dict[str, Any]]:
    """The tier-1 runtime-budget gate: -> (errors, warnings, info).

    NAMED failures: a per-test duration whose calibration-normalized
    value exceeds its committed budget + slack, or a projected suite
    total beyond the ceiling's own fail band.  The projection charges
    every budgeted test its CURRENT duration when observed this run and
    its BLESSED budget when not (a partial run still projects the full
    suite), plus the pooled small-test total — so truncation cannot
    hide an overrun.

    The per-test legs are calibration-normalized (cross-box
    comparability); the ceiling leg is RAW same-box seconds — the
    ceiling is a wall-clock CI property of the box running the suite,
    and the ~2 s calibration snapshot's scheduler noise (up to ~2x on
    a contended 1-vCPU box) must not modulate a wall-clock verdict.
    Like the perf leg's fail/warn bands, the ceiling has an explicit
    noise band: projected > ceiling warns, projected >
    ceiling * (1 + ceiling_slack_pct/100) fails NAMED — a
    timeout-truncated artifact totals ≈ the wall by construction, so a
    margin-free ceiling would be a coin flip.
    """
    if calibration is True:
        calibration = calibrate()
    score = calibration["score"]
    slack = 1.0 + budget.get("slack_pct", 75.0) / 100.0
    floor = budget.get("floor_s", 3.0)
    rows = read_durations(durations_path)
    per: Dict[str, float] = {}
    for r in rows:
        per[r["test"]] = per.get(r["test"], 0.0) + float(r["duration_s"])
    errors: List[str] = []
    warnings: List[str] = []
    budgets = budget.get("tests", {})
    for test, d in sorted(per.items(), key=lambda kv: -kv[1]):
        cur_norm = d * score
        ref = budgets.get(test)
        if ref is None:
            if d >= floor:
                warnings.append(
                    f"{test}: unbudgeted test took {d:.1f}s (>= the "
                    f"{floor:.0f}s floor) — re-bless budgets after a "
                    f"clean run (scripts/perf_gate.py --bless) or "
                    f"re-tier it")
            continue
        if cur_norm > ref["norm_s"] * slack and d >= floor:
            errors.append(
                f"{test}: DURATION BUDGET OVERRUN — {d:.1f}s this run "
                f"(normalized {cur_norm:.0f}) vs committed budget "
                f"{ref['budget_s']:.1f}s (+{budget.get('slack_pct', 75):.0f}% "
                f"slack, normalized cap {ref['norm_s'] * slack:.0f}) — "
                f"re-tier the test (slow marker / lowered-text twin) or "
                f"re-bless after an intended change")
    # projected full-suite total in RAW same-box seconds (see docstring)
    projected_s = 0.0
    for test, ref in budgets.items():
        projected_s += per[test] if test in per else ref["budget_s"]
    small = budget.get("small_total_s", 0.0)
    observed_small = sum(d for t, d in per.items() if t not in budgets)
    projected_s += max(small, observed_small)
    ceiling = budget.get("ceiling_s", TIER1_CEILING_S)
    c_slack_pct = budget.get("ceiling_slack_pct", 15.0)
    fail_s = ceiling * (1.0 + c_slack_pct / 100.0)
    info = {"projected_s": round(projected_s, 1), "ceiling_s": ceiling,
            "ceiling_fail_s": round(fail_s, 1),
            "observed_tests": len(per), "budgeted_tests": len(budgets)}
    if projected_s > ceiling:
        top = sorted(budgets.items(),
                     key=lambda kv: -per.get(kv[0], kv[1]["budget_s"]))[:5]
        tops = ", ".join(f"{t}={per.get(t, ref['budget_s']):.0f}s"
                         for t, ref in top)
        msg = (f"TIER-1 RUNTIME BUDGET — projected suite total "
               f"{projected_s:.0f}s exceeds the {ceiling:.0f}s ceiling "
               f"(fail band {fail_s:.0f}s; top contributors: {tops}) — "
               f"re-tier the heaviest tests (ROADMAP tier-1 velocity "
               f"item) before they truncate CI")
        if projected_s > fail_s:
            errors.append(msg)
        else:
            warnings.append(msg.replace(
                "TIER-1 RUNTIME BUDGET —",
                "tier-1 runtime budget warn —", 1))
    return errors, warnings, info


# ------------------------------------------------------- trend report

def trend_report(rows: Sequence[Mapping[str, Any]], top: int = 20) -> str:
    """The cross-PR trend table, from ledger rows alone (no jax).  One
    line per (suite, arm): run count, first/latest normalized
    rounds/sec (falls back to raw for legacy rows, marked ``raw``),
    and the latest-vs-prior-mean delta."""
    groups: Dict[Tuple[str, str], List[Mapping[str, Any]]] = {}
    for r in rows:
        if r.get("schema") != SCHEMA:
            continue
        groups.setdefault((str(r.get("suite")), str(r.get("arm"))),
                          []).append(r)
    suites = {k[0] for k in groups}
    lines = [f"benchplane trend — {len(rows)} rows, {len(suites)} suites, "
             f"{len(groups)} (suite, arm) series",
             f"{'suite':<16} {'arm':<26} {'runs':>4} {'first':>10} "
             f"{'latest':>10} {'delta':>7}  unit"]

    def _val(r):
        v = r.get("norm_rounds_per_sec")
        if v is not None:
            return v, "norm r/s"
        if r.get("rounds_per_sec") is not None:
            return r["rounds_per_sec"], "raw r/s"
        if r.get("wall_s") is not None:
            return r["wall_s"], "raw s"
        return None, ""

    scored = []
    for (suite, arm), rs in groups.items():
        rs = sorted(rs, key=lambda r: (r.get("t_wall") or 0.0))
        vals = [(v, u) for v, u in (_val(r) for r in rs) if v is not None]
        if not vals:
            continue
        unit = vals[-1][1]
        series = [v for v, u in vals if u == unit]
        first, latest = series[0], series[-1]
        if len(series) > 1:
            prior = sum(series[:-1]) / (len(series) - 1)
            delta = 100.0 * (latest - prior) / prior if prior else 0.0
            dtxt = f"{delta:+.0f}%"
        else:
            dtxt = "-"
        scored.append((suite, arm, len(rs), first, latest, dtxt, unit))
    for suite, arm, n, first, latest, dtxt, unit in sorted(scored)[:top]:
        lines.append(f"{suite:<16} {arm:<26} {n:>4} {first:>10.2f} "
                     f"{latest:>10.2f} {dtxt:>7}  {unit}")
    if len(scored) > top:
        lines.append(f"... {len(scored) - top} more series (--top)")
    calibs = [r["calib_score"] for r in rows
              if isinstance(r.get("calib_score"), (int, float))]
    if calibs:
        lines.append(f"calibration score range: {min(calibs):.0f} .. "
                     f"{max(calibs):.0f} (box drift "
                     f"{max(calibs) / min(calibs):.2f}x — normalized "
                     f"columns absorb it)")
    return "\n".join(lines)
