"""In-scan alerting — device-side detectors over the metrics plane.

Alerting in the reference deployment is a Prometheus rule engine
polling the exporter: rules like ``rate(partisan_rpc_latency_bucket
{le="4"}[1m])`` fire minutes after the regression.  Here the detectors
run INSIDE the jitted round step, folding over the same scalar taps the
metrics ring records — so an alert asserts in the very round its
condition sustains, is visible in the next window flush, and costs a
handful of scalar compares (no extra collectives, no host hops,
program shape unchanged when disabled).

Three detectors, each a *sustained-condition* counter (``consec`` in
:class:`AlertState`): the per-round boolean must hold for ``k``
consecutive rounds before the alert bit asserts, which is exactly the
Prometheus ``for:`` clause moved on-device.

* **convergence stall** — deliveries flatlined while traffic is still
  in flight (``msgs_delivered == 0 and inflight > 0``).  The classic
  gossip failure mode: the overlay wedged, nothing makes progress.
* **SLO burn** — the per-round *delta* of the PR-8 latency histogram
  columns shows more than ``slo_burn_milli``/1000 of completions
  landing past the deadline bucket.  Burn-rate alerting (the SRE
  workbook shape) over cumulative bucket counters: :class:`AlertState`
  snapshots ``(above, total)`` so the detector sees per-round rates,
  not lifetime averages.
* **partition suspicion** — the health plane's reachability fraction
  (``health_reach_frac``, a [0, 1] gauge from the PR-13 BFS probe)
  sits below ``partition_frac_milli``/1000: some alive node cannot
  reach the probe root, sustained — the overlay is likely split.

Each detector is gated at BUILD time on its input columns being
present in the registry (Python ``if``, not ``lax.cond``), so an
engine-only registry gets a stall detector and nothing else, and the
jitted program never carries a dead detector's arithmetic.

Host side, :class:`AlertFirer` edge-detects the flushed alert columns
and emits ``telemetry.emit_event`` rows on each firing/resolved
transition; :func:`alerts_exposition` renders the currently-firing set
in the Prometheus ``ALERTS{alertname=...}`` convention so scrapers
treat the in-scan detectors exactly like rule-engine alerts.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..workload import latency
from .registry import GAUGE, MetricRegistry, MetricSpec

# Alert bit positions (stable: ``alerts_active`` is the OR of
# ``1 << code`` over firing alerts).
ALERT_STALL = 0
ALERT_SLO_BURN = 1
ALERT_PARTITION = 2
N_ALERTS = 3

ALERT_NAMES: Tuple[str, ...] = (
    "convergence_stall", "slo_burn", "partition_suspected")

# Ring column per alert, index-aligned with the codes above.
ALERT_COLUMNS: Tuple[str, ...] = (
    "alert_stall", "alert_slo_burn", "alert_partition")

ALERT_SPECS: Tuple[MetricSpec, ...] = (
    MetricSpec("alert_stall", GAUGE,
               "1 while the convergence-stall alert is firing "
               "(msgs_delivered == 0 with inflight > 0, sustained)."),
    MetricSpec("alert_slo_burn", GAUGE,
               "1 while the SLO burn-rate alert is firing (per-round "
               "fraction of completions past the deadline bucket above "
               "threshold, sustained)."),
    MetricSpec("alert_partition", GAUGE,
               "1 while the partition-suspicion alert is firing "
               "(health_reach_frac below threshold, sustained)."),
    MetricSpec("alerts_active", GAUGE,
               "Bitmask of firing alerts (bit i = alert code i)."),
)


def alert_specs() -> Tuple[MetricSpec, ...]:
    """The ring columns the alert plane records (append via
    ``registry.with_specs(alert_specs())``)."""
    return ALERT_SPECS


def alert_registry(registry: MetricRegistry) -> MetricRegistry:
    """``registry`` plus the alert columns."""
    return registry.with_specs(ALERT_SPECS)


@dataclasses.dataclass(frozen=True)
class AlertSpec:
    """Compile-time alert-plane configuration (thresholds in integer
    milli-units — the device compares pure int32/float32 scalars, no
    host floats baked beyond these constants).

    ``*_rounds`` fields are the Prometheus ``for:`` durations: the
    round-condition must hold that many CONSECUTIVE rounds to fire.
    """
    stall_rounds: int = 8
    slo_family: str = "rpc_latency"
    slo_deadline_rounds: int = 4
    slo_burn_milli: int = 500
    slo_burn_rounds: int = 4
    partition_frac_milli: int = 990
    partition_rounds: int = 4

    def __post_init__(self) -> None:
        for f in ("stall_rounds", "slo_burn_rounds", "partition_rounds"):
            if int(getattr(self, f)) < 1:
                raise ValueError(f"AlertSpec.{f} must be >= 1")
        if not (0 < int(self.slo_burn_milli) <= 1000):
            raise ValueError("AlertSpec.slo_burn_milli must be in (0, 1000]")
        if not (0 < int(self.partition_frac_milli) <= 1000):
            raise ValueError(
                "AlertSpec.partition_frac_milli must be in (0, 1000]")
        if int(self.slo_deadline_rounds) < 0:
            raise ValueError("AlertSpec.slo_deadline_rounds must be >= 0")


@struct.dataclass
class AlertState:
    """Scan-carried detector state: consecutive-round counters per
    alert plus the previous round's ``(above_deadline, total)``
    histogram snapshot (the burn detector differentiates cumulative
    bucket counters)."""
    consec: jax.Array     # [N_ALERTS] int32
    prev_hist: jax.Array  # [2] int32: (above deadline, total completions)


def make_alert_state() -> AlertState:
    return AlertState(consec=jnp.zeros((N_ALERTS,), jnp.int32),
                      prev_hist=jnp.zeros((2,), jnp.int32))


def _deadline_split(spec: AlertSpec) -> Tuple[Tuple[str, ...],
                                              Tuple[str, ...]]:
    """Partition the histogram family's bucket columns into (within
    deadline, past deadline).  A bucket whose inclusive upper edge is
    <= the deadline holds only in-SLO completions; every other bucket
    (including +Inf) counts as burn.  Edge-straddling samples land in
    the conservative (burn) side — same rounding the Prometheus rule
    over ``le`` buckets makes."""
    within: List[str] = []
    above: List[str] = []
    for i, b in enumerate(latency.BUCKET_NAMES):
        name = f"{spec.slo_family}__bucket_{b}"
        edge_ok = (i < latency.N_BUCKETS - 1
                   and latency.BUCKET_EDGES[i] <= spec.slo_deadline_rounds)
        (within if edge_ok else above).append(name)
    return tuple(within), tuple(above)


def make_alert_plane(
    spec: AlertSpec, registry: MetricRegistry,
) -> Tuple[Callable[[AlertState, Mapping[str, jax.Array]],
                    Tuple[AlertState, Dict[str, jax.Array]]],
           Tuple[str, ...]]:
    """Build the in-scan alert update.

    Returns ``(update, detectors)`` where ``update(astate, vals)``
    takes the round's registry-named scalar taps (the dict the runner
    packs into the metrics ring) and returns the advanced state plus
    the alert columns to merge into that dict, and ``detectors`` names
    the alerts whose input columns the registry actually carries
    (build-time gating — absent detectors contribute constant 0
    columns, which the registry mask then folds away if disabled).
    """
    names = set(registry.names)
    stall_on = {"msgs_delivered", "inflight"} <= names
    within, above = _deadline_split(spec)
    fam_cols = within + above
    burn_on = set(fam_cols) <= names
    part_on = "health_reach_frac" in names
    detectors = tuple(n for n, on in zip(
        ALERT_NAMES, (stall_on, burn_on, part_on)) if on)

    thresh = jnp.asarray(
        [spec.stall_rounds, spec.slo_burn_rounds, spec.partition_rounds],
        jnp.int32)
    false = jnp.asarray(False)

    def update(astate: AlertState, vals: Mapping[str, jax.Array]
               ) -> Tuple[AlertState, Dict[str, jax.Array]]:
        prev_hist = astate.prev_hist
        stall = false
        burn = false
        part = false
        if stall_on:
            stall = ((jnp.asarray(vals["msgs_delivered"], jnp.int32) == 0)
                     & (jnp.asarray(vals["inflight"], jnp.int32) > 0))
        if burn_on:
            hi = sum(jnp.asarray(vals[n], jnp.int32) for n in above)
            tot = hi + sum(jnp.asarray(vals[n], jnp.int32) for n in within)
            d_hi = hi - prev_hist[0]
            d_tot = tot - prev_hist[1]
            # per-round burn rate in milli: d_hi/d_tot > milli/1000,
            # cross-multiplied to stay in int32 (no division)
            burn = ((d_tot > 0)
                    & (d_hi * 1000 > jnp.int32(spec.slo_burn_milli) * d_tot))
            prev_hist = jnp.stack([hi, tot])
        if part_on:
            frac = jnp.asarray(vals["health_reach_frac"], jnp.float32)
            part = frac * 1000.0 < jnp.float32(spec.partition_frac_milli)
        conds = jnp.stack([stall, burn, part])
        consec = jnp.where(conds, astate.consec + 1, 0).astype(jnp.int32)
        firing = (consec >= thresh).astype(jnp.int32)
        bits = jnp.asarray([1 << i for i in range(N_ALERTS)], jnp.int32)
        cols = {c: firing[i] for i, c in enumerate(ALERT_COLUMNS)}
        cols["alerts_active"] = jnp.sum(firing * bits)
        return AlertState(consec=consec, prev_hist=prev_hist), cols

    return update, detectors


# ------------------------------------------------------------------ host

class AlertFirer:
    """Edge-detector over flushed metric rows: emits one
    ``telemetry.emit_event`` row per firing/resolved TRANSITION (never
    per round — a sustained alert is one event, like a Prometheus
    notification, not a log line per evaluation)."""

    def __init__(self) -> None:
        self.active: Dict[str, bool] = {n: False for n in ALERT_NAMES}

    def observe(self, row: Mapping[str, Any]
                ) -> List[Tuple[str, str, Optional[int]]]:
        """Fold one flushed ring row; returns the transitions as
        ``(alertname, "firing"|"resolved", round)`` tuples (also
        emitted as host events)."""
        from . import emit_event
        rnd = row.get("round")
        rnd = int(rnd) if rnd is not None else None
        out: List[Tuple[str, str, Optional[int]]] = []
        for name, col in zip(ALERT_NAMES, ALERT_COLUMNS):
            v = row.get(col)
            if v is None:
                continue
            firing = float(v) >= 1.0
            if firing == self.active[name]:
                continue
            self.active[name] = firing
            state = "firing" if firing else "resolved"
            emit_event("alert", alertname=name, alertstate=state,
                       **({"round": rnd} if rnd is not None else {}))
            out.append((name, state, rnd))
        return out

    def observe_rows(self, rows) -> List[Tuple[str, str, Optional[int]]]:
        out: List[Tuple[str, str, Optional[int]]] = []
        for r in rows:
            out.extend(self.observe(r))
        return out

    def firing(self) -> Tuple[str, ...]:
        return tuple(n for n in ALERT_NAMES if self.active[n])


def alerts_exposition(firer: AlertFirer, namespace: str = "partisan") -> str:
    """Render the currently-firing set in the Prometheus rule-engine
    convention: an ``ALERTS{alertname=..., alertstate="firing"} 1``
    gauge family (the exact series a real Prometheus server synthesizes
    for active rules, so dashboards written against rule alerts read
    in-scan alerts unchanged)."""
    lines = [f"# HELP {namespace}_ALERTS In-scan alert plane "
             f"(device-evaluated detectors).",
             f"# TYPE {namespace}_ALERTS gauge"]
    for name in ALERT_NAMES:
        if firer.active[name]:
            lines.append(f'{namespace}_ALERTS{{alertname="{name}",'
                         f'alertstate="firing"}} 1')
    return "\n".join(lines) + "\n"
