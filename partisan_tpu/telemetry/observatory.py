"""Compile observatory (ISSUE 14): the system watching itself compile.

The ROADMAP names XLA compile time as the binding constraint, yet until
now nothing recorded *what* compiles, for *how long*, or whether the
persistent ``.jax_cache`` hit.  This module adds the three legs:

* :class:`CompileLedger` — ``jax.monitoring`` listeners capture every
  trace/lower/backend-compile duration and persistent-cache hit/miss,
  attributed to a program name via the :meth:`CompileLedger.attribute`
  context manager (the flagship-entrypoint registry of
  ``verify/lint/fingerprint.py`` supplies the canonical names).  Rows
  append to ``COMPILE_ledger.jsonl``; counter deltas fan out to any
  :class:`~.sinks.TelemetrySink` (``LEDGER_SPECS`` names them for the
  Prometheus exposition); :meth:`CompileLedger.compile_spans` renders
  the durations as Perfetto slices (``perfetto.chrome_trace``'s
  ``compile_spans=``).

* :class:`StreamSpec` — the host end of the ordered ``io_callback``
  drain the windowed runner / dense dataplane / explorer thread through
  their scans: window metric rows and a round heartbeat reach host
  sinks MID-SCAN instead of one transfer at the end.  ``stream=None``
  compiles a byte-identical program (the ``flight=None`` /
  ``control=None`` discipline), and streamed rows are bit-equal to the
  windowed runner's flushed rows (same float32 ``registry.pack`` row,
  pinned in tests).  Programs containing the callback are NOT
  persistently cacheable (the cache key includes the host callable), so
  the flagship ``stream=None`` programs — the ones the warm-cache
  discipline protects — never carry it.

* the recompile-regression gate — :func:`bless_goldens` /
  :func:`check_goldens` replay the flagship entrypoints against the
  committed ``COMPILE_goldens.json`` (lowered-module hash + canonical
  arg shapes + a pinned cache verdict) and fail with NAMED errors on
  program drift, shape drift, or an unexpected recompile (a persistent
  cache miss where a hit is pinned).  Wall-clock never enters the
  verdict, so the gate is stable in CI.  ``scripts/observatory.py`` is
  the CLI (``--check`` / ``--bless`` / ``--report``).

``jax.monitoring`` has no public listener deregistration, so a ledger's
callbacks stay registered for the life of the process and gate on the
ledger's ``enabled`` flag; :meth:`CompileLedger.uninstall` flips it.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import time
from typing import (Any, Callable, Dict, IO, List, Mapping, Optional,
                    Sequence, Tuple)

import numpy as np

from .registry import COUNTER, MetricSpec

__all__ = [
    "CompileLedger", "StreamSpec", "LEDGER_SPECS",
    "GOLDEN_BASENAME", "LEDGER_BASENAME",
    "bless_goldens", "check_goldens", "measure_entry", "configure_cache",
    "ledger_report",
]

GOLDEN_BASENAME = "COMPILE_goldens.json"
LEDGER_BASENAME = "COMPILE_ledger.jsonl"

# jax.monitoring event name -> ledger short name.  Durations arrive via
# record_event_duration_secs listeners, counts via record_event
# listeners.  Verified against this jax version in tests (the names are
# jax-internal; the ledger degrades to "nothing recorded" if they move,
# and the attribution round-trip test catches that loudly).
DURATION_EVENTS: Dict[str, str] = {
    "/jax/core/compile/jaxpr_trace_duration": "jaxpr_trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "backend_compile",
    "/jax/compilation_cache/compile_time_saved_sec": "compile_time_saved",
    "/jax/compilation_cache/cache_retrieval_time_sec": "cache_retrieval",
}
COUNT_EVENTS: Dict[str, str] = {
    "/jax/compilation_cache/cache_hits": "cache_hit",
    "/jax/compilation_cache/cache_misses": "cache_miss",
    "/jax/compilation_cache/compile_requests_use_cache": "cache_request",
}

#: AOT plane events (ISSUE 17) — recorded explicitly via
#: :meth:`CompileLedger.record_aot`, not jax.monitoring:
#: ``aot_export`` (artifact built, duration = export+compile wall),
#: ``aot_load`` (artifact adopted, duration = deserialize+first-call
#: wall — the number that replaces a cold compile), ``aot_stale``
#: (artifact rejected with a NAMED ``reason`` — never silent).
AOT_EVENTS: Tuple[str, ...] = ("aot_export", "aot_load", "aot_stale")

#: gate-verdict rows the recompile gate itself writes (ISSUE 18):
#: ``cache_evicted`` marks a persistent-cache miss with an UNCHANGED
#: module hash — a stale/evicted ``.jax_cache`` entry, not a recompile
#: regression — so ``--report`` can count evictions separately from
#: genuine misses.
GATE_EVENTS: Tuple[str, ...] = ("cache_evicted",)

#: Prometheus families the ledger feeds through TelemetrySink.write_row
#: (counter deltas; PrometheusSink accumulates into *_total samples).
LEDGER_SPECS: Tuple[MetricSpec, ...] = (
    MetricSpec("xla_backend_compiles", COUNTER,
               "XLA backend_compile invocations observed by the ledger."),
    MetricSpec("xla_compile_seconds", COUNTER,
               "Wall seconds spent in XLA backend_compile."),
    MetricSpec("xla_cache_hits", COUNTER,
               "Persistent compilation-cache hits."),
    MetricSpec("xla_cache_misses", COUNTER,
               "Persistent compilation-cache misses (entry written)."),
    MetricSpec("xla_cache_requests", COUNTER,
               "Compile requests that consulted the persistent cache."),
    MetricSpec("xla_compile_seconds_saved", COUNTER,
               "Compile seconds avoided via persistent-cache hits."),
)

# short event name -> sink counter-row builder
_SINK_ROWS: Dict[str, Callable[[Optional[float]], Dict[str, float]]] = {
    "backend_compile": lambda d: {"xla_backend_compiles": 1.0,
                                  "xla_compile_seconds": float(d or 0.0)},
    "cache_hit": lambda d: {"xla_cache_hits": 1.0},
    "cache_miss": lambda d: {"xla_cache_misses": 1.0},
    "cache_request": lambda d: {"xla_cache_requests": 1.0},
    "compile_time_saved": lambda d: {
        "xla_compile_seconds_saved": float(d or 0.0)},
}


class CompileLedger:
    """Per-program compile/cache ledger over ``jax.monitoring``.

    ``path`` (or an open file) receives one JSON object per event;
    ``sinks`` receive counter-delta rows named by :data:`LEDGER_SPECS`.
    Attribution is a host-side dynamic scope::

        ledger = CompileLedger(path="COMPILE_ledger.jsonl").install()
        with ledger.attribute("engine_step_hyparview_n64", fingerprint=h):
            step.trace(world).lower().compile()

    Events outside any ``attribute`` scope record with ``program=None``
    (jit fires compile requests for small helper programs too — multiple
    rows per attributed program are normal and the summary counts them
    all under the scope's name).
    """

    def __init__(self, path: Optional[Any] = None,
                 sinks: Sequence[Any] = (), mode: str = "a"):
        self.rows: List[Dict[str, Any]] = []
        self.sinks = list(sinks)
        self.run_id = f"{int(time.time() * 1000):x}"
        self._stack: List[Tuple[str, Optional[str]]] = []
        self._seq = 0
        self._enabled = False
        self._installed = False
        self._f: Optional[IO[str]] = None
        self._owns_f = False
        if path is not None:
            if isinstance(path, str):
                self._f = open(path, mode)
                self._owns_f = True
            else:
                self._f = path

    # ------------------------------------------------------ installation

    def install(self) -> "CompileLedger":
        """Register the monitoring listeners (idempotent) and enable
        recording.  Listeners survive for the process lifetime —
        ``uninstall`` only disables them (jax.monitoring has no public
        unregister)."""
        from jax import monitoring
        if not self._installed:
            monitoring.register_event_duration_secs_listener(
                self._on_duration)
            monitoring.register_event_listener(self._on_event)
            self._installed = True
        self._enabled = True
        return self

    def uninstall(self) -> None:
        self._enabled = False

    def close(self) -> None:
        self.uninstall()
        if self._owns_f and self._f is not None and not self._f.closed:
            self._f.close()

    # ------------------------------------------------------- attribution

    @contextlib.contextmanager
    def attribute(self, program: str, fingerprint: Optional[str] = None):
        """Attribute every compile/cache event in the scope to
        ``program`` (innermost scope wins when nested)."""
        self._stack.append((str(program), fingerprint))
        try:
            yield self
        finally:
            self._stack.pop()

    def _current(self) -> Tuple[Optional[str], Optional[str]]:
        return self._stack[-1] if self._stack else (None, None)

    # --------------------------------------------------------- listeners

    def _on_duration(self, event: str, duration: float, **kw) -> None:
        short = DURATION_EVENTS.get(event)
        if self._enabled and short is not None:
            self._record(short, float(duration))

    def _on_event(self, event: str, **kw) -> None:
        short = COUNT_EVENTS.get(event)
        if self._enabled and short is not None:
            self._record(short, None)

    def _record(self, short: str, duration: Optional[float],
                program: Optional[str] = None,
                fingerprint: Optional[str] = None,
                reason: Optional[str] = None) -> None:
        if program is None:
            program, fingerprint = self._current()
        row: Dict[str, Any] = {
            "event": short, "t_wall": time.time(), "seq": self._seq,
            "run": self.run_id, "program": program,
        }
        self._seq += 1
        if duration is not None:
            row["duration_s"] = duration
        if fingerprint is not None:
            row["fingerprint"] = fingerprint
        if reason is not None:
            row["reason"] = reason
        self.rows.append(row)
        if self._f is not None:
            self._f.write(json.dumps(row) + "\n")
            self._f.flush()
        mk = _SINK_ROWS.get(short)
        if mk is not None and self.sinks:
            srow = mk(duration)
            for s in self.sinks:
                s.write_row(srow)

    def record_aot(self, event: str, program: str,
                   duration: Optional[float] = None,
                   reason: Optional[str] = None,
                   fingerprint: Optional[str] = None) -> None:
        """Record an AOT-plane row (``aot_export`` / ``aot_load`` /
        ``aot_stale`` — :data:`AOT_EVENTS`) attributed to ``program``
        explicitly (no :meth:`attribute` scope needed; staleness often
        fires before any compile scope exists)."""
        if event not in AOT_EVENTS:
            raise ValueError(f"unknown AOT event {event!r}; "
                             f"expected one of {AOT_EVENTS}")
        self._record(event, duration, program=program,
                     fingerprint=fingerprint, reason=reason)

    def record_gate(self, event: str, program: str,
                    reason: Optional[str] = None,
                    fingerprint: Optional[str] = None) -> None:
        """Record a gate-verdict row (:data:`GATE_EVENTS`) attributed
        to ``program`` — e.g. ``cache_evicted`` when the recompile gate
        proves a miss is a stale cache entry, not a program change."""
        if event not in GATE_EVENTS:
            raise ValueError(f"unknown gate event {event!r}; "
                             f"expected one of {GATE_EVENTS}")
        self._record(event, None, program=program,
                     fingerprint=fingerprint, reason=reason)

    # ----------------------------------------------------------- queries

    def rows_for(self, program: Optional[str]) -> List[Dict[str, Any]]:
        return [r for r in self.rows if r["program"] == program]

    def count(self, short: str, program: Optional[str] = None) -> int:
        return sum(1 for r in self.rows
                   if r["event"] == short
                   and (program is None or r["program"] == program))

    def hits(self, program: str) -> int:
        return self.count("cache_hit", program)

    def misses(self, program: str) -> int:
        return self.count("cache_miss", program)

    def summary(self) -> Dict[str, Dict[str, Any]]:
        """program -> {compiles, compile_s, cache_hits, cache_misses,
        saved_s} (unattributed events under the ``None`` key)."""
        out: Dict[Any, Dict[str, Any]] = {}
        for r in self.rows:
            d = out.setdefault(r["program"], {
                "compiles": 0, "compile_s": 0.0, "cache_hits": 0,
                "cache_misses": 0, "cache_requests": 0, "saved_s": 0.0})
            ev = r["event"]
            if ev == "backend_compile":
                d["compiles"] += 1
                d["compile_s"] += r.get("duration_s", 0.0)
            elif ev == "cache_hit":
                d["cache_hits"] += 1
            elif ev == "cache_miss":
                d["cache_misses"] += 1
            elif ev == "cache_request":
                d["cache_requests"] += 1
            elif ev == "compile_time_saved":
                d["saved_s"] += r.get("duration_s", 0.0)
        return out

    def compile_spans(self) -> List[Dict[str, Any]]:
        """Duration rows as Perfetto slice dicts for
        ``perfetto.chrome_trace(compile_spans=...)``: each span carries
        its wall start/duration and the attributed program name."""
        spans = []
        for r in self.rows:
            d = r.get("duration_s")
            if d is None:
                continue
            prog = r["program"] or "unattributed"
            spans.append({"name": f"{prog}:{r['event']}",
                          "event": r["event"], "program": prog,
                          "t_start": r["t_wall"] - d, "duration_s": d})
        return spans


# ------------------------------------------------------------- streaming

class StreamSpec:
    """Host drain for mid-scan telemetry (the ``io_callback`` leg).

    Consumed by ``telemetry.runner.make_window_runner(stream=)``,
    ``parallel.dense_dataplane.run_sharded(stream=)`` and
    ``verify.explorer.Explorer(stream=)``:

    * :meth:`_drain_row` — ordered callback target for the windowed
      runner: one packed ``[K]`` float32 registry row per round,
      decoded with the registry bound via :meth:`bind` (bit-equal to
      the ring flush's rows — same float32 source).
    * :meth:`_drain_metrics` — ordered callback target for the dense
      dataplane's replicated per-round metrics dict (no registry; a
      synthetic ``round`` counts callbacks when the dict carries none).
    * :meth:`_beat` — UNORDERED round heartbeat for the explorer's
      vmapped scan (ordered effects cannot be vmapped; the heartbeat's
      operand is unbatched so it fires once per round, not B times).

    Rows fan out to ``sinks`` / ``on_row``; beats to ``on_beat``.
    ``keep_rows=True`` retains rows in memory for parity tests.  All
    targets run on the host mid-scan — callers must
    ``jax.effects_barrier()`` before trusting final totals (the runner
    entry points do).
    """

    def __init__(self, *, registry: Any = None, sinks: Sequence[Any] = (),
                 on_row: Optional[Callable[[Dict[str, float]], None]] = None,
                 on_beat: Optional[Callable[[int], None]] = None,
                 keep_rows: bool = False):
        self.registry = registry
        self.sinks = list(sinks)
        self.on_row = on_row
        self.on_beat = on_beat
        self.keep_rows = keep_rows
        self.rows: List[Dict[str, float]] = []
        self.rows_streamed = 0
        self.beats = 0
        self.last_round = -1
        self.t_first: Optional[float] = None
        self.t_last: Optional[float] = None

    def bind(self, registry: Any) -> "StreamSpec":
        """Attach the registry that decodes packed rows (the runner
        calls this; explicit construction with ``registry=`` also
        works)."""
        if self.registry is None:
            self.registry = registry
        return self

    # -------------------------------------------------- callback targets

    def _note(self, rnd: float) -> None:
        self.t_last = time.time()
        if self.t_first is None:
            self.t_first = self.t_last
        r = int(rnd)
        if r > self.last_round:
            self.last_round = r

    def _fan_out(self, row: Dict[str, float]) -> None:
        self.rows_streamed += 1
        if self.keep_rows:
            self.rows.append(row)
        for s in self.sinks:
            s.write_row(row)
        if self.on_row is not None:
            self.on_row(row)

    def _drain_row(self, packed) -> None:
        if self.registry is None:
            raise RuntimeError("StreamSpec.bind(registry) before streaming "
                               "packed rows")
        vals = np.asarray(packed)
        row = dict(zip(self.registry.names, map(float, vals)))
        self._note(row.get("round", self.rows_streamed))
        self._fan_out(row)

    def _drain_metrics(self, metrics: Mapping[str, Any]) -> None:
        row = {k: float(np.asarray(v)) for k, v in metrics.items()}
        row.setdefault("round", float(self.rows_streamed))
        self._note(row["round"])
        self._fan_out(row)

    def _beat(self, rnd) -> None:
        r = int(np.asarray(rnd))
        self.beats += 1
        self._note(r)
        if self.on_beat is not None:
            self.on_beat(r)

    # ----------------------------------------------------------- queries

    def progress(self) -> Dict[str, Any]:
        """Live view for watchdogs: the last round the device reported,
        stream volume, and the age of the last callback."""
        now = time.time()
        return {
            "last_round": self.last_round,
            "rows_streamed": self.rows_streamed,
            "beats": self.beats,
            "age_s": (now - self.t_last) if self.t_last is not None
            else None,
        }


# --------------------------------------------------- recompile gate core

def configure_cache(cache_dir: str, *, record_all: bool = True
                    ) -> Dict[str, Any]:
    """Point jax at ``cache_dir`` and (with ``record_all``) drop the
    persistent-cache write thresholds to zero so EVERY miss both writes
    its entry and fires the ``cache_misses`` monitoring event — without
    this, fast compiles miss silently (the event only fires when the
    entry is actually written) and the gate cannot see them.  Returns
    the previous config values for restore."""
    import jax
    prev = {
        "jax_compilation_cache_dir":
            jax.config.jax_compilation_cache_dir,
        "jax_persistent_cache_min_compile_time_secs":
            jax.config.jax_persistent_cache_min_compile_time_secs,
        "jax_persistent_cache_min_entry_size_bytes":
            jax.config.jax_persistent_cache_min_entry_size_bytes,
    }
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    if record_all:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return prev


def restore_cache(prev: Mapping[str, Any]) -> None:
    import jax
    for k, v in prev.items():
        jax.config.update(k, v)


def _short_aval(x) -> str:
    dt = getattr(x, "dtype", None)
    shape = getattr(x, "shape", None)
    if dt is None or shape is None:
        return type(x).__name__
    return f"{np.dtype(dt).name}{list(shape)}"


def _arg_shapes(args) -> List[str]:
    import jax
    return [_short_aval(x) for x in jax.tree_util.tree_leaves(args)]


def measure_entry(build: Callable[[], Tuple[Callable, tuple]]
                  ) -> Tuple[Any, Dict[str, Any]]:
    """Trace + lower one flagship entrypoint (no XLA compile); returns
    the lowered object and its golden record: the sha256 of the lowered
    StableHLO text (the program identity the cache key tracks — stable
    across processes, no location metadata) plus the canonical arg
    shapes."""
    fn, args = build()
    traced = fn.trace(*args)
    lowered = traced.lower()
    text = lowered.as_text()
    h = hashlib.sha256(text.encode("utf-8")).hexdigest()[:16]
    return lowered, {"module_hash": h, "arg_shapes": _arg_shapes(args),
                     "pin": "hit"}


def _registry(registry):
    if registry is None:
        from ..verify.lint.fingerprint import FLAGSHIP
        return FLAGSHIP
    return registry


def bless_goldens(path: str, registry: Optional[Dict] = None,
                  ledger: Optional[CompileLedger] = None,
                  progress: Optional[Callable[[str], None]] = None
                  ) -> Dict[str, Dict]:
    """Record the golden ledger: lower every flagship entrypoint, hash
    its module, and compile it once so the persistent cache holds the
    entry the ``pin: hit`` verdict expects.  With a warm cache the
    compile is a cache load; after a program change it pays the compile
    once (which is exactly the cache-warming the pin needs)."""
    registry = _registry(registry)
    out: Dict[str, Dict] = {}
    for name, build in registry.items():
        if progress:
            progress(name)
        lowered, rec = measure_entry(build)
        if ledger is not None:
            with ledger.attribute(name, fingerprint=rec["module_hash"]):
                lowered.compile()
        else:
            lowered.compile()
        out[name] = rec
    with open(path, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    return out


def check_goldens(path: str, registry: Optional[Dict] = None,
                  ledger: Optional[CompileLedger] = None,
                  compile: bool = True,
                  names: Optional[Sequence[str]] = None,
                  progress: Optional[Callable[[str], None]] = None
                  ) -> List[str]:
    """The recompile-regression gate: -> list of NAMED failure strings
    (empty = pass).  Per flagship entrypoint:

    * missing golden / stale golden name -> failure (registry and
      golden must stay in sync);
    * lowered-module hash drift -> failure ("will recompile"): the
      program changed, so every cache entry for it is dead weight and
      every consumer pays the compile wall again — re-bless only when
      the change is intended;
    * canonical arg-shape drift -> failure (the entrypoint's shape
      contract moved);
    * with ``compile=True`` and a ``ledger``: compile the lowered
      program and read the cache verdict from the monitoring events —
      a ``cache_miss`` where the golden pins ``hit`` (or no cache
      consult at all) is the planted-recompile failure.  Durations are
      recorded but never judged (wall-clock tolerant).

    ``compile=False`` is the lower-only mode ``__graft_entry__`` uses
    (no XLA invocation, safe for cold environments); ``names`` filters
    to a subset without tripping the stale-golden check.
    """
    with open(path, encoding="utf-8") as f:
        golden = json.load(f)
    registry = _registry(registry)
    if names is not None:
        registry = {k: v for k, v in registry.items() if k in names}
        golden = {k: v for k, v in golden.items() if k in names}
    errors: List[str] = []
    for name in sorted(set(golden) - set(registry)):
        errors.append(
            f"{name}: in {GOLDEN_BASENAME} but not in the flagship "
            f"registry — remove it or restore the entrypoint, then "
            f"re-bless (scripts/observatory.py --bless)")
    for name, build in registry.items():
        if name not in golden:
            errors.append(
                f"{name}: flagship entrypoint has no compile golden — "
                f"run scripts/observatory.py --bless")
            continue
        if progress:
            progress(name)
        ref = golden[name]
        lowered, cur = measure_entry(build)
        if cur["arg_shapes"] != ref.get("arg_shapes"):
            errors.append(
                f"{name}: canonical arg shapes changed "
                f"{ref.get('arg_shapes')} -> {cur['arg_shapes']} — new "
                f"program shape; re-bless only if intended")
        if cur["module_hash"] != ref.get("module_hash"):
            errors.append(
                f"{name}: lowered module hash drifted "
                f"{ref.get('module_hash')} -> {cur['module_hash']} — the "
                f"program WILL recompile (persistent-cache entries are "
                f"keyed on the module); re-bless after an intended "
                f"program change")
            continue  # a drifted program cannot honor the cache pin
        if not compile or ledger is None:
            continue
        if ref.get("pin", "hit") != "hit":
            continue
        before_h, before_m = ledger.hits(name), ledger.misses(name)
        before_r = ledger.count("cache_request", name)
        with ledger.attribute(name, fingerprint=cur["module_hash"]):
            lowered.compile()
        new_m = ledger.misses(name) - before_m
        new_h = ledger.hits(name) - before_h
        new_r = ledger.count("cache_request", name) - before_r
        if new_m > 0:
            # reached only with the module hash UNCHANGED (drift
            # already failed-and-continued above), so this is NOT a
            # genuine recompile regression: the .jax_cache entry was
            # evicted (atime cleanup — the PR-13 false-miss footgun) or
            # never warmed.  Name it distinctly so nobody re-blesses a
            # golden over a stale cache.
            ledger.record_gate("cache_evicted", name,
                               fingerprint=cur["module_hash"],
                               reason=f"{new_m} miss(es), module hash "
                                      f"unchanged")
            errors.append(
                f"{name}: CACHE_EVICTED — {new_m} persistent-cache "
                f"miss(es) where the golden pins a hit, with the "
                f"lowered module hash UNCHANGED: a stale/evicted "
                f".jax_cache entry (atime cleanup) or a never-warmed "
                f"cache, not a program change (a genuine recompile "
                f"regression fails above as module-hash drift). "
                f"Recover with `python scripts/warm_cache.py --entry "
                f"{name}` and re-run --check; do NOT re-bless")
        elif new_h == 0 and new_r == 0:
            errors.append(
                f"{name}: persistent cache was never consulted — is "
                f"jax_compilation_cache_dir configured? (the gate "
                f"cannot pin cache behavior without it)")
    return errors


# ------------------------------------------------------------- reporting

def read_ledger(path: str) -> List[Dict[str, Any]]:
    rows = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def ledger_report(rows: Sequence[Mapping[str, Any]], top: int = 10
                  ) -> str:
    """Human report over ledger rows: top compile costs, cache hit
    rate, and a per-entrypoint trend (latest run's compile seconds vs
    the mean of earlier runs)."""
    per: Dict[str, Dict[str, Any]] = {}
    runs: Dict[str, Dict[str, float]] = {}
    aot: Dict[str, Dict[str, Any]] = {}
    hits = misses = evicted = 0
    for r in rows:
        prog = r.get("program") or "unattributed"
        d = per.setdefault(prog, {"compiles": 0, "compile_s": 0.0,
                                  "hits": 0, "misses": 0, "saved_s": 0.0})
        ev = r.get("event")
        if ev == "backend_compile":
            d["compiles"] += 1
            d["compile_s"] += r.get("duration_s", 0.0)
            runs.setdefault(r.get("run", "?"), {}).setdefault(prog, 0.0)
            runs[r.get("run", "?")][prog] += r.get("duration_s", 0.0)
        elif ev == "cache_hit":
            d["hits"] += 1
            hits += 1
        elif ev == "cache_miss":
            d["misses"] += 1
            misses += 1
        elif ev == "cache_evicted":
            d["evicted"] = d.get("evicted", 0) + 1
            evicted += 1
        elif ev == "compile_time_saved":
            d["saved_s"] += r.get("duration_s", 0.0)
        elif ev in ("aot_load", "aot_stale", "aot_export"):
            a = aot.setdefault(prog, {"loads": 0, "aot_load_s": 0.0,
                                      "stale": 0, "exports": 0,
                                      "last_reason": None})
            if ev == "aot_load":
                a["loads"] += 1
                a["aot_load_s"] += r.get("duration_s", 0.0)
            elif ev == "aot_stale":
                a["stale"] += 1
                a["last_reason"] = r.get("reason")
            else:
                a["exports"] += 1
    lines = ["compile observatory report", "=" * 26]
    total = hits + misses
    rate = (100.0 * hits / total) if total else float("nan")
    lines.append(f"cache: {hits} hits / {misses} misses "
                 f"({rate:.1f}% hit rate)" if total else
                 "cache: no persistent-cache events recorded")
    if evicted:
        lines.append(f"cache evictions proven by the gate: {evicted} "
                     f"(module hash unchanged — recover with "
                     f"scripts/warm_cache.py, not a re-bless)")
    lines.append("")
    lines.append(f"top {top} compile costs (wall seconds in "
                 f"backend_compile):")
    ranked = sorted(per.items(), key=lambda kv: -kv[1]["compile_s"])
    for prog, d in ranked[:top]:
        lines.append(
            f"  {d['compile_s']:8.2f}s  {prog}  "
            f"(compiles={d['compiles']} hits={d['hits']} "
            f"misses={d['misses']} saved={d['saved_s']:.2f}s)")
    # AOT plane (ISSUE 17): load-instead-of-compile wall clock, per
    # program — aot_load_seconds next to the compile_seconds it replaced
    if aot:
        lines.append("")
        lines.append("aot artifacts (aot_load_seconds vs compile_seconds):")
        lines.append(f"  {'program':<34} {'aot_load_s':>10} "
                     f"{'compile_s':>10} {'speedup':>8}  loads/stale")
        for prog in sorted(aot):
            a = aot[prog]
            load_s = (a["aot_load_s"] / a["loads"]) if a["loads"] else 0.0
            comp = per.get(prog, {})
            comp_s = ((comp.get("compile_s", 0.0) / comp["compiles"])
                      if comp.get("compiles") else 0.0)
            speed = (f"{comp_s / load_s:7.1f}x"
                     if load_s > 0 and comp_s > 0 else "      —")
            lines.append(
                f"  {prog:<34} {load_s:>10.2f} {comp_s:>10.2f} "
                f"{speed:>8}  {a['loads']}/{a['stale']}")
            if a["stale"] and a["last_reason"]:
                lines.append(f"      last stale: {a['last_reason']}")
    # trend: latest run vs the mean of prior runs, per program
    if len(runs) >= 2:
        order = sorted(runs)  # run ids are millisecond-hex: sortable
        latest = runs[order[-1]]
        lines.append("")
        lines.append("per-entrypoint trend (latest run vs mean of "
                     "prior runs):")
        for prog in sorted(latest):
            prior = [runs[rid][prog] for rid in order[:-1]
                     if prog in runs[rid]]
            if not prior:
                lines.append(f"  {prog}: {latest[prog]:.2f}s (new)")
                continue
            base = sum(prior) / len(prior)
            delta = latest[prog] - base
            lines.append(f"  {prog}: {latest[prog]:.2f}s vs "
                         f"{base:.2f}s mean ({delta:+.2f}s)")
    return "\n".join(lines)
