"""In-scan device telemetry + host exporters (SURVEY §5.5 rebuilt for the
scan era).

The reference scatters instrumentation across lager tracing, plumtree
transmission logs, and queue-depth probes; our simulator runs whole
executions inside ``lax.scan``, so telemetry is collected ON DEVICE at
full speed and streamed to the host at a chosen cadence:

  * :mod:`.registry` — metric names -> ring slots + the enable mask
    (disabled metrics cost a constant-folded ``where``, not a branch);
  * :mod:`.ring` — the [window, K] device buffer carried in the scan
    state, flushed with one transfer per window;
  * :mod:`.runner` — windowed scan harness wiring the engine counter
    taps and the topology metrics into the ring;
  * :mod:`.sinks` — JSONL and Prometheus-text exporters;
  * :mod:`.timeline` — per-window wall-clock / rounds-per-sec recorder
    and the opt-in ``jax.profiler`` trace context.

Host events (fault injections, orchestration polls) flow through the
module-level :func:`emit_event`, which fans out to sinks registered with
:func:`add_global_sink` — a no-op when none are (the hot-path guard, like
``logging.trace``).  Every event row is stamped with a monotonic ``seq``
number and, when a windowed run has published one via
:func:`note_round`, the current simulation ``round`` — the correlation
keys the Perfetto export (:mod:`.perfetto`) uses to place host events
on the same timeline as flight-recorder wire entries.  See README.md
"Observability" for the full model.
"""

from __future__ import annotations

import itertools
import time
from typing import List, Optional

from .registry import (COUNTER, GAUGE, DEFAULT_SPECS, HOST_SPECS,
                       MetricRegistry, MetricSpec, default_registry)
from .ring import TelemetryRing, flush, make_ring, record
from .flight import (FlightRing, FlightSpec, flight_entries, flight_flush,
                     flight_mask, flight_record, make_flight_ring,
                     place_flight_ring)
from .tracer import (Span, SpanEvent, TraceRing, TraceSpec, critical_path,
                     deliveries, make_trace_ring, place_trace_ring,
                     read_spans, trace_events, trace_flush, trace_record,
                     trace_spans, wire_deliveries, write_spans)
from .alerts import (ALERT_NAMES, AlertFirer, AlertSpec, alert_registry,
                     alert_specs, alerts_exposition, make_alert_plane,
                     make_alert_state)
from .runner import (ENGINE_KEYMAP, collect_round_metrics,
                     make_window_runner, run_with_telemetry)
from .sinks import JsonlSink, PrometheusSink, TelemetrySink, parse_exposition
from .timeline import RoundTimeline, profile_trace
from .perfetto import chrome_trace, write_chrome_trace
from .observatory import (CompileLedger, LEDGER_SPECS, StreamSpec,
                          bless_goldens, check_goldens, ledger_report)
# benchplane's short names (SCHEMA/make_row/validate/...) would clobber
# the package namespace, so the generic ones are re-exported aliased
from .benchplane import (PERF_SUBSET, calibrate, config_fingerprint,
                         read_bench_ledger)
from .benchplane import SCHEMA as BENCH_SCHEMA
from .benchplane import append_rows as append_bench_rows
from .benchplane import make_row as bench_row
from .benchplane import trend_report as bench_trend_report
from .benchplane import validate as validate_bench_row

__all__ = [
    "COUNTER", "GAUGE", "DEFAULT_SPECS", "HOST_SPECS",
    "MetricRegistry", "MetricSpec", "default_registry",
    "TelemetryRing", "flush", "make_ring", "record",
    "FlightRing", "FlightSpec", "flight_entries", "flight_flush",
    "flight_mask", "flight_record", "make_flight_ring",
    "place_flight_ring",
    "Span", "SpanEvent", "TraceRing", "TraceSpec", "critical_path",
    "deliveries", "make_trace_ring", "place_trace_ring", "read_spans",
    "trace_events", "trace_flush", "trace_record", "trace_spans",
    "wire_deliveries", "write_spans",
    "ALERT_NAMES", "AlertFirer", "AlertSpec", "alert_registry",
    "alert_specs", "alerts_exposition", "make_alert_plane",
    "make_alert_state",
    "ENGINE_KEYMAP", "collect_round_metrics", "make_window_runner",
    "run_with_telemetry",
    "JsonlSink", "PrometheusSink", "TelemetrySink", "parse_exposition",
    "RoundTimeline", "profile_trace",
    "chrome_trace", "write_chrome_trace",
    "CompileLedger", "LEDGER_SPECS", "StreamSpec",
    "bless_goldens", "check_goldens", "ledger_report",
    "BENCH_SCHEMA", "PERF_SUBSET", "append_bench_rows", "bench_row",
    "bench_trend_report", "calibrate", "config_fingerprint",
    "read_bench_ledger", "validate_bench_row",
    "add_global_sink", "remove_global_sink", "global_sinks", "emit_event",
    "note_round", "current_round",
]

# ------------------------------------------------------- host event bus

_GLOBAL_SINKS: List[TelemetrySink] = []
_EVENT_SEQ = itertools.count()
_CURRENT_ROUND: Optional[int] = None


def note_round(rnd: int) -> None:
    """Publish the simulation round the device has reached (called by
    the windowed runners at each flush) so host events emitted between
    flushes carry a ``round`` stamp correlating them with the
    flight-recorder timeline."""
    global _CURRENT_ROUND
    _CURRENT_ROUND = int(rnd)


def current_round() -> Optional[int]:
    """The last :func:`note_round` value (None before any run)."""
    return _CURRENT_ROUND


def add_global_sink(sink: TelemetrySink) -> TelemetrySink:
    """Register a sink for host events (fault injections, orchestration
    polls, bench trials).  Returns the sink for chaining."""
    _GLOBAL_SINKS.append(sink)
    return sink


def remove_global_sink(sink: TelemetrySink) -> None:
    try:
        _GLOBAL_SINKS.remove(sink)
    except ValueError:
        pass


def global_sinks() -> tuple:
    return tuple(_GLOBAL_SINKS)


def emit_event(event: str, /, **fields) -> None:
    """Emit one host telemetry event to every registered global sink.
    Free when no sink is registered (the ``logging.trace`` guard
    pattern) — instrumented call sites never pay for disabled
    observability.  The event name is positional-only so any field
    name (even ``event``-adjacent ones like ``name``) stays usable.

    Every row carries a monotonic ``seq`` stamp (total order over host
    events regardless of sink interleaving) and, when a windowed run
    has published one (:func:`note_round`), the current ``round`` —
    the keys :mod:`.perfetto` correlates host events with
    flight-recorder wire entries on."""
    if not _GLOBAL_SINKS:
        return
    row = {"event": str(event), "seq": next(_EVENT_SEQ),
           "t_wall": time.time(), **fields}
    if _CURRENT_ROUND is not None and "round" not in fields:
        row["round"] = _CURRENT_ROUND
    for s in list(_GLOBAL_SINKS):
        s.write_row(row)
