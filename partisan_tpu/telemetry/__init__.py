"""In-scan device telemetry + host exporters (SURVEY §5.5 rebuilt for the
scan era).

The reference scatters instrumentation across lager tracing, plumtree
transmission logs, and queue-depth probes; our simulator runs whole
executions inside ``lax.scan``, so telemetry is collected ON DEVICE at
full speed and streamed to the host at a chosen cadence:

  * :mod:`.registry` — metric names -> ring slots + the enable mask
    (disabled metrics cost a constant-folded ``where``, not a branch);
  * :mod:`.ring` — the [window, K] device buffer carried in the scan
    state, flushed with one transfer per window;
  * :mod:`.runner` — windowed scan harness wiring the engine counter
    taps and the topology metrics into the ring;
  * :mod:`.sinks` — JSONL and Prometheus-text exporters;
  * :mod:`.timeline` — per-window wall-clock / rounds-per-sec recorder
    and the opt-in ``jax.profiler`` trace context.

Host events (fault injections, orchestration polls) flow through the
module-level :func:`emit_event`, which fans out to sinks registered with
:func:`add_global_sink` — a no-op when none are (the hot-path guard, like
``logging.trace``).  See README.md "Observability" for the full model.
"""

from __future__ import annotations

import time
from typing import List

from .registry import (COUNTER, GAUGE, DEFAULT_SPECS, HOST_SPECS,
                       MetricRegistry, MetricSpec, default_registry)
from .ring import TelemetryRing, flush, make_ring, record
from .runner import (ENGINE_KEYMAP, collect_round_metrics,
                     make_window_runner, run_with_telemetry)
from .sinks import JsonlSink, PrometheusSink, TelemetrySink, parse_exposition
from .timeline import RoundTimeline, profile_trace

__all__ = [
    "COUNTER", "GAUGE", "DEFAULT_SPECS", "HOST_SPECS",
    "MetricRegistry", "MetricSpec", "default_registry",
    "TelemetryRing", "flush", "make_ring", "record",
    "ENGINE_KEYMAP", "collect_round_metrics", "make_window_runner",
    "run_with_telemetry",
    "JsonlSink", "PrometheusSink", "TelemetrySink", "parse_exposition",
    "RoundTimeline", "profile_trace",
    "add_global_sink", "remove_global_sink", "global_sinks", "emit_event",
]

# ------------------------------------------------------- host event bus

_GLOBAL_SINKS: List[TelemetrySink] = []


def add_global_sink(sink: TelemetrySink) -> TelemetrySink:
    """Register a sink for host events (fault injections, orchestration
    polls, bench trials).  Returns the sink for chaining."""
    _GLOBAL_SINKS.append(sink)
    return sink


def remove_global_sink(sink: TelemetrySink) -> None:
    try:
        _GLOBAL_SINKS.remove(sink)
    except ValueError:
        pass


def global_sinks() -> tuple:
    return tuple(_GLOBAL_SINKS)


def emit_event(event: str, /, **fields) -> None:
    """Emit one host telemetry event to every registered global sink.
    Free when no sink is registered (the ``logging.trace`` guard
    pattern) — instrumented call sites never pay for disabled
    observability.  The event name is positional-only so any field
    name (even ``event``-adjacent ones like ``name``) stays usable."""
    if not _GLOBAL_SINKS:
        return
    row = {"event": str(event), "t_wall": time.time(), **fields}
    for s in list(_GLOBAL_SINKS):
        s.write_row(row)
