"""Metric registry — the name -> ring-slot mapping plus the per-metric
enable mask.

The registry is host-side and immutable once a runner is built from it:
metric *names* exist only on the host (SURVEY §5.6 — device code sees
slot indices), and the enable mask is baked into the jitted window
program as a compile-time constant.  A disabled metric therefore costs a
``jnp.where`` against a constant-``False`` predicate, which XLA's
simplifier folds to the zero operand and dead-code-eliminates the
collector feeding it — no ``lax.cond`` branch, no program-shape change
between masks (the in-scan requirement: fixed shapes, ``lax``-only
control flow).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

COUNTER = "counter"
GAUGE = "gauge"


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One metric: its stable name, Prometheus kind, and help text."""
    name: str
    kind: str = GAUGE
    help: str = ""


# The default metric set: the engine counter taps (engine.step's
# route/deliver/tick/collect phases) plus the topology health metrics of
# metrics.py.  ``convergence`` ships disabled — its collector compares
# every pair of [N]-wide membership masks (O(N^2)), the full-membership
# metric, not a default-on cost.
DEFAULT_SPECS: Tuple[MetricSpec, ...] = (
    MetricSpec("round", GAUGE, "Simulation round index."),
    MetricSpec("msgs_routed", COUNTER,
               "Messages entering the router this round (post fault plane "
               "and interposition)."),
    MetricSpec("msgs_delivered", COUNTER,
               "Inbox slots delivered to handlers this round."),
    MetricSpec("msgs_sent", COUNTER,
               "Messages in the outgoing flat buffer after collect."),
    MetricSpec("fault_dropped", COUNTER,
               "Messages dropped by the fault plane (crash masks, "
               "partitions, omission interposition) this round."),
    MetricSpec("inbox_overflow", COUNTER,
               "Messages lost to per-node inbox capacity this round."),
    MetricSpec("out_dropped", COUNTER,
               "Messages dropped at the emission cap / flat-buffer "
               "compaction this round."),
    MetricSpec("unhandled", COUNTER,
               "Delivered messages whose type matched no handler."),
    MetricSpec("inflight", GAUGE,
               "In-flight buffer occupancy at round start."),
    MetricSpec("alive", GAUGE, "Nodes with alive=True."),
    MetricSpec("isolated", GAUGE,
               "Alive nodes with an empty view (metrics.view_stats)."),
    MetricSpec("mean_view", GAUGE,
               "Mean view size over alive nodes (metrics.view_stats)."),
    MetricSpec("convergence", GAUGE,
               "Fraction of alive nodes sharing the modal membership view "
               "(metrics.convergence)."),
)

# Host-side metrics emitted per window flush by the timeline recorder —
# never in the ring, but sinks should know their kinds.
HOST_SPECS: Tuple[MetricSpec, ...] = (
    MetricSpec("rounds_per_sec", GAUGE,
               "Device rounds per wall-clock second over the last "
               "flushed window."),
)

_DEFAULT_DISABLED = frozenset({"convergence"})


class MetricRegistry:
    """Ordered metric table: ``names[i]`` occupies ring column ``i``."""

    def __init__(self, specs: Sequence[MetricSpec] = DEFAULT_SPECS,
                 disabled: Iterable[str] = _DEFAULT_DISABLED):
        self.specs: Tuple[MetricSpec, ...] = tuple(specs)
        self.names: Tuple[str, ...] = tuple(s.name for s in self.specs)
        if len(set(self.names)) != len(self.names):
            raise ValueError("duplicate metric names in registry")
        self._slots: Dict[str, int] = {n: i for i, n in enumerate(self.names)}
        unknown = set(disabled) - set(self.names)
        if unknown:
            raise KeyError(f"disabled metrics not in registry: {unknown}")
        self._mask = np.array([n not in set(disabled) for n in self.names],
                              dtype=bool)

    # ------------------------------------------------------------- queries

    def __contains__(self, name: str) -> bool:
        return name in self._slots

    def __len__(self) -> int:
        return len(self.names)

    def slot(self, name: str) -> int:
        return self._slots[name]

    def spec(self, name: str) -> MetricSpec:
        return self.specs[self._slots[name]]

    def kind(self, name: str) -> str:
        return self.spec(name).kind

    def enabled(self, name: str) -> bool:
        return bool(self._mask[self._slots[name]])

    @property
    def mask(self) -> np.ndarray:
        """[K] bool host constant — bake into jit, never a traced array."""
        return self._mask.copy()

    # ------------------------------------------------------- reconfigure

    def enable(self, *names: str) -> "MetricRegistry":
        off = {n for n in self.names if not self.enabled(n)} - set(names)
        return MetricRegistry(self.specs, off)

    def disable(self, *names: str) -> "MetricRegistry":
        off = {n for n in self.names if not self.enabled(n)} | set(names)
        return MetricRegistry(self.specs, off)

    def with_specs(self, extra: Sequence[MetricSpec]) -> "MetricRegistry":
        off = {n for n in self.names if not self.enabled(n)}
        return MetricRegistry(self.specs + tuple(extra), off)

    # ------------------------------------------------------------- device

    def pack(self, values: Mapping[str, jax.Array]) -> jax.Array:
        """Build one [K] float32 ring row from a name -> scalar mapping.

        Jit-safe: the enable mask is applied per metric with a Python-bool
        predicate, so a disabled metric's collector is constant-folded out
        of the compiled program (a ``where``, not a branch); missing
        metrics record 0."""
        cols = []
        for i, name in enumerate(self.names):
            v = values.get(name)
            if v is None:
                cols.append(jnp.float32(0))
                continue
            v = jnp.asarray(v, jnp.float32).reshape(())
            cols.append(jnp.where(bool(self._mask[i]), v, jnp.float32(0)))
        return jnp.stack(cols)


def default_registry(disabled: Optional[Iterable[str]] = None
                     ) -> MetricRegistry:
    """The engine's default metric set (convergence off — see above)."""
    return MetricRegistry(
        DEFAULT_SPECS,
        _DEFAULT_DISABLED if disabled is None else disabled)


def all_kinds(registry: Optional[MetricRegistry]) -> Dict[str, str]:
    """name -> kind for ring + host metrics (sink configuration helper)."""
    specs = (tuple(registry.specs) if registry is not None
             else DEFAULT_SPECS) + HOST_SPECS
    return {s.name: s.kind for s in specs}


def all_help(registry: Optional[MetricRegistry]) -> Dict[str, str]:
    specs = (tuple(registry.specs) if registry is not None
             else DEFAULT_SPECS) + HOST_SPECS
    return {s.name: s.help for s in specs}
