"""In-scan telemetry harness: run whole windows on device, flush per
window.

``make_window_runner`` compiles ``window`` engine rounds into one
``lax.scan`` that carries (World, TelemetryRing): every round the engine
counter taps (route/deliver/tick/collect phases) plus the topology
metrics of :mod:`partisan_tpu.metrics` are packed into the ring through
the registry's enable mask.  ``run_with_telemetry`` drives the outer
loop: one host sync + ONE [window, K] transfer per window, rows fanned
out to sinks, wall-clock per window recorded on the timeline.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import metrics as metrics_mod
from ..config import Config
from ..engine import ProtocolBase, World, init_world, make_step
from .registry import MetricRegistry, default_registry
from .ring import TelemetryRing, flush, make_ring, record
from .sinks import TelemetrySink
from .timeline import RoundTimeline, profile_trace

# engine step-metrics key -> registry metric name
ENGINE_KEYMAP: Dict[str, str] = {
    "round": "round",
    "routed": "msgs_routed",
    "delivered": "msgs_delivered",
    "sent": "msgs_sent",
    "fault_dropped": "fault_dropped",
    "inbox_overflow": "inbox_overflow",
    "out_dropped": "out_dropped",
    "unhandled": "unhandled",
    "inflight": "inflight",
    "alive": "alive",
    # chaos-plane counters (present when the step compiled a
    # ChaosSchedule; verify/chaos.py)
    "chaos_dropped": "chaos_dropped",
    "chaos_delayed": "chaos_delayed",
    "chaos_duplicated": "chaos_duplicated",
}


def _find_views(state: Any) -> Optional[jax.Array]:
    """Locate the protocol's padded view array ([N, C], -1 padding) —
    the same active/partial unwrap metrics.world_health performs."""
    st = state
    while st is not None:
        views = getattr(st, "active", None)
        if views is None:
            views = getattr(st, "partial", None)
        if views is not None:
            return views
        st = getattr(st, "lower", None)  # unwrap Stacked layers
    return None


def collect_round_metrics(proto: ProtocolBase, world: World,
                          step_metrics: Dict[str, jax.Array],
                          registry: MetricRegistry
                          ) -> Dict[str, jax.Array]:
    """Map one round's engine metrics + topology collectors to registry
    names (device, inside scan).  Disabled metrics still appear here —
    the registry's constant mask zeroes them in ``pack`` and XLA removes
    the dead collectors (a ``where``, not a branch)."""
    vals: Dict[str, jax.Array] = {}
    for k, name in ENGINE_KEYMAP.items():
        if k in step_metrics and name in registry:
            vals[name] = step_metrics[k]
    # workload-plane round counters (ISSUE 8): a protocol's opt-in
    # round_counter_names surface in step metrics under their REGISTRY
    # names already — pass them straight through.  No-op (and identical
    # HLO) when the protocol doesn't opt in or the registry doesn't
    # carry the names.
    for k, v in step_metrics.items():
        if k not in ENGINE_KEYMAP and k in registry:
            vals[k] = v
    views = _find_views(world.state)
    if views is not None and "isolated" in registry:
        vs = metrics_mod.view_stats(views, world.alive)
        vals["isolated"] = vs["isolated"]
        vals["mean_view"] = vs["mean_view"]
    if "convergence" in registry and hasattr(proto, "member_mask"):
        masks = jax.vmap(proto.member_mask)(world.state)
        vals["convergence"] = metrics_mod.convergence(masks, world.alive)
    if views is not None and "health_reach_frac" in registry:
        # the ISSUE-4 health plane (connectivity proxy + view fill);
        # lazy import — verify's package init imports telemetry
        from ..verify import health as health_mod
        vals.update(health_mod.collect_health_views(
            views, world.alive, partition=world.partition))
    # protocol-owned degradation counters (qos ack-ring overflow,
    # dead-letter, relay expiry ...) tap into the ring whenever the
    # registry carries their names (verify.health.QOS_SPECS)
    for k, v in proto.health_counters(world.state).items():
        if k in registry:
            vals[k] = v
    return vals


def make_window_runner(
    cfg: Config, proto: ProtocolBase, registry: MetricRegistry,
    window: int, *,
    step: Optional[Callable] = None,
    flight: Optional[Any] = None,
    stream: Optional[Any] = None,
    trace: Optional[Any] = None,
    alerts: Optional[Any] = None,
    **step_kw: Any,
) -> Callable:
    """Compile ``window`` rounds + ring recording into one jitted scan.

    ``flight`` (a :class:`.flight.FlightSpec`) additionally carries the
    message flight-recorder ring through the same scan — the runner
    then takes and returns a :class:`.flight.FlightRing` alongside the
    metrics ring: ``run_window(world, ring, fring)``.  With
    ``flight=None`` the compiled program is byte-identical to the
    pre-recorder harness (the recorder-off cost is zero by
    construction, not by measurement).

    ``trace`` (a :class:`.tracer.TraceSpec`) likewise co-carries the
    message lifecycle span ring, and ``alerts`` (an
    :class:`.alerts.AlertSpec`) folds the in-scan alert detectors over
    each round's metric taps before they are packed (the alert columns
    must be in ``registry`` — see :func:`.alerts.alert_registry`).
    When either is set the runner takes/returns the EXTENDED carry
    ``run_window(world, ring, fring, tring, astate)`` with ``None``
    placeholders for absent planes; with both ``None`` the two legacy
    signatures (and their compiled programs) are untouched.

    ``stream`` (a :class:`.observatory.StreamSpec`) drains each round's
    packed registry row to the host MID-SCAN through an ordered
    ``io_callback`` — the same ``[K]`` float32 row the ring records, so
    streamed rows are bit-equal to the flushed ones.  ``stream=None``
    keeps the program byte-identical (the ``flight=None`` discipline);
    note a streaming program is never persistently cacheable (the cache
    key includes the host callback), so flagship programs stay
    ``stream=None``."""
    step = step or make_step(cfg, proto, donate=False, flight=flight,
                             trace=trace, **step_kw)

    if stream is not None:
        stream.bind(registry)
        drain = stream._drain_row
        from jax.experimental import io_callback

        def emit(vals):
            io_callback(drain, None, registry.pack(vals), ordered=True)
    else:
        def emit(vals):
            return None

    if trace is not None or alerts is not None:
        alert_update = None
        if alerts is not None:
            from .alerts import make_alert_plane
            alert_update, _ = make_alert_plane(alerts, registry)

        def call_step(w, fr, tr):
            # step signature varies with the compiled planes; normalize
            # to (world, fring, tring, metrics) with None placeholders
            if flight is not None and trace is not None:
                return step(w, fr, tr)
            if flight is not None:
                w2, fr2, m = step(w, fr)
                return w2, fr2, None, m
            if trace is not None:
                w2, tr2, m = step(w, tr)
                return w2, None, tr2, m
            w2, m = step(w)
            return w2, None, None, m

        @jax.jit
        def run_window_ext(world: World, ring: TelemetryRing,
                           fring, tring, astate):
            def body(carry, _):
                w, r, fr, tr, a = carry
                w2, fr2, tr2, m = call_step(w, fr, tr)
                vals = collect_round_metrics(proto, w2, m, registry)
                if alert_update is not None:
                    a, acols = alert_update(a, vals)
                    vals.update(acols)
                emit(vals)
                return (w2, record(r, registry, vals), fr2, tr2, a), None

            (w2, r2, fr2, tr2, a2), _ = jax.lax.scan(
                body, (world, ring, fring, tring, astate), None,
                length=window)
            return w2, r2, fr2, tr2, a2

        return run_window_ext

    if flight is not None:
        @jax.jit
        def run_window_flight(world: World, ring: TelemetryRing, fring):
            def body(carry, _):
                w, r, fr = carry
                w2, fr2, m = step(w, fr)
                vals = collect_round_metrics(proto, w2, m, registry)
                emit(vals)
                return (w2, record(r, registry, vals), fr2), None

            (w2, r2, fr2), _ = jax.lax.scan(
                body, (world, ring, fring), None, length=window)
            return w2, r2, fr2

        return run_window_flight

    @jax.jit
    def run_window(world: World, ring: TelemetryRing):
        def body(carry, _):
            w, r = carry
            w2, m = step(w)
            vals = collect_round_metrics(proto, w2, m, registry)
            emit(vals)
            return (w2, record(r, registry, vals)), None

        (w2, r2), _ = jax.lax.scan(body, (world, ring), None, length=window)
        return w2, r2

    return run_window


def run_with_telemetry(
    cfg: Config, proto: ProtocolBase, n_rounds: int, *,
    window: int = 64,
    registry: Optional[MetricRegistry] = None,
    sinks: Sequence[TelemetrySink] = (),
    timeline: Optional[RoundTimeline] = None,
    world: Optional[World] = None,
    profile_dir: Optional[str] = None,
    profile_window: int = 0,
    step_kw: Optional[Dict[str, Any]] = None,
    flight: Optional[Any] = None,
    on_flight: Optional[Callable] = None,
    stream: Optional[Any] = None,
    trace: Optional[Any] = None,
    on_trace: Optional[Callable] = None,
    alerts: Optional[Any] = None,
    alert_firer: Optional[Any] = None,
) -> Tuple[World, RoundTimeline]:
    """Run ``n_rounds`` with in-scan telemetry, flushing every ``window``.

    Per window: one jitted scan (no host round-trips inside), then one
    [window, K] device->host transfer, rows written to every sink
    (per-round metric rows, then the window timeline row carrying
    ``rounds_per_sec``).  A trailing partial window compiles a second,
    shorter scan.  ``profile_dir`` wraps window ``profile_window`` in a
    ``jax.profiler`` trace.

    ``flight`` (a :class:`.flight.FlightSpec`; its ``window`` must
    match) co-carries the message flight recorder through the same
    scans — still one (metrics) + one (flight) transfer per window —
    and hands each window's decoded ``TraceEntry`` list to
    ``on_flight(entries)``.

    ``stream`` (a :class:`.observatory.StreamSpec`) additionally drains
    every round's metric row to the host mid-scan (live progress for
    long windows); the windowed flush stays authoritative for the
    returned timeline and sink rows.  An ``effects_barrier`` before
    return guarantees every streamed row has landed.

    ``trace`` (a :class:`.tracer.TraceSpec`; its ``window`` must match)
    co-carries the message lifecycle span ring — one extra transfer per
    window — handing each window's decoded :class:`.tracer.SpanEvent`
    list to ``on_trace(events)``.  ``alerts`` (an
    :class:`.alerts.AlertSpec`) runs the in-scan detectors each round;
    the alert columns are appended to the registry automatically when
    absent, and an :class:`.alerts.AlertFirer` (``alert_firer``, or an
    internal one) edge-detects the flushed rows into host alert events.
    """
    registry = registry or default_registry()
    if alerts is not None and "alerts_active" not in registry:
        from .alerts import alert_registry
        registry = alert_registry(registry)
    world = world if world is not None else init_world(cfg, proto)
    timeline = timeline or RoundTimeline()
    ring = make_ring(registry, window)
    fring = None
    if flight is not None:
        from .flight import (flight_entries, flight_flush,
                             make_flight_ring)
        if flight.window != window:
            raise ValueError(
                f"flight.window {flight.window} != runner window "
                f"{window}: the rings flush together")
        fring = make_flight_ring(flight)
    tring = None
    if trace is not None:
        from .tracer import make_trace_ring, trace_events, trace_flush
        if trace.window != window:
            raise ValueError(
                f"trace.window {trace.window} != runner window "
                f"{window}: the rings flush together")
        tring = make_trace_ring(trace)
    astate = None
    if alerts is not None:
        from .alerts import AlertFirer, make_alert_state
        astate = make_alert_state()
        if alert_firer is None:
            alert_firer = AlertFirer()
    ext = trace is not None or alerts is not None
    # one compiled step shared by the full- and partial-window scans
    step = make_step(cfg, proto, donate=False, flight=flight,
                     trace=trace, **(step_kw or {}))
    runner = make_window_runner(cfg, proto, registry, window, step=step,
                                flight=flight, stream=stream,
                                trace=trace, alerts=alerts)
    n_full, rem = divmod(n_rounds, window)
    chunks = [(runner, window)] * n_full
    if rem:
        chunks.append((
            make_window_runner(cfg, proto, registry, rem, step=step,
                               flight=flight, stream=stream,
                               trace=trace, alerts=alerts), rem))

    from . import note_round
    for wi, (run_window, length) in enumerate(chunks):
        ctx = (profile_trace(profile_dir)
               if profile_dir is not None and wi == profile_window
               else contextlib.nullcontext())
        t0 = time.perf_counter()
        with ctx:
            if ext:
                world, ring, fring, tring, astate = run_window(
                    world, ring, fring, tring, astate)
            elif flight is not None:
                world, ring, fring = run_window(world, ring, fring)
            else:
                world, ring = run_window(world, ring)
            rows, ring = flush(ring, registry)  # blocks: the sync point
            frows = None
            if flight is not None:  # the flight transfer is TIMED too
                frows, _overflow, fring = flight_flush(fring)
            trows = None
            if trace is not None:  # ... and the trace transfer
                trows, _toverflow, tring = trace_flush(tring)
        dt = time.perf_counter() - t0
        note_round(int(world.rnd))
        wrow = timeline.observe(length, dt)
        for row in rows:
            for s in sinks:
                s.write_row(row)
        for s in sinks:
            s.write_row(wrow)
        if alert_firer is not None:
            for row in rows:
                alert_firer.observe(row)
        if frows is not None and on_flight is not None:
            on_flight(flight_entries(frows))
        if trows is not None and on_trace is not None:
            on_trace(trace_events(trows))
    if stream is not None:
        jax.effects_barrier()  # every streamed row has landed
    return world, timeline
