"""The device-side metrics ring.

A fixed-shape ``[window, K]`` float32 buffer carried in the scan state:
``record`` writes one row per round (a ``dynamic_update_slice`` at the
cursor — jit-safe, shape-stable), and ``flush`` moves the whole window to
the host in ONE transfer every ``window`` rounds.  This is the in-step
metrics-accumulation pattern of production JAX training stacks applied to
the gossip engine: the scan never syncs per round, observability pays one
[window, K] device->host copy per window.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .registry import MetricRegistry


@struct.dataclass
class TelemetryRing:
    buf: jax.Array     # [window, K] float32 metric rows
    cursor: jax.Array  # scalar int32 — rows recorded since the last flush


def make_ring(registry: MetricRegistry, window: int) -> TelemetryRing:
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return TelemetryRing(
        buf=jnp.zeros((window, len(registry)), jnp.float32),
        cursor=jnp.int32(0),
    )


def record(ring: TelemetryRing, registry: MetricRegistry,
           values: Mapping[str, jax.Array]) -> TelemetryRing:
    """Write one round's metrics into the ring (device, inside scan).

    Overflow wraps (cursor % window) so a missed flush degrades to
    keep-latest rather than an out-of-bounds write; the harness flushes
    every window rounds, so in normal operation the ring never wraps.
    """
    row = registry.pack(values)
    window = ring.buf.shape[0]
    slot = jnp.mod(ring.cursor, window)
    buf = jax.lax.dynamic_update_slice(
        ring.buf, row[None, :], (slot, jnp.int32(0)))
    return ring.replace(buf=buf, cursor=ring.cursor + 1)


def flush(ring: TelemetryRing, registry: MetricRegistry
          ) -> Tuple[List[Dict[str, float]], TelemetryRing]:
    """ONE device->host transfer of the whole window; returns the recorded
    rows (as name -> float dicts, oldest first) and the reset ring.

    Host-side only — never call under jit.  Blocks until the device has
    produced the buffer, so it doubles as the per-window sync point.
    """
    buf = np.asarray(jax.device_get(ring.buf))
    n = int(ring.cursor)
    window = buf.shape[0]
    if n > window:  # wrapped: only the latest `window` rows survive
        start = n % window
        buf = np.concatenate([buf[start:], buf[:start]])
        n = window
    rows = [dict(zip(registry.names, map(float, buf[i]))) for i in range(n)]
    return rows, ring.replace(cursor=jnp.int32(0))
