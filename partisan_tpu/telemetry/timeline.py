"""Round-timeline recorder + opt-in profiler trace.

The ring gives per-round *device* metrics; the timeline adds the host
view: each flush is timestamped, yielding wall-clock per window and
rounds/sec — the number the ROADMAP north star is denominated in.
:func:`profile_trace` wraps one window in a ``jax.profiler`` trace for
kernel-level profiling (opt-in; traces are large).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, List, Optional

import jax


class RoundTimeline:
    """Timestamps each window flush: wall-clock per window, rounds/sec."""

    def __init__(self) -> None:
        self.windows: List[Dict[str, float]] = []

    def observe(self, rounds: int, seconds: float,
                t_wall: Optional[float] = None) -> Dict[str, float]:
        row = {
            "window": len(self.windows),
            "rounds": int(rounds),
            "seconds": float(seconds),
            "rounds_per_sec": (rounds / seconds) if seconds > 0
            else float("inf"),
            "t_wall": time.time() if t_wall is None else t_wall,
        }
        self.windows.append(row)
        return row

    @property
    def total_rounds(self) -> int:
        return int(sum(w["rounds"] for w in self.windows))

    @property
    def total_seconds(self) -> float:
        return float(sum(w["seconds"] for w in self.windows))

    @property
    def rounds_per_sec(self) -> float:
        """Aggregate sustained rate over every observed window."""
        s = self.total_seconds
        return self.total_rounds / s if s > 0 else float("inf")

    def summary(self) -> Dict[str, float]:
        return {
            "windows": len(self.windows),
            "rounds": self.total_rounds,
            "seconds": self.total_seconds,
            "rounds_per_sec": self.rounds_per_sec,
        }


@contextlib.contextmanager
def profile_trace(logdir: str) -> Iterator[None]:
    """``jax.profiler`` trace context for kernel-level profiling of a
    window (opt-in: pass ``profile_dir`` to ``run_with_telemetry``)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
