"""Device-side message lifecycle tracer (ISSUE 16 tentpole) — the span
plane on top of the flight recorder's wire capture.

The flight recorder (:mod:`.flight`) answers "what was on the wire in
round r".  This module answers the question operators actually ask:
"what happened to THIS message, and why did convergence take 14
rounds?" — the reference's causal-context metadata plus
``partisan_trace_orchestrator``'s per-message reconstruction (SURVEY
§5.1/§5.3), rebuilt as in-scan int32 arithmetic:

  * every traced message carries a compact trace id ``(src, born,
    seq)`` — source node, birth round (``Msgs.born``, stable across
    held/retransmit lifetimes) and a sequence stamp.  ``seq`` is either
    a protocol payload field named by ``TraceSpec.seq_field`` (e.g.
    ``"seq"`` for qos.ack streams, ``"ref"`` for workload promises) or,
    by default, the ``wire_hash`` payload digest bitcast to int32 — the
    SAME identity the legacy wire observer records, which is what makes
    the critical-path ground-truth comparison exact.
  * lifecycle events (EMITTED, HELD, EXCHANGED, DELIVERED, ACKED,
    RETRANSMITTED, DEAD_LETTERED, SHED, CHAOS_DROPPED/DELAYED) are
    recorded into a flight-ring-style ``[window, cap, 7]`` int32 ring
    carried through the scan: ONE gather-shaped compaction per round
    over the concatenated event captures, ONE ``dynamic_update_slice``
    at the cursor, counted overflow, ONE device->host transfer per
    window, ZERO collectives (each shard records its own slots under
    the dataplane — identical discipline to :func:`.flight
    .flight_record`).
  * the event set is a COMPILE-TIME filter (``TraceSpec.events``):
    disabled events never build a capture, so a narrow spec costs only
    what it keeps.  ``trace=None`` compiles byte-identical programs
    (the flight recorder's off-path contract).

Host side, :func:`trace_spans` folds the flushed rows into per-message
span trees, :func:`critical_path` walks the delivery DAG backward from
the last delivery to the chain that determined the convergence round,
and :meth:`Span.latency` decomposes end-to-end rounds into
queue / retry / transit / partition-wait segments.  Spans join the
existing Perfetto view via :func:`partisan_tpu.telemetry.perfetto
.chrome_trace(spans=...)`.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..ops import msg as msgops
from ..ops.msg import Msgs

# ---------------------------------------------------------------------------
# lifecycle event codes (the `ev` column) — int32 constants baked into
# the program, decoded by name on the host

EV_EMITTED = 0         # entered the network (post send-interposition)
EV_HELD = 1            # sat a round in the delay/hold buffer
EV_EXCHANGED = 2       # crossed a shard boundary (sharded dataplane only)
EV_DELIVERED = 3       # routed into a destination inbox row
EV_ACKED = 4           # sender saw the ack / promise completed
EV_RETRANSMITTED = 5   # sender re-emitted after backoff
EV_DEAD_LETTERED = 6   # sender gave up (max attempts)
EV_SHED = 7            # admission control dropped the request at issue
EV_CHAOS_DROPPED = 8   # chaos schedule dropped it on the wire
EV_CHAOS_DELAYED = 9   # chaos schedule delayed (or duplicated) it

EVENT_NAMES: Tuple[str, ...] = (
    "emitted", "held", "exchanged", "delivered", "acked",
    "retransmitted", "dead_lettered", "shed", "chaos_dropped",
    "chaos_delayed")
EVENT_CODES: Dict[str, int] = {n: i for i, n in enumerate(EVENT_NAMES)}

# columns of one trace slot, in order
COLUMNS = ("rnd", "ev", "src", "dst", "typ", "born", "seq")
N_COLS = len(COLUMNS)


@struct.dataclass
class TraceRing:
    """Device state of the tracer, carried through the scan.  Same shape
    discipline as :class:`.flight.FlightRing`: ``buf[w, s]`` holds slot
    ``s`` of window-row ``w`` (empty slots have ``rnd == -1``),
    ``overflow`` is ``[n_shards]`` so the dataplane counts per shard
    without a collective."""
    buf: jax.Array       # [window, cap, 7] int32
    cursor: jax.Array    # scalar int32 — rows recorded since last flush
    overflow: jax.Array  # [n_shards] int32 — head-capped slots, cumulative


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """Host-side tracer config — every field is a compile-time constant
    of the jitted step.

    ``cap`` is the per-round EVENT budget (per shard under the
    dataplane).  ``events=None`` records the full lifecycle; otherwise
    only the listed codes, and disabled events never build a capture
    (Python-level gating, the registry enable-mask pattern).  ``typs``
    and ``node_mod``/``node_phase`` are the flight recorder's wire
    filters applied per event.  ``seq_field`` names an int32 payload
    field to use as the sequence stamp; ``None`` falls back to the
    ``wire_hash`` digest (bitcast to int32)."""
    window: int
    cap: int
    events: Optional[Tuple[int, ...]] = None
    typs: Optional[Tuple[int, ...]] = None
    node_mod: int = 1
    node_phase: int = 0
    seq_field: Optional[str] = None

    def __post_init__(self):
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.cap < 1:
            raise ValueError(f"cap must be >= 1, got {self.cap}")
        if self.node_mod < 1:
            raise ValueError(f"node_mod must be >= 1, got {self.node_mod}")
        if not (0 <= self.node_phase < self.node_mod):
            raise ValueError(
                f"node_phase {self.node_phase} outside [0, {self.node_mod})")
        if self.events is not None:
            bad = [e for e in self.events
                   if not (0 <= int(e) < len(EVENT_NAMES))]
            if bad:
                raise ValueError(
                    f"unknown event codes {bad}; valid: "
                    f"{dict(enumerate(EVENT_NAMES))}")


def event_enabled(spec: TraceSpec, ev: int) -> bool:
    """Compile-time (host) check — callers skip building a capture for
    a disabled event entirely, so the filter costs zero device ops."""
    return spec.events is None or ev in spec.events


def make_trace_ring(spec: TraceSpec, n_shards: int = 1) -> TraceRing:
    """An empty ring; ``n_shards > 1`` concatenates per-shard cap slices
    exactly like :func:`.flight.make_flight_ring` (place with
    :func:`place_trace_ring` before a sharded run)."""
    return TraceRing(
        buf=jnp.full((spec.window, n_shards * spec.cap, N_COLS), -1,
                     jnp.int32),
        cursor=jnp.int32(0),
        overflow=jnp.zeros((n_shards,), jnp.int32),
    )


def trace_partition_specs(NODE_AXIS: str) -> TraceRing:
    """shard_map in/out specs: cap axis sharded, cursor replicated,
    one overflow counter per shard."""
    from jax.sharding import PartitionSpec as P
    return TraceRing(buf=P(None, NODE_AXIS), cursor=P(),
                     overflow=P(NODE_AXIS))


def place_trace_ring(ring: TraceRing, mesh) -> TraceRing:
    """device_put the ring with its dataplane shardings."""
    from jax.sharding import NamedSharding
    from ..parallel.mesh import NODE_AXIS
    specs = trace_partition_specs(NODE_AXIS)
    return TraceRing(
        buf=jax.device_put(ring.buf, NamedSharding(mesh, specs.buf)),
        cursor=jax.device_put(ring.cursor,
                              NamedSharding(mesh, specs.cursor)),
        overflow=jax.device_put(ring.overflow,
                                NamedSharding(mesh, specs.overflow)),
    )


# ---------------------------------------------------------------------------
# device-side captures: each event contributes one capture dict of flat
# int32 columns + a keep mask; trace_record compacts ALL of a round's
# captures in ONE gather


def msg_seq(spec: TraceSpec, m: Msgs) -> jax.Array:
    """[M] int32 sequence stamp for a wire buffer: the named payload
    field when ``seq_field`` is set, else the wire_hash digest bitcast
    (value-preserving — the legacy observer's ``TraceEntry.hash``)."""
    if spec.seq_field is not None:
        s = m.data[spec.seq_field]
        return s.reshape((m.cap,)).astype(jnp.int32)
    return jax.lax.bitcast_convert_type(msgops.wire_hash(m), jnp.int32)


def _filter(spec: TraceSpec, keep: jax.Array, src: jax.Array,
            dst: jax.Array, typ: jax.Array) -> jax.Array:
    if spec.typs is not None:
        tt = jnp.asarray(tuple(spec.typs), jnp.int32)
        keep = keep & jnp.any(typ[:, None] == tt[None, :], axis=1)
    if spec.node_mod > 1:
        phase = jnp.int32(spec.node_phase)
        mod = jnp.int32(spec.node_mod)
        keep = keep & ((jnp.maximum(src, 0) % mod == phase)
                       | (jnp.maximum(dst, 0) % mod == phase))
    return keep


def wire_capture(spec: TraceSpec, ev: int, m: Msgs,
                 keep: Optional[jax.Array] = None,
                 seq: Optional[jax.Array] = None) -> Optional[dict]:
    """Capture for a wire-buffer event.  ``keep`` defaults to
    ``m.valid`` — callers pass the exact slot mask for the event (e.g.
    the chaos drop mask over the pre-chaos buffer).  ``seq`` lets the
    caller reuse one :func:`msg_seq` across events that share buffer
    positions (one hash per buffer per round, the <=5% overhead bar).
    Returns ``None`` when the event is compile-time disabled."""
    if not event_enabled(spec, ev):
        return None
    k = m.valid if keep is None else keep
    k = _filter(spec, k, m.src, m.dst, m.typ)
    s = msg_seq(spec, m) if seq is None else seq
    M = m.cap
    return dict(keep=k, ev=jnp.full((M,), ev, jnp.int32), src=m.src,
                dst=m.dst, typ=m.typ, born=m.born, seq=s)


def tap_capture(spec: TraceSpec, ev: int, node_ids: jax.Array,
                tap: dict) -> Optional[dict]:
    """Capture for a protocol-state event (``ProtocolBase.trace_taps``).
    ``tap`` holds per-node per-slot columns: ``keep`` ``[n, S]`` bool
    (or ``[n]``), and optional ``dst``/``typ``/``seq``/``born`` arrays
    broadcastable to ``[n, S]`` (missing -> -1).  ``src`` is implied:
    the tapping node itself (``node_ids``)."""
    if not event_enabled(spec, ev):
        return None
    keep = jnp.asarray(tap["keep"])
    if keep.ndim == 1:
        keep = keep[:, None]
    n, S = keep.shape
    src = jnp.broadcast_to(node_ids.astype(jnp.int32)[:, None], (n, S))

    def col(name):
        v = tap.get(name)
        if v is None:
            return jnp.full((n, S), -1, jnp.int32)
        v = jnp.asarray(v, jnp.int32)
        if v.ndim == 1:
            v = v[:, None]
        return jnp.broadcast_to(v, (n, S))

    dst, typ, seq, born = col("dst"), col("typ"), col("seq"), col("born")
    flat = lambda x: x.reshape((n * S,))  # noqa: E731
    keep, src, dst, typ, seq, born = map(
        flat, (keep, src, dst, typ, seq, born))
    keep = _filter(spec, keep, src, dst, typ)
    return dict(keep=keep, ev=jnp.full((n * S,), ev, jnp.int32), src=src,
                dst=dst, typ=typ, born=born, seq=seq)


def trace_record(ring: TraceRing, spec: TraceSpec,
                 caps: Sequence[Optional[dict]],
                 rnd: jax.Array) -> TraceRing:
    """Write one round's lifecycle events into the ring (device, inside
    the scan / shard_map body).  All captures concatenate into one flat
    column set and compact with ONE cumsum + searchsorted gather into
    the ``[cap, 7]`` row — the flight recorder's O(cap log M) shape;
    slots past ``cap`` increment ``overflow`` (never silent).  Pure
    shard-local arithmetic, zero collectives."""
    window, cap = ring.buf.shape[0], ring.buf.shape[1]
    caps = [c for c in caps if c is not None]
    rnd_col = jnp.broadcast_to(jnp.asarray(rnd, jnp.int32), (cap,))
    if not caps:  # everything compile-time filtered: an empty row
        row = jnp.full((cap, N_COLS), -1, jnp.int32)
        ovf = ring.overflow
    else:
        cat = {k: jnp.concatenate([c[k] for c in caps])
               for k in ("keep", "ev", "src", "dst", "typ", "born", "seq")}
        keep = cat["keep"]
        csum = jnp.cumsum(keep.astype(jnp.int32))     # [M] inclusive
        total = csum[-1]
        n_kept = jnp.minimum(total, cap)
        slots = jnp.arange(cap, dtype=jnp.int32)
        ok = slots < n_kept
        gi = jnp.where(ok, jnp.searchsorted(csum, slots + 1)
                       .astype(jnp.int32), 0)
        cols = jnp.stack([
            rnd_col, cat["ev"][gi], cat["src"][gi], cat["dst"][gi],
            cat["typ"][gi], cat["born"][gi], cat["seq"][gi]], axis=1)
        row = jnp.where(ok[:, None], cols, -1)
        ovf = ring.overflow + (total - n_kept)
    slot = jnp.mod(ring.cursor, window)               # wrap = keep-latest
    buf = jax.lax.dynamic_update_slice(
        ring.buf, row[None], (slot, jnp.int32(0), jnp.int32(0)))
    return ring.replace(buf=buf, cursor=ring.cursor + 1, overflow=ovf)


def trace_flush(ring: TraceRing) -> Tuple[np.ndarray, int, TraceRing]:
    """ONE device->host transfer of the whole window; returns
    ``(rows, overflow, reset_ring)`` exactly like :func:`.flight
    .flight_flush` (wrap degrades to keep-latest; only counters reset)."""
    buf = np.asarray(jax.device_get(ring.buf))
    n = int(ring.cursor)
    window = buf.shape[0]
    if n > window:
        start = n % window
        buf = np.concatenate([buf[start:], buf[:start]])
        n = window
    overflow = int(np.asarray(jax.device_get(ring.overflow)).sum())
    reset = ring.replace(cursor=jnp.int32(0),
                         overflow=jnp.zeros_like(ring.overflow))
    return buf[:n], overflow, reset


# ---------------------------------------------------------------------------
# host side: decode -> span trees -> critical path


class SpanEvent(NamedTuple):
    """One decoded lifecycle event (one kept ring slot)."""
    rnd: int
    ev: int
    src: int
    dst: int
    typ: int
    born: int
    seq: int

    @property
    def name(self) -> str:
        return EVENT_NAMES[self.ev]


def trace_events(rows: np.ndarray) -> List[SpanEvent]:
    """Decode flushed rows (``rnd == -1`` slots are padding) into the
    flat event stream, oldest round first, slot order within a round."""
    out: List[SpanEvent] = []
    rows = np.asarray(rows)
    if rows.size == 0:
        return out
    flat = rows.reshape((-1, N_COLS))
    for r in flat[flat[:, 0] >= 0]:
        out.append(SpanEvent(*(int(v) for v in r)))
    return out


#: span key: the trace id minus the birth round — ``(src, seq)`` joins
#: wire events with protocol-tap events that cannot see ``Msgs.born``
#: (e.g. qos.ack rows); ``born`` is recovered from the first wire event.
SpanKey = Tuple[int, int]


@dataclasses.dataclass
class Span:
    """Per-message lifecycle reconstructed from the event stream."""
    src: int
    seq: int
    typ: int = -1
    dst: int = -1
    born: int = -1
    events: List[SpanEvent] = dataclasses.field(default_factory=list)

    def rounds(self, ev: int) -> List[int]:
        return [e.rnd for e in self.events if e.ev == ev]

    @property
    def first_rnd(self) -> int:
        return min(e.rnd for e in self.events)

    @property
    def last_rnd(self) -> int:
        return max(e.rnd for e in self.events)

    @property
    def delivered_rnd(self) -> Optional[int]:
        d = self.rounds(EV_DELIVERED)
        return min(d) if d else None

    @property
    def acked_rnd(self) -> Optional[int]:
        a = self.rounds(EV_ACKED)
        return min(a) if a else None

    @property
    def attempts(self) -> int:
        return 1 + len(self.rounds(EV_RETRANSMITTED))

    def latency(self) -> Dict[str, int]:
        """Decompose end-to-end rounds into segments: ``queue`` (rounds
        spent held in the delay buffer), ``retry`` (first emission to
        last re-emission), ``transit`` (the delivery hop itself),
        ``partition_wait`` (the unexplained remainder — rounds the
        message's fate was gated on reachability, e.g. a partition
        healing or a peer's inbox draining)."""
        born = self.born if self.born >= 0 else self.first_rnd
        end_r = self.acked_rnd
        if end_r is None:
            end_r = self.delivered_rnd
        if end_r is None:
            end_r = self.last_rnd
        total = max(0, end_r - born)
        queue = len(self.rounds(EV_HELD))
        emits = sorted(self.rounds(EV_EMITTED)
                       + self.rounds(EV_RETRANSMITTED))
        retry = (emits[-1] - emits[0]) if len(emits) > 1 else 0
        transit = 1 if self.delivered_rnd is not None else 0
        wait = max(0, total - queue - retry - transit)
        return {"total": total, "queue": queue, "retry": retry,
                "transit": transit, "partition_wait": wait}


def trace_spans(events: Iterable[SpanEvent]) -> Dict[SpanKey, Span]:
    """Fold the event stream into per-message spans keyed by
    ``(src, seq)``.  ``typ``/``dst``/``born`` fill from the first event
    that knows them (protocol taps record -1 for columns their state
    row cannot see)."""
    spans: Dict[SpanKey, Span] = {}
    for e in events:
        sp = spans.get((e.src, e.seq))
        if sp is None:
            sp = spans[(e.src, e.seq)] = Span(src=e.src, seq=e.seq)
        sp.events.append(e)
        if sp.typ < 0 and e.typ >= 0:
            sp.typ = e.typ
        if sp.dst < 0 and e.dst >= 0:
            sp.dst = e.dst
        if sp.born < 0 and e.born >= 0:
            sp.born = e.born
    return spans


#: a delivery fact: ``(rnd, src, dst, typ, seq)`` — the unit both the
#: tracer and the legacy wire observer can produce, so critical_path
#: runs identically on either side of the ground-truth comparison.
Delivery = Tuple[int, int, int, int, int]


def deliveries(events: Iterable[SpanEvent]) -> List[Delivery]:
    """DELIVERED events as delivery facts."""
    return [(e.rnd, e.src, e.dst, e.typ, e.seq)
            for e in events if e.ev == EV_DELIVERED]


def wire_deliveries(entries) -> List[Delivery]:
    """Legacy wire-observer recomputation: a
    :class:`partisan_tpu.verify.trace.TraceEntry` stream (the
    ``capture_wire`` path records each round's wire buffer — with no
    inbox overflow that IS the delivered set) mapped onto the same
    delivery facts.  The uint32 entry hash bitcasts to the tracer's
    int32 ``seq`` stamp."""
    out: List[Delivery] = []
    for e in entries:
        h = int(e.hash) & 0xFFFFFFFF
        seq = h - (1 << 32) if h >= (1 << 31) else h
        out.append((int(e.rnd), int(e.src), int(e.dst), int(e.typ), seq))
    return out


def critical_path(deliv: Iterable[Delivery]) -> List[Delivery]:
    """The dependency chain that determined the convergence round: walk
    backward from the LAST delivery (max by the full tuple — a total
    order, so recomputations agree exactly), each step picking the
    latest earlier delivery INTO the current link's source node (the
    information arrival that enabled it to send).  Returns the chain
    oldest-first."""
    deliv = sorted(set(deliv))
    if not deliv:
        return []
    by_dst: Dict[int, List[Delivery]] = {}
    for d in deliv:
        by_dst.setdefault(d[2], []).append(d)   # sorted order preserved
    cur = deliv[-1]
    path = [cur]
    while True:
        prior = [d for d in by_dst.get(cur[1], ()) if d[0] < cur[0]]
        if not prior:
            break
        cur = prior[-1]                          # max (rnd, src, dst, ...)
        path.append(cur)
    return path[::-1]


# ---------------------------------------------------------------------------
# persistence (scripts/trace_report.py): one JSON object per event


def write_spans(path: str, events: Iterable[SpanEvent]) -> int:
    n = 0
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps({"rnd": e.rnd, "ev": e.name, "src": e.src,
                                "dst": e.dst, "typ": e.typ, "born": e.born,
                                "seq": e.seq}) + "\n")
            n += 1
    return n


def read_spans(path: str) -> List[SpanEvent]:
    out: List[SpanEvent] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line)
            out.append(SpanEvent(int(d["rnd"]), EVENT_CODES[d["ev"]],
                                 int(d["src"]), int(d["dst"]),
                                 int(d["typ"]), int(d["born"]),
                                 int(d["seq"])))
    return out
