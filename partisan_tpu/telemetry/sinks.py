"""Host-side telemetry sinks.

A :class:`TelemetrySink` consumes row dicts — per-round metric rows
flushed from the device ring, per-window timeline rows, and host events
(fault injections, orchestration polls).  Implementations:

  * :class:`JsonlSink` — one JSON object per row, append-ordered (the
    dets-trace-file analog for metrics; verify/trace.py uses the same
    format for wire traces).
  * :class:`PrometheusSink` — accumulates counters / latest gauges and
    renders the text exposition format (``# HELP`` / ``# TYPE`` /
    samples).  Counter rows carry per-round *deltas*; the sink
    accumulates them into the cumulative ``_total`` samples Prometheus
    expects.  Host events count into
    ``partisan_events_total{event="..."}``.

:func:`parse_exposition` is the minimal exposition-line parser used by
the smoke test to round-trip the output.
"""

from __future__ import annotations

import json
import numbers
import re
from typing import Dict, IO, List, Mapping, Optional, Protocol, Union

import numpy as np

from .registry import MetricRegistry, all_help, all_kinds

Row = Mapping[str, object]

# the workload-plane histogram naming convention (workload/latency.py):
# per-bucket ring columns "fam__bucket_<upper-edge-or-inf>" + "fam__sum"
_HIST_BUCKET_RE = re.compile(r"^(?P<fam>.+)__bucket_(?P<bound>\d+|inf)$")


class TelemetrySink(Protocol):
    def write_row(self, row: Row) -> None: ...
    def close(self) -> None: ...


def _jsonable(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    if hasattr(v, "item") and getattr(v, "ndim", None) == 0:  # jax scalar
        return v.item()
    return v


class JsonlSink:
    """One JSON object per row; flushed per write so readers (and crashed
    runs) always see whole lines."""

    def __init__(self, path_or_file: Union[str, IO[str]], mode: str = "w"):
        if isinstance(path_or_file, str):
            self.path: Optional[str] = path_or_file
            self._f: IO[str] = open(path_or_file, mode)
            self._owns = True
        else:
            self.path = None
            self._f = path_or_file
            self._owns = False
        self.rows_written = 0

    def write_row(self, row: Row) -> None:
        self._f.write(json.dumps(
            {k: _jsonable(v) for k, v in row.items()}) + "\n")
        self._f.flush()
        self.rows_written += 1

    def close(self) -> None:
        if self._owns and not self._f.closed:
            self._f.close()

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PrometheusSink:
    """Text-exposition accumulator.

    Exports every registry metric seen so far (counters accumulate
    per-round deltas, gauges keep the latest value) plus the host-side
    ``rounds_per_sec`` gauge and an ``events_total`` counter labelled by
    event name.  Row keys outside the registry (window bookkeeping like
    ``window`` / ``seconds``) are ignored rather than polluting the
    namespace.
    """

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 path: Optional[str] = None, namespace: str = "partisan"):
        self.namespace = namespace
        self.path = path
        self._kinds = all_kinds(registry)
        self._help = all_help(registry)
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._events: Dict[str, int] = {}

    def write_row(self, row: Row) -> None:
        ev = row.get("event")
        if ev is not None:
            self._events[str(ev)] = self._events.get(str(ev), 0) + 1
            return
        for name, v in row.items():
            kind = self._kinds.get(name)
            if kind is None or not isinstance(v, numbers.Number):
                continue
            if kind == "counter":
                self._counters[name] = self._counters.get(name, 0.0) + float(v)
            else:
                self._gauges[name] = float(v)

    # ------------------------------------------------------------ export

    def _fmt(self, v: float) -> str:
        return repr(int(v)) if float(v).is_integer() else repr(float(v))

    def _hist_families(self) -> Dict[str, Dict[str, float]]:
        """Group gauge columns following the workload-plane histogram
        naming (``fam__bucket_<bound>`` + ``fam__sum``, see
        workload/latency.py) into native histogram families.  A family
        only qualifies when its ``__sum`` column is present — bare
        ``__bucket_`` lookalikes keep rendering as plain gauges."""
        fams: Dict[str, Dict[str, float]] = {}
        for name, v in self._gauges.items():
            m = _HIST_BUCKET_RE.match(name)
            if m is not None:
                fams.setdefault(m["fam"], {})[m["bound"]] = v
        return {f: b for f, b in fams.items()
                if f"{f}__sum" in self._gauges}

    def expose(self) -> str:
        """Render the Prometheus text exposition format, one family per
        metric: ``# HELP`` / ``# TYPE`` headers then the sample line.
        Bucketed ring metrics render as NATIVE histograms — cumulative
        ``le`` buckets plus ``_sum``/``_count`` — instead of a pile of
        per-bucket gauges."""
        ns = self.namespace
        lines: List[str] = []
        hists = self._hist_families()
        hidden = {n for f, b in hists.items()
                  for n in [f"{f}__sum"]
                  + [f"{f}__bucket_{bound}" for bound in b]}
        for name in sorted(self._counters):
            fam = f"{ns}_{name}_total"
            lines.append(f"# HELP {fam} {self._help.get(name, name)}")
            lines.append(f"# TYPE {fam} counter")
            lines.append(f"{fam} {self._fmt(self._counters[name])}")
        for name in sorted(self._gauges):
            if name in hidden:
                continue
            fam = f"{ns}_{name}"
            lines.append(f"# HELP {fam} {self._help.get(name, name)}")
            lines.append(f"# TYPE {fam} gauge")
            lines.append(f"{fam} {self._fmt(self._gauges[name])}")
        for name in sorted(hists):
            fam = f"{ns}_{name}"
            buckets = hists[name]
            finite = sorted((b for b in buckets if b != "inf"), key=int)
            help_text = self._help.get(
                name + "__sum", f"Latency histogram {name} (rounds).")
            lines.append(f"# HELP {fam} {help_text}")
            lines.append(f"# TYPE {fam} histogram")
            cum = 0.0
            for b in finite:
                cum += buckets[b]
                lines.append(
                    f'{fam}_bucket{{le="{b}"}} {self._fmt(cum)}')
            total = cum + buckets.get("inf", 0.0)
            lines.append(f'{fam}_bucket{{le="+Inf"}} {self._fmt(total)}')
            lines.append(
                f"{fam}_sum {self._fmt(self._gauges[name + '__sum'])}")
            lines.append(f"{fam}_count {self._fmt(total)}")
        if self._events:
            fam = f"{ns}_events_total"
            lines.append(f"# HELP {fam} Host telemetry events by name.")
            lines.append(f"# TYPE {fam} counter")
            for ev in sorted(self._events):
                lines.append(f'{fam}{{event="{ev}"}} {self._events[ev]}')
        return "\n".join(lines) + ("\n" if lines else "")

    def close(self) -> None:
        if self.path is not None:
            with open(self.path, "w") as f:
                f.write(self.expose())


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)\s*$")


def parse_exposition(text: str) -> Dict[str, Dict[str, object]]:
    """Minimal Prometheus text-format parser (the smoke-test round-trip):
    returns ``{family: {"help": str, "type": str, "samples":
    {label_string_or_'': float}}}``.  Raises ValueError on lines that are
    neither comments, blanks, nor well-formed samples.
    """
    out: Dict[str, Dict[str, object]] = {}

    def fam(name: str) -> Dict[str, object]:
        return out.setdefault(
            name, {"help": "", "type": "", "samples": {}})

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, _, help_text = line[len("# HELP "):].partition(" ")
            fam(name)["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            fam(name)["type"] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line {lineno}: {line!r}")
        fam(m["name"])["samples"][m["labels"] or ""] = float(m["value"])
    return out
