"""Chrome-trace / Perfetto JSON export of a recorded run.

Joins the three observability planes on one timeline so a run opens in
``ui.perfetto.dev`` / ``chrome://tracing`` as a single picture:

  * **wire tracks** — flight-recorder :class:`verify.trace.TraceEntry`
    streams as complete ("X") slices, one process (track group) per
    node SHARD and one thread lane per source node: the visual analog
    of the reference's per-node trace files
    (``partisan_trace_file.erl`` writes one dets file per run; here the
    shard layout mirrors the dataplane's device placement);
  * **counter tracks** — per-round metric rows from the telemetry ring
    (``msgs_delivered``, ``inflight``, ...) plus the
    ``mesh.collective_stats`` bytes/collective gauges of a compiled
    sharded round, as Chrome counter ("C") events;
  * **host events** — ``telemetry.emit_event`` rows (fault injections,
    orchestration polls), placed by their ``round`` stamp (the
    :func:`telemetry.note_round` correlation) and ordered by their
    monotonic ``seq``.

The simulator has no wall-clock inside the scan, so the time axis is
**rounds**: ``ts = round * us_per_round`` (default 1000 us per round
— one round renders as one millisecond).  The output is the plain
Chrome trace-event JSON object format (``{"traceEvents": [...]}``),
schema-checked in tests/test_flight.py.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

__all__ = ["chrome_trace", "write_chrome_trace"]

# reserved pids: shards occupy [0, n_shards); the two host-side tracks
# follow them.  Host-event instants and compile spans share the ONE
# host process (separate named thread lanes) so a flight trace and a
# compile ledger open in a single Perfetto view without track-name
# collisions (ISSUE 14 small fix).
_METRICS_TRACK = "metrics"
_HOST_TRACK = "host"
_SPANS_TRACK = "message spans"
_HOST_EVENTS_TID = 0
_COMPILE_TID = 1


def _meta(pid: int, name: str) -> Dict[str, Any]:
    return {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name}}


def _thread_meta(pid: int, tid: int, name: str) -> Dict[str, Any]:
    return {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": name}}


def chrome_trace(
    entries: Iterable[Any] = (), *,
    n_nodes: Optional[int] = None,
    n_shards: int = 1,
    typ_names: Optional[Sequence[str]] = None,
    metric_rows: Iterable[Mapping[str, Any]] = (),
    host_events: Iterable[Mapping[str, Any]] = (),
    collective_stats: Optional[Mapping[str, Any]] = None,
    compile_spans: Iterable[Mapping[str, Any]] = (),
    spans: Iterable[Any] = (),
    us_per_round: int = 1000,
) -> Dict[str, Any]:
    """Build the Chrome trace-event dict.

    ``entries`` — TraceEntry stream (flight recorder or legacy).
    ``n_nodes``/``n_shards`` — the dataplane layout: node i renders on
    process ``i // (n_nodes // n_shards)``; without ``n_nodes`` every
    node lands on shard 0.  ``typ_names`` (e.g. ``proto.msg_types``)
    labels slices; unknown tags fall back to ``typ<k>``.
    ``metric_rows`` — ring rows (dicts with ``round``).  ``host_events``
    — event-bus rows (dicts with ``event``/``seq``/``round``).
    ``collective_stats`` — a ``mesh.collective_stats`` result; rendered
    as per-op ``collective_bytes`` / ``collectives_per_round`` counter
    tracks (one sample — the compiled round's contract, constant over
    the run).
    ``compile_spans`` — ``observatory.CompileLedger.compile_spans()``
    rows; rendered as complete slices on the host process's
    "xla compile" lane.  Compile spans carry wall-clock, not rounds, so
    their time base is microseconds from the earliest span — they share
    the VIEW (one process group, no name collisions with host-event
    instants), not the round axis.
    ``spans`` — :class:`.tracer.Span` message lifecycles (the values of
    ``tracer.trace_spans``); each renders one complete slice on a
    "message spans" process (one thread lane per SOURCE node) from
    birth round to terminal event, carrying the latency decomposition
    in ``args``, plus one instant per lifecycle event on the same lane
    — the per-message drill-down the wire track cannot give (it shows
    hops, not lifetimes).
    """
    upr = int(us_per_round)
    n_loc = None
    if n_nodes is not None and n_shards >= 1 and n_nodes % n_shards == 0:
        n_loc = n_nodes // n_shards

    def shard_of(node: int) -> int:
        if n_loc:
            return min(max(node, 0) // n_loc, n_shards - 1)
        return 0

    def typ_name(t: int) -> str:
        if typ_names is not None and 0 <= t < len(typ_names):
            return str(typ_names[t])
        return f"typ{t}"

    metrics_pid = n_shards
    host_pid = n_shards + 1
    events: List[Dict[str, Any]] = [
        _meta(metrics_pid, _METRICS_TRACK), _meta(host_pid, _HOST_TRACK),
        _thread_meta(host_pid, _HOST_EVENTS_TID, "events"),
        _thread_meta(host_pid, _COMPILE_TID, "xla compile")]
    seen_shards = set()

    for e in entries:
        pid = shard_of(e.src)
        if pid not in seen_shards:
            seen_shards.add(pid)
            events.append(_meta(pid, f"node shard {pid}"))
        events.append({
            "name": typ_name(e.typ), "cat": "wire", "ph": "X",
            "ts": e.rnd * upr, "dur": upr, "pid": pid, "tid": e.src,
            "args": {"round": e.rnd, "src": e.src, "dst": e.dst,
                     "typ": e.typ, "channel": e.channel,
                     "hash": e.hash,
                     "dst_shard": shard_of(e.dst)},
        })

    for row in metric_rows:
        rnd = row.get("round")
        if rnd is None:
            continue
        ts = int(float(rnd)) * upr
        for k, v in row.items():
            if k == "round" or not isinstance(v, (int, float)):
                continue
            events.append({"name": k, "ph": "C", "ts": ts,
                           "pid": metrics_pid, "tid": 0,
                           "args": {k: v}})

    if collective_stats is not None:
        counts = dict(collective_stats.get("counts", {}))
        total = dict(collective_stats.get("total_bytes", {}))
        events.append({"name": "collectives_per_round", "ph": "C",
                       "ts": 0, "pid": metrics_pid, "tid": 0,
                       "args": {op: int(n) for op, n in counts.items()
                                if n}})
        events.append({"name": "collective_bytes", "ph": "C",
                       "ts": 0, "pid": metrics_pid, "tid": 0,
                       "args": {op: int(b) for op, b in total.items()
                                if b}})

    for i, row in enumerate(host_events):
        name = row.get("event")
        if name is None:
            continue
        rnd = row.get("round")
        seq = row.get("seq", i)
        # round-stamped events land on the round timeline; unstamped
        # ones order by seq just past the origin
        ts = int(float(rnd)) * upr if rnd is not None else int(seq)
        args = {k: v for k, v in row.items()
                if isinstance(v, (int, float, str, bool))}
        events.append({"name": str(name), "cat": "host", "ph": "i",
                       "s": "g", "ts": ts, "pid": host_pid,
                       "tid": _HOST_EVENTS_TID, "args": args})

    span_list = list(spans)
    if span_list:
        spans_pid = n_shards + 2
        events.append(_meta(spans_pid, _SPANS_TRACK))
        for sp in span_list:
            start = sp.born if sp.born >= 0 else sp.first_rnd
            end = max(sp.last_rnd, start) + 1
            name = (f"{typ_name(sp.typ)} #{sp.seq}" if sp.typ >= 0
                    else f"msg #{sp.seq}")
            args = {"src": sp.src, "dst": sp.dst, "seq": sp.seq,
                    "attempts": sp.attempts, **sp.latency()}
            events.append({
                "name": name, "cat": "span", "ph": "X",
                "ts": start * upr, "dur": (end - start) * upr,
                "pid": spans_pid, "tid": sp.src, "args": args})
            for e in sp.events:
                events.append({
                    "name": e.name, "cat": "span", "ph": "i", "s": "t",
                    "ts": e.rnd * upr, "pid": spans_pid, "tid": sp.src,
                    "args": {"round": e.rnd, "dst": e.dst}})

    cspans = [s for s in compile_spans if s.get("duration_s") is not None]
    if cspans:
        t0_wall = min(float(s.get("t_start", 0.0)) for s in cspans)
        for s in cspans:
            dur_us = max(int(float(s["duration_s"]) * 1e6), 1)
            ts = int((float(s.get("t_start", 0.0)) - t0_wall) * 1e6)
            args = {k: v for k, v in s.items()
                    if isinstance(v, (int, float, str, bool))}
            events.append({
                "name": str(s.get("name", s.get("event", "compile"))),
                "cat": "compile", "ph": "X", "ts": ts, "dur": dur_us,
                "pid": host_pid, "tid": _COMPILE_TID, "args": args})

    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"us_per_round": upr, "n_shards": n_shards,
                          **({"n_nodes": n_nodes}
                             if n_nodes is not None else {})}}


def write_chrome_trace(path: str, *args, **kw) -> Dict[str, Any]:
    """:func:`chrome_trace` + ``json.dump`` — the artifact opens
    directly in ui.perfetto.dev / chrome://tracing."""
    doc = chrome_trace(*args, **kw)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc
