"""Adaptive control plane: in-scan closed-loop controllers (ISSUE 10).

Controllers live *inside* the compiled round step.  They read the
per-round global metrics the engine already produces (unsharded: local
counters; sharded: the ONE stacked psum both dataplanes already emit —
zero added collectives), update integer milli-unit state (EWMA error
filter + AIMD / additive-step laws), and write setpoints back into
protocol state through ``apply_setpoints`` actuator hooks.

The ``ControlPlane`` pytree rides in ``World.aux`` (replicated across
shards), so it persists through ``lax.scan``, checkpoints with the
world, and resumes bit-identically.
"""

from .controllers import (  # noqa: F401
    ERR_CLAMP,
    aimd_step,
    additive_step,
    ewma_filter,
    host_aimd_step,
    host_additive_step,
    host_ewma_filter,
)
from .plane import (  # noqa: F401
    AIMD,
    STEP,
    Controller,
    ControlPlane,
    ControlSpec,
    attach_plane,
    control_specs,
    host_update_plane,
    plane_metrics,
    setpoint_values,
    update_plane,
    validate_control,
)
