"""Integer milli-unit controller primitives with bit-matching host twins.

All device updates are int32 arithmetic in **milli-units** (1000 = 1.0)
using floor division, which jnp and plain Python ints agree on for
negative operands — so each primitive has a host twin in plain Python
that reproduces the device update bit-for-bit (the repo convention from
``workload/latency`` and ``workload/shed``).

Overflow contract: error inputs are clamped to ``±ERR_CLAMP`` (2^20
milli) before filtering, so ``alpha_milli * (err - filt)`` stays within
``1000 * 2^21 < 2^31`` and never wraps.  Setpoint laws require
``hi * mult_milli < 2^31`` from the caller (validated by ControlSpec).
"""

from __future__ import annotations

import jax.numpy as jnp

# error values (milli) are clamped here before entering the filter; the
# filter output then stays inside the clamp hull, so the multiply below
# is wrap-free: 1000 * (2 * ERR_CLAMP) = 2.09e9 < 2^31 - 1.
ERR_CLAMP = 1 << 20


# ------------------------------------------------------------------ device

def clamp_err(err):
    """Clamp a milli-unit error signal into the overflow-safe band."""
    return jnp.clip(jnp.asarray(err, jnp.int32), -ERR_CLAMP, ERR_CLAMP)


def ewma_filter(filt, err, alpha_milli):
    """One EWMA step: filt' = filt + alpha * (err - filt) / 1000.

    ``alpha_milli`` in [0, 1000]; 1000 tracks the raw error, smaller
    values smooth harder.  Floor division throughout.
    """
    filt = jnp.asarray(filt, jnp.int32)
    err = clamp_err(err)
    return filt + (jnp.int32(alpha_milli) * (err - filt)) // 1000


def aimd_step(sp, decrease, *, add, mult_milli, lo, hi):
    """AIMD law (Chiu–Jain): additive move when healthy, multiplicative
    move on violation.

    ``decrease`` is the boolean violation signal (filtered error > 0).
    ``add`` is signed and in setpoint units, ``mult_milli`` is the
    multiplicative factor in milli (900 = x0.9 shrink for admission;
    2000 = x2 growth for a backoff interval).  Result clipped to
    [lo, hi].
    """
    sp = jnp.asarray(sp, jnp.int32)
    gentle = sp + jnp.int32(add)
    hard = (sp * jnp.int32(mult_milli)) // 1000
    return jnp.clip(jnp.where(decrease, hard, gentle), lo, hi)


def additive_step(sp, err, *, step, deadband_milli, lo, hi):
    """Additive step with hysteresis deadband.

    Positive filtered error (above target, after ``sense``) drives the
    setpoint DOWN by ``step``; error below ``-deadband_milli`` drives it
    UP; inside the deadband the setpoint holds — the hysteresis that
    stops limit-cycling on a noisy signal.
    """
    sp = jnp.asarray(sp, jnp.int32)
    err = jnp.asarray(err, jnp.int32)
    down = err > jnp.int32(deadband_milli)
    up = err < -jnp.int32(deadband_milli)
    delta = jnp.where(down, -jnp.int32(step),
                      jnp.where(up, jnp.int32(step), jnp.int32(0)))
    return jnp.clip(sp + delta, lo, hi)


# ------------------------------------------------------------ host twins

def host_clamp_err(err):
    return max(-ERR_CLAMP, min(ERR_CLAMP, int(err)))


def host_ewma_filter(filt, err, alpha_milli):
    err = host_clamp_err(err)
    return int(filt) + (int(alpha_milli) * (err - int(filt))) // 1000


def host_aimd_step(sp, decrease, *, add, mult_milli, lo, hi):
    sp = int(sp)
    nxt = (sp * int(mult_milli)) // 1000 if decrease else sp + int(add)
    return max(int(lo), min(int(hi), nxt))


def host_additive_step(sp, err, *, step, deadband_milli, lo, hi):
    sp, err = int(sp), int(err)
    if err > int(deadband_milli):
        nxt = sp - int(step)
    elif err < -int(deadband_milli):
        nxt = sp + int(step)
    else:
        nxt = sp
    return max(int(lo), min(int(hi), nxt))
