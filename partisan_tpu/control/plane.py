"""ControlPlane: [n_ctl] controller state carried in ``World.aux``.

A ``ControlSpec`` is a static tuple of ``Controller`` descriptors; the
runtime state is a ``ControlPlane`` pytree of five [n_ctl] int32/bool
vectors (setpoint, filtered error, previous raw input, host override
value, host override flag).  ``update_plane`` runs once per round inside
the compiled step, AFTER the round metrics are built:

  unsharded:  metrics are local counters — already global.
  sharded:    metrics come from the ONE stacked psum the dataplanes
              already emit, so every shard sees identical global values
              and updates its replicated plane copy identically.  Zero
              added collectives; sharded == unsharded trajectories are
              bit-identical.

The plane occupies ``World.aux`` (see ``attach_plane``).  This is
mutually exclusive with the verify/faults and verify/model_checker
harnesses, which use ``aux`` as their omission-schedule dict — those
are standalone exploration drivers, never combined with controllers.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from .controllers import (
    ERR_CLAMP,
    aimd_step,
    additive_step,
    ewma_filter,
    host_aimd_step,
    host_additive_step,
    host_ewma_filter,
)

AIMD = "aimd"
STEP = "step"

# raw per-round inputs are clamped here before the x1000 scale so the
# milli conversion cannot wrap: 1000 * 2e6 = 2e9 < 2^31 - 1.
_IN_CLAMP = 2_000_000


@struct.dataclass
class ControlPlane:
    """Runtime controller state, one slot per controller."""
    setpoint: jax.Array     # [n_ctl] int32, actuator units
    filt: jax.Array         # [n_ctl] int32, filtered error (milli)
    prev: jax.Array         # [n_ctl] int32, previous raw metric sample
    override: jax.Array     # [n_ctl] int32, host-pinned value
    override_on: jax.Array  # [n_ctl] bool


@dataclasses.dataclass(frozen=True)
class Controller:
    """Static description of one closed loop.

    ``metric`` names a per-round step-metrics key (engine counter, chaos
    counter, or a protocol round counter).  ``actuator`` names the knob
    the setpoint drives (``wl.*`` / ``ack.*`` protocol hooks, ``dense.*``
    dataplane cadence) — empty string for an observe-only loop.  The
    error signal is ``sense * (1000 * x - target_milli)`` where ``x`` is
    the raw sample (or its per-round delta when ``delta`` — the right
    mode for cumulative counters).
    """
    name: str
    metric: str
    actuator: str = ""
    kind: str = AIMD
    init: int = 0            # initial setpoint, actuator units
    target_milli: int = 0
    sense: int = 1           # +1: big metric == violation; -1: inverted
    delta: bool = True       # difference cumulative inputs per round
    alpha_milli: int = 1000  # EWMA gain; 1000 = unfiltered
    add: int = 0             # AIMD additive move (signed, setpoint units)
    mult_milli: int = 900    # AIMD multiplicative move (milli)
    step: int = 0            # additive-step move (setpoint units)
    deadband_milli: int = 0  # additive-step hysteresis half-width
    lo: int = 0
    hi: int = 1 << 20


@dataclasses.dataclass(frozen=True)
class ControlSpec:
    """The static controller set; index order is the plane's slot order."""
    controllers: Tuple[Controller, ...]

    def __post_init__(self):
        seen = set()
        for c in self.controllers:
            if c.name in seen:
                raise ValueError(f"duplicate controller name {c.name!r}")
            seen.add(c.name)
            if c.kind not in (AIMD, STEP):
                raise ValueError(
                    f"controller {c.name!r}: unknown kind {c.kind!r} "
                    f"(expected {AIMD!r} or {STEP!r})")
            if c.sense not in (-1, 1):
                raise ValueError(
                    f"controller {c.name!r}: sense must be +1 or -1")
            if not 0 <= c.alpha_milli <= 1000:
                raise ValueError(
                    f"controller {c.name!r}: alpha_milli outside [0, 1000]")
            if not c.lo <= c.hi:
                raise ValueError(f"controller {c.name!r}: lo > hi")
            if abs(c.hi) * max(abs(c.mult_milli), 1) >= (1 << 31):
                raise ValueError(
                    f"controller {c.name!r}: hi * mult_milli would "
                    "overflow int32")

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(c.name for c in self.controllers)

    def index(self, name: str) -> int:
        for i, c in enumerate(self.controllers):
            if c.name == name:
                return i
        raise ValueError(
            f"unknown control knob {name!r}: known knobs are "
            f"{list(self.names)}")

    def init_plane(self) -> ControlPlane:
        n = len(self.controllers)
        return ControlPlane(
            setpoint=jnp.asarray([c.init for c in self.controllers],
                                 jnp.int32),
            filt=jnp.zeros((n,), jnp.int32),
            prev=jnp.zeros((n,), jnp.int32),
            override=jnp.zeros((n,), jnp.int32),
            override_on=jnp.zeros((n,), bool),
        )


# ------------------------------------------------------------- device side

def update_plane(spec: ControlSpec, plane: ControlPlane,
                 metrics: Dict[str, jax.Array]) -> ControlPlane:
    """One control round.  ``metrics`` must hold GLOBAL per-round values
    (local counters unsharded; post-psum totals sharded)."""
    sps, filts, prevs = [], [], []
    for i, c in enumerate(spec.controllers):
        raw = jnp.asarray(metrics[c.metric], jnp.int32).reshape(())
        x = raw - plane.prev[i] if c.delta else raw
        xq = jnp.clip(x, -_IN_CLAMP, _IN_CLAMP)
        err = jnp.clip(jnp.int32(c.sense) * (1000 * xq
                                             - jnp.int32(c.target_milli)),
                       -ERR_CLAMP, ERR_CLAMP)
        filt = ewma_filter(plane.filt[i], err, c.alpha_milli)
        if c.kind == AIMD:
            sp = aimd_step(plane.setpoint[i], filt > 0, add=c.add,
                           mult_milli=c.mult_milli, lo=c.lo, hi=c.hi)
        else:
            sp = additive_step(plane.setpoint[i], filt, step=c.step,
                               deadband_milli=c.deadband_milli,
                               lo=c.lo, hi=c.hi)
        sp = jnp.where(plane.override_on[i], plane.override[i], sp)
        sps.append(sp)
        filts.append(filt)
        prevs.append(raw)
    return plane.replace(setpoint=jnp.stack(sps).astype(jnp.int32),
                         filt=jnp.stack(filts).astype(jnp.int32),
                         prev=jnp.stack(prevs).astype(jnp.int32))


def setpoint_values(spec: ControlSpec,
                    plane: ControlPlane) -> Dict[str, jax.Array]:
    """Actuator name -> scalar setpoint (skips observe-only loops)."""
    return {c.actuator: plane.setpoint[i]
            for i, c in enumerate(spec.controllers) if c.actuator}


def plane_metrics(spec: ControlSpec,
                  plane: ControlPlane) -> Dict[str, jax.Array]:
    """Per-round gauge exports: setpoint + filtered error per loop."""
    out = {}
    for i, c in enumerate(spec.controllers):
        out[f"ctl_{c.name}__setpoint"] = plane.setpoint[i]
        out[f"ctl_{c.name}__err_milli"] = plane.filt[i]
    return out


def metric_names(spec: ControlSpec) -> Tuple[str, ...]:
    names = []
    for c in spec.controllers:
        names.append(f"ctl_{c.name}__setpoint")
        names.append(f"ctl_{c.name}__err_milli")
    return tuple(names)


# --------------------------------------------------------------- host twin

def host_init_plane(spec: ControlSpec) -> Dict[str, list]:
    n = len(spec.controllers)
    return {"setpoint": [c.init for c in spec.controllers],
            "filt": [0] * n, "prev": [0] * n,
            "override": [0] * n, "override_on": [False] * n}


def host_update_plane(spec: ControlSpec, plane: Dict[str, list],
                      metrics: Dict[str, int]) -> Dict[str, list]:
    """Plain-Python twin of ``update_plane`` — bit-matches the device."""
    out = {k: list(v) for k, v in plane.items()}
    for i, c in enumerate(spec.controllers):
        raw = int(metrics[c.metric])
        x = raw - plane["prev"][i] if c.delta else raw
        xq = max(-_IN_CLAMP, min(_IN_CLAMP, x))
        err = c.sense * (1000 * xq - c.target_milli)
        err = max(-ERR_CLAMP, min(ERR_CLAMP, err))
        filt = host_ewma_filter(plane["filt"][i], err, c.alpha_milli)
        if c.kind == AIMD:
            sp = host_aimd_step(plane["setpoint"][i], filt > 0, add=c.add,
                                mult_milli=c.mult_milli, lo=c.lo, hi=c.hi)
        else:
            sp = host_additive_step(plane["setpoint"][i], filt,
                                    step=c.step,
                                    deadband_milli=c.deadband_milli,
                                    lo=c.lo, hi=c.hi)
        if plane["override_on"][i]:
            sp = plane["override"][i]
        out["setpoint"][i] = sp
        out["filt"][i] = filt
        out["prev"][i] = raw
    return out


# ------------------------------------------------------------ integration

def attach_plane(world, spec: ControlSpec):
    """Install a fresh ControlPlane into ``World.aux``.

    Raises if aux is occupied — the fault-exploration harnesses
    (verify/faults, verify/model_checker) own aux when active, and the
    two uses are mutually exclusive by design.
    """
    if world.aux is not None:
        raise ValueError(
            "World.aux is occupied (fault-exploration schedule?); the "
            "control plane needs exclusive ownership of aux")
    return world.replace(aux=spec.init_plane())


def validate_control(spec: ControlSpec, known_metrics, known_actuators,
                     *, where: str) -> None:
    """Build-time check: every loop reads a real metric and drives a
    real actuator.  Raised at trace time with named detail."""
    known_metrics = set(known_metrics)
    known_actuators = set(known_actuators)
    for c in spec.controllers:
        if c.metric not in known_metrics:
            raise ValueError(
                f"{where}: controller {c.name!r} reads unknown metric "
                f"{c.metric!r}; available: {sorted(known_metrics)}")
        if c.actuator and c.actuator not in known_actuators:
            raise ValueError(
                f"{where}: controller {c.name!r} drives unknown actuator "
                f"{c.actuator!r}; available: {sorted(known_actuators)}")


def control_specs(spec: ControlSpec):
    """MetricSpec gauges for the telemetry ring / PrometheusSink."""
    from ..telemetry.registry import GAUGE, MetricSpec
    out = []
    for c in spec.controllers:
        out.append(MetricSpec(
            f"ctl_{c.name}__setpoint", GAUGE,
            f"Controller {c.name}: current setpoint ({c.actuator or 'observe-only'})."))
        out.append(MetricSpec(
            f"ctl_{c.name}__err_milli", GAUGE,
            f"Controller {c.name}: EWMA-filtered error (milli-units)."))
    return tuple(out)


# --------------------------------------------------- host knob overrides

def set_knob(plane: ControlPlane, spec: ControlSpec, name: str,
             value: int) -> ControlPlane:
    """Pin controller ``name`` to ``value`` (the partisan_config:set/2
    analog).  Host-side; apply at a window boundary."""
    i = spec.index(name)  # named ValueError on unknown knob
    return plane.replace(
        setpoint=plane.setpoint.at[i].set(jnp.int32(value)),
        override=plane.override.at[i].set(jnp.int32(value)),
        override_on=plane.override_on.at[i].set(True))


def clear_knob(plane: ControlPlane, spec: ControlSpec,
               name: str) -> ControlPlane:
    """Release a pinned knob; the loop resumes from the pinned value."""
    i = spec.index(name)
    return plane.replace(override_on=plane.override_on.at[i].set(False))
