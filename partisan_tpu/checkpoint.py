"""Checkpoint / resume (SURVEY §5.4).

The reference persists scraps of state to disk per node — HyParView's epoch
counter (hyparview :1175-1227), the full-membership OR-set
(full :147-199 under ``persist_state``), the causality backend's ETS
snapshot (causality :261-263).  The TPU rebuild's checkpoint is *total and
cheap* by comparison: one device->host transfer of the whole World pytree
(views, clocks, epochs, in-flight messages, PRNG keys, fault masks), saved
as an ``.npz`` + a JSON manifest of the Config.  Resume = load + re-shard
(``parallel.place_world``) — a restarted cluster continues bit-identically,
which the reference cannot do.

Orbax is available in the image for production multi-host checkpointing;
this module deliberately sticks to numpy files so checkpoints stay
greppable and dependency-free.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .config import Config
from .engine import World

_MANIFEST = "manifest.json"
_ARRAYS = "world.npz"


def _flatten(world: World) -> Tuple[Dict[str, np.ndarray], Any]:
    leaves, treedef = jax.tree_util.tree_flatten(world)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    return arrays, treedef


def save(path: str, cfg: Config, world: World,
         extra: Optional[Dict[str, Any]] = None) -> None:
    """Write a complete checkpoint directory (atomic via rename)."""
    tmp = path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    arrays, _ = _flatten(jax.device_get(world))
    np.savez_compressed(os.path.join(tmp, _ARRAYS), **arrays)
    manifest = {
        "config": dataclasses.asdict(cfg),
        "round": int(world.rnd),
        "extra": extra or {},
    }
    with open(os.path.join(tmp, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=2, default=str)
    if os.path.exists(path):
        import shutil
        shutil.rmtree(path)
    os.replace(tmp, path)


def load(path: str, template: World) -> Tuple[World, Dict[str, Any]]:
    """Restore a checkpoint into the structure of ``template`` (build it
    with ``init_world(cfg, proto)`` for the same Config/protocol).  Returns
    (world, manifest)."""
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    data = np.load(os.path.join(path, _ARRAYS))
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if len(leaves) != len(data.files):
        raise ValueError(
            f"checkpoint has {len(data.files)} leaves, template has "
            f"{len(leaves)} — protocol/config mismatch")
    restored = [data[f"leaf_{i}"] for i in range(len(leaves))]
    world = jax.tree_util.tree_unflatten(treedef, restored)
    return world, manifest


def load_config(path: str) -> Config:
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    raw = dict(manifest["config"])
    # tuples serialize as lists
    for k, v in raw.items():
        if isinstance(v, list):
            raw[k] = tuple(v)
    return Config(**raw)
