"""Checkpoint / resume (SURVEY §5.4).

The reference persists scraps of state to disk per node — HyParView's epoch
counter (hyparview :1175-1227), the full-membership OR-set
(full :147-199 under ``persist_state``), the causality backend's ETS
snapshot (causality :261-263).  The TPU rebuild's checkpoint is *total and
cheap* by comparison: one device->host transfer of the whole World pytree
(views, clocks, epochs, in-flight messages, PRNG keys, fault masks), saved
as an ``.npz`` + a JSON manifest of the Config.  Resume = load + re-shard —
a restarted cluster continues bit-identically, which the reference cannot
do.

Shard-awareness (ISSUE 4 satellite): ``save`` device-gets a world whose
leaves live sharded across the mesh (``jax.device_get`` assembles the
addressable shards into full host arrays), ``load`` validates every leaf
against the template — named shape/dtype mismatches raise a clear error
pointing at the likely config/protocol drift instead of a downstream
reshape crash — and :func:`load_sharded` restores straight through
``parallel.dataplane.place_sharded_world`` so a long chaos soak
(scripts/chaos_soak.py) crash-resumes onto the mesh mid-campaign.

Orbax is available in the image for production multi-host checkpointing;
this module deliberately sticks to numpy files so checkpoints stay
greppable and dependency-free.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .config import Config
from .engine import World

_MANIFEST = "manifest.json"
_ARRAYS = "world.npz"


def _leaf_names(world: World) -> list:
    """Human-readable leaf paths (``state.active``, ``msgs.valid`` ...)
    for error messages; falls back to indices if path flattening is
    unavailable for a custom pytree."""
    try:
        paths, _ = jax.tree_util.tree_flatten_with_path(world)
        return [jax.tree_util.keystr(p) for p, _x in paths]
    except Exception:  # noqa: BLE001 — names are a diagnostic nicety
        return [f"leaf_{i}"
                for i in range(len(jax.tree_util.tree_leaves(world)))]


def _flatten(world: World) -> Tuple[Dict[str, np.ndarray], Any]:
    leaves, treedef = jax.tree_util.tree_flatten(world)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    return arrays, treedef


def save(path: str, cfg: Config, world: World,
         extra: Optional[Dict[str, Any]] = None,
         proto: Optional[Any] = None) -> None:
    """Write a complete checkpoint directory (atomic via rename).

    Works unchanged for worlds placed on a mesh (``place_world`` /
    ``place_sharded_world``): ``jax.device_get`` gathers each leaf's
    addressable shards into one host array.  ``proto`` (the protocol
    instance or its class name) is recorded in the manifest so ``load``
    can refuse a cross-protocol restore by name instead of by shape
    accident."""
    tmp = path + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    arrays, _ = _flatten(jax.device_get(world))
    np.savez_compressed(os.path.join(tmp, _ARRAYS), **arrays)
    manifest = {
        "config": dataclasses.asdict(cfg),
        "round": int(world.rnd),
        "proto": (proto if isinstance(proto, (str, type(None)))
                  else type(proto).__name__),
        "leaves": _leaf_names(world),
        "extra": extra or {},
    }
    with open(os.path.join(tmp, _MANIFEST), "w") as f:
        json.dump(manifest, f, indent=2, default=str)
    if os.path.exists(path):
        import shutil
        shutil.rmtree(path)
    os.replace(tmp, path)


def load(path: str, template: World, cfg: Optional[Config] = None,
         proto: Optional[Any] = None) -> Tuple[World, Dict[str, Any]]:
    """Restore a checkpoint into the structure of ``template`` (build it
    with ``init_world(cfg, proto)`` for the same Config/protocol).
    Returns (world, manifest).

    Validation (clear errors, not reshape crashes):

      * ``cfg`` given -> its ``n_nodes`` must match the manifest's (the
        most common mismatch: resuming a soak with the wrong N);
      * ``proto`` given (instance or class name) -> must match the
        recorded protocol name when the manifest has one;
      * every leaf's shape AND dtype must match the template's, reported
        by leaf path name with the likely cause.
    """
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    if cfg is not None:
        saved_n = manifest.get("config", {}).get("n_nodes")
        if saved_n is not None and int(saved_n) != cfg.n_nodes:
            raise ValueError(
                f"checkpoint was saved at n_nodes={saved_n}, loading "
                f"config has n_nodes={cfg.n_nodes} — rebuild the "
                f"template with the checkpoint's config "
                f"(checkpoint.load_config({path!r}))")
    if proto is not None:
        want = proto if isinstance(proto, str) else type(proto).__name__
        saved_proto = manifest.get("proto")
        if saved_proto is not None and saved_proto != want:
            raise ValueError(
                f"checkpoint holds {saved_proto} state, template "
                f"protocol is {want} — cross-protocol restore refused")
    data = np.load(os.path.join(path, _ARRAYS))
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if len(leaves) != len(data.files):
        raise ValueError(
            f"checkpoint has {len(data.files)} leaves, template has "
            f"{len(leaves)} — protocol/config mismatch")
    names = manifest.get("leaves") or _leaf_names(template)
    restored = []
    for i, tmpl in enumerate(leaves):
        arr = data[f"leaf_{i}"]
        tshape = tuple(getattr(tmpl, "shape", ()))
        tdtype = np.dtype(getattr(tmpl, "dtype", arr.dtype))
        name = names[i] if i < len(names) else f"leaf_{i}"
        if tuple(arr.shape) != tshape or np.dtype(arr.dtype) != tdtype:
            raise ValueError(
                f"checkpoint leaf {name}: saved {arr.shape} "
                f"{np.dtype(arr.dtype).name} vs template {tshape} "
                f"{tdtype.name} — n_nodes / protocol / buffer-capacity "
                f"mismatch between save and restore configs")
        restored.append(arr)
    world = jax.tree_util.tree_unflatten(treedef, restored)
    return world, manifest


def load_sharded(path: str, cfg: Config, proto: Any, mesh,
                 out_cap: Optional[int] = None,
                 control: Optional[Any] = None
                 ) -> Tuple[World, Dict[str, Any]]:
    """Restore a checkpoint straight onto the explicit dataplane: builds
    the template with the mesh-rounded buffer capacity
    (``sharded_out_cap``), validates, then re-packs the message buffer
    to the shard-residency invariant and device_puts every leaf with
    its node sharding (``place_sharded_world``).  The crash-resume path
    of long chaos soaks — the restored world continues bit-identically
    under ``make_sharded_step``.

    Note: the checkpoint must have been saved from a world built with
    the SAME rounded capacity (``init_sharded_world`` or
    ``init_world(out_cap=sharded_out_cap(...))``); a plain unsharded
    capacity shows up as a clear ``msgs`` leaf-shape error.

    ``control`` (a :class:`control.plane.ControlSpec`) declares that the
    checkpoint carries an ISSUE-10 ControlPlane in ``World.aux``: the
    template gets a fresh plane attached so the saved controller state
    validates leaf-by-leaf (named ``.aux`` shape/dtype errors on spec
    drift) and restores REPLICATED across the mesh (``place_world``'s
    aux special-case) — kill-and-resume continues the controller
    trajectory bit-identically."""
    from .engine import init_world
    from .parallel.dataplane import place_sharded_world, sharded_out_cap
    D = int(mesh.devices.size)
    template = init_world(
        cfg, proto, out_cap=sharded_out_cap(cfg, proto, D, out_cap))
    if control is not None:
        from .control.plane import attach_plane
        template = attach_plane(template, control)
    world, manifest = load(path, template, cfg=cfg, proto=proto)
    return place_sharded_world(world, cfg, mesh), manifest


def load_extra(path: str) -> Dict[str, Any]:
    """The manifest's harness-owned ``extra`` dict alone — campaign
    runners (scripts/chaos_soak.py --resume) stash completed-cell
    bookkeeping there and read it back without touching the arrays."""
    with open(os.path.join(path, _MANIFEST)) as f:
        return json.load(f).get("extra", {}) or {}


def load_config(path: str) -> Config:
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    raw = dict(manifest["config"])
    # tuples serialize as lists
    for k, v in raw.items():
        if isinstance(v, list):
            raw[k] = tuple(v)
    return Config(**raw)
