"""The compiled traffic generator: an :class:`qos.rpc.Rpc` subclass
whose TICK is the load driver — every node is simultaneously an RPC
client (arrival process + admission control + promise ring) and server
(the inherited ``lax.switch`` function table), so offered load scales
with N exactly like the serving fabric under test.

Per-node tick pipeline (all shard-local arithmetic — the sharded
dataplane's 2-collective budget holds with the workload plane on):

  1. retransmit: age the promise ring through the QoS exponential-
     backoff timer (qos/ack.retransmit_backoff, Config retransmit_*
     knobs); due slots re-emit their ``rpc_req`` (counted wl_retries),
     slots past the give-up threshold are dead-lettered — freed and
     counted (wl_dead_lettered), never retried silently.  Retransmitted
     requests keep their ORIGINAL birth round (the promise ring, not the
     wire, carries the birth), so retries lengthen — never reset — the
     measured latency.
  2. arrivals: the :class:`workload.arrivals.ArrivalSpec` decides how
     many of the ``A`` issue slots want to fire (open-loop thinning at
     ``wl_rate_milli`` — a STATE column, so one compiled step serves a
     whole offered-load sweep — or closed-loop outstanding top-up).
  3. admission (workload/shed.py): token bucket + outstanding cap when
     the Config shed knobs engage; refusals count wl_shed.
  4. issue: admitted slots allocate a promise (birth = current round),
     pick a destination (uniform or Zipf), and emit ``rpc_req``; ring-
     full losses count call_dropped exactly like ctl-injected calls.

Completion latency is recorded by the inherited ``handle_rpc_reply``
(qos/rpc.py): total completions = sum of the histogram, so there is no
separate wl_completed counter to drift out of sync.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..ops import ring
from ..qos import ack
from ..qos.rpc import Rpc
from . import arrivals as arr
from . import latency, shed


@struct.dataclass
class WlRow:
    """Superset of RpcRow's field names (the inherited Rpc handlers
    ``row.replace(...)`` only fields they know, so they run unchanged on
    this row) plus the driver's issue/retransmit/shed state."""
    # --- RpcRow fields (qos/rpc.py) ---
    next_ref: jax.Array
    prom_valid: jax.Array
    prom_ref: jax.Array
    prom_result: jax.Array
    prom_done: jax.Array
    call_dropped: jax.Array
    prom_birth: jax.Array
    lat_hist: jax.Array
    lat_sum: jax.Array
    slo_ok: jax.Array
    slo_violated: jax.Array
    # --- retransmission state (echo of the acked layers' ring) ---
    prom_dst: jax.Array      # [P] where the request went
    prom_fn: jax.Array       # [P]
    prom_arg: jax.Array      # [P]
    prom_age: jax.Array      # [P]
    prom_attempt: jax.Array  # [P]
    # --- driver state ---
    wl_rate_milli: jax.Array    # scalar — offered rate (state => sweepable)
    wl_tokens_milli: jax.Array  # scalar — shed token bucket
    wl_issued: jax.Array        # scalar — admitted AND ring-allocated
    wl_shed: jax.Array          # scalar — refused at admission
    wl_retries: jax.Array       # scalar — rpc_req retransmissions
    wl_dead_lettered: jax.Array  # scalar — promises abandoned at give-up


class WorkloadRpc(Rpc):
    """RPC + compiled load generator (ISSUE 8 tentpole).

    ``spec`` fixes the arrival process shape at trace time;
    ``rate_milli`` seeds the per-node offered rate (milli-requests per
    round, mutable in state via :meth:`set_rate`).  Works standalone
    over full mesh routing or stacked over a membership layer via
    ``models.stack.Lifted`` — destinations are node ids, which the
    engine routes point-to-point either way.
    """

    def __init__(self, cfg: Config,
                 fns: Sequence[Callable[[jax.Array], jax.Array]] = (),
                 promise_cap: int = 16,
                 spec: arr.ArrivalSpec = arr.ArrivalSpec(),
                 rate_milli: int = 1000):
        super().__init__(cfg, fns, promise_cap)
        self.spec = spec.validate()
        self.A = spec.max_issue
        self.rate_milli = int(rate_milli)
        self.tick_emit_cap = self.P + self.A
        # issue burst + per-promise retransmit pressure
        self.autotune_emit_hint = 2 * (self.P + self.A)
        self.round_counter_names = (
            "wl_issued", "wl_shed", "wl_retries", "wl_dead_lettered",
            "wl_outstanding", "rpc_call_dropped", "rpc_slo_ok",
            "rpc_slo_violated") + latency.family_names("rpc_latency")

    def init(self, cfg: Config, key: jax.Array) -> WlRow:
        base = super().init(cfg, key)
        n, P = cfg.n_nodes, self.P
        # four DISTINCT buffers — reusing one array object for several
        # donated leaves trips XLA's double-donation check
        def z():
            return jnp.zeros((n,), jnp.int32)
        return WlRow(
            **{f: getattr(base, f) for f in (
                "next_ref", "prom_valid", "prom_ref", "prom_result",
                "prom_done", "call_dropped", "prom_birth", "lat_hist",
                "lat_sum", "slo_ok", "slo_violated")},
            prom_dst=jnp.full((n, P), -1, jnp.int32),
            prom_fn=jnp.zeros((n, P), jnp.int32),
            prom_arg=jnp.zeros((n, P), jnp.int32),
            prom_age=jnp.zeros((n, P), jnp.int32),
            prom_attempt=jnp.zeros((n, P), jnp.int32),
            wl_rate_milli=jnp.full((n,), self.rate_milli, jnp.int32),
            wl_tokens_milli=jnp.full(
                (n,), cfg.shed_token_burst_milli, jnp.int32),
            wl_issued=z(), wl_shed=z(), wl_retries=z(),
            wl_dead_lettered=z(),
        )

    # ------------------------------------------------------------- verbs

    def handle_ctl_call(self, cfg, me, row: WlRow, m, key):
        """Host-injected calls also arm the retransmit state (the base
        handler only parks the promise)."""
        ok0, slot = ring.alloc(row.prom_valid)
        dst = m.data["peer"]
        ok = ok0 & (dst >= 0)
        row, em = super().handle_ctl_call(cfg, me, row, m, key)
        wr = lambda a, v: ring.masked_set(a, slot, ok, v)
        row = row.replace(
            prom_dst=wr(row.prom_dst, dst),
            prom_fn=wr(row.prom_fn, m.data["fn"]),
            prom_arg=wr(row.prom_arg, m.data["arg"]),
            prom_age=wr(row.prom_age, 0),
            prom_attempt=wr(row.prom_attempt, 0))
        return row, em

    # -------------------------------------------------------------- tick

    # --- control-plane actuation hooks (ISSUE 10) --------------------------
    # The two knobs of the tick pipeline, factored out so an adaptive
    # subclass can read controller-driven state columns instead of the
    # static Config values.  The base implementations trace EXACTLY the
    # ops the inline code traced before the factoring — byte-identical
    # base programs (warm-cache contract).

    def _backoff_kw(self, cfg: Config, row: WlRow) -> Dict:
        """Keyword set for qos/ack.retransmit_backoff (step 1)."""
        return ack.backoff_kw(cfg)

    def _admit(self, cfg: Config, row: WlRow, want, outstanding):
        """Admission decision (step 3): ``(admitted [A] mask, row')``.
        Config knobs; rate 0 = bucket bypass."""
        use_shed = (cfg.shed_token_rate_milli > 0
                    or cfg.shed_max_outstanding > 0)
        if not use_shed:
            return want, row
        # trace-lint: allow(config-fork): token refill compiles in only when shedding is configured — shed-off keeps the lean program
        if cfg.shed_token_rate_milli > 0:
            tokens = shed.refill(row.wl_tokens_milli,
                                 cfg.shed_token_rate_milli,
                                 cfg.shed_token_burst_milli)
        else:
            tokens = jnp.int32(1000 * self.A)  # never the binding limit
        adm, tokens_out, shed_n = shed.admit(
            tokens, want, outstanding, cfg.shed_max_outstanding)
        # trace-lint: allow(config-fork): same build-time shed gate as the refill above — token column untouched when shedding is off
        if cfg.shed_token_rate_milli > 0:
            row = row.replace(wl_tokens_milli=tokens_out)
        return adm, row.replace(wl_shed=row.wl_shed + shed_n)

    def tick(self, cfg, me, row: WlRow, rnd, key):
        P, A = self.P, self.A
        # 1. retransmit / dead-letter over the promise ring
        valid, age, attempt, due, dead = ack.retransmit_backoff(
            row.prom_valid, row.prom_age, row.prom_attempt, me,
            **self._backoff_kw(cfg, row))
        re_em = self.emit(
            jnp.where(due, row.prom_dst, -1), self.typ("rpc_req"),
            cap=P, ref=row.prom_ref, fn=row.prom_fn, arg=row.prom_arg)
        row = row.replace(
            prom_valid=valid, prom_age=age, prom_attempt=attempt,
            wl_retries=row.wl_retries
            + jnp.sum(due.astype(jnp.int32)),
            wl_dead_lettered=row.wl_dead_lettered + dead)

        # 2. arrivals
        k_issue, k_dst = jax.random.split(key)
        outstanding = jnp.sum(row.prom_valid.astype(jnp.int32))
        want = arr.issue_mask(self.spec, row.wl_rate_milli, rnd,
                              outstanding, k_issue)

        # 3. admission control (hook: static Config knobs on the base
        #    class, controller-driven state on AdaptiveWorkloadRpc)
        adm, row = self._admit(cfg, row, want, outstanding)

        # 4. issue admitted slots (static unroll over A; sequential refs)
        dsts = arr.pick_dsts(self.spec, me, cfg.n_nodes, k_dst)
        pv, pref, pdone, pbirth = (row.prom_valid, row.prom_ref,
                                   row.prom_done, row.prom_birth)
        pdst, pfn, parg = row.prom_dst, row.prom_fn, row.prom_arg
        page, patt = row.prom_age, row.prom_attempt
        ref0 = row.next_ref
        out_dst, out_ref = [], []
        issued = jnp.int32(0)
        dropped = jnp.int32(0)
        # trace-lint: allow(unroll-bomb): A is the small static arrival slot cap and each iteration's ring.alloc depends on the previous write — the audited, intentional unroll (ISSUE 11)
        for i in range(A):
            ok, slot = ring.alloc(pv)
            ok = ok & adm[i]
            wr = lambda a, v: ring.masked_set(a, slot, ok, v)
            ref_i = ref0 + i
            pv = wr(pv, True)
            pref = wr(pref, ref_i)
            pdone = wr(pdone, False)
            pbirth = wr(pbirth, rnd)
            pdst = wr(pdst, dsts[i])
            pfn = wr(pfn, 0)
            parg = wr(parg, rnd)
            page = wr(page, 0)
            patt = wr(patt, 0)
            out_dst.append(jnp.where(ok, dsts[i], -1))
            out_ref.append(ref_i)
            issued = issued + ok.astype(jnp.int32)
            dropped = dropped + (adm[i] & ~ok).astype(jnp.int32)
        # arg = birth round: the server's identity fn echoes it back, so
        # a host observer can recompute every latency sample from the
        # reply wire alone (the parity test's ground truth).
        issue_em = self.emit(
            jnp.stack(out_dst), self.typ("rpc_req"), cap=A,
            ref=jnp.stack(out_ref), fn=0, arg=rnd)
        row = row.replace(
            next_ref=ref0 + A,
            prom_valid=pv, prom_ref=pref, prom_done=pdone,
            prom_birth=pbirth, prom_dst=pdst, prom_fn=pfn,
            prom_arg=parg, prom_age=page, prom_attempt=patt,
            wl_issued=row.wl_issued + issued,
            call_dropped=row.call_dropped + dropped)
        return row, self.merge(re_em, issue_em, cap=self.tick_emit_cap)

    # ----------------------------------------------------------- metrics

    def health_counters(self, state: WlRow):
        out = dict(super().health_counters(state))
        out.update(self._wl_counters(state))
        return out

    def _wl_counters(self, state: WlRow) -> Dict[str, jax.Array]:
        return {
            "wl_issued": jnp.sum(state.wl_issued),
            "wl_shed": jnp.sum(state.wl_shed),
            "wl_retries": jnp.sum(state.wl_retries),
            "wl_dead_lettered": jnp.sum(state.wl_dead_lettered),
            "wl_outstanding": jnp.sum(
                state.prom_valid.astype(jnp.int32)),
        }

    def round_counters(self, state: WlRow) -> Dict[str, jax.Array]:
        """In-scan per-round tap (engine metrics / the dataplane's
        stacked psum): same names as health_counters, shard-local sums
        of cumulative per-node counters."""
        return dict(self.health_counters(state))

    def trace_taps(self, cfg, pre, mid, post, rnd):
        """Lifecycle-tracer taps (ISSUE 16) over the promise-ring
        diffs.  Pair with ``TraceSpec(seq_field="ref")`` so request
        wire spans and these client-side transitions share the
        ``(src, ref)`` trace id.

        * ``acked`` — the promise completed this round (a reply flipped
          ``prom_done`` in the deliver phase);
        * ``retransmitted`` — tick re-armed the slot (same ref, bumped
          attempt);
        * ``dead_lettered`` — tick abandoned the slot (freed outright,
          or reused under a NEW ref by the issue unroll in the same
          tick — refs are monotone, so a ref change marks the old
          promise dead);
        * ``shed`` — admission control refused this many arrivals
          (``wl_shed`` delta), a count event with no peer identity."""
        req = self.typ("rpc_req")
        acked = mid.prom_done & ~pre.prom_done
        retrans = (mid.prom_valid & post.prom_valid
                   & (post.prom_ref == mid.prom_ref)
                   & (post.prom_attempt > mid.prom_attempt))
        dead = mid.prom_valid & (~post.prom_valid
                                 | (post.prom_ref != mid.prom_ref))
        shed_n = (post.wl_shed - mid.wl_shed)[:, None]
        shed_keep = jnp.arange(self.A, dtype=jnp.int32)[None, :] < shed_n
        return (
            ("acked", dict(keep=acked, dst=pre.prom_dst, typ=req,
                           seq=pre.prom_ref, born=pre.prom_birth)),
            ("retransmitted", dict(keep=retrans, dst=post.prom_dst,
                                   typ=req, seq=post.prom_ref,
                                   born=post.prom_birth)),
            ("dead_lettered", dict(keep=dead, dst=mid.prom_dst, typ=req,
                                   seq=mid.prom_ref,
                                   born=mid.prom_birth)),
            ("shed", dict(keep=shed_keep, typ=req, born=rnd)),
        )

    # ------------------------------------------------------ host helpers

    def set_rate(self, state: WlRow, rate_milli: int) -> WlRow:
        """Rewrite the offered rate IN STATE — no recompile: the sweep
        reuses one compiled scan across every load point."""
        return state.replace(wl_rate_milli=jnp.full_like(
            state.wl_rate_milli, jnp.int32(rate_milli)))

    def reset_stats(self, state: WlRow, burst_milli: int) -> WlRow:
        """Zero the measurement plane (histogram + counters) between
        sweep points; the promise ring and refs carry over, so back-to-
        back windows measure steady state, not cold start."""
        z = jnp.zeros_like(state.wl_issued)
        return state.replace(
            lat_hist=jnp.zeros_like(state.lat_hist),
            lat_sum=jnp.zeros_like(state.lat_sum),
            slo_ok=jnp.zeros_like(state.slo_ok),
            slo_violated=jnp.zeros_like(state.slo_violated),
            call_dropped=z, wl_issued=z, wl_shed=z, wl_retries=z,
            wl_dead_lettered=z,
            wl_tokens_milli=jnp.full_like(
                state.wl_tokens_milli, jnp.int32(burst_milli)))


# ===================== adaptive variant (ISSUE 10 control plane) ==========

@struct.dataclass
class AdaptiveWlRow(WlRow):
    """WlRow + the three controller-driven knob columns.  Per-node [n]
    copies of replicated setpoints: shard-local reads under the sharded
    dataplanes, no gathers."""
    wl_shed_rate_milli: jax.Array   # [n] token refill rate (milli/round)
    wl_max_outstanding: jax.Array   # [n] promise-depth cap (<=0 = off)
    wl_retransmit_base: jax.Array   # [n] backoff base interval (rounds)


class AdaptiveWorkloadRpc(WorkloadRpc):
    """WorkloadRpc whose admission + retransmit knobs are STATE the
    control plane moves every round (the PR-8 ``wl_rate_milli``-as-state
    pattern, now closed-loop).

    Actuators:
      ``wl.shed_rate_milli``   token-bucket refill rate; <= 0 bypasses
                               the bucket (base-class semantics).
      ``wl.max_outstanding``   promise-depth cap; <= 0 disables.
      ``wl.retransmit_base``   retransmit base interval, clamped >= 1.

    Seeds come from the Config shed/retransmit knobs unless overridden;
    with no controller attached the knobs simply hold their seeds, so
    the adaptive build is a superset, not a behavior fork.
    """

    actuator_names = ("wl.shed_rate_milli", "wl.max_outstanding",
                      "wl.retransmit_base")

    def __init__(self, cfg: Config,
                 fns: Sequence[Callable[[jax.Array], jax.Array]] = (),
                 promise_cap: int = 16,
                 spec: arr.ArrivalSpec = arr.ArrivalSpec(),
                 rate_milli: int = 1000,
                 shed_rate_milli: int | None = None,
                 max_outstanding: int | None = None,
                 retransmit_base: int | None = None):
        super().__init__(cfg, fns, promise_cap, spec, rate_milli)
        self.shed_rate_milli0 = int(
            cfg.shed_token_rate_milli if shed_rate_milli is None
            else shed_rate_milli)
        self.max_outstanding0 = int(
            cfg.shed_max_outstanding if max_outstanding is None
            else max_outstanding)
        self.retransmit_base0 = int(
            cfg.retransmit_interval if retransmit_base is None
            else retransmit_base)

    def init(self, cfg: Config, key: jax.Array) -> AdaptiveWlRow:
        base = super().init(cfg, key)
        n = cfg.n_nodes
        return AdaptiveWlRow(
            **{f.name: getattr(base, f.name)
               for f in dataclasses.fields(WlRow)},
            wl_shed_rate_milli=jnp.full(
                (n,), self.shed_rate_milli0, jnp.int32),
            wl_max_outstanding=jnp.full(
                (n,), self.max_outstanding0, jnp.int32),
            wl_retransmit_base=jnp.full(
                (n,), self.retransmit_base0, jnp.int32))

    # ------------------------------------------------- actuation hooks

    def _backoff_kw(self, cfg: Config, row: AdaptiveWlRow) -> Dict:
        # per-node scalar under the engine's tick vmap; the existing
        # exponential-backoff math accepts a traced base unchanged
        return ack.backoff_kw(
            cfg, base=jnp.maximum(row.wl_retransmit_base, 1))

    def _admit(self, cfg: Config, row: AdaptiveWlRow, want, outstanding):
        rate = row.wl_shed_rate_milli
        filled = shed.refill(row.wl_tokens_milli, jnp.maximum(rate, 0),
                             cfg.shed_token_burst_milli)
        # rate <= 0 keeps the base class's bucket-bypass semantics,
        # data-dependently: unlimited effective tokens, bucket level
        # frozen at the refilled value
        tokens = jnp.where(rate > 0, filled, jnp.int32(1000 * self.A))
        adm, tokens_out, shed_n = shed.admit_dynamic(
            tokens, want, outstanding, row.wl_max_outstanding)
        return adm, row.replace(
            wl_tokens_milli=jnp.where(rate > 0, tokens_out, filled),
            wl_shed=row.wl_shed + shed_n)

    # ---------------------------------------------- setpoint absorption

    def apply_setpoints(self, cfg: Config, state: AdaptiveWlRow, values):
        def bcast(col, name):
            if name not in values:
                return col
            return jnp.full_like(col, jnp.asarray(values[name], jnp.int32))
        return state.replace(
            wl_shed_rate_milli=bcast(state.wl_shed_rate_milli,
                                     "wl.shed_rate_milli"),
            wl_max_outstanding=bcast(state.wl_max_outstanding,
                                     "wl.max_outstanding"),
            wl_retransmit_base=bcast(state.wl_retransmit_base,
                                     "wl.retransmit_base"))
