"""Device-side workload plane (ISSUE 8): compiled traffic generators,
in-scan latency histograms, and SLO-driven load shedding.

Submodules:

* :mod:`.arrivals` — per-node arrival processes (Poisson thinning,
  on/off bursts, diurnal ramp, Zipf destinations, closed loop).
* :mod:`.latency` — log2-bucketed latency histograms carried in scan
  state, host folds to p50/p95/p99 + SLO counts.
* :mod:`.shed` — token-bucket + queue-depth admission control.
* :mod:`.driver` — :class:`WorkloadRpc`, the Rpc subclass whose tick IS
  the load generator (imported lazily: driver depends on qos.rpc, which
  itself imports :mod:`.latency` — a top-level import here would cycle).
"""

from . import arrivals, latency, shed  # noqa: F401

__all__ = ["arrivals", "latency", "shed", "driver", "WorkloadRpc",
           "WlRow"]


def __getattr__(name):  # PEP 562 lazy loader: break the qos.rpc cycle
    if name in ("driver", "WorkloadRpc", "WlRow"):
        from . import driver as _driver
        if name == "driver":
            return _driver
        return getattr(_driver, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
