"""Compiled per-node arrival processes for the workload plane.

All generators are round-synchronous and per-node: each node's driver
tick asks "how many new requests do I issue this round?" and gets back a
``[A]`` boolean issue mask over its ``A`` issue slots (``A`` =
``ArrivalSpec.max_issue``).  Everything is lax-friendly — the spec is a
frozen Python dataclass baked into the trace, only ``rnd`` and the PRNG
key are traced values — so one compiled step serves a whole sweep when
the offered rate itself is carried in state (see
:class:`workload.driver.WorkloadRpc`, whose ``wl_rate_milli`` state
column scales these processes without recompiling).

Rates are expressed in MILLI-requests per round per node (int32), the
repo's idiom for sub-unit rates under integer-only device arithmetic:
open-loop kinds realize ``rate_milli`` by binomial thinning — each of
the ``A`` slots fires with probability ``eff_milli / (1000 * A)`` via a
uniform draw on the repo PRNG — so the expected issue count per round is
``eff_milli / 1000`` for any ``eff_milli <= 1000 * A``.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Arrival kinds (static Python ints baked into the trace).
POISSON = 0   # open loop, constant rate
ONOFF = 1     # open loop, bursty: rate scaled up during ON windows, 0 OFF
DIURNAL = 2   # open loop, triangle-wave ramp with a fixed period
CLOSED = 3    # closed loop: keep `closed_target` requests outstanding


@dataclasses.dataclass(frozen=True)
class ArrivalSpec:
    """Static description of one arrival process (trace-baked)."""
    kind: int = POISSON
    # Issue slots per node per round; also the open-loop thinning width
    # and the hard per-round issue cap.
    max_issue: int = 4
    # ON/OFF burst shape (ONOFF kind): cycle = on_rounds + off_rounds,
    # ON windows carry burst_milli_scale x the base rate (milli scale,
    # 1000 = 1x), OFF windows are silent.
    on_rounds: int = 8
    off_rounds: int = 24
    burst_milli_scale: int = 4000
    # DIURNAL: triangle wave over `diurnal_period` rounds, scaling the
    # base rate from 0 up to 2x and back (mean = base rate).
    diurnal_period: int = 64
    # Zipf destination skew (milli exponent s; 0 = uniform).  Applied in
    # pick_dsts via an inverse-CDF table baked at build time.
    zipf_milli_s: int = 0
    # CLOSED: outstanding requests each client keeps in flight.
    closed_target: int = 1

    def validate(self) -> "ArrivalSpec":
        if self.kind not in (POISSON, ONOFF, DIURNAL, CLOSED):
            raise ValueError(f"unknown arrival kind {self.kind}")
        if self.max_issue < 1:
            raise ValueError("max_issue must be >= 1")
        if self.kind == ONOFF and self.on_rounds + self.off_rounds < 1:
            raise ValueError("on_rounds + off_rounds must be >= 1")
        if self.kind == DIURNAL and self.diurnal_period < 2:
            raise ValueError("diurnal_period must be >= 2")
        if self.kind == CLOSED and not (
                1 <= self.closed_target <= self.max_issue):
            raise ValueError("closed_target must be in [1, max_issue]")
        return self


def rate_scale_milli(spec: ArrivalSpec, rnd: jax.Array) -> jax.Array:
    """Round-dependent rate multiplier (milli, 1000 = 1x) for the
    open-loop kinds; CLOSED ignores it."""
    rnd = jnp.asarray(rnd, jnp.int32)
    if spec.kind == ONOFF:
        cycle = spec.on_rounds + spec.off_rounds
        on = (rnd % cycle) < spec.on_rounds
        return jnp.where(on, jnp.int32(spec.burst_milli_scale),
                         jnp.int32(0))
    if spec.kind == DIURNAL:
        p = spec.diurnal_period
        ph = rnd % p
        # triangle 0 -> 2000 -> 0 (mean 1000): rises over the first half.
        half = p // 2
        up = (2000 * ph) // half
        down = 2000 - (2000 * (ph - half)) // max(p - half, 1)
        return jnp.where(ph < half, up, down).astype(jnp.int32)
    return jnp.int32(1000)


def issue_mask(spec: ArrivalSpec, rate_milli: jax.Array, rnd: jax.Array,
               outstanding: jax.Array, key: jax.Array) -> jax.Array:
    """``[A]`` bool: which issue slots fire this round for one node.

    Open loop: each slot independently fires with probability
    ``eff_milli / (1000 * A)`` (binomial thinning; ``eff_milli`` is the
    base rate scaled by :func:`rate_scale_milli` and clipped to the
    ``1000 * A`` realizable ceiling).  Closed loop: the first
    ``clip(closed_target - outstanding, 0, A)`` slots fire — the next
    call is issued as soon as a reply (or drop) frees a slot.
    """
    a = spec.max_issue
    if spec.kind == CLOSED:
        want = jnp.clip(jnp.int32(spec.closed_target)
                        - jnp.asarray(outstanding, jnp.int32), 0, a)
        return jnp.arange(a, dtype=jnp.int32) < want
    eff = (jnp.asarray(rate_milli, jnp.int32)
           * rate_scale_milli(spec, rnd)) // 1000
    eff = jnp.clip(eff, 0, 1000 * a)
    draws = jax.random.randint(key, (a,), 0, 1000 * a, dtype=jnp.int32)
    return draws < eff


# ------------------------------------------------------- destinations

def zipf_cdf_milli(n: int, milli_s: int, table: int = 256) -> np.ndarray:
    """Quantized inverse-CDF table for Zipf(s) over ``n`` destinations:
    ``table`` int32 node ids such that a uniform draw over the table
    approximates the Zipf mass (host-built, baked into the trace).
    ``milli_s == 0`` degenerates to uniform striding."""
    if milli_s <= 0:
        return (np.arange(table, dtype=np.int64) * n // table).astype(
            np.int32)
    s = milli_s / 1000.0
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    cdf = np.cumsum(w) / w.sum()
    q = (np.arange(table, dtype=np.float64) + 0.5) / table
    return np.searchsorted(cdf, q, side="left").astype(np.int32)


def pick_dsts(spec: ArrivalSpec, me: jax.Array, n: int,
              key: jax.Array) -> jax.Array:
    """``[A]`` int32 destination ids — Zipf-skewed (or uniform) over the
    id space, with self remapped to the next node so a request always
    leaves the client."""
    a = spec.max_issue
    if spec.zipf_milli_s > 0:
        tbl = jnp.asarray(
            zipf_cdf_milli(n, spec.zipf_milli_s), jnp.int32)
        idx = jax.random.randint(key, (a,), 0, tbl.shape[0],
                                 dtype=jnp.int32)
        dst = tbl[idx]
    else:
        dst = jax.random.randint(key, (a,), 0, n, dtype=jnp.int32)
    me = jnp.asarray(me, jnp.int32)
    return jnp.where(dst == me, (dst + 1) % n, dst)


def expected_issue_per_round(spec: ArrivalSpec, rate_milli: int) -> float:
    """Host-side expectation of issues/round/node for open-loop kinds
    (mean over a full burst/ramp cycle), used by tests and the load
    suite's offered-load axis."""
    cap = 1000.0 * spec.max_issue
    if spec.kind == POISSON:
        return min(float(rate_milli), cap) / 1000.0
    if spec.kind == ONOFF:
        cyc = spec.on_rounds + spec.off_rounds
        on = min(rate_milli * spec.burst_milli_scale / 1000.0, cap)
        return on * spec.on_rounds / cyc / 1000.0
    if spec.kind == DIURNAL:
        # triangle has mean scale 1000 (approximately, up to integer
        # quantization) -> same mean as POISSON.
        return min(float(rate_milli), cap) / 1000.0
    raise ValueError("expected_issue_per_round is open-loop only")
