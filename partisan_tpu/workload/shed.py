"""SLO-driven admission control: per-node token bucket + queue-depth cap.

Overload in a round-synchronous gossip fabric shows up as inbox
saturation several hops from the client — by the time `inbox_overflow`
counts losses, latency has already blown past any deadline.  The shed
plane refuses work at ADMISSION instead: each node holds an integer
token bucket (milli-tokens, refilled `shed_token_rate_milli` per round,
capped at `shed_token_burst_milli`) and a promise-outstanding cap
(`shed_max_outstanding`).  A request the arrival process wants to issue
is admitted only if a full token is available AND the cap has room;
refusals increment `wl_shed` — shed work is COUNTED, never silent,
which is the graceful-degradation contract the load suite asserts
(p99 held within SLO past the knee, sheds visible in the bench rows).

Pure shard-local integer arithmetic — no collectives, so the sharded
dataplane's 2-collective budget is untouched with shedding enabled.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def refill(tokens_milli: jax.Array, rate_milli: int,
           burst_milli: int) -> jax.Array:
    """One round of token refill (saturating at the burst cap)."""
    return jnp.minimum(
        jnp.asarray(tokens_milli, jnp.int32) + jnp.int32(rate_milli),
        jnp.int32(burst_milli))


def admit(tokens_milli: jax.Array, want: jax.Array,
          outstanding: jax.Array, max_outstanding: int
          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Admission decision for one node's ``[A]`` wanted-issue mask.

    Returns ``(admitted [A] bool, tokens_milli', shed_count)``.  Slots
    are considered in order: slot ``i`` is admitted iff a full token
    (1000 milli) remains after funding the slots admitted before it
    and, when ``max_outstanding > 0``, the outstanding depth including
    those slots stays below the cap.  Tokens are only charged for
    ADMITTED slots (a depth-capped refusal does not burn a token).
    ``max_outstanding == 0`` disables the depth cap (Config default).
    ``A`` is small and static, so the sequential dependency unrolls —
    still pure per-node arithmetic under the engine's vmap.
    """
    want = jnp.asarray(want, bool)
    tokens = jnp.asarray(tokens_milli, jnp.int32)
    depth = jnp.asarray(outstanding, jnp.int32)
    shed = jnp.int32(0)
    oks = []
    # trace-lint: allow(unroll-bomb): A is small and static; token charge for slot i depends on slots < i (sequential by contract)
    for i in range(want.shape[0]):
        fits = want[i] & (tokens >= 1000)
        if max_outstanding > 0:
            fits = fits & (depth < jnp.int32(max_outstanding))
        oks.append(fits)
        tokens = tokens - jnp.where(fits, jnp.int32(1000), jnp.int32(0))
        depth = depth + fits.astype(jnp.int32)
        shed = shed + (want[i] & ~fits).astype(jnp.int32)
    return jnp.stack(oks), tokens, shed


def admit_dynamic(tokens_milli: jax.Array, want: jax.Array,
                  outstanding: jax.Array, max_outstanding: jax.Array
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`admit` with a TRACED outstanding cap (controller-driven).

    The static variant bakes the depth-cap comparison in or out of the
    program at build time; here the cap is per-node state a controller
    moves every round, so the comparison is always traced and
    ``cap <= 0`` disables the depth check data-dependently.  Same
    token-charging contract as :func:`admit`.
    """
    want = jnp.asarray(want, bool)
    tokens = jnp.asarray(tokens_milli, jnp.int32)
    depth = jnp.asarray(outstanding, jnp.int32)
    cap = jnp.asarray(max_outstanding, jnp.int32)
    shed = jnp.int32(0)
    oks = []
    # trace-lint: allow(unroll-bomb): same small static A and sequential token charge as admit, with the cap comparison traced
    for i in range(want.shape[0]):
        fits = want[i] & (tokens >= 1000) & ((cap <= 0) | (depth < cap))
        oks.append(fits)
        tokens = tokens - jnp.where(fits, jnp.int32(1000), jnp.int32(0))
        depth = depth + fits.astype(jnp.int32)
        shed = shed + (want[i] & ~fits).astype(jnp.int32)
    return jnp.stack(oks), tokens, shed


def host_admit_dynamic(tokens_milli: int, want, outstanding: int,
                       max_outstanding: int):
    """Plain-Python twin of :func:`admit_dynamic` — same contract as
    :func:`host_admit` (the cap is just a value here either way)."""
    return host_admit(tokens_milli, want, outstanding,
                      int(max_outstanding))


def host_admit(tokens_milli: int, want, outstanding: int,
               max_outstanding: int):
    """Plain-Python twin of :func:`admit` for conservation tests."""
    ok, toks, shed, depth = [], int(tokens_milli), 0, int(outstanding)
    for w in list(want):
        if not w:
            ok.append(False)
            continue
        fits = toks >= 1000 and (
            max_outstanding <= 0 or depth < max_outstanding)
        ok.append(fits)
        if fits:
            toks -= 1000
            depth += 1
        else:
            shed += 1
    return ok, toks, shed
