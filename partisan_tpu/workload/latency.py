"""Log2-bucketed request-latency histograms, carried in the scan state.

The device cannot afford per-request host transfers, so latency folds
into a fixed ``[K]`` bucket-counter vector per node (telemetry-ring
style: cumulative device counters, one host transfer per window, host
folds to quantiles).  Bucketing is INTEGER arithmetic only — bucket ``i``
holds latencies in ``(2^(i-1), 2^i]`` rounds (bucket 0: ``<= 1``; the
last bucket is the ``+Inf`` overflow) — so the device counts bit-match
:func:`host_bucket_index` exactly, which is what the parity test pins
(no float ``log2`` whose rounding could diverge between XLA and numpy).

Naming convention for the telemetry ring / Prometheus plane: a histogram
family ``fam`` occupies ``K + 1`` ring columns —
``fam__bucket_<bound>`` (per-bucket counts, bound = the bucket's
inclusive upper edge in rounds, ``inf`` for the overflow bucket) and
``fam__sum`` (sum of observed latencies).  The columns are CUMULATIVE
device counters and therefore export with GAUGE kind (the PR-4 rule:
a Prometheus sink accumulates COUNTER rows as deltas, which would
double-count a cumulative series); :class:`telemetry.sinks.
PrometheusSink` recognizes the ``__bucket_`` pattern and renders the
family as a native ``# TYPE ... histogram`` with cumulative ``le``
buckets plus ``_sum``/``_count``.
"""

from __future__ import annotations

import math
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry.registry import GAUGE, MetricSpec

# K buckets: upper edges 2^0 .. 2^(K-2) rounds, then +Inf.  2^14 = 16384
# rounds covers every soak horizon in the repo; anything slower is tail
# enough that "overflow" is the right answer.
N_BUCKETS = 16
BUCKET_EDGES: Tuple[int, ...] = tuple(2 ** i for i in range(N_BUCKETS - 1))


def bucket_label(i: int) -> str:
    """Stable name fragment for bucket ``i`` (its upper edge, in rounds)."""
    return str(BUCKET_EDGES[i]) if i < N_BUCKETS - 1 else "inf"


BUCKET_NAMES: Tuple[str, ...] = tuple(
    bucket_label(i) for i in range(N_BUCKETS))


# ----------------------------------------------------------------- device

def bucket_index(lat: jax.Array) -> jax.Array:
    """int32 bucket index for latency ``lat`` (rounds) — pure integer
    comparisons against the static edge table, jit/vmap-safe."""
    edges = jnp.asarray(BUCKET_EDGES, jnp.int32)
    lat = jnp.asarray(lat, jnp.int32)
    return jnp.sum(lat[..., None] > edges, axis=-1).astype(jnp.int32)


def observe(hist: jax.Array, lat_sum: jax.Array, lat: jax.Array,
            ok: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Fold ONE latency sample into a node's ``[K]`` bucket row (masked:
    ``ok`` False leaves both untouched).  Runs per node under the
    engine's vmap."""
    okx = jnp.asarray(ok, bool)
    hist = hist.at[bucket_index(lat)].add(okx.astype(hist.dtype))
    lat_sum = lat_sum + jnp.where(okx, jnp.asarray(lat, lat_sum.dtype), 0)
    return hist, lat_sum


def slo_observe(slo_ok: jax.Array, slo_violated: jax.Array,
                lat: jax.Array, ok: jax.Array, deadline: int
                ) -> Tuple[jax.Array, jax.Array]:
    """Exact SLO accounting at completion time: ``deadline`` is in rounds
    (Config.slo_deadline_rounds); counted device-side so the verdict does
    not depend on the deadline landing on a bucket edge."""
    okx = jnp.asarray(ok, bool)
    good = okx & (jnp.asarray(lat, jnp.int32) <= jnp.int32(deadline))
    return (slo_ok + good.astype(slo_ok.dtype),
            slo_violated + (okx & ~good).astype(slo_violated.dtype))


def hist_counters(family: str, hist: jax.Array, lat_sum: jax.Array
                  ) -> Dict[str, jax.Array]:
    """Registry-named scalar taps for a ``[N, K]`` per-node histogram —
    per-bucket totals summed over (shard-local) nodes plus the latency
    sum.  Shard-local arithmetic: under the dataplane these rows ride
    the single stacked metric psum."""
    tot = jnp.sum(jnp.asarray(hist, jnp.int32), axis=0)
    out = {f"{family}__bucket_{BUCKET_NAMES[i]}": tot[i]
           for i in range(N_BUCKETS)}
    out[f"{family}__sum"] = jnp.sum(lat_sum).astype(jnp.int32)
    return out


def family_names(family: str) -> Tuple[str, ...]:
    """The ring-column names :func:`hist_counters` emits, in order."""
    return tuple(f"{family}__bucket_{b}" for b in BUCKET_NAMES) \
        + (f"{family}__sum",)


def latency_specs(family: str, help_text: str = "") -> Tuple[MetricSpec, ...]:
    """MetricSpecs for one histogram family (GAUGE kind — cumulative
    device counters; the Prometheus sink renders the family as a native
    histogram from the ``__bucket_`` naming)."""
    h = help_text or f"Request latency histogram family {family}."
    specs = [MetricSpec(f"{family}__bucket_{b}", GAUGE,
                        f"{h} Cumulative count of completions with "
                        f"latency <= {b} rounds bucket edge "
                        f"(per-bucket, non-cumulative column).")
             for b in BUCKET_NAMES]
    specs.append(MetricSpec(f"{family}__sum", GAUGE,
                            f"{h} Sum of observed latencies (rounds)."))
    return tuple(specs)


# ------------------------------------------------------------- host twin

def host_bucket_index(lat) -> np.ndarray:
    """Bit-exact numpy twin of :func:`bucket_index`."""
    edges = np.asarray(BUCKET_EDGES, np.int32)
    lat = np.asarray(lat, np.int32)
    return np.sum(lat[..., None] > edges, axis=-1).astype(np.int32)


def host_hist(lats: Sequence[int]) -> np.ndarray:
    """[K] int32 histogram of latency samples, host-exact."""
    out = np.zeros((N_BUCKETS,), np.int32)
    if len(lats):
        np.add.at(out, host_bucket_index(np.asarray(list(lats))), 1)
    return out


# ------------------------------------------------------------ host folds

def quantile_bound(hist, q: float) -> float:
    """Upper-bound estimate of the ``q`` quantile from bucket counts:
    the upper edge (rounds) of the first bucket at which the cumulative
    count reaches ``ceil(q * total)``; ``inf`` when it lands in the
    overflow bucket, ``0.0`` on an empty histogram."""
    h = np.asarray(hist, np.float64)
    total = h.sum()
    if total <= 0:
        return 0.0
    k = max(1, int(math.ceil(q * total)))
    idx = int(np.searchsorted(np.cumsum(h), k, side="left"))
    if idx >= N_BUCKETS - 1:
        return float("inf")
    return float(BUCKET_EDGES[idx])


def fold_quantiles(hist) -> Dict[str, float]:
    """The window fold the load suite / chaos soak report: p50/p95/p99
    upper bounds in rounds."""
    return {"p50": quantile_bound(hist, 0.50),
            "p95": quantile_bound(hist, 0.95),
            "p99": quantile_bound(hist, 0.99)}


def hist_from_row(row: Dict[str, float], family: str) -> np.ndarray:
    """Recover the [K] bucket vector from one flushed ring row (or any
    name->value mapping carrying the family's columns)."""
    return np.asarray(
        [row.get(f"{family}__bucket_{b}", 0.0) for b in BUCKET_NAMES],
        np.float64)


def window_delta(rows: List[Dict[str, float]], family: str,
                 start_round: int = -1) -> np.ndarray:
    """Bucket-count DELTA over a flushed window: last row minus the last
    row at/before ``start_round`` (the columns are cumulative device
    counters).  ``start_round < 0`` folds from zero (whole run)."""
    if not rows:
        return np.zeros((N_BUCKETS,), np.float64)
    end = hist_from_row(rows[-1], family)
    if start_round < 0:
        return end
    base = np.zeros((N_BUCKETS,), np.float64)
    for r in rows:
        if int(r.get("round", -1)) <= start_round:
            base = hist_from_row(r, family)
    return end - base
