"""Logging façade — the ``partisan_logger.erl`` / ``partisan_config:trace``
analog (SURVEY §5.1: a cheap global tracing flag gates protocol logging
everywhere).

Device code cannot log; host-side orchestration (peer_service verbs,
bridge commands, verify harness, orchestration polls) logs through here.
``trace(...)`` is the hot-path guard: a no-op unless the tracing flag is
on, mirroring ``partisan_config:trace/2`` (partisan_config.erl:172-178).
For on-device visibility use engine metrics / ``capture_wire`` instead.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("partisan_tpu")

# case-insensitive truthy set (the usual env-flag spellings)
_TRUTHY = ("1", "true", "yes", "on")

_TRACING = (os.environ.get("PARTISAN_TRACING", "")
            .strip().lower() in _TRUTHY)


def _ensure_visible() -> None:
    """Make traces actually reach a stream under default logging config:
    without any handler (root unconfigured) and with the default WARNING
    level, ``logger.info`` is silently swallowed."""
    if not logger.handlers and not logging.getLogger().handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s %(message)s"))
        logger.addHandler(h)
    if logger.getEffectiveLevel() > logging.INFO:
        logger.setLevel(logging.INFO)


def set_tracing(on: bool) -> None:
    """partisan_config:set(tracing, ...).  Enabling also ensures the
    ``partisan_tpu`` logger has a handler and an INFO-permitting level."""
    global _TRACING
    _TRACING = bool(on)
    if _TRACING:
        _ensure_visible()


if _TRACING:  # env-enabled tracing must be visible too
    _ensure_visible()


def tracing() -> bool:
    return _TRACING


def trace(msg: str, *args) -> None:
    """Gated protocol tracing (the lager:info sites behind the flag)."""
    if _TRACING:
        logger.info(msg, *args)


def info(msg: str, *args) -> None:
    logger.info(msg, *args)


def warning(msg: str, *args) -> None:
    logger.warning(msg, *args)
