"""Logging façade — the ``partisan_logger.erl`` / ``partisan_config:trace``
analog (SURVEY §5.1: a cheap global tracing flag gates protocol logging
everywhere).

Device code cannot log; host-side orchestration (peer_service verbs,
bridge commands, verify harness, orchestration polls) logs through here.
``trace(...)`` is the hot-path guard: a no-op unless the tracing flag is
on, mirroring ``partisan_config:trace/2`` (partisan_config.erl:172-178).
For on-device visibility use engine metrics / ``capture_wire`` instead.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("partisan_tpu")

_TRACING = os.environ.get("PARTISAN_TRACING", "") in ("1", "true")


def set_tracing(on: bool) -> None:
    """partisan_config:set(tracing, ...)."""
    global _TRACING
    _TRACING = on


def tracing() -> bool:
    return _TRACING


def trace(msg: str, *args) -> None:
    """Gated protocol tracing (the lager:info sites behind the flag)."""
    if _TRACING:
        logger.info(msg, *args)


def info(msg: str, *args) -> None:
    logger.info(msg, *args)


def warning(msg: str, *args) -> None:
    logger.warning(msg, *args)
