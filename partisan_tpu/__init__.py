"""partisan_tpu — a TPU-native cluster-membership & gossip simulation framework.

A ground-up rebuild of the capabilities of ServiceFoundation/partisan (an
Erlang cluster-membership/messaging layer) as batched, jittable JAX programs:
N virtual nodes are rows of sharded arrays, one gossip round is one fused
sort-route-deliver-tick step, and protocols (full-membership CRDT gossip,
HyParView, SCAMP v1/v2, Plumtree, the Demers epidemic family) are vectorized
per-node handler tables.  See SURVEY.md at the repo root for the layer map.
"""

from .config import Config, DEFAULT, from_mapping
from .engine import ProtocolBase, World, init_world, make_step, make_run_scan, run

__version__ = "0.1.0"
