"""Cloud orchestration (SURVEY §2.8, L9) — rebuild of
``partisan_orchestration_backend.erl`` + its strategy behaviour
(``clients/1, servers/1, upload_artifact/3, download_artifact/2``,
partisan_orchestration_strategy.erl:24-27).

The reference polls an external discovery service (Redis for
docker-compose, the k8s API for kubernetes), uploads this node's
membership artifact and joins any peers it discovers.  Here the
orchestrator runs host-side next to the simulator: each ``poll`` uploads
the World's membership artifact and issues ``peer_service.join`` commands
for discovered-but-unknown nodes.

Strategies:
  * :class:`FileSystemStrategy` — a shared directory as the artifact
    store; the docker-compose/Redis analog, exercised in CI.
  * :class:`KubernetesStrategy` — pod discovery via the k8s API; needs
    cluster credentials, so it is a documented stub here (the image has
    no egress), same callback surface.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Protocol

import numpy as np

from .engine import ProtocolBase, World
from . import events as events_mod
from . import peer_service


class OrchestrationStrategy(Protocol):
    def upload_artifact(self, name: str, payload: bytes) -> None: ...
    def download_artifacts(self) -> Dict[str, bytes]: ...


class FileSystemStrategy:
    """Artifacts as files in a shared directory (compose/Redis analog)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def upload_artifact(self, name: str, payload: bytes) -> None:
        tmp = os.path.join(self.root, f".{name}.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(self.root, name))

    def download_artifacts(self) -> Dict[str, bytes]:
        out = {}
        for fn in os.listdir(self.root):
            if fn.startswith("."):
                continue
            with open(os.path.join(self.root, fn), "rb") as f:
                out[fn] = f.read()
        return out


class KubernetesStrategy:
    """Pod discovery through the Kubernetes API
    (partisan_kubernetes_orchestration_strategy.erl).  Requires in-cluster
    credentials; construction fails fast outside a cluster."""

    def __init__(self) -> None:
        raise NotImplementedError(
            "kubernetes discovery needs in-cluster API access; use "
            "FileSystemStrategy for local/compose deployments")


class OrchestrationBackend:
    """Host-side polling loop (the gen_server timers of
    partisan_orchestration_backend.erl:38-70 — membership refresh + graph
    upload — collapsed into an explicit ``poll``)."""

    def __init__(self, strategy: OrchestrationStrategy,
                 proto: ProtocolBase, my_node: int,
                 name: Optional[str] = None):
        self.strategy = strategy
        self.proto = proto
        self.my_node = my_node
        self.name = name or f"node-{my_node}"

    def poll(self, world: World) -> World:
        """Upload my membership artifact; join any discovered stranger."""
        mine = events_mod.members(world, self.proto, self.my_node)
        payload = json.dumps(
            {"node": self.my_node, "members": mine}).encode()
        self.strategy.upload_artifact(self.name, payload)

        known = set(mine) | {self.my_node}
        for _, blob in sorted(self.strategy.download_artifacts().items()):
            try:
                art = json.loads(blob)
            except (ValueError, UnicodeDecodeError):
                continue
            peers: List[int] = [int(art.get("node", -1))] + \
                [int(x) for x in art.get("members", [])]
            for p in peers:
                if p >= 0 and p not in known:
                    known.add(p)
                    world = peer_service.join(world, self.proto,
                                              self.my_node, p)
        return world

    def debug_get_tree(self, world: World) -> Dict[int, List[int]]:
        """debug_get_tree analog: every node's member list."""
        n = int(np.asarray(world.alive).shape[0])
        return {i: events_mod.members(world, self.proto, i)
                for i in range(n)}
