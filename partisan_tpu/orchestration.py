"""Cloud orchestration (SURVEY §2.8, L9) — rebuild of
``partisan_orchestration_backend.erl`` + its strategy behaviour
(``clients/1, servers/1, upload_artifact/3, download_artifact/2``,
partisan_orchestration_strategy.erl:24-27).

The reference polls an external discovery service (Redis for
docker-compose, the k8s API for kubernetes), uploads this node's
membership artifact and joins any peers it discovers.  Here the
orchestrator runs host-side next to the simulator: each ``poll`` uploads
the World's membership artifact and issues ``peer_service.join`` commands
for discovered-but-unknown nodes.

Strategies:
  * :class:`FileSystemStrategy` — a shared directory as the artifact
    store; the docker-compose/Redis analog, exercised in CI.
  * :class:`KubernetesStrategy` — pod discovery via the k8s API
    (label-selector queries + bearer token), with an injectable
    ``api_client`` so the discovery logic runs and tests without
    cluster credentials; artifacts delegate to a pluggable store.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Protocol

import numpy as np

from .engine import ProtocolBase, World
from . import events as events_mod
from . import peer_service
from . import telemetry


class OrchestrationStrategy(Protocol):
    def upload_artifact(self, name: str, payload: bytes) -> None: ...
    def download_artifacts(self) -> Dict[str, bytes]: ...


class FileSystemStrategy:
    """Artifacts as files in a shared directory (compose/Redis analog)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def upload_artifact(self, name: str, payload: bytes) -> None:
        tmp = os.path.join(self.root, f".{name}.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(self.root, name))

    def download_artifacts(self) -> Dict[str, bytes]:
        out = {}
        for fn in os.listdir(self.root):
            if fn.startswith("."):
                continue
            with open(os.path.join(self.root, fn), "rb") as f:
                out[fn] = f.read()
        return out


class KubernetesStrategy:
    """Pod discovery through the Kubernetes API — the rebuild of
    ``partisan_kubernetes_orchestration_strategy.erl`` (:20-146):

      * ``clients()`` / ``servers()`` list pods whose labels match
        ``tag=<client|server>,evaluation-timestamp=<ts>`` (the
        reference's URL-encoded labelSelector, :56-66) via
        ``GET $APISERVER/api/v1/pods?labelSelector=...`` with a bearer
        token (:131-146);
      * each pod with both ``metadata.name`` and ``status.podIP``
        becomes a peer spec ``name@podIP:PEER_PORT`` (:86-130) —
        malformed items are skipped exactly like the reference's
        undefined checks;
      * artifacts ride the pluggable store (the reference pushes them
        through Redis EVEN under kubernetes, :33-54 — here any
        OrchestrationStrategy store, e.g. FileSystemStrategy, plays
        that role).

    ``api_client(url, headers) -> (status, body_bytes)`` is injectable
    so the discovery logic runs and tests WITHOUT cluster credentials
    (this image has no egress); the default client reads APISERVER /
    TOKEN from the environment like the reference and fails fast when
    they are absent.
    """

    def __init__(self, artifact_store: Optional[OrchestrationStrategy]
                 = None, api_client=None,
                 api_server: Optional[str] = None,
                 token: Optional[str] = None,
                 peer_port: Optional[int] = None,
                 evaluation_timestamp: int = 0):
        self.store = artifact_store
        self.api_server = api_server or os.environ.get("APISERVER")
        self.token = token or os.environ.get("TOKEN")
        self.peer_port = int(peer_port
                             or os.environ.get("PEER_PORT", "9090"))
        self.evaluation_timestamp = evaluation_timestamp
        if api_client is not None:
            self.api_client = api_client
        else:
            if not self.api_server or not self.token:
                raise RuntimeError(
                    "kubernetes discovery needs APISERVER and TOKEN (or "
                    "an injected api_client); use FileSystemStrategy for "
                    "local/compose deployments")
            self.api_client = self._default_client

    def _default_client(self, url: str, headers: Dict[str, str]):
        import urllib.request
        req = urllib.request.Request(url, headers=headers)
        with urllib.request.urlopen(req, timeout=10) as resp:  # noqa: S310
            return resp.status, resp.read()

    # -- pod discovery (clients/1, servers/1) ------------------------------

    def clients(self) -> List[Dict]:
        return self._pods("client")

    def servers(self) -> List[Dict]:
        return self._pods("server")

    def _pods(self, tag: str) -> List[Dict]:
        selector = (f"tag%3D{tag},evaluation-timestamp%3D"
                    f"{self.evaluation_timestamp}")
        url = f"{self.api_server}/api/v1/pods?labelSelector={selector}"
        headers = {"Authorization": f"Bearer {self.token}"}
        try:
            status, body = self.api_client(url, headers)
        except Exception:  # noqa: BLE001 — discovery is best-effort
            return []
        if status != 200:
            return []          # invalid response -> empty set (:74-79)
        try:
            doc = json.loads(body)
        except ValueError:
            return []
        out = []
        for item in doc.get("items") or []:
            name = (item.get("metadata") or {}).get("name")
            pod_ip = (item.get("status") or {}).get("podIP")
            if not name or not pod_ip:
                continue       # both required (:113-118)
            out.append({"name": f"{name}@{pod_ip}",
                        "host": pod_ip, "port": self.peer_port})
        return out

    # -- artifact store (the reference's Redis leg, :33-54) ----------------

    def upload_artifact(self, name: str, payload: bytes) -> None:
        if self.store is None:
            raise RuntimeError("no artifact store configured")
        self.store.upload_artifact(name, payload)

    def download_artifacts(self) -> Dict[str, bytes]:
        if self.store is None:
            return {}
        return self.store.download_artifacts()


class OrchestrationBackend:
    """Host-side polling loop (the gen_server timers of
    partisan_orchestration_backend.erl:38-70 — membership refresh + graph
    upload — collapsed into an explicit ``poll``)."""

    def __init__(self, strategy: OrchestrationStrategy,
                 proto: ProtocolBase, my_node: int,
                 name: Optional[str] = None,
                 node_table: Optional[Dict[str, int]] = None):
        self.strategy = strategy
        self.proto = proto
        self.my_node = my_node
        self.name = name or f"node-{my_node}"
        # pod/peer name -> virtual node id (names live host-side only,
        # SURVEY §5.6); used by discovery-capable strategies (kubernetes)
        self.node_table = node_table or {}

    def poll(self, world: World) -> World:
        """Upload my membership artifact; join any discovered stranger.
        Each poll's outcome (members known, artifacts seen, joins issued)
        is recorded as an ``orchestration_poll`` telemetry event."""
        mine = events_mod.members(world, self.proto, self.my_node)
        payload = json.dumps(
            {"node": self.my_node, "members": mine}).encode()
        self.strategy.upload_artifact(self.name, payload)

        joins = 0
        artifacts = self.strategy.download_artifacts()
        known = set(mine) | {self.my_node}
        for _, blob in sorted(artifacts.items()):
            try:
                art = json.loads(blob)
            except (ValueError, UnicodeDecodeError):
                continue
            peers: List[int] = [int(art.get("node", -1))] + \
                [int(x) for x in art.get("members", [])]
            for p in peers:
                if p >= 0 and p not in known:
                    known.add(p)
                    joins += 1
                    world = peer_service.join(world, self.proto,
                                              self.my_node, p)

        # pod discovery (kubernetes): join every discovered pod that maps
        # to a virtual node id (the backend's refresh-membership timer,
        # partisan_orchestration_backend.erl:38-70)
        pods_seen = 0
        if hasattr(self.strategy, "clients"):
            pods = self.strategy.clients() + self.strategy.servers()
            pods_seen = len(pods)
            for pod in pods:
                p = self.node_table.get(pod["name"], -1)
                if p >= 0 and p not in known:
                    known.add(p)
                    joins += 1
                    world = peer_service.join(world, self.proto,
                                              self.my_node, p)
        telemetry.emit_event(
            "orchestration_poll", node=self.my_node, name=self.name,
            members=len(mine), artifacts=len(artifacts),
            pods=pods_seen, joins=joins)
        return world

    def debug_get_tree(self, world: World) -> Dict[int, List[int]]:
        """debug_get_tree analog: every node's member list."""
        n = int(np.asarray(world.alive).shape[0])
        return {i: events_mod.members(world, self.proto, i)
                for i in range(n)}
