"""HyParView partial-view membership — the TPU-native rebuild of
``src/partisan_hyparview_peer_service_manager.erl``.

Per-node state mirrors hyparview :88-101: a small *active* view (symmetric,
used for dissemination; cap ``max_active_size`` 6), a larger *passive* view
(backup peers; cap ``max_passive_size`` 30), an epoch counter, and
epoch-scoped disconnect-id maps used to reject stale view operations after
churn (:1622-1676 — "load-bearing for churn correctness", SURVEY §7.3).

Protocol messages, one handler per wire tag (reference handler sites cited):
  join              :703-771   add joiner to active (evict + disconnect when
                               full), reply neighbor, fan forward_join walks
  forward_join      :808-923   ARWL-TTL random walk; accept at TTL 0 or when
                               nearly isolated; passive-add at TTL == PRWL
                               (inert under the 5/30 config defaults, exactly
                               as in the reference — ARWL < PRWL means the
                               check never fires; passive fills via shuffle)
  neighbor          :774-805   symmetric active add
  disconnect        :926-972   id-validated removal, demote to passive
  neighbor_request  :975-1089  promotion handshake with priority + shuffle
  neighbor_accepted            exchange piggyback
  neighbor_rejected
  shuffle           :1091-1136 TTL walk carrying a mixed active/passive sample
  shuffle_reply                equal-size passive sample back to the origin
  (+ ctl_join / ctl_leave control verbs)

Timers (reference: per-node erlang timers; here: staggered round ticks):
  shuffle every ``shuffle_interval`` (:27, 572-607), random passive->active
  promotion every ``random_promotion_interval`` while under min_active
  (:28, 542-561).  The reactive on-EXIT promotion (:609-654) has no analog —
  links cannot fail independently in the simulator; the promotion timer plus
  the churn generator's epoch bumps cover the same repair behavior.

Random walks are one network hop per round: a walk message re-emits itself
with TTL-1, matching the reference's actual message behavior rather than its
code shape (SURVEY §7.3).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import padded_set as ps
from ..ops.msg import Msgs
from .. import prng

HIGH, LOW = 1, 0
_DC_SLOTS = 16      # direct-mapped disconnect-id map size (peer % slots)
_EPOCH_SHIFT = 12   # disconnect id = epoch << 12 | counter


@struct.dataclass
class HvState:
    active: jax.Array        # [N, A] padded peer set
    active_ttl: jax.Array    # [N, A] keepalive countdown per active slot
    passive: jax.Array       # [N, P] padded peer set
    epoch: jax.Array         # [N] int32, bumped on (re)start / churn
    dc_cnt: jax.Array        # [N] int32, per-node disconnect counter
    contact: jax.Array       # [N] int32 join contact, re-tried while isolated
    left: jax.Array          # [N] bool — gracefully departed, inert until rejoin
    sent_dc_peer: jax.Array  # [N, D] who we last disconnected (map keys)
    sent_dc_id: jax.Array    # [N, D] with which id (map values)
    recv_dc_peer: jax.Array  # [N, D]
    recv_dc_id: jax.Array    # [N, D]


# ---- direct-mapped (peer -> id) maps; collisions overwrite, degrading to
# ---- the permissive "no record" default — an explicit approximation of the
# ---- reference's unbounded per-peer maps (hyparview :81-101).

def _dc_get(peers: jax.Array, ids: jax.Array, p: jax.Array) -> jax.Array:
    slot = jnp.where(p >= 0, p % _DC_SLOTS, 0)
    hit = (peers[slot] == p) & (p >= 0)
    return jnp.where(hit, ids[slot], -1)


def _dc_put(peers: jax.Array, ids: jax.Array, p: jax.Array, i: jax.Array):
    slot = jnp.where(p >= 0, p % _DC_SLOTS, 0)
    do = p >= 0
    return (peers.at[slot].set(jnp.where(do, p, peers[slot])),
            ids.at[slot].set(jnp.where(do, i, ids[slot])))


class HyParView(ProtocolBase):
    msg_types = ("join", "forward_join", "neighbor", "disconnect",
                 "neighbor_request", "neighbor_accepted", "neighbor_rejected",
                 "shuffle", "shuffle_reply", "keepalive",
                 "ctl_join", "ctl_leave")
    ctl_peer_field = "joiner"

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.S = 1 + cfg.shuffle_k_active + cfg.shuffle_k_passive
        self.data_spec: Dict = {
            "joiner": ((), jnp.int32),
            "ttl": ((), jnp.int32),
            "id": ((), jnp.int32),      # disconnect id
            "prio": ((), jnp.int32),
            "dcid": ((), jnp.int32),    # sender's last-received dc id for dst
            "origin": ((), jnp.int32),  # shuffle originator
            "sample": ((self.S,), jnp.int32),
        }
        # join: 1 neighbor + (A-1) forward_joins + 1 eviction disconnect
        self.emit_cap = max(cfg.max_active_size + 2, 8)
        # shuffle + promotion + join-retry + keepalives to all active
        self.tick_emit_cap = cfg.max_active_size + 3

    # ------------------------------------------------------------------ state

    def init(self, cfg: Config, key: jax.Array) -> HvState:
        n = cfg.n_nodes
        d = _DC_SLOTS
        return HvState(
            active=jnp.full((n, cfg.max_active_size), -1, jnp.int32),
            active_ttl=jnp.zeros((n, cfg.max_active_size), jnp.int32),
            passive=jnp.full((n, cfg.max_passive_size), -1, jnp.int32),
            epoch=jnp.ones((n,), jnp.int32),
            dc_cnt=jnp.zeros((n,), jnp.int32),
            contact=jnp.full((n,), -1, jnp.int32),
            left=jnp.zeros((n,), bool),
            sent_dc_peer=jnp.full((n, d), -1, jnp.int32),
            sent_dc_id=jnp.full((n, d), -1, jnp.int32),
            recv_dc_peer=jnp.full((n, d), -1, jnp.int32),
            recv_dc_id=jnp.full((n, d), -1, jnp.int32),
        )

    def member_mask(self, row: HvState) -> jax.Array:
        """Active-view one-hot (the manager's members/0 = active view)."""
        n = self.cfg.n_nodes
        m = jnp.zeros((n,), bool)
        return m.at[jnp.clip(row.active, 0, n - 1)].max(row.active >= 0)

    # ------------------------------------------------------------- primitives

    def _is_addable(self, row: HvState, peer: jax.Array,
                    msg_dcid: jax.Array) -> jax.Array:
        """Refuse to re-add a peer that has not yet seen our latest
        disconnect to it (the is_addable epoch/id gate, hyparview
        :1656-1676): addable iff we never disconnected it, or the peer's
        message echoes an id >= our last sent one."""
        mine = _dc_get(row.sent_dc_peer, row.sent_dc_id, peer)
        return (peer >= 0) & ((mine < 0) | (msg_dcid >= mine))

    def _my_dcid_for(self, row: HvState, peer: jax.Array) -> jax.Array:
        """What we echo in join/neighbor messages: the last disconnect id we
        received FROM ``peer`` (proof we have seen it)."""
        return _dc_get(row.recv_dc_peer, row.recv_dc_id, peer)

    def _reset_ttl(self, cfg, row: HvState, peer: jax.Array) -> HvState:
        """Refresh the keepalive countdown on peer's active slot."""
        hit = (row.active == peer) & (peer >= 0)
        return row.replace(active_ttl=jnp.where(
            hit, cfg.keepalive_ttl, row.active_ttl))

    def _add_active(self, cfg, me, row: HvState, peer: jax.Array,
                    key: jax.Array):
        """add_to_active_view (:1371-1420 + eviction :1466-1512): insert
        peer; when full, evict a uniformly random victim, demote it to the
        passive view and emit a ``disconnect`` with a fresh epoch-scoped id.

        Returns (row, dc_dst, dc_id): dc_dst = -1 when nothing was evicted.
        """
        ok = (peer >= 0) & (peer != me) & ~row.left
        peer = jnp.where(ok, peer, -1)
        row = row.replace(passive=ps.remove(row.passive, peer))
        new_active, evicted, _ = ps.insert_evict(row.active, peer, key)
        row = row.replace(active=new_active)
        row = self._reset_ttl(cfg, row, peer)
        # demote the victim (disconnected peers land in passive, :926-972)
        k2 = prng.decision_key(key, 1)
        row = self._add_passive(cfg, me, row, evicted, k2)
        new_id = (row.epoch << _EPOCH_SHIFT) | (row.dc_cnt & ((1 << _EPOCH_SHIFT) - 1))
        did_evict = evicted >= 0
        sp, si = _dc_put(row.sent_dc_peer, row.sent_dc_id,
                         jnp.where(did_evict, evicted, -1), new_id)
        row = row.replace(
            sent_dc_peer=sp, sent_dc_id=si,
            dc_cnt=row.dc_cnt + did_evict.astype(jnp.int32),
        )
        return row, jnp.where(did_evict, evicted, -1), new_id

    def _add_passive(self, cfg, me, row: HvState, peer: jax.Array,
                     key: jax.Array) -> HvState:
        """add_to_passive_view (:1422-1448): only if not myself and not in
        either view; evict a random passive member when full."""
        ok = ((peer >= 0) & (peer != me)
              & ~ps.contains(row.active, peer)
              & ~ps.contains(row.passive, peer))
        peer = jnp.where(ok, peer, -1)
        new_passive, _, _ = ps.insert_evict(row.passive, peer, key)
        return row.replace(passive=new_passive)

    def _merge_exchange(self, cfg, me, row: HvState, sample: jax.Array,
                        key: jax.Array) -> HvState:
        """merge_exchange (:1589-1595): fold a received sample into the
        passive view."""
        for j in range(sample.shape[0]):  # static unroll, S is tiny
            row = self._add_passive(cfg, me, row, sample[j],
                                    prng.decision_key(key, 10 + j))
        return row

    def _shuffle_sample(self, cfg, me, row: HvState, key: jax.Array) -> jax.Array:
        """self ++ k_active of active ++ k_passive of passive (:572-607)."""
        ka = ps.random_k(row.active, prng.decision_key(key, 20),
                         cfg.shuffle_k_active)
        kp = ps.random_k(row.passive, prng.decision_key(key, 21),
                         cfg.shuffle_k_passive)
        return jnp.concatenate([me[None].astype(jnp.int32), ka, kp])

    # --------------------------------------------------------------- handlers

    def handle_join(self, cfg, me, row: HvState, m: Msgs, key: jax.Array):
        peer = m.src
        addable = self._is_addable(row, peer, m.data["dcid"])
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(addable, peer, -1), key)
        # fan the walk to every *other* active member (:738-753)
        others = jnp.where(row2.active == peer, -1, row2.active)
        fj = self.emit(others, self.typ("forward_join"),
                       valid=jnp.broadcast_to(addable, others.shape),
                       joiner=peer, ttl=cfg.arwl)
        nb = self.emit(jnp.where(addable, peer, -1)[None], self.typ("neighbor"),
                       dcid=self._my_dcid_for(row2, peer))
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        return row2, self.merge(nb, dc, fj)

    def handle_forward_join(self, cfg, me, row: HvState, m: Msgs, key):
        joiner, ttl, sender = m.data["joiner"], m.data["ttl"], m.src
        not_me = joiner != me
        accept = ((ttl <= 0) | (ps.size(row.active) <= 1)) & not_me
        addable = joiner >= 0  # walks carry no dcid echo; permissive add
        do_add = accept & addable
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(do_add, joiner, -1), key)
        nb = self.emit(jnp.where(do_add, joiner, -1)[None],
                       self.typ("neighbor"),
                       dcid=self._my_dcid_for(row2, joiner))
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        # passive add at TTL == PRWL (:859-866; inert when ARWL < PRWL)
        at_prwl = (~accept) & (ttl == cfg.prwl) & not_me
        row3 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(at_prwl, a, b),
            self._add_passive(cfg, me, row2, joiner,
                              prng.decision_key(key, 3)), row2)
        # else: keep walking to a random active peer != sender/joiner/me
        nxt = ps.random_member(row3.active, prng.decision_key(key, 4),
                               exclude=jnp.stack([sender, joiner, me]))
        walk_on = (~accept) & not_me & (nxt >= 0)
        fj = self.emit(jnp.where(walk_on, nxt, -1)[None],
                       self.typ("forward_join"),
                       joiner=joiner, ttl=jnp.maximum(ttl - 1, 0))
        # dead-end walk (no eligible next hop): accept locally (:819-854)
        dead_end = (~accept) & not_me & (nxt < 0)
        row4, dc_dst2, dc_id2 = self._add_active(
            cfg, me, row3, jnp.where(dead_end, joiner, -1),
            prng.decision_key(key, 5))
        nb2 = self.emit(jnp.where(dead_end, joiner, -1)[None],
                        self.typ("neighbor"),
                        dcid=self._my_dcid_for(row4, joiner))
        dc2 = self.emit(dc_dst2[None], self.typ("disconnect"), id=dc_id2)
        return row4, self.merge(nb, dc, fj, nb2, dc2)

    def handle_neighbor(self, cfg, me, row: HvState, m: Msgs, key):
        peer = m.src
        addable = self._is_addable(row, peer, m.data["dcid"])
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(addable, peer, -1), key)
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        return row2, dc

    def handle_disconnect(self, cfg, me, row: HvState, m: Msgs, key):
        peer, mid = m.src, m.data["id"]
        last = _dc_get(row.recv_dc_peer, row.recv_dc_id, peer)
        valid = mid > last  # monotone id gate (is_valid_disconnect, :1622-1655)
        rp, ri = _dc_put(row.recv_dc_peer, row.recv_dc_id,
                         jnp.where(valid, peer, -1), mid)
        row = row.replace(recv_dc_peer=rp, recv_dc_id=ri)
        row = row.replace(active=jnp.where(
            valid & (row.active == peer), -1, row.active))
        row = self._add_passive(cfg, me, row, jnp.where(valid, peer, -1), key)
        return row, self.no_emit()

    def handle_neighbor_request(self, cfg, me, row: HvState, m: Msgs, key):
        peer, prio = m.src, m.data["prio"]
        row = self._merge_exchange(cfg, me, row, m.data["sample"],
                                   prng.decision_key(key, 6))
        addable = self._is_addable(row, peer, m.data["dcid"])
        room = ps.size(row.active) < cfg.max_active_size
        accept = addable & ~row.left & ((prio == HIGH) | room)
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(accept, peer, -1), key)
        reply_t = jnp.where(accept, self.typ("neighbor_accepted"),
                            self.typ("neighbor_rejected"))
        sample = self._shuffle_sample(cfg, me, row2, prng.decision_key(key, 7))
        rep = self.emit(peer[None], reply_t, sample=sample,
                        dcid=self._my_dcid_for(row2, peer))
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        return row2, self.merge(rep, dc)

    def handle_neighbor_accepted(self, cfg, me, row: HvState, m: Msgs, key):
        peer = m.src
        addable = self._is_addable(row, peer, m.data["dcid"])
        row = self._merge_exchange(cfg, me, row, m.data["sample"],
                                   prng.decision_key(key, 8))
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(addable, peer, -1), key)
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        return row2, dc

    def handle_neighbor_rejected(self, cfg, me, row: HvState, m: Msgs, key):
        # the promotion timer will try another candidate (:1015-1046)
        return row, self.no_emit()

    def handle_shuffle(self, cfg, me, row: HvState, m: Msgs, key):
        origin, ttl, sender = m.data["origin"], m.data["ttl"], m.src
        nxt = ps.random_member(row.active, prng.decision_key(key, 9),
                               exclude=jnp.stack([origin, sender, me]))
        walk = (ttl > 0) & (nxt >= 0) & (origin != me)
        fwd = self.emit(jnp.where(walk, nxt, -1)[None], self.typ("shuffle"),
                        origin=origin, ttl=ttl - 1, sample=m.data["sample"])
        # accept: reply an equal-size passive sample to origin, merge theirs
        acc = ~walk & (origin != me)
        reply_sample = ps.random_k(row.passive, prng.decision_key(key, 10),
                                   self.S)
        rep = self.emit(jnp.where(acc, origin, -1)[None],
                        self.typ("shuffle_reply"), sample=reply_sample)
        row2 = self._merge_exchange(cfg, me, row, jnp.where(
            acc, m.data["sample"], -1), prng.decision_key(key, 11))
        return row2, self.merge(fwd, rep)

    def handle_shuffle_reply(self, cfg, me, row: HvState, m: Msgs, key):
        row = self._merge_exchange(cfg, me, row, m.data["sample"], key)
        return row, self.no_emit()

    def handle_keepalive(self, cfg, me, row: HvState, m: Msgs, key):
        """Active-link liveness (the TCP-keepalive / EXIT-prune analog,
        partisan_socket.erl:17-19 + pluggable :971-984).  A keepalive from a
        current active peer refreshes its slot TTL; one from a peer that
        believes we are ITS active neighbor but is not in ours re-adds it
        when there is room (no eviction — avoids repair cascades), healing
        one-sided edges left by dropped disconnects."""
        peer = m.src
        present = ps.contains(row.active, peer)
        row = self._reset_ttl(cfg, row, jnp.where(present, peer, -1))
        room = ps.size(row.active) < cfg.max_active_size
        readd = (~present) & room & self._is_addable(row, peer, m.data["dcid"])
        row2, _, _ = self._add_active(cfg, me, row,
                                      jnp.where(readd, peer, -1), key)
        return row2, self.no_emit()

    def handle_ctl_join(self, cfg, me, row: HvState, m: Msgs, key):
        """Remember the contact and send join; the tick re-sends while the
        active view is empty — the connection-retry loop of the reference
        (pluggable :944-969, 1 s tick) that makes join storms safe under
        inbox overflow."""
        peer = m.data["joiner"]
        row = row.replace(contact=jnp.where(peer == me, row.contact, peer),
                          left=jnp.where(peer == me, row.left, False))
        return row, self.emit(peer[None], self.typ("join"),
                              dcid=self._my_dcid_for(row, peer))

    def handle_ctl_leave(self, cfg, me, row: HvState, m: Msgs, key):
        """Graceful leave: disconnect every active peer and clear views."""
        new_id = (row.epoch << _EPOCH_SHIFT) | (row.dc_cnt & ((1 << _EPOCH_SHIFT) - 1))
        dc = self.emit(row.active, self.typ("disconnect"), id=new_id)
        row = row.replace(
            active=jnp.full_like(row.active, -1),
            passive=jnp.full_like(row.passive, -1),
            contact=jnp.full_like(row.contact, -1),
            left=jnp.ones_like(row.left),
            dc_cnt=row.dc_cnt + 1,
        )
        return row, dc

    # ------------------------------------------------------------------ timer

    def tick(self, cfg, me, row: HvState, rnd, key):
        # -- failure detection: age active slots; expired links are demoted
        #    to passive (the EXIT-prune path, pluggable :971-984, hyparview
        #    :609-654 — here triggered by keepalive silence, not socket death)
        occupied = row.active >= 0
        ttl = jnp.where(occupied, row.active_ttl - 1, 0)
        expired = occupied & (ttl <= 0)
        expired_peers = jnp.where(expired, row.active, -1)
        row = row.replace(active=jnp.where(expired, -1, row.active),
                          active_ttl=ttl)
        for j in range(expired_peers.shape[0]):  # static unroll over A slots
            row = self._add_passive(cfg, me, row, expired_peers[j],
                                    prng.decision_key(key, 40 + j))
        # staggered by node id: ~N/interval nodes fire per round, avoiding
        # the synchronized-storm artifact of a global phase
        stay = ~row.left
        shuffle_due = (((rnd + me) % cfg.shuffle_interval) == 0) & stay
        promo_due = (((rnd + me) % cfg.random_promotion_interval) == 0) & stay

        tgt = ps.random_member(row.active, prng.decision_key(key, 12))
        sample = self._shuffle_sample(cfg, me, row, key)
        sh = self.emit(jnp.where(shuffle_due, tgt, -1)[None],
                       self.typ("shuffle"), cap=self.tick_emit_cap,
                       origin=me, ttl=cfg.arwl, sample=sample)

        under = ps.size(row.active) < cfg.min_active_size
        cand = ps.random_member(row.passive, prng.decision_key(key, 13))
        prio = jnp.where(ps.size(row.active) == 0, HIGH, LOW)
        nr = self.emit(jnp.where(promo_due & under, cand, -1)[None],
                       self.typ("neighbor_request"), cap=self.tick_emit_cap,
                       prio=prio, sample=sample,
                       dcid=self._my_dcid_for(row, cand))

        # join retry until the CONTACT acknowledges (connection retry of
        # the pending set, pluggable :944-969 — pending clears on
        # `connected`, NOT on merely having some other active peer; gating
        # on an empty view lets a clique of storm-dropped joiners satisfy
        # each other and form a permanently disconnected island)
        row = row.replace(contact=jnp.where(
            ps.contains(row.active, row.contact), -1, row.contact))
        retry_due = (((rnd % cfg.connection_retry_interval) == 0) & stay
                     & (row.contact >= 0))
        jn = self.emit(jnp.where(retry_due, row.contact, -1)[None],
                       self.typ("join"), cap=self.tick_emit_cap,
                       dcid=self._my_dcid_for(row, row.contact))

        # keepalives to every active peer (failure-detection heartbeat)
        ka_due = ((rnd % cfg.keepalive_interval) == 0) & stay
        dcids = jax.vmap(lambda p: self._my_dcid_for(row, p))(row.active)
        ka = self.emit(jnp.where(ka_due, row.active, -1),
                       self.typ("keepalive"), cap=self.tick_emit_cap,
                       dcid=dcids)
        return row, self.merge(sh, nr, jn, ka, cap=self.tick_emit_cap)
