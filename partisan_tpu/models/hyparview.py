"""HyParView partial-view membership — the TPU-native rebuild of
``src/partisan_hyparview_peer_service_manager.erl``.

Per-node state mirrors hyparview :88-101: a small *active* view (symmetric,
used for dissemination; cap ``max_active_size`` 6), a larger *passive* view
(backup peers; cap ``max_passive_size`` 30), an epoch counter, and
epoch-scoped disconnect-id maps used to reject stale view operations after
churn (:1622-1676 — "load-bearing for churn correctness", SURVEY §7.3).

Protocol messages, one handler per wire tag (reference handler sites cited):
  join              :703-771   add joiner to active (evict + disconnect when
                               full), reply neighbor, fan forward_join walks
  forward_join      :808-923   ARWL-TTL random walk; accept at TTL 0 or when
                               nearly isolated; passive-add at TTL == PRWL
                               (inert under the 5/30 config defaults, exactly
                               as in the reference — ARWL < PRWL means the
                               check never fires; passive fills via shuffle)
  neighbor          :774-805   symmetric active add
  disconnect        :926-972   id-validated removal, demote to passive
  neighbor_request  :975-1089  promotion handshake with priority + shuffle
  neighbor_accepted            exchange piggyback
  neighbor_rejected
  shuffle           :1091-1136 TTL walk carrying a mixed active/passive sample
  shuffle_reply                equal-size passive sample back to the origin
  (+ ctl_join / ctl_leave control verbs)

Timers (reference: per-node erlang timers; here: staggered round ticks):
  shuffle every ``shuffle_interval`` (:27, 572-607), random passive->active
  promotion every ``random_promotion_interval`` while under min_active
  (:28, 542-561).  The reactive on-EXIT promotion (:609-654) has no analog —
  links cannot fail independently in the simulator; the promotion timer plus
  the churn generator's epoch bumps cover the same repair behavior.

Random walks are one network hop per round: a walk message re-emits itself
with TTL-1, matching the reference's actual message behavior rather than its
code shape (SURVEY §7.3).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import padded_set as ps
from ..ops.msg import Msgs
from .. import prng

HIGH, LOW = 1, 0
_DC_SLOTS = 16      # direct-mapped disconnect-id map size (peer % slots)
_EPOCH_SHIFT = 12   # disconnect id = epoch << 12 | counter
_PART_SLOTS = 16    # per-node partition table capacity (overflow counted)


@struct.dataclass
class HvState:
    active: jax.Array        # [N, A] padded peer set
    active_ttl: jax.Array    # [N, A] keepalive countdown per active slot
    passive: jax.Array       # [N, P] padded peer set
    epoch: jax.Array         # [N] int32, bumped on (re)start / churn
    dc_cnt: jax.Array        # [N] int32, per-node disconnect counter
    contact: jax.Array       # [N] int32 join contact, re-tried while isolated
    left: jax.Array          # [N] bool — gracefully departed, inert until rejoin
    sent_dc_peer: jax.Array  # [N, D] who we last disconnected (map keys)
    sent_dc_id: jax.Array    # [N, D] with which id (map values)
    recv_dc_peer: jax.Array  # [N, D]
    recv_dc_id: jax.Array    # [N, D]
    # per-tag reserved active slots (reference :88-101, reserve/1 :398-411)
    rsv_tag: jax.Array       # [N, A] reserved tag per slot (-1 free)
    rsv_peer: jax.Array      # [N, A] peer filling it (-1 open)
    rsv_dropped: jax.Array   # [N] reserve attempts past max_active (counted)
    # protocol-visible partition table (inject/resolve TTL flood,
    # reference :244-254, 1731-1797)
    part_ref: jax.Array      # [N, PT] partition reference ids (-1 free)
    part_peer: jax.Array     # [N, PT] the neighbor marked partitioned
    part_dropped: jax.Array  # [N] entries lost to a full table (counted)
    dc_overwrites: jax.Array  # [N] dc-map slot collisions (approximation
                              # fidelity loss — counted, never silent)


# ---- direct-mapped (peer -> id) maps; collisions overwrite, degrading to
# ---- the permissive "no record" default — an explicit approximation of the
# ---- reference's unbounded per-peer maps (hyparview :81-101).

def _dc_get(peers: jax.Array, ids: jax.Array, p: jax.Array) -> jax.Array:
    slot = jnp.where(p >= 0, p % _DC_SLOTS, 0)
    hit = (peers[slot] == p) & (p >= 0)
    return jnp.where(hit, ids[slot], -1)


def _dc_put(peers: jax.Array, ids: jax.Array, p: jax.Array, i: jax.Array):
    """Returns (peers, ids, overwrote): ``overwrote`` flags a collision
    that evicted a DIFFERENT peer's record — the fidelity-loss event of
    the direct-mapped approximation, counted by callers (VERDICT r1:
    silent-degradation structures must have counters)."""
    slot = jnp.where(p >= 0, p % _DC_SLOTS, 0)
    do = p >= 0
    overwrote = do & (peers[slot] >= 0) & (peers[slot] != p)
    return (peers.at[slot].set(jnp.where(do, p, peers[slot])),
            ids.at[slot].set(jnp.where(do, i, ids[slot])),
            overwrote)


class HyParView(ProtocolBase):
    msg_types = ("join", "forward_join", "neighbor", "disconnect",
                 "neighbor_request", "neighbor_accepted", "neighbor_rejected",
                 "shuffle", "shuffle_reply", "keepalive",
                 "part_inject", "part_resolve",
                 "ctl_join", "ctl_leave", "ctl_reserve",
                 "ctl_part_inject", "ctl_part_resolve")
    ctl_peer_field = "joiner"

    def __init__(self, cfg: Config, tags=None, reservable: bool = False):
        """``tags``: optional [N] int32 node-tag table (-1 untagged) — the
        node_spec tag of the reference (client/server etc).  ``reservable``
        enables the per-tag reserved-slot machinery in _add_active
        (reference :88-101); off by default so untagged deployments keep
        the exact unreserved code path."""
        self.cfg = cfg
        assert cfg.shuffle_k_active <= cfg.max_active_size and \
            cfg.shuffle_k_passive <= cfg.max_passive_size, (
                "shuffle sample sizes cannot exceed the view caps "
                f"(k_active={cfg.shuffle_k_active} vs "
                f"A={cfg.max_active_size}; k_passive="
                f"{cfg.shuffle_k_passive} vs P={cfg.max_passive_size})")
        self.S = 1 + cfg.shuffle_k_active + cfg.shuffle_k_passive
        self.tags = None if tags is None else jnp.asarray(tags, jnp.int32)
        self.reservable = reservable
        self.data_spec: Dict = {
            "joiner": ((), jnp.int32),
            "ttl": ((), jnp.int32),
            "id": ((), jnp.int32),      # disconnect id
            "prio": ((), jnp.int32),
            "dcid": ((), jnp.int32),    # sender's last-received dc id for dst
            "origin": ((), jnp.int32),  # shuffle originator
            "sample": ((self.S,), jnp.int32),
            "tag": ((), jnp.int32, -1),   # ctl_reserve
            "pref": ((), jnp.int32, -1),  # partition reference id
        }
        # join: 1 neighbor + (A-1) forward_joins + 1 eviction disconnect
        self.emit_cap = max(cfg.max_active_size + 2, 8)
        # shuffle + promotion + join-retry + keepalives to all active
        self.tick_emit_cap = cfg.max_active_size + 3

    # ------------------------------------------------------------------ state

    def init(self, cfg: Config, key: jax.Array) -> HvState:
        n = cfg.n_nodes
        d = _DC_SLOTS
        a = cfg.max_active_size
        return HvState(
            active=jnp.full((n, a), -1, jnp.int32),
            active_ttl=jnp.zeros((n, a), jnp.int32),
            passive=jnp.full((n, cfg.max_passive_size), -1, jnp.int32),
            epoch=jnp.ones((n,), jnp.int32),
            dc_cnt=jnp.zeros((n,), jnp.int32),
            contact=jnp.full((n,), -1, jnp.int32),
            left=jnp.zeros((n,), bool),
            sent_dc_peer=jnp.full((n, d), -1, jnp.int32),
            sent_dc_id=jnp.full((n, d), -1, jnp.int32),
            recv_dc_peer=jnp.full((n, d), -1, jnp.int32),
            recv_dc_id=jnp.full((n, d), -1, jnp.int32),
            rsv_tag=jnp.full((n, a), -1, jnp.int32),
            rsv_peer=jnp.full((n, a), -1, jnp.int32),
            rsv_dropped=jnp.zeros((n,), jnp.int32),
            part_ref=jnp.full((n, _PART_SLOTS), -1, jnp.int32),
            part_peer=jnp.full((n, _PART_SLOTS), -1, jnp.int32),
            part_dropped=jnp.zeros((n,), jnp.int32),
            dc_overwrites=jnp.zeros((n,), jnp.int32),
        )

    def health_counters(self, state: HvState):
        """Degradation counters surfaced through metrics.world_health."""
        return {
            "dc_overwrites": jnp.sum(state.dc_overwrites),
            "rsv_dropped": jnp.sum(state.rsv_dropped),
            "part_dropped": jnp.sum(state.part_dropped),
        }

    def member_mask(self, row: HvState) -> jax.Array:
        """Active-view one-hot (the manager's members/0 = active view)."""
        n = self.cfg.n_nodes
        m = jnp.zeros((n,), bool)
        return m.at[jnp.clip(row.active, 0, n - 1)].max(row.active >= 0)

    # ------------------------------------------------------------- primitives

    def _is_addable(self, row: HvState, peer: jax.Array,
                    msg_dcid: jax.Array) -> jax.Array:
        """Refuse to re-add a peer that has not yet seen our latest
        disconnect to it (the is_addable epoch/id gate, hyparview
        :1656-1676): addable iff we never disconnected it, or the peer's
        message echoes an id >= our last sent one."""
        mine = _dc_get(row.sent_dc_peer, row.sent_dc_id, peer)
        return (peer >= 0) & ((mine < 0) | (msg_dcid >= mine))

    def _my_dcid_for(self, row: HvState, peer: jax.Array) -> jax.Array:
        """What we echo in join/neighbor messages: the last disconnect id we
        received FROM ``peer`` (proof we have seen it)."""
        return _dc_get(row.recv_dc_peer, row.recv_dc_id, peer)

    def _reset_ttl(self, cfg, row: HvState, peer: jax.Array) -> HvState:
        """Refresh the keepalive countdown on peer's active slot."""
        hit = (row.active == peer) & (peer >= 0)
        return row.replace(active_ttl=jnp.where(
            hit, cfg.keepalive_ttl, row.active_ttl))

    def _tag_of(self, peer: jax.Array) -> jax.Array:
        if self.tags is None:
            return jnp.int32(-1)
        n = self.tags.shape[0]
        return jnp.where(peer >= 0, self.tags[jnp.clip(peer, 0, n - 1)], -1)

    def _add_active(self, cfg, me, row: HvState, peer: jax.Array,
                    key: jax.Array):
        """add_to_active_view (:1371-1420 + eviction :1466-1512): insert
        peer; when full, evict a uniformly random victim, demote it to the
        passive view and emit a ``disconnect`` with a fresh epoch-scoped id.

        With ``reservable=True``, the reference's per-tag reserved slots
        apply (:1397-1413, 1445-1460, 1477): a peer whose tag matches an
        OPEN reservation fills it; open reservations count toward
        fullness (is_full), so untagged peers see capacity
        A - open_reservations; peers in FILLED reservations are never the
        random eviction victim.  A filled slot is never un-filled — the
        reference's remove_from_reserved is commented out (:1611).

        Returns (row, dc_dst, dc_id): dc_dst = -1 when nothing was evicted.
        """
        ok = (peer >= 0) & (peer != me) & ~row.left
        peer = jnp.where(ok, peer, -1)
        row = row.replace(passive=ps.remove(row.passive, peer))
        if not self.reservable:
            new_active, evicted, _ = ps.insert_evict(row.active, peer, key)
            row = row.replace(active=new_active)
        else:
            A = row.active.shape[0]
            ptag = self._tag_of(peer)
            open_slot = (row.rsv_tag >= 0) & (row.rsv_peer < 0)
            fill_hit = open_slot & (row.rsv_tag == ptag) & (ptag >= 0)
            fills = jnp.any(fill_hit)
            n_open = jnp.sum(open_slot) - fills.astype(jnp.int32)
            present = ps.contains(row.active, peer)
            want = (peer >= 0) & ~present
            free = row.active < 0
            has_free = jnp.any(free)
            first_free = jnp.argmax(free)
            need_evict = want & ((ps.size(row.active) + n_open >= A)
                                 | ~has_free)
            # random eviction among UNPROTECTED members (reserved peers
            # are omitted, :1477)
            protected = jnp.any(
                row.active[None, :] == row.rsv_peer[:, None], axis=0) \
                & (row.active >= 0)
            elig = (row.active >= 0) & ~protected
            g = jax.random.gumbel(key, row.active.shape)
            vslot = jnp.argmax(jnp.where(elig, g, -jnp.inf))
            can = want & jnp.where(need_evict, jnp.any(elig), has_free)
            slot = jnp.where(need_evict, vslot, first_free)
            evicted = jnp.where(can & need_evict, row.active[slot], -1)
            active = row.active.at[slot].set(
                jnp.where(can, peer, row.active[slot]))
            rsv_peer = jnp.where(
                (jnp.arange(A) == jnp.argmax(fill_hit)) & fills & can,
                peer, row.rsv_peer)
            row = row.replace(active=active, rsv_peer=rsv_peer)
        row = self._reset_ttl(cfg, row, peer)
        # demote the victim (disconnected peers land in passive, :926-972)
        k2 = prng.decision_key(key, 1)
        row = self._add_passive(cfg, me, row, evicted, k2)
        new_id = (row.epoch << _EPOCH_SHIFT) | (row.dc_cnt & ((1 << _EPOCH_SHIFT) - 1))
        did_evict = evicted >= 0
        sp, si, over = _dc_put(row.sent_dc_peer, row.sent_dc_id,
                               jnp.where(did_evict, evicted, -1), new_id)
        row = row.replace(
            sent_dc_peer=sp, sent_dc_id=si,
            dc_cnt=row.dc_cnt + did_evict.astype(jnp.int32),
            dc_overwrites=row.dc_overwrites + over.astype(jnp.int32),
        )
        return row, jnp.where(did_evict, evicted, -1), new_id

    def _add_passive(self, cfg, me, row: HvState, peer: jax.Array,
                     key: jax.Array) -> HvState:
        """add_to_passive_view (:1422-1448): only if not myself and not in
        either view; evict a random passive member when full."""
        ok = ((peer >= 0) & (peer != me)
              & ~ps.contains(row.active, peer)
              & ~ps.contains(row.passive, peer))
        peer = jnp.where(ok, peer, -1)
        new_passive, _, _ = ps.insert_evict(row.passive, peer, key)
        return row.replace(passive=new_passive)

    def _merge_exchange(self, cfg, me, row: HvState, sample: jax.Array,
                        key: jax.Array) -> HvState:
        """merge_exchange (:1589-1595): fold a received sample into the
        passive view."""
        # trace-lint: allow(unroll-bomb): S (shuffle sample width) is a tiny static Config bound; each step reuses the previous add's row
        for j in range(sample.shape[0]):
            row = self._add_passive(cfg, me, row, sample[j],
                                    prng.decision_key(key, 10 + j))
        return row

    def _shuffle_sample(self, cfg, me, row: HvState, key: jax.Array) -> jax.Array:
        """self ++ k_active of active ++ k_passive of passive (:572-607)."""
        ka = ps.random_k(row.active, prng.decision_key(key, 20),
                         cfg.shuffle_k_active)
        kp = ps.random_k(row.passive, prng.decision_key(key, 21),
                         cfg.shuffle_k_passive)
        return jnp.concatenate([me[None].astype(jnp.int32), ka, kp])

    # --------------------------------------------------------------- handlers

    def handle_join(self, cfg, me, row: HvState, m: Msgs, key: jax.Array):
        peer = m.src
        addable = self._is_addable(row, peer, m.data["dcid"])
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(addable, peer, -1), key)
        # fan the walk to every *other* active member (:738-753)
        others = jnp.where(row2.active == peer, -1, row2.active)
        fj = self.emit(others, self.typ("forward_join"),
                       valid=jnp.broadcast_to(addable, others.shape),
                       joiner=peer, ttl=cfg.arwl)
        nb = self.emit(jnp.where(addable, peer, -1)[None], self.typ("neighbor"),
                       dcid=self._my_dcid_for(row2, peer))
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        return row2, self.merge(nb, dc, fj)

    def handle_forward_join(self, cfg, me, row: HvState, m: Msgs, key):
        joiner, ttl, sender = m.data["joiner"], m.data["ttl"], m.src
        not_me = joiner != me
        accept = ((ttl <= 0) | (ps.size(row.active) <= 1)) & not_me
        addable = joiner >= 0  # walks carry no dcid echo; permissive add
        do_add = accept & addable
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(do_add, joiner, -1), key)
        nb = self.emit(jnp.where(do_add, joiner, -1)[None],
                       self.typ("neighbor"),
                       dcid=self._my_dcid_for(row2, joiner))
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        # passive add at TTL == PRWL (:859-866; inert when ARWL < PRWL)
        at_prwl = (~accept) & (ttl == cfg.prwl) & not_me
        row3 = jax.tree_util.tree_map(
            lambda a, b: jnp.where(at_prwl, a, b),
            self._add_passive(cfg, me, row2, joiner,
                              prng.decision_key(key, 3)), row2)
        # else: keep walking to a random active peer != sender/joiner/me
        nxt = ps.random_member(row3.active, prng.decision_key(key, 4),
                               exclude=jnp.stack([sender, joiner, me]))
        walk_on = (~accept) & not_me & (nxt >= 0)
        fj = self.emit(jnp.where(walk_on, nxt, -1)[None],
                       self.typ("forward_join"),
                       joiner=joiner, ttl=jnp.maximum(ttl - 1, 0))
        # dead-end walk (no eligible next hop): accept locally (:819-854)
        dead_end = (~accept) & not_me & (nxt < 0)
        row4, dc_dst2, dc_id2 = self._add_active(
            cfg, me, row3, jnp.where(dead_end, joiner, -1),
            prng.decision_key(key, 5))
        nb2 = self.emit(jnp.where(dead_end, joiner, -1)[None],
                        self.typ("neighbor"),
                        dcid=self._my_dcid_for(row4, joiner))
        dc2 = self.emit(dc_dst2[None], self.typ("disconnect"), id=dc_id2)
        return row4, self.merge(nb, dc, fj, nb2, dc2)

    def handle_neighbor(self, cfg, me, row: HvState, m: Msgs, key):
        peer = m.src
        addable = self._is_addable(row, peer, m.data["dcid"])
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(addable, peer, -1), key)
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        return row2, dc

    def handle_disconnect(self, cfg, me, row: HvState, m: Msgs, key):
        peer, mid = m.src, m.data["id"]
        last = _dc_get(row.recv_dc_peer, row.recv_dc_id, peer)
        valid = mid > last  # monotone id gate (is_valid_disconnect, :1622-1655)
        rp, ri, over = _dc_put(row.recv_dc_peer, row.recv_dc_id,
                               jnp.where(valid, peer, -1), mid)
        row = row.replace(recv_dc_peer=rp, recv_dc_id=ri,
                          dc_overwrites=row.dc_overwrites
                          + over.astype(jnp.int32))
        row = row.replace(active=jnp.where(
            valid & (row.active == peer), -1, row.active))
        row = self._add_passive(cfg, me, row, jnp.where(valid, peer, -1), key)
        return row, self.no_emit()

    def handle_neighbor_request(self, cfg, me, row: HvState, m: Msgs, key):
        peer, prio = m.src, m.data["prio"]
        row = self._merge_exchange(cfg, me, row, m.data["sample"],
                                   prng.decision_key(key, 6))
        addable = self._is_addable(row, peer, m.data["dcid"])
        room = ps.size(row.active) < cfg.max_active_size
        accept = addable & ~row.left & ((prio == HIGH) | room)
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(accept, peer, -1), key)
        reply_t = jnp.where(accept, self.typ("neighbor_accepted"),
                            self.typ("neighbor_rejected"))
        sample = self._shuffle_sample(cfg, me, row2, prng.decision_key(key, 7))
        rep = self.emit(peer[None], reply_t, sample=sample,
                        dcid=self._my_dcid_for(row2, peer))
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        return row2, self.merge(rep, dc)

    def handle_neighbor_accepted(self, cfg, me, row: HvState, m: Msgs, key):
        peer = m.src
        addable = self._is_addable(row, peer, m.data["dcid"])
        row = self._merge_exchange(cfg, me, row, m.data["sample"],
                                   prng.decision_key(key, 8))
        row2, dc_dst, dc_id = self._add_active(
            cfg, me, row, jnp.where(addable, peer, -1), key)
        dc = self.emit(dc_dst[None], self.typ("disconnect"), id=dc_id)
        return row2, dc

    def handle_neighbor_rejected(self, cfg, me, row: HvState, m: Msgs, key):
        # the promotion timer will try another candidate (:1015-1046)
        return row, self.no_emit()

    def handle_shuffle(self, cfg, me, row: HvState, m: Msgs, key):
        origin, ttl, sender = m.data["origin"], m.data["ttl"], m.src
        nxt = ps.random_member(row.active, prng.decision_key(key, 9),
                               exclude=jnp.stack([origin, sender, me]))
        walk = (ttl > 0) & (nxt >= 0) & (origin != me)
        fwd = self.emit(jnp.where(walk, nxt, -1)[None], self.typ("shuffle"),
                        origin=origin, ttl=ttl - 1, sample=m.data["sample"])
        # accept: reply an equal-size passive sample to origin, merge theirs
        acc = ~walk & (origin != me)
        reply_sample = ps.random_k(row.passive, prng.decision_key(key, 10),
                                   self.S)
        rep = self.emit(jnp.where(acc, origin, -1)[None],
                        self.typ("shuffle_reply"), sample=reply_sample)
        row2 = self._merge_exchange(cfg, me, row, jnp.where(
            acc, m.data["sample"], -1), prng.decision_key(key, 11))
        return row2, self.merge(fwd, rep)

    def handle_shuffle_reply(self, cfg, me, row: HvState, m: Msgs, key):
        row = self._merge_exchange(cfg, me, row, m.data["sample"], key)
        return row, self.no_emit()

    def handle_keepalive(self, cfg, me, row: HvState, m: Msgs, key):
        """Active-link liveness (the TCP-keepalive / EXIT-prune analog,
        partisan_socket.erl:17-19 + pluggable :971-984).  A keepalive from a
        current active peer refreshes its slot TTL; one from a peer that
        believes we are ITS active neighbor but is not in ours re-adds it
        when there is room (no eviction — avoids repair cascades), healing
        one-sided edges left by dropped disconnects."""
        peer = m.src
        present = ps.contains(row.active, peer)
        row = self._reset_ttl(cfg, row, jnp.where(present, peer, -1))
        room = ps.size(row.active) < cfg.max_active_size
        readd = (~present) & room & self._is_addable(row, peer, m.data["dcid"])
        row2, _, _ = self._add_active(cfg, me, row,
                                      jnp.where(readd, peer, -1), key)
        return row2, self.no_emit()

    def handle_ctl_join(self, cfg, me, row: HvState, m: Msgs, key):
        """Remember the contact and send join; the tick re-sends while the
        active view is empty — the connection-retry loop of the reference
        (pluggable :944-969, 1 s tick) that makes join storms safe under
        inbox overflow."""
        peer = m.data["joiner"]
        row = row.replace(contact=jnp.where(peer == me, row.contact, peer),
                          left=jnp.where(peer == me, row.left, False))
        return row, self.emit(peer[None], self.typ("join"),
                              dcid=self._my_dcid_for(row, peer))

    def handle_ctl_leave(self, cfg, me, row: HvState, m: Msgs, key):
        """Graceful leave: disconnect every active peer and clear views."""
        new_id = (row.epoch << _EPOCH_SHIFT) | (row.dc_cnt & ((1 << _EPOCH_SHIFT) - 1))
        dc = self.emit(row.active, self.typ("disconnect"), id=new_id)
        row = row.replace(
            active=jnp.full_like(row.active, -1),
            passive=jnp.full_like(row.passive, -1),
            contact=jnp.full_like(row.contact, -1),
            left=jnp.ones_like(row.left),
            dc_cnt=row.dc_cnt + 1,
        )
        return row, dc

    def handle_ctl_reserve(self, cfg, me, row: HvState, m: Msgs, key):
        """reserve/1 (:398-411): register an open reserved slot for a
        tag; at most max_active_size reservations, duplicates no-op, and
        an over-capacity reserve is counted (the reference replies
        {error, no_available_slots})."""
        tag = m.data["tag"]
        present = jnp.any((row.rsv_tag == tag) & (tag >= 0))
        free = row.rsv_tag < 0
        has_free = jnp.any(free)
        do = (tag >= 0) & ~present & has_free
        slot = jnp.argmax(free)
        row = row.replace(
            rsv_tag=row.rsv_tag.at[slot].set(
                jnp.where(do, tag, row.rsv_tag[slot])),
            rsv_dropped=row.rsv_dropped
            + ((tag >= 0) & ~present & ~has_free).astype(jnp.int32))
        return row, self.no_emit()

    # ---------------------------------------------------- partition surface

    def _mark_partitions(self, row: HvState, ref: jax.Array) -> HvState:
        """Append (ref, peer) for every current active peer to the
        partition table (handle_partition_injection :1748-1772);
        duplicates skipped, overflow counted."""
        # trace-lint: allow(unroll-bomb): A (active view width) is a tiny static Config bound; dedup needs the sequential fold
        for j in range(row.active.shape[0]):
            p = row.active[j]
            dup = jnp.any((row.part_ref == ref) & (row.part_peer == p))
            want = (p >= 0) & (ref >= 0) & ~dup
            free = row.part_ref < 0
            has_free = jnp.any(free)
            slot = jnp.argmax(free)
            do = want & has_free
            row = row.replace(
                part_ref=row.part_ref.at[slot].set(
                    jnp.where(do, ref, row.part_ref[slot])),
                part_peer=row.part_peer.at[slot].set(
                    jnp.where(do, p, row.part_peer[slot])),
                part_dropped=row.part_dropped
                + (want & ~has_free).astype(jnp.int32))
        return row

    def handle_part_inject(self, cfg, me, row: HvState, m: Msgs, key):
        """Partition-injection flood (:1731-1772): mark every active
        neighbor partitioned under the reference id; while TTL > 0
        re-forward to the active view."""
        ref, ttl = m.data["pref"], m.data["ttl"]
        row = self._mark_partitions(row, ref)
        fwd = self.emit(jnp.where(ttl > 0, row.active, -1),
                        self.typ("part_inject"), pref=ref,
                        ttl=jnp.maximum(ttl - 1, 0))
        return row, fwd

    def handle_part_resolve(self, cfg, me, row: HvState, m: Msgs, key):
        """Resolution flood (:1773-1797): drop entries under the ref;
        only a node whose table CHANGED re-propagates (the flood's
        termination condition)."""
        ref = m.data["pref"]
        hit = (row.part_ref == ref) & (ref >= 0)
        changed = jnp.any(hit)
        row = row.replace(part_ref=jnp.where(hit, -1, row.part_ref),
                          part_peer=jnp.where(hit, -1, row.part_peer))
        fwd = self.emit(jnp.where(changed, row.active, -1),
                        self.typ("part_resolve"), pref=ref)
        return row, fwd

    def handle_ctl_part_inject(self, cfg, me, row: HvState, m: Msgs, key):
        """inject_partition(Origin, TTL) (:244-247): the origin marks its
        neighbors and starts the flood."""
        return self.handle_part_inject(cfg, me, row, m, key)

    def handle_ctl_part_resolve(self, cfg, me, row: HvState, m: Msgs, key):
        """resolve_partition(Reference) (:249-251)."""
        return self.handle_part_resolve(cfg, me, row, m, key)

    # host-side queries ----------------------------------------------------

    def partitions(self, state: HvState, node: int):
        """partitions/0 (:253-254): the node-visible partition set as
        (ref, peer) pairs."""
        import numpy as np
        refs = np.asarray(state.part_ref[node])
        peers = np.asarray(state.part_peer[node])
        return [(int(r), int(p)) for r, p in zip(refs, peers) if r >= 0]

    def reserved(self, state: HvState, node: int):
        """The reservation table as {tag: peer_or_None}."""
        import numpy as np
        tags = np.asarray(state.rsv_tag[node])
        peers = np.asarray(state.rsv_peer[node])
        return {int(t): (int(p) if p >= 0 else None)
                for t, p in zip(tags, peers) if t >= 0}

    # ------------------------------------------------------------------ timer

    def tick(self, cfg, me, row: HvState, rnd, key):
        # -- failure detection: age active slots; expired links are demoted
        #    to passive (the EXIT-prune path, pluggable :971-984, hyparview
        #    :609-654 — here triggered by keepalive silence, not socket death)
        occupied = row.active >= 0
        ttl = jnp.where(occupied, row.active_ttl - 1, 0)
        expired = occupied & (ttl <= 0)
        expired_peers = jnp.where(expired, row.active, -1)
        row = row.replace(active=jnp.where(expired, -1, row.active),
                          active_ttl=ttl)
        # trace-lint: allow(unroll-bomb): A slots, same tiny static bound and sequential _add_passive fold as _merge_exchange
        for j in range(expired_peers.shape[0]):
            row = self._add_passive(cfg, me, row, expired_peers[j],
                                    prng.decision_key(key, 40 + j))
        # staggered by node id: ~N/interval nodes fire per round, avoiding
        # the synchronized-storm artifact of a global phase
        stay = ~row.left
        shuffle_due = (((rnd + me) % cfg.shuffle_interval) == 0) & stay
        promo_due = (((rnd + me) % cfg.random_promotion_interval) == 0) & stay

        tgt = ps.random_member(row.active, prng.decision_key(key, 12))
        sample = self._shuffle_sample(cfg, me, row, key)
        sh = self.emit(jnp.where(shuffle_due, tgt, -1)[None],
                       self.typ("shuffle"), cap=self.tick_emit_cap,
                       origin=me, ttl=cfg.arwl, sample=sample)

        under = ps.size(row.active) < cfg.min_active_size
        cand = ps.random_member(row.passive, prng.decision_key(key, 13))
        prio = jnp.where(ps.size(row.active) == 0, HIGH, LOW)
        nr = self.emit(jnp.where(promo_due & under, cand, -1)[None],
                       self.typ("neighbor_request"), cap=self.tick_emit_cap,
                       prio=prio, sample=sample,
                       dcid=self._my_dcid_for(row, cand))

        # join retry until the CONTACT acknowledges (connection retry of
        # the pending set, pluggable :944-969 — pending clears on
        # `connected`, NOT on merely having some other active peer; gating
        # on an empty view lets a clique of storm-dropped joiners satisfy
        # each other and form a permanently disconnected island)
        row = row.replace(contact=jnp.where(
            ps.contains(row.active, row.contact), -1, row.contact))
        retry_due = (((rnd % cfg.connection_retry_interval) == 0) & stay
                     & (row.contact >= 0))
        jn = self.emit(jnp.where(retry_due, row.contact, -1)[None],
                       self.typ("join"), cap=self.tick_emit_cap,
                       dcid=self._my_dcid_for(row, row.contact))

        # keepalives to every active peer (failure-detection heartbeat)
        ka_due = ((rnd % cfg.keepalive_interval) == 0) & stay
        dcids = jax.vmap(lambda p: self._my_dcid_for(row, p))(row.active)
        ka = self.emit(jnp.where(ka_due, row.active, -1),
                       self.typ("keepalive"), cap=self.tick_emit_cap,
                       dcid=dcids)
        return row, self.merge(sh, nr, jn, ka, cap=self.tick_emit_cap)
