"""The Demers epidemic-protocol family — the reference's example workloads
(``protocols/demers_*.erl``, SURVEY §2.10) rebuilt as batched TPU programs.

Two tiers:

1. **Engine protocols** (`DirectMail`, `DirectMailAcked`, `AntiEntropy`) —
   run through the generic round engine for full interposition / trace /
   fault support at test scale, mirroring how the reference model-checks
   these modules.

2. **`RumorMongering` fast path** — the BASELINE #5 workload
   (protocols/demers_rumor_mongering.erl at 10^6 nodes, 1% churn/round).
   Rumor delivery is a commutative merge (infected |= any rumor arrived), so
   it uses the dense reduce path (ops/msg.reduce_to_nodes rationale): no
   sort, no per-slot loop — each round is two gathers + one scatter + PRNG,
   which is what makes >=1000 rounds/s at N=10^6 feasible.  Semantics follow
   demers_rumor_mongering.erl:39,89-145: FANOUT 2 (partisan.hrl:?FANOUT is 5
   for membership gossip; the rumor protocol uses its own fanout 2), dedup by
   message id (infected-once), re-forward to a random subset, and
   feedback-based loss of interest (a push to an already-infected peer kills
   the sender's interest with probability 1/stop_k).
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import bitset
from ..ops.bitset import mix32
from ..ops.msg import Msgs
from .. import prng
from .stack import UpperProtocol


# =========================================================================
# 1. Direct mail (demers_direct_mail.erl): broadcast = send to every member.
# =========================================================================

@struct.dataclass
class MailState:
    member: jax.Array    # [N, W] membership bitset (static full mesh here;
                         # composition with a live membership layer comes via
                         # the stack combinator, models/stack.py)
    seen: jax.Array      # [N, R] bool — rumor r delivered at node
    acked: jax.Array     # [N, R] int32 — acks received by the origin (acked
                         # variant, demers_direct_mail_acked.erl)


class DirectMail(ProtocolBase):
    """demers_direct_mail.erl:1-147 — reliable broadcast by sending the
    payload to every known member, used by `gossip_test`
    (test/partisan_SUITE.erl:1138)."""

    msg_types = ("mail", "ctl_broadcast")
    acked = False

    def __init__(self, cfg: Config, n_rumors: int = 4):
        self.cfg = cfg
        self.R = n_rumors
        self.data_spec: Dict = {"rumor": ((), jnp.int32),
                                "peer": ((), jnp.int32)}
        self.emit_cap = cfg.n_nodes
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> MailState:
        n, w = cfg.n_nodes, bitset.n_words(cfg.n_nodes)
        full = jnp.tile((~jnp.zeros((w,), jnp.uint32))[None], (n, 1))
        return MailState(
            member=full,
            seen=jnp.zeros((n, self.R), bool),
            acked=jnp.zeros((n, self.R), jnp.int32),
        )

    def _everyone_else(self, row: MailState, me) -> jax.Array:
        mask = bitset.to_mask(row.member, self.cfg.n_nodes)
        mask = mask & (jnp.arange(self.cfg.n_nodes) != me)
        idx, = jnp.nonzero(mask, size=self.emit_cap, fill_value=-1)
        return idx.astype(jnp.int32)

    def handle_ctl_broadcast(self, cfg, me, row: MailState, m: Msgs, key):
        r = m.data["rumor"]
        row = row.replace(seen=row.seen.at[r].set(True))
        return row, self.emit(self._everyone_else(row, me), self.typ("mail"),
                              rumor=r)

    def handle_mail(self, cfg, me, row: MailState, m: Msgs, key):
        r = m.data["rumor"]
        row = row.replace(seen=row.seen.at[r].set(True))
        return row, self.no_emit()


class DirectMailAcked(DirectMail):
    """demers_direct_mail_acked.erl — + per-recipient acks back to origin."""

    msg_types = ("mail", "ctl_broadcast", "ack")
    acked = True

    def handle_mail(self, cfg, me, row: MailState, m: Msgs, key):
        r = m.data["rumor"]
        row = row.replace(seen=row.seen.at[r].set(True))
        return row, self.emit(m.src[None], self.typ("ack"), rumor=r)

    def handle_ack(self, cfg, me, row: MailState, m: Msgs, key):
        r = m.data["rumor"]
        return row.replace(acked=row.acked.at[r].add(1)), self.no_emit()


class MailOverMembership(UpperProtocol):
    """demers_direct_mail as the reference actually runs it in
    ``gossip_test`` (test/partisan_SUITE.erl:1138): the protocol reads its
    peer set from the LIVE membership layer (`partisan:membership/0` at
    broadcast time, demers_direct_mail.erl:94-117) instead of a static
    mesh — joins and leaves between broadcasts change delivery.  Stack it
    over FullMembership with models/stack.Stacked."""

    msg_types = ("mail", "ctl_broadcast")

    def __init__(self, cfg: Config, n_rumors: int = 4):
        self.cfg = cfg
        self.R = n_rumors
        self.data_spec: Dict = {"rumor": ((), jnp.int32)}
        self.emit_cap = cfg.n_nodes
        self.tick_emit_cap = 1

    def init_upper(self, cfg: Config, key: jax.Array):
        return jnp.zeros((cfg.n_nodes, self.R), bool)  # seen

    def handle_ctl_broadcast(self, cfg, me, row, m: Msgs, key):
        r = jnp.clip(m.data["rumor"], 0, self.R - 1)
        peers = self.active_peers(row)
        peers = jnp.where(peers == me, -1, peers)
        seen = row.upper.at[r].set(True)
        return self.up(row, seen), self.emit(peers, self.typ("mail"),
                                             rumor=r)

    def handle_mail(self, cfg, me, row, m: Msgs, key):
        r = jnp.clip(m.data["rumor"], 0, self.R - 1)
        return self.up(row, row.upper.at[r].set(True)), self.no_emit()

    def tick_upper(self, cfg, me, row, rnd, key):
        return row, self.no_emit(self.tick_emit_cap)


# =========================================================================
# 2. Anti-entropy (demers_anti_entropy.erl:115-184): periodic push-pull
#    digest exchange with one random partner.
# =========================================================================

@struct.dataclass
class AeState:
    seen: jax.Array      # [N, R] bool


class AntiEntropy(ProtocolBase):
    """Push-pull: each periodic tick, pick a uniform random peer and push my
    digest; the peer merges and pushes back what it has (pull half)."""

    msg_types = ("push", "pull_reply", "ctl_broadcast")

    def __init__(self, cfg: Config, n_rumors: int = 4):
        self.cfg = cfg
        self.R = n_rumors
        self.data_spec: Dict = {"digest": ((n_rumors,), jnp.int32),
                                "rumor": ((), jnp.int32),
                                "peer": ((), jnp.int32)}
        self.emit_cap = 2
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> AeState:
        return AeState(seen=jnp.zeros((cfg.n_nodes, self.R), bool))

    def handle_ctl_broadcast(self, cfg, me, row, m, key):
        return row.replace(seen=row.seen.at[m.data["rumor"]].set(True)), \
            self.no_emit()

    def handle_push(self, cfg, me, row: AeState, m: Msgs, key):
        theirs = m.data["digest"] > 0
        merged = row.seen | theirs
        # pull half: reply with what I have (they merge symmetrically)
        rep = self.emit(m.src[None], self.typ("pull_reply"),
                        digest=merged.astype(jnp.int32))
        return row.replace(seen=merged), rep

    def handle_pull_reply(self, cfg, me, row: AeState, m: Msgs, key):
        return row.replace(seen=row.seen | (m.data["digest"] > 0)), \
            self.no_emit()

    def tick(self, cfg, me, row: AeState, rnd, key):
        due = ((rnd + me) % cfg.periodic_interval) == 0
        peer = jax.random.randint(key, (), 0, cfg.n_nodes)
        peer = jnp.where(peer == me, (peer + 1) % cfg.n_nodes, peer)
        em = self.emit(jnp.where(due, peer, -1)[None], self.typ("push"),
                       cap=self.tick_emit_cap,
                       digest=row.seen.astype(jnp.int32))
        return row, em


# =========================================================================
# 3. Rumor mongering fast path (BASELINE #5, 10^6 nodes, 1%/round churn).
# =========================================================================

class RumorWorld(NamedTuple):
    infected: jax.Array   # [N] bool — has the rumor (dedup by id == once)
    hot: jax.Array        # [N] bool — still actively spreading
    alive: jax.Array      # [N] bool — churn: dead rows lose state
    rnd: jax.Array        # scalar int32


def rumor_init(n: int, patient_zero: int = 0) -> RumorWorld:
    infected = jnp.zeros((n,), bool).at[patient_zero].set(True)
    return RumorWorld(
        infected=infected,
        hot=infected,
        alive=jnp.ones((n,), bool),
        rnd=jnp.int32(0),
    )


def make_rumor_step(n: int, fanout: int = 2, stop_k: int = 1,
                    churn: float = 0.0, seed: int = 1,
                    variant: str = "shift"):
    """One fused rumor-mongering round.

    emit:    every hot & alive node pushes to `fanout` random targets
    route:   commutative infection merge (dedup-by-id == infect-once)
    feedback: a sender whose (first) target was already infected loses
             interest with probability 1/stop_k
             (the Demers feedback/coin-death variant)
    churn:   each round, `churn` fraction of rows are replaced by fresh
             (uninfected, susceptible) nodes — re-randomizing rows is the
             TPU-native churn model (SURVEY §5.3)

    Two routing variants:

    * ``"shift"`` (default, the TPU-native path): targets are
      ``(i + s_j) mod N`` for ``fanout`` fresh uniform shifts per round —
      push delivery becomes ``jnp.roll`` (streaming, HBM-bandwidth-bound),
      because arbitrary-index gather/scatter of 2M indices serializes on
      the TPU (~25 ms measured vs ~50 us for the rolls).  Per round each
      hot node still contacts ``fanout`` uniformly distributed partners;
      partner choices are correlated *within* a round (a random f-regular
      circulant instead of f independent draws), which leaves the epidemic
      macro-dynamics — growth rate, coverage, endemic churn equilibrium —
      statistically indistinguishable (asserted by the variant-parity
      test).
    * ``"uniform"``: exact per-node independent uniform targets via
      gather/scatter — the literal transcription of
      demers_rumor_mongering.erl:89-145 for fidelity runs at small N.
    """
    base = jax.random.PRNGKey(seed)

    def route_uniform(k_tgt, w, send):
        # uniform over all peers EXCLUDING self (the reference removes
        # MyNode from the candidate set, demers_rumor_mongering.erl:104)
        offs = jax.random.randint(k_tgt, (n, fanout), 1, n)
        targets = (jnp.arange(n)[:, None] + offs) % n  # [N, F]
        tflat = targets.reshape(-1)
        sflat = jnp.repeat(send, fanout)
        hit = sflat & w.alive[tflat]
        new_infected = w.infected.at[tflat].max(hit)
        dup = w.infected[targets[:, 0]] & send
        return new_infected, dup

    def route_shift(k_tgt, w, send):
        shifts = jax.random.randint(k_tgt, (fanout,), 1, n)
        hit = jnp.zeros_like(send)
        for j in range(fanout):  # static unroll, fanout is tiny
            hit = hit | jnp.roll(send, shifts[j])
        new_infected = w.infected | (hit & w.alive)
        # sender i's first target is (i + shifts[0]) mod n
        dup = jnp.roll(w.infected, -shifts[0]) & send
        return new_infected, dup

    if variant not in ("shift", "uniform"):
        raise ValueError(f"unknown rumor routing variant: {variant!r}")
    route = route_shift if variant == "shift" else route_uniform

    def step(w: RumorWorld, _):
        k = jax.random.fold_in(base, w.rnd)
        k_tgt, k_coin, k_churn = jax.random.split(k, 3)

        send = w.hot & w.alive
        new_infected, dup = route(k_tgt, w, send)
        newly = new_infected & ~w.infected
        new_hot = w.hot | newly

        # Per-node Bernoulli masks (feedback coin, churn) come from a
        # salted splitmix finalizer over the node index instead of a bulk
        # threefry draw: threefry at [N] lanes was the single heaviest op
        # of the round (~20% at N=1e6), while the hash is a handful of
        # VPU multiplies.  The salt is one scalar threefry draw per round,
        # so rounds stay independent; quantization is m/2^32.
        iota = jnp.arange(n, dtype=jnp.uint32)

        def bernoulli_hash(key, p):
            salt = jax.random.bits(key, (), jnp.uint32)
            thresh = jnp.uint32(min(max(1, round(p * 4294967296)),
                                    4294967295))
            return mix32(iota ^ salt) < thresh

        # -- feedback: pushing to an already-infected peer kills interest
        #    w.p. 1/stop_k (evaluated on the first lane, as one push-ack);
        #    stop_k == 1 is a sure coin — no draw needed
        if stop_k <= 1:
            new_hot = new_hot & ~dup
        else:
            coin = bernoulli_hash(k_coin, 1.0 / stop_k)
            new_hot = new_hot & ~(dup & coin)

        # -- churn: replace a fraction of rows with fresh susceptible nodes
        if churn > 0.0:
            reborn = bernoulli_hash(k_churn, churn)
            new_infected = new_infected & ~reborn
            new_hot = new_hot & ~reborn

        # -- sustained gossip: when the current rumor burns out (feedback
        #    killed every hot sender, or churn erased it), a NEW rumor
        #    starts at a random node — the workload is continuous rounds of
        #    epidemic dissemination, not a single one-shot broadcast
        dead = ~jnp.any(new_hot & w.alive)
        k_pz = jax.random.fold_in(k, 7)
        pz = jax.random.randint(k_pz, (), 0, n)
        new_infected = new_infected.at[pz].set(new_infected[pz] | dead)
        new_hot = new_hot.at[pz].set(new_hot[pz] | dead)

        w2 = RumorWorld(infected=new_infected, hot=new_hot,
                        alive=w.alive, rnd=w.rnd + 1)
        return w2, None

    return step


class RumorWorldPacked(NamedTuple):
    infected: jax.Array   # [N/32] uint32 bitset
    hot: jax.Array        # [N/32] uint32
    alive: jax.Array      # [N/32] uint32
    rnd: jax.Array        # scalar int32


def rumor_pack(w: RumorWorld) -> RumorWorldPacked:
    return RumorWorldPacked(
        infected=bitset.from_mask(w.infected),
        hot=bitset.from_mask(w.hot),
        alive=bitset.from_mask(w.alive), rnd=w.rnd)


def rumor_unpack(w: RumorWorldPacked, n: int) -> RumorWorld:
    return RumorWorld(
        infected=bitset.to_mask(w.infected, n),
        hot=bitset.to_mask(w.hot, n),
        alive=bitset.to_mask(w.alive, n), rnd=w.rnd)


def make_rumor_step_packed(n: int, fanout: int = 2, stop_k: int = 1,
                           churn: float = 0.0, seed: int = 1):
    """The ``"shift"`` round on uint32-packed bitsets: 32x less HBM
    traffic (the shift variant is bandwidth/launch-overhead-bound at
    N >= 10^6) with identical epidemic dynamics.  Rolls become word-rolls
    with bit carries (bitset.roll_bits); Bernoulli masks come packed from
    bitset.biased_bits.  With stop_k == 1 and churn == 0 the trajectory
    is BIT-IDENTICAL to the unpacked shift variant (same threefry draws);
    the packed Bernoulli generator quantizes p slightly differently, so
    churn/coin runs match distributionally instead (variant-parity test).
    """
    assert n % bitset.WORD == 0, "packed rumor wants n % 32 == 0"
    W = n // bitset.WORD
    base = jax.random.PRNGKey(seed)

    def step(w: RumorWorldPacked, _):
        k = jax.random.fold_in(base, w.rnd)
        k_tgt, k_coin, k_churn = jax.random.split(k, 3)

        send = w.hot & w.alive
        shifts = jax.random.randint(k_tgt, (fanout,), 1, n)
        hit = jnp.zeros_like(send)
        for j in range(fanout):
            hit = hit | bitset.roll_bits(send, shifts[j], n)
        new_infected = w.infected | (hit & w.alive)
        dup = bitset.roll_bits(w.infected, n - shifts[0], n) & send
        newly = new_infected & ~w.infected
        new_hot = w.hot | newly

        if stop_k <= 1:
            new_hot = new_hot & ~dup
        else:
            coin = bitset.biased_bits(k_coin, 1.0 / stop_k, W)
            new_hot = new_hot & ~(dup & coin)

        if churn > 0.0:
            reborn = bitset.biased_bits(k_churn, churn, W)
            new_infected = new_infected & ~reborn
            new_hot = new_hot & ~reborn

        dead = ~jnp.any((new_hot & w.alive) != 0)
        k_pz = jax.random.fold_in(k, 7)
        pz = jax.random.randint(k_pz, (), 0, n)
        wi, bi = pz // bitset.WORD, jnp.uint32(pz % bitset.WORD)
        bit = jnp.where(dead, jnp.uint32(1) << bi, jnp.uint32(0))
        new_infected = new_infected.at[wi].set(new_infected[wi] | bit)
        new_hot = new_hot.at[wi].set(new_hot[wi] | bit)

        return RumorWorldPacked(infected=new_infected, hot=new_hot,
                                alive=w.alive, rnd=w.rnd + 1), None

    return step


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6))
def rumor_run(w: RumorWorld, n_rounds: int, n: int, fanout: int = 2,
              stop_k: int = 1, churn: float = 0.0,
              variant: str = "shift") -> RumorWorld:
    """n_rounds of rumor mongering fully on device (lax.scan), or — for
    ``variant="pallas"`` — in a single fused kernel launch
    (ops/rumor_kernel.py; TPU only, n must be a multiple of 4096)."""
    if variant == "pallas":
        from ..ops.rumor_kernel import rumor_run_fused
        out = rumor_run_fused(rumor_pack(w), n_rounds, n, fanout,
                              stop_k, churn)
        return rumor_unpack(out, n)
    if variant == "packed":
        step = make_rumor_step_packed(n, fanout, stop_k, churn)
        out, _ = jax.lax.scan(step, rumor_pack(w), None, length=n_rounds)
        return rumor_unpack(out, n)
    step = make_rumor_step(n, fanout, stop_k, churn, variant=variant)
    out, _ = jax.lax.scan(step, w, None, length=n_rounds)
    return out
