"""Protocol stacking — running a dissemination protocol over a membership
protocol in one fused step.

In the reference, Plumtree is a separate gen_server that asks the manager for
peers (``Manager:cast_message`` / ``broadcast_members``,
src/partisan_plumtree_broadcast.erl:633-638) — processes compose at runtime.
The TPU-native composition is *static*: :class:`Stacked` fuses a lower
(membership) protocol and an upper (dissemination) protocol into ONE handler
table and ONE state pytree, so a round of the combined system is still a
single jitted step with no cross-protocol host hops.

Contract:
  * combined wire tags = lower.msg_types ++ upper.msg_types (upper handler
    ``typ()`` lookups are offset automatically);
  * payload specs are unioned (same-name fields must agree);
  * lower handlers see only their own state (``row.lower``);
  * upper handlers see the WHOLE row — they may read lower state (e.g. the
    HyParView active view as the broadcast peer set) but only write
    ``row.upper``.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase


@struct.dataclass
class StackState:
    lower: Any
    upper: Any


class UpperProtocol(ProtocolBase):
    """Base for protocols that ride on a membership layer.  Handlers receive
    the full StackState row; use `self.active_peers(row)` for the current
    peer set and return rows via `self.up(row, new_upper)`."""

    _lower_proto: "ProtocolBase | None" = None  # wired by Stacked.__init__

    def up(self, row: StackState, new_upper: Any) -> StackState:
        return row.replace(upper=new_upper)

    def active_peers(self, row: StackState) -> jax.Array:
        """Padded peer-id list from the lower layer: a partial-view manager
        exposes its active view directly; otherwise the lower protocol's
        own member_mask is the source of truth (so its semantics — e.g.
        eviction handling — propagate to the broadcast layer).  Nested
        stacks unwrap to the innermost membership layer."""
        innermost = row.lower
        while isinstance(innermost, StackState):
            innermost = innermost.lower
        if hasattr(innermost, "active"):
            return innermost.active
        if self._lower_proto is not None:
            # member_mask expects the lower protocol's OWN state shape
            # (a Stacked lower takes the StackState, not the unwrapped
            # innermost row)
            mask = self._lower_proto.member_mask(row.lower)
            idx, = jnp.nonzero(mask, size=self.emit_cap, fill_value=-1)
            return idx.astype(jnp.int32)
        raise NotImplementedError(
            "lower protocol exposes no peer set; override active_peers")


class Lifted(UpperProtocol):
    """Adapter: run a PLAIN ProtocolBase (one that neither reads nor
    writes membership state — e.g. qos.rpc.Rpc or the workload driver)
    as the upper layer of a :class:`Stacked`.  The inner protocol's
    handlers see only their own rows, so lifting is mechanical: copy the
    wire surface, delegate handlers/tick/init against ``row.upper``.

    This is what lets the ISSUE-8 load suite drive RPC traffic OVER a
    membership overlay (``Stacked(HyParView(cfg), Lifted(WorkloadRpc(
    cfg)))``) without teaching the driver about stacking."""

    def __init__(self, inner: ProtocolBase):
        assert not isinstance(inner, (Stacked, UpperProtocol)), (
            "Lifted wraps a plain ProtocolBase (nest Stacked on the "
            "lower side instead)")
        self.inner = inner
        self.msg_types = tuple(inner.msg_types)
        self.data_spec = dict(inner.data_spec)
        self.emit_cap = inner.emit_cap
        self.tick_emit_cap = inner.tick_emit_cap
        self.ctl_peer_field = inner.ctl_peer_field
        self.autotune_emit_hint = inner.autotune_emit_hint
        for t in self.msg_types:
            setattr(self, "handle_" + t, self._lift(
                getattr(inner, "handle_" + t)))

    @staticmethod
    def _lift(h):
        def f(cfg, me, row: StackState, m, key):
            up, em = h(cfg, me, row.upper, m, key)
            return row.replace(upper=up), em
        return f

    def _rewire(self, spec, emit_cap, offset) -> None:
        super()._rewire(spec, emit_cap, offset)
        self.inner._rewire(spec, emit_cap, offset)

    def init_upper(self, cfg: Config, key: jax.Array):
        return self.inner.init(cfg, key)

    def tick_upper(self, cfg, me, row: StackState, rnd, key):
        up, em = self.inner.tick(cfg, me, row.upper, rnd, key)
        return row.replace(upper=up), em

    # Stacked hands the upper layer state.upper for both counter taps,
    # which is exactly the inner protocol's own state — pure delegation.
    def health_counters(self, state):
        return self.inner.health_counters(state)

    @property
    def round_counter_names(self) -> Tuple[str, ...]:
        return tuple(self.inner.round_counter_names)

    def round_counters(self, state):
        return self.inner.round_counters(state)

    @property
    def actuator_names(self) -> Tuple[str, ...]:
        return tuple(self.inner.actuator_names)

    def apply_setpoints(self, cfg, state, values):
        return self.inner.apply_setpoints(cfg, state, values)

    def trace_taps(self, cfg, pre, mid, post, rnd):
        # Stacked hands the upper layer its .upper slices (same contract
        # as the counter taps above) — pure delegation
        return self.inner.trace_taps(cfg, pre, mid, post, rnd)


class Stacked(ProtocolBase):
    def __init__(self, lower: ProtocolBase, upper: UpperProtocol):
        # nesting is supported on the LOWER side only: handlers(), init and
        # tick build the upper via its handle_*/init_upper/tick_upper
        # attributes, which a Stacked does not expose.  Stacked(a,
        # Stacked(b, c)) is always expressible as Stacked(Stacked(a, b), c).
        assert isinstance(upper, UpperProtocol), (
            "upper operand must be a plain UpperProtocol (nest on the "
            "lower side: Stacked(Stacked(lower, mid), upper))")
        self.lower, self.upper = lower, upper
        self.msg_types = tuple(lower.msg_types) + tuple(upper.msg_types)
        spec = dict(lower.data_spec)
        for k, v in upper.data_spec.items():
            if k in spec and spec[k] != v:
                raise ValueError(f"data field collision with different "
                                 f"specs: {k}: {spec[k]} vs {v}")
            spec[k] = v
        self.data_spec = spec
        self.emit_cap = max(lower.emit_cap, upper.emit_cap)
        self.tick_emit_cap = lower.tick_emit_cap + upper.tick_emit_cap
        self.ctl_peer_field = lower.ctl_peer_field
        # sum, not max, for the same reason tick_emit_cap sums: during a
        # lower-layer burst (e.g. SCAMP's join storm) a max-sized budget
        # would let the lower layer consume every slot and starve the
        # upper layer's same-round emissions
        self.autotune_emit_hint = \
            lower.autotune_emit_hint + upper.autotune_emit_hint
        # rewire both sub-protocols to emit in the stacked message space
        # (recursively: a lower that is itself a Stacked propagates the
        # unioned spec/caps down to ITS sub-protocols, so three-layer
        # stacks emit structurally identical Msgs)
        for sub, off in ((lower, 0), (upper, len(lower.msg_types))):
            sub._rewire(spec, self.emit_cap, off)
        upper._lower_proto = lower

    def typ(self, name: str) -> int:
        return self.msg_types.index(name) + getattr(self, "_typ_offset", 0)

    def _rewire(self, spec, emit_cap, offset) -> None:
        self._typ_offset = offset
        self.data_spec = spec
        self.emit_cap = emit_cap
        for sub, off in ((self.lower, offset),
                         (self.upper, offset + len(self.lower.msg_types))):
            sub._rewire(spec, emit_cap, off)

    def handlers(self) -> Tuple:
        def wrap_lower(h):
            def f(cfg, me, row, m, key):
                lrow, em = h(cfg, me, row.lower, m, key)
                return row.replace(lower=lrow), em
            return f

        # go through handlers() (not getattr) so a lower that is itself a
        # Stacked contributes its already-wrapped table — nesting works
        lows = tuple(wrap_lower(h) for h in self.lower.handlers())
        ups = tuple(getattr(self.upper, "handle_" + t)
                    for t in self.upper.msg_types)
        return lows + ups

    def init(self, cfg: Config, key: jax.Array) -> StackState:
        k1, k2 = jax.random.split(key)
        return StackState(lower=self.lower.init(cfg, k1),
                          upper=self.upper.init_upper(cfg, k2))

    def tick(self, cfg, me, row: StackState, rnd, key):
        k1, k2 = jax.random.split(key)
        lrow, lem = self.lower.tick(cfg, me, row.lower, rnd, k1)
        row = row.replace(lower=lrow)
        row, uem = self.upper.tick_upper(cfg, me, row, rnd, k2)
        return row, self.merge(lem, uem, cap=self.tick_emit_cap)

    def member_mask(self, row: StackState) -> jax.Array:
        return self.lower.member_mask(row.lower)

    def health_counters(self, state: StackState):
        out = dict(self.lower.health_counters(state.lower))
        out.update(self.upper.health_counters(state.upper))
        return out

    @property
    def round_counter_names(self) -> Tuple[str, ...]:
        return (tuple(self.lower.round_counter_names)
                + tuple(self.upper.round_counter_names))

    def round_counters(self, state: StackState):
        out = dict(self.lower.round_counters(state.lower))
        out.update(self.upper.round_counters(state.upper))
        return out

    @property
    def actuator_names(self) -> Tuple[str, ...]:
        return (tuple(self.lower.actuator_names)
                + tuple(self.upper.actuator_names))

    def apply_setpoints(self, cfg, state: StackState, values):
        # route each layer only the setpoints it declared — mirrors the
        # round_counters merge, but split instead of unioned
        low_names = set(self.lower.actuator_names)
        up_names = set(self.upper.actuator_names)
        low_vals = {k: v for k, v in values.items() if k in low_names}
        up_vals = {k: v for k, v in values.items() if k in up_names}
        lower = state.lower
        upper = state.upper
        if low_vals:
            lower = self.lower.apply_setpoints(cfg, lower, low_vals)
        if up_vals:
            upper = self.upper.apply_setpoints(cfg, upper, up_vals)
        return state.replace(lower=lower, upper=upper)

    def trace_taps(self, cfg, pre, mid, post, rnd):
        # each layer diffs its own state slices (the health_counters
        # split); event-name tuples concatenate lower-first
        return (tuple(self.lower.trace_taps(
                    cfg, pre.lower, mid.lower, post.lower, rnd))
                + tuple(self.upper.trace_taps(
                    cfg, pre.upper, mid.upper, post.upper, rnd)))
