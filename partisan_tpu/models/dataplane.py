"""Application data plane — ``forward_message`` / ``receive_message`` over
the simulated overlay.

This is the TPU-native rebuild of the manager's data hot path: the
reference's ``forward_message(Name, Channel, ServerRef, Msg, Opts)``
pipeline (src/partisan_pluggable_peer_service_manager.erl:183-248) ending
in ``partisan_util:process_forward/2`` delivery to a registered process
(src/partisan_util.erl:385-484), plus the acknowledgement path (store on
send, ack on receive, retransmit timer — pluggable :737-741, 810-816,
905-942 over src/partisan_acknowledgement_backend.erl).

Design: a :class:`DataPlane` rides on ANY membership manager via
:class:`~partisan_tpu.models.stack.Stacked`, so app messages traverse the
same engine round as protocol traffic — same router, same fault masks,
same interposition hooks, same channels/lanes.  Per node:

  * a **receive store** — the ``store_proc`` analog of the reference test
    harness (test/partisan_support.erl:325-333; the `check_forward_message`
    contract, test/partisan_SUITE.erl:1955): a fixed ring of the last ``S``
    delivered (src, server_ref, payload) records plus a monotone
    ``recv_count``, so a host-side poller drains increments and *counts*
    anything overwritten between polls (never silent);
  * an **outstanding ring** for ack-requested sends (the `with_ack` suite
    group): unacked messages re-emit every ``cfg.retransmit_interval``
    rounds — at-least-once, exactly the reference's semantics.

``server_ref`` is an integer registered-name id (names live host-side
only, SURVEY §5.6); payloads are fixed-width int32 vectors.  The
``partition_key`` field uses fill -1 = unkeyed (random lane), matching
dispatch_pid's "no key -> random pick" (partisan_util.erl:142-201).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config import Config
from ..ops.msg import Msgs
from ..qos.ack import backoff_kw, retransmit_backoff
from ..ops import padded_set as ps
from ..ops import ring
from .. import prng
from .stack import StackState, UpperProtocol


@struct.dataclass
class DataRow:
    # receive store ring (store_proc)
    st_src: jax.Array      # [N, S] sender of each stored record
    st_ref: jax.Array      # [N, S] server_ref of each stored record
    st_pay: jax.Array      # [N, S, P] payload words
    recv_count: jax.Array  # [N] monotone delivery counter (ring head)
    # outstanding ring for ack-requested sends
    out_valid: jax.Array   # [N, R]
    out_dst: jax.Array     # [N, R]
    out_ref: jax.Array     # [N, R]
    out_pay: jax.Array     # [N, R, P]
    out_seq: jax.Array     # [N, R] message clock (pluggable :687)
    out_age: jax.Array     # [N, R] rounds since (re)transmission
    out_chan: jax.Array    # [N, R] original channel — retransmits reuse
    out_pk: jax.Array      # [N, R] original partition key (lane affinity)
    out_attempt: jax.Array  # [N, R] retransmissions fired (backoff plane)
    next_seq: jax.Array    # [N] monotone clock source (1-based; 0 = no ack)
    send_dropped: jax.Array  # [N] acked sends lost to a full ring (counted)
    dead_lettered: jax.Array  # [N] slots abandoned at the backoff give-up
                              # threshold (counted, never silent)
    relay_expired: jax.Array  # [N] relays dropped at TTL 0 / no next hop
                              # (the reference logs-and-drops, hyparview
                              # :1154-1157; here counted, never silent)
    # relay exactly-once plane: the reference's spanning-tree fan is
    # acyclic so a relayed message reaches its target once; the
    # partial-view fan here can reach it through several neighbors, so
    # each relay carries a per-source nonce and targets dedup against a
    # small seen-ring (overwritten entries make re-delivery possible
    # again — at-least-once, like every other ring here)
    relay_seq: jax.Array   # [N] monotone nonce source (1-based)
    seen_src: jax.Array    # [N, RS] origin of recently relay-delivered
    seen_nonce: jax.Array  # [N, RS] its nonce
    out_nonce: jax.Array   # [N, R] nonce pinned per outstanding slot so
                           # relayed RETRANSMITS dedup like originals


class DataPlane(UpperProtocol):
    """``ctl_fwd`` (host-injected at the SOURCE row) runs the send-side
    pipeline in-step; ``fwd`` delivers into the destination's store ring;
    ``fwd_ack`` clears the outstanding slot.  Retransmission rides
    ``tick_upper``.

    With ``cfg.broadcast=True``, sends to a destination OUTSIDE the lower
    layer's member view take the transitive relay path (``do_tree_forward``,
    src/partisan_pluggable_peer_service_manager.erl:1500-1539 + relay
    handling in the hyparview manager :1138-1163): the source fans a
    ``relay`` carrying the full message to its active peers with
    ``ttl = cfg.relay_ttl`` (?RELAY_TTL 5, partisan.hrl:9); each hop
    delivers directly when the target is in ITS view, else forwards to
    one random active peer with TTL-1.  The reference fans every hop over
    its (acyclic) spanning-tree out-links; a partial-view overlay has no
    global tree, so intermediate hops walk instead of fanning — same
    reachability, no exponential flood, and expiry is counted
    (``relay_expired``), never silent."""

    msg_types = ("fwd", "fwd_ack", "relay", "ctl_fwd")

    def __init__(self, cfg: Config, payload_words: int = 4,
                 store_cap: int = 32, ring_cap: int = 8):
        self.cfg = cfg
        self.P = payload_words
        self.S = store_cap
        self.R = ring_cap
        self.data_spec: Dict = {
            "peer": ((), jnp.int32),                 # ctl_fwd destination
            "server_ref": ((), jnp.int32),
            "payload": ((payload_words,), jnp.int32),
            "clock": ((), jnp.int32),                # 0 = no ack requested
            "ack": ((), jnp.int32),                  # ctl_fwd: request ack?
            "partition_key": ((), jnp.int32, -1),    # -1 = unkeyed
            # relay plumbing (only exercised when cfg.broadcast)
            "target": ((), jnp.int32, -1),           # final destination
            "origin": ((), jnp.int32),               # original sender (spec
            # shared with hyparview's shuffle originator — specs must agree
            # to stack, models/stack.py union rule)
            "ttl": ((), jnp.int32),
            "rnonce": ((), jnp.int32),               # relay dedup nonce
        }
        # send side fans a relay over the active view when the dst is
        # outside it; the fan width is the lower layer's view cap
        self.relay_fan = cfg.max_active_size if cfg.broadcast else 0
        self.emit_cap = max(1, self.relay_fan) + 1
        self.tick_emit_cap = ring_cap

    # ------------------------------------------------------------------ state

    def init_upper(self, cfg: Config, key: jax.Array) -> DataRow:
        n, S, R, P = cfg.n_nodes, self.S, self.R, self.P
        return DataRow(
            st_src=jnp.full((n, S), -1, jnp.int32),
            st_ref=jnp.zeros((n, S), jnp.int32),
            st_pay=jnp.zeros((n, S, P), jnp.int32),
            recv_count=jnp.zeros((n,), jnp.int32),
            out_valid=jnp.zeros((n, R), bool),
            out_dst=jnp.zeros((n, R), jnp.int32),
            out_ref=jnp.zeros((n, R), jnp.int32),
            out_pay=jnp.zeros((n, R, P), jnp.int32),
            out_seq=jnp.zeros((n, R), jnp.int32),
            out_age=jnp.zeros((n, R), jnp.int32),
            out_chan=jnp.zeros((n, R), jnp.int32),
            out_pk=jnp.full((n, R), -1, jnp.int32),
            out_attempt=jnp.zeros((n, R), jnp.int32),
            next_seq=jnp.ones((n,), jnp.int32),
            send_dropped=jnp.zeros((n,), jnp.int32),
            dead_lettered=jnp.zeros((n,), jnp.int32),
            relay_expired=jnp.zeros((n,), jnp.int32),
            relay_seq=jnp.ones((n,), jnp.int32),
            seen_src=jnp.full((n, 8), -1, jnp.int32),
            seen_nonce=jnp.zeros((n, 8), jnp.int32),
            out_nonce=jnp.zeros((n, R), jnp.int32),
        )

    # --------------------------------------------------------------- handlers

    def handle_ctl_fwd(self, cfg, me, row: StackState, m: Msgs, key):
        """Send side (pluggable forward_message :183-248): an acked send
        parks a copy in the outstanding ring stamped with the next message
        clock; the wire message carries the clock so the receiver can ack
        it.  An unacked send ships clock 0 (fire-and-forget fast path)."""
        up: DataRow = row.upper
        dst = m.data["peer"]
        want_ack = m.data["ack"] > 0
        ok, slot = ring.alloc(up.out_valid)
        stored = want_ack & ok
        seq = jnp.where(want_ack, up.next_seq, 0)
        wr = lambda a, v: ring.masked_set(a, slot, stored, v)
        up = up.replace(
            out_valid=wr(up.out_valid, True),
            out_dst=wr(up.out_dst, dst),
            out_ref=wr(up.out_ref, m.data["server_ref"]),
            out_pay=wr(up.out_pay, m.data["payload"]),
            out_seq=wr(up.out_seq, seq),
            out_age=wr(up.out_age, 0),
            out_chan=wr(up.out_chan, m.channel),
            out_pk=wr(up.out_pk, m.data["partition_key"]),
            out_attempt=wr(up.out_attempt, 0),
            next_seq=up.next_seq + want_ack.astype(jnp.int32),
            send_dropped=up.send_dropped
            + (want_ack & ~ok).astype(jnp.int32),
        )
        # an acked send that could not be stored is NOT shipped (it could
        # never be retransmitted); the drop is counted above
        ship = ~want_ack | stored
        wire_clock = jnp.where(stored, seq, 0)
        # trace-lint: allow(config-fork): unicast vs broadcast forwarding is a build-time protocol variant (with_broadcast suite rows)
        if not cfg.broadcast:
            em = self.emit(jnp.where(ship, dst, -1)[None], self.typ("fwd"),
                           channel=m.channel,
                           server_ref=m.data["server_ref"],
                           payload=m.data["payload"],
                           clock=wire_clock,
                           partition_key=m.data["partition_key"])
            return self.up(row, up), em
        # transitive relay (pluggable :1500-1539): a dst outside the
        # member view has no connection — fan a relay over the active view
        peers = self.active_peers(row)
        direct = jnp.any(peers == dst) | (dst == me)
        nonce = up.relay_seq
        up = up.replace(
            relay_seq=up.relay_seq + (ship & ~direct).astype(jnp.int32),
            out_nonce=ring.masked_set(up.out_nonce, slot,
                                      stored & ~direct, nonce))
        fw = self.emit(jnp.where(ship & direct, dst, -1)[None],
                       self.typ("fwd"), channel=m.channel,
                       server_ref=m.data["server_ref"],
                       payload=m.data["payload"], clock=wire_clock,
                       partition_key=m.data["partition_key"])
        rl = self.emit(jnp.where(ship & ~direct, peers, -1),
                       self.typ("relay"), cap=self.relay_fan,
                       channel=m.channel, target=dst, origin=me,
                       ttl=cfg.relay_ttl, rnonce=nonce,
                       server_ref=m.data["server_ref"],
                       payload=m.data["payload"], clock=wire_clock,
                       partition_key=m.data["partition_key"])
        return self.up(row, up), self.merge(fw, rl)

    def handle_fwd(self, cfg, me, row: StackState, m: Msgs, key):
        """Receive side: process_forward into the store ring (util
        :385-484) + send_acknowledgement when the clock asks for one
        (pluggable :1217-1227, 1612-1617)."""
        up: DataRow = row.upper
        slot = up.recv_count % self.S
        up = up.replace(
            st_src=up.st_src.at[slot].set(m.src),
            st_ref=up.st_ref.at[slot].set(m.data["server_ref"]),
            st_pay=up.st_pay.at[slot].set(m.data["payload"]),
            recv_count=up.recv_count + 1,
        )
        ack_dst = jnp.where(m.data["clock"] > 0, m.src, -1)
        em = self.emit(ack_dst[None], self.typ("fwd_ack"),
                       clock=m.data["clock"])
        return self.up(row, up), em

    def handle_fwd_ack(self, cfg, me, row: StackState, m: Msgs, key):
        up: DataRow = row.upper
        hit = up.out_valid & (up.out_seq == m.data["clock"])
        return self.up(row, up.replace(out_valid=up.out_valid & ~hit)), \
            self.no_emit()

    def handle_relay(self, cfg, me, row: StackState, m: Msgs, key):
        """relay hop (hyparview :1138-1163): target in my active view (or
        myself) -> deliver; else TTL walk to a random active peer.  The
        final hop stays a ``relay`` addressed AT the target so delivery
        records the ORIGIN as the message source, not the last hop (the
        reference relays the original message term for the same reason).
        Acks go straight back to the origin — they ride the direct route,
        whose failure the origin's retransmit timer already covers."""
        up: DataRow = row.upper
        target, ttl = m.data["target"], m.data["ttl"]
        origin, nonce = m.data["origin"], m.data["rnonce"]
        at_me = target == me
        # exactly-once across the redundant fan: copies of one relayed
        # send share (origin, nonce); a copy already delivered is still
        # ACKED (the original reached its destination) but not re-stored
        # nonce 0 = unnonced (a retransmit of an originally-direct send
        # whose dst later left the view): no dedup, at-least-once
        dup = (nonce > 0) & jnp.any(
            (up.seen_src == origin) & (up.seen_nonce == nonce))
        deliver = at_me & ~dup
        # local delivery into the store ring (src = origin)
        slot = up.recv_count % self.S
        st = lambda a, v: a.at[slot].set(jnp.where(deliver, v, a[slot]))
        sslot = up.recv_count % up.seen_src.shape[0]
        sn = lambda a, v: a.at[sslot].set(jnp.where(deliver, v, a[sslot]))
        up = up.replace(
            st_src=st(up.st_src, origin),
            st_ref=st(up.st_ref, m.data["server_ref"]),
            st_pay=st(up.st_pay, m.data["payload"]),
            recv_count=up.recv_count + deliver.astype(jnp.int32),
            seen_src=sn(up.seen_src, origin),
            seen_nonce=sn(up.seen_nonce, nonce),
        )
        ack = self.emit(
            jnp.where(at_me & (m.data["clock"] > 0),
                      m.data["origin"], -1)[None],
            self.typ("fwd_ack"), clock=m.data["clock"])
        # forward: direct when the target is a neighbor, else walk
        peers = self.active_peers(row)
        in_view = jnp.any(peers == target)
        nxt = ps.random_member(peers, prng.decision_key(key, 3),
                               exclude=jnp.stack(
                                   [m.src, me, m.data["origin"]]))
        can_walk = ~in_view & (ttl > 0) & (nxt >= 0)
        hop = jnp.where(in_view, target, jnp.where(can_walk, nxt, -1))
        expired = ~at_me & ~in_view & ~can_walk
        up = up.replace(relay_expired=up.relay_expired
                        + expired.astype(jnp.int32))
        fwd = self.emit(jnp.where(at_me, -1, hop)[None], self.typ("relay"),
                        channel=m.channel, target=target,
                        origin=m.data["origin"],
                        ttl=jnp.maximum(ttl - 1, 0),
                        server_ref=m.data["server_ref"],
                        payload=m.data["payload"], clock=m.data["clock"],
                        partition_key=m.data["partition_key"])
        return self.up(row, up), self.merge(ack, fwd)

    def tick_upper(self, cfg, me, row: StackState, rnd, key):
        """Retransmit timer (pluggable :905-942): re-emit every outstanding
        slot whose age reaches the interval — floored at the simulated
        round-trip (send -> deliver -> ack back = 2 rounds, +1 slack).
        The reference's 1 s timer never races its sub-millisecond ack
        RTT; without the floor every acked send would be delivered
        duplicate-per-round until its ack lands."""
        up: DataRow = row.upper
        valid, age, attempt, due, dead = retransmit_backoff(
            up.out_valid, up.out_age, up.out_attempt, me,
            **backoff_kw(cfg, base=max(cfg.retransmit_interval, 3)))
        up = up.replace(out_valid=valid, out_age=age,
                        out_attempt=attempt,
                        dead_lettered=up.dead_lettered + dead)
        row = self.up(row, up)
        # trace-lint: allow(config-fork): unicast vs broadcast retransmit path is the same build-time variant as handle_ctl_fwd's
        if not cfg.broadcast:
            em = self.emit(jnp.where(due, up.out_dst, -1), self.typ("fwd"),
                           cap=self.tick_emit_cap, channel=up.out_chan,
                           server_ref=up.out_ref, payload=up.out_pay,
                           clock=up.out_seq, partition_key=up.out_pk)
            return row, em
        # relay-aware retransmit (the reference's retransmit re-enters
        # forward_message, which itself tree-forwards when disconnected —
        # pluggable :905-942 over :1309-1363): a due slot whose dst left
        # the view re-enters the relay path through ONE random neighbor
        # per attempt (width stays R; the walk spreads across retries)
        peers = self.active_peers(row)
        direct = jax.vmap(lambda d: jnp.any(peers == d))(up.out_dst) \
            | (up.out_dst == me)
        hops = jax.vmap(lambda j: ps.random_member(
            peers, prng.decision_key(key, 100 + j)))(jnp.arange(self.R))
        dsts = jnp.where(direct, up.out_dst, hops)
        typs = jnp.where(direct, self.typ("fwd"), self.typ("relay"))
        em = self.emit(jnp.where(due & (dsts >= 0), dsts, -1), typs,
                       cap=self.tick_emit_cap, channel=up.out_chan,
                       server_ref=up.out_ref, payload=up.out_pay,
                       clock=up.out_seq, partition_key=up.out_pk,
                       target=up.out_dst, origin=me,
                       ttl=cfg.relay_ttl, rnonce=up.out_nonce)
        return row, em

    def health_counters(self, state: DataRow):
        return {"fwd_send_dropped": jnp.sum(state.send_dropped),
                "fwd_dead_lettered": jnp.sum(state.dead_lettered),
                "relay_expired": jnp.sum(state.relay_expired)}

    # ---------------------------------------------------------- host surface

    def pad_payload(self, payload) -> np.ndarray:
        """Host helper: int sequence -> fixed [P] int32 vector."""
        arr = np.zeros((self.P,), np.int32)
        vals = np.atleast_1d(np.asarray(payload, np.int32))
        assert vals.size <= self.P, \
            f"payload of {vals.size} words > payload_words={self.P}"
        arr[: vals.size] = vals
        return arr

    def received(self, upper: DataRow, node: int, cursor: int = 0,
                 ) -> Tuple[List[Tuple[int, int, List[int]]], int, int]:
        """Drain ``node``'s store ring from ``cursor`` (a previously
        returned position; 0 = from the beginning).  Returns
        ``(records, new_cursor, lost)`` where records are
        ``(src, server_ref, payload_words)`` in delivery order and
        ``lost`` counts records overwritten before this poll reached them
        (ring wrap — counted, never silent)."""
        head = int(np.asarray(upper.recv_count[node]))
        lost = max(0, (head - cursor) - self.S)
        start = max(cursor, head - self.S)
        recs = []
        src = np.asarray(upper.st_src[node])
        ref = np.asarray(upper.st_ref[node])
        pay = np.asarray(upper.st_pay[node])
        for c in range(start, head):
            s = c % self.S
            recs.append((int(src[s]), int(ref[s]),
                         [int(x) for x in pay[s]]))
        return recs, head, lost
