"""SCAMP (Scalable Membership Protocol) v1 & v2 — TPU-native rebuild of
``src/partisan_scamp_v1_membership_strategy.erl`` and
``src/partisan_scamp_v2_membership_strategy.erl``.

Both strategies keep a *partial view* whose expected size self-stabilizes to
O((c+1)·log N); v2 additionally tracks the *in-view* (who holds a
subscription to me), enabling graceful leave by rewiring.

Semantics mirrored (reference sites):
  * join (v1 :51-100, v2 :64-117): add contact to the partial view, send
    ``forward_subscription(me)`` to the contact, forward a subscription for
    the joiner to every existing partial-view member, plus ``c`` (v1) /
    ``c − 1`` (v2) extra copies to random members.
  * forward_subscription (v1 :213-252, v2 :284-327): keep with probability
    P = 1/(1 + |view|) if absent, else re-forward to one random member.
    The reference quantizes P to a biased coin — ``rand:uniform(10) >= 5``
    yields 1 w.p. 0.6, and the subscription is kept when the draw is 0, i.e.
    a *constant* keep probability of 0.4 independent of view size (SURVEY
    §2.4 calls out the fidelity bug).  ``cfg.scamp_exact_keep_probability``
    selects the paper's P (True, default) or the reference's 0.4 coin
    (False, behavioural parity).
  * keep_subscription (v2 :328-338): the keeper notifies the subject, which
    records the keeper in its in-view.
  * remove_subscription (v1 :191-212, v2 :261-283): remove + re-gossip to
    the pre-removal partial view.
  * leave / bootstrap_remove_subscription (v2 :192-238): only the departing
    node acts: in-view members 1..L−(c−1) get ``replace_subscription``
    (rewire their partial-view edge to one of my partial-view members,
    round-robin), the remainder get ``remove_subscription``; local state
    resets.  v1 leave (:102-124) just removes + gossips the removal.
  * periodic + isolation detection (v1 :126-172, v2 :130-178): ping all
    partial-view members every ``periodic_interval``; a node that received
    no ping for ``periodic_interval × scamp_message_window`` rounds
    considers itself isolated and re-subscribes via one random member.

Walk dynamics are one hop per round: a re-forwarded subscription is a fresh
message next round (SURVEY §7.3 "random walks").
"""

from __future__ import annotations

import math
from typing import Dict

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import padded_set as ps
from ..ops.msg import Msgs
from .. import prng


@struct.dataclass
class ScampState:
    partial: jax.Array       # [N, P] padded partial-view peer set
    in_view: jax.Array       # [N, P] padded in-view (v2; unused rows in v1)
    last_msg_rnd: jax.Array  # [N] round of last ping received (isolation)
    left: jax.Array          # [N] bool — departed, inert until rejoin


def default_view_cap(n_nodes: int, c: int) -> int:
    """Partial-view capacity: SCAMP converges to ~(c+1)·ln N subscriptions
    per node; double it for headroom (fixed shapes, SURVEY §7.3)."""
    return max(16, int(2 * (c + 1) * math.log(max(n_nodes, 2))))


class ScampV1(ProtocolBase):
    """v1: single membership set, no in-view, no graceful rewiring."""

    msg_types = ("subscription", "forward_subscription",
                 "remove_subscription", "ping", "ctl_join", "ctl_leave")
    version = 1

    def __init__(self, cfg: Config, view_cap: int | None = None):
        self.cfg = cfg
        self.P = view_cap or default_view_cap(cfg.n_nodes, cfg.scamp_c)
        self.data_spec: Dict = {
            "subject": ((), jnp.int32),      # the node a subscription is for
            "replacement": ((), jnp.int32),  # v2 rewiring target
            "peer": ((), jnp.int32),         # ctl verbs
        }
        # join fans to the whole partial view + c extra copies + 1 to contact
        self.emit_cap = self.P + cfg.scamp_c + 1
        # pings to the whole view + the (rare) isolation re-subscription
        # fan: sized so the tick merge is a pure concat — a compacting
        # merge would run an argsort per node per ROUND (the dominant
        # steady-state cost at N=1024, scripts/profile_engine.py)
        self.tick_emit_cap = self.P + 1 + self.emit_cap
        # autotune burst budget: a join-storm contact must re-forward
        # each staggered subscription to its whole partial view plus
        # c + 1 extra copies in the round it arrives (join, v2 :64-117)
        # — 8/round starves the walks and the overlay settles near a
        # star (measured: mean view 1.7 vs 2.5 uncapped at N=1024);
        # 32 preserves the view-size distribution at ~10x the uncapped
        # round rate
        self.autotune_emit_hint = 32

    # ------------------------------------------------------------------ state

    def init(self, cfg: Config, key: jax.Array) -> ScampState:
        n = cfg.n_nodes
        # partial view starts as {myself} (v1 init :43-49, v2 init :56-62);
        # self is implicit here (ids are rows), so the stored set is empty.
        return ScampState(
            partial=jnp.full((n, self.P), -1, jnp.int32),
            in_view=jnp.full((n, self.P), -1, jnp.int32),
            last_msg_rnd=jnp.zeros((n,), jnp.int32),
            left=jnp.zeros((n,), bool),
        )

    def member_mask(self, row: ScampState) -> jax.Array:
        n = self.cfg.n_nodes
        m = jnp.zeros((n,), bool)
        return m.at[jnp.clip(row.partial, 0, n - 1)].max(row.partial >= 0)

    # ------------------------------------------------------------- primitives

    def _keep_probability(self, row: ScampState) -> jax.Array:
        # trace-lint: allow(config-fork): exact-vs-quantized keep coin is a build-time reference-parity mode, both arms scalar
        if self.cfg.scamp_exact_keep_probability:
            return 1.0 / (1.0 + ps.size(row.partial).astype(jnp.float32))
        return jnp.float32(0.4)  # the reference's quantized coin (:352-360)

    def _forward_on(self, row: ScampState, subject, key, valid=True) -> Msgs:
        """Re-forward a subscription to ONE random partial-view member
        (select_random_sublist(State, 1)).  The subject itself is an eligible
        hop — the reference's view always contains self, so a walk landing on
        its own subject just bounces onward next round."""
        nxt = ps.random_member(row.partial, key)
        return self.emit(jnp.where(valid, nxt, -1)[None],
                         self.typ("forward_subscription"), subject=subject)

    # --------------------------------------------------------------- handlers

    def handle_forward_subscription(self, cfg, me, row: ScampState, m, key):
        """Keep w.p. P if the subject is new to me; otherwise re-forward the
        walk.  The reference never drops a walk outright — a node receiving
        its OWN subscription, or one it already holds, forwards another copy
        (its view always contains itself, so select_random_sublist is never
        empty; v1 :213-252).  Here self is implicit in the row encoding, so
        the walk dies only when the partial view is truly empty."""
        subject = m.data["subject"]
        alive = (subject >= 0) & ~row.left
        can_keep = alive & (subject != me) & ~ps.contains(row.partial, subject)
        coin = jax.random.uniform(prng.decision_key(key, 0), ())
        keep = can_keep & (coin < self._keep_probability(row))
        new_partial = ps.insert(row.partial, jnp.where(keep, subject, -1))
        row = row.replace(partial=new_partial)
        kp = self._keep_notify(me, subject, keep)
        fwd = self._forward_on(row, subject, prng.decision_key(key, 1),
                               valid=alive & ~keep)
        return row, self.merge(kp, fwd)

    def _keep_notify(self, me, subject, keep) -> Msgs:
        """v1 keeps silently; v2 overrides to notify the subject."""
        return self.no_emit(cap=1)

    def handle_remove_subscription(self, cfg, me, row: ScampState, m, key):
        node = m.data["subject"]
        present = ps.contains(row.partial, node) & (node != me)
        # gossip the removal to the pre-removal view (v1 :191-212)
        gossip = self.emit(jnp.where(present, row.partial, -1),
                           self.typ("remove_subscription"), subject=node)
        row = row.replace(partial=ps.remove(
            row.partial, jnp.where(present, node, -1)))
        return row, gossip

    def handle_ping(self, cfg, me, row: ScampState, m, key):
        # liveness only: remember when we last heard from anyone (:179-192);
        # the ping payload carries its send round in `subject`
        return row.replace(
            last_msg_rnd=jnp.maximum(row.last_msg_rnd, m.data["subject"])), \
            self.no_emit()

    def handle_subscription(self, cfg, me, row: ScampState, m, key):
        """A NEW subscription arriving at the contact node.

        Paper mode (`scamp_paper_fanout`): forward one copy to every
        partial-view member plus ``c`` extra copies to random members — the
        SCAMP subscription algorithm that sustains (c+1)·ln N views.  An
        empty-view contact keeps the subscription directly (first join).

        Reference mode: identical to a forward_subscription walk hop."""
        # trace-lint: allow(config-fork): paper-fanout vs walk-hop subscription is a build-time reference-parity mode
        if not cfg.scamp_paper_fanout:
            return self.handle_forward_subscription(cfg, me, row, m, key)
        subject = m.data["subject"]
        ok = (subject >= 0) & (subject != me) & ~row.left
        lonely = ps.size(row.partial) == 0
        keep = ok & lonely & ~ps.contains(row.partial, subject)
        row = row.replace(partial=ps.insert(
            row.partial, jnp.where(keep, subject, -1)))
        kp = self._keep_notify(me, subject, keep)
        fan = self.emit(jnp.where(ok & ~lonely, row.partial, -1),
                        self.typ("forward_subscription"), subject=subject)
        extras = ps.random_k(row.partial, prng.decision_key(key, 2),
                             self.cfg.scamp_c)
        ex = self.emit(jnp.where(ok & ~lonely, extras, -1),
                       self.typ("forward_subscription"), subject=subject)
        return row, self.merge(kp, fan, ex)

    def handle_ctl_join(self, cfg, me, row: ScampState, m, key):
        """join(contact): the joiner-side strategy callback (v1 :51-100):
        adopt the contact, announce my subscription to it, and fan the
        contact's subscription over my previous view ([myself] on a fresh
        node — those copies walk from here, v1 :65-95)."""
        contact = m.data["peer"]
        ok = (contact >= 0) & (contact != me)
        old_view = row.partial
        was_empty = ps.size(old_view) == 0
        row = row.replace(
            partial=ps.insert(row.partial, jnp.where(ok, contact, -1)),
            left=jnp.where(ok, False, row.left))
        # announce my subscription to the contact
        sub_me = self.emit(jnp.where(ok, contact, -1)[None],
                           self.typ("subscription"), subject=me)
        # forward the contact's subscription to everyone I already knew;
        # a fresh node's view is just [myself], which the reference models
        # as walk copies sent to self (fan 1 + sublist 1) — two self-hops
        fan = self.emit(jnp.where(ok, old_view, -1),
                        self.typ("forward_subscription"), subject=contact)
        extras = ps.random_k(old_view, prng.decision_key(key, 2),
                             self._extra_copies(cfg))
        ex = self.emit(jnp.where(ok, extras, -1),
                       self.typ("forward_subscription"), subject=contact)
        self_hops = self.emit(
            jnp.where(ok & was_empty, jnp.stack([me, me]), -1),
            self.typ("forward_subscription"), subject=contact)
        return row, self.merge(sub_me, fan, ex, self_hops)

    def _extra_copies(self, cfg: Config) -> int:
        return cfg.scamp_c  # v2 overrides with c − 1 (:64-117)

    def handle_ctl_leave(self, cfg, me, row: ScampState, m, key):
        """v1 leave (:102-124): drop + gossip removal (no rewiring)."""
        target = m.data["peer"]
        self_leave = target == me
        gossip = self.emit(row.partial, self.typ("remove_subscription"),
                           subject=target)
        row = row.replace(
            partial=jnp.where(self_leave, -1,
                              ps.remove(row.partial, target)),
            left=row.left | self_leave)
        return row, gossip

    # ------------------------------------------------------------------ timer

    def tick(self, cfg, me, row: ScampState, rnd, key):
        stay = ~row.left
        due = (((rnd + me) % cfg.periodic_interval) == 0) & stay
        pings = self.emit(jnp.where(due, row.partial, -1), self.typ("ping"),
                          cap=self.P, subject=rnd)
        silence = rnd - row.last_msg_rnd
        isolated = due & (silence > cfg.periodic_interval
                          * cfg.scamp_message_window)
        resub = self._forward_on(row, me, prng.decision_key(key, 3),
                                 valid=isolated)
        return row, self.merge(pings, resub, cap=self.tick_emit_cap)


class ScampV2(ScampV1):
    """v2: + in-view tracking (keep_subscription) and graceful leave by
    rewiring (bootstrap_remove / replace_subscription), scamp_v2 :46-49."""

    msg_types = ("subscription", "forward_subscription",
                 "remove_subscription", "ping",
                 "keep_subscription", "replace_subscription",
                 "bootstrap_remove_subscription",
                 "ctl_join", "ctl_leave")
    version = 2

    def _extra_copies(self, cfg: Config) -> int:
        return max(cfg.scamp_c - 1, 0)  # "important difference" (v2 :104)

    def _keep_notify(self, me, subject, keep) -> Msgs:
        """Tell the subject we kept its subscription so it can record us in
        its in-view (:314-321)."""
        return self.emit(jnp.where(keep, subject, -1)[None],
                         self.typ("keep_subscription"), cap=1)

    def handle_keep_subscription(self, cfg, me, row: ScampState, m, key):
        row = row.replace(in_view=ps.insert(row.in_view, m.src))
        return row, self.no_emit()

    def handle_replace_subscription(self, cfg, me, row: ScampState, m, key):
        """Rewire: partial-view entries == node become replacement
        (:239-260).  Skip when the replacement is already present or is me
        (padded sets are sets)."""
        node, repl = m.data["subject"], m.data["replacement"]
        hit = (row.partial == node) & (node >= 0)
        ok = (repl >= 0) & (repl != me) & ~ps.contains(row.partial, repl)
        row = row.replace(partial=jnp.where(
            hit, jnp.where(ok, repl, -1), row.partial))
        return row, self.no_emit()

    def handle_bootstrap_remove_subscription(self, cfg, me, row, m, key):
        """Only the departing node acts (:200-238): rewire the first
        L−(c−1) in-view members to partial-view members (round-robin),
        remove-gossip to the rest, reset local state."""
        node = m.data["subject"]
        its_me = node == me
        iv = ps.members_first(row.in_view)
        pv = ps.members_first(row.partial)
        L = ps.size(row.in_view)
        n_pv = jnp.maximum(ps.size(row.partial), 1)
        n_replace = jnp.maximum(L - (self.cfg.scamp_c - 1), 0)
        k = jnp.arange(self.P)
        is_replace = its_me & (k < n_replace) & (iv >= 0)
        is_remove = its_me & (k >= n_replace) & (iv >= 0)
        repl = pv[k % n_pv]
        rmsgs = self.emit(jnp.where(is_replace, iv, -1),
                          self.typ("replace_subscription"),
                          subject=me, replacement=repl)
        dmsgs = self.emit(jnp.where(is_remove, iv, -1),
                          self.typ("remove_subscription"), subject=me)
        row = row.replace(
            partial=jnp.where(its_me, -1, row.partial),
            in_view=jnp.where(its_me, -1, row.in_view),
            left=row.left | its_me)
        return row, self.merge(rmsgs, dmsgs)

    def handle_ctl_leave(self, cfg, me, row: ScampState, m, key):
        """leave(target) (v2 :180-190): notify the partial view (and the
        target itself) with a bootstrap message; the target does the work."""
        target = m.data["peer"]
        to = jnp.concatenate([target[None], row.partial])
        em = self.emit(to, self.typ("bootstrap_remove_subscription"),
                       subject=target)
        return row, em
