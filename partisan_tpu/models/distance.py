"""Distance metrics — the pluggable manager's ping/pong RTT measurement
(src/partisan_pluggable_peer_service_manager.erl:852-873, 1111-1151,
gated by ``distance_enabled``, include/partisan.hrl:40) as a stackable
upper protocol.

Every ``cfg.distance_interval`` rounds a node stamps ``dist_ping`` with
the current round and sends it to every peer of the lower membership
layer; the peer echoes the stamp in ``dist_pong``; the origin records
round-trip time (in rounds — the simulator's clock) per peer.  Under the
engine's delay machinery (ingress/egress delay, '$delay' interposition)
the measured RTT grows accordingly, which is exactly what the reference
uses the numbers for (XBOT-style topology preferences, operator
observability)."""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config import Config
from ..engine import World
from ..ops.msg import Msgs
from .stack import StackState, UpperProtocol


@struct.dataclass
class DistState:
    peer: jax.Array      # [N, P] measured-peer ids (-1 free)
    rtt: jax.Array       # [N, P] last RTT in rounds (-1 unknown)
    last_rnd: jax.Array  # [N] round counter mirror (ticked every round)
    cursor: jax.Array    # [N] round-robin eviction slot when the table
                         # is full (measurements are never silently lost)


class Distance(UpperProtocol):
    """Stack over any membership manager: Stacked(HyParView(cfg), Distance(cfg))."""

    msg_types = ("dist_ping", "dist_pong")

    def __init__(self, cfg: Config, peer_cap: int = 8):
        self.cfg = cfg
        self.P = peer_cap
        self.data_spec: Dict = {"stamp": ((), jnp.int32)}
        self.emit_cap = max(peer_cap, 4)
        self.tick_emit_cap = peer_cap

    def init_upper(self, cfg: Config, key: jax.Array) -> DistState:
        n = cfg.n_nodes
        return DistState(
            peer=jnp.full((n, self.P), -1, jnp.int32),
            rtt=jnp.full((n, self.P), -1, jnp.int32),
            last_rnd=jnp.zeros((n,), jnp.int32),
            cursor=jnp.zeros((n,), jnp.int32),
        )

    # --------------------------------------------------------------- handlers

    def handle_dist_ping(self, cfg, me, row: StackState, m: Msgs, key):
        """Echo the stamp back — the pong half (:1111-1122)."""
        return row, self.emit(m.src[None], self.typ("dist_pong"), cap=1,
                              stamp=m.data["stamp"])

    def handle_dist_pong(self, cfg, me, row: StackState, m: Msgs, key):
        """Record RTT for the echoing peer (:1123-1151).  Delivery happens
        before this round's tick, so "now" is last_rnd + 1."""
        up = row.upper
        rtt = (up.last_rnd + 1) - m.data["stamp"]
        peer, rtts, cursor = record_rtt(up.peer, up.rtt, up.cursor,
                                        m.src, rtt)
        return self.up(row, up.replace(peer=peer, rtt=rtts,
                                       cursor=cursor)), self.no_emit()

    # ------------------------------------------------------------------ timer

    def tick_upper(self, cfg, me, row: StackState, rnd, key):
        up = row.upper.replace(last_rnd=rnd)
        # trace-lint: allow(config-fork): ?DISTANCE_ENABLED is a deliberate trace-time gate — a disabled stack must compile the plane to NOTHING (tests pin that the disabled text is distance_interval-independent)
        if not cfg.distance_enabled:
            # ?DISTANCE_ENABLED (partisan.hrl:40) is a TRACE-time gate:
            # the disabled plane compiles to nothing — no ping emission
            # and no interval arithmetic enters the program, so the
            # lowered text is independent of distance_interval
            # (pinned in tests/test_distance.py).
            return self.up(row, up), self.no_emit()
        due = ((rnd + me) % cfg.distance_interval) == 0
        peers = self.active_peers(row)[: self.P]
        em = self.emit(jnp.where(due, peers, -1), self.typ("dist_ping"),
                       cap=self.tick_emit_cap, stamp=rnd)
        return self.up(row, up), em


def distances(world: World, node: int) -> Dict[int, int]:
    """Host accessor: measured RTTs (rounds) by peer id for one node —
    the `partisan_peer_service_console`-style observability surface."""
    up = world.state.upper
    peers = np.asarray(up.peer[node])
    rtts = np.asarray(up.rtt[node])
    return {int(p): int(r) for p, r in zip(peers, rtts) if p >= 0 and r >= 0}


def record_rtt(peer_tbl: jax.Array, rtt_tbl: jax.Array, cursor: jax.Array,
               src, rtt):
    """Slot-update shared by every RTT collector (Distance above, X-BOT's
    measured mode in models/xbot.py): existing slot, else a free one,
    else round-robin-evict the cursor slot — a fresh measurement is
    never thrown away."""
    cap = peer_tbl.shape[-1]
    hit = peer_tbl == src
    free = peer_tbl < 0
    slot = jnp.where(hit.any(), jnp.argmax(hit),
                     jnp.where(free.any(), jnp.argmax(free), cursor % cap))
    evicting = ~hit.any() & ~free.any()
    return (peer_tbl.at[slot].set(src), rtt_tbl.at[slot].set(rtt),
            cursor + evicting.astype(jnp.int32))
