"""Atomic-commitment and primary-backup workloads — TPU-native rebuilds of
the reference's model-checked example protocols (SURVEY §2.10):

  * :class:`TwoPhaseCommit`  — ``protocols/lampson_2pc.erl``
  * :class:`BernsteinCTP`    — ``protocols/bernstein_ctp.erl`` (2PC + the
    cooperative-termination decision_request/decision sub-protocol)
  * :class:`Skeen3PC`        — ``protocols/skeen_3pc.erl`` (3-phase commit
    with the precommit round and non-blocking participant timeout)
  * :class:`AlsbergDay`      — ``protocols/alsberg_day.erl`` (primary-backup
    replication; the acked/membership variants are flags)

Shape notes: the reference keeps ETS tables of concurrent transactions;
these rebuilds track ONE transaction per coordinator (the reference's own
model-checking harness drives exactly one broadcast per execution,
test/filibuster_SUITE.erl) with participant sets as dense ``[N]`` bool
rows.  Like the reference, commit/abort fan-outs are NOT retransmitted —
dropping one is precisely the divergence the model checker must find
(Makefile:105-113 expects failing schedules for every one of these).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops.msg import Msgs

# participant_status / coordinator_status values
IDLE, PREPARING, PRECOMMITTING, COMMITTING, ABORTING, DONE = 0, 1, 2, 3, 4, 5
P_NONE, P_PREPARED, P_PRECOMMIT, P_COMMITTED, P_ABORTED = 0, 1, 2, 3, 4


@struct.dataclass
class TxnState:
    # coordinator half (valid on the node that got ctl_broadcast)
    c_status: jax.Array      # [N] int32 coordinator_status
    c_value: jax.Array       # [N] int32 transaction payload
    c_prepared: jax.Array    # [N, N] bool — prepared votes collected
    c_precommit: jax.Array   # [N, N] bool — precommit acks (3PC)
    c_acked: jax.Array       # [N, N] bool — commit/abort acks
    c_timeout: jax.Array     # [N] int32 coordinator_timeout countdown
    # participant half
    p_status: jax.Array      # [N] int32 participant_status
    p_value: jax.Array       # [N] int32 stored transaction value
    p_coord: jax.Array       # [N] int32 the coordinator node
    p_timeout: jax.Array     # [N] int32 participant_timeout countdown (ctp/3pc)
    delivered: jax.Array     # [N] int32 — value forwarded to the app on
                             # commit (process_forward, lampson_2pc :378-390);
                             # -1 = nothing delivered. THE agreement surface.


class TwoPhaseCommit(ProtocolBase):
    """lampson_2pc.erl: prepare -> prepared -> commit -> commit_ack with a
    coordinator timeout that aborts while still PREPARING (:189-220).
    Participants ack aborts; a commit already applied stays applied — the
    window the model checker exploits."""

    msg_types = ("prepare", "prepared", "commit", "commit_ack",
                 "abort", "abort_ack", "ctl_broadcast")
    has_precommit = False
    participant_timeout: int | None = None  # ctp/3pc override

    def __init__(self, cfg: Config, coordinator_timeout: int = 8):
        self.cfg = cfg
        self.T = coordinator_timeout
        self.data_spec: Dict = {
            "value": ((), jnp.int32),
            "coord": ((), jnp.int32),
            "decision": ((), jnp.int32),
        }
        self.emit_cap = cfg.n_nodes  # fan-outs go to every participant
        self.tick_emit_cap = cfg.n_nodes

    # ------------------------------------------------------------------ state

    def init(self, cfg: Config, key: jax.Array) -> TxnState:
        n = cfg.n_nodes
        z = jnp.zeros((n,), jnp.int32)
        zb = jnp.zeros((n, n), bool)
        return TxnState(
            c_status=z, c_value=z, c_prepared=zb, c_precommit=zb,
            c_acked=zb, c_timeout=z,
            p_status=z, p_value=z, p_coord=jnp.full((n,), -1, jnp.int32),
            p_timeout=z, delivered=jnp.full((n,), -1, jnp.int32),
        )

    def _everyone(self, me) -> jax.Array:
        """All participants incl. self (membership(), lampson_2pc :150-156)."""
        return jnp.arange(self.cfg.n_nodes, dtype=jnp.int32)

    def _fan(self, me, typ, cond, **data) -> Msgs:
        to = jnp.where(cond, self._everyone(me), -1)
        return self.emit(to, typ, **data)

    # --------------------------------------------------------------- handlers

    def handle_ctl_broadcast(self, cfg, me, row: TxnState, m: Msgs, key):
        """broadcast/2 (:123-156): become coordinator, prepare everywhere."""
        fresh = row.c_status == IDLE
        row = row.replace(
            c_status=jnp.where(fresh, PREPARING, row.c_status),
            c_value=jnp.where(fresh, m.data["value"], row.c_value),
            c_timeout=jnp.where(fresh, self.T, row.c_timeout),
        )
        return row, self._fan(me, self.typ("prepare"), fresh,
                              value=m.data["value"], coord=me)

    def handle_prepare(self, cfg, me, row: TxnState, m: Msgs, key):
        """:433-441 participant side: log + vote prepared."""
        ok = row.p_status == P_NONE
        row = row.replace(
            p_status=jnp.where(ok, P_PREPARED, row.p_status),
            p_value=jnp.where(ok, m.data["value"], row.p_value),
            p_coord=jnp.where(ok, m.data["coord"], row.p_coord),
            p_timeout=jnp.where(ok, self._p_timeout_init(), row.p_timeout),
        )
        return row, self.emit(jnp.where(ok, m.data["coord"], -1)[None],
                              self.typ("prepared"))

    def _p_timeout_init(self):
        return jnp.int32(self.participant_timeout or 0)

    def handle_prepared(self, cfg, me, row: TxnState, m: Msgs, key):
        """:391-424 coordinator: collect votes; all in -> decide commit."""
        voting = row.c_status == PREPARING
        prepared = row.c_prepared.at[m.src].set(
            row.c_prepared[m.src] | voting)
        all_in = jnp.all(prepared)
        row = row.replace(
            c_prepared=prepared,
            c_status=jnp.where(voting & all_in, self._decided_status(),
                               row.c_status))
        em = self._decide_fan(cfg, me, row, voting & all_in)
        return row, em

    def _decided_status(self):
        return jnp.int32(PRECOMMITTING if self.has_precommit else COMMITTING)

    def _decide_fan(self, cfg, me, row, go) -> Msgs:
        typ = self.typ("precommit") if self.has_precommit \
            else self.typ("commit")
        return self._fan(me, typ, go, value=row.c_value, coord=me)

    def handle_commit(self, cfg, me, row: TxnState, m: Msgs, key):
        """:342-355 (:378-390 in 2pc): apply + deliver + ack.  Applies even
        after a local abort — the reference just inserts the commit record —
        which is exactly the observable divergence."""
        row = row.replace(
            p_status=jnp.int32(P_COMMITTED),
            p_value=m.data["value"],
            delivered=m.data["value"],
            p_timeout=jnp.zeros_like(row.p_timeout),
        )
        return row, self.emit(m.data["coord"][None], self.typ("commit_ack"))

    def handle_commit_ack(self, cfg, me, row: TxnState, m: Msgs, key):
        acked = row.c_acked.at[m.src].set(True)
        done = jnp.all(acked) & (row.c_status == COMMITTING)
        row = row.replace(c_acked=acked,
                          c_status=jnp.where(done, DONE, row.c_status))
        return row, self.no_emit()

    def handle_abort(self, cfg, me, row: TxnState, m: Msgs, key):
        """:334-341: delete the participating record + ack.  A node that
        already committed keeps its delivered value (the record delete does
        not undo process_forward)."""
        was_committed = row.p_status == P_COMMITTED
        row = row.replace(
            p_status=jnp.where(was_committed, row.p_status,
                               jnp.int32(P_ABORTED)),
            p_timeout=jnp.zeros_like(row.p_timeout),
        )
        return row, self.emit(m.data["coord"][None], self.typ("abort_ack"))

    def handle_abort_ack(self, cfg, me, row: TxnState, m: Msgs, key):
        acked = row.c_acked.at[m.src].set(True)
        done = jnp.all(acked) & (row.c_status == ABORTING)
        row = row.replace(c_acked=acked,
                          c_status=jnp.where(done, DONE, row.c_status))
        return row, self.no_emit()

    # ------------------------------------------------------------------ timer

    def tick(self, cfg, me, row: TxnState, rnd, key):
        """coordinator_timeout (:189-220): still PREPARING when the clock
        runs out -> abort everywhere."""
        ticking = row.c_status == PREPARING
        t = jnp.where(ticking, row.c_timeout - 1, row.c_timeout)
        fire = ticking & (t <= 0)
        row = row.replace(
            c_timeout=t,
            c_status=jnp.where(fire, ABORTING, row.c_status),
            c_acked=jnp.where(fire, False, row.c_acked),
        )
        em = self._fan(me, self.typ("abort"), fire, coord=me)
        row, em2 = self._participant_tick(cfg, me, row, rnd, key)
        return row, self.merge(em, em2, cap=self.tick_emit_cap)

    def _participant_tick(self, cfg, me, row, rnd, key):
        return row, self.no_emit(self.tick_emit_cap)


class BernsteinCTP(TwoPhaseCommit):
    """bernstein_ctp.erl: 2PC + cooperative termination — a participant
    stuck in PREPARED past its timeout asks every peer for the decision
    (:222-278); any peer that knows (committed or aborted) replies
    ``decision`` (:163-221) and the requester adopts it."""

    msg_types = ("prepare", "prepared", "commit", "commit_ack",
                 "abort", "abort_ack", "decision_request", "decision",
                 "ctl_broadcast")
    participant_timeout = 12

    def handle_decision_request(self, cfg, me, row: TxnState, m: Msgs, key):
        knows = (row.p_status == P_COMMITTED) | (row.p_status == P_ABORTED)
        dec = jnp.where(row.p_status == P_COMMITTED, P_COMMITTED, P_ABORTED)
        rep = self.emit(jnp.where(knows, m.src, -1)[None],
                        self.typ("decision"), decision=dec,
                        value=row.p_value)
        return row, rep

    def handle_decision(self, cfg, me, row: TxnState, m: Msgs, key):
        undecided = row.p_status == P_PREPARED
        adopt_commit = undecided & (m.data["decision"] == P_COMMITTED)
        adopt_abort = undecided & (m.data["decision"] == P_ABORTED)
        row = row.replace(
            p_status=jnp.where(adopt_commit, P_COMMITTED,
                               jnp.where(adopt_abort, P_ABORTED,
                                         row.p_status)),
            delivered=jnp.where(adopt_commit, m.data["value"],
                                row.delivered))
        return row, self.no_emit()

    def _participant_tick(self, cfg, me, row: TxnState, rnd, key):
        """participant_timeout (:254-278): PREPARED too long -> ask around."""
        waiting = row.p_status == P_PREPARED
        t = jnp.where(waiting, row.p_timeout - 1, row.p_timeout)
        fire = waiting & (t <= 0) & (row.p_timeout > 0)
        row = row.replace(p_timeout=jnp.where(
            fire, self.participant_timeout, t))
        em = self._fan(me, self.typ("decision_request"), fire)
        return row, em


class Skeen3PC(TwoPhaseCommit):
    """skeen_3pc.erl: the extra PRECOMMIT round (:357-401) makes commitment
    non-blocking: a participant that reached PRECOMMIT and times out
    commits unilaterally; one stuck in PREPARED aborts (:165-195)."""

    msg_types = ("prepare", "prepared", "precommit", "precommit_ack",
                 "commit", "commit_ack", "abort", "abort_ack",
                 "ctl_broadcast")
    has_precommit = True
    participant_timeout = 12

    def handle_precommit(self, cfg, me, row: TxnState, m: Msgs, key):
        ok = row.p_status == P_PREPARED
        row = row.replace(
            p_status=jnp.where(ok, P_PRECOMMIT, row.p_status),
            p_timeout=jnp.where(ok, self.participant_timeout, row.p_timeout))
        return row, self.emit(jnp.where(ok, m.data["coord"], -1)[None],
                              self.typ("precommit_ack"))

    def handle_precommit_ack(self, cfg, me, row: TxnState, m: Msgs, key):
        """:357-391 coordinator: all precommit acks -> commit round."""
        waiting = row.c_status == PRECOMMITTING
        pc = row.c_precommit.at[m.src].set(row.c_precommit[m.src] | waiting)
        all_in = jnp.all(pc)
        go = waiting & all_in
        row = row.replace(c_precommit=pc,
                          c_status=jnp.where(go, COMMITTING, row.c_status))
        return row, self._fan(me, self.typ("commit"), go,
                              value=row.c_value, coord=me)

    def _participant_tick(self, cfg, me, row: TxnState, rnd, key):
        """participant_timeout (:165-195): PRECOMMIT -> commit unilaterally;
        PREPARED -> abort unilaterally."""
        waiting = (row.p_status == P_PREPARED) | (row.p_status == P_PRECOMMIT)
        t = jnp.where(waiting, row.p_timeout - 1, row.p_timeout)
        fire = waiting & (t <= 0) & (row.p_timeout > 0)
        commit_self = fire & (row.p_status == P_PRECOMMIT)
        abort_self = fire & (row.p_status == P_PREPARED)
        row = row.replace(
            p_timeout=t,
            p_status=jnp.where(commit_self, P_COMMITTED,
                               jnp.where(abort_self, P_ABORTED,
                                         row.p_status)),
            delivered=jnp.where(commit_self, row.p_value, row.delivered))
        return row, self.no_emit(self.tick_emit_cap)


# ======================================================================
# Primary-backup replication (alsberg_day.erl + acked/membership variants)
# ======================================================================

@struct.dataclass
class PbState:
    store: jax.Array        # [N, K] replicated key-value store
    out_valid: jax.Array    # [N, W] outstanding writes at the primary
    out_key: jax.Array      # [N, W]
    out_val: jax.Array      # [N, W]
    out_client: jax.Array   # [N, W]
    out_acks: jax.Array     # [N, W] collaborate_acks received
    client_acked: jax.Array  # [N] int32 — writes confirmed back to client


class AlsbergDay(ProtocolBase):
    """alsberg_day.erl: writes route to the primary (membership[0]); the
    primary applies + fans ``collaborate`` to the backups (:178-219);
    backups apply + ``collaborate_ack`` (:248-…); the primary confirms to
    the client once every backup acked (acked variant —
    ``alsberg_day_acked.erl``; the base variant confirms immediately)."""

    msg_types = ("write_req", "collaborate", "collaborate_ack",
                 "client_reply", "ctl_write")
    acked = True

    def __init__(self, cfg: Config, n_keys: int = 4, out_cap: int = 4):
        self.cfg = cfg
        self.K = n_keys
        self.W = out_cap
        self.data_spec: Dict = {
            "wkey": ((), jnp.int32),
            "value": ((), jnp.int32),
            "client": ((), jnp.int32),
            "slot": ((), jnp.int32),
        }
        self.emit_cap = cfg.n_nodes
        self.tick_emit_cap = 1

    def init(self, cfg: Config, key: jax.Array) -> PbState:
        n = cfg.n_nodes
        return PbState(
            store=jnp.full((n, self.K), -1, jnp.int32),
            out_valid=jnp.zeros((n, self.W), bool),
            out_key=jnp.zeros((n, self.W), jnp.int32),
            out_val=jnp.zeros((n, self.W), jnp.int32),
            out_client=jnp.zeros((n, self.W), jnp.int32),
            out_acks=jnp.zeros((n, self.W), jnp.int32),
            client_acked=jnp.zeros((n,), jnp.int32),
        )

    def handle_ctl_write(self, cfg, me, row: PbState, m: Msgs, key):
        """write/3 from any node forwards to the primary (:178-186)."""
        return row, self.emit(jnp.zeros((1,), jnp.int32),
                              self.typ("write_req"),
                              wkey=m.data["wkey"], value=m.data["value"],
                              client=me)

    def handle_write_req(self, cfg, me, row: PbState, m: Msgs, key):
        """Primary: apply locally, park outstanding, collaborate with the
        backups (:178-219)."""
        k = jnp.clip(m.data["wkey"], 0, self.K - 1)
        free = ~row.out_valid
        ok = jnp.any(free)
        slot = jnp.argmax(free)
        wr = lambda a, v: a.at[slot].set(jnp.where(ok, v, a[slot]))
        row = row.replace(
            store=row.store.at[k].set(jnp.where(ok, m.data["value"],
                                                row.store[k])),
            out_valid=wr(row.out_valid, True),
            out_key=wr(row.out_key, k),
            out_val=wr(row.out_val, m.data["value"]),
            out_client=wr(row.out_client, m.data["client"]),
            out_acks=wr(row.out_acks, 0),
        )
        others = jnp.where(self._backups(me) & ok, self._ids(), -1)
        em = self.emit(others, self.typ("collaborate"),
                       wkey=k, value=m.data["value"], slot=slot)
        return row, em

    def _ids(self):
        return jnp.arange(self.cfg.n_nodes, dtype=jnp.int32)

    def _backups(self, me):
        return self._ids() != 0

    def handle_collaborate(self, cfg, me, row: PbState, m: Msgs, key):
        k = jnp.clip(m.data["wkey"], 0, self.K - 1)
        row = row.replace(store=row.store.at[k].set(m.data["value"]))
        return row, self.emit(m.src[None], self.typ("collaborate_ack"),
                              slot=m.data["slot"])

    def handle_collaborate_ack(self, cfg, me, row: PbState, m: Msgs, key):
        """Primary: all backups acked -> confirm to the client (:221-246)."""
        s = jnp.clip(m.data["slot"], 0, self.W - 1)
        acks = row.out_acks.at[s].add(row.out_valid[s].astype(jnp.int32))
        done = row.out_valid[s] & (acks[s] >= self.cfg.n_nodes - 1)
        row = row.replace(
            out_acks=acks,
            out_valid=row.out_valid.at[s].set(row.out_valid[s] & ~done))
        rep = self.emit(jnp.where(done, row.out_client[s], -1)[None],
                        self.typ("client_reply"))
        return row, rep

    def handle_client_reply(self, cfg, me, row: PbState, m: Msgs, key):
        return row.replace(client_acked=row.client_acked + 1), self.no_emit()
