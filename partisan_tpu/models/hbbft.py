"""HBBFT-style chain worker — TPU-native rebuild of the reference's
``src/partisan_hbbft_worker.erl`` test workload (SURVEY §2.9).

The reference worker wraps an external ``hbbft`` library (threshold-crypto
asynchronous common subset) and exposes a small surface the property tests
drive: ``submit_transaction/2``, ``get_blocks/1``, ``get_buf/1``,
``get_status/1``, chain catch-up via ``sync/2`` + ``fetch_from/2``, and the
host-side ``verify_chain/2`` validator (partisan_hbbft_worker.erl:36-108).
What the tests actually assert is the *contract*: correct nodes build the
same chain of blocks, every block links to its predecessor, committed
transactions come from submitted ones, and nodes that fall behind catch up.

This rebuild keeps that contract but replaces the (external, crypto-heavy)
ACS with a round-native atomic broadcast that vectorizes over all N nodes:

  * epochs are a STATIC schedule: epoch ``e = round // epoch_len`` with
    leader ``e mod N`` (the reference's ``start_on_demand`` trigger becomes
    this fixed cadence — every epoch starts on schedule);
  * phase 0: the leader broadcasts ``propose(epoch, batch)`` drawn from its
    transaction buffer (``hbbft:input`` buffering);
  * on receipt every node stores the batch and broadcasts
    ``echo(epoch, digest)`` — one echo per node per epoch;
  * a node COMMITS the epoch's block once it holds the batch and ``N - f``
    echoes (``f = (N-1) div 3``), writing ``(digest, batch)`` into an
    epoch-indexed ledger and dropping the batch's transactions from its
    buffer (the reference removes block transactions from ``buf`` on every
    ``new_epoch``);
  * blocks are chained by a running hash fold over committed epochs — the
    ``prev_hash`` link of the reference's ``#block{}`` record — recomputed
    by :func:`verify_chain`;
  * catch-up: a periodic anti-entropy tick walks the node's lowest absent
    epoch and asks a random peer ``fetch(epoch)``; a peer holding that
    block answers ``sync(epoch, digest, batch)`` (the reference's
    ``fetch_from``/``sync`` pair, :39-44).

Safety note (crash faults, the fault model of prop_partisan_hbbft): only
the scheduled leader proposes for its epoch, so at most ONE block can ever
gain a quorum per epoch — per-epoch agreement degenerates to
committed-or-absent, absence is repaired by anti-entropy, and forks are
impossible without equivocation.

Byzantine faults (ISSUE 19, the chaos plane's equivocate / forge /
replay / corrupt kinds) are IN scope since this worker is the protocol
the reference built its Byzantine harness around.  ``hardened=True``
(default) compiles three defenses:

  * commit quorum over DISTINCT echo senders, keyed on the digest — a
    per-node voter bitmask kills the vote inflation that duplicated or
    replayed echoes buy an equivocating leader (without it, the
    explorer's 4-event schedule forks the chain:
    tests/test_byzantine.py);
  * propose acceptance checks the SCHEDULED leader id (``src == epoch
    mod N``) — a forged proposal claiming another epoch's leader is
    ignored;
  * sync installs verify ``digest(batch) == digest`` — a forged or
    corrupted catch-up block cannot poison the ledger.

Detection runs in BOTH modes (the counters are evidence, not defense):
``suspect`` counts echoes whose digest conflicts with the stored
proposal (equivocation evidence), ``forked`` counts sync messages
carrying a different digest for an epoch already committed — surfaced
through ``health_counters`` as ``hbbft_equivocation_suspected`` /
``hbbft_fork_detected``.  ``hardened=False`` keeps the pre-ISSUE-19
per-MESSAGE vote arithmetic: the explorer's demonstration target (find,
shrink and replay an equivocation schedule that forks it), never the
mode to deploy.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config import Config
from ..engine import ProtocolBase, World
from ..ops.msg import Msgs


@struct.dataclass
class HbbftState:
    buf: jax.Array            # [N, B] pending txn ids (-1 free slot)
    cur_epoch: jax.Array      # [N] epoch this node is currently running
    cur_digest: jax.Array     # [N] digest of the stored proposal (0 = none)
    cur_batch: jax.Array      # [N, Bk] stored proposal batch (-1 pad)
    have_batch: jax.Array     # [N] bool — propose received this epoch
    echoed: jax.Array         # [N] bool — echo already sent this epoch
    votes: jax.Array          # [N] echo count for (cur_epoch, cur_digest)
    ledger_digest: jax.Array  # [N, E] committed digest per epoch (0 = absent)
    ledger_batch: jax.Array   # [N, E, Bk] committed batch per epoch
    fetch_cursor: jax.Array   # [N] next epoch the anti-entropy walk probes
    voted: jax.Array          # [N, W] uint32 distinct-echo-sender bitmask
    suspect: jax.Array        # [N] cumulative equivocation evidence
    forked: jax.Array         # [N] cumulative conflicting-committed-digest evidence


def _digest(batch: jax.Array) -> jax.Array:
    """uint-mix fold over the batch — the block content hash.  -1 pads are
    folded too (they are part of the canonical fixed-shape block)."""
    h = jnp.uint32(0x9E3779B9)
    x = batch.astype(jnp.uint32)
    # trace-lint: allow(unroll-bomb): batch width is the tiny static B of the hbbft payload — bounded unroll keeps the digest fused
    for i in range(batch.shape[-1]):
        h = h ^ (x[..., i] + jnp.uint32(0x85EBCA6B) + (h << 6) + (h >> 2))
        h = (h ^ (h >> 16)) * jnp.uint32(0x7FEB352D)
    # digest 0 is the sentinel for "absent"; avoid colliding with it
    return jnp.maximum(h.astype(jnp.int32) & 0x7FFFFFFF, 1)


class HbbftWorker(ProtocolBase):
    """Rotating-leader quorum-echo atomic broadcast over the engine."""

    msg_types = ("propose", "echo", "fetch", "sync", "ctl_submit")

    def __init__(self, cfg: Config, batch_size: int = 4, buf_cap: int = 16,
                 max_epochs: int = 32, epoch_len: int = 6,
                 ae_interval: int = 2, hardened: bool = True):
        assert epoch_len >= 4, "propose/echo/commit needs 4 rounds"
        self.cfg = cfg
        self.Bk = batch_size
        self.B = buf_cap
        self.E = max_epochs
        self.L = epoch_len
        self.ae_interval = ae_interval
        self.hardened = hardened
        n = cfg.n_nodes
        self.W = (n + 31) // 32  # voter-bitmask words
        self.f = (n - 1) // 3
        self.quorum = n - self.f
        # Liveness requires the whole echo fan (one echo per node per epoch,
        # all arriving within a round or two) to FIT in the inbox: echoes
        # lost to inbox overflow are never retransmitted, so with
        # inbox_cap < n votes can never reach quorum and no node commits —
        # a total, silent liveness failure (visible only via the
        # inbox_overflow metric).  +2 slack: the echo round can also carry
        # a propose and an anti-entropy fetch/sync.  Tests use n + 4.
        assert cfg.inbox_cap >= n + 2, (
            f"HbbftWorker liveness needs cfg.inbox_cap >= n_nodes + 2 "
            f"(echo fan-in + propose/anti-entropy slack); got "
            f"inbox_cap={cfg.inbox_cap}, n_nodes={n}")
        self.data_spec: Dict = {
            "epoch": ((), jnp.int32),
            "digest": ((), jnp.int32),
            "batch": ((batch_size,), jnp.int32),
            "txn": ((), jnp.int32),
            "peer": ((), jnp.int32),
        }
        self.emit_cap = n          # echo broadcast fans to everyone
        self.tick_emit_cap = n + 1  # propose fan + one anti-entropy fetch

    # ------------------------------------------------------------------ state

    def init(self, cfg: Config, key: jax.Array) -> HbbftState:
        n = cfg.n_nodes
        return HbbftState(
            buf=jnp.full((n, self.B), -1, jnp.int32),
            cur_epoch=jnp.full((n,), -1, jnp.int32),
            cur_digest=jnp.zeros((n,), jnp.int32),
            cur_batch=jnp.full((n, self.Bk), -1, jnp.int32),
            have_batch=jnp.zeros((n,), bool),
            echoed=jnp.zeros((n,), bool),
            votes=jnp.zeros((n,), jnp.int32),
            ledger_digest=jnp.zeros((n, self.E), jnp.int32),
            ledger_batch=jnp.full((n, self.E, self.Bk), -1, jnp.int32),
            fetch_cursor=jnp.zeros((n,), jnp.int32),
            voted=jnp.zeros((n, self.W), jnp.uint32),
            suspect=jnp.zeros((n,), jnp.int32),
            forked=jnp.zeros((n,), jnp.int32),
        )

    def _everyone(self) -> jax.Array:
        return jnp.arange(self.cfg.n_nodes, dtype=jnp.int32)

    def _drop_from_buf(self, buf: jax.Array, batch: jax.Array) -> jax.Array:
        """Remove committed transactions from the pending buffer
        (partisan_hbbft_worker: buffer pruning on new_epoch)."""
        hit = ((buf[:, None] == batch[None, :]) & (batch[None, :] >= 0)).any(-1)
        return jnp.where(hit, -1, buf)

    def _install(self, row: HbbftState, epoch, digest, batch,
                 ok) -> HbbftState:
        """Write a committed block into the epoch ledger (idempotent)."""
        e = jnp.clip(epoch, 0, self.E - 1)
        fresh = ok & (epoch >= 0) & (epoch < self.E) \
            & (row.ledger_digest[e] == 0)
        ld = row.ledger_digest.at[e].set(
            jnp.where(fresh, digest, row.ledger_digest[e]))
        lb = row.ledger_batch.at[e].set(
            jnp.where(fresh, batch, row.ledger_batch[e]))
        buf = jnp.where(fresh, self._drop_from_buf(row.buf, batch), row.buf)
        return row.replace(ledger_digest=ld, ledger_batch=lb, buf=buf)

    # --------------------------------------------------------------- handlers

    def handle_ctl_submit(self, cfg, me, row: HbbftState, m: Msgs, key):
        """submit_transaction/2 (:37-38): append to the pending buffer,
        deduplicating against both the buffer and the committed ledger."""
        txn = m.data["txn"]
        dup = (row.buf == txn).any() | \
            ((row.ledger_batch == txn).any() & (txn >= 0))
        free = jnp.argmax(row.buf < 0)
        can = (txn >= 0) & ~dup & (row.buf[free] < 0)
        return row.replace(buf=row.buf.at[free].set(
            jnp.where(can, txn, row.buf[free]))), self.no_emit()

    def handle_propose(self, cfg, me, row: HbbftState, m: Msgs, key):
        """Store the leader's batch for the current epoch and echo its
        digest to everyone (the RBC 'echo' role collapsed to one phase)."""
        epoch, batch = m.data["epoch"], m.data["batch"]
        ok = (epoch == row.cur_epoch) & ~row.have_batch
        if self.hardened:
            # only the SCHEDULED leader may propose its epoch — a forged
            # proposal claiming someone else's slot is dead on arrival
            ok = ok & (m.src == (epoch % cfg.n_nodes))
        d = _digest(batch)
        row = row.replace(
            have_batch=row.have_batch | ok,
            cur_digest=jnp.where(ok, d, row.cur_digest),
            cur_batch=jnp.where(ok, batch, row.cur_batch))
        do_echo = ok & ~row.echoed
        row = row.replace(echoed=row.echoed | do_echo)
        em = self.emit(jnp.where(do_echo, self._everyone(), -1),
                       self.typ("echo"), epoch=epoch, digest=d)
        return row, em

    def handle_echo(self, cfg, me, row: HbbftState, m: Msgs, key):
        """Count echoes for this epoch's digest.  Honest senders echo at
        most once per epoch, but duplicated / replayed copies arrive as
        separate messages — hardened mode therefore counts DISTINCT
        senders via a voter bitmask; unhardened keeps the inflatable
        per-message count (the explorer's fork target)."""
        ok = (m.data["epoch"] == row.cur_epoch) \
            & (m.data["digest"] == row.cur_digest) & row.have_batch
        # detection (both modes): an echo for our epoch whose digest
        # conflicts with the stored proposal is equivocation evidence
        mismatch = (m.data["epoch"] == row.cur_epoch) & row.have_batch \
            & (m.data["digest"] != row.cur_digest)
        row = row.replace(suspect=row.suspect + mismatch.astype(jnp.int32))
        if self.hardened:
            src = jnp.clip(m.src, 0, cfg.n_nodes - 1)
            word = src // 32
            bit = jnp.uint32(1) << jnp.uint32(src % 32)
            already = (row.voted[word] & bit) != 0
            ok = ok & ~already
            row = row.replace(voted=row.voted.at[word].set(
                jnp.where(ok, row.voted[word] | bit, row.voted[word])))
        return row.replace(votes=row.votes + ok.astype(jnp.int32)), \
            self.no_emit()

    def handle_fetch(self, cfg, me, row: HbbftState, m: Msgs, key):
        """fetch_from/2: answer with the block for the asked epoch if we
        have it (:39-44)."""
        e = jnp.clip(m.data["epoch"], 0, self.E - 1)
        have = (m.data["epoch"] >= 0) & (m.data["epoch"] < self.E) \
            & (row.ledger_digest[e] != 0)
        em = self.emit(jnp.where(have, m.src, -1)[None], self.typ("sync"),
                       cap=1, epoch=m.data["epoch"],
                       digest=row.ledger_digest[e],
                       batch=row.ledger_batch[e])
        return row, em

    def handle_sync(self, cfg, me, row: HbbftState, m: Msgs, key):
        """sync/2: install a caught-up block into the ledger."""
        epoch, digest, batch = (m.data["epoch"], m.data["digest"],
                                m.data["batch"])
        # detection (both modes): a sync carrying a DIFFERENT digest for
        # an epoch we already committed is direct fork evidence
        e = jnp.clip(epoch, 0, self.E - 1)
        conflict = (epoch >= 0) & (epoch < self.E) & (digest != 0) \
            & (row.ledger_digest[e] != 0) & (row.ledger_digest[e] != digest)
        row = row.replace(forked=row.forked + conflict.astype(jnp.int32))
        ok = digest != 0
        if self.hardened:
            # the digest must recompute from the batch — forged or
            # corrupted catch-up blocks cannot poison the ledger
            ok = ok & (_digest(batch) == digest)
        row = self._install(row, epoch, digest, batch, ok)
        return row, self.no_emit()

    # ------------------------------------------------------------------ timer

    def tick(self, cfg, me, row: HbbftState, rnd, key):
        epoch = rnd // self.L
        phase = rnd % self.L
        leader = (epoch % cfg.n_nodes) == me

        # phase 0: roll into the new epoch (reset per-epoch scratch) and,
        # if leader with pending work, broadcast the proposal
        is_new = (phase == 0) & (epoch != row.cur_epoch)
        row = row.replace(
            cur_epoch=jnp.where(is_new, epoch, row.cur_epoch),
            cur_digest=jnp.where(is_new, 0, row.cur_digest),
            cur_batch=jnp.where(is_new, -1, row.cur_batch),
            have_batch=row.have_batch & ~is_new,
            echoed=row.echoed & ~is_new,
            votes=jnp.where(is_new, 0, row.votes),
            voted=jnp.where(is_new, jnp.uint32(0), row.voted))
        # batch = first Bk pending txns (hbbft batch_size)
        order = jnp.argsort(jnp.where(row.buf >= 0, 0, 1), stable=True)
        batch = row.buf[order][: self.Bk]
        propose = is_new & leader & (batch[0] >= 0)
        pr = self.emit(jnp.where(propose, self._everyone(), -1),
                       self.typ("propose"), cap=self.cfg.n_nodes,
                       epoch=epoch, batch=batch)

        # commit once quorum echoes are in (possible from phase 3 on)
        can_commit = (phase >= 3) & row.have_batch \
            & (row.votes >= self.quorum)
        row = self._install(row, row.cur_epoch, row.cur_digest,
                            row.cur_batch, can_commit)

        # anti-entropy: probe one absent past epoch at a random peer
        # (staggered per node so fetch load spreads over the epoch)
        ae_due = ((rnd + me) % self.ae_interval) == 0
        cursor = row.fetch_cursor % jnp.maximum(epoch, 1)
        absent = row.ledger_digest[jnp.clip(cursor, 0, self.E - 1)] == 0
        peer = jax.random.randint(key, (), 0, cfg.n_nodes)
        ask = ae_due & absent & (epoch > 0) & (peer != me)
        fq = self.emit(jnp.where(ask, peer, -1)[None], self.typ("fetch"),
                       cap=1, epoch=cursor)
        row = row.replace(fetch_cursor=jnp.where(ae_due, cursor + 1,
                                                 row.fetch_cursor))
        return row, self.merge(pr, fq, cap=self.tick_emit_cap)

    # ------------------------------------------------------------------ health

    def health_counters(self, state: HbbftState) -> Dict[str, jax.Array]:
        """Byzantine-evidence totals (ISSUE 19): both counters accumulate
        in hardened AND unhardened mode — detection is evidence, not
        defense — so the explorer's ``no_view_poisoning``-style probes and
        the soak's health plane see equivocation even on the target that
        falls to it."""
        return {
            "hbbft_equivocation_suspected":
                jnp.sum(state.suspect).astype(jnp.int32),
            "hbbft_fork_detected": jnp.sum(state.forked).astype(jnp.int32),
        }


# -------------------------------------------------------------------- host API

def get_blocks(world: World, proto: HbbftWorker,
               node: int) -> List[Tuple[int, int, List[int]]]:
    """get_blocks/1: [(epoch, digest, txns)] of the node's committed chain."""
    ld = np.asarray(world.state.ledger_digest[node])
    lb = np.asarray(world.state.ledger_batch[node])
    return [(int(e), int(ld[e]), [int(t) for t in lb[e] if t >= 0])
            for e in np.nonzero(ld)[0]]


def get_buf(world: World, proto: HbbftWorker, node: int) -> List[int]:
    """get_buf/1: pending (uncommitted) transactions."""
    return [int(t) for t in np.asarray(world.state.buf[node]) if t >= 0]


def get_status(world: World, proto: HbbftWorker, node: int) -> Dict[str, int]:
    """get_status/1: epoch / chain length / buffer depth."""
    return {
        "epoch": int(world.state.cur_epoch[node]),
        "chain_len": int((np.asarray(
            world.state.ledger_digest[node]) != 0).sum()),
        "buf_len": len(get_buf(world, proto, node)),
    }


def chain_hash(blocks: List[Tuple[int, int, List[int]]]) -> int:
    """The prev_hash fold: each block's hash mixes its predecessor's —
    the #block{prev_hash} chain link of the reference, genesis linking to
    the empty hash (verify_chain's genesis clause, :59-69)."""
    h = 0
    for epoch, digest, _txns in blocks:
        h = ((h * 0x01000193) ^ (epoch * 0x9E3779B9) ^ digest) & 0xFFFFFFFF
    return h


def verify_chain(world: World, proto: HbbftWorker,
                 submitted: List[int] | None = None) -> Dict[str, object]:
    """verify_chain/2 (:59-108) over every live node: per-epoch agreement
    (equal digest+batch wherever two nodes both committed), digest
    integrity (stored digest recomputes from the batch), no transaction in
    two epochs, and — when ``submitted`` is given — inclusion-only-of
    submitted transactions.  Returns {'ok': bool, ...detail}."""
    alive = np.asarray(world.alive)
    ld = np.asarray(world.state.ledger_digest)
    lb = np.asarray(world.state.ledger_batch)
    live = np.nonzero(alive)[0]
    problems: List[str] = []

    # agreement + integrity
    for e in range(proto.E):
        committed = [i for i in live if ld[i, e] != 0]
        ds = {int(ld[i, e]) for i in committed}
        bs = {tuple(lb[i, e].tolist()) for i in committed}
        if len(ds) > 1 or len(bs) > 1:
            problems.append(f"epoch {e}: divergent blocks {ds}")
        for i in committed[:1]:
            want = int(jax.device_get(_digest(jnp.asarray(lb[i, e]))))
            if want != int(ld[i, e]):
                problems.append(f"epoch {e}: digest mismatch on node {i}")

    # txn uniqueness + inclusion, over the union chain
    seen: Dict[int, int] = {}
    for e in range(proto.E):
        for i in live:
            if ld[i, e] != 0:
                for t in lb[i, e]:
                    t = int(t)
                    if t < 0:
                        continue
                    if seen.setdefault(t, e) != e:
                        problems.append(
                            f"txn {t} in epochs {seen[t]} and {e}")
                    if submitted is not None and t not in submitted:
                        problems.append(f"txn {t} never submitted")
                break
    chains = {int(i): chain_hash(get_blocks(world, proto, int(i)))
              for i in live}
    return {"ok": not problems, "problems": problems, "chains": chains}


def submit_transaction(world: World, proto: HbbftWorker, node: int,
                       txn: int) -> World:
    """submit_transaction/2 — host verb (the test harness entry point)."""
    from .. import peer_service
    return peer_service.send_ctl(world, proto, node, "ctl_submit", txn=txn)
