from .demers import AntiEntropy, DirectMail, DirectMailAcked, rumor_init, rumor_run
from .full_membership import FullMembership
from .hyparview import HyParView
from .plumtree import Plumtree
from .stack import Stacked, StackState, UpperProtocol
