from .chain import ChainWorker
from .commit import AlsbergDay, BernsteinCTP, Skeen3PC, TwoPhaseCommit
from .dataplane import DataPlane
from .demers import (AntiEntropy, DirectMail, DirectMailAcked, rumor_init,
                     rumor_run)
from .distance import Distance
from .echo import Echo
from .full_membership import FullMembership
from .hbbft import HbbftWorker
from .hyparview import HyParView
from .hyparview_dense import DenseHvState, dense_init, run_dense
from .managers import ClientServerManager, StaticManager
from .plumtree import Plumtree
from .plumtree_dense import PtDense, pt_dense_init, run_pt_dense
from .scamp import ScampV1, ScampV2
from .scamp_dense import (DenseScampState, dense_scamp_init,
                          run_dense_scamp)
from .stack import Stacked, StackState, UpperProtocol
