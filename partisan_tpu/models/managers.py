"""Static and client/server peer-service managers — TPU-native rebuilds of
``src/partisan_static_peer_service_manager.erl`` and
``src/partisan_client_server_peer_service_manager.erl``.

Both keep an explicit membership set with **no view gossip**: an edge exists
only because someone joined someone.  The difference is the admission rule
applied during the join handshake:

  * static: always accept (static :403 handles only data forwarding; joins
    accumulate into the set unconditionally).
  * client/server: ``accept_join_with_tag`` (client_server :500-523) —
    servers accept servers and clients; clients accept only servers, which
    yields the star topology of the reference's client/server tests
    (tags set by test support, test/partisan_support.erl:303-317).

Handshake shape mirrors the reference's {connected, Node, TheirTag, _}
flow (client_server :322-364): the joiner requests with its tag, the peer
admits by its own rule and replies with *its* tag, and the joiner then
applies the same rule before adding the peer — membership stays one-sided
per node exactly as in the reference (each node's set is what IT accepted).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..engine import ProtocolBase
from ..ops import padded_set as ps
from ..ops.msg import Msgs

SERVER, CLIENT = 0, 1


@struct.dataclass
class MgrState:
    members: jax.Array   # [N, C] padded member set (what this node accepted)
    tag: jax.Array       # [N] int32 — SERVER / CLIENT
    left: jax.Array      # [N] bool


class StaticManager(ProtocolBase):
    """Static membership: joins accumulate, nothing is gossiped, leaves
    remove locally and notify the target only."""

    msg_types = ("join_req", "join_ack", "leave_note",
                 "ctl_join", "ctl_leave")

    def __init__(self, cfg: Config, member_cap: int | None = None):
        self.cfg = cfg
        self.C = member_cap or min(cfg.n_nodes - 1, 32)
        self.data_spec: Dict = {
            "peer": ((), jnp.int32),
            "tag": ((), jnp.int32),
        }
        self.emit_cap = self.C  # ctl_leave notifies every member
        self.tick_emit_cap = 1

    # admission rule; overridden by the client/server manager
    def _accept(self, my_tag: jax.Array, their_tag: jax.Array) -> jax.Array:
        return jnp.bool_(True)

    def init(self, cfg: Config, key: jax.Array) -> MgrState:
        n = cfg.n_nodes
        return MgrState(
            members=jnp.full((n, self.C), -1, jnp.int32),
            tag=self.init_tags(cfg),
            left=jnp.zeros((n,), bool),
        )

    def init_tags(self, cfg: Config) -> jax.Array:
        return jnp.zeros((cfg.n_nodes,), jnp.int32)

    def member_mask(self, row: MgrState) -> jax.Array:
        n = self.cfg.n_nodes
        m = jnp.zeros((n,), bool)
        return m.at[jnp.clip(row.members, 0, n - 1)].max(row.members >= 0)

    # --------------------------------------------------------------- handlers

    def handle_ctl_join(self, cfg, me, row: MgrState, m: Msgs, key):
        peer = m.data["peer"]
        ok = (peer >= 0) & (peer != me)
        row = row.replace(left=jnp.where(ok, False, row.left))
        return row, self.emit(jnp.where(ok, peer, -1)[None],
                              self.typ("join_req"),
                              tag=self._my_tag(row, me))

    def _my_tag(self, row: MgrState, me) -> jax.Array:
        return row.tag  # row is this node's slice; tag is scalar here

    def handle_join_req(self, cfg, me, row: MgrState, m: Msgs, key):
        mine = self._my_tag(row, me)
        accept = self._accept(mine, m.data["tag"]) & ~row.left
        row = row.replace(members=ps.insert(
            row.members, jnp.where(accept, m.src, -1)))
        ack = self.emit(jnp.where(accept, m.src, -1)[None],
                        self.typ("join_ack"), tag=mine)
        return row, ack

    def handle_join_ack(self, cfg, me, row: MgrState, m: Msgs, key):
        accept = self._accept(self._my_tag(row, me), m.data["tag"]) \
            & ~row.left
        row = row.replace(members=ps.insert(
            row.members, jnp.where(accept, m.src, -1)))
        return row, self.no_emit()

    def handle_leave_note(self, cfg, me, row: MgrState, m: Msgs, key):
        row = row.replace(members=ps.remove(row.members, m.src))
        return row, self.no_emit()

    def handle_ctl_leave(self, cfg, me, row: MgrState, m: Msgs, key):
        """Self-leave: notify every member, clear local state (static
        :248 {stop, normal} on self-removal)."""
        target = m.data["peer"]
        self_leave = target == me
        note = self.emit(jnp.where(self_leave, row.members, -1),
                         self.typ("leave_note"), cap=self.C)
        row = row.replace(
            members=jnp.where(self_leave, -1,
                              ps.remove(row.members, target)),
            left=row.left | self_leave)
        return row, note


class ClientServerManager(StaticManager):
    """Star topology via tag-gated admission (client_server :500-523).
    ``n_servers`` leading node ids are servers; the rest are clients."""

    def __init__(self, cfg: Config, n_servers: int = 1,
                 member_cap: int | None = None):
        super().__init__(cfg, member_cap)
        self.n_servers = n_servers

    def init_tags(self, cfg: Config) -> jax.Array:
        ids = jnp.arange(cfg.n_nodes)
        return jnp.where(ids < self.n_servers, SERVER, CLIENT).astype(
            jnp.int32)

    def _accept(self, my_tag: jax.Array, their_tag: jax.Array) -> jax.Array:
        # server accepts everyone; client accepts only servers
        return (my_tag == SERVER) | (their_tag == SERVER)
