"""Dense-representation SCAMP — the second membership strategy re-laid
TPU-fast (the models/hyparview_dense.py recipe applied to
``src/partisan_scamp_v2_membership_strategy.erl``).

The engine path (models/scamp.py) proves the protocol message for
message: subscription walks that hop one partial-view member per round
and keep with probability 1/(1+|view|), the contact fan-out of
|view| + c copies per join, keep-notifications filling the in-view
(v2 :328-338).  Its COO message shape is scatter-latency-bound at TPU
scale like HyParView's was.  This module re-expresses the same dynamics
as whole-array ops:

  walkers    every in-flight subscription walk is a (subject, position)
             pair in a fixed [N, C] slot table: subject = row, position
             = current holder.  One round = one gather of the holders'
             views + one keep-coin per walker + one hop gather — the
             engine's forward_subscription handler (:284-327)
             batch-evaluated for every walk at once.
  keep       walkers that keep propose (subject -> holder) through
             ``reverse_select`` — the same sort-routed delivery the
             dense HyParView uses for neighbor proposals — and the
             holder admits up to 4 new subscriptions per round
             (duplicate subjects deduped); a second reverse_select
             routes the v2 keep-notification back to the subject's
             in-view.  Full views refuse-and-count (the padded-set
             analog of the reference's unbounded orddict).
  join       a churned/reborn node adopts a random live contact and
             spawns its walk copies AT the contact: one per contact
             partial-view member plus ``scamp_c`` extras at random
             members (subscription fan-out, v2 :64-117) — positions
             gathered from the contact's row, no messages.
  isolation  a live node with an empty partial view and no active
             walkers re-subscribes through a fresh random contact
             (isolation detection, v2 :130-178).

What is deliberately NOT carried over (and why that is faithful):
graceful leave/rewiring and remove_subscription gossip are
reconfiguration VERBS, exercised against the engine path
(tests/test_scamp.py) — the dense variant models the steady-state +
churn regime the big-N benchmarks measure, where departure is crash
and recovery is re-subscription.  Walks expire (counted) after
``max_age`` hops instead of walking forever: the keep-coin terminates
real walks in O(|view|) hops, so expiry only fires on pathological
orphans (e.g. every reachable view saturated).

Parity bar (SURVEY §7.3 "two RNG semantics"): distributional —
tests/test_scamp_dense.py asserts weak connectivity and that the
view-size distribution brackets the engine path's at N=256.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config import Config
from ..ops import padded_set as ps
from .hyparview_dense import (_gather_rows, refuse_tpu_shape_bug,
                              reverse_select)
from .scamp import default_view_cap


@struct.dataclass
class DenseScampState:
    partial: jax.Array    # [N, P] padded partial view
    in_view: jax.Array    # [N, P] padded in-view (who holds my sub)
    walk_pos: jax.Array   # [N, C] walker positions (-1 = inactive)
    walk_age: jax.Array   # [N, C] hops walked
    alive: jax.Array      # [N]
    insert_dropped: jax.Array  # [N] keeps refused by a full view
    walk_expired: jax.Array    # [N] walks dead of old age (counted)
    walk_truncated: jax.Array  # [N] join fan copies lost to full slots
    in_view_dropped: jax.Array  # [N] keep-notifications lost to the
                                # c=4 per-subject reverse_select cap
    last_reset: jax.Array  # [N] round of the node's last restart
                           # (-10^6 = never) — drives the amortized
                           # stale-entry sweep
    pstamp: jax.Array      # [N, P] admission round of each partial
                           # entry — the sweep deletes exactly the
                           # entries older than the peer's last restart
    ivstamp: jax.Array     # [N, P] same for in_view entries
    rnd: jax.Array


def walker_caps(cfg: Config) -> Tuple[int, int]:
    """(P, C): view cap and walker slots.  C bounds ONE subject's
    concurrent walk copies; the join fan-out (one copy per contact view
    member + c extras, v2 :64-117) truncates to C with the excess
    counted (walk_truncated).

    C comes from ``cfg.scamp_walker_slots`` (default 8; round 4 dropped
    it from 16): the walker plane's two reverse_select sorts run over
    N·C slots, and steady-state occupancy is under ONE walker per node
    (2^16 soak: ~60k active of 1M slots at C=16) — so halving C bought
    +55-60% rounds/s on the chip (results.csv: scamp_dense_65536
    17.8 -> 27.5, scamp_dense_4096 298 -> 475).  The trade is explicit:
    a typical join fan is mean view ~4 + scamp_c extras, which EXCEEDS
    8, so truncation is a routine per-join cut (counted,
    walk_truncated), not a rare burst — the official rows show weak
    connectivity essentially unchanged (99.59% vs 99.6% reached at
    2^16; 4093/4096 at 4096) with views settling thinner (mean 3.6-3.8
    vs 4.3-5.6), inside the engine-matched parity band asserted by
    tests/test_scamp_dense.py (which red-lines below C ~6).  Raise C
    back toward 16 if a workload needs the fatter-view equilibrium
    more than the throughput."""
    return default_view_cap(cfg.n_nodes, cfg.scamp_c), \
        cfg.scamp_walker_slots


def dense_scamp_init(cfg: Config) -> DenseScampState:
    n = cfg.n_nodes
    p, c = walker_caps(cfg)
    st = DenseScampState(
        partial=jnp.full((n, p), -1, jnp.int32),
        in_view=jnp.full((n, p), -1, jnp.int32),
        walk_pos=jnp.full((n, c), -1, jnp.int32),
        walk_age=jnp.zeros((n, c), jnp.int32),
        alive=jnp.ones((n,), bool),
        insert_dropped=jnp.zeros((n,), jnp.int32),
        walk_expired=jnp.zeros((n,), jnp.int32),
        walk_truncated=jnp.zeros((n,), jnp.int32),
        in_view_dropped=jnp.zeros((n,), jnp.int32),
        last_reset=jnp.full((n,), -1000000, jnp.int32),
        pstamp=jnp.zeros((n, p), jnp.int32),
        ivstamp=jnp.zeros((n, p), jnp.int32),
        rnd=jnp.int32(0),
    )
    # bootstrap: every node joins through a random contact (the
    # orchestration-layer peer discovery, as in hyparview_dense)
    key = jax.random.PRNGKey(cfg.seed ^ 0x5CA37)
    ids = jnp.arange(n, dtype=jnp.int32)
    contact = jax.random.randint(key, (n,), 0, n, jnp.int32)
    contact = jnp.where(contact == ids, (contact + 1) % n, contact)
    return _spawn_walks(st, contact, jnp.ones((n,), bool), key, cfg)


def _spawn_walks(st: DenseScampState, contact: jax.Array,
                 doing: jax.Array, key: jax.Array,
                 cfg: Config) -> DenseScampState:
    """Join through ``contact`` for rows where ``doing``: adopt the
    contact and place the subscription fan-out's walk copies at the
    contact — one per contact partial-view member (they each received a
    forward), plus scamp_c extras at random members; an empty-view
    contact holds the walks itself (first-join keep, :284-327)."""
    n = st.partial.shape[0]
    _, c_slots = walker_caps(cfg)
    ids = jnp.arange(n, dtype=jnp.int32)
    partial = jnp.where(doing[:, None], -1, st.partial)
    partial = partial.at[:, 0].set(
        jnp.where(doing, contact, partial[:, 0]))
    crow = _gather_rows(st.partial, contact)               # [N, P]
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        jax.random.fold_in(key, 77), ids)
    extras = jax.vmap(ps.random_k, in_axes=(0, 0, None))(
        crow, keys, cfg.scamp_c)                           # [N, c]
    # one walk copy per contact view member + c extras — the fan-out
    # tracks the contact's ACTUAL view size like the reference's (a cap-
    # wide spawn would inflate in-degree ~P per join).  An empty-view
    # contact gets ONE walker standing at the contact itself, whose
    # keep-coin is 1/(1+0) = 1 — exactly the reference's direct first-
    # join keep (:284-327 lonely branch).  Fan beyond the C walker
    # slots truncates, counted.
    spawn_full = jnp.concatenate([crow, extras], axis=1)
    # compact valid spawns to the front so truncation drops only excess
    spawn_full = jax.vmap(ps.members_first)(spawn_full)
    spawn = spawn_full[:, :c_slots]
    lost = jnp.sum(spawn_full[:, c_slots:] >= 0, axis=1)
    empty_contact = jnp.sum(crow >= 0, axis=1) == 0
    spawn = spawn.at[:, 0].set(
        jnp.where(empty_contact, contact, spawn[:, 0]))
    new_pos = jnp.where(doing[:, None], spawn, st.walk_pos)
    return st.replace(
        partial=partial,
        in_view=jnp.where(doing[:, None], -1, st.in_view),
        walk_pos=new_pos,
        walk_age=jnp.where(doing[:, None], 0, st.walk_age),
        walk_truncated=st.walk_truncated
        + jnp.where(doing, lost, 0).astype(jnp.int32),
        pstamp=jnp.where(doing[:, None], st.rnd, st.pstamp),
        ivstamp=jnp.where(doing[:, None], st.rnd, st.ivstamp),
    )


# columns of the concatenated (partial ++ in_view) planes re-checked
# per round by the amortized stale-entry sweep: removal latency is
# ceil(W/K_SWEEP) rounds.  Module-level so the 2^20 shape search
# (scripts/repro_scamp_dense_fault.py --ksweep) can vary it; jit cache
# correctness is per-process (fresh process per variant).
K_SWEEP = 8


def make_dense_scamp_round(cfg: Config, churn: float = 0.0,
                           max_age: int = 64,
                           skip: Tuple[str, ...] = (),
                           phase_window: int = 1,
                           resub_policy=None):
    # ``skip``: static tuple of phases to omit.  {churn, admit, inview}
    # are the bisection/ablation surface for the N=2^16 TPU worker fault
    # (ROADMAP 1d); {resub, sweep} are the CADENCE surface (ISSUE 2) —
    # the staggered runner's light rounds omit the isolation
    # re-subscribe (with its contact-row gather + members_first sort,
    # the round's dominant whole-plane sort) and the stale sweep, both
    # periodic maintenance in the reference (scamp_v2 :130-178 runs
    # periodic/1 at 10 s against 1 s delivery).  Static so every value
    # is its own jit cache entry (the round-3 env-var gate was
    # invisible to the cache and could silently reuse a stale program).
    # Production every-round runs leave it empty.
    #
    # ``phase_window=k`` > 1 is the HEAVY half of the staggered cadence
    # (run_dense_scamp_staggered): the stale sweep widens to k*K_SWEEP
    # columns so consecutive heavies (k rounds apart, each starting at
    # column rnd*K_SWEEP) cover exactly the columns the every-round
    # program would have — the per-round amortized sweep rate is
    # preserved, quantized to the heavy grid.  Isolation re-subscribe
    # needs no widening: `lonely` is a state predicate, so a node
    # isolated in a light round is still lonely when the next heavy
    # fires (detection latency <= k rounds, the reference's own
    # periodic isolation-detection latency).  phase_window=1 (default)
    # is bit-identical to the pre-cadence program.
    _dbg = frozenset(skip)
    assert _dbg <= {"churn", "admit", "inview", "resub", "sweep"}, (
        f"unknown phase(s) in skip: "
        f"{_dbg - {'churn', 'admit', 'inview', 'resub', 'sweep'}}")
    assert phase_window >= 1
    N = cfg.n_nodes
    # Loud gate, now at 2^20 (round 5): single launches of <=50 scanned
    # rounds run N=2^20 clean (1000-round soak) and run_dense_scamp
    # chunks to launch_cap_for(N)=50 there, so 2^20 is admitted; a
    # single >=100-round launch at 2^20 still faults the v5e worker
    # (see LAUNCH_CAP's comment), and shapes beyond 2^20 are unprobed —
    # the gate holds at the largest validated shape.
    refuse_tpu_shape_bug(N, "dense SCAMP", limit=1 << 20)
    P, C = walker_caps(cfg)
    ids = jnp.arange(N, dtype=jnp.int32)

    def nkeys(key, salt):
        return jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            jax.random.fold_in(key, salt), ids)

    def step(st: DenseScampState) -> DenseScampState:
        key = jax.random.fold_in(
            jax.random.PRNGKey(cfg.seed ^ 0x5CADE), st.rnd)
        alive = st.alive
        partial, in_view = st.partial, st.in_view
        pstamp, ivstamp = st.pstamp, st.ivstamp
        pos, age = st.walk_pos, st.walk_age

        # ---- churn: restart-in-place.  Round-4 restructure (the
        # ROADMAP 1d lever): churn only CLEARS state here — restarted
        # rows wipe their views/walkers and stamp last_reset — and the
        # rejoin rides the isolation re-subscribe below, since a
        # cleared row satisfies the isolation predicate by
        # construction.  One _spawn_walks instance per round instead
        # of the round-3 program's two; the round-3 shape faulted the
        # TPU worker at N=2^16 beyond ~50 scanned rounds (compositional
        # — every op individually clean) while this schedule runs 100-
        # round launches clean (see LAUNCH_CAP for the residual length
        # sensitivity; results.csv scamp_dense_65536).  Walk spawns
        # gather the contact's POST-clear view (a restarted contact can
        # still host the walker itself via the empty-view first-join
        # branch — it is alive, restart-in-place).
        last_reset = st.last_reset
        if churn > 0.0 and 'churn' not in _dbg:
            ck = jax.random.fold_in(key, 0)
            reset = (jax.random.uniform(ck, (N,)) < churn) & alive
            partial = jnp.where(reset[:, None], -1, partial)
            in_view = jnp.where(reset[:, None], -1, in_view)
            pos = jnp.where(reset[:, None], -1, pos)
            age = jnp.where(reset[:, None], 0, age)
            pstamp = jnp.where(reset[:, None], st.rnd, pstamp)
            ivstamp = jnp.where(reset[:, None], st.rnd, ivstamp)
            last_reset = jnp.where(reset, st.rnd, last_reset)

        # ---- amortized stale-entry sweep (the remove_subscription
        # effect): each round re-checks a rotating window of K_SWEEP=8
        # columns
        # of the concatenated (partial ++ in_view) planes against
        # the peer's last restart, so every stale entry clears within
        # ~ceil(W/8) rounds of the restart.  Bounded removal latency is the faithful cadence —
        # the reference's remove_subscription is GOSSIP-carried, never
        # instantaneous (scamp_v2 :180-227) — and it shares the
        # reference's removal semantics: a re-proposal of a held
        # subject refreshes the entry's stamp (resubscribe supersedes
        # the pending unsubscribe), so only subscriptions the subject
        # never re-requests are swept.  It is also the difference between
        # ~5 and ~19 rounds/s at 2^16: the round-3 full-plane scrub
        # gather pushed XLA into a pathological schedule costing
        # ~140 ms a round (scripts/profile_scamp.py; the same fusion
        # pass Check-fails outright on a neighboring ablation variant,
        # scripts/repro_scamp_dense_fault.py).  Runs in churn-free
        # programs too, so a settle window finishes the sweep.
        if 'sweep' not in _dbg:
            cat = jnp.concatenate([partial, in_view], axis=1)
            scat = jnp.concatenate([pstamp, ivstamp], axis=1)
            W = cat.shape[1]
            # phase_window widens the rotating window so the k-cadence
            # heavy round sweeps the k rounds' worth of columns the
            # every-round program would have (see the param docstring)
            for j in range(K_SWEEP * phase_window):
                cj = (st.rnd * K_SWEEP + j) % W
                col = jnp.take(cat, cj, axis=1)              # [N]
                lr = last_reset[jnp.clip(col, 0, N - 1)]     # [N]
                # exact: delete iff the entry was admitted BEFORE the
                # peer's last restart (same-round admissions are always
                # post-clear — churn runs first in the step)
                stale = (col >= 0) & (jnp.take(scat, cj, axis=1) < lr)
                cat = cat.at[:, cj].set(jnp.where(stale, -1, col))
            partial = cat[:, : partial.shape[1]]
            in_view = cat[:, partial.shape[1]:]

        # ---- re-subscribe: churned rows (cleared above) and isolated
        # rows (empty view, no walkers) join through a fresh contact.
        # Periodic in the cadence: a light round's lonely rows stay
        # lonely until the next heavy fires (<= k rounds, the
        # reference's periodic isolation-detection latency)
        if 'resub' not in _dbg:
            lonely = alive & (jnp.sum(partial >= 0, axis=1) == 0) \
                & (jnp.sum(pos >= 0, axis=1) == 0)
            # chaos-aware hook (ISSUE 4): a (lonely, rnd) -> keep-mask
            # policy, e.g. verify.chaos.quiesce_resub — suppress re-join
            # storms around scheduled crash/partition events.  None =
            # the pre-hook program, bit-identical.
            if resub_policy is not None:
                lonely = lonely & resub_policy(lonely, st.rnd)
            fresh = jax.random.randint(
                jax.random.fold_in(key, 3), (N,), 0, N, jnp.int32)
            fresh = jnp.where(fresh == ids, (fresh + 1) % N, fresh)
            st3 = _spawn_walks(
                st.replace(partial=partial, in_view=in_view,
                           walk_pos=pos, walk_age=age, pstamp=pstamp,
                           ivstamp=ivstamp),
                fresh, lonely, jax.random.fold_in(key, 4), cfg)
            partial, in_view = st3.partial, st3.in_view
            pstamp, ivstamp = st3.pstamp, st3.ivstamp
            pos, age = st3.walk_pos, st3.walk_age
            walk_truncated = st3.walk_truncated
        else:
            walk_truncated = st.walk_truncated

        # ---- one walk hop for every active walker.  The walker plane
        # touches only O(N*C) SCALARS: view sizes are gathered from a
        # precomputed [N] vector and hops sample a random SLOT of the
        # holder's row (uniform over occupied members by rejection —
        # an empty draw bounces one round), so no [N*C, P] row gather
        # ever materializes.
        sizes_all = jnp.sum(partial >= 0, axis=1)          # [N]
        flat_pos = pos.reshape(-1)                         # [N*C]
        subj = jnp.repeat(ids, C)                          # [N*C]
        active_w = (flat_pos >= 0) & alive[jnp.clip(flat_pos, 0, N - 1)] \
            & jnp.repeat(alive, C)   # own-aliveness is a broadcast, not
                                     # a 1M-index gather
        hsize = jnp.where(active_w,
                          sizes_all[jnp.clip(flat_pos, 0, N - 1)], 0)
        can_keep = active_w & (flat_pos != subj)
        # trace-lint: allow(config-fork): same build-time keep-coin mode as ScampV1._keep_probability, dense lowering
        if cfg.scamp_exact_keep_probability:
            p_keep = 1.0 / (1.0 + hsize.astype(jnp.float32))
        else:
            p_keep = jnp.float32(0.4)
        coin = jax.random.uniform(jax.random.fold_in(key, 5),
                                  (N * C,))
        keep = can_keep & (coin < p_keep)

        # keepers propose (subject -> holder); holders admit up to 4
        chosen = reverse_select(
            jnp.where(keep, flat_pos, -1),
            jax.random.bits(jax.random.fold_in(key, 6), (), jnp.uint32),
            N, 4, use_kernel=cfg.use_pallas_route)         # [N, 4] walker ids
        # dedup same-subject proposals within a holder's admit list
        csubj = jnp.where(chosen >= 0, chosen // C, -1)    # [N, 4]
        earlier = jnp.tril(jnp.ones((4, 4), bool), k=-1)
        dup = jnp.any((csubj[:, :, None] == csubj[:, None, :])
                      & (csubj[:, :, None] >= 0) & earlier[None], axis=2)
        csubj = jnp.where(dup, -1, csubj)
        admitted = jnp.zeros((N, 4), bool)
        dropped = jnp.zeros((N,), jnp.int32)
        for j in (range(0) if 'admit' in _dbg else range(4)):
            s_j = csubj[:, j]
            dup_slot = (partial == s_j[:, None]) & (s_j >= 0)[:, None]
            # a re-proposal of an ALREADY-HELD subject refreshes the
            # entry's stamp: resubscribe supersedes a pending (swept)
            # unsubscribe, so the sweep cannot delete a subscription
            # the subject re-requested after its restart
            pstamp = jnp.where(dup_slot, st.rnd, pstamp)
            hit = jnp.any(dup_slot, axis=1)
            want = (s_j >= 0) & ~hit
            free = jnp.sum(partial >= 0, axis=1) < P
            do = want & free
            prev = partial
            partial, _, ins = jax.vmap(ps.insert_evict, in_axes=(0, 0, None))(
                partial, jnp.where(do, s_j, -1), None)
            pstamp = jnp.where(partial != prev, st.rnd, pstamp)
            admitted = admitted.at[:, j].set(do & ins)
            dropped = dropped + (want & ~free).astype(jnp.int32)
        # keep-notification (v2): admitted subjects record the holder
        # in their in-view — routed by a second reverse_select over the
        # flattened admit matrix (entry e = holder * 4 + j)
        iv_lost = jnp.zeros((N,), jnp.int32)
        if 'inview' not in _dbg:
          ev_subj = jnp.where(admitted, csubj, -1).reshape(-1)
          back = reverse_select(
              ev_subj,
              jax.random.bits(jax.random.fold_in(key, 7), (), jnp.uint32),
              N, 4, use_kernel=cfg.use_pallas_route)
          for j in range(4):
              e_j = back[:, j]
              holder_j = jnp.where(e_j >= 0, e_j // 4, -1)
              # mirror the partial plane's re-proposal stamp refresh
              # (ADVICE r4): if the holder is ALREADY in the subject's
              # in-view (stale entry from before the holder's restart,
              # not yet swept), the insert below is a no-op and the old
              # ivstamp would let the sweep delete a live subscription —
              # a post-restart re-admission must supersede the pending
              # sweep on BOTH planes
              iv_dup = (in_view == holder_j[:, None]) \
                  & (holder_j >= 0)[:, None]
              ivstamp = jnp.where(iv_dup, st.rnd, ivstamp)
              prev = in_view
              in_view, _, _ = jax.vmap(ps.insert_evict, in_axes=(0, 0, None))(
                  in_view, holder_j, None)
              ivstamp = jnp.where(in_view != prev, st.rnd, ivstamp)
          # count-don't-silence: a subject admitted at more than 4
          # holders in one round loses the excess in-view
          # notifications to the reverse_select cap (ADVICE r3)
          sent_per_subj = jax.ops.segment_sum(
              (ev_subj >= 0).astype(jnp.int32),
              jnp.clip(ev_subj, 0, N - 1), N)
          got_per_subj = jnp.sum(back >= 0, axis=1)
          iv_lost = sent_per_subj - got_per_subj

        # a walker whose proposal was ADMITTED dies; one whose proposal
        # lost the admit race (or was refused) re-forwards next round
        # from the same holder (the reference re-forwards on duplicate
        # keep, :284-327)
        kept_flat = jnp.zeros((N * C + 1,), bool)
        kept_flat = kept_flat.at[jnp.where(
            admitted, chosen, N * C)].set(True, mode="drop")
        kept = kept_flat[:N * C]

        # non-keeping walkers hop to a random occupied slot of the
        # holder's view (rejection-uniform: an empty slot draw bounces
        # one round); empty/dead holders bounce too (age still ticks)
        slot_r = jax.random.randint(jax.random.fold_in(key, 8),
                                    (N * C,), 0, P)
        nxt = partial.reshape(-1)[
            jnp.clip(flat_pos, 0, N - 1) * P + slot_r]
        hop = active_w & ~keep & (nxt >= 0)
        new_flat = jnp.where(kept, -1,
                             jnp.where(hop, nxt, flat_pos))
        new_age = jnp.where(active_w, age.reshape(-1) + 1,
                            age.reshape(-1))
        expired = (new_flat >= 0) & (new_age > max_age)
        st_out = st.replace(
            partial=partial,
            in_view=in_view,
            walk_pos=jnp.where(expired, -1,
                               new_flat).reshape(N, C),
            walk_age=jnp.where(expired, 0, new_age).reshape(N, C),
            alive=alive,
            insert_dropped=st.insert_dropped + dropped,
            walk_expired=st.walk_expired
            + jax.ops.segment_sum(expired.astype(jnp.int32), subj, N),
            walk_truncated=walk_truncated,
            in_view_dropped=st.in_view_dropped + iv_lost,
            last_reset=last_reset,
            pstamp=pstamp,
            ivstamp=ivstamp,
            rnd=st.rnd + 1,
        )
        return st_out

    return jax.jit(step)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _run_dense_scamp_launch(st: DenseScampState, n_rounds: int,
                            cfg: Config, churn: float,
                            skip: Tuple[str, ...]) -> DenseScampState:
    # launch-length-conditioned gate (the plumtree runners' pattern):
    # a single scan LONGER than the validated cap at N > 2^16 is the
    # documented-faulting shape — refuse it loudly even though
    # make_dense_scamp_round's shape-only gate admits the N
    limit = (1 << 20) if n_rounds <= launch_cap_for(cfg.n_nodes) \
        else (1 << 16)
    refuse_tpu_shape_bug(cfg.n_nodes, "dense SCAMP long scan",
                         limit=limit)
    step = make_dense_scamp_round(cfg, churn, skip=skip)
    out, _ = jax.lax.scan(lambda s, _: (step(s), None), st, None,
                          length=n_rounds)
    return out


# Per-LAUNCH scan-length caps — shared across the dense programs; the
# constants and launch_cap_for live in hyparview_dense (next to the
# refuse_tpu_shape_bug gate) and are re-exported here for the callers
# that learned them at this address.  History of the bug this bounds
# (scripts/repro_scamp_dense_fault.py):
#   * round-3 shape: worker "kernel fault" beyond ~50 scanned rounds;
#   * round-4 mid shape (one _spawn_walks + instant scrub): clean at
#     100, faulted at ~200 — and a neighboring ablation variant
#     (skip=admit) crashed the COMPILER outright
#     (scatter_emitter.cc:2824 Check failure in the fusion pass);
#   * round-4 final shape (stamp-exact amortized sweep): clean at 500+
#     single-launch at 2^16, but a single 100-round launch faults at
#     N=2^20 — while 25- and 50-round launches run 2^20 CLEAN (round-5
#     search: 8x25, 4x50, and a 20x50 = 1000-round soak, identical
#     walker trajectories across chunkings).
# Every constituent op is individually clean and CPU runs are clean at
# any length — not a code bug.  Chunking is semantically invisible
# (the carried state is identical) and costs one host round-trip per
# launch, so the cap stays and TIGHTENS with shape: 100 up to 2^16
# (validated round 4), 50 above (validated at 2^20 round 5).
from .hyparview_dense import (LAUNCH_CAP, LAUNCH_CAP_BIG,  # noqa: F401
                              launch_cap_for)


def run_dense_scamp(st: DenseScampState, n_rounds: int, cfg: Config,
                    churn: float = 0.0,
                    skip: Tuple[str, ...] = ()) -> DenseScampState:
    """Run ``n_rounds`` dense-SCAMP rounds, chunked into launches of at
    most :func:`launch_cap_for` scanned rounds (see LAUNCH_CAP's
    comment; one jit cache entry per distinct chunk length)."""
    cap = launch_cap_for(cfg.n_nodes)
    done = 0
    while done < n_rounds:
        step_n = min(cap, n_rounds - done)
        st = _run_dense_scamp_launch(st, step_n, cfg, churn, skip)
        done += step_n
    return st


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def run_dense_scamp_staggered(st: DenseScampState, n_blocks: int,
                              cfg: Config, churn: float = 0.0,
                              k: int = 5,
                              skip: Tuple[str, ...] = ()
                              ) -> DenseScampState:
    """Phase-staggered dense SCAMP (ISSUE 2): the hyparview_dense
    cadence recipe at the reference's own timer layout — walk
    delivery/keep/admit every round (the 1 s message plane), isolation
    re-subscribe + stale sweep every k-th round (scamp_v2's periodic/1
    runs at 10 s, :130-178).  One k-round block is

        [heavy (resub + k-widened sweep + delivery), light x k-1]

    with per-node maintenance cadence preserved (see
    make_dense_scamp_round's phase_window contract) — at k=1 the block
    collapses to the every-round program and the trajectory is
    BIT-IDENTICAL to run_dense_scamp (tests/test_scamp_dense.py pins
    it).  The trade is the C=8-shaped one (walker_caps docstring):
    bootstrap knits ~2x slower (resub latency <= k) and views settle
    thinner (N=256 CPU: mean_view ~2.9 vs 4.1 flat) while weak
    connectivity converges to the same near-full regime (99%+ reached)
    — asserted distributionally by the cadence tests.  Runs
    n_blocks * k rounds; chunk via
    :func:`run_dense_scamp_staggered_chunked` at N > 2^16."""
    limit = (1 << 20) if n_blocks * k <= launch_cap_for(cfg.n_nodes) \
        else (1 << 16)
    refuse_tpu_shape_bug(cfg.n_nodes, "dense SCAMP staggered",
                         limit=limit)
    from .dense_cadence import as_body, block_scan
    heavy = make_dense_scamp_round(cfg, churn, skip=skip,
                                   phase_window=k)
    light = make_dense_scamp_round(
        cfg, churn, skip=tuple(skip) + ("resub", "sweep"))
    return block_scan([(as_body(heavy), 1), (as_body(light), k - 1)],
                      st, n_blocks)


def run_dense_scamp_staggered_chunked(st: DenseScampState,
                                      n_blocks: int, cfg: Config,
                                      churn: float = 0.0, k: int = 5,
                                      skip: Tuple[str, ...] = ()
                                      ) -> DenseScampState:
    """run_dense_scamp_staggered in launches of whole k-round blocks,
    at most launch_cap_for(N) rounds per launch (the validated
    bounded-launch shape; chunking is semantically invisible — the
    carried state is identical, tests/test_scamp_dense.py)."""
    cap = launch_cap_for(cfg.n_nodes)
    assert k <= cap, (
        f"staggered block of k={k} rounds exceeds the validated launch "
        f"cap {cap} at N={cfg.n_nodes}; lower k")
    cap_blocks = max(1, cap // k)
    done = 0
    while done < n_blocks:
        b = min(cap_blocks, n_blocks - done)
        st = run_dense_scamp_staggered(st, b, cfg, churn, k, skip)
        done += b
    return st


def _expand_reach(partial: jax.Array, alive: jax.Array,
                  r: jax.Array) -> jax.Array:
    """One BFS hop over the symmetric closure of the partial views."""
    n = partial.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    # forward edges: rows of reached
    nb = _gather_rows(partial, jnp.where(r, ids, -1))
    hit = jnp.zeros((n,), bool).at[
        jnp.clip(nb, 0, n - 1)].max(nb >= 0, mode="drop")
    # reverse edges: any row that POINTS AT a reached node
    points = jnp.any(
        r[jnp.clip(partial, 0, n - 1)] & (partial >= 0), axis=1)
    return r | ((hit | points) & alive)


@functools.partial(jax.jit, static_argnums=(3,))
def _expand_hops(partial: jax.Array, alive: jax.Array, r: jax.Array,
                 hops: int) -> Tuple[jax.Array, jax.Array]:
    out = r
    for _ in range(hops):
        out = _expand_reach(partial, alive, out)
    return out, jnp.any(out != r)


@jax.jit
def _health_stats(st: DenseScampState, reach: jax.Array
                  ) -> Dict[str, jax.Array]:
    partial, alive = st.partial, st.alive
    sizes = jnp.sum(partial >= 0, axis=1)
    live = jnp.sum(alive)
    return {
        "connected": jnp.sum(reach & alive) == live,
        "reached": jnp.sum(reach & alive),
        "live": live,
        "mean_view": jnp.sum(jnp.where(alive, sizes, 0))
        / jnp.maximum(live, 1),
        "walkers": jnp.sum(st.walk_pos >= 0),
        "expired": jnp.sum(st.walk_expired),
    }


@jax.jit
def _scamp_reach_fused(st: DenseScampState) -> jax.Array:
    """Whole-BFS-on-device (while_loop to fixpoint) — the small-N path."""
    partial, alive = st.partial, st.alive
    n = partial.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    reach0 = ids == jnp.argmax(alive).astype(jnp.int32)

    def body(c):
        r, _ = c
        r2 = _expand_reach(partial, alive, r)
        return r2, jnp.any(r2 != r)

    reach, _ = jax.lax.while_loop(lambda c: c[1], body,
                                  (reach0, jnp.bool_(True)))
    return reach


def scamp_health(st: DenseScampState) -> Dict[str, jax.Array]:
    """Weak connectivity over the symmetric closure of the partial
    views + view-size stats (the engine path's health surface,
    tests/test_scamp.py).

    At N > 2^16 the fused while_loop BFS is ITSELF a worker-faulting
    program shape at [N, P] (round-5 probe: the round scans run 2^20
    clean chunked, then the health readback crashed the worker) — the
    same launch-bounding medicine applies: the walk rides the shared
    host-driven driver (hyparview_dense.bounded_bfs) in 8-hop jitted
    launches to a fixpoint."""
    from .hyparview_dense import bounded_bfs
    n = st.partial.shape[0]
    if n <= (1 << 16):
        return _health_stats(st, _scamp_reach_fused(st))
    reach = bounded_bfs(
        lambda r, h: _expand_hops(st.partial, st.alive, r, h),
        st.alive, n, 8)
    return _health_stats(st, reach)
